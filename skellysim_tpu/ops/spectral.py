"""Periodic & slab-confined spectral Ewald Stokes evaluators (skelly-spectral).

`ops.ewald` covers the FREE-SPACE fast path: its truncated-kernel trick
buys an aperiodic answer from a periodic FFT. This module is the genuinely
periodic twin — the workload class the reference serves through PVFMM's
periodic wrappers (`kernels.hpp:56-134`) and ROADMAP item 3 names as the
open gap: triply-periodic suspensions ("tp") and doubly-periodic
slab-confined scenes ("dp": x/y periodic, z free), following the
performance-portable spectral Ewald for Stokes (arXiv 2606.19059) and the
linear-time doubly-periodic formulation's free-dimension treatment
(arXiv 2210.01837).

Mathematical structure (classic Hasimoto splitting over a lattice; every
identity below is pinned by `tests/test_spectral.py` against dense lattice
oracles):

* Near field: the SAME screened real-space kernels as `ops.ewald`
  (G_near ~ erfc(xi r)), summed over minimum images. The planner enforces
  ``rc <= min(L_periodic)/2`` so the +-1 image shell is complete; the cell
  list wraps per periodic axis and clips along the free axis.
* Triply periodic far field: the k-space lattice multiplier is exactly the
  textbook Hasimoto form
      uhat(k) = (Phi(k) / (eta k^2)) (I - khat khat) fhat(k),
      Phi(k)  = (1 + k^2/(4 xi^2)) e^{-k^2/(4 xi^2)},
  carried in the same ``-Bhat (k^2 I - k k^T)/(8 pi eta)`` code shape as
  `ewald._far_field` with ``Bhat_per(k) = -8 pi Phi(k)/k^4`` and the k = 0
  mode dropped (zero-mean-flow convention, matched by the oracle). The FFT
  box IS the physical box — no padding in periodic dims, and the window's
  mod-M wrap is exact physics, not an approximation to control.
* Doubly periodic far field: mixed lattice — x/y modes are discrete, z is
  handled on a PADDED grid. For kperp != 0 the z-periodization error of
  the padded box decays like e^{-|kperp| (Lz_grid - Dz)}, so the plan pads
  ``Lz_grid >= Dz + (ln(1/tol) + 3) max(Lx, Ly)/(2 pi)``. The kperp = 0
  column
  (the xy-averaged flow, where the kernel grows ~|z|) gets the 1D
  Vico-Greengard treatment: the exact column kernel
      K1(z) = -(|z|/2) erf(xi |z|) - e^{-xi^2 z^2}/(4 xi sqrt(pi))
  (the mollified |z| transform, constant pinned by K1 ~ -|z|/2 at large z)
  is truncated at R_z > Dz and applied in k as
      K1hat_R(kz) = -(T1(kz)/2) Phi(kz),
      T1(k) = 2 (cos kR - 1)/k^2 + 2 R sin(kR)/k   (T1(0) = R^2),
  exact for |z| < R_z - O(1/xi); it multiplies the x/y velocity channels
  only ((I - khat khat)_zz = 0 on the column). The stresslet column is the
  same story one derivative down: multiplier i Phi/(2 eta kz), kernel
  K2(z) = -erf(xi z)/2 - (xi z/(2 sqrt(pi))) e^{-xi^2 z^2}, truncated
  transform T_s(kz) = i (1 - cos kz R)/kz.
* Spreading/interpolation: the separable truncated-Gaussian window of
  `ops.ewald`, generalized to ANISOTROPIC grids — per-axis spacing h_i and
  window variance tau_i = (P h_i)^2 / (16 ln(1/tol)), deconvolved by
  dividing by the separable what(k)^2.

The plan (`plan_spectral`) is bucket-quantized DATA, not a trace constant:
grid dims snap onto the FFT-friendly ``GRID_RUNGS`` ladder (2^a 3^b,
~x1.5 geometric — overridable through `BucketPolicy.grid_ladder`), extents
and occupancy ride the same ladders as `plan_ewald`, and the two anchors
(box_lo, cell_lo) enter traced so drifting scenes sharing a rung reuse one
compiled program.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .ewald import (_ladder, stokeslet_disp_block,
                    stresslet_disp_block_ewald)

__all__ = ["SpectralPlan", "plan_spectral", "stokeslet_spectral",
           "stresslet_spectral", "strip_anchors", "plan_anchors",
           "fill_positions", "GRID_RUNGS"]

_SQRT_PI = math.sqrt(math.pi)

#: FFT-friendly grid-dimension ladder (2^a 3^b, ~x1.5 geometric): the
#: spectral analogue of the occupancy/node ladders — a drifting scene's
#: grid requirement snaps UP onto a rung so the plan (the jit key) is
#: stable until the requirement swings ~50%. Overridable per deployment
#: through `system.buckets.BucketPolicy.grid_ladder`.
GRID_RUNGS = (16, 24, 32, 48, 64, 96, 128, 192, 256, 384, 512)


def _grid_rung(n, ladder):
    """Smallest ladder rung >= n (the top rung caps oversized requests —
    accuracy then degrades gracefully instead of compiling unbounded
    grids)."""
    for r in ladder:
        if r >= n:
            return int(r)
    return int(ladder[-1])


# ---------------------------------------------------------------------- plan

@dataclass(frozen=True)
class SpectralPlan:
    """Static geometry/resolution of one periodic spectral-Ewald evaluation
    (hashable; selects compiled programs). Anisotropic throughout: per-axis
    grid dims, extents, window variances, and cell sizes — a slab's padded
    free axis need not match its periodic axes.

    ``box_lo``/``cell_lo`` are traced anchors exactly as in `EwaldPlan`:
    strip them (`strip_anchors`) from the jit key and pass them as the
    [2, 3] `plan_anchors` operand.
    """

    mode: str                 # "tp" (triply periodic) | "dp" (slab)
    xi: float                 # splitting parameter
    rc: float                 # near-field cutoff (<= min periodic L / 2)
    Rz: float                 # dp kperp=0 column truncation radius (tp: 0)
    box_lo: tuple             # FFT grid anchor (traced at run time)
    box_L: tuple              # per-axis grid extents (periodic axes: the
                              # physical box; dp z: padded free extent)
    M3: tuple                 # per-axis grid points (GRID_RUNGS rungs)
    P: int                    # window support (grid points per dim)
    tau3: tuple               # per-axis Gaussian window variances
    cell_lo: tuple            # near-field cell-lattice anchor (traced)
    cells3: tuple             # per-axis cell counts (periodic axes tile
                              # the box exactly: cells * cell_size = L)
    cell_size3: tuple         # per-axis cell sizes (>= rc)
    max_occ: int              # static per-cell capacity
    eta: float

    @property
    def h3(self) -> tuple:
        return tuple(L / m for L, m in zip(self.box_L, self.M3))

    @property
    def Lper(self) -> tuple:
        """Periodic lengths with 0.0 marking the free axis."""
        if self.mode == "tp":
            return self.box_L
        return (self.box_L[0], self.box_L[1], 0.0)


def strip_anchors(plan: SpectralPlan) -> SpectralPlan:
    """Zero the traced anchor fields — the hashable jit key for this plan."""
    import dataclasses

    return dataclasses.replace(plan, box_lo=(0.0, 0.0, 0.0),
                               cell_lo=(0.0, 0.0, 0.0))


def plan_anchors(plan: SpectralPlan, dtype=None):
    """[2, 3] traced-operand anchors (box_lo, cell_lo)."""
    return jnp.asarray([plan.box_lo, plan.cell_lo],
                       dtype=dtype or jnp.float64)


#: the R2 low-discrepancy lattice `ops.ewald` uses for padding placement
_R2_ALPHAS = (0.8191725133961645, 0.6710436067037893, 0.5497004779019703)


def fill_positions(plan: SpectralPlan, cell_lo, n, dtype):
    """[n, 3] well-spread positions inside the near-field cell region for
    zero-strength padding nodes (`ewald.fill_positions`, per-axis sizes)."""
    t = (jnp.arange(n, dtype=dtype) + 0.5)[:, None]
    alphas = jnp.asarray(_R2_ALPHAS, dtype=dtype)[None, :]
    frac = (t * alphas) % 1.0
    extent = ((jnp.asarray(plan.cells3, dtype=dtype) - 0.01)
              * jnp.asarray(plan.cell_size3, dtype=dtype))
    return jnp.asarray(cell_lo, dtype=dtype) + frac * extent


def _fill_positions_np(plan_like, n):
    """NumPy mirror of `fill_positions` for host-side occupancy counting."""
    t = (np.arange(n, dtype=np.float64) + 0.5)[:, None]
    frac = (t * np.asarray(_R2_ALPHAS)[None, :]) % 1.0
    cell_lo, cells3, cell_size3 = plan_like
    extent = (np.asarray(cells3, dtype=np.float64) - 0.01) \
        * np.asarray(cell_size3, dtype=np.float64)
    return np.asarray(cell_lo) + frac * extent


def plan_spectral(points, box, eta, tol=1e-6, max_grid=512, target_occ=32.0,
                  n_fill=0, grid_ladder=()):
    """Choose (xi, rc, grid M3, window P, cell lattice) for a target
    relative tolerance on a periodic box.

    ``box`` is the periodic cell: 3 lengths -> triply periodic, 2 lengths
    (Lx, Ly) -> doubly periodic slab with z free (extent measured from the
    cloud, ladder-quantized). Host-side NumPy, once per step/geometry.

    Parameter rules (shared calibration with `plan_ewald`, each pinned by
    `tests/test_spectral.py`):
      * rc from target cell occupancy, CAPPED at min(L_periodic)/2 so the
        minimum-image +-1 cell shell is complete — the periodic analogue
        of the free-space truncation-radius rule;
      * xi = sqrt(ln(1/tol))/rc, k_max = 2 xi sqrt(ln(1/tol) + 4);
      * per-axis M from k_max L_i / pi, snapped UP onto the `GRID_RUNGS`
        (or ``grid_ladder``) FFT-friendly ladder; oversized requirements
        relax xi through the same fixed-point loop as `plan_ewald` (rc
        re-capped each round);
      * dp only: R_z = D_z + (sqrt(ln 1/tol) + 3)/xi and
        Lz_grid = D_z + max(R_z + 4/xi, (ln(1/tol) + 3) max(Lx,Ly)/(2 pi)) —
        the truncated-column support plus the kperp != 0 z-periodization
        margin, whichever is larger.

    Every derived quantity is a function of ladder-quantized inputs, so
    the plan — the jit compilation key — is stable while the geometry
    drifts; the anchors hop on their own lattices and enter traced.
    """
    box = tuple(float(b) for b in box)
    if len(box) not in (2, 3):
        raise ValueError(
            f"periodic box must have 2 (slab) or 3 (triply periodic) "
            f"lengths, got {len(box)}")
    if min(box) <= 0.0:
        raise ValueError(f"periodic box lengths must be positive: {box}")
    mode = "tp" if len(box) == 3 else "dp"
    rungs = tuple(int(r) for r in (grid_ladder or GRID_RUNGS))
    rungs_capped = tuple(r for r in rungs if r <= max_grid) or rungs[:1]

    pts = np.asarray(points, dtype=np.float64).reshape(-1, 3)
    lo = pts.min(axis=0) if len(pts) else np.zeros(3)
    hi = pts.max(axis=0) if len(pts) else np.zeros(3)
    logtol = math.log(1.0 / tol)
    N = max(len(pts) + int(n_fill), 1)
    N_q = max(1, 2 ** math.ceil(math.log2(N)))

    if mode == "dp":
        Dz = _ladder(max(float(hi[2] - lo[2]), 1e-3), 1e-3)
        vol = box[0] * box[1] * Dz
        min_Lper = min(box[0], box[1])
    else:
        Dz = 0.0
        vol = box[0] * box[1] * box[2]
        min_Lper = min(box)

    rc = (target_occ * vol / N_q) ** (1.0 / 3.0)
    rc = min(rc, min_Lper / 2.0)
    xi = math.sqrt(max(logtol, 1.0)) / rc
    P = max(6, min(26, int(math.ceil(logtol / 1.2)) + 2))

    # fixed point for (xi, Rz, Lz_grid, M3) under the grid cap — the dp
    # padded extent depends on xi, and a capped grid's k_max on the extent
    k_rule = 2.0 * math.sqrt(logtol + 4.0)
    Rz = 0.0
    for _ in range(4):
        if mode == "dp":
            Rz = Dz + (math.sqrt(logtol) + 3.0) / xi
            # +3 nats of headroom: at exactly logtol the smallest-kperp
            # mode's image leakage e^{-kperp (Lz - Dz)} lands ON tol with
            # a ~unit prefactor (measured 1.2e-6 at tol 1e-6 on a slab
            # cloud); the extra margin drops it to ~5e-8.
            pad_k = (logtol + 3.0) * max(box[0], box[1]) / (2.0 * math.pi)
            Lz_grid = Dz + max(Rz + 4.0 / xi, pad_k)
            L3 = (box[0], box[1], Lz_grid)
        else:
            L3 = box
        M_req = [int(math.ceil(k_rule * xi * L / math.pi)) for L in L3]
        if max(M_req) <= max_grid:
            break
        xi = (math.pi * max_grid / max(L3)) / k_rule
        rc = min(math.sqrt(max(logtol, 1.0)) / xi, min_Lper / 2.0)
        xi = math.sqrt(max(logtol, 1.0)) / rc
    M3 = tuple(max(_grid_rung(m, rungs_capped),
                   _grid_rung(2 * P, rungs_capped)) for m in M_req)
    # window variance: measured on the periodic multiplier (tp cloud,
    # tol 1e-6, P 14) — the /16 free-space balance leaves the truncation
    # side dominant at 1.9e-6 rel err; /20 rebalances to 1.0e-7, and the
    # aliasing side only reappears below /12
    tau3 = tuple((P * L / M) ** 2 / (20.0 * logtol)
                 for L, M in zip(L3, M3))

    # near-field cell lattice: periodic axes tile the box EXACTLY
    # (cells * cell_size = L, so the wrap-mod-C neighbor shell is the
    # minimum-image shell); the free axis clips like `plan_ewald`
    cell_size3 = []
    cells3 = []
    cell_lo = []
    for ax in range(3):
        if mode == "tp" or ax < 2:
            L = box[ax]
            C = max(int(L / rc), 1)
            s = L / C
            a = s * math.floor(float(lo[ax]) / s)
        else:
            s = max(rc, 1e-6)
            ext_q = _ladder(max(float(hi[2] - lo[2]), 1e-3), 1e-3)
            C = int(math.ceil(ext_q / s)) + 2
            a = s * (math.floor(float(lo[2]) / s) - 1)
        cell_size3.append(float(s))
        cells3.append(int(C))
        cell_lo.append(float(a))
    cell_size3 = tuple(cell_size3)
    cells3 = tuple(cells3)
    cell_lo = tuple(cell_lo)

    if mode == "dp":
        center_z = float(lo[2] + hi[2]) / 2.0
        anchor_z = cell_size3[2] * math.floor(center_z / cell_size3[2])
        box_lo = (cell_lo[0], cell_lo[1], float(anchor_z - L3[2] / 2.0))
    else:
        box_lo = cell_lo

    # host-side occupancy count (wrapped periodic coords + fill lattice)
    def cell_index(p):
        idx = np.empty((len(p), 3), dtype=np.int64)
        for ax in range(3):
            x = p[:, ax] - cell_lo[ax]
            if mode == "tp" or ax < 2:
                x = x - box[ax] * np.floor(x / box[ax])
            i = np.floor(x / cell_size3[ax]).astype(np.int64)
            idx[:, ax] = np.clip(i, 0, cells3[ax] - 1)
        return idx

    ci = cell_index(pts) if len(pts) else np.zeros((0, 3), np.int64)
    if n_fill:
        fp = _fill_positions_np((cell_lo, cells3, cell_size3), int(n_fill))
        ci = np.vstack([ci, cell_index(fp)])
    flat = (ci[:, 0] * cells3[1] + ci[:, 1]) * cells3[2] + ci[:, 2]
    occ = int(np.bincount(flat, minlength=int(np.prod(cells3))).max()) \
        if len(flat) else 1
    # the same x1.5 / 8-aligned occupancy rungs as `plan_ewald`
    need = occ * 1.15
    rung = 8.0
    while rung < need:
        rung *= 1.5
    occ = int(-8 * (-rung // 8))

    return SpectralPlan(mode=mode, xi=float(xi), rc=float(rc), Rz=float(Rz),
                        box_lo=box_lo, box_L=tuple(float(L) for L in L3),
                        M3=M3, P=int(P), tau3=tau3, cell_lo=cell_lo,
                        cells3=cells3, cell_size3=cell_size3, max_occ=occ,
                        eta=float(eta))


# ---------------------------------------------------------------- near field

def _wrap_positions(plan: SpectralPlan, cell_lo, pts):
    """Wrap periodic coordinates into [cell_lo, cell_lo + L); the free
    axis passes through."""
    L = jnp.asarray(plan.Lper, pts.dtype)
    per = L > 0
    Ls = jnp.where(per, L, 1.0)
    return jnp.where(per, pts - Ls * jnp.floor((pts - cell_lo) / Ls), pts)


def _min_image(d, Lper, dtype):
    """Minimum-image displacement per periodic axis (free axes untouched)."""
    L = jnp.asarray(Lper, dtype)
    per = L > 0
    Ls = jnp.where(per, L, 1.0)
    return jnp.where(per, d - Ls * jnp.round(d / Ls), d)


_NBR_OFFSETS = np.array([(i, j, k) for i in (-1, 0, 1)
                         for j in (-1, 0, 1) for k in (-1, 0, 1)],
                        dtype=np.int32)  # [27, 3]

#: elements per near-field chunk tile (see `ewald._NEAR_TILE_BUDGET`)
_NEAR_TILE_BUDGET = 3_000_000


def _bucket_points_per(plan: SpectralPlan, cell_lo, pts, payload):
    """Sort (wrapped) points into [prod(cells3), max_occ] padded buckets —
    `ewald._bucket_points` with per-axis cell sizes."""
    Cx, Cy, Cz = plan.cells3
    C3 = Cx * Cy * Cz
    cs = jnp.asarray(plan.cell_size3, pts.dtype)
    ci = jnp.floor((pts - cell_lo) / cs).astype(jnp.int32)
    ci = jnp.clip(ci, 0, jnp.asarray(plan.cells3, dtype=jnp.int32) - 1)
    flat = (ci[:, 0] * Cy + ci[:, 1]) * Cz + ci[:, 2]
    order = jnp.argsort(flat)
    flat_s = flat[order]
    pts_s = pts[order]
    pay_s = payload[order]
    counts = jnp.zeros(C3, dtype=jnp.int32).at[flat_s].add(1)
    starts = jnp.concatenate([jnp.zeros(1, jnp.int32),
                              jnp.cumsum(counts)[:-1].astype(jnp.int32)])
    rank = jnp.arange(flat_s.shape[0], dtype=jnp.int32) - starts[flat_s]
    rank = jnp.minimum(rank, plan.max_occ - 1)
    slot = flat_s * plan.max_occ + rank
    B = C3 * plan.max_occ
    # far sentinel for empty slots: wrapped into the box by min-image but
    # killed by zero payload (sources) / the occupancy mask (targets)
    bpts = jnp.full((B, 3), 1e8, dtype=pts.dtype).at[slot].set(pts_s)
    bpay = jnp.zeros((B,) + payload.shape[1:], dtype=payload.dtype
                     ).at[slot].set(pay_s)
    return (bpts.reshape(C3, plan.max_occ, 3),
            bpay.reshape((C3, plan.max_occ) + payload.shape[1:]),
            order, flat)


def _neighbor_ids(plan: SpectralPlan):
    """[C3, 27] neighbor cell ids (wrap on periodic axes, clip on the free
    axis) + the first-occurrence dedup mask."""
    Cx, Cy, Cz = plan.cells3
    C3 = Cx * Cy * Cz
    cid = jnp.arange(C3, dtype=jnp.int32)
    cx, rem = cid // (Cy * Cz), cid % (Cy * Cz)
    cy, cz = rem // Cz, rem % Cz
    offs = jnp.asarray(_NBR_OFFSETS)

    def move(c, off, C, periodic):
        n = c[:, None] + off[None, :]
        return n % C if periodic else jnp.clip(n, 0, C - 1)

    nx = move(cx, offs[:, 0], Cx, True)
    ny = move(cy, offs[:, 1], Cy, True)
    nz = move(cz, offs[:, 2], Cz, plan.mode == "tp")
    nid = (nx * Cy + ny) * Cz + nz
    eq = nid[:, :, None] == nid[:, None, :]
    tri = jnp.tril(jnp.ones((27, 27), dtype=bool), k=-1)
    uniq = ~jnp.any(eq & tri[None], axis=2)
    return nid, uniq


def _near_field_per(plan: SpectralPlan, cell_lo, r_src, f_src, r_trg,
                    near_fn):
    """Periodic cell-list near field: dense screened tiles over the 27
    wrap/clip neighbor cells with minimum-image displacements.

    ``near_fn(d, payload, xi) -> [t, 3]`` is a displacement-tile kernel
    (`ewald.stokeslet_disp_block` / `stresslet_disp_block_ewald`);
    positions must already be wrapped (`_wrap_positions`).
    """
    Cx, Cy, Cz = plan.cells3
    C3 = Cx * Cy * Cz
    mo = plan.max_occ
    Lper = plan.Lper
    src_b, f_b, _, _ = _bucket_points_per(plan, cell_lo, r_src, f_src)
    trg_b, idx_b, _, flat_t = _bucket_points_per(
        plan, cell_lo, r_trg, jnp.arange(r_trg.shape[0], dtype=jnp.int32))
    nid, uniq = _neighbor_ids(plan)

    def per_cell(t_pts, n_ids, n_uniq):
        s_pts = src_b[n_ids].reshape(-1, 3)          # [27 * mo, 3]
        pay = f_b[n_ids]
        mask = n_uniq.reshape((27,) + (1,) * (pay.ndim - 1))
        s_f = jnp.where(mask, pay, 0.0).reshape((-1,) + f_b.shape[2:])
        d = _min_image(t_pts[:, None, :] - s_pts[None, :, :], Lper,
                       t_pts.dtype)
        return near_fn(d, s_f, plan.xi)

    chunk = max(1, min(C3, _NEAR_TILE_BUDGET // max(27 * mo * mo, 1)))
    n_chunks = -(-C3 // chunk)
    pad = n_chunks * chunk - C3

    def padded(a, fill):
        widths = ((0, pad),) + ((0, 0),) * (a.ndim - 1)
        return jnp.pad(a, widths, constant_values=fill).reshape(
            (n_chunks, chunk) + a.shape[1:])

    u_b = lax.map(
        lambda args: jax.vmap(per_cell)(*args),
        (padded(trg_b, 1e8), padded(nid, 0), padded(uniq, False)))
    u_b = u_b.reshape(n_chunks * chunk, mo, 3)[:C3]

    counts_t = jnp.zeros(C3, dtype=jnp.int32).at[flat_t].add(1)
    slot_rank = jnp.arange(C3 * mo, dtype=jnp.int32) % mo
    valid = slot_rank < jnp.repeat(counts_t, mo)
    out = jnp.zeros((r_trg.shape[0], 3), dtype=r_trg.dtype)
    out = out.at[idx_b.reshape(-1)].add(
        jnp.where(valid[:, None], u_b.reshape(-1, 3), 0.0))
    return out / (8.0 * math.pi * plan.eta)


# ----------------------------------------------------------------- far field

def _window_1d_ax(x, h, tau, P, dtype):
    """One-axis separable Gaussian window (per-axis h/tau — the grid is
    anisotropic)."""
    u = x / h
    i0 = jnp.floor(u - (P - 1) / 2.0).astype(jnp.int32)
    grid_pos = (i0[:, None]
                + jnp.arange(P, dtype=jnp.int32)[None, :]).astype(dtype) * h
    d = x[:, None] - grid_pos
    return i0, jnp.exp(-d * d / (4.0 * tau))


def _window_indices(plan: SpectralPlan, pts_local, dtype):
    """Flat wrapped grid indices [N, P, P, P] + separable weight products.
    The mod-M wrap is exact physics on periodic axes and the free-axis box
    margin keeps wrapped kernel images outside every pair distance (the
    `ewald._window_indices` argument)."""
    Mx, My, Mz = plan.M3
    hx, hy, hz = plan.h3
    tx, ty, tz = plan.tau3
    P = plan.P
    ix, wx = _window_1d_ax(pts_local[:, 0], hx, tx, P, dtype)
    iy, wy = _window_1d_ax(pts_local[:, 1], hy, ty, P, dtype)
    iz, wz = _window_1d_ax(pts_local[:, 2], hz, tz, P, dtype)
    p_idx = jnp.arange(P, dtype=jnp.int32)
    gx = (ix[:, None] + p_idx[None, :]) % Mx
    gy = (iy[:, None] + p_idx[None, :]) % My
    gz = (iz[:, None] + p_idx[None, :]) % Mz
    flat = ((gx[:, :, None, None] * My + gy[:, None, :, None]) * Mz
            + gz[:, None, None, :])
    w3 = (wx[:, :, None, None] * wy[:, None, :, None]
          * wz[:, None, None, :])
    return flat, w3


#: elements per gridding chunk (see `ewald._GRID_CHUNK_BUDGET`)
_GRID_CHUNK_BUDGET = 16_000_000


def _point_chunks(plan: SpectralPlan, n):
    P3 = plan.P ** 3
    chunk = max(1, min(n, _GRID_CHUNK_BUDGET // P3))
    return chunk, -(-n // chunk)


def _spread(plan: SpectralPlan, pts_local, values, dtype):
    """Type-1 gridding onto the [Mx, My, Mz, C] grid, point-chunked."""
    Mx, My, Mz = plan.M3
    n = pts_local.shape[0]
    C = values.shape[-1]
    chunk, n_chunks = _point_chunks(plan, n)
    pad = n_chunks * chunk - n
    pts_p = jnp.pad(pts_local, ((0, pad), (0, 0))).reshape(n_chunks, chunk, 3)
    val_p = jnp.pad(values, ((0, pad), (0, 0))).reshape(n_chunks, chunk, C)

    def body(grid, args):
        pts_c, val_c = args
        flat, w3 = _window_indices(plan, pts_c, dtype)
        contrib = w3[..., None] * val_c[:, None, None, None, :]
        return grid.at[flat.reshape(-1)].add(contrib.reshape(-1, C)), None

    grid, _ = lax.scan(body, jnp.zeros((Mx * My * Mz, C), dtype=dtype),
                       (pts_p, val_p))
    return grid.reshape(Mx, My, Mz, C)


def _interp(plan: SpectralPlan, pts_local, grid, dtype):
    """Type-2 interpolation of grid [Mx, My, Mz, C] at points, chunked."""
    n = pts_local.shape[0]
    C = grid.shape[-1]
    chunk, n_chunks = _point_chunks(plan, n)
    pad = n_chunks * chunk - n
    pts_p = jnp.pad(pts_local, ((0, pad), (0, 0))).reshape(n_chunks, chunk, 3)
    flat_grid = grid.reshape(-1, C)

    def body(pts_c):
        flat, w3 = _window_indices(plan, pts_c, dtype)
        vals = flat_grid[flat.reshape(-1)].reshape(flat.shape + (C,))
        return jnp.einsum("npqr,npqrk->nk", w3, vals)

    out = lax.map(body, pts_p)
    return out.reshape(n_chunks * chunk, C)[:n]


def _kgrid_per(plan: SpectralPlan, dtype):
    """Mixed-lattice spectral geometry: (kx, ky, kz, k2, scalar) where the
    scalar folds the PERIODIC Hasimoto multiplier Bhat_per = -8 pi Phi/k^4
    (k = 0 dropped), the anisotropic quadrature factor hx hy hz, the
    separable window deconvolution, and 1/(8 pi eta)."""
    Mx, My, Mz = plan.M3
    hx, hy, hz = plan.h3
    tx, ty, tz = plan.tau3
    kx = (2.0 * math.pi * jnp.fft.fftfreq(Mx, d=hx)).astype(dtype)[
        :, None, None]
    ky = (2.0 * math.pi * jnp.fft.fftfreq(My, d=hy)).astype(dtype)[
        None, :, None]
    kz = (2.0 * math.pi * jnp.fft.rfftfreq(Mz, d=hz)).astype(dtype)[
        None, None, :]
    k2 = kx * kx + ky * ky + kz * kz
    x = k2 / (4.0 * plan.xi * plan.xi)
    ghat = (1.0 + x) * jnp.exp(-x)
    k2s = jnp.where(k2 > 0, k2, 1.0)
    Bhat = jnp.where(k2 > 0, -8.0 * math.pi * ghat / (k2s * k2s), 0.0)
    what = (((4.0 * math.pi) ** 1.5) * math.sqrt(tx * ty * tz)
            * jnp.exp(-(tx * kx * kx + ty * ky * ky + tz * kz * kz)))
    scalar = Bhat * (hx * hy * hz) / (what * what) / (8.0 * math.pi
                                                      * plan.eta)
    return kx, ky, kz, k2, scalar


def _t1_trunc(k, R):
    """1D transform of ``|z| 1_{|z|<R}``: T1(k) = 2(cos kR - 1)/k^2
    + 2 R sin(kR)/k, series R^2 (1 - (kR)^2/4 + (kR)^4/72) for small kR."""
    kR = k * R
    small = kR < 0.5
    ks = jnp.where(small, 1.0, k)
    T_exact = 2.0 * (jnp.cos(kR) - 1.0) / (ks * ks) \
        + 2.0 * R * jnp.sin(kR) / ks
    kR2 = kR * kR
    T_series = R * R * (1.0 - kR2 / 4.0 + kR2 * kR2 / 72.0)
    return jnp.where(small, T_series, T_exact)


def _column_geometry(plan: SpectralPlan, dtype):
    """Shared dp kperp = 0 column pieces: (kz [Mzh], Phi(kz), grid scale
    hx hy hz / what(0, 0, kz)^2)."""
    hx, hy, hz = plan.h3
    tx, ty, tz = plan.tau3
    Mz = plan.M3[2]
    kz = (2.0 * math.pi * jnp.fft.rfftfreq(Mz, d=hz)).astype(dtype)
    x = kz * kz / (4.0 * plan.xi * plan.xi)
    ghat = (1.0 + x) * jnp.exp(-x)
    what = (((4.0 * math.pi) ** 1.5) * math.sqrt(tx * ty * tz)
            * jnp.exp(-tz * kz * kz))
    return kz, ghat, (hx * hy * hz) / (what * what)


def _column_stokeslet(plan: SpectralPlan, Hcol, dtype):
    """dp kperp = 0 Stokeslet column: truncated 1D kernel
    K1hat_R(kz) = -(T1(kz)/2) Phi(kz) on the x/y channels; the z channel
    is zero ((I - khat khat)_zz = 0 on the column)."""
    kz, ghat, scale = _column_geometry(plan, dtype)
    s0 = (-0.5 * _t1_trunc(kz, plan.Rz)) * ghat * scale / plan.eta
    return jnp.stack([s0 * Hcol[:, 0], s0 * Hcol[:, 1],
                      jnp.zeros_like(Hcol[:, 2])], axis=-1)


def _column_stresslet(plan: SpectralPlan, Hcol, dtype):
    """dp kperp = 0 stresslet column: multiplier i Phi/(2 eta kz) with the
    sign-kernel truncation T_s(kz) = i (1 - cos kz R)/kz; channel combos
    u_x <- S_xz + S_zx, u_y <- S_yz + S_zy, u_z <- tr S (row-major 9)."""
    kz, ghat, scale = _column_geometry(plan, dtype)
    kzs = jnp.where(kz > 0, kz, 1.0)
    Ts = jnp.where(kz > 0, (1.0 - jnp.cos(kz * plan.Rz)) / kzs, 0.0)
    s0 = 1j * Ts * ghat * scale / (2.0 * plan.eta)
    return jnp.stack([s0 * (Hcol[:, 2] + Hcol[:, 6]),
                      s0 * (Hcol[:, 5] + Hcol[:, 7]),
                      s0 * (Hcol[:, 0] + Hcol[:, 4] + Hcol[:, 8])], axis=-1)


def _far_field(plan: SpectralPlan, lo, r_src, f_src, r_trg):
    """Gridded periodic Stokeslet far field (tp: pure lattice multiplier;
    dp: mixed lattice + truncated kperp = 0 column). Normalization is the
    `ewald._far_field` bookkeeping made anisotropic: the grid multiplier is
    Khat(k) hx hy hz / what(k)^2 and irfftn's 1/(Mx My Mz) supplies 1/V."""
    dtype = r_src.dtype
    Mx, My, Mz = plan.M3

    with jax.named_scope("spread"):
        H = _spread(plan, r_src - lo, f_src, dtype)
    with jax.named_scope("fft"):
        Hk = jnp.fft.rfftn(H, axes=(0, 1, 2))
    with jax.named_scope("kspace"):
        kx, ky, kz, k2, scalar = _kgrid_per(plan, dtype)
        coeff = -scalar
        kdotF = kx * Hk[..., 0] + ky * Hk[..., 1] + kz * Hk[..., 2]
        Uk = jnp.stack([
            coeff * (k2 * Hk[..., 0] - kx * kdotF),
            coeff * (k2 * Hk[..., 1] - ky * kdotF),
            coeff * (k2 * Hk[..., 2] - kz * kdotF),
        ], axis=-1)
        if plan.mode == "dp":
            Uk = Uk.at[0, 0].set(_column_stokeslet(plan, Hk[0, 0], dtype))
    with jax.named_scope("fft"):
        U = jnp.fft.irfftn(Uk, s=(Mx, My, Mz), axes=(0, 1, 2))
    with jax.named_scope("interp"):
        return _interp(plan, r_trg - lo, U.astype(dtype), dtype)


def _far_field_stresslet(plan: SpectralPlan, lo, r_dl, f_dl, r_trg):
    """Gridded periodic stresslet far field: `ewald._far_field_stresslet`'s
    9-channel multiplier on the periodic Bhat, plus the dp column."""
    dtype = r_dl.dtype
    Mx, My, Mz = plan.M3

    with jax.named_scope("spread"):
        H = _spread(plan, r_dl - lo, f_dl.reshape(-1, 9), dtype)
    with jax.named_scope("fft"):
        Hk = jnp.fft.rfftn(H, axes=(0, 1, 2))
    with jax.named_scope("kspace"):
        kx, ky, kz, k2, scalar = _kgrid_per(plan, dtype)
        coeff = 1j * scalar
        kv = (kx, ky, kz)
        kSk = sum(kv[j] * kv[k] * Hk[..., 3 * j + k]
                  for j in range(3) for k in range(3))
        Uk = jnp.stack([
            coeff * (kv[i] * kSk
                     - 0.5 * k2 * (sum(kv[k] * (Hk[..., 3 * i + k]
                                                + Hk[..., 3 * k + i])
                                       for k in range(3))
                                   + (Hk[..., 0] + Hk[..., 4] + Hk[..., 8])
                                   * kv[i]))
            for i in range(3)], axis=-1)
        if plan.mode == "dp":
            Uk = Uk.at[0, 0].set(_column_stresslet(plan, Hk[0, 0], dtype))
    with jax.named_scope("fft"):
        U = jnp.fft.irfftn(Uk, s=(Mx, My, Mz), axes=(0, 1, 2))
    with jax.named_scope("interp"):
        return _interp(plan, r_trg - lo, U.astype(dtype), dtype)


# ------------------------------------------------------------ jitted entries

@partial(jax.jit, static_argnames=("plan", "n_self"))
def _stokeslet_spectral_impl(plan: SpectralPlan, anchors, r_src, r_trg,
                             f_src, n_self: int):
    """Jitted core; ``plan`` must be anchor-stripped and ``anchors`` is the
    [2, 3] (box_lo, cell_lo) traced operand."""
    lo_box = anchors[0].astype(r_src.dtype)
    lo_cell = anchors[1].astype(r_src.dtype)
    src_w = _wrap_positions(plan, lo_cell, r_src)
    trg_w = _wrap_positions(plan, lo_cell, r_trg)
    with jax.named_scope("near"):
        u_near = _near_field_per(plan, lo_cell, src_w, f_src, trg_w,
                                 near_fn=stokeslet_disp_block)
    u_far = _far_field(plan, lo_box, src_w, f_src, trg_w)
    if n_self:
        # the wave-space sum at a coincident target includes only the
        # p = 0 image's smooth G_far(0) — the free-space self coefficient;
        # every p != 0 image term is a genuine periodic contribution
        self_coeff = 4.0 * plan.xi / (_SQRT_PI * 8.0 * math.pi * plan.eta)
        u_far = u_far.at[:n_self].add(-self_coeff * f_src[:n_self])
    return u_near + u_far


def stokeslet_spectral(plan: SpectralPlan, r_src, r_trg, f_src,
                       n_self: int | None = None):
    """Singular periodic Stokeslet sum via spectral Ewald.

    Same calling convention as `ewald.stokeslet_ewald` (coincident self
    pairs drop; ``n_self`` marks the leading targets coinciding with
    ``r_src[:n_self]``, auto-detected by object identity), summed over the
    periodic images of `plan`'s box with the zero-mean-flow (k = 0
    dropped) convention. Positions may be unwrapped — both the cell list
    and the spreading wrap them against the traced anchors.
    """
    if n_self is None:
        n_self = r_src.shape[0] if r_trg is r_src else 0
    return _stokeslet_spectral_impl(strip_anchors(plan),
                                    plan_anchors(plan, r_src.dtype),
                                    r_src, r_trg, f_src, int(n_self))


@partial(jax.jit, static_argnames=("plan",))
def _stresslet_spectral_impl(plan: SpectralPlan, anchors, r_dl, r_trg,
                             f_dl):
    lo_box = anchors[0].astype(r_dl.dtype)
    lo_cell = anchors[1].astype(r_dl.dtype)
    src_w = _wrap_positions(plan, lo_cell, r_dl)
    trg_w = _wrap_positions(plan, lo_cell, r_trg)
    with jax.named_scope("near"):
        u_near = _near_field_per(plan, lo_cell, src_w, f_dl, trg_w,
                                 near_fn=stresslet_disp_block_ewald)
    u_far = _far_field_stresslet(plan, lo_box, src_w, f_dl, trg_w)
    # no self term: every screened double-layer coefficient vanishes at
    # r = 0 (`ewald.stresslet_near_block_ewald`)
    return u_near + u_far


def stresslet_spectral(plan: SpectralPlan, r_dl, r_trg, f_dl):
    """Singular periodic stresslet (double-layer) sum via spectral Ewald
    (``f_dl`` [n_src, 3, 3], same semantics as `ewald.stresslet_ewald`)."""
    return _stresslet_spectral_impl(strip_anchors(plan),
                                    plan_anchors(plan, r_dl.dtype),
                                    r_dl, r_trg, f_dl)


# ---------------------------------------------------------------- skelly-audit

def auditable_programs():
    """The periodic fast path's audit entry: the jitted spectral Stokeslet
    on a triply-periodic cloud. Its contract pins that the evaluator is
    collective-free single-chip, callback-free, carries the state dtype end
    to end, owns a PINNED fft inventory (the first registered program with
    fft primitives — the `fft-inventory` check exists for it), and compiles
    once across a cell-lattice anchor hop with drifted positions."""
    from ..audit.registry import AuditProgram, built_from

    def make_scene():
        rng = np.random.default_rng(17)
        box = (4.0, 4.0, 4.0)
        pts = rng.uniform(0.0, 4.0, (256, 3))
        f = rng.standard_normal((256, 3))
        plan = plan_spectral(pts, box, eta=1.0, tol=1e-4)
        return plan, jnp.asarray(pts), jnp.asarray(f)

    def build():
        plan, pts, f = make_scene()
        return built_from(_stokeslet_spectral_impl, strip_anchors(plan),
                          plan_anchors(plan), pts, pts, f, pts.shape[0])

    def retrace_probe():
        from ..testing import trace_counting_jit

        plan, pts, f = make_scene()
        step = trace_counting_jit(_stokeslet_spectral_impl.__wrapped__,
                                  static_argnames=("plan", "n_self"))
        step(strip_anchors(plan), plan_anchors(plan), pts, pts, f,
             pts.shape[0])
        # anchor hop + drifted positions: same program, must not retrace
        step(strip_anchors(plan), plan_anchors(plan) + plan.cell_size3[0],
             pts + 0.01, pts + 0.01, f, pts.shape[0])
        return step.trace_count

    return [AuditProgram(
        name="stokeslet_spectral", layer="ops",
        summary="periodic spectral-Ewald Stokeslet evaluator (triply "
                "periodic cloud, FFT far field + wrapped near tiles, f64)",
        build=build, retrace_probe=retrace_probe)]
