"""Pairwise Stokes kernels (Stokeslet / stresslet / rotlet / regularized Oseen).

TPU-native re-implementation of the reference evaluator seam
(`/root/reference/include/kernels.hpp:14-51`, `/root/reference/src/core/kernels.cpp`):
the uniform `Evaluator` signature (r_sl, r_dl, r_trg, f_sl, f_dl, eta) maps here to
plain jit-able functions over `[n, 3]` row-major arrays. All functions are pure,
shape-static, and differentiable; the hot all-pairs sums are evaluated in target
blocks so XLA can tile the distance matmuls onto the MXU without materializing the
full O(N^2) interaction tensor.

Conventions (matched to the reference semantics):

* Stokeslet (Oseen tensor): ``u_i = 1/(8 pi eta) * sum_j [ f_j / r + (d . f_j) d / r^3 ]``
  with ``d = x_trg - x_src`` and the self term (r == 0) dropped
  (`src/core/kernels.cpp:54-67` scale factor 1/(8 pi), divided by eta).
* Stresslet ("stokes_doublevel", 9-component double-layer source):
  ``u = 1/(8 pi eta) * sum_j -3 (d^T S_j d) d / r^5`` (`src/core/kernels.cpp:11-40`).
* Regularized Oseen: for ``r <= epsilon_distance`` replace ``1/r -> 1/sqrt(r^2+reg^2)``
  (`src/core/kernels.cpp:85-195`, defaults reg=5e-3, eps=1e-5 `include/kernels.hpp:35-51`).
* Rotlet: ``u = 1/(8 pi eta) * sum_j (rho_j x d) / r^3`` (`src/core/kernels.cpp:206-242`).
* stresslet_times_normal(_times_density): factor -3/(4 pi), no eta
  (`src/core/kernels.cpp:264-334`); consistent with the stresslet above under the
  double-layer convention ``f_dl = 2 eta n (x) rho``.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

DEFAULT_REG = 5e-3
DEFAULT_EPS = 1e-5

__all__ = [
    "stokeslet_direct",
    "stresslet_direct",
    "oseen_contract",
    "oseen_tensor",
    "rotlet",
    "stresslet_times_normal",
    "stresslet_times_normal_times_density",
]


def _block_iter(n: int, block: int) -> int:
    """Number of blocks covering n (n padded up to a multiple of block)."""
    return -(-n // block)


def _blocked_target_sum(kernel_fn, r_trg, block_size):
    """Evaluate ``kernel_fn(trg_block) -> [b, 3]`` over target blocks via lax.map.

    Pads targets to a block multiple so every iteration has a static shape; the
    padding rows compute garbage that is sliced off. This keeps compile time flat
    across target counts within the same padded bucket while bounding peak memory
    at O(block_size * n_src).
    """
    n_trg = r_trg.shape[0]
    if n_trg == 0:
        return jnp.zeros((0, 3), dtype=r_trg.dtype)
    nb = _block_iter(n_trg, block_size)
    pad = nb * block_size - n_trg
    r_pad = jnp.pad(r_trg, ((0, pad), (0, 0)))
    blocks = r_pad.reshape(nb, block_size, 3)
    u = lax.map(kernel_fn, blocks)
    return u.reshape(nb * block_size, 3)[:n_trg]


#: sources beyond this count are chunked (the [t_block, n_src] intermediates
#: would otherwise scale HBM use linearly with n_src — 640k sources against
#: a 4096-target block is a 31 GB displacement tensor)
_SRC_CHUNK_THRESHOLD = 32768
_DEFAULT_SRC_BLOCK = 8192


def _pair_sum(pair_fn, r_trg, src_arrays, block_size, source_block):
    """Target-blocked, source-chunked pairwise sum.

    ``pair_fn(trg_block, *src_chunk_arrays) -> [t, 3]`` must give zero
    contribution for zero-padded sources (every kernel here does: padded
    strengths are zero, and exactly-coincident pairs are masked).
    """
    n_src = src_arrays[0].shape[0]
    if source_block is None:
        source_block = (_DEFAULT_SRC_BLOCK if n_src > _SRC_CHUNK_THRESHOLD
                        else None)
    if source_block is None or n_src <= source_block:
        return _blocked_target_sum(lambda trg: pair_fn(trg, *src_arrays),
                                   r_trg, block_size)
    ns_b = _block_iter(n_src, source_block)
    pad = ns_b * source_block - n_src
    chunks = tuple(
        jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1)).reshape(
            (ns_b, source_block) + a.shape[1:])
        for a in src_arrays)

    def kernel(trg):
        def body(acc, chunk):
            return acc + pair_fn(trg, *chunk), None

        acc, _ = lax.scan(body, jnp.zeros((trg.shape[0], 3), dtype=trg.dtype),
                          chunks)
        return acc

    return _blocked_target_sum(kernel, r_trg, block_size)


def stokeslet_block(trg, src, f_src):
    """Unscaled Stokeslet partial sum of one (target-block, source-block) pair.

    Shared by the blocked single-program path and the ring evaluator
    (`parallel/ring.py`) so the masking/regularization semantics cannot
    diverge between backends.
    """
    d = trg[:, None, :] - src[None, :, :]
    r2 = jnp.sum(d * d, axis=-1)
    mask = r2 > 0.0
    rinv = jnp.where(mask, lax.rsqrt(jnp.where(mask, r2, 1.0)), 0.0)
    rinv3 = rinv * rinv * rinv
    df = jnp.einsum("tsk,sk->ts", d, f_src)
    return jnp.einsum("ts,sk->tk", rinv, f_src) + jnp.einsum("ts,tsk->tk", df * rinv3, d)


def stokeslet_block_mxu(trg, src, f_src):
    """`stokeslet_block` restructured so the O(t*s*3) contractions are MXU
    matmuls instead of reductions over a materialized [t, s, 3] displacement
    tensor:

      r2_ts = |t|^2 + |s|^2 - 2 (t @ s^T)               (one [t,3]x[3,s] matmul)
      df_ts = (t @ f^T) - (s . f)_s                      (one matmul)
      u_tk  = rinv @ f + t_k * rowsum(c) - c @ s,  c = df * rinv^3
                                                         (two [t,s]x[s,3] matmuls)

    Only rsqrt + ~6 multiplies per pair stay elementwise on the VPU.

    NUMERICS CAVEAT (why this is opt-in, not the default): the subtraction
    form loses absolute accuracy ~eps * (|t'|^2 + |s'|^2) on r2, so (a) exact
    self-pair detection by r2 == 0 is no longer reliable — pairs are instead
    masked below a relative threshold 16 eps (|t'|^2+|s'|^2), i.e.
    separations under ~4 sqrt(eps) |t'| are treated as coincident — and (b)
    near-field pairs closer than ~sqrt(eps) |t'| carry O(1) relative error.

    Coordinates are recentered on the source block's *first point* (t', s'):
    the dangerous pairs are close ones, and a close target sits near the
    source block, so when source blocks are spatially local (consecutive
    nodes of one fiber; `fibers.container.sort_fibers_morton` for whole
    clouds) |t'| is the block extent and both bounds tighten to harmless.
    Pure far-field blocks have large r2, where the subtraction form is
    accurate anyway. The first point — not the mean — because zero- or
    sentinel-padded tail sources (the ring evaluator pads at 1e7) would
    drag a mean arbitrarily far from the real points.
    """
    center = src[0]
    trg = trg - center
    src = src - center
    eps = jnp.finfo(trg.dtype).eps
    t2 = jnp.sum(trg * trg, axis=1)
    s2 = jnp.sum(src * src, axis=1)
    ts = trg @ src.T
    scale = t2[:, None] + s2[None, :]
    r2 = jnp.maximum(scale - 2.0 * ts, 0.0)
    mask = r2 > 16.0 * eps * scale
    rinv = jnp.where(mask, lax.rsqrt(jnp.where(mask, r2, 1.0)), 0.0)
    rinv3 = rinv * rinv * rinv
    df = trg @ f_src.T - jnp.sum(src * f_src, axis=1)[None, :]
    c = df * rinv3
    return rinv @ f_src + trg * jnp.sum(c, axis=1, keepdims=True) - c @ src


def stresslet_block_mxu(trg, src, S):
    """`stresslet_block` in matmul form (same strategy and numerics caveat as
    `stokeslet_block_mxu`): with d = t - s,

      d.S.d = T9 @ S9^T - t @ (S s + S^T s)^T + (s.S.s)     (matmuls; T9/S9
               are the 9 coordinate products t_i t_j / S_ij per point)
      u_tk  = t_k rowsum(c) - c @ s,   c = -3 (d.S.d) r^-5   (two matmuls)

    leaving rsqrt + ~6 multiplies per pair on the VPU. Like
    `stokeslet_block_mxu`, coordinates recenter on the source block's first
    point.
    """
    center = src[0]
    trg = trg - center
    src = src - center
    eps = jnp.finfo(trg.dtype).eps
    t2 = jnp.sum(trg * trg, axis=1)
    s2 = jnp.sum(src * src, axis=1)
    scale = t2[:, None] + s2[None, :]
    r2 = jnp.maximum(scale - 2.0 * (trg @ src.T), 0.0)
    mask = r2 > 16.0 * eps * scale
    rinv = jnp.where(mask, lax.rsqrt(jnp.where(mask, r2, 1.0)), 0.0)
    rinv5 = (rinv * rinv) ** 2 * rinv

    T9 = (trg[:, :, None] * trg[:, None, :]).reshape(trg.shape[0], 9)
    S9 = S.reshape(S.shape[0], 9)
    Ss = jnp.einsum("sij,sj->si", S, src)
    STs = jnp.einsum("sij,si->sj", S, src)
    sSs = jnp.einsum("si,si->s", src, Ss)
    dSd = T9 @ S9.T - trg @ (Ss + STs).T + sSs[None, :]
    c = -3.0 * dSd * rinv5
    return trg * jnp.sum(c, axis=1, keepdims=True) - c @ src


def stresslet_block(trg, src, S):
    """Unscaled stresslet partial sum of one (target-block, source-block) pair."""
    d = trg[:, None, :] - src[None, :, :]
    r2 = jnp.sum(d * d, axis=-1)
    mask = r2 > 0.0
    rinv = jnp.where(mask, lax.rsqrt(jnp.where(mask, r2, 1.0)), 0.0)
    rinv5 = rinv * rinv * rinv * rinv * rinv
    dSd = jnp.einsum("tsi,sij,tsj->ts", d, S, d)
    return jnp.einsum("ts,tsk->tk", -3.0 * dSd * rinv5, d)


def oseen_block(trg, src, density, eta, reg, epsilon_distance):
    """Regularized-Oseen partial sum (already eta-scaled via fr/gr)."""
    d = trg[:, None, :] - src[None, :, :]
    r2 = jnp.sum(d * d, axis=-1)
    fr, gr = _regularized_frgr(r2, eta, reg, epsilon_distance)
    df = jnp.einsum("tsk,sk->ts", d, density)
    return jnp.einsum("ts,sk->tk", fr, density) + jnp.einsum("ts,tsk->tk", gr * df, d)


def pallas_impl_for(impl: str, *arrays) -> str:
    """Resolve ``impl="pallas"`` against the pallas tier's dtype contract.

    The pallas tier is f32-only: any f64 operand (full-precision solves,
    mixed-mode refinement flows that resolve to a concrete impl name)
    downgrades to the exact XLA path, mirroring how the f64 accuracy tier
    stays off the MXU tiles. One predicate shared by the direct seam here
    and the ring evaluator (`parallel.ring`) so the contract cannot drift
    between them. Non-pallas names pass through untouched.
    """
    if impl == "pallas" and any(jnp.asarray(a).dtype == jnp.float64
                                for a in arrays):
        return "exact"
    return impl


@partial(jax.jit, static_argnames=("block_size", "source_block", "impl"))
def stokeslet_direct(r_src, r_trg, f_src, eta, *, block_size: int = 4096,
                     source_block: int | None = None, impl: str = "exact"):
    """Singular Stokeslet sum: [n_src,3] sources, [n_trg,3] targets -> [n_trg,3].

    Self-interactions (exactly coincident points) contribute zero, matching
    `pvfmm::stokes_vel` / `src/core/kernels.cu:17-41`. Sources beyond
    ``_SRC_CHUNK_THRESHOLD`` are scanned in ``source_block`` chunks so peak
    memory stays O(block_size * source_block) at BASELINE scale (640k nodes).

    ``impl="mxu"`` selects the matmul-form tile (`stokeslet_block_mxu`) that
    moves the O(N^2 * 3) contractions onto the MXU — see its numerics caveat
    and per-source-block recentering. ``impl="df"`` evaluates in double-float
    f32 arithmetic (`df_kernels.stokeslet_direct_df`, ~1e-14 per-pair
    relative) — the accuracy tier for refinement residuals on hardware whose
    native f64 is emulated. ``impl="pallas_df"`` is the same arithmetic as a
    fused Pallas VMEM tile (`pallas_df.stokeslet_pallas_df`) — Mosaic on
    real TPUs, interpret mode on CPU. The DF tiers return ``r_trg.dtype``
    like every other impl (an f32 solve must not silently promote to f64);
    callers that want the f64-valued result of f32 inputs use the DF
    kernels directly.
    """
    if impl == "pallas_df":
        from .pallas_df import stokeslet_pallas_df

        u = stokeslet_pallas_df(r_src, r_trg, f_src, eta,
                                interpret=jax.default_backend() == "cpu")
        # seam contract: preserve the caller's dtype — the DF tiles return
        # f64 unconditionally, which silently promoted an f32 solve's whole
        # Krylov pipeline to f64 (callers wanting the f64 output call the
        # DF kernels directly)
        return u.astype(r_trg.dtype)
    if impl == "df":
        from .df_kernels import stokeslet_direct_df

        u = stokeslet_direct_df(
            r_src, r_trg, f_src, eta, block_size=min(block_size, 1024),
            source_block=source_block or 4096)
        return u.astype(r_trg.dtype)  # see the pallas_df branch
    impl = pallas_impl_for(impl, r_trg, r_src, f_src)
    if impl == "pallas":
        # fused VMEM-tile kernel (`ops.pallas_kernels`); Mosaic lowering on
        # real TPUs (measured ~53 Gpairs/s vs ~15 for the XLA path on v5e),
        # interpret mode on CPU (tests / fallback).
        from .pallas_kernels import stokeslet_pallas

        return stokeslet_pallas(r_src, r_trg, f_src, eta,
                                interpret=jax.default_backend() == "cpu")
    factor = 1.0 / (8.0 * math.pi)
    if impl == "mxu":
        u = _pair_sum(stokeslet_block_mxu, r_trg, (r_src, f_src),
                      block_size, source_block)
    else:
        u = _pair_sum(stokeslet_block, r_trg, (r_src, f_src), block_size,
                      source_block)
    return u * (factor / eta)


@partial(jax.jit, static_argnames=("block_size", "source_block", "impl"))
def stresslet_direct(r_dl, r_trg, f_dl, eta, *, block_size: int = 4096,
                     source_block: int | None = None, impl: str = "exact"):
    """Singular stresslet (double-layer) sum.

    ``f_dl`` is [n_src, 3, 3] (the 9-component source S with rows indexed like the
    reference's sxx..szz, i.e. ``f_dl[s, i, j] = S_ij``); returns [n_trg, 3].
    ``impl="mxu"`` selects the matmul-form tile (`stresslet_block_mxu`,
    recentered per source block on its first point — see
    `stokeslet_block_mxu`'s caveat). ``impl="df"`` evaluates in double-float
    f32 arithmetic (`df_kernels.stresslet_direct_df`); ``impl="pallas_df"``
    is the fused Pallas tile of the same arithmetic. Both return
    ``r_trg.dtype`` (see `stokeslet_direct`).
    """
    if impl == "pallas_df":
        from .pallas_df import stresslet_pallas_df

        u = stresslet_pallas_df(r_dl, r_trg, f_dl, eta,
                                interpret=jax.default_backend() == "cpu")
        return u.astype(r_trg.dtype)  # see stokeslet_direct's pallas_df branch
    if impl == "df":
        from .df_kernels import stresslet_direct_df

        u = stresslet_direct_df(
            r_dl, r_trg, f_dl, eta, block_size=min(block_size, 1024),
            source_block=source_block or 4096)
        return u.astype(r_trg.dtype)  # see stokeslet_direct's pallas_df branch
    impl = pallas_impl_for(impl, r_trg, r_dl, f_dl)
    if impl == "pallas":
        # see `stokeslet_direct`'s pallas branch
        from .pallas_kernels import stresslet_pallas

        return stresslet_pallas(r_dl, r_trg, f_dl, eta,
                                interpret=jax.default_backend() == "cpu")
    factor = 1.0 / (8.0 * math.pi)
    if impl == "mxu":
        u = _pair_sum(stresslet_block_mxu, r_trg, (r_dl, f_dl),
                      block_size, source_block)
    else:
        u = _pair_sum(stresslet_block, r_trg, (r_dl, f_dl), block_size,
                      source_block)
    return u * (factor / eta)


def _reg_rinv(r2, reg, epsilon_distance, *, inclusive: bool, drop_self: bool):
    """1/r with the reference's near-field regularization, NaN-safe for gradients.

    ``inclusive`` picks the boundary test (`r <= eps` for the Oseen kernels
    `src/core/kernels.cpp:108`, strict `r < eps` for rotlet/stresslet
    `src/core/kernels.cpp:225,278`). ``drop_self`` zeroes exactly-coincident
    pairs (the Oseen/stresslet self-term skip); when False the regularized
    value is kept even at r == 0 (rotlet semantics — its contribution still
    vanishes because the displacement is zero).
    """
    eps2 = epsilon_distance * epsilon_distance
    near = (r2 <= eps2) if inclusive else (r2 < eps2)
    r2_eff = jnp.where(near, r2 + reg * reg, r2)
    if drop_self:
        nonzero = r2 > 0.0
        return jnp.where(nonzero, lax.rsqrt(jnp.where(nonzero, r2_eff, 1.0)), 0.0)
    return lax.rsqrt(jnp.maximum(r2_eff, jnp.finfo(r2.dtype).tiny))


def _regularized_frgr(r2, eta, reg, epsilon_distance):
    """fr = 1/(8 pi eta r), gr = 1/(8 pi eta r^3) with the reference's regularization.

    Exactly coincident points (r == 0) give zero; points closer than
    ``epsilon_distance`` use ``r -> sqrt(r^2 + reg^2)`` (`src/core/kernels.cpp:96-115`).
    """
    factor = 1.0 / (8.0 * math.pi * eta)
    rinv = _reg_rinv(r2, reg, epsilon_distance, inclusive=True, drop_self=True)
    fr = factor * rinv
    gr = factor * rinv * rinv * rinv
    return fr, gr


@partial(jax.jit, static_argnames=("block_size", "source_block"))
def oseen_contract(r_src, r_trg, density, eta, reg=DEFAULT_REG,
                   epsilon_distance=DEFAULT_EPS, *, block_size: int = 4096,
                   source_block: int | None = None):
    """Regularized Oseen tensor contracted with a density: -> [n_trg, 3].

    Mirror of `kernels::oseen_tensor_contract_direct` (`src/core/kernels.cpp:85-131`).
    """
    return _pair_sum(
        lambda trg, src, dens: oseen_block(trg, src, dens, eta, reg,
                                           epsilon_distance),
        r_trg, (r_src, density), block_size, source_block)


@jax.jit
def oseen_tensor(r_src, r_trg, eta, reg=DEFAULT_REG, epsilon_distance=DEFAULT_EPS):
    """Dense regularized Oseen tensor: -> [n_trg, 3, n_src, 3].

    Mirror of `kernels::oseen_tensor_direct` (`src/core/kernels.cpp:146-195`); reshape
    to ``(3*n_trg, 3*n_src)`` for the reference's interleaved-xyz layout. Used for the
    per-fiber dense self-mobility block, so it is not target-blocked.
    """
    d = r_trg[:, None, :] - r_src[None, :, :]
    r2 = jnp.sum(d * d, axis=-1)
    fr, gr = _regularized_frgr(r2, eta, reg, epsilon_distance)
    eye = jnp.eye(3, dtype=r_src.dtype)
    G = fr[:, :, None, None] * eye[None, None] + gr[:, :, None, None] * d[:, :, :, None] * d[:, :, None, :]
    # [n_trg, n_src, 3, 3] -> [n_trg, 3, n_src, 3]
    return jnp.transpose(G, (0, 2, 1, 3))


@partial(jax.jit, static_argnames=("block_size", "source_block"))
def rotlet(r_src, r_trg, density, eta, reg=DEFAULT_REG, epsilon_distance=DEFAULT_EPS,
           *, block_size: int = 4096, source_block: int | None = None):
    """Rotlet sum ``u = 1/(8 pi eta) sum_j (rho_j x d)/r^3`` -> [n_trg, 3].

    Mirror of `kernels::rotlet` (`src/core/kernels.cpp:206-242`). Note the reference
    regularizes by the *squared* epsilon test on r^2 and keeps the (zero) self term.
    """
    factor = 1.0 / (8.0 * math.pi * eta)

    def block(trg, src, dens):
        d = trg[:, None, :] - src[None, :, :]
        r2 = jnp.sum(d * d, axis=-1)
        rinv = _reg_rinv(r2, reg, epsilon_distance, inclusive=False, drop_self=False)
        fr = rinv * rinv * rinv
        cross = jnp.cross(dens[None, :, :], d)
        return jnp.einsum("ts,tsk->tk", fr, cross)

    return _pair_sum(block, r_trg, (r_src, density), block_size,
                     source_block) * factor


@jax.jit
def stresslet_times_normal(r, normals, eta, reg=DEFAULT_REG, epsilon_distance=DEFAULT_EPS):
    """Dense stresslet-contracted-with-normal operator -> [n, 3, n, 3].

    ``M[i, :, j, :] = -3/(4 pi) (d . n_j) / r^5 * d d^T`` with ``d = r_i - r_j`` and
    zero diagonal blocks. Mirror of `kernels::stresslet_times_normal`
    (`src/core/kernels.cpp:264-287`; note: no eta dependence). Reshape to
    ``(3n, 3n)`` for the reference layout.
    """
    factor = -3.0 / (4.0 * math.pi)
    n = r.shape[0]
    d = r[:, None, :] - r[None, :, :]
    r2 = jnp.sum(d * d, axis=-1)
    offdiag = ~jnp.eye(n, dtype=bool)
    rinv = _reg_rinv(r2, reg, epsilon_distance, inclusive=False, drop_self=False)
    rinv5 = rinv ** 5
    dn = jnp.einsum("ijk,jk->ij", d, normals)
    coeff = jnp.where(offdiag, factor * dn * rinv5, 0.0)
    M = coeff[:, :, None, None] * d[:, :, :, None] * d[:, :, None, :]
    return jnp.transpose(M, (0, 2, 1, 3))


@partial(jax.jit, static_argnames=("block_size",))
def stresslet_times_normal_blocked(r, normals, eta, reg=DEFAULT_REG,
                                   epsilon_distance=DEFAULT_EPS, *,
                                   block_size: int = 512):
    """Row-blocked `stresslet_times_normal` returning the [3n, 3n] matrix
    directly (interleaved-xyz layout, = the 4D form's `.reshape(3n, 3n)`).

    Two reasons over the dense 4D builder: peak memory is
    O(block_size * n) instead of O(n^2) intermediates, and no [.., n, 3]
    array is ever materialized — XLA's (8, 128) tiled layout pads a
    trailing dim of 3 to 128, a 42x HBM blowup that turns a 6000-node
    shell operator into a 55 GB allocation.
    """
    factor = -3.0 / (4.0 * math.pi)
    n = r.shape[0]
    nb = _block_iter(n, block_size)
    pad = nb * block_size - n
    r_pad = jnp.pad(r, ((0, pad), (0, 0)))
    row_idx = jnp.arange(nb * block_size, dtype=jnp.int32).reshape(nb,
                                                                   block_size)
    col_idx = jnp.arange(n, dtype=jnp.int32)

    def rows(args):
        trg, idx = args
        b = trg.shape[0]
        d = trg[:, None, :] - r[None, :, :]
        r2 = jnp.sum(d * d, axis=-1)
        offdiag = idx[:, None] != col_idx[None, :]
        rinv = _reg_rinv(r2, reg, epsilon_distance, inclusive=False,
                         drop_self=False)
        dn = jnp.einsum("bjk,jk->bj", d, normals)
        coeff = jnp.where(offdiag, factor * dn * rinv**5, 0.0)
        M = coeff[:, :, None, None] * d[:, :, :, None] * d[:, :, None, :]
        # [b, n, 3, 3] -> [b, 3(row), n, 3(col)] -> [3b, 3n]: the transpose
        # fuses into the block's output copy, which is 2-D (no padded-3 dims)
        return jnp.transpose(M, (0, 2, 1, 3)).reshape(3 * b, 3 * n)

    M = lax.map(rows, (r_pad.reshape(nb, block_size, 3), row_idx))
    return M.reshape(3 * nb * block_size, 3 * n)[:3 * n]


def subtract_singularity_columns(M, sing_vecs, weights):
    """Second-kind singularity subtraction on a [3n, 3n] interleaved matrix.

    ``M[3i+a, 3i+k] -= e_k[i, a] / w_i`` for the three singularity vectors
    ``sing_vecs = (ex, ey, ez)`` (each [n, 3]) — the diagonal-block
    correction of `precompute.py:113-130` / `body_spherical.cpp:168-181`,
    scattered in 2-D so no [.., n, 3]-shaped intermediate is materialized
    (XLA tile-pads a trailing dim of 3 to 128: 42x HBM).
    """
    n = weights.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    rows = 3 * idx[:, None] + jnp.arange(3, dtype=jnp.int32)[None, :]  # [n, 3]
    for k, e in enumerate(sing_vecs):
        M = M.at[rows, (3 * idx + k)[:, None]].add(-e / weights[:, None])
    return M


@partial(jax.jit, static_argnames=("block_size",))
def stresslet_times_normal_times_density(r, normals, density, eta, reg=DEFAULT_REG,
                                         epsilon_distance=DEFAULT_EPS, *, block_size: int = 4096):
    """Contracted stresslet ``S_i = -3/(4 pi) sum_{j != i} (d.rho_j)(d.n_j)/r^5 d``.

    Mirror of `kernels::stresslet_times_normal_times_density`
    (`src/core/kernels.cpp:307-334`). Sources and targets are the same point set;
    the diagonal is excluded via the r > 0 mask (the reference skips i == j).
    """
    factor = -3.0 / (4.0 * math.pi)

    def block(trg):
        d = trg[:, None, :] - r[None, :, :]
        r2 = jnp.sum(d * d, axis=-1)
        rinv = _reg_rinv(r2, reg, epsilon_distance, inclusive=False, drop_self=True)
        rinv5 = rinv ** 5
        dn = jnp.einsum("tsk,sk->ts", d, normals)
        dr_ = jnp.einsum("tsk,sk->ts", d, density)
        return jnp.einsum("ts,tsk->tk", dn * dr_ * rinv5, d)

    return _blocked_target_sum(block, r, block_size) * factor
