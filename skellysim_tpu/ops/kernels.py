"""Pairwise Stokes kernels (Stokeslet / stresslet / rotlet / regularized Oseen).

TPU-native re-implementation of the reference evaluator seam
(`/root/reference/include/kernels.hpp:14-51`, `/root/reference/src/core/kernels.cpp`):
the uniform `Evaluator` signature (r_sl, r_dl, r_trg, f_sl, f_dl, eta) maps here to
plain jit-able functions over `[n, 3]` row-major arrays. All functions are pure,
shape-static, and differentiable; the hot all-pairs sums are evaluated in target
blocks so XLA can tile the distance matmuls onto the MXU without materializing the
full O(N^2) interaction tensor.

Conventions (matched to the reference semantics):

* Stokeslet (Oseen tensor): ``u_i = 1/(8 pi eta) * sum_j [ f_j / r + (d . f_j) d / r^3 ]``
  with ``d = x_trg - x_src`` and the self term (r == 0) dropped
  (`src/core/kernels.cpp:54-67` scale factor 1/(8 pi), divided by eta).
* Stresslet ("stokes_doublevel", 9-component double-layer source):
  ``u = 1/(8 pi eta) * sum_j -3 (d^T S_j d) d / r^5`` (`src/core/kernels.cpp:11-40`).
* Regularized Oseen: for ``r <= epsilon_distance`` replace ``1/r -> 1/sqrt(r^2+reg^2)``
  (`src/core/kernels.cpp:85-195`, defaults reg=5e-3, eps=1e-5 `include/kernels.hpp:35-51`).
* Rotlet: ``u = 1/(8 pi eta) * sum_j (rho_j x d) / r^3`` (`src/core/kernels.cpp:206-242`).
* stresslet_times_normal(_times_density): factor -3/(4 pi), no eta
  (`src/core/kernels.cpp:264-334`); consistent with the stresslet above under the
  double-layer convention ``f_dl = 2 eta n (x) rho``.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

DEFAULT_REG = 5e-3
DEFAULT_EPS = 1e-5

__all__ = [
    "stokeslet_direct",
    "stresslet_direct",
    "oseen_contract",
    "oseen_tensor",
    "rotlet",
    "stresslet_times_normal",
    "stresslet_times_normal_times_density",
]


def _block_iter(n: int, block: int) -> int:
    """Number of blocks covering n (n padded up to a multiple of block)."""
    return -(-n // block)


def _blocked_target_sum(kernel_fn, r_trg, block_size):
    """Evaluate ``kernel_fn(trg_block) -> [b, 3]`` over target blocks via lax.map.

    Pads targets to a block multiple so every iteration has a static shape; the
    padding rows compute garbage that is sliced off. This keeps compile time flat
    across target counts within the same padded bucket while bounding peak memory
    at O(block_size * n_src).
    """
    n_trg = r_trg.shape[0]
    if n_trg == 0:
        return jnp.zeros((0, 3), dtype=r_trg.dtype)
    nb = _block_iter(n_trg, block_size)
    pad = nb * block_size - n_trg
    r_pad = jnp.pad(r_trg, ((0, pad), (0, 0)))
    blocks = r_pad.reshape(nb, block_size, 3)
    u = lax.map(kernel_fn, blocks)
    return u.reshape(nb * block_size, 3)[:n_trg]


def stokeslet_block(trg, src, f_src):
    """Unscaled Stokeslet partial sum of one (target-block, source-block) pair.

    Shared by the blocked single-program path and the ring evaluator
    (`parallel/ring.py`) so the masking/regularization semantics cannot
    diverge between backends.
    """
    d = trg[:, None, :] - src[None, :, :]
    r2 = jnp.sum(d * d, axis=-1)
    mask = r2 > 0.0
    rinv = jnp.where(mask, lax.rsqrt(jnp.where(mask, r2, 1.0)), 0.0)
    rinv3 = rinv * rinv * rinv
    df = jnp.einsum("tsk,sk->ts", d, f_src)
    return jnp.einsum("ts,sk->tk", rinv, f_src) + jnp.einsum("ts,tsk->tk", df * rinv3, d)


def stresslet_block(trg, src, S):
    """Unscaled stresslet partial sum of one (target-block, source-block) pair."""
    d = trg[:, None, :] - src[None, :, :]
    r2 = jnp.sum(d * d, axis=-1)
    mask = r2 > 0.0
    rinv = jnp.where(mask, lax.rsqrt(jnp.where(mask, r2, 1.0)), 0.0)
    rinv5 = rinv * rinv * rinv * rinv * rinv
    dSd = jnp.einsum("tsi,sij,tsj->ts", d, S, d)
    return jnp.einsum("ts,tsk->tk", -3.0 * dSd * rinv5, d)


def oseen_block(trg, src, density, eta, reg, epsilon_distance):
    """Regularized-Oseen partial sum (already eta-scaled via fr/gr)."""
    d = trg[:, None, :] - src[None, :, :]
    r2 = jnp.sum(d * d, axis=-1)
    fr, gr = _regularized_frgr(r2, eta, reg, epsilon_distance)
    df = jnp.einsum("tsk,sk->ts", d, density)
    return jnp.einsum("ts,sk->tk", fr, density) + jnp.einsum("ts,tsk->tk", gr * df, d)


@partial(jax.jit, static_argnames=("block_size",))
def stokeslet_direct(r_src, r_trg, f_src, eta, *, block_size: int = 4096):
    """Singular Stokeslet sum: [n_src,3] sources, [n_trg,3] targets -> [n_trg,3].

    Self-interactions (exactly coincident points) contribute zero, matching
    `pvfmm::stokes_vel` / `src/core/kernels.cu:17-41`.
    """
    factor = 1.0 / (8.0 * math.pi)
    u = _blocked_target_sum(lambda trg: stokeslet_block(trg, r_src, f_src),
                            r_trg, block_size)
    return u * (factor / eta)


@partial(jax.jit, static_argnames=("block_size",))
def stresslet_direct(r_dl, r_trg, f_dl, eta, *, block_size: int = 4096):
    """Singular stresslet (double-layer) sum.

    ``f_dl`` is [n_src, 3, 3] (the 9-component source S with rows indexed like the
    reference's sxx..szz, i.e. ``f_dl[s, i, j] = S_ij``); returns [n_trg, 3].
    """
    factor = 1.0 / (8.0 * math.pi)
    u = _blocked_target_sum(lambda trg: stresslet_block(trg, r_dl, f_dl),
                            r_trg, block_size)
    return u * (factor / eta)


def _reg_rinv(r2, reg, epsilon_distance, *, inclusive: bool, drop_self: bool):
    """1/r with the reference's near-field regularization, NaN-safe for gradients.

    ``inclusive`` picks the boundary test (`r <= eps` for the Oseen kernels
    `src/core/kernels.cpp:108`, strict `r < eps` for rotlet/stresslet
    `src/core/kernels.cpp:225,278`). ``drop_self`` zeroes exactly-coincident
    pairs (the Oseen/stresslet self-term skip); when False the regularized
    value is kept even at r == 0 (rotlet semantics — its contribution still
    vanishes because the displacement is zero).
    """
    eps2 = epsilon_distance * epsilon_distance
    near = (r2 <= eps2) if inclusive else (r2 < eps2)
    r2_eff = jnp.where(near, r2 + reg * reg, r2)
    if drop_self:
        nonzero = r2 > 0.0
        return jnp.where(nonzero, lax.rsqrt(jnp.where(nonzero, r2_eff, 1.0)), 0.0)
    return lax.rsqrt(jnp.maximum(r2_eff, jnp.finfo(r2.dtype).tiny))


def _regularized_frgr(r2, eta, reg, epsilon_distance):
    """fr = 1/(8 pi eta r), gr = 1/(8 pi eta r^3) with the reference's regularization.

    Exactly coincident points (r == 0) give zero; points closer than
    ``epsilon_distance`` use ``r -> sqrt(r^2 + reg^2)`` (`src/core/kernels.cpp:96-115`).
    """
    factor = 1.0 / (8.0 * math.pi * eta)
    rinv = _reg_rinv(r2, reg, epsilon_distance, inclusive=True, drop_self=True)
    fr = factor * rinv
    gr = factor * rinv * rinv * rinv
    return fr, gr


@partial(jax.jit, static_argnames=("block_size",))
def oseen_contract(r_src, r_trg, density, eta, reg=DEFAULT_REG,
                   epsilon_distance=DEFAULT_EPS, *, block_size: int = 4096):
    """Regularized Oseen tensor contracted with a density: -> [n_trg, 3].

    Mirror of `kernels::oseen_tensor_contract_direct` (`src/core/kernels.cpp:85-131`).
    """
    return _blocked_target_sum(
        lambda trg: oseen_block(trg, r_src, density, eta, reg, epsilon_distance),
        r_trg, block_size)


@jax.jit
def oseen_tensor(r_src, r_trg, eta, reg=DEFAULT_REG, epsilon_distance=DEFAULT_EPS):
    """Dense regularized Oseen tensor: -> [n_trg, 3, n_src, 3].

    Mirror of `kernels::oseen_tensor_direct` (`src/core/kernels.cpp:146-195`); reshape
    to ``(3*n_trg, 3*n_src)`` for the reference's interleaved-xyz layout. Used for the
    per-fiber dense self-mobility block, so it is not target-blocked.
    """
    d = r_trg[:, None, :] - r_src[None, :, :]
    r2 = jnp.sum(d * d, axis=-1)
    fr, gr = _regularized_frgr(r2, eta, reg, epsilon_distance)
    eye = jnp.eye(3, dtype=r_src.dtype)
    G = fr[:, :, None, None] * eye[None, None] + gr[:, :, None, None] * d[:, :, :, None] * d[:, :, None, :]
    # [n_trg, n_src, 3, 3] -> [n_trg, 3, n_src, 3]
    return jnp.transpose(G, (0, 2, 1, 3))


@partial(jax.jit, static_argnames=("block_size",))
def rotlet(r_src, r_trg, density, eta, reg=DEFAULT_REG, epsilon_distance=DEFAULT_EPS,
           *, block_size: int = 4096):
    """Rotlet sum ``u = 1/(8 pi eta) sum_j (rho_j x d)/r^3`` -> [n_trg, 3].

    Mirror of `kernels::rotlet` (`src/core/kernels.cpp:206-242`). Note the reference
    regularizes by the *squared* epsilon test on r^2 and keeps the (zero) self term.
    """
    factor = 1.0 / (8.0 * math.pi * eta)

    def block(trg):
        d = trg[:, None, :] - r_src[None, :, :]
        r2 = jnp.sum(d * d, axis=-1)
        rinv = _reg_rinv(r2, reg, epsilon_distance, inclusive=False, drop_self=False)
        fr = rinv * rinv * rinv
        cross = jnp.cross(density[None, :, :], d)
        return jnp.einsum("ts,tsk->tk", fr, cross)

    return _blocked_target_sum(block, r_trg, block_size) * factor


@jax.jit
def stresslet_times_normal(r, normals, eta, reg=DEFAULT_REG, epsilon_distance=DEFAULT_EPS):
    """Dense stresslet-contracted-with-normal operator -> [n, 3, n, 3].

    ``M[i, :, j, :] = -3/(4 pi) (d . n_j) / r^5 * d d^T`` with ``d = r_i - r_j`` and
    zero diagonal blocks. Mirror of `kernels::stresslet_times_normal`
    (`src/core/kernels.cpp:264-287`; note: no eta dependence). Reshape to
    ``(3n, 3n)`` for the reference layout.
    """
    factor = -3.0 / (4.0 * math.pi)
    n = r.shape[0]
    d = r[:, None, :] - r[None, :, :]
    r2 = jnp.sum(d * d, axis=-1)
    offdiag = ~jnp.eye(n, dtype=bool)
    rinv = _reg_rinv(r2, reg, epsilon_distance, inclusive=False, drop_self=False)
    rinv5 = rinv ** 5
    dn = jnp.einsum("ijk,jk->ij", d, normals)
    coeff = jnp.where(offdiag, factor * dn * rinv5, 0.0)
    M = coeff[:, :, None, None] * d[:, :, :, None] * d[:, :, None, :]
    return jnp.transpose(M, (0, 2, 1, 3))


@partial(jax.jit, static_argnames=("block_size",))
def stresslet_times_normal_times_density(r, normals, density, eta, reg=DEFAULT_REG,
                                         epsilon_distance=DEFAULT_EPS, *, block_size: int = 4096):
    """Contracted stresslet ``S_i = -3/(4 pi) sum_{j != i} (d.rho_j)(d.n_j)/r^5 d``.

    Mirror of `kernels::stresslet_times_normal_times_density`
    (`src/core/kernels.cpp:307-334`). Sources and targets are the same point set;
    the diagonal is excluded via the r > 0 mask (the reference skips i == j).
    """
    factor = -3.0 / (4.0 * math.pi)

    def block(trg):
        d = trg[:, None, :] - r[None, :, :]
        r2 = jnp.sum(d * d, axis=-1)
        rinv = _reg_rinv(r2, reg, epsilon_distance, inclusive=False, drop_self=True)
        rinv5 = rinv ** 5
        dn = jnp.einsum("tsk,sk->ts", d, normals)
        dr_ = jnp.einsum("tsk,sk->ts", d, density)
        return jnp.einsum("ts,tsk->tk", dn * dr_ * rinv5, d)

    return _blocked_target_sum(block, r, block_size) * factor
