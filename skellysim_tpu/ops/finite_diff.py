"""Host-side numerics: Fornberg finite-difference weights and barycentric resampling.

These build the static differentiation/downsampling matrices cached per fiber
resolution; they run once at program start in NumPy (float64) and are then closed
over by jit'd code as constants. Mirrors `utils::finite_diff` and
`utils::barycentric_matrix` (`/root/reference/src/core/utils.cpp:12-105`), which
follow Fornberg, SIAM Rev. 40(3), 685 (1998) and the standard barycentric
interpolation formula.
"""

from __future__ import annotations

import numpy as np


def finite_diff(s: np.ndarray, M: int, n_s: int) -> np.ndarray:
    """Mth-derivative matrix on grid points ``s`` using ``n_s``-point stencils.

    Interior rows use centered stencils; rows near the ends fall back to one-sided
    stencils over the first/last ``n_s`` points, matching the reference's windowing
    (`src/core/utils.cpp:54-68`).
    """
    s = np.asarray(s, dtype=np.float64)
    npts = s.size
    if npts < n_s:
        raise ValueError(
            f"finite_diff needs at least n_s={n_s} grid points for an order-{M} "
            f"derivative with this stencil, got {npts}"
        )
    D = np.zeros((npts, npts))
    n_half = (n_s - 1) // 2
    n_s = n_s - 1

    for xi in range(npts):
        si = s[xi]
        if xi < n_half:
            xlow, xhigh = 0, n_s + 1
        elif xi > npts - n_half - 2:
            xlow, xhigh = npts - n_s - 1, npts
        else:
            xlow, xhigh = xi - n_half, xi - n_half + n_s + 1

        x = s[xlow:xhigh]

        # Fornberg's recursion for the weights of all derivatives up to order M
        c1 = 1.0
        c4 = x[0] - si
        c = np.zeros((n_s + 1, M + 1))
        c[0, 0] = 1.0
        for i in range(1, n_s + 1):
            mn = min(i, M)
            c2 = 1.0
            c5 = c4
            c4 = x[i] - si
            for j in range(i):
                c3 = x[i] - x[j]
                c2 = c2 * c3
                if j == i - 1:
                    for k in range(mn, 0, -1):
                        c[i, k] = c1 * (k * c[i - 1, k - 1] - c5 * c[i - 1, k]) / c2
                    c[i, 0] = -c1 * c5 * c[i - 1, 0] / c2
                for k in range(mn, 0, -1):
                    c[j, k] = (c4 * c[j, k] - k * c[j, k - 1]) / c3
                c[j, 0] = c4 * c[j, 0] / c3
            c1 = c2
        D[xi, xlow:xlow + n_s + 1] = c[:, M]

    return D


def barycentric_matrix(x: np.ndarray, y: np.ndarray) -> np.ndarray:
    """Resampling matrix P mapping values on grid ``x`` (size N) to grid ``y`` (size M).

    Uses the trapezoidal barycentric weights of the reference
    (`src/core/utils.cpp:12-36`): w = [0.5, -1, 1, ..., -0.5*(-1)^N].
    """
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    N, M = x.size, y.size

    w = np.ones(N)
    w[1::2] = -1.0
    w[0] = 0.5
    w[N - 1] = -0.5 * (-1.0) ** N

    P = np.zeros((M, N))
    with np.errstate(divide="ignore", invalid="ignore"):
        for j in range(M):
            diff = y[j] - x
            terms = w / diff
            S = terms.sum()
            P[j] = np.where(np.abs(diff) > np.finfo(np.float64).eps, terms / S, 1.0)
    return P
