"""PairEvaluator: the one spec object for the pair-evaluator seam.

Before this module, every flow call site carried the evaluator selection
as loose kwargs (``evaluator=``, ``impl=``, ``ewald_plan=``,
``ewald_anchors=``) and each new evaluator grew every signature in
`fibers.container`, `periphery.periphery`, `bodies.bodies`, and the whole
`System` pipeline. The spec hoists that selection into ONE hashable value
(`PairEvaluator`) built once per solve and passed through unchanged — the
runtime mirror of the reference's single `Evaluator` slot
(`fiber_container_base.cpp:20-33`, `include/kernels.hpp:56-134`).

The spec is a frozen dataclass so it can ride jit ``static_argnames``
(it selects compiled programs, exactly like the plans it carries); the
plan's traced anchors travel NEXT to it as a regular operand
(``pair_anchors``) so anchor hops under drift reuse compiled programs.

``plan`` is polymorphic over the fast-summation planners — an
`ops.ewald.EwaldPlan` or an `ops.treecode.TreePlan`; `plan_module`
dispatches to the owning module's ``strip_anchors``/``plan_anchors``.
"""

from __future__ import annotations

import types
from dataclasses import dataclass

#: runtime evaluator names, the single source for validation everywhere
#: (System.__init__, the config schema, the listener's evaluator map)
EVALUATORS = ("direct", "ring", "ewald", "tree", "spectral")

#: accepted spellings -> runtime evaluator names, shared by the TOML schema
#: (`config.schema`) and the listener protocol (`listener.cpp:117` semantics)
#: so config files and runtime requests can never disagree about which names
#: are valid: reference names ("CPU"/"GPU"/"TPU" = dense direct, "FMM" = the
#: fast-evaluator slot -> free-space Ewald, "PVFMM" = the reference's
#: periodic backend -> the
#: spectral Ewald evaluator) plus our native names. Lookups are
#: case-insensitive at both call sites. Read-only view: both importers bind
#: the SAME object, so a mutation at one site would silently change what
#: names the other accepts.
EVALUATOR_ALIASES = types.MappingProxyType(
    {"cpu": "direct", "gpu": "direct", "tpu": "direct",
     "fmm": "ewald",
     "pvfmm": "spectral",
     "direct": "direct", "ring": "ring", "ewald": "ewald",
     "tree": "tree", "spectral": "spectral"})


def plan_module(plan):
    """The ops module owning ``plan`` (lazy imports: the spec itself must
    stay importable without pulling both planners in)."""
    from . import ewald, spectral, treecode

    if isinstance(plan, ewald.EwaldPlan):
        return ewald
    if isinstance(plan, treecode.TreePlan):
        return treecode
    if isinstance(plan, spectral.SpectralPlan):
        return spectral
    raise TypeError(f"unknown pair-evaluator plan type {type(plan)!r}")


@dataclass(frozen=True)
class PairEvaluator:
    """Hashable pair-evaluator selection (a jit-static value).

    ``evaluator`` is one of `EVALUATORS`; ``impl`` the pairwise tile
    (`Params.kernel_impl` semantics); ``plan`` the anchor-STRIPPED fast
    plan for "ewald"/"tree" (None for the dense/ring paths — and passing
    ``plan=None`` with a fast evaluator name is how role-gated callers,
    e.g. the mixed solver's f64 refinement flows, force the dense tile
    without renaming the evaluator).
    """

    evaluator: str = "direct"
    impl: str = "exact"
    plan: object = None

    def __post_init__(self):
        if self.evaluator not in EVALUATORS:
            raise ValueError(
                f"unknown pair evaluator {self.evaluator!r}; "
                f"runtime values are {', '.join(EVALUATORS)}")

    @property
    def is_fast(self) -> bool:
        """True when this spec routes through a fast-summation plan."""
        return (self.plan is not None
                and self.evaluator in ("ewald", "tree", "spectral"))


def resolve(pair, pair_anchors, dtype, evaluator: str = "direct",
            impl: str = "exact", ewald_plan=None, ewald_anchors=None):
    """Collapse the spec/loose-kwarg duality at a flow entry point.

    Returns ``(evaluator, impl, ewald_plan, ewald_anchors, pair_anchors)``:
    the spec (when given) supersedes the loose kwargs, missing anchors are
    materialized from the plan's own stored anchor (so stripped plans need
    anchors passed explicitly), and an "ewald" spec is re-aliased onto the
    legacy ewald kwargs its branch consumes. The one unpack shared by
    `fibers.container.flow_multi`, `periphery.flow`, and `bodies.flow` —
    keeping the anchor-materialization rule from drifting per call site."""
    if pair is None:
        return evaluator, impl, ewald_plan, ewald_anchors, pair_anchors
    if pair.plan is not None and pair_anchors is None:
        pair_anchors = plan_module(pair.plan).plan_anchors(pair.plan, dtype)
    if pair.evaluator == "ewald":
        ewald_plan, ewald_anchors = pair.plan, pair_anchors
    return pair.evaluator, pair.impl, ewald_plan, ewald_anchors, pair_anchors


def make_pair(evaluator: str, impl: str, plan=None, anchors=None,
              dtype=None):
    """(spec, anchors) with the plan anchor-stripped and its traced anchors
    materialized — the one constructor System and tests share so the
    strip/anchor discipline cannot drift per call site."""
    if plan is None:
        return PairEvaluator(evaluator=evaluator, impl=impl), None
    mod = plan_module(plan)
    if anchors is None:
        anchors = mod.plan_anchors(plan, dtype)
    return (PairEvaluator(evaluator=evaluator, impl=impl,
                          plan=mod.strip_anchors(plan)), anchors)
