"""Barycentric Lagrange treecode: hierarchical O(N log N) kernel summation.

The second fast pair-evaluator filling the reference's FMM slot
(`/root/reference/include/kernels.hpp:56-134` wraps STKFMM/PVFMM), next to
`ops.ewald`. Where the Ewald split is grid-based (FFTs over the whole box),
this is the hierarchical answer (Wang, Krasny & Tlupova, arXiv:1811.12498;
kernel-aggregated FMM arXiv:2010.15155 is the accuracy/cost reference
point): source clusters are compressed onto tensor-product Chebyshev grids
by barycentric Lagrange anterpolation, and well-separated cluster fields
are evaluated through the *same* pairwise kernel tiles as the dense path —
a kernel-independent far field that serves the Stokeslet, the stresslet
double layer, and the regularized Oseen kernel with one traversal.

Classic treecodes are hostile to XLA (recursive adaptive trees, per-target
multipole-acceptance tests = data-dependent control flow). The TPU-native
shape used here is fully static:

* a FIXED-DEPTH uniform octree over a cubic box; leaves are padded,
  power-of-two-laddered buckets (`max_occ`) with masked empty lanes —
  the ensemble masked-lane trick applied to space instead of batch
  (neutralization per docs/audit.md "Masking discipline", proven on the
  lowered `stokeslet_tree` program by the `mask` audit check);
* the multipole acceptance criterion is INDEX-based (the standard FMM
  well-separatedness: cells at one level interact iff their parents are
  neighbors but they are not), so every interaction list is a host-side
  integer constant baked at trace time — no `jnp.where` ever decides
  *whether* to evaluate a cluster, only masks what empty lanes contribute;
* the upward pass (leaf anterpolation + child->parent transfers) is a
  stack of batched [occ, p^3] / [8 p^3, p^3] matmuls — the MXU-friendly
  batched-matmul layout `stokeslet_block_mxu` established;
* near and far fields are evaluated TARGET-ROW-MAJOR: targets are sorted
  by leaf and processed in compact fixed-size chunks, each row gathering
  its own 27 neighbor buckets (near: dense exact tile, coincident pairs
  drop — so no analytic self term exists anywhere, unlike the Ewald far
  field's Gaussian correction) or its leaf's interaction-list proxies
  (far). Row-major evaluation is what keeps the padded-lane waste linear:
  a cell-major traversal would pay 27 * max_occ^2 per cell INCLUDING the
  empty cells, which for clustered clouds costs more than the dense
  O(N^2) tile it is meant to beat.

Accuracy is controlled by the interpolation order p (`TreePlan.order`):
with the one-cell-buffer acceptance criterion the measured relative error
contracts ~5x per order (see `plan_tree`'s calibrated rule, pinned by
`tests/test_treecode.py`). Cost per target ~ 27*occ (near) +
sum_levels |ilist| * p^3 (far) vs N for the dense tile, so the treecode
pays off for large N at moderate tolerance — the f32 Krylov interior of
the mixed solver, exactly like `ewald_tol` (the f64 refinement residual
stays dense either way; see `System._prep`'s role gating).

Plan/anchor discipline mirrors `ops.ewald`: every derived quantity is a
deterministic function of ladder-quantized inputs so the plan (the jit
compilation key) is stable under geometric drift, and the box anchor
enters traced (`strip_anchors`/`plan_anchors`) so a quantized anchor hop
reuses the compiled program.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import lru_cache, partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import kernels
from .ewald import _R2_ALPHAS, _ladder

__all__ = ["TreePlan", "plan_tree", "stokeslet_tree", "stresslet_tree",
           "oseen_tree", "strip_anchors", "plan_anchors", "fill_positions"]


# ---------------------------------------------------------------------- plan

@dataclass(frozen=True)
class TreePlan:
    """Static tree geometry/resolution (hashable; selects compiled programs).

    Built host-side by `plan_tree` — the analogue of the reference FMM's
    per-step tree rebuild (`kernels.hpp:78-122`). ``box_lo`` is carried for
    convenience but enters the computation as a *traced* operand: callers
    that jit on the plan strip it (`strip_anchors`) so a quantized-anchor
    hop under drift reuses the compiled program. ``depth == 0`` is the
    degenerate single-cell tree: the evaluators dispatch straight to the
    dense kernels (bitwise-identical results — pinned by tests).
    """

    depth: int       # leaf level L; 8^L leaves (0 = dense fallback)
    order: int       # p: Chebyshev points per dimension (p^3 per cluster)
    box_lo: tuple    # root-box lower corner (traced at run time; None once
                     # anchor-stripped — see `strip_anchors`)
    box_L: float     # root-box edge (ladder-quantized)
    max_occ: int     # static per-leaf bucket capacity
    tol: float       # target relative accuracy (field-normalized: the
                     # bound is on max_i |du_i| / max_i |u_i| — per-point
                     # relative error is unbounded at near-zero-velocity
                     # targets for ANY summation scheme)

    @property
    def n_leaves(self) -> int:
        return 8 ** self.depth

    @property
    def leaf_size(self) -> float:
        return self.box_L / (2 ** self.depth)


def strip_anchors(plan: TreePlan) -> TreePlan:
    """Drop the traced anchor field — the hashable jit key for this plan.

    The stripped plan carries ``box_lo=None`` (not a zero tuple): anchors
    for a stripped plan MUST come in as the explicit traced operand, and
    `plan_anchors` refuses to fabricate them — a silently-zeroed anchor
    would bucket every point relative to the origin, clip the cloud into
    boundary leaves, and evict sources past ``max_occ`` with no error.
    """
    import dataclasses

    return dataclasses.replace(plan, box_lo=None)


def plan_anchors(plan: TreePlan, dtype=None):
    """[1, 3] traced-operand anchor (box_lo)."""
    if plan.box_lo is None:
        raise ValueError(
            "anchor-stripped TreePlan has no anchors to materialize; pass "
            "the traced anchors explicitly (pair_anchors= / the anchors "
            "value make_pair returned next to the spec)")
    return jnp.asarray([plan.box_lo], dtype=dtype or jnp.float64)


def fill_positions(plan: TreePlan, box_lo, n, dtype):
    """[n, 3] well-spread positions inside the root box (R2 lattice).

    Same role as `ewald.fill_positions`: inactive/padding source nodes with
    zero strengths must live *somewhere* with static shapes, and replicated
    padding would pile them into one leaf and blow up `max_occ`.
    """
    t = (jnp.arange(n, dtype=dtype) + 0.5)[:, None]
    alphas = jnp.asarray(_R2_ALPHAS, dtype=dtype)[None, :]
    frac = (t * alphas) % 1.0
    return jnp.asarray(box_lo, dtype=dtype) + frac * (0.999 * plan.box_L)


def _fill_positions_np(box_lo, box_L, n):
    """NumPy mirror of `fill_positions` for host-side occupancy counting."""
    t = (np.arange(n, dtype=np.float64) + 0.5)[:, None]
    frac = (t * np.asarray(_R2_ALPHAS)[None, :]) % 1.0
    return np.asarray(box_lo) + frac * (0.999 * box_L)


#: measured error contraction per interpolation order for the 1/r-family
#: kernels under the one-cell-buffer acceptance criterion (random and
#: line-clustered clouds, `tests/test_treecode.py` pins the rule end to
#: end). Measured (uniform cloud, depths 2-3): p=3 -> 4.3e-3, p=4 ->
#: 7.9e-4, p=5 -> 1.4e-4, p=6 -> 2.8e-5, p=8 -> 8e-7 — a ~5.3x
#: contraction per order; the rule err(p) ~ 0.05 * 5^-(p-2) upper-bounds
#: every measured point with >= 2x margin.
_ACC_BASE = 5.0
_ACC_C0 = 0.05


def order_for_tol(tol: float, max_order: int = 12) -> int:
    """Interpolation order p for a target relative accuracy (calibrated)."""
    p = 2 + math.ceil(math.log(max(_ACC_C0 / tol, 1.0)) / math.log(_ACC_BASE))
    return int(min(max(p, 2), max_order))


def plan_tree(points, tol=1e-4, target_occ=32.0, max_depth=5, n_fill=0,
              max_order=12):
    """Choose (depth, order, box, leaf capacity) for a target relative
    accuracy. Host-side (NumPy), once per step/geometry, like `plan_ewald`.

    Rules (each pinned by `tests/test_treecode.py`):
      * depth from the point count: leaves sized for ~``target_occ`` points
        -> depth = ceil(log8(N_q / target_occ)) on the pow2-laddered count
        N_q, clamped to [2, max_depth]; below the 2-level minimum (the
        first level with well-separated cells) the plan degenerates to
        depth 0 = the dense kernels.
      * order from tol via the measured contraction rule (`order_for_tol`).
      * box edge from the cloud extent, laddered, with margin
        1/(1 - 2^-depth) so the leaf-lattice-quantized anchor still covers
        the cloud; the anchor hops only on the leaf lattice.
      * leaf capacity from measured occupancy (fills included) on the
        geometric x1.5 / 8-aligned rung ladder with 15% headroom, like
        `plan_ewald` — a recompile should need a ~30% occupancy swing.

    ``n_fill`` reserves occupancy for that many zero-strength padding nodes
    placed by `fill_positions` (inactive fiber slots).
    """
    pts = np.asarray(points, dtype=np.float64)
    lo = pts.min(axis=0)
    hi = pts.max(axis=0)
    extent = max(float((hi - lo).max()), 1e-3)
    N = len(pts) + int(n_fill)
    N_q = max(1, 2 ** math.ceil(math.log2(max(N, 1))))

    depth = math.ceil(math.log(max(N_q / target_occ, 1.0)) / math.log(8.0))
    depth = min(depth, max_depth)
    if depth < 2:
        # no well-separated cells exist above the 2-level minimum: the
        # tree would be pure near field with bucketing overhead — dense
        # is strictly better and bitwise-identical
        return TreePlan(depth=0, order=order_for_tol(tol, max_order),
                        box_lo=(float(lo[0]), float(lo[1]), float(lo[2])),
                        box_L=_ladder(extent, 1e-3), max_occ=1,
                        tol=float(tol))

    order = order_for_tol(tol, max_order)
    L_box = _ladder(extent / (1.0 - 2.0 ** -depth) + 1e-9, 1e-3)
    cell = L_box / (2 ** depth)
    box_lo = tuple(float(cell * math.floor(a / cell)) for a in lo)

    C = 2 ** depth
    ci = np.clip(((pts - np.asarray(box_lo)) / cell).astype(int), 0, C - 1)
    if n_fill:
        fp = _fill_positions_np(box_lo, L_box, int(n_fill))
        cif = np.clip(((fp - np.asarray(box_lo)) / cell).astype(int), 0,
                      C - 1)
        ci = np.vstack([ci, cif])
    flat = (ci[:, 0] * C + ci[:, 1]) * C + ci[:, 2]
    occ = int(np.bincount(flat, minlength=C ** 3).max()) if len(flat) else 1
    need = occ * 1.15
    rung = 8.0
    while rung < need:
        rung *= 1.5
    occ = int(-8 * (-rung // 8))

    return TreePlan(depth=int(depth), order=int(order), box_lo=box_lo,
                    box_L=float(L_box), max_occ=occ, tol=float(tol))


# -------------------------------------------- host-side static tree geometry

_OCTS = np.array([(i, j, k) for i in (0, 1) for j in (0, 1) for k in (0, 1)],
                 dtype=np.int64)                      # [8, 3] child octants
_NBR_OFFSETS = np.array([(i, j, k) for i in (-1, 0, 1)
                         for j in (-1, 0, 1) for k in (-1, 0, 1)],
                        dtype=np.int64)               # [27, 3]


def _coords(level: int) -> np.ndarray:  # skelly-lint: ignore-function[trace-hygiene] — host-side tree geometry from the STATIC plan level only (never traced values); freezing into the program as constants is the treecode's static-interaction-list design (module docstring)
    """[8^level, 3] integer cell coords in flat order (i*C + j)*C + k."""
    C = 2 ** level
    g = np.arange(C, dtype=np.int64)
    return np.stack(np.meshgrid(g, g, g, indexing="ij"),
                    axis=-1).reshape(-1, 3)


@lru_cache(maxsize=None)
def _vlists(level: int) -> tuple:
    """Per-cell V-lists at one level: children of the parent's 27 neighbor
    cells (itself included) that are NOT neighbors of the cell — the
    standard index-based well-separatedness criterion. Returns a tuple of
    per-cell int64 arrays of flat same-level cell ids."""
    C = 2 ** level
    co = _coords(level)
    parent = co >> 1
    cand = ((parent[:, None, None, :] + _NBR_OFFSETS[None, :, None, :]) * 2
            + _OCTS[None, None, :, :])                # [C3, 27, 8, 3]
    valid = np.all((cand >= 0) & (cand < C), axis=-1)
    cheb = np.abs(cand - co[:, None, None, :]).max(axis=-1)
    keep = valid & (cheb > 1)
    flat = (cand[..., 0] * C + cand[..., 1]) * C + cand[..., 2]
    return tuple(flat[i][keep[i]] for i in range(C ** 3))


@lru_cache(maxsize=None)
def _interaction_lists(depth: int):
    """Per-LEAF far-field interaction lists over levels 2..depth.

    Each leaf's far set is the union over levels of its ancestor's V-list
    at that level; entries index the flat cross-level proxy array (level
    offsets applied). Returns (ilist [n_leaves, maxI] int32 padded with
    ``total_cells`` — the zero-strength sentinel slot — , total_cells,
    level_offsets dict, child_index arrays per level for the upward pass).
    """
    assert depth >= 2
    offsets = {}
    total = 0
    for lev in range(2, depth + 1):
        offsets[lev] = total
        total += 8 ** lev

    leaf_co = _coords(depth)
    n_leaves = 8 ** depth
    per_leaf = []
    vl = {lev: _vlists(lev) for lev in range(2, depth + 1)}
    for b in range(n_leaves):
        parts = []
        for lev in range(2, depth + 1):
            anc = leaf_co[b] >> (depth - lev)
            Cl = 2 ** lev
            anc_flat = (anc[0] * Cl + anc[1]) * Cl + anc[2]
            parts.append(vl[lev][anc_flat] + offsets[lev])
        per_leaf.append(np.concatenate(parts) if parts
                        else np.zeros(0, dtype=np.int64))
    maxI = max(1, max(len(p) for p in per_leaf))
    ilist = np.full((n_leaves, maxI), total, dtype=np.int32)
    for b, p in enumerate(per_leaf):
        ilist[b, :len(p)] = p

    child_idx = {}
    for lev in range(2, depth):
        co = _coords(lev)
        Cc = 2 ** (lev + 1)
        ch = co[:, None, :] * 2 + _OCTS[None, :, :]   # [8^lev, 8, 3]
        child_idx[lev] = ((ch[..., 0] * Cc + ch[..., 1]) * Cc
                          + ch[..., 2]).astype(np.int32)
    return ilist, total, offsets, child_idx


# ------------------------------------------------- barycentric interpolation

def _cheb_nodes_np(p: int) -> np.ndarray:  # skelly-lint: ignore-function[trace-hygiene] — host-side interpolation nodes from the STATIC plan order only; frozen trace-time constants by design (module docstring)
    """Chebyshev points of the 2nd kind on [-1, 1] (endpoints included)."""
    if p == 1:
        return np.zeros(1)
    return np.cos(np.pi * np.arange(p) / (p - 1))


def _bary_w_np(p: int) -> np.ndarray:  # skelly-lint: ignore-function[trace-hygiene] — host-side barycentric weights from the STATIC plan order only; frozen trace-time constants by design (module docstring)
    """Barycentric weights for 2nd-kind Chebyshev points."""
    w = np.ones(p) * np.where(np.arange(p) % 2 == 0, 1.0, -1.0)
    w[0] *= 0.5
    w[-1] *= 0.5
    return w


def _bary_1d(y, nodes, w):
    """Barycentric Lagrange basis values L_k(y): [..., n] -> [..., n, p].

    Near-node evaluations snap to the one-hot basis row: the raw formula's
    c = w/(y - t) overflows in f32 for |y - t| ~ 1e-38, and masked-lane
    sentinel points may sit exactly on a node.
    """
    diff = y[..., None] - nodes
    eps = jnp.finfo(diff.dtype).eps
    hit = jnp.abs(diff) < 64.0 * eps
    c = w / jnp.where(hit, 1.0, diff)
    L = c / jnp.sum(c, axis=-1, keepdims=True)
    any_hit = jnp.any(hit, axis=-1, keepdims=True)
    return jnp.where(any_hit, hit.astype(diff.dtype), L)


def _bary_1d_np(y, p):
    """NumPy mirror of `_bary_1d` for the trace-time transfer matrices."""
    t = _cheb_nodes_np(p)
    w = _bary_w_np(p)
    diff = y[:, None] - t[None, :]
    hit = np.abs(diff) < 1e-13
    c = w[None, :] / np.where(hit, 1.0, diff)
    L = c / c.sum(axis=1, keepdims=True)
    return np.where(hit.any(axis=1, keepdims=True), hit.astype(float), L)


@lru_cache(maxsize=None)
def _transfer_np(p: int) -> np.ndarray:
    """Child->parent anterpolation transfer: U[oct, n, m] = parent basis
    L_m evaluated at child proxy point n (octant-indexed like `_OCTS`).

    Scale-invariant: the same [8, p^3, p^3] matrix serves every level.
    """
    t = _cheb_nodes_np(p)
    # child half h (0 = low, 1 = high) maps child-local t to parent coords
    U1 = {h: _bary_1d_np((t + (2 * h - 1)) / 2.0, p) for h in (0, 1)}
    U = np.zeros((8, p ** 3, p ** 3))
    for o, (hx, hy, hz) in enumerate(_OCTS):
        U[o] = np.einsum("ax,by,cz->abcxyz", U1[hx], U1[hy], U1[hz]
                         ).reshape(p ** 3, p ** 3)
    return U


@lru_cache(maxsize=None)
def _nodes3_np(p: int) -> np.ndarray:
    """[p^3, 3] tensor-product Chebyshev offsets (unit half-width)."""
    t = _cheb_nodes_np(p)
    return np.stack(np.meshgrid(t, t, t, indexing="ij"),
                    axis=-1).reshape(-1, 3)


# --------------------------------------------------------------- device side

#: elements per chunked tile — bounds the materialized per-chunk
#: intermediates (near tiles, far gathers, anterpolation weights)
_TILE_BUDGET = 3_000_000


def _chunked_map(fn, args, n, budget_per_item):
    """lax.map of a BATCHED ``fn`` over leading-axis chunks sized to the
    budget: ``fn`` receives [chunk, ...] slices of every arg (padded rows
    compute garbage that is sliced off)."""
    chunk = max(1, min(n, _TILE_BUDGET // max(budget_per_item, 1)))
    n_chunks = -(-n // chunk)
    pad = n_chunks * chunk - n

    def padded(a):
        widths = ((0, pad),) + ((0, 0),) * (a.ndim - 1)
        return jnp.pad(a, widths).reshape((n_chunks, chunk) + a.shape[1:])

    out = lax.map(lambda xs: fn(*xs), tuple(padded(a) for a in args))
    return out.reshape((n_chunks * chunk,) + out.shape[2:])[:n]


def _cell_centers(plan: TreePlan, lo, level: int, dtype):
    """[8^level, 3] cell centers at one level (from the traced anchor)."""
    C = 2 ** level
    cell = plan.box_L / C
    idx = jnp.arange(C ** 3, dtype=jnp.int32)
    ix, rem = idx // (C * C), idx % (C * C)
    iy, iz = rem // C, rem % C
    ijk = jnp.stack([ix, iy, iz], axis=-1).astype(dtype)
    return lo[None, :] + (ijk + 0.5) * cell


def _leaf_ids(plan: TreePlan, lo, pts):
    """Flat leaf index per point (boundary-clipped into the grid)."""
    C = 2 ** plan.depth
    cell = plan.box_L / C
    ci = jnp.clip(((pts - lo) / cell).astype(jnp.int32), 0, C - 1)
    return (ci[:, 0] * C + ci[:, 1]) * C + ci[:, 2]


def _bucket(plan: TreePlan, lo, centers, pts, payload):
    """Sort sources into [n_leaves, max_occ] buckets (padded, masked).

    Padded lanes carry their cell's CENTER (barycentric-safe: a far
    sentinel would make the anterpolation denominators catastrophically
    cancel in f32) and zero payload (so they contribute nothing anywhere).
    """
    C3 = plan.n_leaves
    mo = plan.max_occ
    flat = _leaf_ids(plan, lo, pts)
    order = jnp.argsort(flat)
    flat_s = flat[order]
    pts_s = pts[order]
    pay_s = payload[order]
    counts = jnp.zeros(C3, dtype=jnp.int32).at[flat_s].add(1)
    starts = jnp.concatenate([jnp.zeros(1, jnp.int32),
                              jnp.cumsum(counts)[:-1].astype(jnp.int32)])
    rank = jnp.arange(flat_s.shape[0], dtype=jnp.int32) - starts[flat_s]
    rank = jnp.minimum(rank, mo - 1)  # clamp overflow (plan sized it)
    slot = flat_s * mo + rank
    bpts = jnp.repeat(centers, mo, axis=0).at[slot].set(pts_s)
    bpay = jnp.zeros((C3 * mo,) + payload.shape[1:],
                     dtype=payload.dtype).at[slot].set(pay_s)
    return (bpts.reshape(C3, mo, 3),
            bpay.reshape((C3, mo) + payload.shape[1:]))


def _upward(plan: TreePlan, lo, src_b, pay_b, dtype):
    """Leaf anterpolation + child->parent transfers.

    Returns the flat cross-level proxy arrays (levels 2..depth in
    `_interaction_lists` order, plus one zero sentinel cell):
    positions [T+1, p^3, 3] and strengths [T+1, p^3, C].
    """
    p = plan.order
    p3 = p ** 3
    C = pay_b.shape[-1]
    nodes1 = jnp.asarray(_cheb_nodes_np(p), dtype=dtype)
    bw = jnp.asarray(_bary_w_np(p), dtype=dtype)
    half_leaf = plan.leaf_size / 2.0
    centers_leaf = _cell_centers(plan, lo, plan.depth, dtype)

    def anterp(pts_l, pay_l, cen_l):
        y = (pts_l - cen_l[:, None, :]) / half_leaf       # [B, mo, 3]
        Lx = _bary_1d(y[..., 0], nodes1, bw)              # [B, mo, p]
        Ly = _bary_1d(y[..., 1], nodes1, bw)
        Lz = _bary_1d(y[..., 2], nodes1, bw)
        W = (Lx[:, :, :, None, None] * Ly[:, :, None, :, None]
             * Lz[:, :, None, None, :]).reshape(
                 pts_l.shape[0], -1, p3)                  # [B, mo, p^3]
        return jnp.einsum("bcm,bck->bmk", W, pay_l)       # [B, p^3, C]

    fh = _chunked_map(anterp, (src_b, pay_b, centers_leaf),
                      plan.n_leaves, plan.max_occ * p3)

    _, total, offsets, child_idx = _interaction_lists(plan.depth)
    U = jnp.asarray(_transfer_np(p), dtype=dtype)          # [8, p^3, p^3]
    by_level = {plan.depth: fh}
    for lev in range(plan.depth - 1, 1, -1):
        g = by_level[lev + 1][jnp.asarray(child_idx[lev])]  # [8^lev,8,p^3,C]
        by_level[lev] = jnp.einsum("onm,qonk->qmk", U, g)

    nodes3 = jnp.asarray(_nodes3_np(p), dtype=dtype)       # [p^3, 3]
    pts_parts = []
    f_parts = []
    for lev in range(2, plan.depth + 1):
        half = plan.box_L / (2 ** lev) / 2.0
        cen = _cell_centers(plan, lo, lev, dtype)
        pts_parts.append(cen[:, None, :] + half * nodes3[None, :, :])
        f_parts.append(by_level[lev])
    proxy_pts = jnp.concatenate(
        pts_parts + [jnp.zeros((1, p3, 3), dtype=dtype)], axis=0)
    proxy_f = jnp.concatenate(
        f_parts + [jnp.zeros((1, p3, C), dtype=dtype)], axis=0)
    return proxy_pts, proxy_f


def _neighbor_table(depth: int):  # skelly-lint: ignore-function[trace-hygiene] — host-side neighbor table from the STATIC plan depth only; frozen trace-time constants by design (module docstring)
    """[C3, 27] boundary-clipped neighbor cell ids + [C3, 27] first-
    occurrence mask (clipped duplicates would double-count sources)."""
    C = 2 ** depth
    co = _coords(depth)
    nb = np.clip(co[:, None, :] + _NBR_OFFSETS[None, :, :], 0, C - 1)
    nid = ((nb[..., 0] * C + nb[..., 1]) * C + nb[..., 2])    # [C3, 27]
    eq = nid[:, :, None] == nid[:, None, :]
    uniq = ~np.any(eq & np.tril(np.ones((27, 27), dtype=bool), k=-1)[None],
                   axis=2)
    return nid.astype(np.int32), uniq


def _tree_eval(plan: TreePlan, lo, r_src, payload, r_trg, near_fn, far_fn,
               scale_near, scale_far):
    """Shared traversal: bucket sources, upward pass, then target-row-major
    near tiles + far cluster evaluations over leaf-sorted target chunks.

    ``payload`` is [n_src, C] flat channels; ``near_fn(trg, src, pay)`` /
    ``far_fn(trg, pts, pay)`` take [B, 3] target rows against PER-ROW
    source sets [B, S, 3] / [B, S, C] and return [B, 3] raw row sums,
    scaled by ``scale_near`` / ``scale_far`` (the regularized-Oseen near
    tile is pre-scaled, the bare kernels are not).
    """
    dtype = r_trg.dtype
    mo = plan.max_occ
    p3 = plan.order ** 3
    C = payload.shape[-1]
    centers = _cell_centers(plan, lo, plan.depth, dtype)
    src_b, pay_b = _bucket(plan, lo, centers, r_src, payload)
    # "upward"/"near"/"far" device-time scopes (obs/profile.py): metadata
    # only — op counts, accuracy, and the stokeslet_tree contract unchanged
    with jax.named_scope("upward"):
        proxy_pts, proxy_f = _upward(plan, lo, src_b, pay_b, dtype)

    nid_np, uniq_np = _neighbor_table(plan.depth)
    nid = jnp.asarray(nid_np)
    uniq = jnp.asarray(uniq_np)
    ilist_np, _, _, _ = _interaction_lists(plan.depth)
    ilist = jnp.asarray(ilist_np)                      # [C3, maxI]
    maxI = ilist.shape[1]

    # leaf-sorted targets: consecutive rows share (and cache) the same
    # neighbor buckets / interaction lists; the inverse permutation
    # restores caller order at the end
    n_trg = r_trg.shape[0]
    flat_t = _leaf_ids(plan, lo, r_trg)
    order = jnp.argsort(flat_t)
    trg_s = r_trg[order]
    leaf_s = flat_t[order]

    def near_rows(t_pts, leaf):
        ids = nid[leaf]                                # [B, 27]
        s_pts = src_b[ids].reshape(t_pts.shape[0], 27 * mo, 3)
        pay = jnp.where(uniq[leaf][:, :, None, None], pay_b[ids], 0.0)
        return near_fn(t_pts, s_pts,
                       pay.reshape(t_pts.shape[0], 27 * mo, C))

    with jax.named_scope("near"):
        u = _chunked_map(near_rows, (trg_s, leaf_s), n_trg,
                         27 * mo * (3 + C)) * scale_near

    def far_rows(t_pts, leaf):
        ids = ilist[leaf]                              # [B, maxI]
        s_pts = proxy_pts[ids].reshape(t_pts.shape[0], maxI * p3, 3)
        s_f = proxy_f[ids].reshape(t_pts.shape[0], maxI * p3, C)
        return far_fn(t_pts, s_pts, s_f)

    with jax.named_scope("far"):
        u = u + _chunked_map(far_rows, (trg_s, leaf_s), n_trg,
                             maxI * p3 * (3 + C)) * scale_far

    out = jnp.zeros((n_trg, 3), dtype=dtype)
    return out.at[order].set(u)


# ------------------------------------------------------------------ kernels

def _stokeslet_rows(trg, src, f):
    """Row-major Stokeslet partial sum: [B, 3] targets, each against its
    OWN [B, S, 3] source set — the same masking/regularization semantics
    as `kernels.stokeslet_block` (which shares one source block across
    target rows and so cannot serve the per-row gathers here)."""
    d = trg[:, None, :] - src
    r2 = jnp.sum(d * d, axis=-1)
    mask = r2 > 0.0
    rinv = jnp.where(mask, lax.rsqrt(jnp.where(mask, r2, 1.0)), 0.0)
    rinv3 = rinv * rinv * rinv
    df = jnp.sum(d * f, axis=-1)
    return (jnp.einsum("bs,bsk->bk", rinv, f)
            + jnp.einsum("bs,bsk->bk", df * rinv3, d))


def _stresslet_rows(trg, src, pay):
    """Row-major stresslet partial sum (`kernels.stresslet_block` semantics;
    ``pay`` carries the 9 flat S components per source)."""
    S = pay.reshape(pay.shape[0], pay.shape[1], 3, 3)
    d = trg[:, None, :] - src
    r2 = jnp.sum(d * d, axis=-1)
    mask = r2 > 0.0
    rinv = jnp.where(mask, lax.rsqrt(jnp.where(mask, r2, 1.0)), 0.0)
    rinv5 = rinv * rinv * rinv * rinv * rinv
    dSd = jnp.einsum("bsi,bsij,bsj->bs", d, S, d)
    return jnp.einsum("bs,bsk->bk", -3.0 * dSd * rinv5, d)


def _oseen_rows(trg, src, density, eta, reg, epsilon_distance):
    """Row-major regularized-Oseen partial sum (`kernels.oseen_block`
    semantics, already eta-scaled via fr/gr)."""
    d = trg[:, None, :] - src
    r2 = jnp.sum(d * d, axis=-1)
    fr, gr = kernels._regularized_frgr(r2, eta, reg, epsilon_distance)
    df = jnp.sum(d * density, axis=-1)
    return (jnp.einsum("bs,bsk->bk", fr, density)
            + jnp.einsum("bs,bsk->bk", gr * df, d))


@partial(jax.jit, static_argnames=("plan",))
def _stokeslet_tree_impl(plan: TreePlan, anchors, r_src, r_trg, f_src, eta):
    """Jitted core; ``plan`` must be anchor-stripped and ``anchors`` is the
    [1, 3] traced box_lo operand."""
    lo = anchors[0].astype(r_src.dtype)
    factor = 1.0 / (8.0 * math.pi)
    return _tree_eval(plan, lo, r_src, f_src, r_trg,
                      _stokeslet_rows, _stokeslet_rows,
                      factor / eta, factor / eta)


@partial(jax.jit, static_argnames=("plan",))
def _stresslet_tree_impl(plan: TreePlan, anchors, r_dl, r_trg, f_dl, eta):
    lo = anchors[0].astype(r_dl.dtype)
    factor = 1.0 / (8.0 * math.pi)
    return _tree_eval(plan, lo, r_dl, f_dl.reshape(-1, 9), r_trg,
                      _stresslet_rows, _stresslet_rows,
                      factor / eta, factor / eta)


@partial(jax.jit, static_argnames=("plan",))
def _oseen_tree_impl(plan: TreePlan, anchors, r_src, r_trg, density, eta,
                     reg, epsilon_distance):
    lo = anchors[0].astype(r_src.dtype)

    def near(trg, src, pay):
        # already 1/(8 pi eta)-scaled via fr/gr; regularization only acts
        # within epsilon_distance, far below the cell size, so the far
        # field is the plain Stokeslet cluster evaluation
        return _oseen_rows(trg, src, pay, eta, reg, epsilon_distance)

    return _tree_eval(plan, lo, r_src, density, r_trg,
                      near, _stokeslet_rows,
                      1.0, 1.0 / (8.0 * math.pi) / eta)


def stokeslet_tree(plan: TreePlan, r_src, r_trg, f_src, eta):
    """Singular Stokeslet sum via the treecode: same semantics as
    `kernels.stokeslet_direct` (coincident pairs drop — they always land in
    the exact near tile, so no analytic self term exists anywhere).

    ``depth == 0`` plans dispatch to the dense kernel itself (bitwise
    identical). The box anchor enters traced: a drifting cloud whose
    quantized anchor hops one leaf-lattice step reuses the compiled
    program.
    """
    if plan.depth == 0:
        return kernels.stokeslet_direct(r_src, r_trg, f_src, eta)
    return _stokeslet_tree_impl(strip_anchors(plan),
                                plan_anchors(plan, r_src.dtype),
                                r_src, r_trg, f_src, eta)


def stresslet_tree(plan: TreePlan, r_dl, r_trg, f_dl, eta):
    """Singular stresslet (double-layer) sum via the treecode; ``f_dl`` is
    [n_src, 3, 3] like `kernels.stresslet_direct`. The double-layer kernel
    carries one extra derivative, so achieved error runs a few x the
    Stokeslet-calibrated tol — plan a tighter tol for double-layer targets
    (same guidance as `stresslet_ewald`)."""
    if plan.depth == 0:
        return kernels.stresslet_direct(r_dl, r_trg, f_dl, eta)
    return _stresslet_tree_impl(strip_anchors(plan),
                                plan_anchors(plan, r_dl.dtype),
                                r_dl, r_trg, f_dl, eta)


def oseen_tree(plan: TreePlan, r_src, r_trg, density, eta,
               reg=kernels.DEFAULT_REG,
               epsilon_distance=kernels.DEFAULT_EPS):
    """Regularized-Oseen contraction via the treecode: same semantics as
    `kernels.oseen_contract` (near-field regularization below
    ``epsilon_distance``, coincident pairs drop)."""
    if plan.depth == 0:
        return kernels.oseen_contract(r_src, r_trg, density, eta, reg,
                                      epsilon_distance)
    return _oseen_tree_impl(strip_anchors(plan),
                            plan_anchors(plan, r_src.dtype),
                            r_src, r_trg, density, eta, reg,
                            epsilon_distance)


# ---------------------------------------------------------------- skelly-audit

def auditable_programs():
    """The ops layer's audit entry: the jitted treecode Stokeslet evaluator
    on a fiber-like clustered cloud. Its contract pins that the hot fast
    path is collective-free single-chip, callback-free, carries the state
    dtype end to end (no promotions), and compiles once across anchor hops
    (the drift-stability invariant `plan_tree` exists to provide)."""
    from ..audit.registry import AuditProgram, built_from

    def make_scene():
        rng = np.random.default_rng(61)
        nf, nn = 32, 16
        origins = rng.uniform(-2, 2, (nf, 3))
        dirs = rng.normal(size=(nf, 3))
        dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
        t = np.linspace(0, 1.0, nn)
        pts = (origins[:, None, :]
               + t[None, :, None] * dirs[:, None, :]).reshape(-1, 3)
        f = rng.standard_normal((len(pts), 3))
        plan = plan_tree(pts, tol=1e-4)
        return plan, jnp.asarray(pts), jnp.asarray(f)

    def build():
        plan, pts, f = make_scene()
        return built_from(_stokeslet_tree_impl, strip_anchors(plan),
                          plan_anchors(plan), pts, pts, f, 1.0)

    def retrace_probe():
        from ..testing import trace_counting_jit

        plan, pts, f = make_scene()
        step = trace_counting_jit(_stokeslet_tree_impl.__wrapped__,
                                  static_argnames=("plan",))
        step(strip_anchors(plan), plan_anchors(plan), pts, pts, f, 1.0)
        # anchor hop + drifted values: same program, must not retrace
        step(strip_anchors(plan), plan_anchors(plan) + plan.leaf_size,
             pts + 0.01, pts + 0.01, f, 1.0)
        return step.trace_count

    return [AuditProgram(
        name="stokeslet_tree", layer="ops",
        summary="treecode Stokeslet evaluator (depth-2 octree, clustered "
                "fiber cloud, f64)",
        build=build, retrace_probe=retrace_probe)]
