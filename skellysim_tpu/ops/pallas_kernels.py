"""Pallas TPU kernels for the hot pairwise Stokes sums.

The XLA path (`ops.kernels`) materializes [block, n_src] displacement tensors
in HBM between fused ops; these kernels keep the whole interaction tile in
VMEM: coordinates live transposed as [3, N] so the source axis is the 128-wide
lane dimension, each grid cell computes a [TILE_T, TILE_S] interaction block
with pure VPU arithmetic (~20 flops/pair, no MXU dependency), and target tiles
accumulate across the sequential source-tile grid axis.

Numerics follow `ops.kernels.stokeslet_block` exactly: coincident pairs (r == 0)
contribute zero. Padded sources contribute exactly zero because their
force/stresslet densities are zero-padded (every additive term carries a
density factor); the large-but-finite coordinate sentinel only guarantees the
intermediate r^2/rsqrt stay finite so no NaN/Inf can propagate into real rows.
A kernel added on this pattern MUST keep every term density-scaled.

These kernels are float32 (the TPU-resident hot path); the f64 accuracy-gated
path stays on the XLA kernels. `interpret=True` runs them on CPU for the
backend-consistency tests (SURVEY.md §4.1).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# sentinel for padded source coordinates: far enough that rinv underflows to
# exactly 0 in f32, small enough that r^2 stays finite
_PAD_SENTINEL = 1e18

# Tile shapes swept on a v5 lite chip (round 5): stokeslet peaks at
# (256, 1024) ~53 Gpairs/s, stresslet at (128, 2048) ~48 Gpairs/s — the
# stresslet's 9-row source tile wants a wider lane dim at a shorter target
# tile to fit VMEM. Larger source tiles (512x2048+) exceed VMEM and fail to
# compile.
DEFAULT_TILE_T = 256
DEFAULT_TILE_S = 1024
STRESSLET_TILE_T = 128
STRESSLET_TILE_S = 2048


def _vma(*arrays):
    """Union of the operands' varying-mesh-axes: pallas_call under shard_map
    must declare which mesh axes its output varies over (jax >= 0.9
    check_vma); outside shard_map every vma is empty and this is a no-op.
    Pre-0.9 jax (the pinned container version) has neither `jax.typeof` nor
    the vma system — nothing to declare (`parallel.compat` runs those
    shard_maps with replication checking off)."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return frozenset()
    out = frozenset()
    for a in arrays:
        out |= getattr(typeof(a), "vma", frozenset())
    return out


def _out_struct(shape, dtype, *arrays):
    """`jax.ShapeDtypeStruct` carrying the operands' vma union where the
    jax version supports it (>= 0.9); plain struct on the pre-vma pinned
    container jax, whose ShapeDtypeStruct rejects the kwarg."""
    if getattr(jax, "typeof", None) is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=_vma(*arrays))


def _pad_to(a, n, axis, value=0.0):
    pad = n - a.shape[axis]
    if pad == 0:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, pad)
    return jnp.pad(a, widths, constant_values=value)


def stokeslet_tile_sums(trg_T, src_T, f_T):
    """Unscaled Stokeslet pair sums for one transposed-layout tile:
    ``[3, nt]`` targets x ``[3, ns]`` sources/forces -> ``(ux, uy, uz)``
    row sums. Pure jnp math on values already loaded from refs — the ONE
    definition shared by the gridded VMEM tile below and the fused ring
    kernel (`parallel.ring_fused`), so the two cannot drift."""
    tx, ty, tz = trg_T[0, :], trg_T[1, :], trg_T[2, :]
    sx, sy, sz = src_T[0, :], src_T[1, :], src_T[2, :]
    fx, fy, fz = f_T[0, :], f_T[1, :], f_T[2, :]

    dx = tx[:, None] - sx[None, :]
    dy = ty[:, None] - sy[None, :]
    dz = tz[:, None] - sz[None, :]
    r2 = dx * dx + dy * dy + dz * dz
    mask = r2 > 0.0
    rinv = jnp.where(mask, lax.rsqrt(jnp.where(mask, r2, 1.0)), 0.0)
    rinv3 = rinv * rinv * rinv

    df = dx * fx[None, :] + dy * fy[None, :] + dz * fz[None, :]
    common = df * rinv3

    ux = jnp.sum(rinv * fx[None, :] + common * dx, axis=1)
    uy = jnp.sum(rinv * fy[None, :] + common * dy, axis=1)
    uz = jnp.sum(rinv * fz[None, :] + common * dz, axis=1)
    return ux, uy, uz


def _stokeslet_kernel(trg_ref, src_ref, f_ref, out_ref):
    """One [TILE_T, TILE_S] interaction tile; accumulates over grid axis 1."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    ux, uy, uz = stokeslet_tile_sums(trg_ref[:], src_ref[:], f_ref[:])
    out_ref[0, :] += ux
    out_ref[1, :] += uy
    out_ref[2, :] += uz


@partial(jax.jit, static_argnames=("tile_t", "tile_s", "interpret"))
def stokeslet_pallas(r_src, r_trg, f_src, eta, *, tile_t: int = DEFAULT_TILE_T,
                     tile_s: int = DEFAULT_TILE_S, interpret: bool = False):
    """Singular Stokeslet sum as a fused Pallas kernel.

    Same contract as `ops.kernels.stokeslet_direct`: [n_src, 3] sources,
    [n_trg, 3] targets, [n_src, 3] forces -> [n_trg, 3] velocities.
    """
    n_trg, n_src = r_trg.shape[0], r_src.shape[0]
    if n_trg == 0 or n_src == 0:
        return jnp.zeros_like(r_trg)
    dtype = r_trg.dtype

    nt = pl.cdiv(n_trg, tile_t) * tile_t
    ns = pl.cdiv(n_src, tile_s) * tile_s

    trg_T = _pad_to(r_trg.T, nt, axis=1)
    src_T = _pad_to(r_src.T, ns, axis=1, value=_PAD_SENTINEL)
    f_T = _pad_to(f_src.T, ns, axis=1)

    grid = (nt // tile_t, ns // tile_s)
    # index-map zeros must be np.int32: under jax_enable_x64 a literal 0
    # traces as i64 while grid indices stay i32, and Mosaic rejects the
    # mixed-type index map (remote-compile HTTP 500 on this backend)
    z = np.int32(0)
    u_T = pl.pallas_call(
        _stokeslet_kernel,
        # vma: inside shard_map (the ring evaluator's tile) the output varies
        # over whatever mesh axes the operands do; outside it's frozenset()
        out_shape=_out_struct((3, nt), dtype, trg_T, src_T, f_T),
        grid=grid,
        in_specs=[
            pl.BlockSpec((3, tile_t), lambda i, j: (z, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, tile_s), lambda i, j: (z, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, tile_s), lambda i, j: (z, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((3, tile_t), lambda i, j: (z, i),
                               memory_space=pltpu.VMEM),
        cost_estimate=pl.CostEstimate(
            flops=22 * nt * ns, bytes_accessed=4 * 3 * (nt + 2 * ns + nt),
            transcendentals=nt * ns),
        interpret=interpret,
    )(trg_T, src_T, f_T)

    factor = 1.0 / (8.0 * math.pi)
    return u_T.T[:n_trg] * (factor / eta)


def stresslet_tile_sums(trg_T, src_T, s_T):
    """Unscaled stresslet pair sums for one transposed-layout tile:
    ``[3, nt]`` targets x ``[3, ns]`` sources + ``[9, ns]`` row-major
    stresslet components -> ``(ux, uy, uz)``. Shared by the gridded tile
    and the fused ring kernel like `stokeslet_tile_sums`."""
    tx, ty, tz = trg_T[0, :], trg_T[1, :], trg_T[2, :]
    sx, sy, sz = src_T[0, :], src_T[1, :], src_T[2, :]

    dx = tx[:, None] - sx[None, :]
    dy = ty[:, None] - sy[None, :]
    dz = tz[:, None] - sz[None, :]
    r2 = dx * dx + dy * dy + dz * dz
    mask = r2 > 0.0
    rinv = jnp.where(mask, lax.rsqrt(jnp.where(mask, r2, 1.0)), 0.0)
    rinv2 = rinv * rinv
    rinv5 = rinv2 * rinv2 * rinv

    # d^T S d over the 9 components (S row-major: Sxx..Szz)
    dSd = (dx * dx * s_T[0, :][None, :] + dx * dy * s_T[1, :][None, :]
           + dx * dz * s_T[2, :][None, :] + dy * dx * s_T[3, :][None, :]
           + dy * dy * s_T[4, :][None, :] + dy * dz * s_T[5, :][None, :]
           + dz * dx * s_T[6, :][None, :] + dz * dy * s_T[7, :][None, :]
           + dz * dz * s_T[8, :][None, :])
    common = -3.0 * dSd * rinv5

    return (jnp.sum(common * dx, axis=1), jnp.sum(common * dy, axis=1),
            jnp.sum(common * dz, axis=1))


def _stresslet_kernel(trg_ref, src_ref, s_ref, out_ref):
    """Stresslet tile: s_ref holds the 9 source components [9, TILE_S]."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    ux, uy, uz = stresslet_tile_sums(trg_ref[:], src_ref[:], s_ref[:])
    out_ref[0, :] += ux
    out_ref[1, :] += uy
    out_ref[2, :] += uz


@partial(jax.jit, static_argnames=("tile_t", "tile_s", "interpret"))
def stresslet_pallas(r_dl, r_trg, f_dl, eta, *, tile_t: int = STRESSLET_TILE_T,
                     tile_s: int = STRESSLET_TILE_S, interpret: bool = False):
    """Singular stresslet sum as a fused Pallas kernel.

    Same contract as `ops.kernels.stresslet_direct`: ``f_dl`` is [n_src, 3, 3].
    """
    n_trg, n_src = r_trg.shape[0], r_dl.shape[0]
    if n_trg == 0 or n_src == 0:
        return jnp.zeros_like(r_trg)
    dtype = r_trg.dtype

    nt = pl.cdiv(n_trg, tile_t) * tile_t
    ns = pl.cdiv(n_src, tile_s) * tile_s

    trg_T = _pad_to(r_trg.T, nt, axis=1)
    src_T = _pad_to(r_dl.T, ns, axis=1, value=_PAD_SENTINEL)
    s_T = _pad_to(f_dl.reshape(n_src, 9).T, ns, axis=1)

    grid = (nt // tile_t, ns // tile_s)
    z = np.int32(0)  # see stokeslet_pallas: i64/i32 index-map mix breaks Mosaic
    u_T = pl.pallas_call(
        _stresslet_kernel,
        out_shape=_out_struct((3, nt), dtype, trg_T, src_T, s_T),
        grid=grid,
        in_specs=[
            pl.BlockSpec((3, tile_t), lambda i, j: (z, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, tile_s), lambda i, j: (z, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((9, tile_s), lambda i, j: (z, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((3, tile_t), lambda i, j: (z, i),
                               memory_space=pltpu.VMEM),
        cost_estimate=pl.CostEstimate(
            flops=40 * nt * ns, bytes_accessed=4 * (3 * nt + 12 * ns + 3 * nt),
            transcendentals=nt * ns),
        interpret=interpret,
    )(trg_T, src_T, s_T)

    factor = 1.0 / (8.0 * math.pi)
    return u_T.T[:n_trg] * (factor / eta)


# eta chosen so stokeslet_pallas's trailing (1/(8 pi))/eta scale is exactly
# 1.0: these block entry points return the UNSCALED pair sum, matching the
# `ops.kernels.stokeslet_block` contract (the caller — the ring evaluator —
# applies 1/(8 pi eta) once at the end).
_UNIT_ETA = 1.0 / (8.0 * math.pi)


def stokeslet_pallas_block(r_trg, r_src, f_src, *, interpret: bool = False):
    """Unscaled Stokeslet interaction block — the ring evaluator's Pallas
    tile (`parallel.ring.ring_stokeslet(impl="pallas")`). Same signature
    order as `ops.kernels.stokeslet_block` (targets first)."""
    return stokeslet_pallas(r_src, r_trg, f_src, _UNIT_ETA,
                            interpret=interpret)


def stresslet_pallas_block(r_trg, r_dl, f_dl, *, interpret: bool = False):
    """Unscaled stresslet interaction block for the ring evaluator."""
    return stresslet_pallas(r_dl, r_trg, f_dl, _UNIT_ETA,
                            interpret=interpret)


def auditable_kernels():
    """The gridded tile kernels' entries for the ``dma`` audit check:
    each traced at its default multi-tile grid (2x2, so the block specs —
    not degenerate whole-array blocks — are what the VMEM accounting
    walks). No DMA/semaphore traffic here; the check pins exactly that
    (zero comm slots, zero semaphores) plus the tile footprint against
    the shared budget. Defining this seam licenses this module for the
    ``raw-dma`` lint rule."""
    from ..audit.dmaflow import pallas_calls
    from ..audit.registry import AuditKernel, BuiltKernel

    specs = [
        ("stokeslet_pallas_tiles", stokeslet_pallas,
         DEFAULT_TILE_T, DEFAULT_TILE_S, (3,)),
        ("stresslet_pallas_tiles", stresslet_pallas,
         STRESSLET_TILE_T, STRESSLET_TILE_S, (3, 3)),
    ]

    def build(fn, tile_t, tile_s, pay):
        def _build():
            n_trg, n_src = 2 * tile_t, 2 * tile_s
            closed = jax.make_jaxpr(
                lambda r_s, r_t, f: fn(r_s, r_t, f, _UNIT_ETA))(
                    jnp.zeros((n_src, 3), jnp.float32),
                    jnp.zeros((n_trg, 3), jnp.float32),
                    jnp.zeros((n_src,) + pay, jnp.float32))
            (kernel_jaxpr, grid_mapping), = pallas_calls(closed.jaxpr)
            return BuiltKernel(kernel_jaxpr=kernel_jaxpr,
                               grid_mapping=grid_mapping, n_dev=1,
                               scene={})
        return _build

    return [
        AuditKernel(name=name, layer="ops",
                    summary=(f"gridded {name.split('_')[0]} pair kernel: "
                             f"{tile_t}x{tile_s} VMEM tiles"),
                    build=build(fn, tile_t, tile_s, pay))
        for name, fn, tile_t, tile_s, pay in specs
    ]
