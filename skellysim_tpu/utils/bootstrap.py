"""Force a virtual multi-device CPU platform before JAX backend init.

One shared implementation of the workaround needed in this environment (used by
``tests/conftest.py``, ``__graft_entry__.dryrun_multichip``, and subprocess
tests): the session registers an experimental ``axon`` TPU plugin via a
sitecustomize hook whose client init goes through a tunnel that can block for
minutes, and which hijacks backend selection even under ``JAX_PLATFORMS=cpu``.
Multi-chip sharding correctness is validated on a virtual CPU device mesh
(``--xla_force_host_platform_device_count``), mirroring the reference's
multi-rank-without-a-cluster strategy
(/root/reference/tests/core/unit_tests/CMakeLists.txt:12-19: ctest under
``mpiexec -n 2``).

Must be called before JAX initializes any backend; the pin is process-wide and
irreversible (XLA backends are created once), so callers that also need a real
TPU must use a separate process.
"""

from __future__ import annotations

import os
import re
import sys

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def _jax_initialized() -> bool:
    """True if JAX has already committed to a backend (too late to bootstrap).

    Private-API probe; on attribute drift we return True (fail closed) so the
    caller verifies the device count instead of mutating dead env vars.
    """
    xb = sys.modules.get("jax._src.xla_bridge")
    if xb is None:
        return False
    try:
        return bool(xb._default_backend) or bool(xb._backends)
    except AttributeError:
        return True


def force_cpu_devices(n_devices: int | None = None) -> None:
    """Pin JAX to CPU with at least ``n_devices`` virtual devices.

    Safe to call multiple times. If JAX is already initialized, verifies the
    existing platform exposes enough devices and raises otherwise.
    """
    if not _jax_initialized():
        if n_devices is not None:
            flags = os.environ.get("XLA_FLAGS", "")
            m = re.search(rf"{_COUNT_FLAG}=(\d+)", flags)
            if m is None:
                os.environ["XLA_FLAGS"] = (
                    f"{flags} {_COUNT_FLAG}={n_devices}").strip()
            elif int(m.group(1)) < n_devices:
                os.environ["XLA_FLAGS"] = flags.replace(
                    m.group(0), f"{_COUNT_FLAG}={n_devices}")
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            import jax._src.xla_bridge as _xb  # private; guarded for drift

            _xb._backend_factories.pop("axon", None)
        except Exception:
            pass
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    if n_devices is not None:
        import jax

        have = jax.device_count()
        if have < n_devices:
            raise RuntimeError(
                f"JAX initialized with {have} device(s) < {n_devices}. "
                "force_cpu_devices must run before JAX backend init, or set "
                f"XLA_FLAGS={_COUNT_FLAG}={n_devices} JAX_PLATFORMS=cpu in "
                "the environment.")


#: one min-compile-time threshold for every cache consumer (CLIs, bench.py,
#: the obs cost gate): trivial programs stay out of the persistent cache
CACHE_MIN_COMPILE_S = 1.0


def default_cache_dir() -> str:
    """The package root's ``.jax_cache`` — the ONE default location shared
    by every CLI, `bench.py`, and the obs cost gate, so a cold server start
    reuses the executables a CI run or bench already compiled. Override
    with the ``SKELLYSIM_JAX_CACHE`` environment variable."""
    env = os.environ.get("SKELLYSIM_JAX_CACHE")
    if env:
        return env
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg), ".jax_cache")


def enable_compilation_cache(cache_dir: str | None = "auto") -> str | None:
    """Point JAX's persistent XLA compilation cache at ``cache_dir``.

    The one implementation behind every CLI's cache wiring (run, ensemble,
    serve, listener, the obs cost gate, bench.py): compiled executables
    persist across processes, so a cold server start (or CI re-run) whose
    programs were compiled before skips the multi-minute XLA compiles and
    goes straight to warm admission. The persistent cache is DEFAULT-ON
    (skelly-bucket): ``"auto"`` resolves to `default_cache_dir`; ``None``,
    ``""`` or ``"off"`` disable it (the CLIs' ``--no-jax-cache`` /
    ``[runtime] jax_cache = "off"`` opt-outs); anything else is an
    explicit directory. Returns the absolute cache path or None when off.

    Min-compile-time threshold of `CACHE_MIN_COMPILE_S` keeps trivial
    programs out of the cache; failures are non-fatal — an unwritable
    cache dir must not kill a run that would merely recompile.
    """
    if not cache_dir or cache_dir == "off":
        return None
    if cache_dir == "auto":
        cache_dir = default_cache_dir()
    import jax

    path = os.path.abspath(cache_dir)
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          CACHE_MIN_COMPILE_S)
    except Exception as e:
        import logging

        logging.getLogger("skellysim_tpu").warning(
            "compilation cache %s not enabled: %s", path, e)
        return None
    return path
