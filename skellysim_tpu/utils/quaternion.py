"""Quaternion helpers (w, x, y, z convention), jit/vmap friendly.

Replaces Eigen::Quaterniond usage in body stepping (`body_spherical.cpp:13-35`)
and the reference's minimal Python quaternion (`src/skelly_sim/quaternion.py`).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# host-side constant: creating a device array at import time would trigger JAX
# backend init as a side effect of `import skellysim_tpu` (before callers can
# pin a platform); consumers jnp.asarray() it with their own dtype
IDENTITY = np.asarray([1.0, 0.0, 0.0, 0.0])


def multiply(q1, q2):
    w1, x1, y1, z1 = q1[..., 0], q1[..., 1], q1[..., 2], q1[..., 3]
    w2, x2, y2, z2 = q2[..., 0], q2[..., 1], q2[..., 2], q2[..., 3]
    return jnp.stack([
        w1 * w2 - x1 * x2 - y1 * y2 - z1 * z2,
        w1 * x2 + x1 * w2 + y1 * z2 - z1 * y2,
        w1 * y2 - x1 * z2 + y1 * w2 + z1 * x2,
        w1 * z2 + x1 * y2 - y1 * x2 + z1 * w2,
    ], axis=-1)


def rotation_matrix(q):
    """[..., 4] -> [..., 3, 3] rotation matrix."""
    w, x, y, z = q[..., 0], q[..., 1], q[..., 2], q[..., 3]
    return jnp.stack([
        jnp.stack([1 - 2 * (y**2 + z**2), 2 * (x * y - w * z), 2 * (x * z + w * y)], axis=-1),
        jnp.stack([2 * (x * y + w * z), 1 - 2 * (x**2 + z**2), 2 * (y * z - w * x)], axis=-1),
        jnp.stack([2 * (x * z - w * y), 2 * (y * z + w * x), 1 - 2 * (x**2 + y**2)], axis=-1),
    ], axis=-2)


def from_rotation_vector(phi):
    """Rotation vector [..., 3] -> quaternion; safe at |phi| = 0."""
    norm = jnp.linalg.norm(phi, axis=-1, keepdims=True)
    safe = jnp.where(norm > 0, norm, 1.0)
    half = 0.5 * norm
    w = jnp.cos(half)
    xyz = jnp.where(norm > 0, jnp.sin(half) * phi / safe, jnp.zeros_like(phi))
    return jnp.concatenate([w, xyz], axis=-1)


def normalize(q):
    return q / jnp.linalg.norm(q, axis=-1, keepdims=True)
