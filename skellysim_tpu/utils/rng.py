"""Counter-based RNG with dump/restore, the analogue of the reference's trng4
`yarn2` engines (`/root/reference/src/core/rng.cpp:13-63`).

Two named streams mirror the reference: ``shared`` (identical draws everywhere;
`split(2,0)`) and ``distributed`` (per-domain draws; `split(2,1)` +
per-rank split). On TPU there are no ranks — the whole simulation is one
program — so both streams are plain counter-based JAX key chains. Determinism
is *rank-count independent*, which removes the reference's resume restriction
(`trajectory_reader.cpp:204-219`: resume requires the same rank count).

State is (seed, counter) per stream, serialized to the trajectory as
``"seed:counter"`` strings in the reference's `rng_state` field
(`io_maps.hpp:24`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class Stream:
    """One counter-based draw stream. Every draw folds the counter into the
    base key, so state = (seed, stream_id, counter) fully determines the
    future sequence."""

    def __init__(self, seed: int, stream_id: int, counter: int = 0):
        self.seed = int(seed)
        self.stream_id = int(stream_id)
        self.counter = int(counter)
        self._base = jax.random.fold_in(jax.random.PRNGKey(self.seed),
                                        self.stream_id)

    def _next_key(self):
        k = jax.random.fold_in(self._base, self.counter)
        self.counter += 1
        return k

    def uniform(self, low=0.0, high=1.0, size=None):
        shape = () if size is None else ((size,) if np.isscalar(size) else tuple(size))
        out = np.asarray(jax.random.uniform(
            self._next_key(), shape, dtype=jnp.float64, minval=low, maxval=high))
        return float(out) if size is None else out

    def uniform_int(self, low: int, high: int, size=None):
        """Integer in [low, high) (trng `uniform_int_dist` semantics)."""
        shape = () if size is None else ((size,) if np.isscalar(size) else tuple(size))
        out = np.asarray(jax.random.randint(self._next_key(), shape, low, high))
        return int(out) if size is None else out

    def normal(self, mu=0.0, sigma=1.0, size=None):
        shape = () if size is None else ((size,) if np.isscalar(size) else tuple(size))
        out = mu + sigma * np.asarray(jax.random.normal(
            self._next_key(), shape, dtype=jnp.float64))
        return float(out) if size is None else out

    def poisson_int(self, lam: float, size=None) -> int:
        shape = () if size is None else ((size,) if np.isscalar(size) else tuple(size))
        out = np.asarray(jax.random.poisson(self._next_key(), lam, shape))
        return int(out) if size is None else out

    def dump(self) -> str:
        return f"{self.seed}:{self.stream_id}:{self.counter}"

    @staticmethod
    def load(s: str) -> "Stream":
        seed, stream_id, counter = (int(v) for v in s.split(":"))
        return Stream(seed, stream_id, counter)


class SimRNG:
    """The two-stream RNG bundle (`RNG::init`, `rng.cpp:18-32`)."""

    def __init__(self, seed: int = 1):
        self.shared = Stream(seed, 0)
        self.distributed = Stream(seed, 1)

    def member(self, i: int) -> "SimRNG":
        """Deterministic per-member bundle for ensemble replicas.

        Member ``i`` draws from stream ids ``(2i + 2, 2i + 3)`` of the same
        seeds — disjoint from the base bundle's ``(0, 1)`` and from every
        other member, and a pure function of ``(seed, i)``: replica i's
        draws are reproducible no matter how the ensemble scheduler packs
        lanes or in what order members run. Derivation ignores the base
        streams' counters for the same reason. The derived bundle
        round-trips through `dump_state`/`from_state` unchanged (stream
        state is ``seed:stream_id:counter``), so member trajectories
        resume like single runs.
        """
        if i < 0:
            raise ValueError(f"member index must be >= 0, got {i}")
        rng = SimRNG.__new__(SimRNG)
        rng.shared = Stream(self.shared.seed, 2 * i + 2)
        rng.distributed = Stream(self.distributed.seed, 2 * i + 3)
        return rng

    def dump_state(self):
        """Trajectory `rng_state` payload: [[name, state], ...]."""
        return [["shared", self.shared.dump()],
                ["distributed", self.distributed.dump()]]

    @staticmethod
    def from_state(state) -> "SimRNG":
        rng = SimRNG()
        names = {name: s for name, s in state}
        if "shared" in names:
            rng.shared = Stream.load(names["shared"])
        if "distributed" in names:
            rng.distributed = Stream.load(names["distributed"])
        return rng
