from . import quaternion  # noqa: F401
