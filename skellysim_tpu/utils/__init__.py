from . import quaternion  # noqa: F401
from .rng import SimRNG, Stream  # noqa: F401
