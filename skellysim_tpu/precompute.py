"""Precompute console: periphery + body quadrature/operator npz generation.

Counterpart of the reference's `skelly_precompute` entry point
(`/root/reference/src/skelly_sim/precompute.py:17-280`): reads the TOML config,
builds every body's quadrature npz and the periphery's dense operator npz, and
— for surface-of-revolution peripheries — rewrites the config with the actual
node count chosen by the envelope discretization.

Usage: python -m skellysim_tpu.precompute [skelly_config.toml]
"""

from __future__ import annotations

import argparse
import os

import numpy as np

from .config import schema
from .periphery.precompute import precompute_body, precompute_periphery


def precompute_from_config(config_file: str, verbose: bool = True,
                           operator_backend: str = "host") -> None:
    # the float64 operator promised to the solver requires x64: BOTH backends
    # assemble through the JAX kernels (`periphery.build_shell_operator` wraps
    # `kernels.stresslet_times_normal_blocked`), and without x64 jnp silently
    # canonicalizes the assembly to f32 — measured 2.7e-8 relative error on
    # the stored operator when this enable was missing (round 5 verify)
    import jax

    jax.config.update("jax_enable_x64", True)
    config = schema.load_config(config_file)
    config_dir = os.path.dirname(os.path.abspath(config_file)) or "."

    done: set[str] = set()
    for body in config.bodies:
        path = os.path.join(config_dir, body.precompute_file)
        if path in done:
            continue
        if verbose:
            print(f"Precomputing body ({body.shape}, n={body.n_nodes}) "
                  f"-> {body.precompute_file}")
        a, b, c = body.axis_length
        data = precompute_body(body.shape.lower(), body.n_nodes,
                               radius=body.radius, a=a, b=b, c=c)
        np.savez(path, **data)
        done.add(path)

    periphery = getattr(config, "periphery", None)
    if periphery is not None:
        kw: dict = {"eta": config.params.eta}
        if periphery.shape == "sphere":
            kw["radius"] = periphery.radius
        elif periphery.shape == "ellipsoid":
            kw.update(a=periphery.a, b=periphery.b, c=periphery.c)
        elif periphery.shape == "surface_of_revolution":
            kw["envelope"] = dict(periphery.envelope)
        if verbose:
            print(f"Precomputing periphery ({periphery.shape}, "
                  f"n={periphery.n_nodes}) -> {periphery.precompute_file}")
        data = precompute_periphery(periphery.shape, periphery.n_nodes,
                                    operator_backend=operator_backend, **kw)
        np.savez(os.path.join(config_dir, periphery.precompute_file), **data)

        n_actual = data["nodes"].shape[0]
        if n_actual != periphery.n_nodes:
            # the envelope discretization picks the real node count; write it
            # back so the runtime sees consistent sizes (`precompute.py:270-280`)
            if verbose:
                print(f"Updating config n_nodes: {periphery.n_nodes} -> {n_actual}")
            periphery.n_nodes = n_actual
            config.save(config_file)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="skellysim-tpu-precompute",
        description="Generate periphery/body precompute npz files for a config")
    ap.add_argument("config_file", nargs="?", default="skelly_config.toml")
    ap.add_argument("--device-operator", action="store_true",
                    help="assemble + invert the dense shell operator on the "
                         "accelerator (float32 preconditioner-grade inverse; "
                         "the float64 operator and the quadrature are "
                         "unchanged) — seconds instead of minutes at 6000 "
                         "nodes")
    args = ap.parse_args(argv)
    precompute_from_config(
        args.config_file,
        operator_backend="device" if args.device_operator else "host")


if __name__ == "__main__":
    main()
