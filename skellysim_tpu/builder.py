"""Config → runnable simulation: the TOML contract wired into runtime objects.

TPU-native counterpart of `System::init` (`/root/reference/src/core/system.cpp:632-720`):
reads the TOML config + precompute npz files and assembles `System`, the initial
`SimState`, and the `SimRNG`. Where the reference constructs C++ containers and
scatters precompute rows over MPI ranks, here everything lands in batched device
arrays (sharding is applied later by `parallel.shard_state`).

Restrictions vs the reference (deliberate, batched-tensor design):
- all fibers in one config must share `n_nodes` (one resolution bucket);
- all bodies must share `n_nodes` and `n_nucleation_sites` (one body batch).
"""

from __future__ import annotations

import os

import numpy as np
import jax
import jax.numpy as jnp

from .bodies import bodies as bd
from .config import schema
from .fibers import container as fc
from .periphery import periphery as peri
from .system import BackgroundFlow, PointSources, System
from .utils.rng import SimRNG


def _load_npz(path: str, what: str) -> dict:
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{what} precompute file '{path}' not found — run the precompute "
            "step first (python -m skellysim_tpu.precompute)")
    with np.load(path) as z:
        return {k: z[k] for k in z.files}


def build_fibers(cfg_fibers: list, dtype):
    """FiberGroup (one resolution) or tuple of per-resolution buckets.

    Mixed n_nodes configs bucket by resolution in first-appearance order —
    the batched counterpart of the reference's mixed-resolution
    `std::list` container (`fiber_finite_difference.cpp:519-562`). Each
    fiber's config position is recorded as `config_rank` so trajectory
    output stays reference- (config-) ordered.
    """
    if not cfg_fibers:
        return None
    by_n: dict = {}
    for rank, f in enumerate(cfg_fibers):
        by_n.setdefault(int(f.n_nodes), []).append((rank, f))

    def one_bucket(items):
        ranks = [r for r, _ in items]
        fibs = [f for _, f in items]
        n = fibs[0].n_nodes
        x = np.stack([np.asarray(f.x, dtype=float).reshape(n, 3) for f in fibs])
        parent_body = np.array([f.parent_body for f in fibs], dtype=np.int32)
        parent_site = np.array([f.parent_site for f in fibs], dtype=np.int32)
        minus_clamped = np.array([f.minus_clamped or f.parent_body >= 0
                                  for f in fibs])
        return fc.make_group(
            x,
            lengths=np.array([f.length for f in fibs]),
            bending_rigidity=np.array([f.bending_rigidity for f in fibs]),
            radius=np.array([f.radius for f in fibs]),
            force_scale=np.array([f.force_scale for f in fibs]),
            minus_clamped=minus_clamped,
            binding_body=parent_body, binding_site=parent_site,
            config_rank=np.array(ranks, dtype=np.int32),
            dtype=dtype)

    groups = [one_bucket(items) for items in by_n.values()]
    return groups[0] if len(groups) == 1 else tuple(groups)


def build_bodies(cfg_bodies: list, config_dir: str, dtype,
                 synthesize_precompute: bool = False):
    """BodyGroup (one shape/resolution) or tuple of per-(shape, n_nodes,
    n_sites) buckets.

    Mixed body types/sizes bucket in first-appearance order — the batched
    counterpart of the reference's polymorphic `BodyContainer`
    (`body_container.cpp:523-550`). `config_rank` records each body's
    config position: it is the GLOBAL id fibers' `parent_body` refers to
    and the trajectory's wire order.

    ``synthesize_precompute`` computes analytic (sphere/ellipsoid) body
    surfaces in-process when the npz is MISSING — skelly-serve's path:
    tenant configs arrive as TOML text over the wire and cannot carry npz
    files, but a spherical MTOC's quadrature is a deterministic function
    of (shape, n_nodes, radius) the server can rebuild itself
    (docs/scenarios.md "DI tenants"). The default (off) keeps the CLI's
    explicit missing-file error — batch runs precompute up front.
    """
    if not cfg_bodies:
        return None
    if any(b.shape == "deformable" for b in cfg_bodies):
        from .bodies import deformable

        deformable.make_group()  # raises: declared-but-unimplemented parity stub

    def load(b):
        path = os.path.join(config_dir, b.precompute_file)
        if (synthesize_precompute and not os.path.exists(path)
                and b.shape in ("sphere", "ellipsoid")):
            from .periphery.precompute import precompute_body

            a, bb, c = b.axis_length
            return precompute_body(b.shape, b.n_nodes, radius=b.radius,
                                   a=a, b=bb, c=c)
        return _load_npz(path, "body")

    pre_all = [load(b) for b in cfg_bodies]

    def runtime_quat(b):
        # TOML orientation follows the schema/Eigen-coeffs order [x, y, z, w]
        # (`skelly_config.py:729`, default [0,0,0,1]); runtime + trajectory
        # wire use (w, x, y, z) (`eigen_quaternion_plugin.h:27-36`)
        x, y, z, w = np.asarray(b.orientation, dtype=float)
        return np.array([w, x, y, z])

    def sites_ref(b, ns):
        # config nucleation sites are lab-frame at t=0; body-frame storage must
        # undo the configured orientation (lab = pos + R(q) @ ref,
        # `body_spherical.cpp:158`), so ref = R(q)^T @ (lab - pos)
        from .utils import quaternion as quat

        s = np.asarray(b.nucleation_sites, dtype=float).reshape(ns, 3)
        R = np.asarray(quat.rotation_matrix(runtime_quat(b)))
        return (s - np.asarray(b.position)) @ R  # (R^T @ d^T)^T = d @ R

    by_key: dict = {}
    for rank, (b, p) in enumerate(zip(cfg_bodies, pre_all)):
        key = (b.shape, p["node_positions_ref"].shape[0],
               len(b.nucleation_sites) // 3)
        by_key.setdefault(key, []).append((rank, b, p))

    def one_bucket(key, items):
        shape, _, ns = key
        ranks = [r for r, _, _ in items]
        bods = [b for _, b, _ in items]
        pre = [p for _, _, p in items]
        ext_type = [bd.EXTFORCE_OSCILLATORY
                    if b.external_force_type == "Oscillatory"
                    else bd.EXTFORCE_LINEAR for b in bods]
        return bd.make_group(
            np.stack([p["node_positions_ref"] for p in pre]),
            np.stack([p["node_normals_ref"] for p in pre]),
            np.stack([p["node_weights"] for p in pre]),
            position=np.stack([b.position for b in bods]),
            orientation=np.stack([runtime_quat(b) for b in bods]),
            nucleation_sites_ref=np.stack([sites_ref(b, ns) for b in bods]),
            external_force=np.stack([b.external_force for b in bods]),
            external_torque=np.stack([b.external_torque for b in bods]),
            ext_force_type=np.array(ext_type, dtype=np.int32),
            osc_amplitude=np.array([b.external_oscillation_force_amplitude
                                    for b in bods]),
            osc_omega=np.array([2 * np.pi * b.external_oscillation_force_frequency
                                for b in bods]),
            osc_phase=np.array([b.external_oscillation_force_phase
                                for b in bods]),
            radius=np.array([b.radius for b in bods]),
            kind=shape if shape in ("sphere", "ellipsoid") else "generic",
            # semiaxes drive the ellipsoid rigid-motion containment override
            # in velocity fields (`system.cpp:371-380`); zero for others
            semiaxes=np.array([b.axis_length if b.shape == "ellipsoid"
                               else [0.0, 0.0, 0.0] for b in bods]),
            config_rank=np.array(ranks, dtype=np.int32),
            dtype=dtype)

    groups = [one_bucket(key, items) for key, items in by_key.items()]
    return groups[0] if len(groups) == 1 else tuple(groups)


def build_periphery(cfg_periphery, config_dir: str, dtype, precond_dtype=None):
    """(PeripheryState, PeripheryShape) from config + precompute npz.

    ``precond_dtype`` stores M_inv (the preconditioner) at a lower precision
    — the mixed solver only ever applies it in f32, so keeping an f64 copy
    would waste (3N)^2 * 8 bytes of HBM."""
    data = _load_npz(os.path.join(config_dir, cfg_periphery.precompute_file),
                     "periphery")
    state = peri.make_state(data["nodes"], data["normals"],
                            data["quadrature_weights"],
                            data["stresslet_plus_complementary"],
                            data["M_inv"], dtype=dtype,
                            precond_dtype=precond_dtype)
    shape_name = getattr(cfg_periphery, "shape", "sphere")
    if shape_name == "sphere":
        shape = peri.PeripheryShape(kind="sphere", radius=cfg_periphery.radius)
    elif shape_name == "ellipsoid":
        shape = peri.PeripheryShape(
            kind="ellipsoid",
            abc=(cfg_periphery.a, cfg_periphery.b, cfg_periphery.c))
    else:
        shape = peri.PeripheryShape(kind="generic")
    return state, shape


def build_point_sources(cfg_points: list, dtype) -> PointSources | None:
    if not cfg_points:
        return None
    return PointSources.make(
        position=np.stack([p.position for p in cfg_points]),
        force=np.stack([p.force for p in cfg_points]),
        torque=np.stack([p.torque for p in cfg_points]),
        time_to_live=np.array([p.time_to_live for p in cfg_points]),
        dtype=dtype)


def build_background(cfg_bg, dtype) -> BackgroundFlow | None:
    if cfg_bg is None:
        return None
    if not any(cfg_bg.uniform) and not any(cfg_bg.scale_factor):
        return None
    return BackgroundFlow.make(uniform=cfg_bg.uniform,
                               components=cfg_bg.components,
                               scale=cfg_bg.scale_factor, dtype=dtype)


def build_simulation(config, config_dir: str = ".", dtype=jnp.float64,
                     mesh=None, synthesize_body_precompute: bool = False):
    """Config (object or TOML path) → (System, SimState, SimRNG).

    ``mesh`` enables the ring pair evaluator when the config selects
    pair_evaluator = "ring"; without one the dense direct path runs.
    ``synthesize_body_precompute`` rebuilds missing analytic body npz
    in-process (`build_bodies`) — the serve submit path.
    """
    if isinstance(config, (str, os.PathLike)):
        config_dir = os.path.dirname(os.path.abspath(config)) or "."
        config = schema.load_config(str(config))

    params = schema.to_runtime_params(config.params)
    if params.pair_evaluator == "ring" and mesh is None:
        import warnings

        warnings.warn("config selects pair_evaluator='ring' but no mesh was "
                      "given to build_simulation; using the direct evaluator")
    shell, shape = (None, None)
    if getattr(config, "periphery", None) is not None:
        # mixed mode gets an f32 M_inv, halving the shell preconditioner's
        # HBM; one policy shared with System._precision_for
        from .params import resolve_precision

        mixed = resolve_precision(params.solver_precision,
                                  dtype == jnp.float64) == "mixed"
        pdt = jnp.float32 if mixed else None
        shell, shape = build_periphery(config.periphery, config_dir, dtype,
                                       precond_dtype=pdt)

    fibers = build_fibers(config.fibers, dtype)
    if (fibers is not None and params.pair_evaluator == "ring"
            and mesh is not None):
        # round the fiber batch up to a mesh-divisible node count with inert
        # padding fibers so user configs never hit the ring divisibility
        # ValueError (System._fiber_flow); re-homed onto the one bucket
        # policy module (`system.buckets.pad_for_mesh`) — each bucket pads
        # to a mesh-divisible node count, so the concatenated total divides
        from .system.buckets import pad_for_mesh

        fibers = pad_for_mesh(fibers, mesh.size)

    system = System(params, shell_shape=shape, mesh=mesh)
    state = system.make_state(
        fibers=fibers,
        points=build_point_sources(config.point_sources, dtype),
        background=build_background(config.background, dtype),
        shell=shell,
        bodies=build_bodies(
            config.bodies, config_dir, dtype,
            synthesize_precompute=synthesize_body_precompute))
    rng = SimRNG(seed=config.params.seed)
    return system, state, rng
