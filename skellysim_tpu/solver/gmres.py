"""Matrix-free right-preconditioned GMRES, jit-able and mesh-shardable.

Replaces the reference's Trilinos Belos PseudoBlockGmresSolMgr wrapper
(`/root/reference/src/core/solver_hydro.cpp:63-95`, `include/solver.hpp:10-49`)
with a pure-JAX implementation:

* right preconditioning (``A M^-1 (M x) = b``), matching
  `problem.setRightPrec(preconditioner_)` (`solver_hydro.cpp:66`)
* ICGS orthogonalization (two rounds of classical Gram-Schmidt), matching
  `belosList.set("Orthogonalization", "ICGS")` (`solver_hydro.cpp:72`)
* convergence on the implicit (Givens) residual relative to ||b||, matching
  Belos' relative convergence tolerance with the reference's zero initial guess
* fixed-size Krylov basis + `lax.while_loop` so the whole solve stays inside one
  XLA program; dot products are plain jnp reductions, so under pjit sharding the
  compiler inserts the psum collectives the reference got from Tpetra/MPI.

The solver runs entirely on device; the per-step "rebuild the Belos problem"
host round-trip of the reference (`system.cpp:467`) has no analogue here.

Batching semantics (the ensemble subsystem's contract, pinned by
`tests/test_ensemble.py::test_gmres_vmap_masked_convergence`): because all
control flow is `lax` primitives, `jax.vmap(gmres)` lifts to ONE batched
while_loop that runs until every member is done; members whose ``cond`` has
gone false get their carries select-masked (unchanged), so each member's
``x``/``iters``/``residual`` are exactly what its solo solve reports — a
converged member is never perturbed by a slower neighbor still iterating.
Values match the solo solve to roundoff (batched GEMM accumulation orders
differ at ~1 ulp); bit-exact members need the per-member program inlined
per lane (the ensemble runner's ``batch_impl="unroll"``).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..guard.verdict import (BREAKDOWN, NONFINITE, STAGNATION,
                             nonfinite_word)


class GmresResult(NamedTuple):
    x: jnp.ndarray          # solution
    iters: jnp.ndarray      # int32, total inner iterations
    residual: jnp.ndarray   # implicit (Givens) relative residual at exit
    converged: jnp.ndarray  # bool
    #: explicit relative residual ||b - A x|| / ||b|| from one extra matvec
    #: after exit — the reference's post-solve check (`solver_hydro.cpp:81-92`,
    #: `include/solver.hpp:38`). With restarts + a right preconditioner the
    #: implicit residual can drift from the true one; compare the two to
    #: detect loss of accuracy.
    residual_true: jnp.ndarray
    #: refinement sweeps taken (`gmres_ir` only; 0 for plain `gmres`).
    #: Each sweep costs one HIGH-precision residual matvec — the dominant
    #: per-sweep cost at scale on TPU, so tuning `inner_tol` is about this
    #: count as much as about total inner iterations. Plain int default (a
    #: jnp scalar here would initialize the JAX backend at import time —
    #: a hang when the TPU tunnel is wedged).
    refines: int | jnp.ndarray = 0
    #: int32, restart cycles taken (`gmres`: outer Arnoldi restart cycles;
    #: `gmres_ir`: refinement sweeps, == refines) — the skelly-scope
    #: `gmres_cycles` metric, and ALWAYS the number of rows written into
    #: ``history`` (the `history_rows` decode invariant)
    cycles: int | jnp.ndarray = 0
    #: optional [history, 3] device-side ring buffer of per-restart
    #: (cumulative iters, implicit residual, explicit residual) rows —
    #: `gmres(history=N)`. Written with pure `.at[].set` updates inside the
    #: solver loop (NO host callback: skelly-audit's host-sync contract
    #: stays empty), read out host-side via `history_rows`. None when
    #: disabled. `gmres_ir` records one row per refinement SWEEP
    #: (cumulative inner iters, the sweep's inner implicit exit residual,
    #: the f64 explicit residual after the update).
    history: jnp.ndarray | None = None
    #: int32 packed health word (`guard.verdict` bit layout: nonfinite /
    #: stagnation / breakdown), ORed together INSIDE the solver loops with
    #: `jnp.isfinite` + masked int ops — no host sync, so skelly-audit's
    #: host-sync contract stays empty and the word batches under `vmap`
    #: like every other carry. 0 = healthy. Plain int default for the same
    #: import-time reason as ``refines``.
    health: int | jnp.ndarray = 0


def _icgs(V, w, k, n_restart, rdot):
    """Two-pass classical Gram-Schmidt of w against V[:k+1] (rows are basis vectors).

    Uses a mask over the fixed-size basis so the loop stays shape-static.
    ``rdot(V, w)`` computes the batch of basis dot products — under the SPMD
    solver this is the one collective (a `psum`) per orthogonalization pass.
    """
    keep = jnp.arange(n_restart + 1, dtype=jnp.int32) <= k
    h = jnp.zeros(n_restart + 1, dtype=w.dtype)
    for _ in range(2):
        # select, not multiply: 0 * inf = NaN would poison the masked
        # rows if a dot overflowed (docs/audit.md "Masking discipline");
        # bitwise identical to the product for finite dots
        proj = jnp.where(keep, rdot(V, w), 0.0)   # [m+1] masked <v_i, w>
        w = w - proj @ V
        h = h + proj
    return w, h


def _reductions(rdot):
    """(rdot, norm) pair from an optional injected reduction.

    ``rdot(A, w)`` contracts the vector (solution-layout) axis: ``A @ w`` for
    the single-program solver; the SPMD solver (`parallel.spmd`) injects a
    partial-dot + `lax.psum` so GMRES runs unchanged on row-sharded Krylov
    vectors with explicit collectives. ``w`` may carry a trailing block axis
    (``[n, s]`` — the s-step cycle's batched Gram reduction rides the SAME
    seam: one psum of an ``[rows, s]`` block instead of ``s`` sequential
    ``[rows]`` reductions). The default path keeps `jnp.linalg.norm`
    bit-for-bit (golden trajectories pin it).

    Every reduction through this seam is REPLICATION-RESTORING under the
    SPMD layout: the sharded head rows contract into one psum (identical
    result on every shard) and the replicated tail contributes the same
    product everywhere — which is why the replication analyzer
    (`audit.repflow`, docs/parallel.md "Replication discipline") can prove
    the solver's while_loop predicates replicated and the mesh programs
    deadlock-free, for the sequential AND the s-step batched-Gram cycles.
    """
    if rdot is None:
        return (lambda A, w: A @ w), jnp.linalg.norm
    return rdot, lambda v: jnp.sqrt(rdot(v, v))


def _chol_ridge(S, scale):
    """Cholesky of the projected candidate Gram with a noise-floor ridge.

    ``S`` is the BCGS-projected Gram (raw Gram minus the projection outer
    product) — near convergence it collapses toward zero while its entries
    carry cancellation noise of order ``rows * eps * scale`` (``scale`` =
    the largest RAW candidate norm^2), which can push it indefinite. The
    ridge sits AT that noise floor, so the factorization stays finite and
    the perturbation it adds is below what the subtraction already lost.
    GMRES self-corrects the O(ridge) Hessenberg error through the
    explicit-residual restart (see `gmres.outer_cond`)."""
    s = S.shape[0]
    eps = jnp.asarray(jnp.finfo(S.dtype).eps, dtype=S.dtype)
    ridge = eps * jnp.maximum(scale, jnp.asarray(1.0, dtype=S.dtype))
    # select, not `ridge * eye` (0 * inf = NaN; see _icgs)
    diag = jnp.eye(s, dtype=bool)
    return jnp.linalg.cholesky(S + jnp.where(diag, ridge, 0.0))


@partial(jax.jit, static_argnames=("matvec", "precond", "restart", "maxiter",
                                   "debug", "rdot", "history", "block_s"))
def gmres(matvec: Callable, b: jnp.ndarray, *, precond: Callable | None = None,
          tol: float = 1e-10, restart: int = 100, maxiter: int = 1000,
          debug: bool = False, rdot: Callable | None = None,
          history: int = 0, block_s: int = 1) -> GmresResult:
    """Solve ``matvec(x) = b`` with right-preconditioned restarted GMRES.

    ``precond`` approximates A^-1 (applied on the right). Initial guess is zero,
    like the reference's freshly constructed solution vector each step.
    ``debug=True`` prints the residuals after each restart cycle (the
    analogue of Belos' per-iteration verbosity, `solver_hydro.cpp:73-83`).

    ``rdot`` optionally replaces the vector-axis contraction (``A @ w``) for
    every dot product and norm — the seam `parallel.spmd` uses to run this
    exact solver on row-sharded Krylov vectors inside `shard_map`, with one
    explicit `psum` per reduction instead of compiler-chosen all-gathers.

    Acceptance is on the explicit residual ``||b - A x|| / ||b||`` recomputed
    at every restart boundary (one extra matvec per cycle), so the returned
    ``converged``/``residual_true`` can never disagree the way Belos'
    implicit test can (`solver_hydro.cpp:85-92`).

    ``history=N`` (static) additionally carries an [N, 3] device-side ring
    buffer of per-restart (cumulative iters, implicit, explicit) residual
    rows through the outer loop — the skelly-scope convergence history
    (docs/observability.md). Pure masked ``.at[].set`` writes, so the loop
    stays free of host callbacks (audit's host-sync contract) and batches
    under `vmap` like every other carry; unwritten rows stay NaN. Read it
    out with `history_rows(result.history, result.cycles)`.

    ``block_s=s`` (static, default 1) switches the Arnoldi cycle to the
    communication-avoiding s-step form (`Params.gmres_block_s`,
    docs/parallel.md): each round generates ``s`` preconditioned Krylov
    candidates (monomial matvec powers) and orthogonalizes them in TWO
    batched ``[(m+1)+s, s]`` Gram reductions through ``rdot`` (BCGS +
    Cholesky-QR, then one CGS2 re-orthogonalization pass for f32-interior
    stability) instead of 3 reductions per iteration — under the SPMD
    solver that is 2 psum rounds per ``s`` iterations instead of ``3s``.
    ``block_s=1`` is the EXACT sequential path, bitwise identical to the
    pre-s-step solver (pinned by `tests/test_gmres.py`); the restart
    length rounds up to a multiple of ``s`` so every round is full.
    """
    if block_s < 1:
        raise ValueError(f"block_s must be >= 1, got {block_s}")
    n = b.shape[0]
    dtype = b.dtype
    m = min(restart, maxiter)
    if block_s > 1:
        # full rounds only: the cycle advances s columns at a time, so the
        # basis length must divide (overshoot past maxiter inside one cycle
        # is bounded by s-1 and the outer loop still stops on maxiter)
        m = -(-m // block_s) * block_s
    M = precond if precond is not None else (lambda v: v)
    rdot, _norm = _reductions(rdot)

    b_norm = _norm(b)
    # all-zero RHS -> solution zero, declare converged immediately
    safe_b_norm = jnp.where(b_norm > 0.0, b_norm, 1.0)
    tol_abs = tol * safe_b_norm

    def arnoldi_cycle(x0, r0):
        """One restart cycle from x0 with precomputed residual r0 = b - A x0;
        returns (x, implicit_resid, inner_iters, breakdown=False — only the
        s-step cycle has a Cholesky-ridge breakdown path)."""
        beta = _norm(r0)
        safe_beta = jnp.where(beta > 0.0, beta, 1.0)

        V0 = jnp.zeros((m + 1, n), dtype=dtype).at[0].set(r0 / safe_beta)
        H0 = jnp.zeros((m + 1, m), dtype=dtype)
        cs0 = jnp.zeros(m, dtype=dtype)
        sn0 = jnp.zeros(m, dtype=dtype)
        g0 = jnp.zeros(m + 1, dtype=dtype).at[0].set(beta)

        def cond(state):
            k, _, _, _, _, _, done = state
            return (k < m) & ~done

        def body(state):
            k, V, H, cs, sn, g, done = state
            # skelly-pulse phase scopes (obs/profile.py): metadata-only —
            # the compiled program, contracts, and baselines are unchanged
            with jax.named_scope("arnoldi"):
                w = matvec(M(V[k]))
            with jax.named_scope("gram"):
                w, h = _icgs(V, w, k, m, rdot)
                h_norm = _norm(w)
                h = h.at[k + 1].set(h_norm)
                V = V.at[k + 1].set(w / jnp.where(h_norm > 0.0, h_norm, 1.0))

            with jax.named_scope("givens"):
                # apply accumulated Givens rotations to the new column
                def rot(i, hcol):
                    hi, hip = hcol[i], hcol[i + 1]
                    return hcol.at[i].set(cs[i] * hi + sn[i] * hip).at[i + 1].set(-sn[i] * hi + cs[i] * hip)

                h = lax.fori_loop(0, k, rot, h)
                # new rotation to zero h[k+1]
                denom = jnp.sqrt(h[k] ** 2 + h[k + 1] ** 2)
                denom_safe = jnp.where(denom > 0.0, denom, 1.0)
                c_new = jnp.where(denom > 0.0, h[k] / denom_safe, 1.0)
                s_new = jnp.where(denom > 0.0, h[k + 1] / denom_safe, 0.0)
                h = h.at[k].set(denom).at[k + 1].set(0.0)
                cs = cs.at[k].set(c_new)
                sn = sn.at[k].set(s_new)
                g = g.at[k + 1].set(-s_new * g[k]).at[k].set(c_new * g[k])
                H = H.at[:, k].set(h)

            done = jnp.abs(g[k + 1]) <= tol_abs
            return k + 1, V, H, cs, sn, g, done

        k, V, H, cs, sn, g, done = lax.while_loop(
            cond, body, (jnp.int32(0), V0, H0, cs0, sn0, g0, beta <= tol_abs))

        # solve the k x k triangular system via masked back-substitution
        idx = jnp.arange(m, dtype=jnp.int32)
        active = idx < k

        def back_sub(i, y):
            j = m - 1 - i
            hjj = H[j, j]
            rhs = g[j] - jnp.dot(H[j, :], y)
            yj = jnp.where(active[j], rhs / jnp.where(hjj != 0.0, hjj, 1.0), 0.0)
            return y.at[j].set(yj)

        y = lax.fori_loop(0, m, back_sub, jnp.zeros(m, dtype=dtype))
        dx = M(y @ V[:m])
        resid = jnp.abs(g[jnp.minimum(k, m)]) / safe_b_norm
        return x0 + dx, resid, k, jnp.asarray(False)

    def arnoldi_cycle_block(x0, r0):
        """Communication-avoiding restart cycle (``block_s`` > 1).

        Each while-round extends the basis by ``s`` columns: generate the
        monomial candidates p_j = (A M)^j v_k, orthogonalize the block in
        ONE batched [(m+1)+s, s] Gram reduction (BCGS against the masked
        basis + Cholesky-QR among the candidates), re-orthogonalize once
        (CGS2) with a second batched reduction, then recover the s raw
        Hessenberg columns from the change-of-basis coefficients — pure
        replicated small-matrix work, no collectives. Under the SPMD rdot
        that is 2 psum rounds per s iterations instead of the sequential
        cycle's 3 per iteration.

        The Hessenberg recovery (Hoemmen-style): with C = <v_i, p_j> and
        upper-triangular R = coefficients of the new orthonormal rows q_u
        in p_j, the coefficient vector of p_t in the EXTENDED basis is
        e_t = C[:, t] + scatter(R[:, t] at rows k+1...). Then

            Hraw[:, k]   = e_0                          (A M v_k = p_1)
            Hraw[:, k+t] = (e_t - Hraw @ e_{t-1}|without-diag)
                           / e_{t-1}[k+t]               (t = 1..s-1)

        because A M q_{t-1} expands p_t's defining relation through the
        already-known raw columns. Givens rotations then triangularize each
        recovered column exactly as the sequential path does, so restart /
        convergence / back-substitution semantics are unchanged.
        """
        s = block_s
        beta = _norm(r0)
        safe_beta = jnp.where(beta > 0.0, beta, 1.0)

        V0 = jnp.zeros((m + 1, n), dtype=dtype).at[0].set(r0 / safe_beta)
        Hr0 = jnp.zeros((m + 1, m), dtype=dtype)   # raw Arnoldi columns
        H0 = jnp.zeros((m + 1, m), dtype=dtype)    # Givens-rotated columns
        cs0 = jnp.zeros(m, dtype=dtype)
        sn0 = jnp.zeros(m, dtype=dtype)
        g0 = jnp.zeros(m + 1, dtype=dtype).at[0].set(beta)
        eps = jnp.asarray(jnp.finfo(dtype).eps, dtype=dtype)
        rows = jnp.asarray(m + 1 + s, dtype=dtype)

        def cond(state):
            k, *rest = state
            return (k < m) & ~rest[-1]

        def body(state):
            k, V, Hr, H, cs, sn, g, brk, done = state

            # ---- s preconditioned matvec powers (one matvec per trip)
            def gen(j, P):
                prev = jnp.where(j == 0, V[k], P[jnp.maximum(j - 1, 0)])
                return P.at[j].set(matvec(M(prev)))

            with jax.named_scope("arnoldi"):
                P = lax.fori_loop(0, s, gen, jnp.zeros((s, n), dtype=dtype))

            with jax.named_scope("gram"):
                # ---- BCGS + Cholesky-QR: first batched Gram (collective 1)
                keep = jnp.arange(m + 1, dtype=jnp.int32) <= k
                # select, not multiply (0 * inf = NaN; see _icgs)
                Vm = jnp.where(keep[:, None], V, 0.0)
                G = rdot(jnp.concatenate([Vm, P], axis=0), P.T)
                C1, S1 = G[:m + 1], G[m + 1:]
                scale1 = rows * jnp.max(jnp.diagonal(S1))
                W = P - C1.T @ Vm
                L1 = _chol_ridge(S1 - C1.T @ C1, scale1)
                Q1 = jax.scipy.linalg.solve_triangular(L1, W, lower=True)

                # ---- CGS2 re-orthogonalization: second batched Gram
                # (collective 2)
                G2 = rdot(jnp.concatenate([Vm, Q1], axis=0), Q1.T)
                C2, S2 = G2[:m + 1], G2[m + 1:]
                W2 = Q1 - C2.T @ Vm
                L2 = _chol_ridge(S2 - C2.T @ C2,
                                 rows * jnp.max(jnp.diagonal(S2)))
                Q = jax.scipy.linalg.solve_triangular(L2, W2, lower=True)

                # effective change of basis over BOTH passes:
                #   p_j = C[:, j] . V  +  sum_u Rm[u, j] q_u
                C = C1 + C2 @ L1.T
                Rm = (L1 @ L2).T                # upper triangular [s, s]
                # a fully converged/dependent candidate block can still
                # leave NaN rows in Q (0/0 through the triangular solves);
                # those rows are never ACCEPTED (col_ok below) but they
                # must not poison V — a NaN row times a zero
                # back-substitution weight is NaN
                Q = jnp.where(jnp.isfinite(Q), Q, 0.0)
                V = lax.dynamic_update_slice(V, Q, (k + 1, jnp.int32(0)))
            # breakdown floor for the recovered subdiagonals: below the
            # projected Gram's noise floor the computed q direction is
            # cancellation noise, not a Krylov direction — end the cycle
            # (the outer loop's explicit residual decides what's next)
            tiny = jnp.sqrt(eps * scale1) + jnp.asarray(
                jnp.finfo(dtype).tiny, dtype=dtype)

            def ecol(t):
                base = lax.dynamic_update_slice(
                    jnp.zeros(m + 1, dtype=dtype), Rm[:, t], (k + 1,))
                return base + C[:, t]

            def givens_col(j, hcol, cs, sn, g):
                def rot(i, hc):
                    hi, hip = hc[i], hc[i + 1]
                    return (hc.at[i].set(cs[i] * hi + sn[i] * hip)
                            .at[i + 1].set(-sn[i] * hi + cs[i] * hip))

                hcol = lax.fori_loop(0, j, rot, hcol)
                hj, hjp = hcol[j], hcol[j + 1]
                denom = jnp.sqrt(hj ** 2 + hjp ** 2)
                denom_safe = jnp.where(denom > 0.0, denom, 1.0)
                c_new = jnp.where(denom > 0.0, hj / denom_safe, 1.0)
                s_new = jnp.where(denom > 0.0, hjp / denom_safe, 0.0)
                hcol = hcol.at[j].set(denom).at[j + 1].set(0.0)
                cs = cs.at[j].set(c_new)
                sn = sn.at[j].set(s_new)
                g = g.at[j + 1].set(-s_new * g[j]).at[j].set(c_new * g[j])
                return hcol, cs, sn, g

            accepted = jnp.int32(0)
            prev_e = jnp.zeros(m + 1, dtype=dtype)
            with jax.named_scope("givens"):
                for t in range(s):   # static: s is small, no collectives
                    j = k + t
                    e_t = ecol(t)
                    if t == 0:
                        hraw = e_t
                        rdiag = jnp.asarray(1.0, dtype=dtype)  # no division
                    else:
                        rdiag = prev_e[j]
                        coef = prev_e.at[j].set(0.0)[:m]
                        hraw = (e_t - Hr @ coef) / jnp.where(rdiag > tiny,
                                                             rdiag, 1.0)
                    col_ok = jnp.isfinite(hraw).all() & (rdiag > tiny)
                    acc = ~done & col_ok
                    # a rejected column while the cycle was still live is
                    # the Cholesky-ridge breakdown the health word reports
                    # (the outer loop's explicit residual decides whether
                    # the solve still converged; the BREAKDOWN bit survives
                    # either way)
                    brk = brk | (~done & ~col_ok)
                    hrot, cs_n, sn_n, g_n = givens_col(j, hraw, cs, sn, g)
                    Hr = jnp.where(acc, Hr.at[:, j].set(hraw), Hr)
                    H = jnp.where(acc, H.at[:, j].set(hrot), H)
                    cs = jnp.where(acc, cs_n, cs)
                    sn = jnp.where(acc, sn_n, sn)
                    g = jnp.where(acc, g_n, g)
                    accepted = accepted + acc.astype(jnp.int32)
                    done = done | (~done & ~col_ok) \
                        | (acc & (jnp.abs(g[j + 1]) <= tol_abs))
                    prev_e = e_t
            return k + accepted, V, Hr, H, cs, sn, g, brk, done

        k, V, Hr, H, cs, sn, g, brk, done = lax.while_loop(
            cond, body, (jnp.int32(0), V0, Hr0, H0, cs0, sn0, g0,
                         jnp.asarray(False), beta <= tol_abs))

        # identical masked back-substitution to the sequential cycle
        idx = jnp.arange(m, dtype=jnp.int32)
        active = idx < k

        def back_sub(i, y):
            j = m - 1 - i
            hjj = H[j, j]
            rhs = g[j] - jnp.dot(H[j, :], y)
            yj = jnp.where(active[j], rhs / jnp.where(hjj != 0.0, hjj, 1.0),
                           0.0)
            return y.at[j].set(yj)

        y = lax.fori_loop(0, m, back_sub, jnp.zeros(m, dtype=dtype))
        dx = M(y @ V[:m])
        resid = jnp.abs(g[jnp.minimum(k, m)]) / safe_b_norm
        return x0 + dx, resid, k, brk

    cycle = arnoldi_cycle if block_s == 1 else arnoldi_cycle_block

    def outer_cond(state):
        (x, r, resid_true, prev_true, resid_impl, total_iters, cycles,
         hist, health) = state
        del x, r, cycles, hist, health
        # acceptance on the EXPLICIT residual: with restarts + a right
        # preconditioner the implicit (Givens) residual drifts from the true
        # one, and Belos' loss-of-accuracy warning (`solver_hydro.cpp:85-92`)
        # fires after the fact. Restarting on ||b - A x|| (one extra matvec
        # per cycle) repairs any repairable drift. When the operator's own
        # noise floor sits above tol (pure-f32 stiff fiber rows) no restart
        # can help: exit once the inner loop converges implicitly but the
        # explicit residual stops improving (< 2x per cycle).
        stalled = (resid_impl <= tol) & (resid_true > 0.5 * prev_true)
        return (resid_true > tol) & (total_iters < maxiter) & ~stalled

    def outer_body(state):
        x, r, resid_true, _, _, total_iters, cycles, hist, health = state
        x, resid_impl, k, brk = cycle(x, r)
        r = b - matvec(x)
        prev_true = resid_true
        resid_true = _norm(r) / safe_b_norm
        # the health word (guard.verdict bit layout), built from values the
        # loop already carries — pure int/bool ops, no host sync, vmaps
        # like every other carry. The stall predicate here is EXACTLY what
        # outer_cond will exit on next trip, so the bit and the early exit
        # can never disagree.
        health = health | nonfinite_word(resid_true)
        health = health | jnp.where(brk, jnp.int32(BREAKDOWN), jnp.int32(0))
        stall_next = ((resid_impl <= tol) & (resid_true > 0.5 * prev_true)
                      & (resid_true > tol))
        health = health | jnp.where(stall_next, jnp.int32(STAGNATION),
                                    jnp.int32(0))
        if debug:
            jax.debug.print(
                "gmres restart {c}: iters={i} implicit={ri:.3e} "
                "explicit={re:.3e}",
                c=cycles + 1, i=total_iters + k, ri=resid_impl, re=resid_true)
        if history > 0:
            row = jnp.stack([(total_iters + k).astype(dtype), resid_impl,
                             resid_true])
            hist = hist.at[lax.rem(cycles, jnp.int32(history))].set(row)
        return (x, r, resid_true, prev_true, resid_impl, total_iters + k,
                cycles + 1, hist, health)

    x0 = jnp.zeros_like(b)
    init_resid = jnp.where(b_norm > 0.0, jnp.array(jnp.inf, dtype=dtype), jnp.array(0.0, dtype=dtype))
    hist0 = jnp.full((max(history, 0), 3), jnp.nan, dtype=dtype)
    # a nonfinite RHS short-circuits the loop through the b_norm guards
    # (NaN > 0.0 is False -> init_resid 0.0 -> zero trips, "converged"
    # with x = 0) — the exact silent-poisoning mode the health word
    # exists to surface, so stamp it at entry
    health0 = nonfinite_word(b_norm)
    (x, _, resid_true, _, resid_impl, iters, cycles, hist,
     health) = lax.while_loop(
        outer_cond, outer_body,
        (x0, b, init_resid, init_resid, init_resid, jnp.int32(0),
         jnp.int32(0), hist0, health0))
    # iteration budget exhausted without reaching tol = stagnation too
    # (the "burns the full restart budget with no escalation" mode)
    health = health | jnp.where((resid_true > tol) & (resid_impl > tol)
                                & (iters >= maxiter),
                                jnp.int32(STAGNATION), jnp.int32(0))
    # converged like Belos (either measure passed); residual_true lets the
    # caller's loss-of-accuracy gate flag implicit-only convergence
    return GmresResult(x=x, iters=iters, residual=resid_impl,
                       converged=(resid_true <= tol) | (resid_impl <= tol),
                       residual_true=resid_true, cycles=cycles,
                       history=hist if history > 0 else None,
                       health=health)


@partial(jax.jit, static_argnames=("matvec_hi", "matvec_lo", "precond_lo",
                                   "restart", "maxiter", "max_refine",
                                   "rdot", "history", "block_s"))
def gmres_ir(matvec_hi: Callable, matvec_lo: Callable, b: jnp.ndarray, *,
             precond_lo: Callable | None = None, tol: float = 1e-10,
             inner_tol: float = 1e-5, restart: int = 100, maxiter: int = 1000,
             max_refine: int = 8, rdot: Callable | None = None,
             history: int = 0, block_s: int = 1) -> GmresResult:
    """Mixed-precision GMRES with iterative refinement.

    The TPU-native answer to the reference's f64 accuracy gates (GMRES tol
    1e-10, `solver_hydro.cpp:71-78`; kernel agreement 5e-9,
    `tests/core/kernel_test.cpp:93`) on hardware whose `LuDecomposition` is
    f32-only and whose MXU prefers f32/bf16:

      * ``matvec_lo`` / ``precond_lo`` take and return ``b.dtype`` (f64)
        vectors but may evaluate their expensive interior — the O(N^2)
        kernel flows, the dense shell matmul, the batched LU solves — in
        f32 (see `System._apply_matvec(lo=...)`). Stiff small ops (the
        fiber 4nx4n blocks, whose rows reach ~1e7: f32 entry rounding
        injects O(1) absolute noise there) stay f64 — they are a vanishing
        fraction of the flops;
      * ``matvec_hi`` is the exact f64 operator — used once per refinement
        sweep for the true residual r = b - A x;
      * iterative refinement: solve A d = r with the cheap operator to
        ``inner_tol``, update x += d, repeat until the **explicit f64
        residual** meets ``tol``. Each sweep contracts the residual by
        ~max(inner_tol, operator noise), so 1e-10 takes 2-3 sweeps.

    Returns a `GmresResult` whose ``residual`` IS the explicit f64 relative
    residual (no implicit/explicit drift possible, unlike plain restarted
    GMRES). ``history=N`` records one ring-buffer row per refinement SWEEP
    — (cumulative inner iters, the sweep's inner implicit exit residual,
    the f64 explicit residual after the correction) — all in ``b.dtype``
    (no narrow->wide promotion edges: the inner solve's vectors already
    carry ``b.dtype``, only its interior is f32). ``block_s`` passes
    through to the inner Krylov solve (the s-step communication-avoiding
    cycle — see `gmres`); the refinement sweep structure is unchanged.
    """
    M = precond_lo if precond_lo is not None else (lambda v: v)
    _norm = _reductions(rdot)[1]
    b_norm = _norm(b)
    safe_b_norm = jnp.where(b_norm > 0.0, b_norm, 1.0)

    def cond(state):
        x, r, r_rel, outer, total, hist, health = state
        del x, r, hist, health
        return (r_rel > tol) & (outer < max_refine)

    def body(state):
        x, r, _, outer, total, hist, health = state
        d = gmres(matvec_lo, r, precond=M, tol=inner_tol,
                  restart=restart, maxiter=maxiter, rdot=rdot,
                  block_s=block_s)
        x = x + d.x
        # the HIGH-precision residual matvec is the refinement sweep's
        # dominant cost — scoped "refine" for device-time attribution
        # (obs/profile.py; metadata only, the program is unchanged)
        with jax.named_scope("refine"):
            r = b - matvec_hi(x)
            r_rel = _norm(r) / safe_b_norm
        # accumulate the inner solves' verdicts, plus a nonfinite check on
        # the f64 explicit residual (a poisoned correction shows up here
        # even when the f32 inner loop "converged"). The inner STAGNATION
        # bit is deliberately masked off: an f32 inner loop stalling at its
        # noise floor is the NORMAL mixed-precision exit (see the stall
        # note in `gmres.outer_cond`) — refinement-level stagnation is
        # judged on the f64 sweep contraction below, not the f32 interior.
        health = health | (jnp.asarray(d.health, dtype=jnp.int32)
                           & jnp.int32(~STAGNATION))
        health = health | nonfinite_word(r_rel)
        if history > 0:
            row = jnp.stack([(total + d.iters).astype(b.dtype), d.residual,
                             r_rel])
            hist = hist.at[lax.rem(outer, jnp.int32(history))].set(row)
        return x, r, r_rel, outer + 1, total + d.iters, hist, health

    x0 = jnp.zeros_like(b)
    init_rel = jnp.where(b_norm > 0.0, jnp.asarray(jnp.inf, dtype=b.dtype),
                         jnp.asarray(0.0, dtype=b.dtype))
    hist0 = jnp.full((max(history, 0), 3), jnp.nan, dtype=b.dtype)
    health0 = nonfinite_word(b_norm)
    x, _, r_rel, outers, iters, hist, health = lax.while_loop(
        cond, body, (x0, b, init_rel, jnp.int32(0), jnp.int32(0), hist0,
                     health0))
    # refinement budget exhausted above tol = stagnation (each sweep
    # should contract by ~inner_tol; when it doesn't, more sweeps won't
    # help — the escalation ladder's cue to change the program instead)
    health = health | jnp.where((r_rel > tol) & (outers >= max_refine),
                                jnp.int32(STAGNATION), jnp.int32(0))
    # `cycles` == ring rows written, for BOTH solvers (`history_rows`
    # decodes on that invariant): here each refinement sweep writes one row
    return GmresResult(x=x, iters=iters, residual=r_rel,
                       converged=r_rel <= tol, residual_true=r_rel,
                       refines=outers, cycles=outers,
                       history=hist if history > 0 else None,
                       health=health)


def collective_rounds(iters, cycles, block_s: int = 1,
                      restart: int | None = None) -> int:
    """Dot-product collective rounds one solve paid through the ``rdot``
    seam — the quantity the s-step cycle exists to shrink, surfaced as the
    run-loop metrics field ``collective_rounds`` and summed/meaned by
    `obs summarize` (docs/observability.md).

    Sequential (``block_s=1``): 3 reductions per inner iteration (two ICGS
    Gram passes + the new column's norm). s-step: 2 batched Gram reductions
    per round of ``s`` iterations. Both plus 2 per restart boundary (the
    entry-residual norm and the explicit-residual norm). For `gmres_ir`
    results ``cycles`` counts refinement SWEEPS, not the inner solver's
    restart cycles — pass ``restart`` (the caller's `Params.gmres_restart`)
    so boundaries are floored at ``ceil(iters / restart)`` and an inner
    restart blow-up still moves the metric. A (tight) lower bound, not an
    exact trace count; host-side bookkeeping only — never traced."""
    iters, cycles = int(iters), int(cycles)
    boundaries = cycles
    if restart:
        boundaries = max(boundaries, -(-iters // max(int(restart), 1)))
    if block_s <= 1:
        return 3 * iters + 2 * boundaries
    return 2 * (-(-iters // block_s)) + 2 * boundaries


def history_rows(history, cycles) -> list:
    """Chronological ``[iters, implicit, explicit]`` rows actually written
    into a convergence ring buffer — the host-side decode for the
    ``gmres_history`` metrics field (docs/observability.md).

    Handles ring wrap: with ``cycles > len(history)`` the buffer holds the
    LAST ``len(history)`` cycles, rotated so the oldest surviving row comes
    first. Host-only (called from the run loop / scheduler after the device
    fetch — never inside jitted code).
    """
    import numpy as np

    if history is None:
        return []
    h = np.asarray(history)
    c = int(cycles)
    cap = h.shape[0]
    if cap == 0 or c == 0:
        return []
    if c <= cap:
        rows = h[:c]
    else:
        start = c % cap
        rows = np.concatenate([h[start:], h[:start]], axis=0)
    return [[int(r[0]), float(r[1]), float(r[2])] for r in rows]


# ---------------------------------------------------------------- skelly-audit

def auditable_programs():
    """The solver layer's audit entry: a bare f32 GMRES solve on a dense
    well-conditioned operator. This is the program the mixed-precision path
    embeds as its Krylov inner loop — its contract pins that the f32 hot
    loop stays f32 (zero promotion edges: a single f64 constant here would
    promote every Arnoldi vector), collective-free, callback-free, and
    compiles once."""
    from ..audit.registry import AuditProgram, built_from

    def make_problem(n=64, seed=11):
        import jax.numpy as jnp
        import numpy as np

        rng = np.random.default_rng(seed)
        A = jnp.asarray(np.eye(n) + 0.1 * rng.standard_normal((n, n)),
                        dtype=jnp.float32)
        b = jnp.asarray(rng.standard_normal(n), dtype=jnp.float32)
        return A, b

    def solve(A, b):
        return gmres(lambda x: A @ x, b, tol=1e-4, restart=32, maxiter=64)

    def build():
        import jax

        A, b = make_problem()
        return built_from(jax.jit(solve), A, b)

    def retrace_probe():
        from ..testing import trace_counting_jit

        A, b = make_problem()
        step = trace_counting_jit(solve)
        step(A, b)
        step(A, b + 1.0)  # same shapes/dtypes: must not retrace
        return step.trace_count

    return [AuditProgram(
        name="gmres_f32", layer="solver",
        summary="bare f32 GMRES on a dense 64x64 operator (the mixed "
                "path's Krylov inner loop)",
        build=build, retrace_probe=retrace_probe)]
