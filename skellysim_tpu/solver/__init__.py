from .gmres import gmres, GmresResult  # noqa: F401
