from .gmres import gmres, gmres_ir, GmresResult  # noqa: F401
