"""Shared simulation fixtures for tests, benchmarks, and the driver dry-run.

Counterpart of the reference's pytest helpers (`src/skelly_sim/testing.py:18-33`),
adapted to the in-memory build path: one place that assembles the standard
coupled scene (spherical periphery + one externally forced rigid body) so the
dry-run, the ring-vs-direct tests, and the bench all measure the *same* system.

The shell uses uniform quadrature weights (4*pi*R^2/N on Fibonacci nodes)
rather than the production Reeger-Fornberg weights — fixture-grade accuracy,
identical solver structure and flop profile.
"""

from __future__ import annotations

import functools

import numpy as np


class TraceCountingJit:
    """`jax.jit` wrapper that counts how many times the function is TRACED.

    The runtime companion to skelly-lint's static pass (docs/lint.md): a
    retrace means some argument changed its static signature — a Python
    scalar where a jnp scalar belongs, a dtype flip, a shape change — and
    every retrace pays full compilation on the hot path. Tests pin the
    expected count (`tests/test_retrace.py`: the top-level system step must
    trace exactly once across same-shape calls).

    >>> step = trace_counting_jit(system._solve_impl,
    ...                           static_argnames=("ewald_plan",))
    >>> step(state); step(state2)       # same shapes/dtypes
    >>> assert step.trace_count == 1
    """

    def __init__(self, fn, **jit_kwargs):
        import jax

        self._count = 0

        @functools.wraps(fn)
        def counting(*args, **kwargs):
            self._count += 1
            return fn(*args, **kwargs)

        self._jitted = jax.jit(counting, **jit_kwargs)

    def __call__(self, *args, **kwargs):
        return self._jitted(*args, **kwargs)

    @property
    def trace_count(self) -> int:
        return self._count


def trace_counting_jit(fn, **jit_kwargs) -> TraceCountingJit:
    """Wrap ``fn`` in `jax.jit` (kwargs pass through) counting traces via
    ``.trace_count``. Imports jax lazily so importing `skellysim_tpu.testing`
    never initializes a backend."""
    return TraceCountingJit(fn, **jit_kwargs)


def make_coupled_parts(shell_n: int, body_n: int, dtype, *, radius: float = 6.0,
                       body_position=(0.0, 0.0, -2.0),
                       body_force=(0.0, 0.0, 0.5), operator_builder=None):
    """(shell_state, shell_shape, body_group) for the standard coupled scene.

    ``operator_builder(nodes, normals, weights) -> (operator, M_inv)`` defaults
    to the host-side `periphery.build_shell_operator`; pass a device builder to
    assemble/invert the dense operator on an accelerator.
    """
    from .bodies import bodies as bd
    from .periphery import periphery as peri
    from .periphery.precompute import precompute_body
    from .periphery.shapes import sphere_shape

    spec = sphere_shape(shell_n, radius=radius * 1.04)
    normals = -spec.node_normals  # periphery normals point inward
    weights = np.full(shell_n, 4 * np.pi * (radius * 1.04) ** 2 / shell_n)
    build = operator_builder or peri.build_shell_operator
    op, M_inv = build(spec.nodes, normals, weights)
    shell = peri.make_state(spec.nodes, normals, weights, op, M_inv,
                            dtype=dtype)
    shape = peri.PeripheryShape(kind="sphere", radius=radius)

    pre = precompute_body("sphere", body_n, radius=0.5)
    bodies = bd.make_group(
        pre["node_positions_ref"], pre["node_normals_ref"], pre["node_weights"],
        position=np.asarray([body_position], dtype=float),
        external_force=np.asarray([body_force], dtype=float),
        radius=np.array([0.5]), kind="sphere", dtype=dtype)
    return shell, shape, bodies
