"""The chaos smoke: boot a real server, break it on purpose, assert recovery.

`python -m skellysim_tpu.guard.smoke WORKDIR` — exit-code gated in
`ci/run_ci.sh` (docs/robustness.md). Two acts over one spawned serve
subprocess (jax-free parent, like the serve smoke):

1. **Quarantine**: two tenants in one capacity bucket; a `chaos`
   request NaN-poisons tenant A's lane. A must answer ``status=failed``
   with a nonzero nonfinite verdict while B streams to completion.
2. **Crash recovery**: submit a longer-running tenant, SIGKILL the server
   mid-flight, restart it on the same config + journal. The restarted
   server must re-admit the live tenant from the write-ahead journal and
   drive it to completion; the failed tenant's terminal record must
   survive too.

~40 s wall, dominated by the two warmup compiles (the journal recovery
REQUIRES a second cold server — that is the point).
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np


def _scene(shift: float):
    from ..config import BackgroundSource, Config, Fiber

    cfg = Config()
    cfg.params.dt_initial = cfg.params.dt_write = 0.005
    cfg.params.t_final = 0.02
    cfg.params.gmres_tol = 1e-10
    cfg.params.adaptive_timestep_flag = False
    # skelly-flight armed: the quarantine assertion below must come WITH
    # anomaly provenance naming the poisoned fiber (docs/observability.md
    # "Flight recorder"); tenants share the server's params contract, so
    # every scene carries the same window
    cfg.params.flight_window = 16
    fib = Fiber(n_nodes=8, length=1.0, bending_rigidity=0.01)
    fib.fill_node_positions(np.array([shift, 0.0, 0.0]),
                            np.array([0.0, 0.0, 1.0]))
    cfg.fibers = [fib]
    cfg.background = BackgroundSource(uniform=[1.0, 0.0, 0.0])
    return cfg


def main(workdir: str) -> int:
    from ..config import schema
    from ..config.toml_io import dumps as toml_dumps
    from ..serve.client import SpawnedServer
    from .verdict import NONFINITE

    def toml_of(cfg):
        return toml_dumps(schema.unpack(cfg))

    path = os.path.join(workdir, "chaos_config.toml")
    journal = os.path.join(workdir, "chaos_journal.bin")
    _scene(0.0).save(path)
    with open(path, "a") as fh:
        fh.write('\n[serve]\nmax_lanes = 2\nbatch_impl = "unroll"\n'
                 'chaos_enabled = true\njournal_every = 2\n'
                 f'journal_path = "{journal}"\n')

    # both servers share one persistent XLA cache: the RESTARTED server's
    # warmup then reuses the first boot's compile (the --jax-cache
    # pattern every CLI shares) — recovery latency, not compile latency
    cache = ["--jax-cache", os.path.join(workdir, ".jax_cache")]

    # ---- act 1: NaN quarantine, sibling survives. Tenants are seated at
    # submit time (free lanes); the horizons are long enough (20 rounds)
    # that the chaos request lands while A is still running.
    trace = os.path.join(workdir, "chaos_trace.jsonl")
    srv = SpawnedServer(path, args=cache + ["--trace-file", trace])
    with srv.client() as c:
        ta = c.submit(toml_of(_scene(0.1)), t_final=0.1)["tenant"]
        tb = c.submit(toml_of(_scene(0.3)), t_final=0.1)["tenant"]
        c.chaos("nan_lane", tenant=ta)
        sa = c.wait(ta, timeout=120)
        sb = c.wait(tb, timeout=120)
        assert sa["status"] == "failed", sa
        assert sa["health"] & NONFINITE, sa
        assert sa["verdict"], sa
        # skelly-flight provenance: the failed status must NAME the
        # poisoned lane's offender — poison_lane NaNs every fiber
        # position, so the first offender is fiber 0 of the fiber_x field
        prov = (sa.get("flight") or {}).get("provenance")
        assert prov, sa.get("flight")
        assert prov["field"] == "fiber_x", prov
        assert prov["fiber"] == 0, prov
        assert (sa["flight"]["tail"]
                and sa["flight"]["tail"][-1]["health"] & NONFINITE), \
            sa["flight"]
        assert sb["status"] == "finished", sb
        assert sb["health"] == 0, sb
        frames_b = c.stream(tb)["frames"]
        assert len(frames_b) >= 2, len(frames_b)
        stats = c.stats()
        assert stats["faults"].get("chaos_nan") == 1, stats["faults"]
        assert stats["faults"].get("lane_failed") == 1, stats["faults"]
        # fault localization counters (/stats): the offender FIELD
        assert stats["fault_fields"].get("fiber_x") == 1, \
            stats["fault_fields"]
        print(f"chaos smoke act 1 ok: {ta} failed "
              f"(verdict {sa['verdict']}, offender {prov['field']} fiber "
              f"{prov['fiber']}), {tb} finished with "
              f"{len(frames_b)} frames")

        # the blast-radius CLI over the server's own telemetry stream
        # must localize the same fault (jax-free parse, flushed per
        # event — readable while the server is live)
        from ..obs.flight import render_flight_report

        report = render_flight_report([trace])
        assert f"{ta}: FAULT" in report, report
        assert "field=fiber_x fiber 0" in report, report
        print("chaos smoke: obs flight report localizes the fault "
              f"({ta}: fiber_x fiber 0)")

        # ---- act 2: SIGKILL mid-flight, journal recovery
        tc = c.submit(toml_of(_scene(0.5)), t_final=0.5)["tenant"]
        # let it run a couple of rounds (journal checkpoints every 2)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = c.status(tc)
            if st["status"] == "running" and st["steps"] >= 2:
                break
            time.sleep(0.05)
        assert st["status"] == "running", st
        # SIGKILL while the client is still CONNECTED: a graceful
        # disconnect would evict tc (by design) — the crash must beat it
        srv.kill()
    print(f"chaos smoke: server SIGKILLed with {tc} at t={st['t']:g}")

    srv2 = SpawnedServer(path, args=cache)
    try:
        with srv2.client() as c:
            # live tenant re-admitted from the journal...
            sc = c.wait(tc, timeout=120)
            assert sc["status"] == "finished", sc
            assert abs(sc["t"] - 0.5) < 1e-9, sc
            # ...and the failed/finished records survived the crash —
            # including the failed tenant's journaled blast radius (the
            # provenance must outlive the server that observed it)
            sa2 = c.status(ta)
            assert sa2["status"] == "failed", sa2
            prov2 = (sa2.get("flight") or {}).get("provenance")
            assert prov2 and prov2["field"] == "fiber_x" \
                and prov2["fiber"] == 0, sa2.get("flight")
            assert c.status(tb)["status"] == "finished", c.status(tb)
            stats = c.stats()
            assert stats["journal"], stats
        rc = srv2.stop()
        assert rc == 0, f"restarted server exited rc={rc}"
    finally:
        if srv2._proc.poll() is None:
            srv2._proc.kill()
    print(f"chaos smoke act 2 ok: {tc} recovered from journal and "
          f"finished after SIGKILL; terminal records intact")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1]))
