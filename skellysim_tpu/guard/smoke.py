"""The chaos smoke: boot a real server, break it on purpose, assert recovery.

`python -m skellysim_tpu.guard.smoke WORKDIR` — exit-code gated in
`ci/run_ci.sh` (docs/robustness.md). Two acts over one spawned serve
subprocess (jax-free parent, like the serve smoke):

1. **Quarantine**: two tenants in one capacity bucket; a `chaos`
   request NaN-poisons tenant A's lane. A must answer ``status=failed``
   with a nonzero nonfinite verdict while B streams to completion.
2. **Crash recovery**: submit a longer-running tenant, SIGKILL the server
   mid-flight, restart it on the same config + journal. The restarted
   server must re-admit the live tenant from the write-ahead journal and
   drive it to completion; the failed tenant's terminal record must
   survive too.

~40 s wall, dominated by the two warmup compiles (the journal recovery
REQUIRES a second cold server — that is the point).
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np


def _scene(shift: float):
    from ..config import BackgroundSource, Config, Fiber

    cfg = Config()
    cfg.params.dt_initial = cfg.params.dt_write = 0.005
    cfg.params.t_final = 0.02
    cfg.params.gmres_tol = 1e-10
    cfg.params.adaptive_timestep_flag = False
    fib = Fiber(n_nodes=8, length=1.0, bending_rigidity=0.01)
    fib.fill_node_positions(np.array([shift, 0.0, 0.0]),
                            np.array([0.0, 0.0, 1.0]))
    cfg.fibers = [fib]
    cfg.background = BackgroundSource(uniform=[1.0, 0.0, 0.0])
    return cfg


def main(workdir: str) -> int:
    from ..config import schema
    from ..config.toml_io import dumps as toml_dumps
    from ..serve.client import SpawnedServer
    from .verdict import NONFINITE

    def toml_of(cfg):
        return toml_dumps(schema.unpack(cfg))

    path = os.path.join(workdir, "chaos_config.toml")
    journal = os.path.join(workdir, "chaos_journal.bin")
    _scene(0.0).save(path)
    with open(path, "a") as fh:
        fh.write('\n[serve]\nmax_lanes = 2\nbatch_impl = "unroll"\n'
                 'chaos_enabled = true\njournal_every = 2\n'
                 f'journal_path = "{journal}"\n')

    # both servers share one persistent XLA cache: the RESTARTED server's
    # warmup then reuses the first boot's compile (the --jax-cache
    # pattern every CLI shares) — recovery latency, not compile latency
    cache = ["--jax-cache", os.path.join(workdir, ".jax_cache")]

    # ---- act 1: NaN quarantine, sibling survives. Tenants are seated at
    # submit time (free lanes); the horizons are long enough (20 rounds)
    # that the chaos request lands while A is still running.
    srv = SpawnedServer(path, args=cache)
    with srv.client() as c:
        ta = c.submit(toml_of(_scene(0.1)), t_final=0.1)["tenant"]
        tb = c.submit(toml_of(_scene(0.3)), t_final=0.1)["tenant"]
        c.chaos("nan_lane", tenant=ta)
        sa = c.wait(ta, timeout=120)
        sb = c.wait(tb, timeout=120)
        assert sa["status"] == "failed", sa
        assert sa["health"] & NONFINITE, sa
        assert sa["verdict"], sa
        assert sb["status"] == "finished", sb
        assert sb["health"] == 0, sb
        frames_b = c.stream(tb)["frames"]
        assert len(frames_b) >= 2, len(frames_b)
        stats = c.stats()
        assert stats["faults"].get("chaos_nan") == 1, stats["faults"]
        assert stats["faults"].get("lane_failed") == 1, stats["faults"]
        print(f"chaos smoke act 1 ok: {ta} failed "
              f"(verdict {sa['verdict']}), {tb} finished with "
              f"{len(frames_b)} frames")

        # ---- act 2: SIGKILL mid-flight, journal recovery
        tc = c.submit(toml_of(_scene(0.5)), t_final=0.5)["tenant"]
        # let it run a couple of rounds (journal checkpoints every 2)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = c.status(tc)
            if st["status"] == "running" and st["steps"] >= 2:
                break
            time.sleep(0.05)
        assert st["status"] == "running", st
        # SIGKILL while the client is still CONNECTED: a graceful
        # disconnect would evict tc (by design) — the crash must beat it
        srv.kill()
    print(f"chaos smoke: server SIGKILLed with {tc} at t={st['t']:g}")

    srv2 = SpawnedServer(path, args=cache)
    try:
        with srv2.client() as c:
            # live tenant re-admitted from the journal...
            sc = c.wait(tc, timeout=120)
            assert sc["status"] == "finished", sc
            assert abs(sc["t"] - 0.5) < 1e-9, sc
            # ...and the failed/finished records survived the crash
            assert c.status(ta)["status"] == "failed", c.status(ta)
            assert c.status(tb)["status"] == "finished", c.status(tb)
            stats = c.stats()
            assert stats["journal"], stats
        rc = srv2.stop()
        assert rc == 0, f"restarted server exited rc={rc}"
    finally:
        if srv2._proc.poll() is None:
            srv2._proc.kill()
    print(f"chaos smoke act 2 ok: {tc} recovered from journal and "
          f"finished after SIGKILL; terminal records intact")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1]))
