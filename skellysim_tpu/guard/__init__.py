"""skelly-guard: device-side solver health verdicts, escalation, quarantine.

The robustness layer (docs/robustness.md). The reference SkellySim aborts
the whole MPI job when a fiber solve loses accuracy or GMRES stalls
(`solver_hydro.cpp:85-92` warns, the run loop raises); a long-lived
multi-tenant service (skelly-serve) needs the opposite: one tenant's
divergence must never take down the batch, and a killed server must come
back with every tenant intact. Four legs:

* `guard.verdict` — the packed per-member health word computed INSIDE the
  solver loops (`jnp.isfinite` + masked reductions, no host sync: audit's
  host-sync contract stays empty) and threaded through
  `GmresResult.health` -> `StepInfo.health` -> `EnsembleStepInfo.health`;
* `guard.escalate` — the bounded device-side retry ladder
  (`Params.guard_*`): halve dt, fall back `gmres_block_s -> 1`, route the
  Krylov interior through the full-f64 dense path, before a member is
  declared failed. One implementation serves sequential `System.run` and
  the vmapped ensemble (the ladder stages are max-one-trip `while_loop`s,
  so a healthy batch pays nothing);
* quarantine — the ensemble scheduler retires lanes with terminal
  verdicts as ``failed`` (masked inert, siblings bitwise-unaffected) and
  skelly-serve surfaces ``status="failed"`` with the decoded verdict plus
  a crash-safe write-ahead tenant journal (`serve.journal`);
* `guard.chaos` — fault injectors (NaN a lane, zero a preconditioner,
  garble wire frames, SIGKILL the server) driving the test suite and the
  `ci/run_ci.sh` chaos smoke.
"""

from .verdict import (BREAKDOWN, DT_UNDERFLOW, HEALTH_BITS, HEALTH_OK,
                      NONFINITE, STAGNATION, decode, is_terminal,
                      retryable)

__all__ = [
    "HEALTH_OK", "NONFINITE", "STAGNATION", "BREAKDOWN", "DT_UNDERFLOW",
    "HEALTH_BITS", "decode", "is_terminal", "retryable",
]
