"""The device-side escalation ladder: retry a bad trial before failing it.

`escalate(system, state, first_attempt)` wraps one already-computed trial
solve with up to three bounded retry stages, all INSIDE the traced program
(`System._solve_impl` calls it below every jit/vmap entry point, so
sequential `System.run` and the vmapped ensemble share this one
implementation — the batching note in `solver/gmres.py` applies: a
vmapped bounded `while_loop` select-masks members whose predicate went
false, so one stalling member retries without perturbing its healthy
siblings, and a fully healthy batch takes ZERO trips through any stage).

Ladder order (`Params.guard_*`, docs/robustness.md):

1. **dt halvings** (``guard_dt_halvings`` > 0) — re-solve at dt/2, dt/4,
   ... — the cheapest lever: most stagnations are a too-ambitious step on
   a stiffening configuration. Floored at ``dt_min`` under the adaptive
   gate (below it the verdict escalates to the host's underflow path).
2. **block fallback** (``guard_block_fallback``) — re-solve with
   ``gmres_block_s=1``: the s-step monomial basis trades conditioning for
   fewer collectives; its Cholesky-ridge breakdowns resolve on the exact
   sequential cycle.
3. **f64 dense fallback** (``guard_f64_fallback``) — re-solve with
   ``force_full=True``: the mixed path's f32 Krylov interior is replaced
   by the full-precision operator (the `pair=None` role-gated dense
   path), the last resort when the f32 noise floor IS the stall.

Only RETRYABLE verdicts (stagnation/breakdown — `verdict.retryable`)
enter the ladder: a nonfinite state is poisoned beyond any dt, and
dt_underflow is the host ladder's terminal signal. Each stage is a
max-N-trip `lax.while_loop` rather than a `lax.cond` so that under `vmap`
a batch with no bad member skips the stage entirely (batched `cond`
lowers to select-of-both-branches — it would re-solve EVERY member EVERY
step).

Cost note: every enabled stage traces one extra copy of the solve into
the program (compile time and code size scale with enabled stages).
That is the price of host-sync-free escalation; the stages default off.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from . import verdict


def _select(pred, new_tree, old_tree):
    """Scalar-predicate select over every leaf of (state, x, info)."""
    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(pred, a, b), new_tree, old_tree)


def _normalize(out, state, *, dt_used, retries):
    """Fix the StepInfo leaf dtypes so every ladder stage's output carries
    one pytree signature (the mixed and full solve paths return python-int
    `refines`/`cycles` vs traced ones; `while_loop`/`where` need them
    uniform)."""
    new_state, x, info = out
    info = info._replace(
        converged=jnp.asarray(info.converged, dtype=bool),
        iters=jnp.asarray(info.iters, dtype=jnp.int32),
        loss_of_accuracy=jnp.asarray(info.loss_of_accuracy, dtype=bool),
        refines=jnp.asarray(info.refines, dtype=jnp.int32),
        cycles=jnp.asarray(info.cycles, dtype=jnp.int32),
        health=jnp.asarray(info.health, dtype=jnp.int32),
        dt_used=jnp.asarray(dt_used, dtype=state.dt.dtype),
        guard_retries=jnp.asarray(retries, dtype=jnp.int32))
    return new_state, x, info


def escalate(system, state, first, *, pair=None, pair_anchors=None):
    """(new_state, x, info) after running the enabled ladder stages on the
    already-computed ``first`` attempt. ``state`` is the trial's INPUT
    state (the retry base); the returned ``info.dt_used`` is the dt that
    actually advanced, ``info.guard_retries`` the retries paid."""
    p = system.params
    out = _normalize(first, state, dt_used=state.dt, retries=0)

    def needs_retry(info):
        """Retry only what is BOTH retryable and not actually solved: a
        BREAKDOWN bit can ride a solve whose restart still converged (the
        outer loop's explicit residual repaired it — `solver/gmres.py`
        sets the bit 'either way'), and re-solving those would pay extra
        full solves and perturb dt on healthy steps. The explicit
        residual, not `converged`, is the gate: the implicit-converged/
        explicit-stuck stall (loss-of-accuracy) reports converged=True
        and is exactly what the ladder exists to escalate."""
        return (verdict.retryable(info.health)
                & (info.residual_true > p.gmres_tol))

    def resolve(dt_trial, retries, **overrides):
        trial = state._replace(dt=dt_trial.astype(state.dt.dtype))
        attempt = system._solve_once(trial, pair=pair,
                                     pair_anchors=pair_anchors, **overrides)
        return _normalize(attempt, state, dt_used=dt_trial, retries=retries)

    # ---- stage 1: dt halvings (dynamic — one bounded while_loop)
    if p.guard_dt_halvings > 0:
        max_h = p.guard_dt_halvings  # static python int (Params is hashable)

        def h_cond(carry):
            tries, cur = carry
            dt64 = cur[2].dt_used.astype(jnp.float64)
            floor_ok = ((dt64 * 0.5 >= p.dt_min)
                        if p.adaptive_timestep_flag else True)
            return (tries < max_h) & needs_retry(cur[2]) & floor_ok

        def h_body(carry):
            tries, cur = carry
            dt_half = cur[2].dt_used.astype(jnp.float64) * 0.5
            return tries + 1, resolve(dt_half, cur[2].guard_retries + 1)

        _, out = lax.while_loop(h_cond, h_body, (jnp.int32(0), out))

    def one_shot(stage_fn):
        """Run ``stage_fn`` at most once, only while the verdict is still
        retryable — spelled as a 1-trip while_loop so a healthy (batch of)
        member(s) skips the extra solve entirely under vmap (see module
        docstring)."""
        def cond(carry):
            tried, cur = carry
            return ~tried & needs_retry(cur[2])

        def body(carry):
            _, cur = carry
            return jnp.asarray(True), stage_fn(cur)

        _, res = lax.while_loop(cond, body, (jnp.asarray(False), out))
        return res

    # ---- stage 2: s-step -> sequential Arnoldi cycle
    if p.guard_block_fallback and p.gmres_block_s > 1:
        out = one_shot(lambda cur: resolve(
            cur[2].dt_used.astype(jnp.float64), cur[2].guard_retries + 1,
            block_s=1))

    # ---- stage 3: full-precision f64 dense re-solve
    if p.guard_f64_fallback and system._precision_for(state) == "mixed":
        out = one_shot(lambda cur: resolve(
            cur[2].dt_used.astype(jnp.float64), cur[2].guard_retries + 1,
            block_s=1 if p.guard_block_fallback else None,
            force_full=True))

    return out
