"""Fault injectors: deliberately break things the guard must survive.

Drives the fault-injection tests (tests/test_guard.py, tests/test_serve.py,
tests/test_ensemble.py) and the `ci/run_ci.sh` chaos smoke
(`python -m skellysim_tpu.guard.smoke`). Four injector families, matching
the failure modes docs/robustness.md enumerates:

* `poison_state` / `poison_lane` — flip NaNs into a (lane's) state between
  rounds: the silent-ensemble-poisoning mode the quarantine exists for;
* `zero_preconditioner` — force GMRES stagnation by nulling the
  preconditioner on a live `System` (the implicit residual collapses via
  degenerate Givens rotations while the explicit one never moves — the
  exact implicit/explicit divergence Belos warns about);
* `garble_frame` / `truncate_frame` / `oversized_header` — wire-level
  client-frame corruption for the protocol robustness tests;
* `SIGKILL` — via `serve.client.SpawnedServer.kill()`; the journal
  recovery tests own that path.

Injectors are ordinary host-side functions; none are imported by
production code paths (the serve `chaos` request imports lazily and is
config-gated off by default).
"""

from __future__ import annotations

import jax.numpy as jnp


def poison_state(state, *, value=float("nan")):
    """``state`` with every floating leaf of its FIBER positions set to
    ``value`` — the canonical poisoned-member injection. Shapes/dtypes are
    untouched, so the poisoned state still rides the same compiled
    program (`ensemble.runner.set_lane` accepts it)."""
    from ..fibers import container as fc

    def poison(g):
        return g._replace(x=jnp.full_like(g.x, value))

    buckets = tuple(poison(g) for g in fc.as_buckets(state.fibers))
    fibers = (buckets[0] if isinstance(state.fibers, fc.FiberGroup)
              else buckets)
    return state._replace(fibers=fibers)


def poison_lane(ens, lane: int, *, value=float("nan")):
    """An `EnsembleState` with lane ``lane``'s member state poisoned
    (between-rounds injection: assign the result back to
    ``scheduler.ens``). Sibling lanes' leaves are returned PHYSICALLY
    unchanged up to the one-lane `.at[].set` — the NaN-isolation pin
    asserts their trajectories stay bitwise identical."""
    from ..ensemble.runner import lane_state, set_lane

    poisoned = poison_state(lane_state(ens.states, lane), value=value)
    return ens._replace(states=set_lane(ens.states, lane, poisoned))


def zero_preconditioner(system):
    """Patch ``system`` (in place; returns it) so every preconditioner
    application is zero — the stagnation injector. GMRES's Krylov updates
    become A·0 = 0: the Givens recurrence zeroes the implicit residual
    while x never moves, so the solve exits through the stall path with a
    STAGNATION verdict (`guard.verdict`).

    Patch BEFORE the system's first solve: `observed_jit` caches compiled
    programs per call signature, so a system that already solved keeps
    its healthy compilation for identical shapes.
    """
    orig = system._apply_precond

    def zeroed(state, caches, body_caches, v, **kw):
        return jnp.zeros_like(orig(state, caches, body_caches, v, **kw))

    system._apply_precond = zeroed
    return system


# ------------------------------------------------------------ wire chaos

def garble_frame(payload: bytes, *, seed: int = 0, flips: int = 16) -> bytes:
    """``payload`` with ``flips`` deterministic byte flips — still a
    well-FRAMED message, no longer valid msgpack (the server must answer
    a structured error, not die)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    buf = bytearray(payload)
    for _ in range(max(1, flips)):
        i = int(rng.integers(0, len(buf)))
        buf[i] ^= 0xFF
    return bytes(buf)


def truncate_frame(framed: bytes, keep: int) -> bytes:
    """The first ``keep`` bytes of a framed (header + payload) message —
    a mid-frame disconnect / partial delivery."""
    return framed[:keep]


def oversized_header(size: int) -> bytes:
    """A frame header claiming ``size`` bytes (no body) — the hostile /
    corrupt header the decoder must survive via skip mode."""
    from ..serve import protocol

    return protocol.HEADER.pack(size)


def nan_lane_of(scheduler, member_id: str, *, value=float("nan")) -> int:
    """Poison the lane currently running ``member_id`` on a live
    scheduler; returns the lane index. The serve `chaos` request's
    implementation."""
    lane = scheduler.lane_of(member_id)
    if lane is None:
        raise ValueError(f"member {member_id!r} holds no lane")
    scheduler.ens = poison_lane(scheduler.ens, lane, value=value)
    return lane
