"""The packed solver health word: bit layout + device/host helpers.

One int32 word per member, computed inside the jitted solver loops and
carried next to `loss_of_accuracy` through every step-info surface
(`solver.gmres.GmresResult.health`, `system.StepInfo.health`,
`ensemble.runner.EnsembleStepInfo.health`). Bits are ORed as conditions
are observed within one solve attempt; a guard-ladder retry
(`guard.escalate`) REPLACES the word with the retried attempt's — so
``health == 0`` always means "the step that advanced was healthy", and
``StepInfo.guard_retries`` records that escalation happened. ``0`` is a
healthy solve.

Import discipline: jax-free at module import (the bit constants and
`decode` serve jax-free surfaces — the serve client, `obs summarize`);
the two device-side predicates import jax.numpy lazily.

Bit layout (docs/robustness.md):

======  ============  =====================================================
bit     name          set when
======  ============  =====================================================
0x1     nonfinite     NaN/Inf in the RHS, the explicit residual, or the
                      post-advance fiber error — the poisoned-lane signal
0x2     stagnation    the solve exited without reaching tol: the explicit
                      residual stopped improving across a restart (< 2x
                      per cycle with the implicit test converged) or the
                      iteration/refinement budget ran out
0x4     breakdown     the s-step cycle's Cholesky-ridge column recovery
                      hit its noise-floor breakdown and ended a cycle
                      early (`solver.gmres._chol_ridge` path)
0x8     dt_underflow  the adaptive dt ladder fell below `Params.dt_min`
                      (stamped by the step/ensemble layer, not the solver)
======  ============  =====================================================

``terminal`` verdicts (`is_terminal`) quarantine a lane: ``nonfinite`` and
``dt_underflow`` — no retry at any dt can repair a poisoned state or a
vanished timestep. ``stagnation``/``breakdown`` are retryable: the
escalation ladder (`guard.escalate`) and the host adaptive-dt loop both
get a shot before the member is declared failed.
"""

from __future__ import annotations

HEALTH_OK = 0
NONFINITE = 1 << 0
STAGNATION = 1 << 1
BREAKDOWN = 1 << 2
DT_UNDERFLOW = 1 << 3

#: name -> bit, in bit order (the decode table; docs/robustness.md)
HEALTH_BITS = {
    "nonfinite": NONFINITE,
    "stagnation": STAGNATION,
    "breakdown": BREAKDOWN,
    "dt_underflow": DT_UNDERFLOW,
}

#: verdicts no retry can repair (quarantine triggers)
TERMINAL_MASK = NONFINITE | DT_UNDERFLOW


def decode(word) -> list:
    """Host-side: the set bit names of one health word, bit order.
    ``decode(0) == []`` (healthy)."""
    w = int(word)
    return [name for name, bit in HEALTH_BITS.items() if w & bit]


def describe(word) -> str:
    """Host-side log/status spelling: ``"stagnation|breakdown"`` or
    ``"ok"``."""
    names = decode(word)
    return "|".join(names) if names else "ok"


def nonfinite_word(value):
    """Device-side (traced): an int32 word carrying NONFINITE where
    ``value`` is not finite, else 0 — THE one spelling of the
    nonfinite-stamp rule, shared by the solver entry/exit checks
    (`solver.gmres`), the step-level fiber-error check
    (`system._solve_once`), and the SPMD step (`parallel.spmd`), so the
    rule cannot drift between them. OR it into a health word:
    ``health | nonfinite_word(resid)``."""
    import jax.numpy as jnp

    return jnp.where(jnp.isfinite(value), jnp.int32(0),
                     jnp.int32(NONFINITE))


def is_terminal(word):
    """Device-side (traced) or host-side: True where the word carries a
    verdict quarantine must act on (nonfinite / dt_underflow)."""
    import jax.numpy as jnp

    return (jnp.asarray(word, dtype=jnp.int32) & TERMINAL_MASK) != 0


def retryable(word):
    """Device-side (traced) or host-side: True where the word is bad but
    NOT terminal — the escalation ladder's retry predicate. A nonfinite
    or underflowed member is past saving; everything else gets the
    ladder."""
    import jax.numpy as jnp

    w = jnp.asarray(word, dtype=jnp.int32)
    return (w != 0) & ((w & TERMINAL_MASK) == 0)
