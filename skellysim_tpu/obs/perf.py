"""Bench-history regression gate: read the archived rounds, diff the
ladder (skelly-pulse).

`bench.py` archives round artifacts (``benchmarks/MULTICHIP_r01..r08``,
root ``TREECODE_r07.json`` …) but until now nothing READ them — a ladder
regression only surfaced if someone eyeballed two JSONs. ``python -m
skellysim_tpu.obs perf --compare DIR [--gate PCT]`` closes the loop:

* every ``<GROUP>_r<NN>.json`` in the dir is one round of one group
  (multichip / collectives / treecode / compile / scenarios — any future
  group joins by naming convention);
* the trajectory table prints each group's gated metrics across ALL
  rounds (failed/timeout rounds — the r01–r05 `{"rc": 124}` shells —
  render as ``-``, never crash the report);
* the LATEST TWO parseable rounds are diffed on the gated metrics; a
  drop worse than ``--gate`` percent exits 1.

Gated metrics are the throughput/speedup RATIOS (key suffixes in
`GATED_SUFFIXES` — ``speedup_vs_1dev``, ``tree_vs_direct``,
``*_per_s`` …), not raw walls: ratios survive scene-size changes between
rounds, walls do not. Rounds stamped ``"downscaled": true`` (the CPU
fallback — every round so far; see `_mark_downscaled` in bench.py) report
regressions as WARNINGS and exit 0: toy-scale CPU walls swing ±35%
run-to-run, and a gate that cries wolf gets deleted. The gate ARMS
ITSELF on the first real-backend round pair.

Two comparisons per metric (skelly-roofline): the latest-two adjacent
diff AND the drop vs the BEST parseable round — a slow multi-round drift
(-15% per round for three rounds) passes every adjacent diff but not the
vs-best column. Both gate with the same downscale discipline: vs-best is
warn-only unless BOTH the latest and the best round are real-backend.

``CAMPAIGN_rNN.json`` manifests (bench.py --campaign) live in the same
dir but are NOT rounds — `scan_rounds` skips them; `validate_campaign` /
`render_campaign` back the `obs campaign FILE` subcommand instead.

jax-free (json only), cheap enough for every CI tier (<100 ms).
"""

from __future__ import annotations

import json
import os
import re

#: artifact naming convention: <GROUP>_r<NN>.json (bench.py archives)
ROUND_RE = re.compile(r"^([A-Za-z0-9]+(?:_[A-Za-z0-9]+)*)_r(\d+)\.json$")

#: numeric-leaf key suffixes that gate (all higher-is-better ratios/rates)
GATED_SUFFIXES = ("speedup_vs_1dev", "tree_vs_direct", "spectral_vs_direct",
                  "gpairs_per_s", "equiv_gpairs_per_s", "members_per_s",
                  "steps_per_s", "warm_speedup", "hit_speedup",
                  "armed_vs_off")

#: per-group headline metrics for the trajectory table (dotted paths);
#: groups not listed fall back to their first few gated metrics.
#: scenarios/compile/flight joined the archive in the skelly-flight round
#: (bench.py `_archive_round`) — their members/s, warm/bucket-hit, and
#: recorder-overhead ratios now diff like the MULTICHIP/TREECODE ladders.
#: A headline absent from a round (e.g. the B8/B32 rungs on CPU-downscaled
#: rounds) renders "-", never an error.
HEADLINES = {
    "multichip": ["coupled_spmd.d2.speedup_vs_1dev",
                  "coupled_spmd.d4.speedup_vs_1dev",
                  "coupled_spmd.d8.speedup_vs_1dev",
                  "matvec.d8.speedup_vs_1dev"],
    "treecode": ["n65536.tree_vs_direct", "n16384.tree_vs_direct"],
    "spectral": ["n65536.spectral_vs_direct", "n16384.spectral_vs_direct"],
    "scenarios": ["ladder.B1.members_per_s", "ladder.B2.members_per_s",
                  "ladder.B4.members_per_s", "ladder.B8.members_per_s",
                  "ladder.B32.members_per_s"],
    "compile": ["warm_speedup", "bucket_hit.hit_speedup"],
    "flight": ["armed_vs_off", "k0.steps_per_s", "k32.steps_per_s"],
}


def flatten(doc, prefix="") -> dict:
    """Nested dict -> {dotted.path: number} over int/float leaves (bools
    excluded — `downscaled` must not become a gated metric)."""
    out: dict = {}
    if not isinstance(doc, dict):
        return out
    for k, v in doc.items():
        path = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            out[path] = float(v)
        elif isinstance(v, dict):
            out.update(flatten(v, path))
    return out


def gated_metrics(flat: dict) -> dict:
    return {k: v for k, v in flat.items()
            if k.rsplit(".", 1)[-1] in GATED_SUFFIXES
            or any(k.endswith("." + s) for s in GATED_SUFFIXES)}


class Round:
    def __init__(self, group: str, number: int, path: str):
        self.group = group
        self.number = number
        self.path = path
        self.doc: dict = {}
        self.error = None
        try:
            with open(path) as fh:
                self.doc = json.load(fh)
            if not isinstance(self.doc, dict):
                raise ValueError("artifact is not a JSON object")
        except Exception as e:
            self.doc = {}
            self.error = f"{type(e).__name__}: {e}"
        self.flat = flatten(self.doc)
        self.gated = gated_metrics(self.flat)

    @property
    def parseable(self) -> bool:
        """A round carrying at least one gated metric — the r01–r05
        timeout shells ({"rc": 124, "ok": false}) are not."""
        return bool(self.gated)

    @property
    def downscaled(self) -> bool:
        return bool(self.doc.get("downscaled"))

    @property
    def label(self) -> str:
        return f"r{self.number:02d}"


def scan_rounds(bench_dir: str) -> dict:
    """{group: [Round sorted by number]} over ``<GROUP>_r<NN>.json``."""
    groups: dict = {}
    if not os.path.isdir(bench_dir):
        raise FileNotFoundError(f"no such bench dir: {bench_dir!r}")
    for fname in sorted(os.listdir(bench_dir)):
        m = ROUND_RE.match(fname)
        if not m:
            continue
        group = m.group(1).lower()
        if group == "campaign":
            continue   # campaign manifests are reports ABOUT rounds
        groups.setdefault(group, []).append(
            Round(group, int(m.group(2)), os.path.join(bench_dir, fname)))
    for rounds in groups.values():
        rounds.sort(key=lambda r: r.number)
    return groups


def compare_rounds(prev: Round, cur: Round, gate_pct: float) -> list:
    """[(metric, prev, cur, pct_change, regressed)] over the gated
    metrics both rounds carry (higher is better for all of them)."""
    out = []
    for key in sorted(set(prev.gated) & set(cur.gated)):
        a, b = prev.gated[key], cur.gated[key]
        if a == 0:
            continue
        pct = (b - a) / abs(a) * 100.0
        out.append((key, a, b, pct, pct < -gate_pct))
    return out


def best_rounds(parseable: list) -> dict:
    """{metric: (best value, Round it came from)} over every parseable
    round — the vs-best column's reference. Higher is better for every
    gated metric; ties go to the EARLIEST round (a later equal round is
    "recovered", not "new best")."""
    best: dict = {}
    for r in parseable:
        for key, v in r.gated.items():
            if key not in best or v > best[key][0]:
                best[key] = (v, r)
    return best


def vs_best_entries(parseable: list, gate_pct: float) -> list:
    """[(metric, best value, best Round, cur value, pct_vs_best,
    regressed_vs_best, soft)] for the LATEST parseable round against the
    best round per metric. ``soft`` (warn-only) when the latest OR the
    best round is downscaled — the vs-best gate arms with the same
    real-backend discipline as the adjacent diff."""
    if not parseable:
        return []
    cur = parseable[-1]
    best = best_rounds(parseable)
    out = []
    for key in sorted(cur.gated):
        if key not in best:
            continue
        bv, br = best[key]
        if bv <= 0 or br is cur:
            continue
        b = cur.gated[key]
        pct = (b - bv) / abs(bv) * 100.0
        out.append((key, bv, br, b, pct, pct < -gate_pct,
                    cur.downscaled or br.downscaled))
    return out


def render_report(bench_dir: str, gate_pct: float = 25.0):
    """(report text, exit code): the `obs perf --compare` body.

    Exit 1 iff a non-downscaled round pair regressed a gated metric by
    more than ``gate_pct`` percent; 2 when the dir holds no rounds."""
    groups = scan_rounds(bench_dir)
    out: list = []
    failures = 0
    warnings = 0
    if not groups:
        return (f"no <GROUP>_rNN.json round artifacts under {bench_dir!r}\n",
                2)
    for group in sorted(groups):
        rounds = groups[group]
        out.append(f"== {group} trajectory ({len(rounds)} round(s)) ==")
        headline = HEADLINES.get(group)
        if headline is None:
            parseable = [r for r in rounds if r.parseable]
            headline = (sorted(parseable[-1].gated)[:4] if parseable
                        else [])
        def _hdr(h: str) -> str:
            # "coupled_spmd.d8.speedup_vs_1dev" -> "coupled_spmd.d8": the
            # gated suffix is implied, the component path disambiguates
            for s in GATED_SUFFIXES:
                if h.endswith("." + s):
                    return h[:-(len(s) + 1)]
            return h

        parseable = [r for r in rounds if r.parseable]
        rows = [("round",) + tuple(_hdr(h) for h in headline) + ("flags",)]
        for r in rounds:
            if not r.parseable:
                rows.append((r.label,) + ("-",) * len(headline)
                            + ("unparseable" if r.error else "incomplete",))
                continue
            vals = tuple("-" if r.flat.get(h) is None
                         else f"{r.flat[h]:g}" for h in headline)
            rows.append((r.label,) + vals
                        + ("downscaled" if r.downscaled else "",))
        if len(parseable) >= 2:
            # the best-round column: where each headline peaked across the
            # whole trajectory, so a slow drift is visible at a glance
            best = best_rounds(parseable)
            rows.append(("best",) + tuple(
                f"{best[h][0]:g}@{best[h][1].label}" if h in best else "-"
                for h in headline) + ("",))
        widths = [max(len(row[i]) for row in rows)
                  for i in range(len(rows[0]))]
        out.extend("  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip()
                   for row in rows)

        if len(parseable) < 2:
            out.append(f"({group}: <2 parseable rounds — nothing to diff)")
            out.append("")
            continue
        prev, cur = parseable[-2], parseable[-1]
        soft = prev.downscaled or cur.downscaled
        out.append(f"diff {prev.label} -> {cur.label} "
                   f"(gate {gate_pct:g}%"
                   + (", downscaled rounds: warn-only)" if soft else ")"))
        vsb = {e[0]: e for e in vs_best_entries(parseable, gate_pct)}
        adjacent = compare_rounds(prev, cur, gate_pct)
        seen = set()
        for key, a, b, pct, regressed in adjacent:
            seen.add(key)
            _, bv, br, _, pct_b, reg_b, soft_b = vsb.get(
                key, (key, None, None, None, None, False, True))
            hard = (regressed and not soft) or (reg_b and not soft_b)
            warn = (not hard) and ((regressed and soft)
                                   or (reg_b and soft_b))
            mark = ""
            if hard:
                mark = "  REGRESSION" + ("" if regressed else " (vs best)")
                failures += 1
            elif warn:
                mark = "  WARN (downscaled — not gated)"
                warnings += 1
            tail = (f" | best {bv:g}@{br.label} ({pct_b:+.1f}% vs best)"
                    if bv is not None else "")
            out.append(f"  {key}: {a:g} -> {b:g} ({pct:+.1f}%){tail}{mark}")
        # vs-best regressions on metrics the adjacent diff couldn't see
        # (absent from the previous round) still gate
        for key, (_, bv, br, b, pct_b, reg_b, soft_b) in sorted(vsb.items()):
            if key in seen or not reg_b:
                continue
            if soft_b:
                mark = "  WARN (downscaled — not gated)"
                warnings += 1
            else:
                mark = "  REGRESSION (vs best)"
                failures += 1
            out.append(f"  {key}: {b:g} vs best {bv:g}@{br.label} "
                       f"({pct_b:+.1f}% vs best){mark}")
        out.append("")
    if failures:
        out.append(f"skelly-pulse: {failures} gated regression(s) beyond "
                   f"{gate_pct:g}% — fix the ladder or re-measure "
                   "deliberately (docs/performance.md)")
    elif warnings:
        out.append(f"skelly-pulse: {warnings} downscaled-round warning(s); "
                   "gate passes (CPU toy rounds never gate — re-measure "
                   "on hardware)")
    else:
        out.append("skelly-pulse: bench history within gate")
    return "\n".join(out) + "\n", (1 if failures else 0)


def report_json(bench_dir: str, gate_pct: float = 25.0):
    """(doc, exit code) — the machine-readable twin of `render_report`,
    with the SAME exit-code contract (2 when the dir holds no rounds, 1 on
    a gated non-downscaled regression): a CI job wired with ``--json``
    must fail exactly when the text gate would."""
    groups = scan_rounds(bench_dir)
    doc: dict = {"gate_pct": gate_pct, "groups": {}}
    failures = 0
    for group, rounds in groups.items():
        parseable = [r for r in rounds if r.parseable]
        entry = {
            "rounds": [r.label for r in rounds],
            "parseable": [r.label for r in parseable],
            "trajectory": {r.label: r.gated for r in parseable},
        }
        group_failures = group_warnings = 0
        if parseable:
            latest = parseable[-1]
            entry["latest"] = {
                "round": latest.label,
                "downscaled": latest.downscaled,
                "backend": latest.doc.get("backend"),
                "device_kind": latest.doc.get("device_kind"),
                "headlines": {h: latest.flat.get(h)
                              for h in HEADLINES.get(group,
                                                     sorted(latest.gated)[:4])
                              },
            }
            entry["best"] = {k: {"value": v, "round": r.label}
                             for k, (v, r) in best_rounds(parseable).items()}
        if len(parseable) >= 2:
            prev, cur = parseable[-2], parseable[-1]
            soft = prev.downscaled or cur.downscaled
            vsb = {e[0]: e for e in vs_best_entries(parseable, gate_pct)}
            metrics = []
            seen = set()
            for k, a, b, pct, reg in compare_rounds(prev, cur, gate_pct):
                seen.add(k)
                m = {"metric": k, "prev": a, "cur": b,
                     "pct": round(pct, 2), "regressed": reg}
                if k in vsb:
                    _, bv, br, _, pct_b, reg_b, soft_b = vsb[k]
                    m.update({"best": bv, "best_round": br.label,
                              "pct_vs_best": round(pct_b, 2),
                              "regressed_vs_best": reg_b,
                              "vs_best_downscaled": soft_b})
                else:
                    reg_b, soft_b = False, True
                hard = (reg and not soft) or (reg_b and not soft_b)
                if hard:
                    group_failures += 1
                elif (reg and soft) or (reg_b and soft_b):
                    group_warnings += 1
                metrics.append(m)
            for k, (_, bv, br, b, pct_b, reg_b, soft_b) in sorted(
                    vsb.items()):
                if k in seen or not reg_b:
                    continue
                metrics.append({"metric": k, "cur": b, "best": bv,
                                "best_round": br.label,
                                "pct_vs_best": round(pct_b, 2),
                                "regressed_vs_best": True,
                                "vs_best_downscaled": soft_b})
                if soft_b:
                    group_warnings += 1
                else:
                    group_failures += 1
            entry["diff"] = {"from": prev.label, "to": cur.label,
                             "downscaled": soft, "metrics": metrics}
            failures += group_failures
        entry["verdict"] = ("FAIL" if group_failures
                            else "WARN" if group_warnings else "PASS")
        doc["groups"][group] = entry
    doc["failures"] = failures
    rc = 2 if not doc["groups"] else (1 if failures else 0)
    return doc, rc


# ------------------------------------------------------ campaign manifests

#: provenance keys every campaign manifest must carry (the uniform bench
#: artifact stamp, skelly-roofline)
CAMPAIGN_PROVENANCE_KEYS = ("backend", "jax_version", "device_kind",
                            "downscaled", "telemetry_version")

#: statuses the campaign parent records per group
CAMPAIGN_STATUSES = ("ok", "skipped_budget", "timeout", "error")


def load_campaign(path: str) -> dict:
    with open(path) as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict):
        raise ValueError("campaign manifest is not a JSON object")
    return doc


def validate_campaign(doc: dict) -> list:
    """Structural errors in a CAMPAIGN_rNN.json manifest ([] = valid).

    The contract the CI smoke and the round-trip test gate on: a round id,
    a non-empty per-group status map, the uniform provenance stamp with an
    EXPLICIT boolean downscale flag, a gate section carrying the perf
    gate's exit code, and a rooflines map (may be empty — profiling is
    best-effort, its absence is recorded, not fatal)."""
    errs = []
    if not isinstance(doc, dict):
        return ["manifest is not a JSON object"]
    rnd = doc.get("round")
    if not isinstance(rnd, str) or not re.fullmatch(r"r\d{2,}", rnd):
        errs.append(f"round: want 'rNN', got {rnd!r}")
    groups = doc.get("groups")
    if not isinstance(groups, dict) or not groups:
        errs.append("groups: want a non-empty {name: {status: ...}} map")
    else:
        for name, g in groups.items():
            status = g.get("status") if isinstance(g, dict) else None
            if not (isinstance(status, str)
                    and (status in CAMPAIGN_STATUSES
                         or status.startswith("error"))):
                errs.append(f"groups.{name}.status: got {status!r}")
    for key in CAMPAIGN_PROVENANCE_KEYS:
        if key not in doc:
            errs.append(f"missing provenance key {key!r}")
    if not isinstance(doc.get("downscaled"), bool):
        errs.append("downscaled: want an explicit bool")
    gate = doc.get("gate")
    if not isinstance(gate, dict) or not isinstance(gate.get("rc"), int):
        errs.append("gate: want {rc: int, ...} from `obs perf --json`")
    if not isinstance(doc.get("rooflines"), dict):
        errs.append("rooflines: want a {group: summary} map (may be empty)")
    return errs


def render_campaign(doc: dict) -> str:
    """The `obs campaign FILE` text body (validity is the caller's check)."""
    out = [f"== campaign {doc.get('round', '?')} "
           f"({doc.get('generated_by', 'bench.py --campaign')}) =="]
    out.append(f"backend: {doc.get('backend')}  device_kind: "
               f"{doc.get('device_kind')}  jax: {doc.get('jax_version')}"
               + ("  [DOWNSCALED]" if doc.get("downscaled") else ""))
    rows = [("group", "status", "roofline")]
    rooflines = doc.get("rooflines") or {}
    for name, g in sorted((doc.get("groups") or {}).items()):
        roof = rooflines.get(name)
        if isinstance(roof, dict) and roof.get("phases"):
            top = roof["phases"][0]
            desc = (f"{roof.get('classified_frac', 0):.0%} classified; "
                    f"top {top.get('phase')}: {top.get('verdict')}")
        elif isinstance(roof, dict) and roof.get("error"):
            desc = f"roofline error: {roof['error']}"
        else:
            desc = "-"
        rows.append((name, str((g or {}).get("status", "?")), desc))
    widths = [max(len(r[i]) for r in rows) for i in range(3)]
    out.extend("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
               for r in rows)
    gate = doc.get("gate") or {}
    rc = gate.get("rc")
    verdicts = {name: (entry or {}).get("verdict", "?")
                for name, entry in ((gate.get("report") or {})
                                    .get("groups") or {}).items()}
    out.append("gate: rc=" + str(rc)
               + ("  " + "  ".join(f"{n}={v}" for n, v
                                   in sorted(verdicts.items()))
                  if verdicts else ""))
    if doc.get("downscaled"):
        out.append("(downscaled campaign: regressions warn, never fail — "
                   "the gate arms on the first real-backend round)")
    return "\n".join(out) + "\n"
