"""Bench-history regression gate: read the archived rounds, diff the
ladder (skelly-pulse).

`bench.py` archives round artifacts (``benchmarks/MULTICHIP_r01..r07``,
root ``TREECODE_r06.json`` …) but until now nothing READ them — a ladder
regression only surfaced if someone eyeballed two JSONs. ``python -m
skellysim_tpu.obs perf --compare DIR [--gate PCT]`` closes the loop:

* every ``<GROUP>_r<NN>.json`` in the dir is one round of one group
  (multichip / collectives / treecode / compile / scenarios — any future
  group joins by naming convention);
* the trajectory table prints each group's gated metrics across ALL
  rounds (failed/timeout rounds — the r01–r05 `{"rc": 124}` shells —
  render as ``-``, never crash the report);
* the LATEST TWO parseable rounds are diffed on the gated metrics; a
  drop worse than ``--gate`` percent exits 1.

Gated metrics are the throughput/speedup RATIOS (key suffixes in
`GATED_SUFFIXES` — ``speedup_vs_1dev``, ``tree_vs_direct``,
``*_per_s`` …), not raw walls: ratios survive scene-size changes between
rounds, walls do not. Rounds stamped ``"downscaled": true`` (the CPU
fallback — every round so far; see `_mark_downscaled` in bench.py) report
regressions as WARNINGS and exit 0: toy-scale CPU walls swing ±35%
run-to-run, and a gate that cries wolf gets deleted. The gate ARMS
ITSELF on the first real-backend round pair.

jax-free (json only), cheap enough for every CI tier (<100 ms).
"""

from __future__ import annotations

import json
import os
import re

#: artifact naming convention: <GROUP>_r<NN>.json (bench.py archives)
ROUND_RE = re.compile(r"^([A-Za-z0-9]+(?:_[A-Za-z0-9]+)*)_r(\d+)\.json$")

#: numeric-leaf key suffixes that gate (all higher-is-better ratios/rates)
GATED_SUFFIXES = ("speedup_vs_1dev", "tree_vs_direct", "spectral_vs_direct",
                  "gpairs_per_s", "equiv_gpairs_per_s", "members_per_s",
                  "steps_per_s", "warm_speedup", "hit_speedup",
                  "armed_vs_off")

#: per-group headline metrics for the trajectory table (dotted paths);
#: groups not listed fall back to their first few gated metrics.
#: scenarios/compile/flight joined the archive in the skelly-flight round
#: (bench.py `_archive_round`) — their members/s, warm/bucket-hit, and
#: recorder-overhead ratios now diff like the MULTICHIP/TREECODE ladders.
#: A headline absent from a round (e.g. the B8/B32 rungs on CPU-downscaled
#: rounds) renders "-", never an error.
HEADLINES = {
    "multichip": ["coupled_spmd.d2.speedup_vs_1dev",
                  "coupled_spmd.d4.speedup_vs_1dev",
                  "coupled_spmd.d8.speedup_vs_1dev",
                  "matvec.d8.speedup_vs_1dev"],
    "treecode": ["n65536.tree_vs_direct", "n16384.tree_vs_direct"],
    "spectral": ["n65536.spectral_vs_direct", "n16384.spectral_vs_direct"],
    "scenarios": ["ladder.B1.members_per_s", "ladder.B2.members_per_s",
                  "ladder.B4.members_per_s", "ladder.B8.members_per_s",
                  "ladder.B32.members_per_s"],
    "compile": ["warm_speedup", "bucket_hit.hit_speedup"],
    "flight": ["armed_vs_off", "k0.steps_per_s", "k32.steps_per_s"],
}


def flatten(doc, prefix="") -> dict:
    """Nested dict -> {dotted.path: number} over int/float leaves (bools
    excluded — `downscaled` must not become a gated metric)."""
    out: dict = {}
    if not isinstance(doc, dict):
        return out
    for k, v in doc.items():
        path = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            out[path] = float(v)
        elif isinstance(v, dict):
            out.update(flatten(v, path))
    return out


def gated_metrics(flat: dict) -> dict:
    return {k: v for k, v in flat.items()
            if k.rsplit(".", 1)[-1] in GATED_SUFFIXES
            or any(k.endswith("." + s) for s in GATED_SUFFIXES)}


class Round:
    def __init__(self, group: str, number: int, path: str):
        self.group = group
        self.number = number
        self.path = path
        self.doc: dict = {}
        self.error = None
        try:
            with open(path) as fh:
                self.doc = json.load(fh)
            if not isinstance(self.doc, dict):
                raise ValueError("artifact is not a JSON object")
        except Exception as e:
            self.doc = {}
            self.error = f"{type(e).__name__}: {e}"
        self.flat = flatten(self.doc)
        self.gated = gated_metrics(self.flat)

    @property
    def parseable(self) -> bool:
        """A round carrying at least one gated metric — the r01–r05
        timeout shells ({"rc": 124, "ok": false}) are not."""
        return bool(self.gated)

    @property
    def downscaled(self) -> bool:
        return bool(self.doc.get("downscaled"))

    @property
    def label(self) -> str:
        return f"r{self.number:02d}"


def scan_rounds(bench_dir: str) -> dict:
    """{group: [Round sorted by number]} over ``<GROUP>_r<NN>.json``."""
    groups: dict = {}
    if not os.path.isdir(bench_dir):
        raise FileNotFoundError(f"no such bench dir: {bench_dir!r}")
    for fname in sorted(os.listdir(bench_dir)):
        m = ROUND_RE.match(fname)
        if not m:
            continue
        group = m.group(1).lower()
        groups.setdefault(group, []).append(
            Round(group, int(m.group(2)), os.path.join(bench_dir, fname)))
    for rounds in groups.values():
        rounds.sort(key=lambda r: r.number)
    return groups


def compare_rounds(prev: Round, cur: Round, gate_pct: float) -> list:
    """[(metric, prev, cur, pct_change, regressed)] over the gated
    metrics both rounds carry (higher is better for all of them)."""
    out = []
    for key in sorted(set(prev.gated) & set(cur.gated)):
        a, b = prev.gated[key], cur.gated[key]
        if a == 0:
            continue
        pct = (b - a) / abs(a) * 100.0
        out.append((key, a, b, pct, pct < -gate_pct))
    return out


def render_report(bench_dir: str, gate_pct: float = 25.0):
    """(report text, exit code): the `obs perf --compare` body.

    Exit 1 iff a non-downscaled round pair regressed a gated metric by
    more than ``gate_pct`` percent; 2 when the dir holds no rounds."""
    groups = scan_rounds(bench_dir)
    out: list = []
    failures = 0
    warnings = 0
    if not groups:
        return (f"no <GROUP>_rNN.json round artifacts under {bench_dir!r}\n",
                2)
    for group in sorted(groups):
        rounds = groups[group]
        out.append(f"== {group} trajectory ({len(rounds)} round(s)) ==")
        headline = HEADLINES.get(group)
        if headline is None:
            parseable = [r for r in rounds if r.parseable]
            headline = (sorted(parseable[-1].gated)[:4] if parseable
                        else [])
        def _hdr(h: str) -> str:
            # "coupled_spmd.d8.speedup_vs_1dev" -> "coupled_spmd.d8": the
            # gated suffix is implied, the component path disambiguates
            for s in GATED_SUFFIXES:
                if h.endswith("." + s):
                    return h[:-(len(s) + 1)]
            return h

        rows = [("round",) + tuple(_hdr(h) for h in headline) + ("flags",)]
        for r in rounds:
            if not r.parseable:
                rows.append((r.label,) + ("-",) * len(headline)
                            + ("unparseable" if r.error else "incomplete",))
                continue
            vals = tuple("-" if r.flat.get(h) is None
                         else f"{r.flat[h]:g}" for h in headline)
            rows.append((r.label,) + vals
                        + ("downscaled" if r.downscaled else "",))
        widths = [max(len(row[i]) for row in rows)
                  for i in range(len(rows[0]))]
        out.extend("  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip()
                   for row in rows)

        parseable = [r for r in rounds if r.parseable]
        if len(parseable) < 2:
            out.append(f"({group}: <2 parseable rounds — nothing to diff)")
            out.append("")
            continue
        prev, cur = parseable[-2], parseable[-1]
        soft = prev.downscaled or cur.downscaled
        out.append(f"diff {prev.label} -> {cur.label} "
                   f"(gate {gate_pct:g}%"
                   + (", downscaled rounds: warn-only)" if soft else ")"))
        for key, a, b, pct, regressed in compare_rounds(prev, cur,
                                                        gate_pct):
            mark = ""
            if regressed:
                if soft:
                    mark = "  WARN (downscaled — not gated)"
                    warnings += 1
                else:
                    mark = "  REGRESSION"
                    failures += 1
            out.append(f"  {key}: {a:g} -> {b:g} ({pct:+.1f}%){mark}")
        out.append("")
    if failures:
        out.append(f"skelly-pulse: {failures} gated regression(s) beyond "
                   f"{gate_pct:g}% — fix the ladder or re-measure "
                   "deliberately (docs/performance.md)")
    elif warnings:
        out.append(f"skelly-pulse: {warnings} downscaled-round warning(s); "
                   "gate passes (CPU toy rounds never gate — re-measure "
                   "on hardware)")
    else:
        out.append("skelly-pulse: bench history within gate")
    return "\n".join(out) + "\n", (1 if failures else 0)


def report_json(bench_dir: str, gate_pct: float = 25.0):
    """(doc, exit code) — the machine-readable twin of `render_report`,
    with the SAME exit-code contract (2 when the dir holds no rounds, 1 on
    a gated non-downscaled regression): a CI job wired with ``--json``
    must fail exactly when the text gate would."""
    groups = scan_rounds(bench_dir)
    doc: dict = {"gate_pct": gate_pct, "groups": {}}
    failures = 0
    for group, rounds in groups.items():
        parseable = [r for r in rounds if r.parseable]
        entry = {
            "rounds": [r.label for r in rounds],
            "parseable": [r.label for r in parseable],
            "trajectory": {r.label: r.gated for r in parseable},
        }
        if len(parseable) >= 2:
            prev, cur = parseable[-2], parseable[-1]
            soft = prev.downscaled or cur.downscaled
            metrics = [
                {"metric": k, "prev": a, "cur": b,
                 "pct": round(pct, 2), "regressed": reg}
                for k, a, b, pct, reg in compare_rounds(prev, cur,
                                                        gate_pct)]
            entry["diff"] = {"from": prev.label, "to": cur.label,
                             "downscaled": soft, "metrics": metrics}
            if not soft:
                failures += sum(1 for m in metrics if m["regressed"])
        doc["groups"][group] = entry
    rc = 2 if not doc["groups"] else (1 if failures else 0)
    return doc, rc
