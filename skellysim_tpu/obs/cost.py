"""Cost accounting: FLOPs/bytes/memory per audited program, with baselines.

skelly-scope's third leg. skelly-audit pins what the lowered programs *are*
(collectives, dtype edges, callbacks); this module pins what they *cost*:
for every entry in the SAME registry (`audit.programs.all_programs()` — the
`auditable_programs()` seam is reused, nothing re-registers), the program
is compiled and XLA's own static analyses are read out::

    .lower().compile().cost_analysis()    -> flops, bytes accessed
    .lower().compile().memory_analysis()  -> argument/output/temp bytes

and compared against a checked-in baseline (`obs/baselines/<name>.toml`,
written/updated via ``python -m skellysim_tpu.obs cost --update``). The
drift gate mirrors the audit-contract discipline:

* a registered program with no baseline file is a finding (new programs
  must arrive with their cost pinned);
* any gated metric drifting beyond the baseline's ``tol_pct`` (default
  ``25.0``) is a finding — regressions AND improvements, so the baseline
  always describes the current program (a stale "cheap" baseline would
  hide the next regression inside its slack);
* a baseline file whose program is no longer registered is a finding;
* deliberate changes are recorded with ``[[suppress]]`` entries (``check``
  + ``match`` + mandatory ``reason``; unused entries are findings) — the
  same engine as audit contracts (`audit.engine.apply_suppressions`).

The numbers are XLA *static* analyses of the compiled module — exact flop
and traffic counts for the optimized program on the compiling backend, not
wall-time samples — so they are deterministic run-to-run and honest about
program-structure regressions (an accidental f64 promotion or a dropped
fusion moves them immediately). `memory_analysis` is the compiled
footprint: ``peak_bytes`` here is argument + output + temp — the resident
proxy that tracks HBM pressure on accelerators (on CPU XLA, temp covers
the scratch the schedule actually allocates).
"""

from __future__ import annotations

import os
import time

from ..audit.engine import Finding, apply_suppressions

BASELINE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "baselines")

#: relative drift tolerance (percent) when a baseline pins no tol_pct
DEFAULT_TOL_PCT = 25.0

#: gated metrics, in table order. A baseline may pin a subset (only pinned
#: keys gate), but `--update` always writes all of them.
COST_KEYS = ("flops", "bytes_accessed", "argument_bytes", "output_bytes",
             "temp_bytes", "peak_bytes")

CHECK_ID = "cost-baseline"


def baseline_path(name: str, baseline_dir: str | None = None) -> str:
    return os.path.join(baseline_dir or BASELINE_DIR, f"{name}.toml")


def measure_built(built) -> dict:
    """Compile one `audit.registry.BuiltProgram` and read XLA's static cost
    + memory analyses into a flat metrics dict."""
    if getattr(built, "lowered", None) is None:
        raise ValueError(
            "BuiltProgram carries no lowered artifact (built_from now "
            "retains it); cost accounting needs `.lowered.compile()`")
    t0 = time.perf_counter()
    compiled = built.lowered.compile()
    compile_s = time.perf_counter() - t0
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ca = ca or {}
    ma = compiled.memory_analysis()

    def mem(attr):
        return int(getattr(ma, attr, 0) or 0)

    arg_b = mem("argument_size_in_bytes")
    out_b = mem("output_size_in_bytes")
    tmp_b = mem("temp_size_in_bytes")
    return {
        "flops": float(ca.get("flops", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)),
        "argument_bytes": arg_b,
        "output_bytes": out_b,
        "temp_bytes": tmp_b,
        "peak_bytes": arg_b + out_b + tmp_b,
        "compile_s": round(compile_s, 3),   # informational, never gated
    }


def load_baseline(name: str, baseline_dir: str | None = None):
    """(baseline dict | None, [Finding]) — validation findings only."""
    from ..config import toml_io

    path = baseline_path(name, baseline_dir)
    if not os.path.exists(path):
        return None, [Finding(name, CHECK_ID, (
            f"no cost baseline at obs/baselines/{name}.toml — every "
            "registered program must pin its cost (run `python -m "
            "skellysim_tpu.obs cost --update` and commit the result)"))]
    data = toml_io.load(path)
    out = []
    declared = data.get("program", {}).get("name")
    if declared is not None and declared != name:
        out.append(Finding(name, CHECK_ID, (
            f"baseline file {name}.toml declares program.name="
            f"{declared!r} — copy-paste drift")))
    for i, sup in enumerate(data.get("suppress", [])):
        if not sup.get("check") or not sup.get("match"):
            out.append(Finding(name, CHECK_ID, (
                f"suppress entry #{i + 1} needs both `check` and a "
                "non-empty `match`")))
        if not sup.get("reason"):
            out.append(Finding(name, CHECK_ID, (
                f"suppress entry #{i + 1} is missing its reason: every "
                "suppression must say why")))
    return data, out


def cost_findings(name: str, measured: dict, baseline: dict):
    """Drift findings for one program against its (loaded) baseline."""
    out = []
    base = baseline.get("cost", {})
    tol = float(base.get("tol_pct", DEFAULT_TOL_PCT))
    for key in COST_KEYS:
        if key not in base:
            continue
        b = float(base[key])
        m = float(measured[key])
        denom = max(abs(b), 1.0)
        drift = (m - b) / denom * 100.0
        if abs(drift) > tol:
            kind = "regression" if m > b else "improvement"
            out.append(Finding(name, CHECK_ID, (
                f"{key} drifted {drift:+.1f}% ({kind}): baseline {b:g}, "
                f"measured {m:g} (tol ±{tol:g}%) — fix the program or "
                "re-baseline deliberately with `obs cost --update`")))
    return out


def write_baseline(name: str, measured: dict,
                   baseline_dir: str | None = None) -> str:
    """Write/refresh one baseline file, preserving an existing file's
    ``tol_pct`` and ``[[suppress]]`` entries (the deliberate knobs)."""
    from ..config import toml_io

    path = baseline_path(name, baseline_dir)
    prev = toml_io.load(path) if os.path.exists(path) else {}
    cost = {k: measured[k] for k in COST_KEYS}
    if "tol_pct" in prev.get("cost", {}):
        cost["tol_pct"] = prev["cost"]["tol_pct"]
    data = {"program": {"name": name}, "cost": cost}
    if prev.get("suppress"):
        data["suppress"] = prev["suppress"]
    os.makedirs(os.path.dirname(path), exist_ok=True)
    toml_io.dump(data, path)
    return path


def audit_costs(progs, baseline_dir: str | None = None,
                update: bool = False, registry_names=None):
    """Measure every program and gate against baselines.

    Returns ``(rows, findings)``: one row dict per program (name + the
    measured metrics; ``error`` instead when the build/compile failed) and
    the unsuppressed findings. ``update=True`` rewrites baseline files from
    the measurements instead of gating (validation findings still count).

    ``registry_names`` is the FULL registered-program name set for the
    stale-baseline scan; it defaults to ``progs``'s names, but a caller
    auditing a filtered subset (``--program NAME``) must pass the full set
    or every other program's perfectly valid baseline reads as stale.
    """
    rows = []
    findings = []
    seen = (set(registry_names) if registry_names is not None else
            {p.name for p in progs})
    for prog in progs:
        baseline, f_load = load_baseline(prog.name, baseline_dir)
        prog_findings = [] if (update and baseline is None) else list(f_load)
        try:
            measured = measure_built(prog.build())
        except Exception as e:  # a program that no longer compiles IS a finding
            rows.append({"name": prog.name,
                         "error": f"{type(e).__name__}: {e}"})
            prog_findings.append(Finding(prog.name, CHECK_ID, (
                f"entry point failed to build/compile: "
                f"{type(e).__name__}: {e}")))
            findings.extend(apply_suppressions(prog.name, baseline,
                                               prog_findings))
            continue
        rows.append(dict({"name": prog.name}, **measured))
        if update:
            write_baseline(prog.name, measured, baseline_dir)
        elif baseline is not None:
            prog_findings.extend(cost_findings(prog.name, measured, baseline))
        findings.extend(apply_suppressions(prog.name, baseline,
                                           prog_findings))
    # stale baseline files: the registry no longer names them
    bdir = baseline_dir or BASELINE_DIR
    if os.path.isdir(bdir):
        for fn in sorted(os.listdir(bdir)):
            stem, ext = os.path.splitext(fn)
            if ext == ".toml" and stem not in seen:
                findings.append(Finding(stem, CHECK_ID, (
                    f"stale baseline obs/baselines/{fn}: no registered "
                    "program by that name — remove it (or the program "
                    "lost its registration silently)")))
    return rows, findings


def render_table(rows) -> str:
    """Fixed-width cost table (the CLI's report body)."""
    cols = ("name", "flops", "bytes_accessed", "peak_bytes", "argument_bytes",
            "temp_bytes", "compile_s")
    heads = ("program", "flops", "bytes", "peak_B", "arg_B", "temp_B",
             "compile_s")

    def fmt(row, key):
        if "error" in row and key != "name":
            return "build error" if key == "flops" else ""
        v = row.get(key, "")
        if isinstance(v, float) and key in ("flops", "bytes_accessed"):
            return f"{v:.3e}"
        return str(v)

    table = [heads] + [tuple(fmt(r, c) for c in cols) for r in rows]
    widths = [max(len(line[i]) for line in table) for i in range(len(cols))]
    return "\n".join("  ".join(cell.ljust(w) for cell, w in
                               zip(line, widths)).rstrip()
                     for line in table)
