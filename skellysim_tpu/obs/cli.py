"""skelly-scope CLI: `python -m skellysim_tpu.obs
<summarize|flight|cost|profile|roofline|timeline|perf|campaign>`.

``flight FILE [FILE...]`` renders the skelly-flight blast-radius report
from any mix of metrics/telemetry JSONL: each faulted member's
diagnostics trajectory into the fault (strain/speed/clearance/norm rows
from the device-side recorder ring) plus the anomaly provenance naming
the first nonfinite's field/fiber/node (docs/observability.md "Flight
recorder"). jax-free, torn-trailing-line tolerant.

``summarize FILE [FILE...]`` renders any mix of telemetry/metrics JSONL
streams (run-loop metrics, `System.run(trace_path=...)` traces, ensemble
metrics, bench traces) into per-span timings, compile events, lane
occupancy, and solver convergence stats. Pure host-side text processing —
it never initializes a jax backend (the package import pulls the jax
*module* in, nothing more).

``profile DIR [--by phase|collective|op] [--json]`` attributes the device
op time of a ``--profile`` dump to the named_scope phase vocabulary
(`obs.profile`, docs/observability.md "Device-time attribution").

``timeline TRACE.jsonl [TRACE...] [--profile DIR] -o out.perfetto.json``
merges telemetry spans, compile instants, and (optionally) the profiler's
device phases into ONE Chrome-trace/Perfetto artifact (`obs.timeline`).

``roofline DIR [--program P | --cost-table TOML] [--device-kind K]
[--executions N] [--json]`` joins a profile dump's per-phase device walls
with the program's static cost table and the audit contract's pinned
collective bytes against the checked-in device-peak table
(`obs/device_peaks.toml`) — achieved FLOP/s / bytes/s, arithmetic
intensity, compute-/memory-/comms-bound verdicts, and achieved-vs-peak
per phase (`obs.roofline`, docs/observability.md "Roofline"). Unknown
device kinds rate as "unrated", never a crash.

``perf --compare DIR [--gate PCT] [--json]`` diffs the archived bench
rounds (``benchmarks/MULTICHIP_r*.json`` …) and exits 1 on a gated-metric
regression on non-downscaled rounds (`obs.perf`) — the CI bench-history
gate. Renders the full trajectory with a best-round row and gates the
latest round against BOTH its predecessor and the best round per metric.

``campaign FILE [--json]`` validates + renders a ``CAMPAIGN_rNN.json``
manifest (`bench.py --campaign`): per-group statuses, roofline summaries,
gate verdicts. Exit 2 on a structurally-invalid manifest, 1 when the
recorded gate failed, 0 otherwise — the CI campaign smoke's gate.

``cost`` measures every registered auditable program's XLA cost/memory
analysis and (``--check``) gates it against `obs/baselines/*.toml` — exit
status mirrors skelly-lint/skelly-audit so CI gates on it directly: 0
clean, 1 findings, 2 usage errors. ``--update`` rewrites the baselines
from the current measurement (the sanctioned re-baseline path; ``tol_pct``
and ``[[suppress]]`` entries are preserved). Like the audit CLI it
bootstraps the 8-device virtual CPU platform with x64 BEFORE jax loads, so
the SPMD programs lower/compile identically to the test environment and
the checked-in baselines are reproducible.
"""

from __future__ import annotations

import argparse
import sys


def _bootstrap_backend():
    from ..utils.bootstrap import enable_compilation_cache, force_cpu_devices

    force_cpu_devices(8)
    import jax

    jax.config.update("jax_enable_x64", True)
    # persistent compile cache — the ONE implementation + min-compile-time
    # threshold in utils.bootstrap, shared with bench.py and every CLI: the
    # cost gate compiles every registered program, and warm CI re-runs skip
    # the XLA compile seconds — tracing/lowering (which the measurements
    # come from) is unaffected, and cost/memory analyses read the same
    # values off cache-loaded executables (pinned by the double-run in the
    # CI gate's bring-up)
    enable_compilation_cache("auto")


def _cmd_summarize(args) -> int:
    import os

    from .summarize import summarize_files

    missing = [p for p in args.files if not os.path.exists(p)]
    if missing:
        print(f"skelly-scope: no such file(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2
    print(summarize_files(args.files), end="")
    return 0


def _cmd_cost(args) -> int:
    _bootstrap_backend()
    from ..audit.programs import all_programs
    from .cost import audit_costs, render_table

    progs = all_programs()
    registry_names = {p.name for p in progs}
    if args.program:
        unknown = [n for n in args.program if n not in registry_names]
        if unknown:
            print(f"skelly-scope: unknown program(s): {', '.join(unknown)} "
                  "(try `python -m skellysim_tpu.audit --list-programs`)",
                  file=sys.stderr)
            return 2
        progs = [p for p in progs if p.name in set(args.program)]

    # registry_names keeps the stale-baseline scan honest under --program:
    # a filtered run must not read the other programs' baselines as stale
    rows, findings = audit_costs(progs, baseline_dir=args.baseline_dir,
                                 update=args.update,
                                 registry_names=registry_names)
    print(render_table(rows))
    if args.update:
        print(f"skelly-scope: {len(rows)} baseline(s) written under "
              f"{args.baseline_dir or 'obs/baselines/'}")
    for f in findings:
        print(f.render())
    if findings:
        # exit 1 with or without --check — the status really does mirror
        # skelly-lint/skelly-audit (a drift must never ride a 0 out of a
        # scripted run); --check remains the CI gate's explicit spelling
        print(f"skelly-scope: {len(findings)} cost finding(s) across "
              f"{len(progs)} program(s). Fix the program, or re-baseline "
              "deliberately (`obs cost --update`, docs/observability.md).",
              file=sys.stderr)
        return 1
    print(f"skelly-scope: {len(progs)} program(s) within cost baselines.")
    return 0


def _cmd_flight(args) -> int:
    import os

    from .flight import render_flight_report

    missing = [p for p in args.files if not os.path.exists(p)]
    if missing:
        print(f"skelly-flight: no such file(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2
    print(render_flight_report(args.files), end="")
    return 0


def _cmd_profile(args) -> int:
    import json as json_mod

    from . import profile as profile_mod

    try:
        trace = profile_mod.load_device_trace(args.dir)
    except FileNotFoundError as e:
        print(f"skelly-pulse: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json_mod.dumps(profile_mod.profile_json(trace)))
    else:
        print(profile_mod.render_table(trace, by=args.by), end="")
    return 0


def _cmd_roofline(args) -> int:
    import json as json_mod

    from . import roofline as roofline_mod

    try:
        doc = roofline_mod.roofline_report(
            args.dir, program=args.program, cost_table=args.cost_table,
            device_kind=args.device_kind, executions=args.executions,
            n_devices=args.n_devices)
    except (FileNotFoundError, KeyError) as e:
        msg = e.args[0] if e.args else e
        print(f"skelly-roofline: {msg}", file=sys.stderr)
        return 2
    if args.json:
        print(json_mod.dumps(doc))
    else:
        print(roofline_mod.render_roofline(doc), end="")
    return 0


def _cmd_campaign(args) -> int:
    import json as json_mod
    import os

    from . import perf as perf_mod

    if not os.path.exists(args.file):
        print(f"skelly-roofline: no such manifest: {args.file}",
              file=sys.stderr)
        return 2
    try:
        doc = perf_mod.load_campaign(args.file)
    except Exception as e:
        print(f"skelly-roofline: unreadable manifest: {e}", file=sys.stderr)
        return 2
    errors = perf_mod.validate_campaign(doc)
    if errors:
        for err in errors:
            print(f"skelly-roofline: invalid manifest: {err}",
                  file=sys.stderr)
        return 2
    if args.json:
        print(json_mod.dumps(doc))
    else:
        print(perf_mod.render_campaign(doc), end="")
    return 1 if (doc.get("gate") or {}).get("rc") == 1 else 0


def _cmd_timeline(args) -> int:
    import os

    from . import timeline as timeline_mod

    missing = [p for p in args.traces if not os.path.exists(p)]
    if missing:
        print(f"skelly-pulse: no such trace file(s): "
              f"{', '.join(missing)}", file=sys.stderr)
        return 2
    try:
        counts = timeline_mod.write_timeline(args.traces, args.out,
                                             profile_dir=args.profile)
    except FileNotFoundError as e:
        print(f"skelly-pulse: {e}", file=sys.stderr)
        return 2
    print(f"skelly-pulse: {args.out}: {counts['events']} events "
          f"({counts['host_slices']} host slices, {counts['instants']} "
          f"instants, {counts['device_slices']} device slices) — open in "
          "ui.perfetto.dev or chrome://tracing")
    return 0


def _cmd_perf(args) -> int:
    import json as json_mod

    from . import perf as perf_mod

    if not args.compare:
        print("skelly-pulse: perf needs --compare DIR", file=sys.stderr)
        return 2
    try:
        if args.json:
            # exit-code contract shared with the text path via report_json
            # (2 = no rounds, 1 = gated regression) — a --json CI wiring
            # must fail exactly when the text gate would
            doc, rc = perf_mod.report_json(args.compare,
                                           gate_pct=args.gate)
            print(json_mod.dumps(doc, indent=1))
            return rc
        report, rc = perf_mod.render_report(args.compare,
                                            gate_pct=args.gate)
    except FileNotFoundError as e:
        print(f"skelly-pulse: {e}", file=sys.stderr)
        return 2
    print(report, end="")
    return rc


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m skellysim_tpu.obs",
        description="skelly-scope: runtime telemetry — span/compile event "
                    "summaries, the program cost gate, device-time "
                    "attribution, merged timelines, and the bench-history "
                    "gate (docs/observability.md).")
    sub = parser.add_subparsers(dest="cmd")

    p_sum = sub.add_parser(
        "summarize", help="render telemetry/metrics JSONL file(s) into "
                          "span/compile/lane/convergence tables")
    p_sum.add_argument("files", nargs="+", metavar="JSONL")

    p_flight = sub.add_parser(
        "flight", help="skelly-flight blast-radius report: diagnostics "
                       "trajectory into each fault + anomaly provenance "
                       "(offender field/fiber/node) from metrics/"
                       "telemetry JSONL")
    p_flight.add_argument("files", nargs="+", metavar="JSONL")

    p_prof = sub.add_parser(
        "profile", help="attribute a --profile dump's device op time to "
                        "named phases (docs/observability.md)")
    p_prof.add_argument("dir", metavar="DIR",
                        help="jax.profiler.trace dump directory")
    p_prof.add_argument("--by", default="phase",
                        choices=("phase", "collective", "op"),
                        help="grouping for the attribution table")
    p_prof.add_argument("--json", action="store_true",
                        help="machine-readable report (all groupings)")

    p_roof = sub.add_parser(
        "roofline", help="per-phase roofline attribution over a --profile "
                         "dump: achieved vs peak, AI, bound verdicts "
                         "(docs/observability.md \"Roofline\")")
    p_roof.add_argument("dir", metavar="DIR",
                        help="jax.profiler.trace dump directory")
    p_roof.add_argument("--program", default=None, metavar="NAME",
                        help="registered program whose cost baseline "
                             "(obs/baselines/) + audit contract size the "
                             "flops/bytes and collective traffic")
    p_roof.add_argument("--cost-table", default=None, metavar="TOML",
                        help="standalone cost-table sidecar ([cost] + "
                             "[collectives.*] max_bytes) overriding "
                             "--program")
    p_roof.add_argument("--device-kind", default=None, metavar="KIND",
                        help="device kind to rate against (default: the "
                             "dump's provenance.json sidecar; unknown "
                             "kinds rate as 'unrated')")
    p_roof.add_argument("--executions", type=int, default=1, metavar="N",
                        help="timed program executions inside the "
                             "profiling window (default 1)")
    p_roof.add_argument("--n-devices", type=int, default=None, metavar="D",
                        help="device lanes in the window (default: "
                             "distinct trace pids)")
    p_roof.add_argument("--json", action="store_true")

    p_camp = sub.add_parser(
        "campaign", help="validate + render a bench.py --campaign "
                         "manifest (CAMPAIGN_rNN.json); exit 2 invalid, "
                         "1 when the recorded gate failed")
    p_camp.add_argument("file", metavar="FILE",
                        help="path to a CAMPAIGN_rNN.json manifest")
    p_camp.add_argument("--json", action="store_true")

    p_tl = sub.add_parser(
        "timeline", help="merge telemetry JSONL (+ profiler dump) into one "
                         "perfetto/chrome-trace JSON")
    p_tl.add_argument("traces", nargs="+", metavar="TRACE.jsonl")
    p_tl.add_argument("--profile", default=None, metavar="DIR",
                      help="profiler dump dir for the device-phase track")
    p_tl.add_argument("-o", "--out", required=True,
                      help="output path (e.g. out.perfetto.json)")

    p_perf = sub.add_parser(
        "perf", help="bench-history regression gate over archived "
                     "<GROUP>_rNN.json rounds")
    p_perf.add_argument("--compare", metavar="DIR",
                        help="bench artifact directory (benchmarks/)")
    p_perf.add_argument("--gate", type=float, default=25.0, metavar="PCT",
                        help="regression tolerance percent on gated "
                             "metrics (default 25; downscaled rounds "
                             "warn instead of failing)")
    p_perf.add_argument("--json", action="store_true")

    p_cost = sub.add_parser(
        "cost", help="measure every auditable program's XLA cost/memory "
                     "analysis; --check gates against obs/baselines/")
    p_cost.add_argument("--check", action="store_true",
                        help="the CI gate's explicit spelling (findings "
                             "exit 1 with or without it: drift, uncovered "
                             "program, stale baseline)")
    p_cost.add_argument("--update", action="store_true",
                        help="rewrite baselines from the current "
                             "measurements (preserves tol_pct/suppress)")
    p_cost.add_argument("--program", action="append", default=None,
                        metavar="NAME", help="restrict to this program "
                                             "(repeatable)")
    p_cost.add_argument("--baseline-dir", default=None,
                        help="baseline directory (default: obs/baselines/)")

    args = parser.parse_args(argv)
    if args.cmd == "summarize":
        return _cmd_summarize(args)
    if args.cmd == "flight":
        return _cmd_flight(args)
    if args.cmd == "profile":
        return _cmd_profile(args)
    if args.cmd == "roofline":
        return _cmd_roofline(args)
    if args.cmd == "campaign":
        return _cmd_campaign(args)
    if args.cmd == "timeline":
        return _cmd_timeline(args)
    if args.cmd == "perf":
        return _cmd_perf(args)
    if args.cmd == "cost":
        if args.check and args.update:
            print("skelly-scope: --check and --update are mutually "
                  "exclusive", file=sys.stderr)
            return 2
        return _cmd_cost(args)
    parser.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
