"""Device-time attribution from `jax.profiler` dumps (skelly-pulse).

`--profile DIR` wraps a run in `jax.profiler.trace(DIR)`, which drops a
TensorBoard profile bundle nobody in the tree could read until now: a
Chrome trace-event JSON (`*.trace.json.gz` — per-op device execution
events) and an XSpace protobuf (`*.xplane.pb`) that EMBEDS the optimized
HLO of every profiled module. This module joins the two into per-phase
device-time totals:

* the trace events carry each executed op's wall time but only its HLO
  instruction name (``dot.3``, ``fusion.17``);
* the HLO proto's per-instruction ``metadata.op_name`` carries the
  `jax.named_scope` path the tracing code declared
  (``jit(step)/.../prep/dot_general``) — the hot pipeline threads the
  phase vocabulary below through every layer (`system/system.py`,
  `solver/gmres.py`, `parallel/spmd.py`, `parallel/ring.py`,
  `ops/treecode.py`).

Folding device op time onto the scope path gives the table ROADMAP item 2
needs: where a d8 coupled solve actually spends its device time, with
collectives split by kind (the same ``all_reduce``/``all_gather``/
``collective_permute`` names the audit contracts pin).

No protobuf dependency: the XSpace/HLO containers are walked with a
~50-line protobuf wire-format reader over the handful of field numbers
involved (`XSpace.planes` -> the ``/host:metadata`` plane ->
``Hlo Proto`` stats -> `HloModuleProto.computations[].instructions[]`).
Unknown fields are skipped by wire type, so schema growth degrades to
missing metadata (reported as unattributed time), never a crash.

jax-free on purpose (json/gzip/struct only): `obs profile` and
`obs timeline` parse dumps without paying JAX backend init, like
`obs summarize`.
"""

from __future__ import annotations

import contextlib
import gzip
import json
import os
from typing import Optional

#: the named_scope phase vocabulary threaded through the hot pipeline.
#: A scope-path component is a PHASE component iff it appears here —
#: everything else in the op_name (jit(...) wrappers, transform scopes,
#: op leaf names) is attribution noise. Grow this set together with the
#: named_scope sites (docs/observability.md "Device-time attribution").
PHASE_SCOPES = frozenset({
    # System step phases (system/system.py, parallel/spmd.py)
    "prep", "gmres", "precond", "refine", "advance",
    # solver phases inside the Krylov loop (solver/gmres.py)
    "arnoldi", "gram", "givens",
    # SPMD collective phases (parallel/spmd.py, parallel/ring.py)
    "ring-step", "allgather-density", "psum-dots",
    # treecode traversal phases (ops/treecode.py)
    "upward", "near", "far",
    # spectral-Ewald pipeline phases (ops/spectral.py; "near" is shared
    # with the treecode vocabulary above)
    "spread", "fft", "kspace", "interp",
    # in-trace auxiliaries: the device DI update (scenarios/di_device.py)
    # and the jitted collision gate (system/system.py)
    "dynamic-instability", "collision",
})

#: HLO collective opcode -> the audit contract's collective kind names
#: (audit/checks.py collective-contract inventory)
COLLECTIVE_KINDS = {
    "all-reduce": "all_reduce",
    "all-gather": "all_gather",
    "collective-permute": "collective_permute",
    "all-to-all": "all_to_all",
    "reduce-scatter": "reduce_scatter",
    "collective-broadcast": "collective_broadcast",
}


# --------------------------------------------------- protobuf wire reading

def _read_varint(buf, i):
    v = 0
    shift = 0
    while True:
        b = buf[i]
        i += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, i
        shift += 7


def _fields(buf):
    """One message level -> {field_number: [values]} (ints for varints,
    bytes for length-delimited; fixed32/64 skipped). Returns None when the
    buffer does not parse as a protobuf message."""
    i, n = 0, len(buf)
    out: dict = {}
    try:
        while i < n:
            tag, i = _read_varint(buf, i)
            fnum, wtype = tag >> 3, tag & 7
            if fnum == 0 or fnum > 1 << 20:
                return None
            if wtype == 0:
                v, i = _read_varint(buf, i)
                out.setdefault(fnum, []).append(v)
            elif wtype == 2:
                ln, i = _read_varint(buf, i)
                if ln < 0 or i + ln > n:
                    return None
                out.setdefault(fnum, []).append(bytes(buf[i:i + ln]))
                i += ln
            elif wtype == 5:
                i += 4
            elif wtype == 1:
                i += 8
            else:
                return None
    except IndexError:
        return None
    return out


def _utf8(b) -> str:
    try:
        return b.decode("utf-8")
    except (UnicodeDecodeError, AttributeError):
        return ""


def _module_op_names(hlo_module: bytes) -> dict:
    """HloModuleProto bytes -> {instruction name: metadata.op_name}.

    HloModuleProto.computations = field 3 (HloComputationProto),
    HloComputationProto.instructions = field 2 (HloInstructionProto),
    HloInstructionProto.name = field 1, .metadata = field 7 (OpMetadata),
    OpMetadata.op_name = field 2 — the named_scope path."""
    out: dict = {}
    mod = _fields(hlo_module)
    if not mod:
        return out
    for comp_b in mod.get(3, []):
        comp = _fields(comp_b)
        if not comp:
            continue
        for instr_b in comp.get(2, []):
            instr = _fields(instr_b)
            if not instr or 1 not in instr or 7 not in instr:
                continue
            name = _utf8(instr[1][0])
            meta = _fields(instr[7][0])
            if not name or not meta or 2 not in meta:
                continue
            op_name = _utf8(meta[2][0])
            if op_name:
                out[name] = op_name
    return out


def load_op_name_map(xplane_path: str) -> dict:
    """{(module_name, instruction_name): scope path} from an xplane dump.

    The profiler stores each profiled module's optimized `HloProto` as a
    bytes stat (stat-metadata name ``Hlo Proto``) on the ``/host:metadata``
    plane's event metadata; the event-metadata name is
    ``module_name(program_id)``. Degrades to {} on any structural surprise
    — callers then report the time as unattributed, never crash."""
    with open(xplane_path, "rb") as fh:
        space = _fields(fh.read())
    out: dict = {}
    if not space:
        return out
    for plane_b in space.get(1, []):
        plane = _fields(plane_b)
        if not plane:
            continue
        # find the "Hlo Proto" stat-metadata id for THIS plane
        hlo_stat_ids = set()
        for sm_entry in plane.get(5, []):
            entry = _fields(sm_entry)
            if not entry or 2 not in entry:
                continue
            meta = _fields(entry[2][0])
            if meta and _utf8(meta.get(2, [b""])[0]) == "Hlo Proto":
                hlo_stat_ids.add(meta.get(1, entry.get(1, [0]))[0])
        if not hlo_stat_ids:
            continue
        for em_entry in plane.get(4, []):
            entry = _fields(em_entry)
            if not entry or 2 not in entry:
                continue
            emeta = _fields(entry[2][0])
            if not emeta:
                continue
            # "jit_f(5)" -> "jit_f" (trace events carry the bare name)
            mod_name = _utf8(emeta.get(2, [b""])[0]).rsplit("(", 1)[0]
            for stat_b in emeta.get(5, []):
                stat = _fields(stat_b)
                if (not stat or stat.get(1, [None])[0] not in hlo_stat_ids
                        or 6 not in stat):
                    continue
                hlo = _fields(stat[6][0])
                if not hlo or 1 not in hlo:
                    continue
                for instr, op_name in _module_op_names(hlo[1][0]).items():
                    out[(mod_name, instr)] = op_name
    return out


# ------------------------------------------------------- trace-event reading

def find_profile_files(profile_dir: str):
    """(trace_json_paths, xplane_paths) for the LATEST run under a
    `jax.profiler.trace` dump dir (``DIR/plugins/profile/<ts>/``); a dir
    already containing the files (or a run dir itself) works too."""
    candidates = [profile_dir]
    runs_root = os.path.join(profile_dir, "plugins", "profile")
    if os.path.isdir(runs_root):
        runs = sorted(d for d in os.listdir(runs_root)
                      if os.path.isdir(os.path.join(runs_root, d)))
        candidates = [os.path.join(runs_root, runs[-1])] if runs else []
    for cand in candidates:
        if not os.path.isdir(cand):
            continue
        names = sorted(os.listdir(cand))
        traces = [os.path.join(cand, f) for f in names
                  if f.endswith(".trace.json.gz")
                  or f.endswith(".trace.json")]
        xplanes = [os.path.join(cand, f) for f in names
                   if f.endswith(".xplane.pb")]
        if traces:
            return traces, xplanes
    return [], []


def _load_trace_events(path: str) -> list:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as fh:
        doc = json.load(fh)
    return doc.get("traceEvents", doc) if isinstance(doc, dict) else doc


def _self_times(events: list) -> list:
    """Per-event SELF durations: each complete ("X") event's duration minus
    its same-thread children's — so nested op events (fusions wrapping
    sub-ops, while bodies re-reporting region ops) never double-count.
    Returns [(event, self_dur_us)]."""
    by_tid: dict = {}
    for ev in events:
        if ev.get("ph") != "X" or "dur" not in ev or "ts" not in ev:
            continue
        by_tid.setdefault((ev.get("pid"), ev.get("tid")), []).append(ev)
    out = []
    for evs in by_tid.values():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: list = []   # (end_ts, child_sum_slot) — slot is a 1-elem list
        for ev in evs:
            ts, dur = ev["ts"], ev["dur"]
            while stack and ts >= stack[-1][0] - 1e-9:
                stack.pop()
            if stack:
                stack[-1][1][0] += dur
            slot = [0.0]
            stack.append((ts + dur, slot))
            out.append((ev, slot))
    return [(ev, max(ev["dur"] - slot[0], 0.0)) for ev, slot in out]


def phase_of(op_name: str) -> Optional[str]:
    """Slash-joined RECOGNIZED scope components of a metadata op_name, or
    None — ``jit(step)/.../gmres/precond/dot_general`` -> ``gmres/precond``.
    Dedupes immediate repeats (a scope re-entered per ring hop)."""
    comps = []
    for c in op_name.split("/"):
        if c in PHASE_SCOPES and (not comps or comps[-1] != c):
            comps.append(c)
    return "/".join(comps) if comps else None


def collective_kind(op_event_name: str) -> Optional[str]:
    """``all-reduce.17`` -> ``all_reduce`` (audit-contract spelling).

    Prefix-matches past the opcode so the TPU lowering's async pairs
    (``all-reduce-start.N`` / ``all-reduce-done.N``) and fused collective
    thunks (``all-reduce-fusion``) classify as their kind too — on real
    chips EVERY collective is async, and missing them would file all comm
    time under "(computation)"."""
    base = op_event_name.split(".")[0].split(" ")[0]
    for opcode, kind in COLLECTIVE_KINDS.items():
        if base == opcode or base.startswith(opcode + "-"):
            return kind
    return None


class DeviceTrace:
    """Aggregated per-op device time from one profile dump.

    ``rows`` is a list of dicts: op (instruction name), module, phase
    (recognized scope path or None), collective (kind or None), scope (the
    full metadata op_name when known), dur_us (summed SELF time), count.
    ``events`` keeps the raw per-execution op events (ts/dur/self_us/
    phase/...) for the timeline renderer.
    """

    def __init__(self, rows: list, events: list):
        self.rows = rows
        self.events = events

    # ------------------------------------------------------------- totals

    @property
    def total_us(self) -> float:
        return sum(r["dur_us"] for r in self.rows)

    @property
    def attributed_us(self) -> float:
        return sum(r["dur_us"] for r in self.rows if r["phase"])

    @property
    def inferred_us(self) -> float:
        return sum(r["dur_us"] for r in self.rows
                   if r["phase"] and r.get("inferred"))

    @property
    def attributed_frac(self) -> float:
        tot = self.total_us
        return (self.attributed_us / tot) if tot > 0 else 0.0

    def _group(self, key_fn) -> list:
        groups: dict = {}
        for r in self.rows:
            key = key_fn(r)
            g = groups.setdefault(key, {"dur_us": 0.0, "count": 0,
                                        "collectives": {}})
            g["dur_us"] += r["dur_us"]
            g["count"] += r["count"]
            if r["collective"]:
                g["collectives"][r["collective"]] = (
                    g["collectives"].get(r["collective"], 0.0) + r["dur_us"])
        tot = self.total_us
        out = []
        for key, g in groups.items():
            out.append({"key": key, "dur_us": round(g["dur_us"], 3),
                        "count": g["count"],
                        "share": (g["dur_us"] / tot) if tot > 0 else 0.0,
                        "collectives": {k: round(v, 3) for k, v
                                        in sorted(g["collectives"].items())}})
        out.sort(key=lambda r: -r["dur_us"])
        return out

    def by_phase(self) -> list:
        """Per-phase totals; unattributed time reported under the explicit
        ``(unattributed)`` key, never hidden."""
        return self._group(lambda r: r["phase"] or "(unattributed)")

    def by_collective(self) -> list:
        """Collectives by kind + one ``(computation)`` row for the rest —
        the comm/compute split the CA-GMRES ladder work tunes against."""
        return self._group(lambda r: r["collective"] or "(computation)")

    def by_op(self) -> list:
        return self._group(lambda r: f"{r['module']}/{r['op']}")


def load_device_trace(profile_dir: str) -> DeviceTrace:
    """Parse a `jax.profiler.trace` dump dir into a `DeviceTrace`.

    Device op events are the trace events carrying an ``hlo_op``/
    ``hlo_module`` arg (XLA executor events — host Python/runtime frames
    never carry them); their scope paths come from the xplane-embedded
    HLO metadata. Raises FileNotFoundError when the dir holds no trace."""
    traces, xplanes = find_profile_files(profile_dir)
    if not traces:
        raise FileNotFoundError(
            f"no *.trace.json(.gz) under {profile_dir!r} — is this a "
            "`--profile DIR` dump (DIR/plugins/profile/<run>/)?")
    op_names: dict = {}
    for xp in xplanes:
        try:
            op_names.update(load_op_name_map(xp))
        except Exception:
            pass   # missing metadata -> unattributed time, reported as such

    kept_events = []
    for tpath in traces:
        events = _load_trace_events(tpath)
        for ev, self_us in _self_times(events):
            args = ev.get("args") or {}
            op = args.get("hlo_op")
            module = args.get("hlo_module")
            if not op and not module:
                continue
            op = op or ev.get("name", "?")
            scope = (op_names.get((module, op))
                     or op_names.get((module, ev.get("name", ""))) or "")
            phase = phase_of(scope) if scope else None
            coll = collective_kind(ev.get("name", "")) or collective_kind(op)
            kept_events.append({
                "name": ev.get("name", op), "op": op,
                "module": module or "?", "ts": ev.get("ts", 0.0),
                "dur": ev.get("dur", 0.0), "self_us": self_us,
                "phase": phase, "inferred": False, "collective": coll,
                "pid": ev.get("pid"), "tid": ev.get("tid")})
    _infer_gap_phases(kept_events)

    agg: dict = {}
    for e in kept_events:
        key = (e["module"], e["op"], e["phase"])
        row = agg.setdefault(key, {
            "op": e["op"], "module": e["module"], "phase": e["phase"],
            "inferred": e["inferred"], "collective": e["collective"],
            "scope": op_names.get((e["module"], e["op"]), ""),
            "dur_us": 0.0, "count": 0})
        row["dur_us"] += e["self_us"]
        row["count"] += 1
    rows = sorted(agg.values(), key=lambda r: -r["dur_us"])
    for r in rows:
        r["dur_us"] = round(r["dur_us"], 3)
    return DeviceTrace(rows, kept_events)


def _infer_gap_phases(events: list) -> None:
    """Temporal-locality gap fill for metadata-less ops.

    XLA optimization renames/expands instructions (fusions, the
    triangular-solve while+dot expansion) whose names then miss the
    xplane HLO's pre-optimization metadata. The device thread executes
    serially in phase-contiguous segments, so an unmatched op whose
    nearest metadata-attributed neighbors ON BOTH SIDES (same thread)
    agree on a phase almost surely belongs to it: inherit, and mark the
    event ``inferred`` so the table reports directly-attributed and
    inferred shares separately (never silently)."""
    by_tid: dict = {}
    for e in events:
        by_tid.setdefault((e["pid"], e["tid"]), []).append(e)
    for evs in by_tid.values():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        # prev_phase[i]: phase of the nearest attributed event at or before i
        n = len(evs)
        prev_ph = [None] * n
        last = None
        for i, e in enumerate(evs):
            if e["phase"]:
                last = e["phase"]
            prev_ph[i] = last
        nxt = None
        for i in range(n - 1, -1, -1):
            e = evs[i]
            if e["phase"]:
                nxt = e["phase"]
            elif prev_ph[i] is not None and prev_ph[i] == nxt:
                e["phase"] = nxt
                e["inferred"] = True


# -------------------------------------------------------------- rendering

def render_table(trace: DeviceTrace, by: str = "phase") -> str:
    """The `obs profile` text report (docs/observability.md)."""
    groups = {"phase": trace.by_phase, "collective": trace.by_collective,
              "op": trace.by_op}[by]()
    rows = [(by, "time_ms", "share", "ops", "collectives")]
    for g in groups[:40]:
        colls = "  ".join(f"{k}={v / 1e3:.3f}ms"
                          for k, v in g["collectives"].items())
        rows.append((str(g["key"]), f"{g['dur_us'] / 1e3:.3f}",
                     f"{g['share']:.1%}", str(g["count"]), colls))
    widths = [max(len(r[i]) for r in rows) for i in range(5)]
    out = ["  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
           for r in rows]
    if len(groups) > 40:
        out.append(f"... ({len(groups) - 40} more rows; --json for all)")
    out.append("")
    tot = trace.total_us
    inf_frac = (trace.inferred_us / tot) if tot > 0 else 0.0
    out.append(f"device op time: {tot / 1e3:.3f}ms over "
               f"{sum(r['count'] for r in trace.rows)} op executions; "
               f"{trace.attributed_frac:.1%} attributed to named phases "
               f"({trace.attributed_frac - inf_frac:.1%} via HLO metadata, "
               f"{inf_frac:.1%} inferred from phase-contiguous neighbors)")
    return "\n".join(out) + "\n"


def profile_json(trace: DeviceTrace) -> dict:
    return {
        "total_us": round(trace.total_us, 3),
        "attributed_us": round(trace.attributed_us, 3),
        "inferred_us": round(trace.inferred_us, 3),
        "attributed_frac": round(trace.attributed_frac, 4),
        "by_phase": trace.by_phase(),
        "by_collective": trace.by_collective(),
        "by_op": trace.by_op(),
    }


# ---------------------------------------------------------- capture context

def _write_profile_provenance(profile_dir: str) -> None:
    """Drop a ``provenance.json`` sidecar next to the dump so jax-free
    consumers (`obs roofline`) know WHICH device the capture ran on —
    jax is live inside `profile_session`, so this is the one moment the
    device_kind is knowable without a backend init later."""
    try:
        import jax

        from .tracer import provenance

        info = provenance()
        info["backend"] = jax.default_backend()
        os.makedirs(profile_dir, exist_ok=True)
        with open(os.path.join(profile_dir, "provenance.json"), "w") as fh:
            json.dump(info, fh)
    except Exception:
        pass   # a sidecar must never fail the capture it describes


@contextlib.contextmanager
def profile_session(profile_dir: str):
    """Profiler capture tuned for device-time attribution.

    `jax.profiler.trace` captures Python host frames too
    (``python_tracer_level=1``); around a loop that COMPILES inside the
    window, those frames flood the ~1M-event trace buffer and evict the
    device op events this parser needs (observed: a 2-step `System.run`
    produced 1,000,027 events with ZERO surviving ``hlo_op`` args). This
    context creates the profiler session with the Python tracer OFF and
    ``enable_hlo_proto`` on — host-side timing is the span tracer's job
    (docs/observability.md), the profiler's is the device. Falls back to
    plain `jax.profiler.trace` when the options API is unavailable.

    jax imports stay inside the context so module import remains jax-free.
    """
    import jax

    try:
        from jax._src.lib import xla_client

        import jax.extend.backend as jax_backend

        jax_backend.get_backend()   # TPU tracer needs an initialized backend
        opts = xla_client.profiler.ProfileOptions()
        opts.python_tracer_level = 0
        opts.enable_hlo_proto = True
        sess = xla_client.profiler.ProfilerSession(opts)
    except Exception:
        try:
            with jax.profiler.trace(str(profile_dir)):
                yield
        finally:
            _write_profile_provenance(str(profile_dir))
        return
    try:
        yield
    finally:
        sess.export(sess.stop(), str(profile_dir))
        _write_profile_provenance(str(profile_dir))


# ------------------------------------------------- telemetry-stream bridge

def device_phase_events(profile_dir: str) -> list:
    """The ``device_phase`` telemetry records for a profile dump: one per
    phase (incl. ``(unattributed)``) with ``dur_s``/``share``/``ops`` and
    the per-kind collective split. Appended to the run's `--trace-file` by
    the CLIs so `obs summarize` renders device time next to host spans."""
    trace = load_device_trace(profile_dir)
    out = []
    for g in trace.by_phase():
        out.append({"phase": g["key"], "dur_s": round(g["dur_us"] / 1e6, 6),
                    "share": round(g["share"], 4), "ops": g["count"],
                    "collectives": {k: round(v / 1e6, 6)
                                    for k, v in g["collectives"].items()}})
    return out


def emit_device_phases(profile_dir: str, tracer=None) -> int:
    """Parse ``profile_dir`` and emit one ``device_phase`` event per phase
    into ``tracer`` (or the process-active tracer). Returns the number of
    events emitted; swallows parse errors (a broken profiler dump must
    never fail the run that produced it) but NOT tracer write errors."""
    from . import tracer as obs_tracer

    tr = tracer if tracer is not None else obs_tracer.active()
    if tr is None:
        return 0
    try:
        events = device_phase_events(profile_dir)
    except Exception as e:
        tr.emit("device_phase_error", error=f"{type(e).__name__}: {e}",
                profile_dir=str(profile_dir))
        return 0
    for rec in events:
        tr.emit("device_phase", **rec)
    return len(events)
