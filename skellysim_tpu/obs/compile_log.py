"""Compile observer: a jit wrapper that turns traces/compiles into events.

skelly-scope's second leg. skelly-audit's retrace-budget check and
`testing.trace_counting_jit` catch retraces in TESTS; this wrapper makes
them visible at RUNTIME: every call that triggered a fresh trace of the
wrapped function emits one ``compile`` event into the active tracer
(`obs.tracer`) with the program name, the call's wall time (trace + XLA
compile + first execution — the full first-call cost a user experiences),
the donated argument positions, and the argument shape/dtype signature. A
retrace on the hot path then shows up in the `obs summarize` timeline with
the signature that caused it, instead of only failing a budget after the
fact.

`ObservedJit` is drop-in for `jax.jit` where the codebase already has a
wrapper seam: `System.__init__`'s jits, `parallel.spmd.build_spmd_step`'s
``jit_wrapper=`` parameter, and `ensemble.EnsembleRunner`'s step jit all
route through it. `.trace()` / `.lower()` pass through to the underlying
jit, so `audit.registry.built_from` keeps working on wrapped entry points.
Overhead with no active tracer: one counter comparison per call.
"""

from __future__ import annotations

import functools
import time

from . import tracer as _tracer

#: dtype -> short signature token (fallback: the dtype's own name)
_DTYPE_SHORT = {"float64": "f64", "float32": "f32", "bfloat16": "bf16",
                "float16": "f16", "int64": "i64", "int32": "i32",
                "uint32": "u32", "bool": "b1", "complex64": "c64",
                "complex128": "c128"}

#: signature leaves beyond this many are elided (huge pytrees — a SimState
#: has dozens of leaves; the first ones carry the discriminating shapes)
_SIG_MAX_LEAVES = 16


def _cache_active() -> bool:
    """True when a persistent XLA compilation cache directory is set —
    the compile event's cold-vs-cache-served discriminator."""
    import jax

    try:
        return bool(jax.config.jax_compilation_cache_dir)
    except AttributeError:
        return False


def arg_signature(args, kwargs) -> str:
    """Compact shape/dtype signature of a call's pytree leaves, e.g.
    ``f64[16,16,3],f64[],i32[16]`` — the retrace-diagnosis payload."""
    import jax

    leaves = jax.tree_util.tree_leaves((args, kwargs))
    toks = []
    for leaf in leaves[:_SIG_MAX_LEAVES]:
        dt = getattr(leaf, "dtype", None)
        shape = getattr(leaf, "shape", None)
        if dt is not None and shape is not None:
            short = _DTYPE_SHORT.get(str(dt), str(dt))
            toks.append(f"{short}[{','.join(str(d) for d in shape)}]")
        else:
            toks.append(type(leaf).__name__)
    if len(leaves) > _SIG_MAX_LEAVES:
        toks.append(f"+{len(leaves) - _SIG_MAX_LEAVES} more")
    return ",".join(toks)


class ObservedJit:
    """`jax.jit` twin that reports each fresh trace as a ``compile`` event.

    Same trace-counting approach as `testing.trace_counting_jit` (the
    wrapped Python body runs exactly once per trace); the counter doubles
    as the runtime's own retrace detector via ``trace_count``.
    """

    def __init__(self, fn, *, name: str | None = None, **jit_kwargs):
        import jax

        self.name = name or getattr(fn, "__name__", "jit")
        self._count = 0
        self._trace_s = 0.0

        @functools.wraps(fn)
        def counting(*args, **kwargs):
            t0 = time.perf_counter()
            self._count += 1
            out = fn(*args, **kwargs)
            # tracing time only (compile happens after the trace returns)
            self._trace_s = time.perf_counter() - t0
            return out

        self._jitted = jax.jit(counting, **jit_kwargs)
        donated = jit_kwargs.get("donate_argnums", ())
        self._donated = list(donated if isinstance(donated, (tuple, list))
                             else (donated,))

    def __call__(self, *args, **kwargs):
        tr = _tracer.active()
        if tr is None:
            return self._jitted(*args, **kwargs)
        before = self._count
        t0 = time.perf_counter()
        out = self._jitted(*args, **kwargs)
        if self._count > before:
            tr.emit("compile", name=self.name,
                    wall_s=round(time.perf_counter() - t0, 6),
                    trace_s=round(self._trace_s, 6),
                    traces=self._count, donated=self._donated,
                    arg_sig=arg_signature(args, kwargs),
                    # whether a persistent XLA cache dir was active for
                    # this compile: `obs summarize` splits true cold
                    # compiles from cache-served ones on this stamp
                    persistent_cache=_cache_active())
        return out

    # audit/cost seam: `built_from` traces/lowers through the wrapper
    def trace(self, *args, **kwargs):
        return self._jitted.trace(*args, **kwargs)

    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    @property
    def trace_count(self) -> int:
        return self._count


def observed_jit(fn, *, name: str | None = None, **jit_kwargs) -> ObservedJit:
    """`jax.jit` replacement that logs compiles to the active tracer."""
    return ObservedJit(fn, name=name, **jit_kwargs)


def jit_wrapper(name: str):
    """A `build_spmd_step(jit_wrapper=...)`-compatible factory: the seam
    passes ``(fn, **jit_kwargs)``, we add the program name."""
    return lambda fn, **jit_kwargs: ObservedJit(fn, name=name, **jit_kwargs)
