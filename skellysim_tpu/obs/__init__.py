"""skelly-scope: runtime telemetry (docs/observability.md).

Four legs over one JSONL event format:

* `obs.tracer` — nestable spans + arbitrary events; the run loop, the
  ensemble scheduler, and bench.py all emit through the process-wide
  active tracer (`tracer.use` / `tracer.span` / `tracer.emit`);
* `obs.compile_log` — `observed_jit`, a `jax.jit` twin that reports every
  fresh trace/compile as an event (System/ensemble/SPMD jits route
  through it);
* `obs.cost` — XLA cost/memory analysis per auditable program, gated
  against checked-in `obs/baselines/*.toml`;
* `python -m skellysim_tpu.obs` — `summarize` (render any telemetry/
  metrics JSONL mix) and `cost [--check|--update]` (the CI drift gate).

Import-light on purpose: the obs modules themselves import jax only
lazily (span sync, compile observation, the cost gate), and `summarize`
never initializes a jax backend. NOTE the *package* import still runs
`skellysim_tpu/__init__.py`, which imports jax at module level — that is
why bench.py's jax-avoiding parent process pins its own
`TELEMETRY_VERSION` literal instead of importing this (tests/test_obs.py
cross-checks the two).
"""

from .tracer import TELEMETRY_VERSION, Tracer, active, emit, span, use

__all__ = ["TELEMETRY_VERSION", "Tracer", "active", "emit", "span", "use"]
