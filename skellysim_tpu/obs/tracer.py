"""Span tracer: one JSONL event stream for runtime telemetry.

skelly-scope's first leg (docs/observability.md). The reference instruments
its hot path with spdlog scope markers and one wall-clock timer around each
GMRES solve (`solver_hydro.cpp:81-91`); this module replaces that with a
structured event stream every surface shares: `System.run` / `_run_loop`,
the ensemble scheduler, and `bench.py` all emit through the SAME tracer, so
`python -m skellysim_tpu.obs summarize` renders run metrics, ensemble lane
churn, and bench group timings from one format.

Design constraints:

* **Import-light.** This module imports jax only lazily
  (`jax.block_until_ready`, and only when a span actually registered a
  device sync tree). Reaching it through the package still runs
  `skellysim_tpu/__init__.py`'s module-level `import jax` — which is why
  `bench.py`'s parent process (which must never import jax: the axon TPU
  plugin can wedge at client init) pins its own `TELEMETRY_VERSION`
  literal instead of importing this module; only the bench *children*
  (which import jax anyway) construct tracers.
* **Zero-cost when inactive.** The module-level `span()` / `emit()` helpers
  consult the active tracer once and no-op without one, so the run loop and
  scheduler carry their instrumentation unconditionally.
* **Device-work attribution.** XLA dispatch is async: a jit call returns
  before the device finishes, so a naive span around it undercounts by
  >100x (the `_run_loop` wall_s lesson). A span that should absorb its
  device work registers the output pytree via ``sp.sync(tree)``; the span
  blocks on it at exit, so the duration covers the device execution.

Event lines are JSON objects with common keys ``ev`` (event kind), ``ts``
(monotonic seconds, arbitrary origin — deltas only), ``pid``, ``host``.
Kinds emitted here: ``telemetry`` (stream header, carries ``version``),
``span`` (``name``, ``path`` = slash-joined open-span stack, ``dur_s``,
plus caller fields), and whatever callers pass to `emit` (``compile`` from
`obs.compile_log`, ``lane`` from the ensemble scheduler). The step records
of the run-loop/ensemble metrics JSONL (`system.METRICS_FIELDS`) carry no
``ev`` key; `obs summarize` accepts both shapes in any mix.
"""

from __future__ import annotations

import contextlib
import json
import os
import socket
import time
from typing import Optional

#: version stamp of the event schema AND the bench artifact format
#: (bench.py pins its own copy — it cannot import this module in the
#: jax-free parent process; tests/test_obs.py asserts the two agree)
TELEMETRY_VERSION = 1


def provenance(downscaled=None) -> dict:
    """The self-description stamp timelines and bench artifacts share:
    ``jax_version`` + ``device_kind`` (+ ``downscaled`` when the caller
    states it) — skelly-pulse's answer to "which hardware/runtime
    produced these numbers?" (bench artifacts used to hand-stamp
    ``telemetry_version`` only).

    jax-free-safe: consults ``sys.modules`` instead of importing — a
    process that never imported jax (bench's parent) gets ``None``
    placeholders rather than a backend init, and the tracer header stays
    zero-cost in jax-free contexts. In a process whose backend is live
    (every CLI/run/bench child), ``jax.devices()`` is already cached.
    """
    import sys

    jax = sys.modules.get("jax")
    info = {"jax_version": getattr(jax, "__version__", None)
            if jax is not None else None}
    kind = None
    if jax is not None:
        try:
            devs = jax.devices()
            kind = devs[0].device_kind if devs else None
        except Exception:
            kind = None
    info["device_kind"] = kind
    if downscaled is not None:
        info["downscaled"] = bool(downscaled)
    return info


class _Span:
    """Mutable handle yielded by `Tracer.span`: attach fields / a sync tree."""

    __slots__ = ("fields", "_sync")

    def __init__(self):
        self.fields = {}
        self._sync = None

    def note(self, **fields):
        """Attach extra fields to the span event emitted at exit."""
        self.fields.update(fields)

    def sync(self, tree):
        """Register a pytree to `jax.block_until_ready` at span exit, so the
        device work producing it is attributed to THIS span (returns the
        tree unchanged, for inline use)."""
        self._sync = tree
        return tree


class Tracer:
    """Append telemetry events to a JSONL file (or an in-memory list).

    ``path=None`` keeps events in ``self.events`` — the test/analysis mode.
    File mode appends (a resumed run extends its stream; the header line
    re-stamps the segment) and flushes per event: a crashed run keeps every
    event up to the crash.
    """

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.events = [] if path is None else None
        self._fh = open(path, "a") if path else None
        self._stack: list[str] = []
        self._pid = os.getpid()
        try:
            self._host = socket.gethostname()
        except Exception:
            self._host = "unknown"
        # header carries the provenance stamp: a telemetry stream is
        # self-describing about runtime + hardware (None placeholders in
        # jax-free processes — provenance() never imports jax itself)
        self.emit("telemetry", version=TELEMETRY_VERSION, **provenance())

    # ------------------------------------------------------------------ emit

    def emit(self, ev: str, **fields):
        rec = {"ev": ev, "ts": round(time.perf_counter(), 6),
               "pid": self._pid, "host": self._host}
        rec.update(fields)
        if self._fh is not None:
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()
        else:
            self.events.append(rec)

    @contextlib.contextmanager
    def span(self, name: str, **fields):
        """Nestable timed scope; emits ONE ``span`` event at exit whose
        ``path`` is the slash-joined stack of open spans (attribution) and
        whose ``dur_s`` includes any registered device sync."""
        sp = _Span()
        self._stack.append(name)
        t0 = time.perf_counter()
        try:
            yield sp
        finally:
            try:
                if sp._sync is not None:
                    import jax

                    jax.block_until_ready(sp._sync)
            finally:
                dur = time.perf_counter() - t0
                path = "/".join(self._stack)
                self._stack.pop()
                self.emit("span", name=name, path=path,
                          dur_s=round(dur, 6), **{**fields, **sp.fields})

    # ----------------------------------------------------------- lifecycle

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ------------------------------------------------------- active-tracer state

#: the process-wide active tracer; instrumented code paths (run loop,
#: scheduler, compile observer) consult it through `active()` so telemetry
#: is a no-op until someone installs one via `use()`
_ACTIVE: Optional[Tracer] = None


def active() -> Optional[Tracer]:
    return _ACTIVE


@contextlib.contextmanager
def use(tracer: Optional[Tracer]):
    """Install ``tracer`` as the process-wide active tracer for the block
    (``None`` is allowed and keeps telemetry off — callers need no branch)."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = tracer
    try:
        yield tracer
    finally:
        _ACTIVE = prev


_NULL_SPAN = _Span()


@contextlib.contextmanager
def _null_span():
    # a fresh-enough dummy: note()/sync() write into a shared throwaway
    _NULL_SPAN.fields.clear()
    _NULL_SPAN._sync = None
    yield _NULL_SPAN


def span(name: str, **fields):
    """`Tracer.span` on the active tracer, or an inert span when telemetry
    is off — instrumentation sites never branch."""
    tr = _ACTIVE
    if tr is None:
        return _null_span()
    return tr.span(name, **fields)


def emit(ev: str, **fields):
    """`Tracer.emit` on the active tracer; no-op when telemetry is off."""
    tr = _ACTIVE
    if tr is not None:
        tr.emit(ev, **fields)
