"""Per-phase roofline attribution over a profiler dump (skelly-roofline).

`obs profile` answers WHERE a step spends device time (per-phase walls,
`obs.profile`); `obs cost` pins WHAT each program costs statically
(flops / bytes_accessed / peak_bytes, `obs/baselines/*.toml`); the audit
contracts pin HOW MUCH each collective kind may move (`max_bytes`,
`audit/contracts/*.toml`). This module joins the three against a
checked-in device-peak table (`obs/device_peaks.toml`, keyed by the
`device_kind` provenance every artifact carries) into the roofline
question per phase: achieved FLOP/s and bytes/s, arithmetic intensity,
a compute-/memory-/comms-bound verdict, and an MFU-style
achieved-vs-peak ratio — with ICI utilization DERIVED from the pinned
collective byte bounds, not guessed.

Attribution model (stated, not hidden):

* XLA's `cost_analysis()` publishes PROGRAM totals, not per-op tables
  (the trace events carry only ``hlo_module``/``hlo_op``), so per-phase
  flops/bytes are the program totals apportioned over the measured
  per-phase COMPUTE self-time (wall minus collective time). Phase
  arithmetic intensity therefore inherits the program's static
  intensity; the per-phase differentiation comes from the measured
  comm/compute split and the per-phase walls.
* Collective bytes per executed op are the audit contract's ``max_bytes``
  pin for that kind — an upper bound, so ICI utilization is a ceiling.
* Walls sum over device lanes; flops scale with ``n_devices`` (the cost
  tables are per-shard SPMD modules), so achieved rates are PER-CHIP and
  compare directly against the per-chip peaks.
* ``executions`` is the number of timed program executions inside the
  profiling window (default 1 — exactly the d2 acceptance capture).

Unknown device kinds rate as ``unrated``: comms-bound verdicts (a
measured fact) survive, compute/memory verdicts and achieved-vs-peak
ratios degrade to None — never a crash.

jax-free like `obs.profile`: json/toml parsing only, no backend init.
"""

from __future__ import annotations

import json
import os
from typing import Optional

DEVICE_PEAKS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "device_peaks.toml")

#: contract dir the collective byte bounds come from (audit/contracts/)
CONTRACT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "audit", "contracts")

#: the verdict vocabulary (docs/observability.md "Roofline")
VERDICTS = ("compute-bound", "memory-bound", "comms-bound", "unrated")

#: a phase is comms-bound when collectives take more than half its wall
COMM_BOUND_FRAC = 0.5

#: keys every device_peaks.toml row must carry
PEAK_KEYS = ("peak_flops", "hbm_gbps", "ici_gbps")


def _load_toml(path: str) -> dict:
    from ..config import toml_io

    return toml_io.load(path)


# ------------------------------------------------------------- input tables

def load_device_peaks(path: Optional[str] = None) -> dict:
    """{device_kind key: {peak_flops, hbm_gbps, ici_gbps}} from the
    checked-in rating table."""
    return dict(_load_toml(path or DEVICE_PEAKS_PATH).get("device") or {})


def peaks_for_kind(device_kind, table: Optional[dict] = None):
    """(matched key | None, peaks dict | None) — case-insensitive
    SUBSTRING match, longest key wins ("TPU v5p" beats "TPU v5").
    Unknown/missing kinds return (None, None): the unrated path."""
    if not device_kind:
        return None, None
    if table is None:
        table = load_device_peaks()
    kind = str(device_kind).lower()
    best_key, best_peaks = None, None
    for key, peaks in table.items():
        if key.lower() in kind and (best_key is None
                                    or len(key) > len(best_key)):
            best_key, best_peaks = key, peaks
    if best_peaks is not None and not all(k in best_peaks
                                          for k in PEAK_KEYS):
        return None, None   # malformed row degrades to unrated, not a crash
    return best_key, best_peaks


def load_cost_table(program: str,
                    baseline_dir: Optional[str] = None) -> Optional[dict]:
    """The program's checked-in static cost table
    (`obs/baselines/<program>.toml` ``[cost]``) or None — reading the
    committed baseline keeps this path jax-free (no compile needed)."""
    from .cost import baseline_path

    path = baseline_path(program, baseline_dir)
    if not os.path.exists(path):
        return None
    cost = _load_toml(path).get("cost")
    return dict(cost) if isinstance(cost, dict) else None


def load_collective_bytes(program: str,
                          contract_dir: Optional[str] = None) -> dict:
    """{collective kind: max_bytes} from the program's audit contract —
    the pinned per-op operand bound ICI utilization derives from."""
    path = os.path.join(contract_dir or CONTRACT_DIR, f"{program}.toml")
    if not os.path.exists(path):
        return {}
    out = {}
    for kind, spec in (_load_toml(path).get("collectives") or {}).items():
        if isinstance(spec, dict) and "max_bytes" in spec:
            out[kind] = float(spec["max_bytes"])
    return out


def load_cost_sidecar(path: str):
    """(cost dict | None, {kind: max_bytes}) from a standalone cost-table
    TOML (``[cost]`` + optional ``[collectives.<kind>] max_bytes``) — the
    `--cost-table` override for fixtures and unregistered programs."""
    data = _load_toml(path)
    cost = data.get("cost")
    coll = {k: float(v["max_bytes"])
            for k, v in (data.get("collectives") or {}).items()
            if isinstance(v, dict) and "max_bytes" in v}
    return (dict(cost) if isinstance(cost, dict) else None), coll


def load_profile_provenance(profile_dir: str) -> dict:
    """The ``provenance.json`` sidecar `profile_session` drops next to the
    dump (jax_version/device_kind/backend); {} when absent."""
    try:
        with open(os.path.join(profile_dir, "provenance.json")) as fh:
            doc = json.load(fh)
        return doc if isinstance(doc, dict) else {}
    except Exception:
        return {}


# ------------------------------------------------------------ the roofline

def _phase_groups(trace) -> list:
    """Per-phase rollup KEEPING per-kind collective counts (by_phase()
    only keeps durations; counts size the comm bytes)."""
    groups: dict = {}
    for r in trace.rows:
        key = r["phase"] or "(unattributed)"
        g = groups.setdefault(key, {"phase": key, "dur_us": 0.0, "ops": 0,
                                    "collectives": {}})
        g["dur_us"] += r["dur_us"]
        g["ops"] += r["count"]
        if r["collective"]:
            c = g["collectives"].setdefault(
                r["collective"], {"dur_us": 0.0, "count": 0})
            c["dur_us"] += r["dur_us"]
            c["count"] += r["count"]
    out = sorted(groups.values(), key=lambda g: -g["dur_us"])
    return out


def analyze(trace, cost: Optional[dict] = None,
            collective_bytes: Optional[dict] = None,
            peaks: Optional[dict] = None,
            executions: int = 1,
            n_devices: Optional[int] = None) -> dict:
    """The roofline join over a parsed `DeviceTrace` — pure math, every
    input injectable (the oracle tests drive this directly)."""
    collective_bytes = collective_bytes or {}
    if n_devices is None:
        pids = {e.get("pid") for e in trace.events}
        n_devices = max(1, len(pids)) if pids else 1
    executions = max(int(executions), 1)

    flops_total = float(cost["flops"]) if cost and "flops" in cost else None
    bytes_total = (float(cost["bytes_accessed"])
                   if cost and "bytes_accessed" in cost else None)
    ai = (flops_total / bytes_total
          if flops_total is not None and bytes_total else None)

    peak_flops = peak_bps = ici_bps = ridge = None
    if peaks is not None:
        peak_flops = float(peaks["peak_flops"])
        peak_bps = float(peaks["hbm_gbps"]) * 1e9
        ici_bps = float(peaks["ici_gbps"]) * 1e9
        ridge = peak_flops / peak_bps if peak_bps else None

    groups = _phase_groups(trace)
    total_us = trace.total_us
    total_compute_us = sum(
        max(g["dur_us"] - sum(c["dur_us"] for c in g["collectives"].values()),
            0.0) for g in groups)

    phases = []
    classified_us = 0.0
    for g in groups:
        wall_us = g["dur_us"]
        if wall_us <= 0:
            continue
        comm_us = sum(c["dur_us"] for c in g["collectives"].values())
        compute_us = max(wall_us - comm_us, 0.0)
        comm_frac = comm_us / wall_us
        # per-chip wall of this phase inside the window (lane-summed / lanes)
        wall_chip_s = wall_us * 1e-6 / n_devices

        frac = (compute_us / total_compute_us) if total_compute_us > 0 else 0.0
        flops = flops_total * executions * frac if flops_total is not None else None
        bytes_ = bytes_total * executions * frac if bytes_total is not None else None
        achieved_fps = (flops / wall_chip_s
                        if flops is not None and wall_chip_s > 0 else None)
        achieved_bps = (bytes_ / wall_chip_s
                        if bytes_ is not None and wall_chip_s > 0 else None)

        # comm bytes from the pinned per-op bounds: count * max_bytes per
        # kind; kinds without a pin stay unsized (ici rate from sized only)
        comm_bytes = 0.0
        unsized = []
        colls = {}
        for kind, c in sorted(g["collectives"].items()):
            b = collective_bytes.get(kind)
            colls[kind] = {"dur_us": round(c["dur_us"], 3),
                           "count": c["count"],
                           "bytes": (c["count"] * b) if b is not None
                           else None}
            if b is None:
                unsized.append(kind)
            else:
                comm_bytes += c["count"] * b
        comm_bps = (comm_bytes / (comm_us * 1e-6)
                    if comm_bytes and comm_us > 0 else None)

        if comm_frac > COMM_BOUND_FRAC:
            verdict = "comms-bound"
            vs_peak = (comm_bps / ici_bps
                       if comm_bps is not None and ici_bps else None)
        elif ai is None or ridge is None:
            verdict = "unrated"
            vs_peak = None
        elif ai >= ridge:
            verdict = "compute-bound"
            vs_peak = (achieved_fps / peak_flops
                       if achieved_fps is not None and peak_flops else None)
        else:
            verdict = "memory-bound"
            vs_peak = (achieved_bps / peak_bps
                       if achieved_bps is not None and peak_bps else None)

        if (g["phase"] != "(unattributed)"
                and verdict != "unrated" and vs_peak is not None):
            classified_us += wall_us

        phases.append({
            "phase": g["phase"],
            "wall_us": round(wall_us, 3),
            "share": round(wall_us / total_us, 4) if total_us > 0 else 0.0,
            "ops": g["ops"],
            "comm_us": round(comm_us, 3),
            "comm_frac": round(comm_frac, 4),
            "flops": round(flops, 1) if flops is not None else None,
            "bytes": round(bytes_, 1) if bytes_ is not None else None,
            "ai": round(ai, 4) if ai is not None else None,
            "achieved_flops_per_s": (round(achieved_fps, 1)
                                     if achieved_fps is not None else None),
            "achieved_bytes_per_s": (round(achieved_bps, 1)
                                     if achieved_bps is not None else None),
            "comm_bytes": round(comm_bytes, 1) if comm_bytes else 0.0,
            "ici_bytes_per_s": (round(comm_bps, 1)
                                if comm_bps is not None else None),
            "unsized_collectives": unsized,
            "collectives": colls,
            "verdict": verdict,
            "achieved_vs_peak": (round(vs_peak, 6)
                                 if vs_peak is not None else None),
        })

    # window totals: the MFU-style per-chip utilization of the whole step
    window_chip_s = total_us * 1e-6 / n_devices
    tot_fps = (flops_total * executions / window_chip_s
               if flops_total is not None and window_chip_s > 0 else None)
    tot_bps = (bytes_total * executions / window_chip_s
               if bytes_total is not None and window_chip_s > 0 else None)
    totals = {
        "achieved_flops_per_s": (round(tot_fps, 1)
                                 if tot_fps is not None else None),
        "achieved_bytes_per_s": (round(tot_bps, 1)
                                 if tot_bps is not None else None),
        "mfu": (round(tot_fps / peak_flops, 6)
                if tot_fps is not None and peak_flops else None),
        "hbm_util": (round(tot_bps / peak_bps, 6)
                     if tot_bps is not None and peak_bps else None),
        "comm_us": round(sum(p["comm_us"] for p in phases), 3),
    }
    return {
        "total_us": round(total_us, 3),
        "attributed_frac": round(trace.attributed_frac, 4),
        "classified_frac": (round(classified_us / total_us, 4)
                            if total_us > 0 else 0.0),
        "n_devices": n_devices,
        "executions": executions,
        "ai": round(ai, 4) if ai is not None else None,
        "ridge_flops_per_byte": round(ridge, 4) if ridge is not None else None,
        "peak_memory_bytes": (int(cost["peak_bytes"])
                              if cost and "peak_bytes" in cost else None),
        "phases": phases,
        "totals": totals,
    }


def roofline_report(profile_dir: str, program: Optional[str] = None,
                    cost_table: Optional[str] = None,
                    device_kind: Optional[str] = None,
                    executions: int = 1,
                    n_devices: Optional[int] = None,
                    baseline_dir: Optional[str] = None,
                    contract_dir: Optional[str] = None,
                    peaks_path: Optional[str] = None) -> dict:
    """The `obs roofline DIR` document: parse the dump, resolve the cost
    table (``--cost-table`` sidecar > ``--program`` baseline+contract),
    resolve device_kind (flag > the dump's provenance sidecar), rate
    against the peak table, and run `analyze`.

    Raises FileNotFoundError for a missing dump; an unknown program (no
    baseline) raises KeyError — the CLI maps both to exit 2. Unknown
    device kinds are NOT errors: they rate as unrated."""
    from .profile import load_device_trace

    trace = load_device_trace(profile_dir)

    cost, coll = None, {}
    if cost_table:
        if not os.path.exists(cost_table):
            raise FileNotFoundError(f"no cost table at {cost_table!r}")
        cost, coll = load_cost_sidecar(cost_table)
    elif program:
        cost = load_cost_table(program, baseline_dir)
        if cost is None:
            raise KeyError(
                f"no cost baseline for program {program!r} under "
                f"obs/baselines/ — run `python -m skellysim_tpu.obs cost "
                "--update` or pass --cost-table")
        coll = load_collective_bytes(program, contract_dir)

    provenance = load_profile_provenance(profile_dir)
    kind = device_kind or provenance.get("device_kind")
    rated_as, peaks = peaks_for_kind(kind, load_device_peaks(peaks_path))

    doc = analyze(trace, cost=cost, collective_bytes=coll, peaks=peaks,
                  executions=executions, n_devices=n_devices)
    doc.update({
        "profile_dir": str(profile_dir),
        "program": program,
        "device_kind": kind,
        "rated_as": rated_as,
        "peaks": dict(peaks) if peaks else None,
        "provenance": provenance or None,
    })
    return doc


# -------------------------------------------------------------- rendering

def _fmt_rate(v, unit: str) -> str:
    if v is None:
        return "-"
    for scale, prefix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(v) >= scale:
            return f"{v / scale:.2f}{prefix}{unit}"
    return f"{v:.2f}{unit}"


def render_roofline(doc: dict) -> str:
    """The `obs roofline` text report (docs/observability.md)."""
    rows = [("phase", "time_ms", "share", "verdict", "vs-peak", "comm%",
             "flop/s", "B/s", "ici B/s")]
    for p in doc["phases"]:
        rows.append((
            p["phase"], f"{p['wall_us'] / 1e3:.3f}", f"{p['share']:.1%}",
            p["verdict"],
            ("-" if p["achieved_vs_peak"] is None
             else f"{p['achieved_vs_peak']:.2%}"),
            f"{p['comm_frac']:.0%}",
            _fmt_rate(p["achieved_flops_per_s"], "F/s"),
            _fmt_rate(p["achieved_bytes_per_s"], "B/s"),
            _fmt_rate(p["ici_bytes_per_s"], "B/s"),
        ))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    out = ["  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
           for r in rows]
    out.append("")
    kind = doc.get("device_kind") or "unknown"
    rating = (f"rated as {doc['rated_as']!r}" if doc.get("rated_as")
              else "UNRATED (no device_peaks.toml row — verdicts from the "
                   "comm/compute split only)")
    out.append(f"device_kind: {kind} — {rating}; "
               f"{doc['n_devices']} device lane(s), "
               f"{doc['executions']} execution(s)")
    if doc.get("ai") is not None:
        ridge = doc.get("ridge_flops_per_byte")
        out.append(f"program intensity: {doc['ai']:g} flop/byte"
                   + (f" (ridge {ridge:g})" if ridge is not None else "")
                   + (f"; static peak memory {doc['peak_memory_bytes']:,} B"
                      if doc.get("peak_memory_bytes") else ""))
    mfu = doc["totals"].get("mfu")
    if mfu is not None:
        out.append(f"window MFU {mfu:.2%}, HBM util "
                   f"{doc['totals']['hbm_util']:.2%} (per chip)")
    out.append(f"classified {doc['classified_frac']:.1%} of device time "
               f"({doc['attributed_frac']:.1%} attributed to named phases)")
    return "\n".join(out) + "\n"
