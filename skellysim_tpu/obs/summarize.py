"""Render telemetry/metrics JSONL streams into a human-readable report.

One parser for every line shape the repo emits (docs/observability.md):

* tracer events (``ev`` key): ``span`` / ``compile`` / ``lane`` /
  ``telemetry`` headers — from `obs.tracer` (run loop, ensemble scheduler,
  bench groups);
* run-loop step records (`system.METRICS_FIELDS` — no ``ev``/``event``
  key) and ensemble metrics records (``event`` = start/step/retire/...,
  `io.ensemble_io`);
* resume markers (``{"resume": true, ...}``).

The report's sections — per-span timings, compile events, faults, lane
occupancy, dynamic instability, solver convergence — are each omitted
when their inputs are absent,
so the same command serves a single-run metrics file, a trace file, an
ensemble metrics file, or all of them at once.
"""

from __future__ import annotations

import json


def _fmt_s(v: float) -> str:
    return f"{v:.4f}"


class Summary:
    """Accumulator over parsed JSONL records."""

    def __init__(self):
        #: span durations keyed (source stream id, path) — the source
        #: column only renders when more than one file was ingested
        self.spans: dict[tuple, list[float]] = {}
        self.compiles: list[dict] = []
        #: `device_phase` records (skelly-pulse: the profiler dump folded
        #: into the stream by the run CLIs — docs/observability.md
        #: "Device-time attribution")
        self.device_phases: list[dict] = []
        #: fault events by kind (`ev == "fault"` — solver health verdicts,
        #: lane quarantines, chaos injections, wire-frame rejects,
        #: fused-ring fallbacks; docs/robustness.md)
        self.faults: dict[str, int] = {}
        self.fault_verdicts: dict[str, int] = {}
        #: fused-ring fallback eligibility legs (``leg`` — budget vs
        #: platform vs missing-api, `parallel.compat._fused_fallback`)
        self.fault_legs: dict[str, int] = {}
        self.lane_events: dict[str, int] = {}
        self.lane_rounds: list[dict] = []
        #: admission latencies from lane admit/backfill events
        #: (`queue_wait_s`, emitted by the ensemble scheduler)
        self.queue_waits: list[float] = []
        self.steps: list[dict] = []
        #: flight-recorder rows keyed by member (skelly-flight): the
        #: metrics records' ``flight`` column and the telemetry stream's
        #: ``flight`` events both land here (docs/observability.md)
        self.flight_rows: dict[str, list[dict]] = {}
        #: fault-event offender fields (``prov_field`` — anomaly
        #: provenance, `obs.flight.PROV_FIELDS`)
        self.fault_fields: dict[str, int] = {}
        #: metrics-column vs telemetry-event flight-row pairing: the run
        #: loop writes the SAME trial row to both streams — summarizing
        #: the pair must count it once, while two separate
        #: (bitwise-identical) runs' rows must NOT collapse
        #: (`obs.flight.FlightRowDedup` credit matching)
        self._flight_dedup = None
        self.resumes = 0
        self.versions: set[int] = set()
        self.unparsed = 0
        #: torn trailing lines (kill-9 mid-write): tolerated, reported
        #: separately from mid-file garbage — the `serve/journal.py`
        #: replay discipline applied to report inputs
        self.torn_tails = 0
        #: source-stream id stamped on ingested step records: `round` ids
        #: restart at 0 per ensemble run, so wall dedupe must never merge
        #: round 0 of file A with round 0 of file B
        self._stream = 0
        #: stream id -> display label (file basename, "#N"-deduped) for
        #: the per-file provenance columns; direct `add_line` callers
        #: (tests) land on stream 0 / label "-"
        self.sources: dict[int, str] = {}

    # ------------------------------------------------------------- ingest

    def add_line(self, line: str):
        line = line.strip()
        if not line:
            return
        try:
            rec = json.loads(line)
        except ValueError:
            self.unparsed += 1
            return
        if not isinstance(rec, dict):
            self.unparsed += 1
            return
        self.add_record(rec)

    def add_record(self, rec: dict):
        ev = rec.get("ev")
        if ev == "telemetry":
            self.versions.add(rec.get("version"))
        elif ev == "span":
            key = (self._stream, rec.get("path") or rec.get("name", "?"))
            self.spans.setdefault(key, []).append(
                float(rec.get("dur_s", 0.0)))
            # ensemble batched-step spans carry lane-occupancy fields
            if "live" in rec and "lanes" in rec:
                self.lane_rounds.append(dict(rec, _stream=self._stream))
        elif ev == "device_phase":
            self.device_phases.append(dict(rec, _stream=self._stream))
        elif ev == "compile":
            self.compiles.append(rec)
        elif ev == "fault":
            kind = rec.get("kind", "?")
            self.faults[kind] = self.faults.get(kind, 0) + 1
            if rec.get("verdict"):
                v = str(rec["verdict"])
                self.fault_verdicts[v] = self.fault_verdicts.get(v, 0) + 1
            if rec.get("prov_field"):
                f = str(rec["prov_field"])
                self.fault_fields[f] = self.fault_fields.get(f, 0) + 1
            if rec.get("leg"):
                leg = str(rec["leg"])
                self.fault_legs[leg] = self.fault_legs.get(leg, 0) + 1
        elif ev == "flight":
            row = {k: rec.get(k) for k in rec
                   if k not in ("ev", "ts", "pid", "host")}
            self._add_flight_row(rec, row, "trace")
        elif ev == "lane":
            action = rec.get("action", "?")
            self.lane_events[action] = self.lane_events.get(action, 0) + 1
            if "queue_wait_s" in rec:
                self.queue_waits.append(float(rec["queue_wait_s"]))
        elif ev is None:
            if rec.get("resume"):
                self.resumes += 1
            elif "iters" in rec and rec.get("event", "step") == "step":
                # run-loop METRICS_FIELDS record, or an ensemble step record
                self.steps.append(dict(rec, _stream=self._stream))
                if isinstance(rec.get("flight"), dict):
                    self._add_flight_row(rec, rec["flight"], "metrics")

    def _add_flight_row(self, rec: dict, row: dict, kind: str):
        from .flight import FlightRowDedup, flight_row_key, member_of

        if self._flight_dedup is None:
            self._flight_dedup = FlightRowDedup()
        member = member_of(rec)
        if self._flight_dedup.is_duplicate(flight_row_key(member, row),
                                           kind):
            return
        self.flight_rows.setdefault(member, []).append(row)

    def add_file(self, path: str):
        import os

        self._stream += 1
        label = os.path.basename(path) or path
        if label in self.sources.values():
            label = f"{label}#{self._stream}"
        self.sources[self._stream] = label
        # torn-trailing-line tolerance (kill -9 mid-write, same replay
        # discipline as serve/journal.py): THE one rule lives in
        # `obs.flight.iter_jsonl_tolerant`, shared with `obs flight` — a
        # torn final line is a partial write, reported as such; mid-file
        # garbage stays an unparseable-line count
        from .flight import iter_jsonl_tolerant

        for rec, torn in iter_jsonl_tolerant(path):
            if rec is None:
                if torn:
                    self.torn_tails += 1
                else:
                    self.unparsed += 1
                continue
            self.add_record(rec)

    def _label(self, stream: int) -> str:
        return self.sources.get(stream, "-")

    @property
    def _multi_source(self) -> bool:
        return len(self.sources) > 1

    # ------------------------------------------------------------- render

    def _span_section(self, out: list[str]):
        if not self.spans:
            return
        out.append("== spans ==")
        # with several input files the span table carries per-file
        # provenance (a serve run's multiple --trace-files used to
        # interleave indistinguishably)
        multi = self._multi_source
        header = (("source",) if multi else ()) + (
            "span", "count", "total_s", "mean_s", "max_s")
        rows = [header]
        for stream, path in sorted(self.spans,
                                   key=lambda k: (k[1], self._label(k[0]))):
            durs = self.spans[(stream, path)]
            src = ((self._label(stream),) if multi else ())
            rows.append(src + (path, str(len(durs)), _fmt_s(sum(durs)),
                               _fmt_s(sum(durs) / len(durs)),
                               _fmt_s(max(durs))))
        widths = [max(len(r[i]) for r in rows) for i in range(len(header))]
        out.extend("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
                   for r in rows)
        out.append("")

    def _device_phase_section(self, out: list[str]):
        """Device time by phase (skelly-pulse): the profiler dump's
        attribution table folded into the stream as `device_phase` events
        — rendered next to the host spans so one summarize answers both
        "where did the host wait" and "where did the device work"."""
        if not self.device_phases:
            return
        out.append("== device time by phase ==")
        multi = self._multi_source
        header = (("source",) if multi else ()) + (
            "phase", "time_s", "share", "ops", "collectives")
        rows = [header]
        for rec in sorted(self.device_phases,
                          key=lambda r: -float(r.get("dur_s", 0.0))):
            colls = "  ".join(f"{k}={float(v):.4f}s" for k, v in
                              sorted((rec.get("collectives") or {}).items()))
            src = ((self._label(rec.get("_stream", 0)),) if multi else ())
            rows.append(src + (str(rec.get("phase", "?")),
                               _fmt_s(float(rec.get("dur_s", 0.0))),
                               f"{float(rec.get('share', 0.0)):.1%}",
                               str(rec.get("ops", "?")), colls))
        widths = [max(len(r[i]) for r in rows) for i in range(len(header))]
        out.extend("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
                   for r in rows)
        out.append("")

    def _compile_section(self, out: list[str]):
        if not self.compiles:
            return
        out.append("== compile events ==")
        for rec in self.compiles:
            # cache stamp (skelly-bucket): "cached" = a persistent XLA
            # cache dir was active, so the wall time is trace + cache
            # load, not a true cold compile; older streams without the
            # stamp render as "?"
            cache = rec.get("persistent_cache")
            cache_s = ("?" if cache is None
                       else ("cached" if cache else "cold"))
            out.append(
                f"{rec.get('name', '?')}: trace #{rec.get('traces', '?')} "
                f"wall={rec.get('wall_s', '?')}s "
                f"trace={rec.get('trace_s', '?')}s "
                f"cache={cache_s} "
                f"donated={rec.get('donated', [])} "
                f"sig={str(rec.get('arg_sig', ''))[:120]}")
        by_name: dict[str, int] = {}
        for rec in self.compiles:
            by_name[rec.get("name", "?")] = by_name.get(
                rec.get("name", "?"), 0) + 1
        retraced = {n: c for n, c in by_name.items() if c > 1}
        if retraced:
            out.append("RETRACES: " + ", ".join(
                f"{n} x{c}" for n, c in sorted(retraced.items())))
        out.append("")

    def _fault_section(self, out: list[str]):
        if not self.faults:
            return
        out.append("== faults ==")
        rows = [("kind", "count")]
        rows += [(k, str(v)) for k, v in sorted(self.faults.items())]
        widths = [max(len(r[i]) for r in rows) for i in range(2)]
        out.extend("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
                   for r in rows)
        if self.fault_verdicts:
            out.append("verdicts: " + ", ".join(
                f"{v}={n}" for v, n in sorted(self.fault_verdicts.items())))
        if self.fault_fields:
            # skelly-flight anomaly provenance: which FIELD blew up first
            out.append("offender fields: " + ", ".join(
                f"{f}={n}" for f, n in sorted(self.fault_fields.items())))
        if self.fault_legs:
            # which fused-ring eligibility leg failed: "too big for VMEM"
            # (budget) reads very differently from "not a TPU" (platform)
            out.append("legs: " + ", ".join(
                f"{leg}={n}" for leg, n in sorted(self.fault_legs.items())))
        out.append("")

    def _lane_section(self, out: list[str]):
        if not self.lane_events and not self.lane_rounds:
            return
        out.append("== ensemble lanes ==")
        if self.lane_events:
            out.append("events: " + ", ".join(
                f"{k}={v}" for k, v in sorted(self.lane_events.items())))
        if self.lane_rounds:
            by_stream: dict = {}
            for r in self.lane_rounds:
                by_stream.setdefault(r.get("_stream", 0), []).append(r)
            for stream in sorted(by_stream,
                                 key=lambda s: self._label(s)):
                rounds = by_stream[stream]
                live = [float(r["live"]) for r in rounds]
                lanes = max(float(r["lanes"]) for r in rounds)
                occ = sum(live) / (len(live) * lanes) if lanes else 0.0
                src = (f"[{self._label(stream)}] " if self._multi_source
                       else "")
                out.append(f"{src}rounds: {len(rounds)}  lanes: "
                           f"{int(lanes)}  mean occupancy: {occ:.1%}")
        if self.queue_waits:
            w = self.queue_waits
            out.append(f"admission wait: mean {sum(w) / len(w):.4f}s  "
                       f"max {max(w):.4f}s  (n={len(w)})")
        out.append("")

    def _scenario_section(self, out: list[str]):
        """Dynamic-instability table (docs/scenarios.md): per-member fiber
        population trajectory + growth-reseat events. Rendered only when
        the stream carries DI activity — the fields are all-zero on
        deterministic runs."""
        di_steps = [s for s in self.steps
                    if s.get("nucleations") or s.get("catastrophes")
                    or s.get("active_fibers")]
        # a ScenarioEnsemble trace carries BOTH the scheduler's "growth"
        # (lane froze) and the sweep's "growth_reseat" (member re-admitted)
        # for the same reseat; a serve trace carries "growth" only — take
        # the max, not the sum
        growths = max(self.lane_events.get("growth", 0),
                      self.lane_events.get("growth_reseat", 0))
        if not di_steps and not growths:
            return
        out.append("== dynamic instability ==")
        by_member: dict[str, list[dict]] = {}
        for s in self.steps:
            by_member.setdefault(s.get("member", "run"), []).append(s)
        rows = [("member", "steps", "nucleated", "catastrophes",
                 "active (first->last, max)")]
        for member in sorted(by_member):
            recs = by_member[member]
            act = [int(r.get("active_fibers", 0)) for r in recs]
            rows.append((
                member, str(len(recs)),
                str(sum(int(r.get("nucleations", 0)) for r in recs)),
                str(sum(int(r.get("catastrophes", 0)) for r in recs)),
                f"{act[0]} -> {act[-1]}, max {max(act)}" if act else "-"))
        widths = [max(len(r[i]) for r in rows) for i in range(5)]
        out.extend("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
                   for r in rows)
        total_n = sum(int(s.get("nucleations", 0)) for s in self.steps)
        total_c = sum(int(s.get("catastrophes", 0)) for s in self.steps)
        out.append(f"events: nucleations={total_n}  catastrophes={total_c}"
                   + (f"  growth-reseats={growths}" if growths else ""))
        out.append("")

    def _flight_section(self, out: list[str]):
        """Physics-diagnostics table (skelly-flight,
        docs/observability.md "Flight recorder"): per-member extrema of
        the recorder's per-step rows — strain, node speed, signed wall
        clearance, solution norm — plus any anomaly provenance. Rendered
        only when the stream carries flight rows (Params.flight_window >
        0)."""
        if not self.flight_rows:
            return
        out.append("== physics diagnostics (flight recorder) ==")
        rows = [("member", "steps", "max_strain", "max_speed",
                 "min_clear", "max_|x|", "flagged")]

        def vals(rs, key):
            return [r[key] for r in rs
                    if isinstance(r.get(key), (int, float))]

        for member in sorted(self.flight_rows):
            rs = self.flight_rows[member]
            strains = vals(rs, "max_strain")
            speeds = vals(rs, "max_speed")
            clears = vals(rs, "min_clearance")
            norms = vals(rs, "solution_norm")
            flagged = sum(1 for r in rs if r.get("health"))
            rows.append((
                member, str(len(rs)),
                f"{max(strains):.3g}" if strains else "-",
                f"{max(speeds):.3g}" if speeds else "-",
                f"{min(clears):.3g}" if clears else "-",
                f"{max(norms):.3g}" if norms else "-",
                str(flagged) if flagged else "-"))
        widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
        out.extend("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
                   for r in rows)
        provs = [(m, r["provenance"]) for m, rs in self.flight_rows.items()
                 for r in rs if isinstance(r.get("provenance"), dict)]
        for m, p in provs[-4:]:
            where = (f"fiber {p.get('fiber')} node {p.get('node')}"
                     if p.get("fiber", -1) not in (None, -1)
                     else f"row {p.get('node')}")
            out.append(f"provenance: {m}: first nonfinite in "
                       f"{p.get('field')} ({where})")
        out.append("")

    def _convergence_section(self, out: list[str]):
        if not self.steps:
            return
        out.append("== solver convergence ==")
        n = len(self.steps)
        accepted = sum(1 for s in self.steps if s.get("accepted"))
        iters = [int(s.get("iters", 0)) for s in self.steps]
        out.append(f"trial steps: {n}  accepted: {accepted}  "
                   f"rejected: {n - accepted}"
                   + (f"  (resume markers: {self.resumes})"
                      if self.resumes else ""))
        out.append(f"gmres iters: min {min(iters)}  "
                   f"mean {sum(iters) / n:.1f}  max {max(iters)}")
        cycles = [int(s["gmres_cycles"]) for s in self.steps
                  if "gmres_cycles" in s]
        if cycles:
            out.append(f"gmres restart cycles: mean "
                       f"{sum(cycles) / len(cycles):.1f}  max {max(cycles)}")
        # dot-product psum rounds per solve (`solver.gmres.collective_rounds`
        # — iters/block_s batched Gram rounds + per-cycle residual norms):
        # the s-step ladder lever, surfaced here so a collective-count
        # regression shows up in telemetry, not just in bench reruns
        rounds = [int(s["collective_rounds"]) for s in self.steps
                  if "collective_rounds" in s]
        if rounds:
            out.append(f"collective rounds/solve: mean "
                       f"{sum(rounds) / len(rounds):.1f}  max {max(rounds)}"
                       f"  total {sum(rounds)}")
        rt = [float(s["residual_true"]) for s in self.steps
              if s.get("residual_true") is not None]
        if rt:
            out.append(f"explicit residual: max {max(rt):.3e}  "
                       f"last {rt[-1]:.3e}")
        refines = [int(s.get("refines", 0)) for s in self.steps]
        if any(refines):
            out.append(f"refinement sweeps: total {sum(refines)}  "
                       f"max {max(refines)}")
        loa = sum(1 for s in self.steps if s.get("loss_of_accuracy"))
        if loa:
            out.append(f"LOSS-OF-ACCURACY steps: {loa}")
        # a SUCCESSFUL escalation replaces the health word with the healed
        # attempt's 0 (guard/verdict.py), so retries must be reported even
        # when no step stayed flagged — those are exactly the runs where
        # the ladder paid extra solves
        unhealthy = sum(1 for s in self.steps if s.get("health"))
        retries = sum(int(s.get("guard_retries", 0)) for s in self.steps)
        if unhealthy or retries:
            out.append(f"HEALTH-FLAGGED steps: {unhealthy}  "
                       f"(guard retries: {retries})")
        # ensemble step records share one batched round's wall across every
        # live lane (io.ensemble_io schema) — dedupe by (stream, round) so
        # the total is the drain's wall, not lanes x wall, while rounds
        # from DIFFERENT input files (ids restart at 0 per run) still
        # count separately
        walls: dict = {}
        for i, s in enumerate(self.steps):
            if "wall_ms" not in s:
                continue
            key = (("round", s.get("_stream", 0), s["round"])
                   if "round" in s else ("step", 0, i))
            walls[key] = float(s["wall_ms"])
        if walls:
            vals = list(walls.values())
            label = ("batched-round wall"
                     if any(k[0] == "round" for k in walls) else "step wall")
            out.append(f"{label}: total {sum(vals) / 1e3:.3f}s  mean "
                       f"{sum(vals) / len(vals):.1f}ms  "
                       f"max {max(vals):.1f}ms")
        hists = [s["gmres_history"] for s in self.steps
                 if s.get("gmres_history")]
        if hists:
            last = hists[-1]
            rows = ", ".join(f"({int(it)}it {im:.1e}/{ex:.1e})"
                             for it, im, ex in last[-4:])
            out.append(f"last step's restart history "
                       f"(iters implicit/explicit): {rows}")
        out.append("")

    def render(self) -> str:
        out: list[str] = []
        if self.versions:
            vs = ", ".join(str(v) for v in sorted(self.versions,
                                                  key=lambda v: str(v)))
            out.append(f"telemetry version(s): {vs}")
            out.append("")
        self._span_section(out)
        self._device_phase_section(out)
        self._compile_section(out)
        self._fault_section(out)
        self._lane_section(out)
        self._scenario_section(out)
        self._flight_section(out)
        self._convergence_section(out)
        if self.torn_tails:
            out.append(f"({self.torn_tails} torn trailing line(s) ignored "
                       "— partial write, e.g. kill -9 mid-record)")
        if self.unparsed:
            out.append(f"({self.unparsed} unparseable line(s) skipped)")
        if not out:
            out.append("no telemetry or metrics records found")
        return "\n".join(out).rstrip() + "\n"


def summarize_files(paths) -> str:
    s = Summary()
    for p in paths:
        s.add_file(p)
    return s.render()
