"""skelly-flight: device-side physics flight recorder with anomaly provenance.

skelly-guard (docs/robustness.md) tells us *that* a solve died — a 4-bit
health word — but not which fiber, node, or field blew up, or what the
strain/clearance/dt trajectory looked like in the steps leading in. This
module is the simulation analogue of a training stack's grad-norm /
loss-scale monitors: a bounded, always-on, in-trace ring of per-step
physics diagnostics with fault localization.

The recorder is a fixed ``[K, D]`` float32 ring buffer (`FlightRecorder`)
riding `system.SimState.flight`, written with pure masked ``.at[].set``
updates inside the jitted trial step — exactly the GMRES history ring's
discipline (`solver.gmres`): NO host callbacks (skelly-audit's host-sync
contract stays empty), batches under `vmap` per ensemble member, and
``Params.flight_window = 0`` (the default) disables it entirely — the
carry vanishes and every pre-flight program is bitwise identical.

One row per trial step (`FLIGHT_FIELDS`, storage order):

======  =============  ====================================================
col     name           meaning
======  =============  ====================================================
0       t              entry simulation time of the trial
1       dt_used        the dt the trial actually solved with
2       max_strain     max per-fiber inextensibility violation over active
                       fibers (NaN strain records as +inf: "blew up")
3       strain_fiber   argmax fiber id of col 2 (global slot index)
4       max_speed      max node speed |x_new - x_old| / dt over live nodes
5       min_clearance  min signed node-periphery clearance (negative =
                       penetration — visible, unlike the collision bool);
                       +inf with no wall, NaN column with no shell
6       body_norm      norm of the body solution block (node tractions +
                       rigid force/torque dofs); 0 with no bodies
7       solution_norm  norm of the full solution vector
8       residual_true  the solve's explicit relative residual
9       health         the packed `guard.verdict` word (int-valued f32)
10      prov_field     anomaly provenance: first-offender field id
                       (`PROV_FIELDS` index; 0 = no nonfinite found)
11      prov_fiber     offender fiber slot (-1 for non-fiber fields)
12      prov_node      offender node / flat row index (-1 when col 10 = 0)
======  =============  ====================================================

**Anomaly provenance** (cols 10-12): when the health verdict stamps
nonfinite, a masked argmax over per-field isnan/isinf captures the FIRST
offender as ``(field_id, fiber_idx, node_idx)`` — joining guard's
"something died" with "who and where". Fields are scanned in priority
order (`PROV_FIELDS`): the trial's ENTRY fiber positions and tensions
(the poisoned-lane injection surface), the entry shell density, the
shell node geometry (the wall every flow evaluates against), the body
solution, then the solve's output solution vector (mid-solve blow-ups).

Under `parallel.spmd` the same row is computed with explicit collectives
(`lax.pmax`/`pmin` on the reductions, index-min tie-breaks on the
argmaxes), so every shard writes the bitwise-identical replicated ring —
the replication analyzer (`audit.repflow`) proves the armed mesh program
clean (tests/test_flight.py).

Import discipline: jax-free at module import (the decode helpers and the
`obs flight` report serve jax-free surfaces — the serve client, the obs
CLI); the device-side recorder imports jax.numpy lazily, like
`guard.verdict`.

Host-side consumers: the run loop's metrics JSONL carries the decoded
current row under the ``flight`` key (`system.METRICS_FIELDS`), the
ensemble scheduler attaches the ring tail + provenance to ``failed``
retirement records and ``fault`` events, serve exposes per-tenant tails on
``/status`` and fault-localization counters on ``/stats``, and ``python
-m skellysim_tpu.obs flight FILES...`` renders the blast-radius report
(docs/observability.md "Flight recorder").
"""

from __future__ import annotations

import json
import math
from typing import NamedTuple

#: ring row columns, in storage order (see the module table)
FLIGHT_FIELDS = ("t", "dt_used", "max_strain", "strain_fiber", "max_speed",
                 "min_clearance", "body_norm", "solution_norm",
                 "residual_true", "health", "prov_field", "prov_fiber",
                 "prov_node")

#: provenance field-id table (``prov_field`` column values, priority order:
#: the scan stops at the FIRST field carrying a nonfinite). Note the shell
#: DENSITY is scanned even though a poisoned density alone cannot fail a
#: solve (the Krylov solve starts from zero and overwrites it) — it marks
#: a state already faulted upstream; the shell NODES (the wall geometry
#: every flow evaluates against) are the shell field that can poison a
#: trial outright.
PROV_FIELDS = ("none", "fiber_x", "fiber_tension", "shell_density",
               "shell_nodes", "body_solution", "solution")

#: integer-valued ring columns (decoded back to int host-side)
_ID_FIELDS = frozenset(("strain_fiber", "health", "prov_field",
                        "prov_fiber", "prov_node"))

#: provenance order-key base: within one field, offenders rank by
#: ``fiber * 1024 + node`` (or the flat row index), clamped below this —
#: the cross-shard tie-break the SPMD reduction minimizes. Bounds the
#: localizable index space at 2^26 rows (~67M), far above any scene here.
_ORDER_BASE = 1 << 26


class FlightRecorder(NamedTuple):
    """The device-side ring: ``rows`` [K, D] f32 (NaN until written) +
    ``count`` (int32 scalar, rows written — monotonic; decode wrap with
    `ring_rows`). Rides `SimState.flight`; [B, K, D] / [B] under the
    ensemble member axis."""

    rows: object
    count: object


def new_ring(window: int):
    """A fresh recorder for ``Params.flight_window = window`` (None when
    0 — the disabled recorder is an ABSENT pytree field, so the compiled
    program is bitwise identical to a pre-flight one)."""
    if not window:
        return None
    import jax.numpy as jnp

    return FlightRecorder(
        rows=jnp.full((int(window), len(FLIGHT_FIELDS)), jnp.nan,
                      dtype=jnp.float32),
        count=jnp.int32(0))


# ---------------------------------------------------------- device recorder

def record_step(entry_state, new_state, solution, *, residual_true, health,
                dt_used, shell_shape=None, solution_norm=None,
                axis_name=None, axis_size=1, sol_scan_rows=None,
                shell_sharded=False):
    """Append one diagnostics row to ``new_state.flight``'s ring; returns
    the updated `FlightRecorder` (callers ``_replace`` it back).

    Pure masked jnp ops — no host sync, vmaps per member. ``axis_name``
    switches on the SPMD spelling: reductions go through `lax.pmax`/
    `pmin`, argmax ids globalize via ``axis_index * local_count`` offsets
    and index-min tie-breaks, so every shard writes the bitwise-identical
    replicated row. ``sol_scan_rows`` restricts the solution-vector
    provenance scan to the shard-resident head rows (the replicated tail
    is the body block, scanned as its own field); ``shell_sharded``
    globalizes the local density row block's node indices.
    """
    import jax.numpy as jnp
    from jax import lax

    from ..bodies import bodies as bd
    from ..fibers import container as fc

    ring = new_state.flight
    if ring is None:
        raise ValueError(
            "record_step needs an armed ring on new_state.flight — arm the "
            "state with System.ensure_flight / make_state "
            "(Params.flight_window > 0)")
    f32 = jnp.float32
    i32 = jnp.int32
    spmd = axis_name is not None
    shard = lax.axis_index(axis_name).astype(i32) if spmd else None

    def _pmax(v):
        return lax.pmax(v, axis_name) if spmd else v

    def _pmin(v):
        return lax.pmin(v, axis_name) if spmd else v

    old_buckets = fc.as_buckets(entry_state.fibers)
    new_buckets = fc.as_buckets(new_state.fibers)

    def node_mask2d(g):
        m = g.active[:, None]
        if g.rt_mats is not None:
            m = m & g.rt_mats.node_mask[None, :]
        return jnp.broadcast_to(m, (g.n_fibers, g.n_nodes))

    # ---- max |strain| over active fibers + argmax fiber id (a NaN strain
    # records as +inf — "this fiber blew up" must win the max, not lose
    # every comparison)
    max_strain = jnp.asarray(-1.0, f32)
    strain_fiber = i32(-1)
    goff = 0
    for g in new_buckets:
        errs = fc.fiber_errors(g).astype(f32)
        errs = jnp.where(jnp.isnan(errs), jnp.inf, errs)
        errs = jnp.where(g.active, errs, -1.0)
        i = jnp.argmax(errs).astype(i32)
        v = errs[i]
        gid = goff + i + (shard * g.n_fibers if spmd else 0)
        take = v > max_strain
        max_strain = jnp.where(take, v, max_strain)
        strain_fiber = jnp.where(take, gid, strain_fiber)
        goff += g.n_fibers * (axis_size if spmd else 1)
    if spmd:
        vg = _pmax(max_strain)
        cand = jnp.where(max_strain == vg, strain_fiber, i32(2**30))
        cand = _pmin(cand)
        strain_fiber = jnp.where(cand < 2**30, cand, i32(-1))
        max_strain = vg

    # ---- max node speed |x_new - x_old| / dt over live nodes
    max_speed = jnp.asarray(0.0, f32)
    dt_f = jnp.maximum(jnp.asarray(dt_used, f32), f32(1e-30))
    for g_old, g_new in zip(old_buckets, new_buckets):
        d = (jnp.linalg.norm(g_new.x - g_old.x, axis=-1)).astype(f32) / dt_f
        d = jnp.where(node_mask2d(g_new), d, 0.0)
        d = jnp.where(jnp.isnan(d), jnp.inf, d)
        max_speed = jnp.maximum(max_speed, jnp.max(d))
    max_speed = _pmax(max_speed)

    # ---- min signed node-periphery clearance (negative = penetration)
    min_clear = jnp.asarray(jnp.nan, f32)
    if shell_shape is not None and new_state.shell is not None and new_buckets:
        from ..periphery import periphery as peri

        vals = []
        for g in new_buckets:
            c = peri.signed_clearance(
                shell_shape, g.x.reshape(-1, 3)).astype(f32)
            m = node_mask2d(g).reshape(-1)
            # a NaN position reads as the worst clearance, not a masked one
            c = jnp.where(jnp.isnan(c), -jnp.inf, c)
            vals.append(jnp.where(m, c, jnp.inf))
        min_clear = _pmin(jnp.min(jnp.concatenate(vals)))

    # ---- body solution block norm (replicated under SPMD: no collective)
    b_list = bd.as_buckets(new_state.bodies)
    if b_list:
        sq = sum(jnp.sum(g.solution * g.solution) for g in b_list)
        body_norm = jnp.sqrt(sq).astype(f32)
    else:
        body_norm = jnp.asarray(0.0, f32)

    if solution_norm is None:
        solution_norm = jnp.linalg.norm(solution)
    sol_norm = jnp.asarray(solution_norm, f32)

    # ---- anomaly provenance: first nonfinite as (field, fiber, node).
    # Candidates in PROV_FIELDS priority order; the reverse fold below
    # keeps the FIRST field (and first bucket within it) that has any.
    cands = []
    goff = 0
    for g in old_buckets:
        per = g.n_nodes * 3
        # & active: a dead slot's garbage bits must never win the argmax,
        # or provenance names a padded lane (docs/audit.md "Masking
        # discipline"); False pads can't beat a live True
        bad = ((~jnp.isfinite(g.x)) & g.active[:, None, None]).reshape(-1)
        idx = jnp.argmax(bad).astype(i32)
        fib = goff + idx // per + (shard * g.n_fibers if spmd else 0)
        cands.append((1, bad.any(), fib, (idx % per) // 3))
        goff += g.n_fibers * (axis_size if spmd else 1)
    goff = 0
    for g in old_buckets:
        bad = ((~jnp.isfinite(g.tension)) & g.active[:, None]).reshape(-1)
        idx = jnp.argmax(bad).astype(i32)
        fib = goff + idx // g.n_nodes + (shard * g.n_fibers if spmd else 0)
        cands.append((2, bad.any(), fib, idx % g.n_nodes))
        goff += g.n_fibers * (axis_size if spmd else 1)
    if entry_state.shell is not None:
        rho = entry_state.shell.density
        bad = ~jnp.isfinite(rho)
        idx = jnp.argmax(bad).astype(i32)
        node = idx // 3
        if spmd and shell_sharded:
            node = node + shard * i32(rho.shape[0] // 3)
        cands.append((3, bad.any(), i32(-1), node))
        nodes = entry_state.shell.nodes
        bad = (~jnp.isfinite(nodes)).reshape(-1)
        idx = jnp.argmax(bad).astype(i32)
        node = idx // 3
        if spmd and shell_sharded:
            node = node + shard * i32(nodes.shape[0])
        cands.append((4, bad.any(), i32(-1), node))
    for g in bd.as_buckets(entry_state.bodies):
        bad = (~jnp.isfinite(g.solution)).reshape(-1)
        idx = jnp.argmax(bad).astype(i32)
        cands.append((5, bad.any(), i32(-1), idx))
    sol_scan = (solution if sol_scan_rows is None
                else solution[:sol_scan_rows])
    bad = ~jnp.isfinite(sol_scan)
    idx = jnp.argmax(bad).astype(i32)
    if spmd and sol_scan_rows is not None:
        idx = idx + shard * i32(sol_scan_rows)
    cands.append((6, bad.any(), i32(-1), idx))

    field = i32(0)
    p_fib = i32(-1)
    p_node = i32(-1)
    for fid, any_, fb, nd in reversed(cands):
        field = jnp.where(any_, i32(fid), field)
        p_fib = jnp.where(any_, fb, p_fib)
        p_node = jnp.where(any_, nd, p_node)
    if spmd:
        # cross-shard: minimize (field priority, fiber*1024+node) so every
        # shard agrees on ONE offender bitwise
        order = jnp.minimum(jnp.where(p_fib >= 0, p_fib * 1024 + p_node,
                                      p_node), i32(_ORDER_BASE - 1))
        key = jnp.where(field > 0, field * _ORDER_BASE + order, i32(2**30))
        kmin = _pmin(key)
        mine = key == kmin
        field = _pmax(jnp.where(mine, field, i32(0)))
        p_fib = _pmax(jnp.where(mine, p_fib + 2, i32(0))) - 2
        p_node = _pmax(jnp.where(mine, p_node + 2, i32(0))) - 2

    row = jnp.stack([
        jnp.asarray(entry_state.time, f32),
        jnp.asarray(dt_used, f32),
        max_strain, strain_fiber.astype(f32), max_speed, min_clear,
        body_norm, sol_norm,
        jnp.asarray(residual_true, f32),
        jnp.asarray(health, i32).astype(f32),
        field.astype(f32), p_fib.astype(f32), p_node.astype(f32)])
    window = ring.rows.shape[0]
    count = jnp.asarray(ring.count, i32)
    rows = ring.rows.at[lax.rem(count, i32(window))].set(row)
    return FlightRecorder(rows=rows, count=count + 1)


# ------------------------------------------------------------- host decode

def decode_row(row) -> dict:
    """One ring row -> a named dict (`FLIGHT_FIELDS` keys + a
    ``provenance`` sub-dict when the row localized a nonfinite). Id
    columns come back as ints; NaN floats as None (absent diagnostic);
    ±inf floats as the STRINGS ``"inf"``/``"-inf"`` — the blow-up signal
    survives, while the JSONL streams these rows feed stay RFC-8259
    (Python's json would emit a bare ``Infinity`` token that jq /
    JSON.parse / pandas all reject, exactly on the faulted lines).
    Numeric consumers (the summarize extrema, timeline counters) filter
    on isinstance(v, (int, float)) and skip them; health + provenance
    still mark the fault."""
    out = {}
    for name, v in zip(FLIGHT_FIELDS, row):
        v = float(v)
        if name in _ID_FIELDS:
            out[name] = int(v) if math.isfinite(v) else None
        elif math.isnan(v):
            out[name] = None
        elif math.isinf(v):
            out[name] = "inf" if v > 0 else "-inf"
        else:
            out[name] = v
    prov = None
    fid = out.get("prov_field")
    if fid:
        fname = (PROV_FIELDS[fid] if 0 <= fid < len(PROV_FIELDS)
                 else str(fid))
        prov = {"field": fname, "fiber": out.get("prov_fiber"),
                "node": out.get("prov_node")}
    out["provenance"] = prov
    return out


def ring_rows(rows, count) -> list:
    """Chronological decoded rows actually written into a ring — the
    host-side wrap decode, same invariant as `solver.gmres.history_rows`:
    with ``count > K`` the buffer holds the LAST K rows, rotated oldest
    first. Host-only (never traced)."""
    import numpy as np

    if rows is None:
        return []
    h = np.asarray(rows)
    c = int(count)
    cap = h.shape[0]
    if cap == 0 or c == 0:
        return []
    if c <= cap:
        ordered = h[:c]
    else:
        start = c % cap
        ordered = np.concatenate([h[start:], h[:start]], axis=0)
    return [decode_row(r) for r in ordered]


def last_row(rows, count):
    """The most recent decoded row, or None before any write — O(1):
    decodes only the row at ``(count - 1) % K`` (the run loop and the
    scheduler call this per step/lane; the full-ring decode is the
    failure path's job, `failure_payload`)."""
    import numpy as np

    if rows is None:
        return None
    h = np.asarray(rows)
    c = int(count)
    if h.shape[0] == 0 or c == 0:
        return None
    return decode_row(h[(c - 1) % h.shape[0]])


def failure_payload(rows, count) -> dict:
    """The structured blast-radius attachment for ``failed`` retirement
    records / tenant status: the ring tail (chronological) plus the last
    row's provenance (`io.ensemble_io.ENSEMBLE_FAILURE_FIELDS`)."""
    tail = ring_rows(rows, count)
    return {"tail": tail,
            "provenance": tail[-1]["provenance"] if tail else None}


# --------------------------------------------------------- the obs flight CLI

def iter_jsonl_tolerant(path: str):
    """(record, is_torn_tail) pairs over a JSONL file — THE one torn-tail
    rule, shared by this report and `obs.summarize`. A FINAL line that
    fails to parse (kill-9 mid-write — the `serve/journal.py` replay
    discipline) yields ``(None, True)`` instead of raising; mid-file
    garbage, and any line that parses to a non-dict, yields ``(None,
    False)`` so callers count it as genuinely unparseable."""
    def parse(line, is_last):
        line = line.strip()
        if not line:
            return None
        try:
            rec = json.loads(line)
        except ValueError:
            return (None, is_last)
        return (rec, False) if isinstance(rec, dict) else (None, False)

    # streamed with one line of lookahead (NOT readlines(): a long serve
    # run's trace can reach GB — only torn-tail detection needs to know
    # which line is last)
    with open(path) as fh:
        prev = None
        for line in fh:
            if prev is not None:
                out = parse(prev, False)
                if out is not None:
                    yield out
            prev = line
        if prev is not None:
            out = parse(prev, True)
            if out is not None:
                yield out


def flight_row_key(member: str, row: dict) -> tuple:
    """Dedupe key for one member's flight row — the run loop writes the
    SAME trial row to the metrics JSONL (``flight`` column) and the
    telemetry stream (``flight`` event); reports ingesting both must
    count it once. Shared with `obs.summarize`."""
    return (member,) + tuple(
        row.get(k) for k in ("t", "dt_used", "solution_norm",
                             "residual_true", "health"))


def member_of(rec: dict) -> str:
    """Normalized member label of one record: ``member`` then ``tenant``,
    explicit None checks (member id 0 is falsy but real), str()'d so
    metrics records and fault events key identically; a sequential
    run-loop record with neither keys as ``"run"``."""
    member = rec.get("member")
    if member is None:
        member = rec.get("tenant")
    return "run" if member is None else str(member)


class FlightRowDedup:
    """Pair each metrics-column flight row with its telemetry-event twin.

    A naive value-keyed set would ALSO collapse two bitwise-identical
    runs' rows when their files are summarized together (this repo pins
    bitwise determinism everywhere, so identical values across runs are
    the expected case, not a coincidence). Credit matching instead: a
    row of one KIND ("metrics" column vs "trace" event) is a duplicate
    only if an unmatched row of the OTHER kind carries the same key —
    and consuming the match re-arms the pair, so run 2's metrics+trace
    pair dedupes against itself, never against run 1's."""

    _KINDS = ("metrics", "trace")

    def __init__(self):
        self._pending = {k: set() for k in self._KINDS}

    def is_duplicate(self, key: tuple, kind: str) -> bool:
        other = self._KINDS[1 - self._KINDS.index(kind)]
        if key in self._pending[other]:
            self._pending[other].discard(key)
            return True
        self._pending[kind].add(key)
        return False


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.3g}"
    return str(v)


class FlightReport:
    """Accumulate flight-recorder records from any mix of telemetry /
    metrics JSONL streams and render the blast-radius report."""

    def __init__(self):
        #: member -> list of per-step decoded flight rows (run-loop
        #: metrics "flight" values, ensemble step records, "flight"
        #: telemetry events)
        self.steps: dict = {}
        #: member -> failure payload ({"tail": rows, "provenance": ...})
        #: from failed/dt_underflow retirement records
        self.failures: dict = {}
        #: member -> {"verdict": ..., "health": ...} failure context
        self.verdicts: dict = {}
        #: fault-event provenance counters (field name -> count)
        self.fault_fields: dict = {}
        self.torn_tails = 0
        self.unparsed = 0
        #: metrics-column vs telemetry-event row pairing — the run loop
        #: writes the SAME trial row to both streams; two separate
        #: (bitwise-identical) runs' rows must NOT collapse
        self._dedup = FlightRowDedup()
        #: (member, field) pairs whose fault provenance already counted —
        #: one quarantine emits BOTH a failure record (metrics) and a
        #: fault event (trace); feeding both files must count the fault
        #: once (the PR-13 growth-reseat lesson)
        self._fault_counted: set = set()

    def _count_fault_field(self, member: str, field):
        key = (member, str(field))
        if key in self._fault_counted:
            return
        self._fault_counted.add(key)
        f = str(field)
        self.fault_fields[f] = self.fault_fields.get(f, 0) + 1

    def _add_step(self, member: str, row: dict, kind: str):
        if self._dedup.is_duplicate(flight_row_key(member, row), kind):
            return
        self.steps.setdefault(member, []).append(row)

    def add_record(self, rec: dict):
        ev = rec.get("ev")
        member = member_of(rec)
        if ev == "flight":
            row = {k: rec.get(k) for k in FLIGHT_FIELDS if k in rec}
            if row:
                row["provenance"] = rec.get("provenance")
                self._add_step(member, row, "trace")
            return
        if ev == "fault":
            if rec.get("prov_field"):
                self._count_fault_field(member, rec["prov_field"])
            if rec.get("verdict"):
                ctx = self.verdicts.setdefault(member, {})
                ctx.update(verdict=rec["verdict"], health=rec.get("health"))
                if rec.get("prov_field"):
                    # trace-only streams carry provenance on the fault
                    # event (the scheduler flattens it there); keep it so
                    # the report localizes without the metrics file
                    ctx["provenance"] = {"field": rec["prov_field"],
                                         "fiber": rec.get("prov_fiber"),
                                         "node": rec.get("prov_node")}
            return
        if ev is not None:
            return
        event = rec.get("event", "step")
        if event == "step" and isinstance(rec.get("flight"), dict):
            self._add_step(member, rec["flight"], "metrics")
        elif event in ("failed", "dt_underflow"):
            if isinstance(rec.get("flight"), dict):
                self.failures[member] = rec["flight"]
            self.verdicts.setdefault(member, {}).update(
                verdict=rec.get("verdict"), health=rec.get("health"))
            prov = (rec.get("flight") or {}).get("provenance")
            if prov and prov.get("field"):
                self._count_fault_field(member, prov["field"])

    def add_file(self, path: str):
        for rec, torn in iter_jsonl_tolerant(path):
            if rec is None:
                if torn:
                    self.torn_tails += 1
                else:
                    self.unparsed += 1
                continue
            self.add_record(rec)

    # ------------------------------------------------------------ render

    def _tail_table(self, out: list, rows: list, limit: int = 8):
        cols = ("t", "dt_used", "max_strain", "max_speed", "min_clearance",
                "solution_norm", "residual_true", "health")
        table = [cols]
        for r in rows[-limit:]:
            table.append(tuple(_fmt(r.get(c)) for c in cols))
        widths = [max(len(row[i]) for row in table) for i in range(len(cols))]
        out.extend("  " + "  ".join(c.ljust(w) for c, w in zip(row, widths))
                   .rstrip() for row in table)

    def render(self) -> str:
        out: list = []
        members = sorted(set(self.steps) | set(self.failures)
                         | set(self.verdicts))
        faulted = [m for m in members
                   if m in self.failures or m in self.verdicts]
        for m in faulted:
            ctx = self.verdicts.get(m, {})
            verdict = ctx.get("verdict") or "?"
            if isinstance(verdict, list):
                verdict = "|".join(verdict) or "ok"
            out.append(f"== {m}: FAULT ({verdict}) ==")
            payload = self.failures.get(m) or {}
            tail = payload.get("tail") or self.steps.get(m, [])
            prov = payload.get("provenance")
            if prov is None and tail:
                prov = tail[-1].get("provenance")
            if prov is None:
                prov = ctx.get("provenance")
            if prov and prov.get("field"):
                where = (f"fiber {prov.get('fiber')} node "
                         f"{prov.get('node')}"
                         if prov.get("fiber", -1) not in (None, -1)
                         else f"row {prov.get('node')}")
                out.append(f"first offender: field={prov['field']} {where}")
            else:
                out.append("first offender: (not localized)")
            if tail:
                out.append(f"trajectory into the fault "
                           f"(last {min(len(tail), 8)} of {len(tail)} "
                           "recorded steps):")
                self._tail_table(out, tail)
            out.append("")
        healthy = [m for m in members if m not in faulted and self.steps.get(m)]
        if healthy:
            out.append(f"== healthy members ({len(healthy)}) ==")
            for m in healthy:
                rows = self.steps[m]
                # numeric filter: blow-up rows carry "inf" STRINGS (see
                # decode_row) — extrema are over the finite points
                strains = [r["max_strain"] for r in rows
                           if isinstance(r.get("max_strain"), (int, float))]
                speeds = [r["max_speed"] for r in rows
                          if isinstance(r.get("max_speed"), (int, float))]
                out.append(
                    f"{m}: {len(rows)} step(s)"
                    + (f"  max_strain {max(strains):.3g}" if strains else "")
                    + (f"  max_speed {max(speeds):.3g}" if speeds else ""))
            out.append("")
        if self.fault_fields:
            out.append("fault localization (offender field -> faults): "
                       + ", ".join(f"{k}={v}" for k, v in
                                   sorted(self.fault_fields.items())))
        if self.torn_tails:
            out.append(f"({self.torn_tails} torn trailing line(s) ignored — "
                       "partial write, e.g. kill -9 mid-record)")
        if self.unparsed:
            out.append(f"({self.unparsed} unparseable line(s) skipped)")
        if not out:
            out.append("no flight-recorder records found (arm with "
                       "Params.flight_window > 0)")
        return "\n".join(out).rstrip() + "\n"


def render_flight_report(paths) -> str:
    rep = FlightReport()
    for p in paths:
        rep.add_file(p)
    return rep.render()
