"""Fixed-bucket log-scale latency histograms (skelly-pulse).

The serving SLO story needs DISTRIBUTIONS, not means: "mean admission
wait 80 ms" hides the p99 tenant that waited 4 s. `LogHistogram` is the
smallest structure that answers p50/p95/p99 under continuous ingest:

* fixed geometric bucket edges (``lo * ratio^k`` up to ``hi``, default 8
  buckets/decade) — O(1) observe, O(buckets) percentile, bounded memory
  forever (a `/stats` accumulator must not grow with traffic the way the
  old ``queue_waits`` list did);
* percentile read-out by geometric interpolation inside the covering
  bucket — relative error bounded by one bucket ratio (~33% at
  8/decade), pinned against a numpy oracle in tests/test_obs.py;
* Prometheus-compatible cumulative rendering (`buckets()` yields
  ``(le, cumulative_count)`` with the ``+Inf`` terminal), consumed by
  `serve.protocol.render_prometheus` for scrape endpoints.

jax-free and import-light like the tracer — `serve.metrics` folds tracer
events into these on the event loop's hot path.
"""

from __future__ import annotations

import math


class LogHistogram:
    """Log-scale histogram over positive values (seconds, typically).

    ``lo``/``hi`` bound the resolved range: values below ``lo`` land in
    the underflow bucket (upper edge ``lo``), values at/above ``hi`` in
    the overflow bucket (edge ``+Inf``). ``per_decade`` sets resolution.
    """

    def __init__(self, lo: float = 1e-4, hi: float = 1e3,
                 per_decade: int = 8):
        if not (0.0 < lo < hi):
            raise ValueError(f"need 0 < lo < hi, got lo={lo} hi={hi}")
        self.lo = float(lo)
        self.hi = float(hi)
        self.per_decade = int(per_decade)
        n = int(math.ceil(math.log10(hi / lo) * per_decade))
        #: bucket upper edges: [lo * r^1 ... >= hi], preceded by the
        #: underflow edge lo and followed by +Inf
        self.edges = [lo * 10.0 ** ((k + 1) / per_decade)
                      for k in range(n)]
        # counts[0] = (0, lo]; counts[1 + k] = (edge_{k-1}, edge_k];
        # counts[-1] = overflow
        self.counts = [0] * (n + 2)
        self.n = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = 0.0

    # -------------------------------------------------------------- ingest

    def observe(self, value: float) -> None:
        v = float(value)
        if not (v >= 0.0) or math.isinf(v):   # NaN/negative/inf -> clamp
            v = 0.0 if not (v >= 0.0) else self.hi
        self.n += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        if v <= self.lo:
            self.counts[0] += 1
        elif v >= self.hi:
            self.counts[-1] += 1
        else:
            k = int(math.log10(v / self.lo) * self.per_decade)
            k = min(max(k, 0), len(self.edges) - 1)
            # float rounding at an edge: keep the invariant v <= edge[k]
            while k + 1 < len(self.edges) and v > self.edges[k]:
                k += 1
            self.counts[1 + k] += 1

    # ------------------------------------------------------------- readout

    def percentile(self, q: float) -> float:
        """Approximate q-th percentile (q in [0, 100]) by geometric
        interpolation within the covering bucket; 0.0 when empty."""
        if self.n == 0:
            return 0.0
        rank = q / 100.0 * self.n
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            lo_edge = (0.0 if i == 0
                       else self.lo if i == 1
                       else self.edges[i - 2])
            hi_edge = (self.lo if i == 0
                       else self.edges[i - 1] if i - 1 < len(self.edges)
                       else self.max)
            if cum + c >= rank:
                frac = min(max((rank - cum) / c, 0.0), 1.0)
                lo_e = max(lo_edge, self.min if i <= 1 else lo_edge,
                           1e-12)
                hi_e = max(min(hi_edge, self.max), lo_e)
                return lo_e * (hi_e / lo_e) ** frac
            cum += c
        return self.max

    def quantiles(self) -> dict:
        return {"p50": self.percentile(50.0), "p95": self.percentile(95.0),
                "p99": self.percentile(99.0)}

    def summary(self) -> dict:
        """The `/stats` SLO block: counts + moments + percentiles."""
        out = {"n": self.n, "mean": (self.sum / self.n) if self.n else 0.0,
               "max": self.max if self.n else 0.0}
        out.update(self.quantiles())
        return out

    def buckets(self) -> list:
        """Prometheus-style cumulative ``[(le, cumulative_count)]`` with
        the terminal ``("+Inf", n)``; only edges up to the last occupied
        bucket are listed (plus +Inf), keeping wire payloads small."""
        out = []
        cum = 0
        last_occupied = max((i for i, c in enumerate(self.counts) if c),
                            default=-1)
        for i, c in enumerate(self.counts[:-1]):
            cum += c
            if i > last_occupied:
                break
            edge = self.lo if i == 0 else self.edges[i - 1]
            out.append((edge, cum))
        out.append(("+Inf", self.n))
        return out

    def to_wire(self) -> dict:
        """msgpack/JSON-safe dict for the stats response (`from_wire`
        round-trips it client-side for prometheus rendering)."""
        return {"summary": self.summary(),
                "sum": self.sum,
                "buckets": [[le, c] for le, c in self.buckets()]}


def render_prometheus_histogram(name: str, wire: dict,
                                help_text: str = "") -> list:
    """Prometheus exposition lines for one `LogHistogram.to_wire` dict."""
    out = []
    if help_text:
        out.append(f"# HELP {name} {help_text}")
    out.append(f"# TYPE {name} histogram")
    for le, c in wire.get("buckets", []):
        le_s = "+Inf" if le == "+Inf" else f"{float(le):.6g}"
        out.append(f'{name}_bucket{{le="{le_s}"}} {int(c)}')
    summary = wire.get("summary", {})
    out.append(f"{name}_sum {float(wire.get('sum', 0.0)):.6g}")
    out.append(f"{name}_count {int(summary.get('n', 0))}")
    return out
