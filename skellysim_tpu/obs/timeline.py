"""One merged Chrome-trace/Perfetto timeline from telemetry (+ profiler)
streams (skelly-pulse).

``python -m skellysim_tpu.obs timeline TRACE.jsonl [PROFILE_DIR] -o
out.perfetto.json`` renders a single artifact that chrome://tracing and
ui.perfetto.dev load directly, with three track families:

* **host** — every tracer span as a complete ("X") slice (the span event
  is emitted at scope EXIT carrying ``dur_s``, so the slice starts at
  ``ts - dur_s``), one process per telemetry source pid, one thread per
  source pid/stream; `lane`/`fault`/`resume` records as instants;
* **compile** — `observed_jit` compile events as instants on a dedicated
  thread (the warm-path-retrace needle in the haystack);
* **device** — when a ``--profile`` dump dir rides along, the per-op
  device events from `obs.profile.load_device_trace`, one thread per
  attributed PHASE (the named_scope vocabulary), so the device track
  reads as a phase Gantt chart.

Clock caveat: host telemetry timestamps are `time.perf_counter` while the
profiler's are the runtime's tracing clock — the two are rebased so the
first device op aligns with the start of the host stream's first ``step``
span (falling back to the stream origin). Cross-track alignment is
therefore approximate; durations and within-track ordering are exact.

jax-free (json only), like every obs parser.
"""

from __future__ import annotations

import json
import math

from . import profile as profile_mod

#: synthetic pids of the merged timeline's process tracks
HOST_PID = 1
DEVICE_PID = 100
#: host-track tid of the compile/instant lane
COMPILE_TID = 9999


def _load_jsonl(path: str) -> list:
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def timeline_events(trace_paths, profile_dir=None) -> list:
    """The merged ``traceEvents`` list (Chrome trace-event JSON array
    form). ``trace_paths`` is one path or a list of telemetry JSONL
    paths; ``profile_dir`` optionally adds the device track."""
    if isinstance(trace_paths, str):
        trace_paths = [trace_paths]
    events: list = [{"ph": "M", "pid": HOST_PID, "name": "process_name",
                     "args": {"name": "host telemetry"}},
                    {"ph": "M", "pid": HOST_PID, "name":
                     "process_sort_index", "args": {"sort_index": 0}}]

    recs: list = []
    for i, path in enumerate(trace_paths):
        for rec in _load_jsonl(path):
            rec["_stream"] = i
            recs.append(rec)

    # origin: earliest span START (ts - dur_s) or event ts across streams
    starts = []
    first_step_start = None
    for rec in recs:
        ts = rec.get("ts")
        if ts is None:
            continue
        start = ts - float(rec.get("dur_s", 0.0)) \
            if rec.get("ev") == "span" else ts
        starts.append(start)
        if (rec.get("ev") == "span" and rec.get("name") == "step"
                and first_step_start is None):
            first_step_start = start
    t0 = min(starts) if starts else 0.0

    tids = {}

    def tid_of(rec) -> int:
        key = (rec.get("_stream", 0), rec.get("pid", 0))
        if key not in tids:
            tids[key] = len(tids) + 1
            events.append({"ph": "M", "pid": HOST_PID, "tid": tids[key],
                           "name": "thread_name",
                           "args": {"name": f"pid {key[1]} "
                                            f"(stream {key[0]})"}})
        return tids[key]

    events.append({"ph": "M", "pid": HOST_PID, "tid": COMPILE_TID,
                   "name": "thread_name", "args": {"name": "compiles"}})

    n_spans = n_compiles = 0
    for rec in recs:
        ev = rec.get("ev")
        ts = rec.get("ts")
        if ts is None:
            continue
        us = lambda t: round((t - t0) * 1e6, 3)  # noqa: E731
        if ev == "span":
            dur_s = float(rec.get("dur_s", 0.0))
            args = {k: v for k, v in rec.items()
                    if k not in ("ev", "ts", "dur_s", "name", "_stream")
                    and isinstance(v, (str, int, float, bool))}
            events.append({"ph": "X", "pid": HOST_PID, "tid": tid_of(rec),
                           "ts": us(ts - dur_s), "dur": round(dur_s * 1e6,
                                                              3),
                           "name": rec.get("name", "?"), "args": args})
            n_spans += 1
        elif ev == "compile":
            events.append({
                "ph": "i", "s": "p", "pid": HOST_PID, "tid": COMPILE_TID,
                "ts": us(ts), "name": f"compile {rec.get('name', '?')}",
                "args": {k: v for k, v in rec.items()
                         if k in ("name", "wall_s", "trace_s", "traces",
                                  "arg_sig", "persistent_cache")}})
            n_compiles += 1
        elif ev == "flight":
            # skelly-flight recorder rows as perfetto COUNTER tracks (one
            # per diagnostic, per member), so the physics trajectory into
            # a fault renders next to the host spans and the device-phase
            # tracks (docs/observability.md "Flight recorder")
            member = rec.get("member")
            suffix = f" [{member}]" if member not in (None, "run") else ""
            for field in ("max_strain", "max_speed", "min_clearance",
                          "solution_norm", "residual_true", "health"):
                v = rec.get(field)
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                if not math.isfinite(v):
                    # an inf strain (a blow-up row) would serialize as the
                    # bare `Infinity` token and make the WHOLE artifact
                    # unloadable in Perfetto — exactly the traces this
                    # counter exists to render; drop the point, the fault
                    # instant still marks the event
                    continue
                events.append({"ph": "C", "pid": HOST_PID,
                               "ts": us(ts),
                               "name": f"flight:{field}{suffix}",
                               "args": {"value": v}})
        elif ev in ("lane", "fault", "journal", "device_phase_error"):
            label = rec.get("action") or rec.get("kind") or ev
            events.append({
                "ph": "i", "s": "t", "pid": HOST_PID, "tid": tid_of(rec),
                "ts": us(ts), "name": f"{ev}:{label}",
                "args": {k: v for k, v in rec.items()
                         if isinstance(v, (str, int, float, bool))
                         and k not in ("ev", "ts", "_stream")}})
        elif ev is None and rec.get("resume"):
            events.append({"ph": "i", "s": "t", "pid": HOST_PID,
                           "tid": tid_of(rec), "ts": us(ts or 0.0),
                           "name": "resume", "args": {}})

    if profile_dir is not None:
        events.extend(_device_track(profile_dir, first_step_start, t0))
    return events


def _device_track(profile_dir: str, first_step_start, host_t0) -> list:
    """Device-phase track: op events re-based so the first device op
    aligns with the host stream's first ``step`` span (approximate — see
    module docstring), one thread per phase."""
    trace = profile_mod.load_device_trace(profile_dir)
    if not trace.events:
        return []
    out = [{"ph": "M", "pid": DEVICE_PID, "name": "process_name",
            "args": {"name": "device (profiler)"}},
           {"ph": "M", "pid": DEVICE_PID, "name": "process_sort_index",
            "args": {"sort_index": 1}}]
    dev_t0 = min(e["ts"] for e in trace.events)
    # offset in us: device ts are already us; host origin is seconds
    base_us = ((first_step_start - host_t0) * 1e6
               if first_step_start is not None else 0.0)
    # tids key on (phase, SOURCE thread): a d2/d8 profile runs the same
    # phase concurrently on several device threads, and chrome-trace
    # expects per-tid slices to nest — merging them onto one tid would
    # produce overlapping non-nested slices that render wrong
    src_tids = sorted({(e["pid"], e["tid"]) for e in trace.events})
    src_idx = {st: i for i, st in enumerate(src_tids)}
    phase_tids: dict = {}
    for e in sorted(trace.events, key=lambda e: e["ts"]):
        phase = e["phase"] or "(unattributed)"
        key = (phase, e["pid"], e["tid"])
        tid = phase_tids.get(key)
        if tid is None:
            tid = len(phase_tids) + 1
            phase_tids[key] = tid
            label = (phase if len(src_tids) == 1
                     else f"{phase} [dev {src_idx[(e['pid'], e['tid'])]}]")
            out.append({"ph": "M", "pid": DEVICE_PID, "tid": tid,
                        "name": "thread_name", "args": {"name": label}})
        args = {"module": e["module"], "self_us": round(e["self_us"], 3)}
        if e["collective"]:
            args["collective"] = e["collective"]
        if e.get("inferred"):
            args["inferred_phase"] = True
        out.append({"ph": "X", "pid": DEVICE_PID, "tid": tid,
                    "ts": round(base_us + e["ts"] - dev_t0, 3),
                    "dur": e["dur"], "name": e["name"], "args": args})
    return out


def write_timeline(trace_paths, out_path: str, profile_dir=None) -> dict:
    """Write the merged timeline JSON; returns summary counts for CLIs."""
    events = timeline_events(trace_paths, profile_dir=profile_dir)
    doc = {"displayTimeUnit": "ms", "traceEvents": events}
    with open(out_path, "w") as fh:
        json.dump(doc, fh)
        fh.write("\n")
    return {
        "events": len(events),
        "host_slices": sum(1 for e in events
                           if e.get("ph") == "X"
                           and e.get("pid") == HOST_PID),
        "instants": sum(1 for e in events if e.get("ph") == "i"),
        "counters": sum(1 for e in events if e.get("ph") == "C"),
        "device_slices": sum(1 for e in events
                             if e.get("ph") == "X"
                             and e.get("pid") == DEVICE_PID),
    }
