"""Deformable body: declared interface, deliberately unimplemented.

Parity marker for the reference's `DeformableBody` stub
(`/root/reference/include/body_deformable.hpp:17-47`,
`src/core/body_deformable.cpp:13-41`): the reference declares a 4-unknowns-
per-node deformable surface but every method is an empty body and
`flow_deformable` throws (`body_container.cpp:449-463`). We keep the same
surface so configs selecting it fail loudly at build time rather than
silently producing a rigid body.
"""

from __future__ import annotations


class DeformableBodyNotImplemented(NotImplementedError):
    pass


SOLUTION_PER_NODE = 4  # `body_deformable.hpp:35`: get_solution_size = 4 * n


def make_group(*args, **kwargs):
    raise DeformableBodyNotImplemented(
        "deformable bodies are declared but not implemented (matching the "
        "reference stub: `body_deformable.cpp:13-41`, flow_deformable throws "
        "at `body_container.cpp:449-463`)")
