from .bodies import BodyGroup, BodyCaches, make_group  # noqa: F401
