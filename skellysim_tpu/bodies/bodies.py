"""Rigid bodies (MTOCs/centrosomes) as first/second-kind boundary integrals.

TPU-native replacement for `SphericalBody`/`EllipsoidalBody`/`BodyContainer`
(`/root/reference/src/core/body_spherical.cpp`, `body_ellipsoidal.cpp`,
`body_container.cpp`): bodies of one surface resolution live in batched arrays
[nb, n, ...] and all per-body dense operators are vmapped; the reference's
rank-0 body ownership + MPI broadcast disappears (body state is replicated in
the jit program). Spherical and ellipsoidal bodies share one formulation (the
reference's two classes are near-duplicates); the `kind` only matters for
collision geometry.

Solution layout per body (matching `body_spherical.hpp:61`):
[3n node densities (node-major xyz) | 6 rigid velocities (U, omega)].

External forces support the reference's Linear and Oscillatory schedules
(`body_container.cpp:413-447`).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import kernels
from ..utils import quaternion as quat

EXTFORCE_LINEAR = 0
EXTFORCE_OSCILLATORY = 1


class BodyGroup(NamedTuple):
    """Batched same-resolution rigid bodies (a pytree; [nb] leading axis)."""

    nodes_ref: jnp.ndarray        # [nb, n, 3]
    normals_ref: jnp.ndarray      # [nb, n, 3]
    weights: jnp.ndarray          # [nb, n]
    nucleation_sites_ref: jnp.ndarray  # [nb, ns, 3]
    position: jnp.ndarray         # [nb, 3]
    orientation: jnp.ndarray      # [nb, 4] quaternion (w, x, y, z)
    solution: jnp.ndarray         # [nb, 3n+6]
    velocity: jnp.ndarray         # [nb, 3]
    angular_velocity: jnp.ndarray  # [nb, 3]
    external_force: jnp.ndarray   # [nb, 3]
    external_torque: jnp.ndarray  # [nb, 3]
    ext_force_type: jnp.ndarray   # [nb] int32 (Linear/Oscillatory)
    osc_amplitude: jnp.ndarray    # [nb]
    osc_omega: jnp.ndarray        # [nb]
    osc_phase: jnp.ndarray        # [nb]
    radius: jnp.ndarray           # [nb] attachment radius (spheres; 0 otherwise)
    kind_sphere: jnp.ndarray      # [nb] bool: sphere (True) / ellipsoid (False)
    #: [nb, 3] ellipsoid semiaxes in the body frame (zeros for spheres /
    #: generic bodies) — drives the rigid-motion containment override in
    #: velocity fields (`system.cpp:371-380` handles ellipsoids too)
    semiaxes: jnp.ndarray = None
    #: int32 [nb] original config-order index. With multiple shape/resolution
    #: buckets the solver layout is bucket-major; `config_rank` is the GLOBAL
    #: body id fibers' `binding_body` refers to, and trajectory writers sort
    #: bodies back to it so the wire stays reference- (config-) ordered.
    config_rank: jnp.ndarray = None

    @property
    def n_bodies(self) -> int:
        return self.nodes_ref.shape[0]

    @property
    def n_nodes(self) -> int:
        return self.nodes_ref.shape[1]

    @property
    def solution_size(self) -> int:
        return self.n_bodies * (3 * self.n_nodes + 6)


class BodyCaches(NamedTuple):
    nodes: jnp.ndarray       # [nb, n, 3] lab frame
    normals: jnp.ndarray     # [nb, n, 3] lab frame
    nucleation_sites: jnp.ndarray  # [nb, ns, 3] lab frame
    K: jnp.ndarray           # [nb, 3n, 6]
    ex: jnp.ndarray          # [nb, n, 3] singularity-subtraction vectors
    ey: jnp.ndarray
    ez: jnp.ndarray
    lu: jnp.ndarray          # batched LU of the dense body operator
    piv: jnp.ndarray


def make_group(nodes_ref, normals_ref, weights, *, position=None, orientation=None,
               nucleation_sites_ref=None, external_force=0.0, external_torque=0.0,
               ext_force_type=EXTFORCE_LINEAR, osc_amplitude=0.0, osc_omega=0.0,
               osc_phase=0.0, radius=0.0, kind="sphere", semiaxes=0.0,
               config_rank=None, dtype=jnp.float64) -> BodyGroup:
    nodes_ref = jnp.asarray(nodes_ref, dtype=dtype)
    if nodes_ref.ndim == 2:
        nodes_ref = nodes_ref[None]
    nb, n = nodes_ref.shape[0], nodes_ref.shape[1]

    def mat(v, shape):
        return jnp.broadcast_to(jnp.asarray(v, dtype=dtype), shape)

    if nucleation_sites_ref is None:
        nucleation_sites_ref = jnp.zeros((nb, 0, 3), dtype=dtype)
    else:
        nucleation_sites_ref = jnp.asarray(nucleation_sites_ref, dtype=dtype)
        if nucleation_sites_ref.ndim == 2:
            nucleation_sites_ref = jnp.broadcast_to(
                nucleation_sites_ref[None], (nb,) + nucleation_sites_ref.shape)

    return BodyGroup(
        nodes_ref=nodes_ref,
        normals_ref=mat(normals_ref, (nb, n, 3)),
        weights=mat(weights, (nb, n)),
        nucleation_sites_ref=nucleation_sites_ref,
        position=mat(0.0 if position is None else position, (nb, 3)),
        orientation=(jnp.broadcast_to(jnp.asarray(quat.IDENTITY, dtype=dtype), (nb, 4))
                     if orientation is None else mat(orientation, (nb, 4))),
        solution=jnp.zeros((nb, 3 * n + 6), dtype=dtype),
        velocity=jnp.zeros((nb, 3), dtype=dtype),
        angular_velocity=jnp.zeros((nb, 3), dtype=dtype),
        external_force=mat(external_force, (nb, 3)),
        external_torque=mat(external_torque, (nb, 3)),
        ext_force_type=jnp.broadcast_to(jnp.asarray(ext_force_type, jnp.int32), (nb,)),
        osc_amplitude=mat(osc_amplitude, (nb,)),
        osc_omega=mat(osc_omega, (nb,)),
        osc_phase=mat(osc_phase, (nb,)),
        radius=mat(radius, (nb,)),
        kind_sphere=jnp.broadcast_to(jnp.asarray(kind == "sphere"), (nb,)),
        semiaxes=mat(semiaxes, (nb, 3)),
        config_rank=(jnp.arange(nb, dtype=jnp.int32) if config_rank is None
                     else jnp.asarray(config_rank, dtype=jnp.int32)),
    )


def as_buckets(bodies) -> tuple:
    """Normalize a bodies field (None | BodyGroup | iterable of buckets) to
    a tuple. `BodyGroup` is itself a NamedTuple, so the single-group test
    must precede generic tuple handling."""
    if bodies is None:
        return ()
    if isinstance(bodies, BodyGroup):
        return (bodies,)
    return tuple(bodies)


def n_total(bodies) -> int:
    """Total body count across buckets (the global `binding_body` id space)."""
    return sum(g.n_bodies for g in as_buckets(bodies))


def local_binding(fibers, group: BodyGroup, n_bodies_total: int):
    """Remap fibers' GLOBAL `binding_body` ids into ``group``-local slots.

    Returns a fibers view whose `binding_body` is the local slot for fibers
    bound to a body in this bucket and -1 otherwise — what the per-bucket
    `link_conditions` / `repin_to_bodies` expect. The lookup table is built
    from `config_rank` (the global id of each slot), host-independent and
    jit-safe (static shapes).
    """
    ranks = (group.config_rank if group.config_rank is not None
             else jnp.arange(group.n_bodies, dtype=jnp.int32))
    lookup = jnp.full((max(n_bodies_total, 1),), -1, dtype=jnp.int32)
    lookup = lookup.at[ranks].set(jnp.arange(group.n_bodies, dtype=jnp.int32))
    bb = fibers.binding_body
    local = jnp.where(bb >= 0, lookup[jnp.clip(bb, 0, n_bodies_total - 1)], -1)
    return fibers._replace(binding_body=local)


# ----------------------------------------------------------------- kinematics

def place(group: BodyGroup):
    """Lab-frame nodes/normals/nucleation sites (`SphericalBody::place`,
    `body_spherical.cpp:146-159`)."""
    rot = quat.rotation_matrix(group.orientation)          # [nb, 3, 3]
    nodes = group.position[:, None, :] + jnp.einsum("bij,bnj->bni", rot, group.nodes_ref)
    normals = jnp.einsum("bij,bnj->bni", rot, group.normals_ref)
    sites = group.position[:, None, :] + jnp.einsum("bij,bsj->bsi", rot,
                                                    group.nucleation_sites_ref)
    return nodes, normals, sites


def update_cache(group: BodyGroup, eta, precond_dtype=None) -> BodyCaches:
    """Lab placement + singularity subtraction + K matrix + dense LU
    (`update_cache_variables`, `body_spherical.cpp:94-127`).

    ``precond_dtype`` stores the LU factors in a lower precision (f32 for
    TPU, whose LuDecomposition is f32-only)."""
    nodes, normals, sites = place(group)
    nb, n = group.n_bodies, group.n_nodes

    def sing(nodes_b, normals_b, w_b, k):
        e = jnp.zeros((n, 3), dtype=nodes_b.dtype).at[:, k].set(w_b)
        return kernels.stresslet_times_normal_times_density(nodes_b, normals_b, e, eta)

    ex = jax.vmap(lambda a, b, w: sing(a, b, w, 0))(nodes, normals, group.weights)
    ey = jax.vmap(lambda a, b, w: sing(a, b, w, 1))(nodes, normals, group.weights)
    ez = jax.vmap(lambda a, b, w: sing(a, b, w, 2))(nodes, normals, group.weights)

    # K: node-major 3-row blocks [I | cross(r)] (`update_K_matrix`, `:74-86`)
    vec = nodes - group.position[:, None, :]               # [nb, n, 3]
    eye3 = jnp.eye(3, dtype=nodes.dtype)

    def k_node(v):
        rotpart = jnp.array([[0.0, v[2], -v[1]],
                             [-v[2], 0.0, v[0]],
                             [v[1], -v[0], 0.0]], dtype=v.dtype)
        return jnp.concatenate([eye3, rotpart], axis=1)    # [3, 6]

    K = jax.vmap(jax.vmap(k_node))(vec).reshape(nb, 3 * n, 6)

    # dense operator A (`update_preconditioner`, `:104-127`); assembled in
    # the 2-D [3n, 3n] layout throughout — a [.., n, 3]-shaped intermediate
    # would be tile-padded 3 -> 128 by XLA (42x HBM)
    def build_A(nodes_b, normals_b, w_b, ex_b, ey_b, ez_b, K_b):
        M = kernels.stresslet_times_normal_blocked(
            nodes_b, normals_b, eta, block_size=min(512, -(-n // 8) * 8))
        M = kernels.subtract_singularity_columns(M, (ex_b, ey_b, ez_b), w_b)
        top = jnp.concatenate([M, -K_b], axis=1)
        bottom = jnp.concatenate([-K_b.T, jnp.eye(6, dtype=M.dtype)], axis=1)
        return jnp.concatenate([top, bottom], axis=0)

    A = jax.vmap(build_A)(nodes, normals, group.weights, ex, ey, ez, K)
    if precond_dtype is not None:
        A = A.astype(precond_dtype)
    lu, piv = jax.vmap(jax.scipy.linalg.lu_factor)(A)

    return BodyCaches(nodes=nodes, normals=normals, nucleation_sites=sites,
                      K=K, ex=ex, ey=ey, ez=ez, lu=lu, piv=piv)


# ------------------------------------------------------------------ operators

def matvec(group: BodyGroup, caches: BodyCaches, x_bodies, v_bodies):
    """A_body x per body (`SphericalBody::matvec`, `body_spherical.cpp:39-63`).

    ``x_bodies`` [nb, 3n+6]; ``v_bodies`` [nb, n, 3] velocities at body nodes.
    """
    nb, n = group.n_bodies, group.n_nodes
    d = x_bodies[:, :3 * n].reshape(nb, n, 3)
    U = x_bodies[:, 3 * n:]

    c = (d[:, :, 0:1] / group.weights[..., None] * caches.ex
         + d[:, :, 1:2] / group.weights[..., None] * caches.ey
         + d[:, :, 2:3] / group.weights[..., None] * caches.ez)   # [nb, n, 3]

    KU = jnp.einsum("bik,bk->bi", caches.K, U)                    # [nb, 3n]
    KTl = jnp.einsum("bik,bi->bk", caches.K, d.reshape(nb, 3 * n))

    res_nodes = -c.reshape(nb, 3 * n) - KU + v_bodies.reshape(nb, 3 * n)
    res_com = -KTl + U
    return jnp.concatenate([res_nodes, res_com], axis=1)


def apply_preconditioner(group: BodyGroup, caches: BodyCaches, x_bodies):
    """Dense LU solves (`apply_preconditioner`, `body_spherical.cpp:37`);
    solves in the LU factors' (possibly lower) precision and casts back."""
    out = jax.vmap(lambda lu, piv, b: jax.scipy.linalg.lu_solve((lu, piv), b))(
        caches.lu, caches.piv, x_bodies.astype(caches.lu.dtype))
    return out.astype(x_bodies.dtype)


def update_RHS(group: BodyGroup, v_on_bodies):
    """RHS = [-v_nodes, 0(6)] per body (`update_RHS`, `body_spherical.cpp:134-138`)."""
    nb, n = group.n_bodies, group.n_nodes
    return jnp.concatenate([-v_on_bodies.reshape(nb, 3 * n),
                            jnp.zeros((nb, 6), dtype=v_on_bodies.dtype)], axis=1)


def flow(group: BodyGroup, caches: BodyCaches, r_trg, x_bodies, forces_torques,
         eta, impl: str = "exact", ewald_plan=None, ewald_anchors=None,
         pair=None, pair_anchors=None):
    """Body -> target velocities (`flow_spherical`, `body_container.cpp:269-339`):
    double-layer stresslet from node densities + Stokeslet from COM forces +
    rotlet from COM torques. ``forces_torques`` is [nb, 6]. Pass
    ``x_bodies=None`` to skip the stresslet term (e.g. the explicit RHS flow,
    which only carries COM forces/torques). The COM Stokeslet/rotlet stay on
    the exact tile regardless of ``impl`` — nb sources are negligible.

    With an ``ewald_plan`` (covering body nodes + targets) the node-density
    double layer sums through the spectral-Ewald stresslet — the
    one-evaluator-serves-all seam (`body_container.cpp:552-573` routes body
    flows through the FMM); a ``pair`` spec (`ops.evaluator.PairEvaluator`)
    carrying a `TreePlan` routes it through the barycentric-treecode
    stresslet instead. Coincident body-node targets drop in every mode
    (no stresslet self term)."""
    from ..ops.evaluator import resolve

    nb, n = group.n_bodies, group.n_nodes
    _, impl, ewald_plan, ewald_anchors, pair_anchors = resolve(
        pair, pair_anchors, r_trg.dtype, impl=impl, ewald_plan=ewald_plan,
        ewald_anchors=ewald_anchors)
    if x_bodies is None:
        v = jnp.zeros_like(r_trg)
    else:
        densities = x_bodies[:, :3 * n].reshape(nb * n, 3)
        normals = caches.normals.reshape(nb * n, 3)
        f_dl = 2.0 * eta * normals[:, :, None] * densities[:, None, :]
        if (pair is not None and pair.evaluator == "tree"
                and pair.plan is not None and pair.plan.depth > 0):
            from ..ops import treecode as tcode

            v = tcode._stresslet_tree_impl(
                pair.plan, pair_anchors, caches.nodes.reshape(nb * n, 3),
                r_trg, f_dl, eta)
        elif (pair is not None and pair.evaluator == "spectral"
                and pair.plan is not None):
            from ..ops import spectral as spec

            v = spec._stresslet_spectral_impl(
                pair.plan, pair_anchors, caches.nodes.reshape(nb * n, 3),
                r_trg, f_dl) * (pair.plan.eta / eta)
        elif ewald_plan is not None:
            from ..ops import ewald as ew

            if ewald_anchors is None:
                ewald_anchors = ew.plan_anchors(ewald_plan, r_trg.dtype)
                ewald_plan = ew.strip_anchors(ewald_plan)
            v = ew._stresslet_ewald_impl(
                ewald_plan, ewald_anchors, caches.nodes.reshape(nb * n, 3),
                r_trg, f_dl) * (ewald_plan.eta / eta)
        else:
            v = kernels.stresslet_direct(caches.nodes.reshape(nb * n, 3),
                                         r_trg, f_dl, eta, impl=impl)
    v = v + kernels.stokeslet_direct(group.position, r_trg, forces_torques[:, :3], eta)
    v = v + kernels.rotlet(group.position, r_trg, forces_torques[:, 3:], eta)
    return v


def external_forces_torques(group: BodyGroup, time):
    """Linear / oscillatory force schedule [nb, 6]
    (`calculate_external_forces_torques`, `body_container.cpp:413-447`)."""
    osc = group.osc_amplitude * jnp.sin(group.osc_omega * time - group.osc_phase)
    scale = jnp.where(group.ext_force_type == EXTFORCE_OSCILLATORY, osc, 1.0)
    force = scale[:, None] * group.external_force
    return jnp.concatenate([force, group.external_torque], axis=1)


def step(group: BodyGroup, body_sol, dt) -> BodyGroup:
    """Integrate rigid motion (`SphericalBody::step`, `body_spherical.cpp:13-35`)."""
    nb, n = group.n_bodies, group.n_nodes
    U = body_sol[:, 3 * n:3 * n + 3]
    omega = body_sol[:, 3 * n + 3:]
    new_pos = group.position + U * dt
    dq = quat.from_rotation_vector(omega * dt)
    new_q = quat.normalize(quat.multiply(dq, group.orientation))
    return group._replace(position=new_pos, orientation=new_q, solution=body_sol,
                          velocity=U, angular_velocity=omega)


# ------------------------------------------------------------- link conditions

def link_conditions(group: BodyGroup, caches: BodyCaches, fibers, fiber_caches,
                    fiber_sol, x_bodies):
    """Fiber <-> body attachment coupling (`calculate_link_conditions`,
    `body_container.cpp:170-267`).

    Returns (velocities_on_fiber [nf, 7], body_forces_torques [nb, 6]).
    ``fiber_sol`` is [nf, 4n_f] in [x|y|z|T] block layout.
    """
    nf, n_f = fibers.n_fibers, fibers.n_nodes
    nb, n = group.n_bodies, group.n_nodes
    dtype = fiber_sol.dtype
    mats = fibers.mats

    attached = fibers.binding_body >= 0
    body_idx = jnp.clip(fibers.binding_body, 0, nb - 1)
    site_idx = jnp.clip(fibers.binding_site, 0,
                        max(group.nucleation_sites_ref.shape[1] - 1, 0))

    body_vel = x_bodies[:, 3 * n:3 * n + 3]
    body_omega = x_bodies[:, 3 * n + 3:]

    if group.nucleation_sites_ref.shape[1] == 0:
        return (jnp.zeros((nf, 7), dtype=dtype), jnp.zeros((nb, 6), dtype=dtype))

    sites = caches.nucleation_sites[body_idx, site_idx]          # [nf, 3]
    site_pos = sites - group.position[body_idx]                  # body-frame offset

    x_new = jnp.stack([fiber_sol[:, :n_f], fiber_sol[:, n_f:2 * n_f],
                       fiber_sol[:, 2 * n_f:3 * n_f]], axis=-1)  # [nf, n_f, 3]
    T0 = fiber_sol[:, 3 * n_f]
    xs0 = fiber_caches.xs[:, 0]                                  # [nf, 3] old tangent

    s = 2.0 / fibers.length
    D2, D3 = jnp.asarray(mats.D2, dtype=dtype), jnp.asarray(mats.D3, dtype=dtype)
    xss0 = (s[:, None] ** 2) * jnp.einsum("j,fjk->fk", D2[0], x_new)
    xsss0 = (s[:, None] ** 3) * jnp.einsum("j,fjk->fk", D3[0], x_new)

    E = fibers.bending_rigidity[:, None]
    F_body = -E * xsss0 + xs0 * T0[:, None]
    L_body = (-E * jnp.cross(site_pos, xsss0)
              + jnp.cross(site_pos, xs0) * T0[:, None]
              + E * jnp.cross(xs0, xss0))

    ft = jnp.where(attached[:, None], jnp.concatenate([F_body, L_body], axis=1), 0.0)
    body_ft = jax.ops.segment_sum(ft, body_idx, num_segments=nb)

    vb = body_vel[body_idx]
    wb = body_omega[body_idx]
    v_fiber = -vb - jnp.cross(wb, site_pos)
    tension_cond = -jnp.einsum("fk,fk->f", xs0, vb) \
        + jnp.einsum("fk,fk->f", jnp.cross(xs0, site_pos), wb)
    site_hat = site_pos / jnp.linalg.norm(site_pos, axis=1, keepdims=True)
    w_fiber = jnp.cross(site_hat, wb)

    v7 = jnp.concatenate([v_fiber, tension_cond[:, None], w_fiber], axis=1)
    v7 = jnp.where(attached[:, None], v7, 0.0)
    return v7, body_ft


def repin_to_bodies(fibers, nucleation_sites, group: BodyGroup):
    """Move attached fiber minus ends back onto their nucleation sites
    (`repin_to_bodies`, `fiber_container_finite_difference.cpp:308-316`).
    ``nucleation_sites`` is the lab-frame [nb, ns, 3] array from `place`."""
    if group.nucleation_sites_ref.shape[1] == 0:
        return fibers
    attached = fibers.binding_body >= 0
    body_idx = jnp.clip(fibers.binding_body, 0, group.n_bodies - 1)
    site_idx = jnp.clip(fibers.binding_site, 0, group.nucleation_sites_ref.shape[1] - 1)
    sites = nucleation_sites[body_idx, site_idx]
    delta = jnp.where(attached[:, None], sites - fibers.x[:, 0], 0.0)
    return fibers._replace(x=fibers.x + delta[:, None, :])


# ------------------------------------------------------------------ collisions

def check_collision_shell(group: BodyGroup, shell_radius, threshold):
    """Spherical body vs spherical periphery (`periphery.cpp:94-97`);
    non-sphere pairs never collide (reference stub parity)."""
    dist = jnp.linalg.norm(group.position, axis=1) + group.radius
    hit = (dist > (shell_radius - threshold)) & group.kind_sphere
    return jnp.any(hit)


def check_collision_pairwise(group: BodyGroup, threshold):
    """Sphere-sphere body collisions (`body_spherical.cpp:304-307`)."""
    nb = group.n_bodies
    d2 = jnp.sum((group.position[:, None, :] - group.position[None, :, :]) ** 2, axis=-1)
    rsum = group.radius[:, None] + group.radius[None, :] + threshold
    both_spheres = group.kind_sphere[:, None] & group.kind_sphere[None, :]
    offdiag = ~jnp.eye(nb, dtype=bool)
    return jnp.any((d2 < rsum**2) & both_spheres & offdiag)


def check_collision_pairwise_multi(buckets, threshold):
    """Sphere-sphere collisions across ALL buckets (collision only needs the
    per-body position/radius/kind columns, which concatenate trivially)."""
    buckets = as_buckets(buckets)
    if not buckets:
        return jnp.asarray(False)
    flat = BodyGroup(
        nodes_ref=jnp.zeros((n_total(buckets), 0, 3),
                            dtype=buckets[0].position.dtype),
        normals_ref=None, weights=None, nucleation_sites_ref=None,
        position=jnp.concatenate([g.position for g in buckets]),
        orientation=None, solution=None, velocity=None, angular_velocity=None,
        external_force=None, external_torque=None, ext_force_type=None,
        osc_amplitude=None, osc_omega=None, osc_phase=None,
        radius=jnp.concatenate([g.radius for g in buckets]),
        kind_sphere=jnp.concatenate([g.kind_sphere for g in buckets]))
    return check_collision_pairwise(flat, threshold)


def check_collision_shell_multi(buckets, shell_radius, threshold):
    buckets = as_buckets(buckets)
    hit = jnp.asarray(False)
    for g in buckets:
        hit = hit | check_collision_shell(g, shell_radius, threshold)
    return hit
