"""Process driver: run / resume / listen on a TOML config.

Counterpart of the reference CLI (`/root/reference/src/skelly_sim.cpp:12-68`):
flag parsing, trajectory-existence guards, dispatch to the time loop or the
listener server. No MPI/Kokkos boot — device setup is JAX's.

Usage: python -m skellysim_tpu [--config-file=...] [--resume] [--overwrite] [--listen]
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys

from .builder import build_simulation
from .io.trajectory import TrajectoryWriter, resume_state
from .utils.rng import SimRNG

TRAJECTORY_FILE = "skelly_sim.out"


def _snapshot_path(traj: str, suffix: str) -> str:
    """Sibling snapshot path: 'skelly_sim.out' -> 'skelly_sim.<suffix>'.

    A trajectory path without the '.out' extension gets the suffix appended,
    never substituted — a naive str.replace could alias the trajectory itself.
    """
    base, ext = os.path.splitext(traj)
    return (base if ext == ".out" else traj) + "." + suffix


def run(config_file: str, resume: bool = False, overwrite: bool = False,
        trajectory_path: str | None = None,
        metrics_path: str | None = None,
        trace_path: str | None = None,
        profile_dir: str | None = None) -> None:
    traj = trajectory_path or os.path.join(
        os.path.dirname(os.path.abspath(config_file)) or ".", TRAJECTORY_FILE)

    # trajectory guards (`skelly_sim.cpp:32-50`)
    if os.path.exists(traj) and not (resume or overwrite):
        sys.exit(f"Trajectory '{traj}' already exists and neither --resume nor "
                 "--overwrite was given; refusing to clobber it")
    if resume and not os.path.exists(traj):
        sys.exit(f"--resume given but trajectory '{traj}' does not exist")

    system, state, rng = build_simulation(config_file)

    # skelly-bucket: quantize the scene onto its capacity bucket BEFORE the
    # first compile, so every scene sharing the bucket key hits one warm
    # program (docs/performance.md). The default policy is the identity;
    # [runtime] ladders opt into padding.
    from .config.schema import load_runtime_config
    from .system import buckets as bucket_mod

    policy = bucket_mod.BucketPolicy.from_runtime(
        load_runtime_config(config_file))
    # the spectral evaluator's grid rungs are plan data, not state shapes —
    # they ride the System, not bucketize
    system.grid_ladder = policy.grid_ladder
    state, bucket_key = bucket_mod.bucketize(
        state, policy, pair_evaluator=system.params.pair_evaluator)
    import logging

    logging.getLogger("skellysim_tpu").info(
        "scene bucket: %s", bucket_key.describe())

    if resume:
        state, rng_state, reader = resume_state(traj, state)
        reader.close()
        # resume rebuilds fibers from the frame (live rows only) — re-land
        # on the same bucket so the warm program still serves the run
        state, bucket_key = bucket_mod.bucketize(
            state, policy, pair_evaluator=system.params.pair_evaluator)
        if rng_state:
            rng = SimRNG.from_state(rng_state)
        writer = TrajectoryWriter(traj, append=True)
        if metrics_path and os.path.exists(metrics_path):
            # marker line segmenting runs in an appended metrics file: step
            # indices restart at 0 per run, so post-hoc analysis needs the
            # boundary (schema note at system.METRICS_FIELDS)
            import json

            with open(metrics_path, "a") as fh:
                fh.write(json.dumps({"resume": True,
                                     "t": float(state.time)}) + "\n")
        print(f"Resuming from t={float(state.time):.6g}")
    else:
        writer = TrajectoryWriter(traj)
        # initial config snapshot (`system.cpp:716`, `skelly_sim.initial_config`)
        shutil.copyfile(config_file, _snapshot_path(traj, "initial_config"))
        writer.write_frame(state, rng_state=rng.dump_state())

    with writer:
        final = system.run(state, writer=writer.write_frame, rng=rng,
                           metrics_path=metrics_path, trace_path=trace_path,
                           profile_dir=profile_dir)

    shutil.copyfile(config_file, _snapshot_path(traj, "final_config"))
    print(f"Finished at t={float(final.time):.6g}")


def resolve_cache_dir(config_file: str, *, flag: str | None,
                      off: bool) -> str:
    """Persistent-cache resolution shared by the CLIs: ``--no-jax-cache`` >
    ``--jax-cache DIR`` > the config's ``[runtime] jax_cache`` > "auto"
    (default-on at `utils.bootstrap.default_cache_dir`). A missing/broken
    config falls back to "auto" — cache wiring must never mask the real
    config error the build step will report properly."""
    if off:
        return "off"
    if flag:
        return flag
    try:
        from .config.schema import load_runtime_config

        return load_runtime_config(config_file).jax_cache
    except Exception:
        return "auto"


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="skellysim-tpu",
        description="TPU-native cytoskeletal hydrodynamics simulator")
    ap.add_argument("--config-file", default="skelly_config.toml")
    ap.add_argument("--resume", action="store_true",
                    help="continue an existing trajectory from its last frame")
    ap.add_argument("--overwrite", action="store_true",
                    help="overwrite an existing trajectory")
    ap.add_argument("--listen", action="store_true",
                    help="post-processing server: msgpack requests on stdin")
    ap.add_argument("--metrics-file", default=None,
                    help="append one JSON line of step metrics per trial step")
    ap.add_argument("--trace-file", default=None,
                    help="skelly-scope telemetry JSONL (span + compile "
                         "events; render with `python -m skellysim_tpu.obs "
                         "summarize`, docs/observability.md)")
    ap.add_argument("--profile", default=None, metavar="DIR",
                    help="device profiler capture of the whole loop "
                         "(obs.profile.profile_session — python tracer "
                         "off so device ops survive the buffer); the dump "
                         "is parsed afterwards and device_phase events "
                         "are appended to --trace-file. Render with "
                         "`python -m skellysim_tpu.obs profile DIR` / "
                         "`obs timeline` (docs/observability.md)")
    ap.add_argument("--jax-cache", default=None, metavar="DIR",
                    help="persistent XLA compilation cache directory shared "
                         "across runs/CLIs (default: [runtime] jax_cache, "
                         "falling back to the package .jax_cache — the "
                         "cache is ON unless --no-jax-cache)")
    ap.add_argument("--no-jax-cache", action="store_true",
                    help="disable the persistent compilation cache "
                         "(equivalent to [runtime] jax_cache = 'off')")
    ap.add_argument("--log-level", default=os.environ.get("SKELLYSIM_LOG", "INFO"),
                    help="log level for the skellysim_tpu logger "
                         "(the reference reads SPDLOG_LEVEL similarly)")
    args = ap.parse_args(argv)

    import logging

    logging.basicConfig(level=args.log_level.upper(),
                        format="[%(asctime)s] [%(levelname)s] %(message)s",
                        stream=sys.stderr)

    # the builder's default f64 state is only real under x64: without this,
    # every array silently canonicalizes to f32 and a gmres_tol of 1e-10
    # floors at ~1e-5 while steps are still "accepted" (found by round-5
    # verify — the same silent-degradation class as the precompute CLI).
    # On TPU, f64 states route through the mixed-precision solver because
    # solver_precision DEFAULTS to "auto" (params.py/schema.py — "mixed" on
    # accelerators, "full" on CPU), so x64 does not put the hot loop on the
    # f32-only-LU / emulated-f64 cliff.
    import jax

    jax.config.update("jax_enable_x64", True)

    from .utils.bootstrap import enable_compilation_cache

    enable_compilation_cache(resolve_cache_dir(
        args.config_file, flag=args.jax_cache, off=args.no_jax_cache))

    # multi-host bring-up (no-op single-process; the analogue of the
    # reference's MPI_Init, `skelly_sim.cpp:14`) — must run before any JAX
    # backend init so every host joins the same runtime
    from .parallel import initialize_multihost, process_info

    if initialize_multihost():
        logging.getLogger("skellysim_tpu").info(
            "multi-host runtime: %s", process_info())

    if args.listen:
        from .listener import serve  # deferred: heavy post-processing imports
        serve(args.config_file)
        return
    run(args.config_file, resume=args.resume, overwrite=args.overwrite,
        metrics_path=args.metrics_file, trace_path=args.trace_file,
        profile_dir=args.profile)


if __name__ == "__main__":
    main()
