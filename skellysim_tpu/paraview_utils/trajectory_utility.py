"""Standalone trajectory frame indexing/loading for the ParaView readers.

Counterpart of the reference `paraview_utils/trajectory_utility.py`: no
package imports so ParaView's Python can exec it next to the reader scripts.
Handles both single-file trajectories (this framework) and the reference's
per-rank multi-file layout (`skelly_sim.out.0`, `.1`, ...).
"""

import msgpack


class DesyncError(Exception):
    pass


def get_frame_info(filenames):
    """(file handles, per-file frame offsets, times) for a set of trajectory
    files; skips each file's header frame."""
    if not filenames:
        return [], [], []

    fhs, fpos_all, times = [], [], []
    for filename in filenames:
        f = open(filename, "rb")
        fhs.append(f)
        unpacker = msgpack.Unpacker(f, raw=False)
        fpos = []
        ftimes = []
        while True:
            try:
                pos = unpacker.tell()
                obj = unpacker.unpack()
            except msgpack.exceptions.OutOfData:
                break
            if isinstance(obj, dict) and "time" in obj:
                fpos.append(pos)
                ftimes.append(obj["time"])
        fpos_all.append(fpos)
        if not times:
            times = ftimes
        elif times != ftimes:
            raise DesyncError("trajectory files disagree on frame times")
    return fhs, fpos_all, times


def load_frame(fhs, fpos, index):
    """Merge the index-th frame across files; fibers concatenate, bodies and
    shell come from the first file (rank 0 in the reference layout)."""
    data = []
    for i in range(len(fhs)):
        fhs[i].seek(fpos[i][index])
        data.append(msgpack.Unpacker(fhs[i], raw=False).unpack())

    time, dt = data[0]["time"], data[0]["dt"]
    fibers = []
    for el in data:
        if el["time"] != time or el["dt"] != dt:
            raise DesyncError
        fibers.extend(el["fibers"][1])
        el.pop("fibers")

    frame = data[0]
    frame["fibers"] = fibers
    # flatten [spherical, deformable, ellipsoidal] sublists
    frame["bodies"] = [b for sub in frame["bodies"] for b in sub]
    return frame


def load_field_frame(fhs, fpos, index):
    """Raw per-file frames of a velocity-field dump (no merging)."""
    data = []
    for i in range(len(fhs)):
        fhs[i].seek(fpos[i][index])
        data.append(msgpack.Unpacker(fhs[i], raw=False).unpack())
    return data


def eigen_points(field):
    """['__eigen__', rows, cols, ...] -> list of [x, y, z] points."""
    rows, cols = field[1], field[2]
    flat = field[3:]
    if rows == 3:
        return [flat[3 * i:3 * i + 3] for i in range(cols)]
    if cols == 1 or rows == 1:
        n = len(flat) // 3
        return [flat[3 * i:3 * i + 3] for i in range(n)]
    raise ValueError(f"cannot interpret eigen field {rows}x{cols} as points")
