"""ParaView RequestInformation script for the velocity-field reader."""

from pathlib import Path

import vtk  # noqa: F401
from trajectory_utility import get_frame_info

outInfo = self.GetOutputInformation(0)  # noqa: F821
files = (sorted(Path(".").glob("skelly_sim.vf.*"))
         or [p for p in [Path("skelly_sim.vf")] if p.exists()])
self.fhs, self.fpos, self.times = get_frame_info(files)  # noqa: F821
outInfo.Set(vtk.vtkStreamingDemandDrivenPipeline.TIME_RANGE(),
            [self.times[0], self.times[-1]], 2)  # noqa: F821
outInfo.Set(vtk.vtkStreamingDemandDrivenPipeline.TIME_STEPS(),
            self.times, len(self.times))  # noqa: F821
