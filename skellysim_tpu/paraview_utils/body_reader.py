"""ParaView Programmable Source: bodies as sphere glyphs (RequestData body).

Use `fiber_reader_request.py` as the RequestInformation script. Mirrors the
reference `paraview_utils/body_reader.py`; body radii come from
`skelly_config.toml` next to the trajectory.
"""

import toml
import vtk  # noqa: F401
from trajectory_utility import load_frame

toml_file = "skelly_config.toml"

outInfo = self.GetOutputInformation(0)  # noqa: F821

if outInfo.Has(vtk.vtkStreamingDemandDrivenPipeline.UPDATE_TIME_STEP()):
    time = outInfo.Get(vtk.vtkStreamingDemandDrivenPipeline.UPDATE_TIME_STEP())
else:
    time = 0

timestep = len(self.times) - 1  # noqa: F821
for i in range(len(self.times) - 1):  # noqa: F821
    if self.times[i] <= time < self.times[i + 1]:  # noqa: F821
        timestep = i
        break

frame = load_frame(self.fhs, self.fpos, timestep)  # noqa: F821

with open(toml_file) as f:
    skelly_config = toml.load(f)

mb = vtk.vtkMultiBlockDataSet()
for i, body in enumerate(frame["bodies"]):
    position = body["position_"][3:]  # ['__eigen__', 3, 1, x, y, z]
    s = vtk.vtkSphereSource()
    s.SetRadius(skelly_config["bodies"][i]["radius"])
    s.SetCenter(position)
    s.SetThetaResolution(32)
    s.SetPhiResolution(32)
    s.Update()
    mb.SetBlock(i, s.GetOutput())

self.GetOutput().ShallowCopy(mb)  # noqa: F821
