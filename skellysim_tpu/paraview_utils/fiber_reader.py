"""ParaView Programmable Source: fibers as polylines (RequestData body).

Paste into a Programmable Source with `fiber_reader_request.py` as the
RequestInformation script; set `self.times/fhs/fpos` there. Mirrors the
reference `paraview_utils/fiber_reader.py`.
"""

import vtk  # noqa: F401  (provided by ParaView's Python)
from trajectory_utility import eigen_points, load_frame

outInfo = self.GetOutputInformation(0)  # noqa: F821 (ParaView binds `self`)

if outInfo.Has(vtk.vtkStreamingDemandDrivenPipeline.UPDATE_TIME_STEP()):
    time = outInfo.Get(vtk.vtkStreamingDemandDrivenPipeline.UPDATE_TIME_STEP())
else:
    time = 0

timestep = len(self.times) - 1  # noqa: F821
for i in range(len(self.times) - 1):  # noqa: F821
    if self.times[i] <= time < self.times[i + 1]:  # noqa: F821
        timestep = i
        break

frame = load_frame(self.fhs, self.fpos, timestep)  # noqa: F821

pts = vtk.vtkPoints()
lines = vtk.vtkCellArray()
offset = 0
for fib in frame["fibers"]:
    nodes = eigen_points(fib["x_"])
    lines.InsertNextCell(len(nodes))
    for node in nodes:
        lines.InsertCellPoint(offset)
        pts.InsertPoint(offset, node)
        offset += 1

pd = self.GetPolyDataOutput()  # noqa: F821
pd.SetPoints(pts)
pd.SetLines(lines)
