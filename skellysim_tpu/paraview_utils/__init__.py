"""ParaView programmable-source readers for skellysim_tpu trajectories.

Mirror of the reference toolkit (`/root/reference/src/skelly_sim/paraview_utils/`):
each `*_reader.py` is the RequestData body of a ParaView Programmable Source
and each `*_reader_request.py` its RequestInformation script;
`trajectory_utility.py` is the standalone frame indexer/loader they share
(standalone because ParaView executes these scripts outside this package).
The trajectory format is byte-compatible with the reference, so these readers
work on reference trajectories too (and vice versa).
"""
