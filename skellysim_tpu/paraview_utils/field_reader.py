"""ParaView Programmable Source: velocity-field point cloud (RequestData body).

Use `field_reader_request.py` as the RequestInformation script. Reads frames
{time, dt, x_grid, v_grid} written by `skellysim_tpu.io.FieldWriter` (or the
reference's `skelly_sim.vf.*` files). Mirrors the reference
`paraview_utils/field_reader.py`: points carry 'velocities' and 'magnitudes'
arrays.
"""

import vtk  # noqa: F401
from trajectory_utility import load_field_frame

outInfo = self.GetOutputInformation(0)  # noqa: F821

if outInfo.Has(vtk.vtkStreamingDemandDrivenPipeline.UPDATE_TIME_STEP()):
    time = outInfo.Get(vtk.vtkStreamingDemandDrivenPipeline.UPDATE_TIME_STEP())
else:
    time = 0

timestep = len(self.times) - 1  # noqa: F821
for i in range(len(self.times) - 1):  # noqa: F821
    if self.times[i] <= time < self.times[i + 1]:  # noqa: F821
        timestep = i
        break

frame = load_field_frame(self.fhs, self.fpos, timestep)  # noqa: F821

npts = int(sum(data["x_grid"][2] for data in frame))
pts = vtk.vtkPoints()

velocities = vtk.vtkDoubleArray()
velocities.SetName("velocities")
velocities.SetNumberOfComponents(3)
velocities.SetNumberOfTuples(npts)

magnitudes = vtk.vtkDoubleArray()
magnitudes.SetName("magnitudes")
magnitudes.SetNumberOfValues(npts)

offset = 0
for data in frame:
    n_local = data["x_grid"][2]
    x_grid = data["x_grid"][3:]
    v_grid = data["v_grid"][3:]
    for i in range(n_local):
        v = v_grid[3 * i:3 * (i + 1)]
        pts.InsertPoint(offset, x_grid[3 * i:3 * (i + 1)])
        velocities.SetTuple(offset, v)
        magnitudes.SetValue(offset, (v[0] ** 2 + v[1] ** 2 + v[2] ** 2) ** 0.5)
        offset += 1

pd = self.GetPolyDataOutput()  # noqa: F821
pd.SetPoints(pts)
pd.GetPointData().AddArray(velocities)
pd.GetPointData().AddArray(magnitudes)
