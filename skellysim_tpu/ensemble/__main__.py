"""`python -m skellysim_tpu.ensemble` — the ensemble sweep driver."""

from .cli import main

if __name__ == "__main__":
    main()
