"""Continuous-batching scheduler: queue -> lanes -> retire -> backfill.

The host-side half of the ensemble subsystem, shaped like an inference
server's batch scheduler: a fixed number of compiled lanes B, a work queue
of pending members, and a drain loop that steps the whole batch, writes
per-member trajectory frames at dt_write boundaries, retires members that
reach their ``t_final``, and immediately backfills freed lanes from the
queue — pure leaf substitution at fixed shapes (`runner.set_lane`), so a
10k-member sweep streams through ONE compiled program
(`testing.trace_counting_jit` pins the single trace in
tests/test_ensemble.py).

The per-step host work is one small device fetch (the [B] outcome vectors in
`EnsembleStepInfo`) plus frame encodes for whichever members crossed a write
boundary; the solves themselves never leave the device.
"""

from __future__ import annotations

import dataclasses
import logging
import time as _time
from collections import deque
from typing import Callable, Optional

import numpy as np

from ..guard import verdict as _verdict
from ..obs import flight as flight_mod
from ..obs import tracer as obs_tracer
from ..solver.gmres import history_rows
from ..system.system import SimState, crossed_write_boundary
from ..utils.rng import SimRNG
from .runner import EnsembleRunner, lane_state, rng_carry, set_lane

logger = logging.getLogger("skellysim_tpu")

#: ensemble t_final for an empty lane: `time < -inf` is never true, so idle
#: lanes are inert masked no-ops until the queue refills them
IDLE_T_FINAL = float("-inf")


def _fiber_capacity(state) -> int:
    """Total fiber-slot count of a member state (the growth-event id)."""
    from ..fibers import container as fc

    return sum(g.n_fibers for g in fc.as_buckets(state.fibers))


@dataclasses.dataclass
class MemberSpec:
    """One queued simulation: initial state + end time (+ optional RNG whose
    dump rides in the member's trajectory frames, `SimRNG.member(i)`)."""

    member_id: str
    state: SimState
    t_final: float
    rng: Optional[SimRNG] = None
    #: perf_counter timestamp of queue entry (stamped by the scheduler when
    #: absent); lane events report ``queue_wait_s`` — admission latency,
    #: the serving SLO — from it
    enqueued_at: Optional[float] = None


@dataclasses.dataclass
class _Lane:
    spec: MemberSpec
    steps: int = 0       # trial steps taken (accepted + rejected)
    frames: int = 0      # frames written (excluding the initial frame)
    t: float = 0.0       # entry time of the NEXT trial
    dt: float = 0.0      # entry dt of the NEXT trial


class EnsembleScheduler:
    """Drain a member queue through B compiled lanes.

    ``writer(member_id, state, rng_state=None)`` is called for each frame a
    member crosses (`io.ensemble_io.MemberTrajectoryWriters` is the
    file-based implementation; any callable works). ``metrics`` is a
    callable receiving one dict per record (`io.ensemble_io
    .EnsembleMetricsWriter.write`); record kinds are "start", "step",
    "retire", and "dt_underflow" (schema in docs/ensemble.md).

    ``step_fn`` overrides the runner's jit'd step — the trace-counting tests
    pass `testing.trace_counting_jit(runner.step_impl)` here.

    ``on_dt_underflow``: the sequential loop raises RuntimeError when the
    adaptive dt falls below dt_min; "raise" (default) mirrors that,
    "retire" retires just the failing member (recorded in metrics) and keeps
    the rest of the sweep running — the serving-shaped choice for large
    sweeps.

    ``on_failure``: what to do with a lane the runner quarantined on a
    TERMINAL health verdict (`EnsembleStepInfo.failed` — a nonfinite
    state no retry can repair; docs/robustness.md). "raise" (default)
    mirrors the sequential loop's eventual abort; "retire" (skelly-serve)
    retires just that member with reason ``"failed"`` — its metrics
    record and `on_retire` callback carry the decoded verdict, and its
    siblings' trajectories are bitwise-unaffected (the quarantine pin in
    tests/test_ensemble.py).

    ``template`` allows an INITIALLY-EMPTY scheduler (``members=[]``): a
    long-lived service (skelly-serve) constructs the compiled lanes before
    any tenant exists, then feeds them incrementally via `admit` + `poll`.
    The template state defines the lanes' static shapes — the capacity
    bucket every later member must match.

    ``on_retire(member_id, state, reason, **extra)`` receives the member's
    FINAL lane state the moment before its lane is freed — the exact
    snapshot point (possibly newer than its last dt_write frame);
    skelly-serve stores it for tenant snapshot/resume. ``extra`` carries
    structured failure context (``health``/``verdict``) on ``failed`` and
    ``dt_underflow`` retirements, plus ``rng_state`` (the member's current
    serialized RNG streams) whenever the member carries a `SimRNG`.

    ``on_growth``: what to do with a lane whose device dynamic-instability
    update reported ``needs_growth`` (the member's nucleation burst
    outgrew its capacity bucket; the runner froze the lane un-advanced).
    "raise" (default) aborts; "retire" retires the member with reason
    ``"growth"`` — its CURRENT state and synced RNG ride the `on_retire`
    callback, and the caller (scenarios.sweep, skelly-serve) re-admits it
    onto the next capacity rung (docs/scenarios.md "Growth reseats").
    """

    def __init__(self, runner: EnsembleRunner, members, batch: int, *,
                 writer: Optional[Callable] = None,
                 metrics: Optional[Callable] = None,
                 step_fn: Optional[Callable] = None,
                 write_initial_frames: bool = False,
                 on_dt_underflow: str = "raise",
                 on_failure: str = "raise",
                 on_growth: str = "raise",
                 max_rounds: Optional[int] = None,
                 template: Optional[SimState] = None,
                 on_retire: Optional[Callable] = None):
        if batch < 1:
            raise ValueError(f"batch must be >= 1, got {batch}")
        if on_dt_underflow not in ("raise", "retire"):
            raise ValueError(
                f"unknown on_dt_underflow {on_dt_underflow!r}; "
                "use 'raise' or 'retire'")
        if on_failure not in ("raise", "retire"):
            raise ValueError(
                f"unknown on_failure {on_failure!r}; use 'raise' or 'retire'")
        if on_growth not in ("raise", "retire"):
            raise ValueError(
                f"unknown on_growth {on_growth!r}; use 'raise' or 'retire'")
        members = list(members)
        if not members and template is None:
            raise ValueError("ensemble needs at least one member (or a "
                             "template= state for an initially-empty service)")
        self.runner = runner
        self.batch = batch
        self.queue = deque()
        self.writer = writer
        self.metrics = metrics
        self.step_fn = step_fn or runner.step
        self.write_initial_frames = write_initial_frames
        self.on_dt_underflow = on_dt_underflow
        self.on_failure = on_failure
        self.on_growth = on_growth
        self.on_retire = on_retire
        self.max_rounds = max_rounds
        self.rounds = 0
        self.retired: list = []
        #: template member state for idle-lane padding (inert masked lanes)
        self._template = template if template is not None else members[0].state
        self.lanes: list = [None] * batch
        # seed the lanes: every lane starts on the template (idle), then the
        # queue fills as many as it can
        self.ens = runner.make_ensemble([self._template] * batch,
                                        [IDLE_T_FINAL] * batch)
        for spec in members:
            self.admit(spec)

    # ----------------------------------------------------------- lane churn

    def _emit(self, record: dict):
        if self.metrics is not None:
            self.metrics(record)

    def _rng_state(self, spec: MemberSpec):
        return spec.rng.dump_state() if spec.rng is not None else None

    def _start_member(self, lane: int, spec: MemberSpec):
        # snapshot-decoded states carry no flight-recorder ring (the wire
        # never does) — normalize to the lanes' armed/stripped structure
        spec.state = self.runner.system.ensure_flight(spec.state)
        if self.runner.di_enabled and spec.rng is None:
            raise ValueError(
                f"member {spec.member_id}: dynamic-instability members need "
                "a per-member SimRNG (SimRNG(seed).member(i)) — the device "
                "DI update draws from its distributed stream")
        self.ens = self.ens._replace(
            states=set_lane(self.ens.states, lane, spec.state),
            t_final=self.ens.t_final.at[lane].set(spec.t_final))
        if self.runner.di_enabled:
            # seat the member's RNG stream carry next to its state leaves
            self.ens = self.ens._replace(
                di_rng=self.ens.di_rng.at[lane].set(rng_carry(spec.rng)))
        self.lanes[lane] = _Lane(spec=spec, t=float(spec.state.time),
                                 dt=float(spec.state.dt))
        # admission latency (queue entry -> lane seat): the serving SLO
        # skelly-serve's /stats reports; `obs summarize` folds it into the
        # lane-occupancy table
        wait_s = (max(0.0, _time.perf_counter() - spec.enqueued_at)
                  if spec.enqueued_at is not None else 0.0)
        # skelly-scope lane churn: "admit" seats a member before the first
        # batched step, "backfill" refills a lane freed mid-drain (the
        # continuous-batching move; obs summarize reports occupancy)
        obs_tracer.emit("lane",
                        action="admit" if self.rounds == 0 else "backfill",
                        lane=lane, member=spec.member_id,
                        queue_wait_s=round(wait_s, 6))
        self._emit({"event": "start", "member": spec.member_id, "lane": lane,
                    "t": float(spec.state.time), "t_final": spec.t_final,
                    "queue_wait_s": round(wait_s, 6)})
        if self.write_initial_frames and self.writer is not None:
            self.writer(spec.member_id, spec.state,
                        rng_state=self._rng_state(spec))
        logger.info("ensemble start member=%s lane=%d t_final=%g",
                    spec.member_id, lane, spec.t_final)

    def _retire_member(self, lane: int, reason: str = "finished",
                       final_state=None, extra: Optional[dict] = None):
        ln = self.lanes[lane]
        extra = extra or {}
        if self.on_retire is not None:
            # the member's exact final state, before the lane is reused —
            # the snapshot skelly-serve resumes evicted tenants from
            # (``final_state`` lets `evict` reuse its own fetch instead of
            # gathering the lane twice)
            if final_state is None:
                final_state = lane_state(self.ens.states, lane)
            self.on_retire(ln.spec.member_id, final_state, reason,
                           rng_state=self._rng_state(ln.spec), **extra)
        obs_tracer.emit("lane", action="retire", lane=lane,
                        member=ln.spec.member_id, reason=reason,
                        steps=ln.steps, **extra)
        self._emit({"event": "retire" if reason == "finished" else reason,
                    "member": ln.spec.member_id, "lane": lane, "t": ln.t,
                    "steps": ln.steps, "frames": ln.frames, **extra})
        logger.info("ensemble retire member=%s lane=%d t=%.6g steps=%d (%s)",
                    ln.spec.member_id, lane, ln.t, ln.steps, reason)
        self.retired.append(ln.spec.member_id)
        if (self.writer is not None and hasattr(self.writer, "close_member")
                and reason != "growth"):
            # file-based writers free the handle as the lane frees — except
            # on a growth reseat, where the member is about to re-admit at
            # the next capacity rung and keeps streaming to the same file
            self.writer.close_member(ln.spec.member_id)
        self.lanes[lane] = None
        self.ens = self.ens._replace(
            t_final=self.ens.t_final.at[lane].set(IDLE_T_FINAL))
        if self.queue:
            self._start_member(lane, self.queue.popleft())

    # -------------------------------------------------- incremental service

    def admit(self, spec: MemberSpec):
        """Enqueue one member; seat it immediately when a lane is free.

        The incremental half of the continuous-batching API (skelly-serve's
        admission path): lanes keep their compiled program — seating is pure
        leaf substitution (`runner.set_lane`), so tenants join a running
        service without retracing. Returns the lane index when the member
        seated now, None when it queued behind occupied lanes."""
        if spec.enqueued_at is None:
            spec.enqueued_at = _time.perf_counter()
        self.queue.append(spec)
        seated = None
        for lane in range(self.batch):
            if not self.queue:
                break
            if self.lanes[lane] is None:
                nxt = self.queue.popleft()
                self._start_member(lane, nxt)
                if nxt is spec:
                    seated = lane
        return seated

    def evict(self, lane: int, reason: str = "evicted") -> SimState:
        """Free an occupied lane mid-service and return the member's CURRENT
        state — the exact resume point, possibly newer than its last
        dt_write frame. The lane backfills from the queue like any
        retirement (skelly-serve's graceful-eviction path)."""
        if not 0 <= lane < self.batch or self.lanes[lane] is None:
            raise ValueError(f"evict: lane {lane} is not occupied")
        state = lane_state(self.ens.states, lane)
        self._retire_member(lane, reason=reason, final_state=state)
        return state

    def lane_of(self, member_id: str):
        """Lane index currently running ``member_id``, or None."""
        for lane, ln in enumerate(self.lanes):
            if ln is not None and ln.spec.member_id == member_id:
                return lane
        return None

    def unqueue(self, member_id: str) -> Optional[MemberSpec]:
        """Drop a still-QUEUED member (never seated; no lane churn).
        Returns the removed spec — its ``state`` is the member's resume
        point (skelly-serve snapshots it) — or None when the id is not in
        the queue."""
        for spec in self.queue:
            if spec.member_id == member_id:
                self.queue.remove(spec)
                return spec
        return None

    @property
    def live(self) -> int:
        """Occupied lane count."""
        return sum(1 for ln in self.lanes if ln is not None)

    # ------------------------------------------------------------ the drain

    def run(self) -> list:
        """Drain queue + lanes to completion; returns retired member ids in
        retirement order."""
        while any(ln is not None for ln in self.lanes):
            if self.max_rounds is not None and self.rounds >= self.max_rounds:
                break
            self.poll()
        return self.retired

    def poll(self) -> list:
        """ONE batched round over the current lanes: step, record outcomes,
        write crossed frames, retire + backfill. A no-op on an idle (all
        lanes empty) scheduler. Returns the member ids retired this round.

        `run` is poll() in a loop; a long-lived service interleaves poll()
        with `admit`/`evict` between rounds — one compiled program
        throughout."""
        if not any(ln is not None for ln in self.lanes):
            return []
        p = self.runner.system.params
        retired_before = len(self.retired)
        live = sum(1 for ln in self.lanes if ln is not None)
        with obs_tracer.span("ensemble_step", round=self.rounds,
                             live=live, lanes=self.batch):
            wall0 = _time.perf_counter()
            self.ens, info = self.step_fn(self.ens)
            # ONE device fetch for all [B] outcome vectors (it doubles
            # as the span's device-work barrier)
            fetched = {f: np.asarray(getattr(info, f))
                       for f in ("running", "accepted", "iters",
                                 "residual", "residual_true",
                                 "fiber_error", "refines",
                                 "loss_of_accuracy", "dt_underflow",
                                 "dt_used", "t", "dt_next", "cycles",
                                 "health", "failed", "guard_retries",
                                 "nucleations", "catastrophes",
                                 "active_fibers", "needs_growth")}
            hist = (np.asarray(info.history)
                    if info.history is not None else None)
            # skelly-flight: the per-member recorder rings ride the stacked
            # state ([B, K, 13] + [B] counts) — one fetch serves the step
            # records, the failure payloads, and the telemetry events
            fl = self.ens.states.flight
            flight_rows = np.asarray(fl.rows) if fl is not None else None
            flight_counts = np.asarray(fl.count) if fl is not None else None
            wall_s = _time.perf_counter() - wall0
        self.rounds += 1
        if self.runner.di_enabled:
            # keep each seated member's SimRNG current with its in-trace
            # stream carry: frames/snapshots written below then resume with
            # the exact counters the device draws left off at
            counters = np.asarray(self.ens.di_rng)
            for lane, ln in enumerate(self.lanes):
                if ln is not None and ln.spec.rng is not None:
                    ln.spec.rng.distributed.counter = int(counters[lane, 2])

        for lane, ln in enumerate(self.lanes):
            if ln is None:
                continue
            if not bool(fetched["running"][lane]):
                # occupied but inert: the member was seated already at or
                # past its t_final (e.g. a degenerate swept t_final, or a
                # resumed state beyond it). Without this retire the lane
                # would spin the drain loop forever.
                self._retire_member(lane)
                continue
            accepted = bool(fetched["accepted"][lane])
            underflow = bool(fetched["dt_underflow"][lane])
            failed = bool(fetched["failed"][lane])
            health = int(fetched["health"][lane])
            dt_used = float(fetched["dt_used"][lane])
            t_new = float(fetched["t"][lane])
            flight_row = (flight_mod.last_row(flight_rows[lane],
                                              flight_counts[lane])
                          if flight_rows is not None else None)
            if bool(fetched["needs_growth"][lane]):
                # the member's nucleation burst outgrew this capacity
                # bucket: the runner froze the lane un-advanced (state and
                # RNG counter exactly as before the round). Hand the member
                # back for a reseat onto the next capacity rung — its
                # current state rides on_retire like any retirement
                # (scenarios.sweep and skelly-serve re-admit it there).
                cap = _fiber_capacity(lane_state(self.ens.states, lane))
                obs_tracer.emit("lane", action="growth", lane=lane,
                                member=ln.spec.member_id, capacity=cap,
                                t=ln.t)
                if self.on_growth == "raise":
                    raise RuntimeError(
                        f"ensemble member {ln.spec.member_id}: nucleation "
                        f"outgrew its fiber capacity bucket ({cap} slots) "
                        "at t="
                        f"{ln.t:.6g}; drive the sweep through scenarios."
                        "ScenarioEnsemble (or serve) for automatic growth "
                        "reseats, or start at a larger capacity")
                self._retire_member(lane, reason="growth",
                                    extra={"capacity": cap})
                continue
            if failed:
                # terminal health verdict: the runner froze the lane
                # un-advanced (quarantine — siblings bitwise-unaffected);
                # retire it as "failed" with the decoded verdict, or
                # mirror the sequential loop's abort. The flight
                # recorder's last-window tail + anomaly provenance ride
                # the failure record and the fault event (obs.flight —
                # "who and where" next to "something died").
                verdict_s = _verdict.describe(health)
                payload = (flight_mod.failure_payload(
                    flight_rows[lane], flight_counts[lane])
                    if flight_rows is not None else None)
                prov = (payload or {}).get("provenance") or {}
                prov_fields = ({"prov_field": prov.get("field"),
                                "prov_fiber": prov.get("fiber"),
                                "prov_node": prov.get("node")}
                               if prov else {})
                obs_tracer.emit("fault", kind="lane_failed", lane=lane,
                                member=ln.spec.member_id, health=health,
                                verdict=verdict_s, t=ln.t, **prov_fields)
                if self.on_failure == "raise":
                    raise RuntimeError(
                        f"ensemble member {ln.spec.member_id}: terminal "
                        f"solver health verdict '{verdict_s}' "
                        f"(health={health:#x}) at t={ln.t:.6g}")
                self._retire_member(lane, reason="failed",
                                    extra={"health": health,
                                           "verdict": verdict_s,
                                           "flight": payload})
                continue
            if underflow:
                # the sequential loop raises before writing this trial's
                # metrics line — no step record here either
                if self.on_dt_underflow == "raise":
                    raise RuntimeError(
                        f"ensemble member {ln.spec.member_id}: timestep "
                        f"smaller than dt_min ({p.dt_min}) at t={ln.t:.6g}"
                    )
                obs_tracer.emit("fault", kind="dt_underflow", lane=lane,
                                member=ln.spec.member_id, health=health,
                                t=ln.t)
                self._retire_member(lane, reason="dt_underflow",
                                    extra={"health": health,
                                           "verdict":
                                               _verdict.describe(health),
                                           "flight": (
                                               flight_mod.failure_payload(
                                                   flight_rows[lane],
                                                   flight_counts[lane])
                                               if flight_rows is not None
                                               else None)})
                continue
            ln.steps += 1
            self._emit({
                "event": "step", "member": ln.spec.member_id,
                "lane": lane, "round": self.rounds - 1,
                "step": ln.steps - 1, "t": ln.t,
                "dt": dt_used, "iters": int(fetched["iters"][lane]),
                "gmres_cycles": int(fetched["cycles"][lane]),
                "residual": float(fetched["residual"][lane]),
                "residual_true": float(fetched["residual_true"][lane]),
                "fiber_error": float(fetched["fiber_error"][lane]),
                "accepted": accepted,
                "refines": int(fetched["refines"][lane]),
                "loss_of_accuracy": bool(
                    fetched["loss_of_accuracy"][lane]),
                "health": health,
                "guard_retries": int(fetched["guard_retries"][lane]),
                "nucleations": int(fetched["nucleations"][lane]),
                "catastrophes": int(fetched["catastrophes"][lane]),
                "active_fibers": int(fetched["active_fibers"][lane]),
                "wall_s": round(wall_s, 4),
                "wall_ms": round(wall_s * 1e3, 3),
                "gmres_history": history_rows(
                    hist[lane] if hist is not None else None,
                    fetched["cycles"][lane]),
                "flight": flight_row})
            if flight_row is not None:
                # telemetry twin of the metrics column: `obs timeline`
                # renders these as per-member counter tracks
                obs_tracer.emit("flight", member=ln.spec.member_id,
                                lane=lane, **flight_row)
            ln.t = t_new
            ln.dt = float(fetched["dt_next"][lane])
            if (accepted and self.writer is not None
                    and crossed_write_boundary(t_new, dt_used,
                                               p.dt_write)):
                self.writer(ln.spec.member_id,
                            lane_state(self.ens.states, lane),
                            rng_state=self._rng_state(ln.spec))
                ln.frames += 1
            if t_new >= ln.spec.t_final:
                self._retire_member(lane)
        return self.retired[retired_before:]


def run_ensemble(system, members, batch: int = 8, *, batch_impl: str = "vmap",
                 writer=None, metrics=None, write_initial_frames: bool = False,
                 on_dt_underflow: str = "raise", on_failure: str = "raise",
                 max_rounds=None) -> list:
    """One-call convenience: build an `EnsembleRunner` over ``system`` and
    drain ``members`` (a MemberSpec iterable) through ``batch`` lanes."""
    runner = EnsembleRunner(system, batch_impl=batch_impl)
    return EnsembleScheduler(
        runner, members, batch, writer=writer, metrics=metrics,
        write_initial_frames=write_initial_frames,
        on_dt_underflow=on_dt_underflow, on_failure=on_failure,
        max_rounds=max_rounds).run()
