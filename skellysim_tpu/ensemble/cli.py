"""Ensemble driver: sweep-spec TOML -> per-member trajectories + metrics.

Usage: python -m skellysim_tpu.ensemble --sweep-file=ensemble.toml
           [--output-dir=DIR] [--batch=B] [--overwrite] [--metrics-file=F]

The sweep spec (`config.sweep`, docs/ensemble.md) names a base run config
and the member expansion (replicas x sweep axes). Every member is built
through the same `builder.build_simulation` path as a single run, validated
to share the base's compiled program (identical runtime Params up to
seed/t_final, identical state structure), and streamed through the
continuous-batching scheduler. Outputs land in the output directory:
`<member_id>.out` reference-format trajectories plus one aggregated
`ensemble_metrics.jsonl`.
"""

from __future__ import annotations

import argparse
import os
import sys


def _members_from_sweep(sweep_file: str):
    """(system, [MemberSpec], spec) — build every member simulation and
    validate the one-compiled-program contract."""
    from ..builder import build_simulation
    from ..config import schema
    from ..config.sweep import apply_overrides, load_members
    from ..utils.rng import SimRNG
    from .scheduler import MemberSpec

    spec, base_path, base, plans = load_members(sweep_file)
    if ((spec.replicas > 1 or any(ax.key == "params.seed"
                                  for ax in spec.sweep))
            and base.params.dynamic_instability.n_nodes == 0):
        # without dynamic instability nothing in the batched runner
        # consumes the member RNG, so replica members differ ONLY in their
        # serialized RNG streams and write identical physics; never let
        # that burn a sweep silently. (DI sweeps are the stochastic case
        # replicas exist for — they route through scenarios.ScenarioEnsemble
        # below, where each member's stream drives its own
        # nucleation/catastrophe draws.)
        import logging

        logging.getLogger("skellysim_tpu").warning(
            "replicas/seed sweep without dynamic instability: members of "
            "one sweep point run identical deterministic physics (they "
            "differ only in their recorded RNG streams); use replicas=1, "
            "or enable [dynamic_instability] for stochastic members")
    config_dir = os.path.dirname(os.path.abspath(base_path)) or "."
    if not plans:
        sys.exit(f"sweep spec '{sweep_file}' expands to zero members")

    # members may differ only in the knobs handled outside the trace — the
    # one-compiled-program contract shared with skelly-serve admission
    norm = schema.normalized_member_params

    # skelly-bucket: every member quantizes onto the base config's bucket
    # policy BEFORE stacking, so heterogeneous members (different fiber
    # counts / live resolutions under a configured ladder) ride one
    # bucket's compiled program instead of failing the leaf-shape check
    from ..system import buckets as bucket_mod

    policy = bucket_mod.BucketPolicy.from_runtime(
        schema.load_runtime_config(base_path))

    system = None
    members = []
    base_key = None
    for plan in plans:
        cfg = apply_overrides(base, plan.overrides)
        sys_i, state_i, _ = build_simulation(cfg, config_dir=config_dir)
        # spectral grid rungs are plan data, not state shapes — they ride
        # the System (cli.py does the same for single runs)
        sys_i.grid_ladder = policy.grid_ladder
        state_i, key_i = bucket_mod.bucketize(
            state_i, policy, pair_evaluator=sys_i.params.pair_evaluator)
        if system is None:
            system = sys_i
            base_norm = norm(cfg.params)
            base_key = key_i
        elif norm(cfg.params) != base_norm:
            sys.exit(f"member {plan.member_id}: overrides changed runtime "
                     "params; ensemble members must share one compiled "
                     "program (sweep state values, not params)")
        elif key_i != base_key:
            sys.exit(f"member {plan.member_id}: lands in bucket "
                     f"{key_i.describe()} but member 0's program is "
                     f"{base_key.describe()}; widen the [runtime] "
                     "bucket_ladder/node_ladder so all members share one "
                     "bucket")
        members.append(MemberSpec(
            member_id=plan.member_id, state=state_i, t_final=plan.t_final,
            rng=SimRNG(plan.seed).member(plan.index)))
    return system, members, spec, policy


def run(sweep_file: str, output_dir: str | None = None,
        batch: int | None = None, batch_impl: str | None = None,
        overwrite: bool = False, metrics_path: str | None = None,
        trace_path: str | None = None,
        profile_dir: str | None = None) -> list:
    """Expand + drain a sweep; returns retired member ids.

    ``profile_dir`` wraps the drain in the device profiler
    (`obs.profile.profile_session`) and, after it closes, folds the dump
    back into the telemetry stream as ``device_phase`` events — so `obs
    summarize` on the ``--trace-file`` shows device time by phase next to
    the lane occupancy (docs/observability.md)."""
    import contextlib

    from ..io.ensemble_io import EnsembleMetricsWriter, MemberTrajectoryWriters
    from ..obs import tracer as obs_tracer
    from .scheduler import EnsembleScheduler
    from .runner import EnsembleRunner

    out_dir = output_dir or (os.path.dirname(os.path.abspath(sweep_file))
                             or ".")
    system, members, spec, policy = _members_from_sweep(sweep_file)
    metrics_path = metrics_path or os.path.join(out_dir,
                                                "ensemble_metrics.jsonl")
    writers = MemberTrajectoryWriters(out_dir, overwrite=overwrite)
    # fail on existing trajectories BEFORE any compute, like the single-run
    # CLI's up-front clobber guard
    if not overwrite:
        clobbered = [m.member_id for m in members
                     if os.path.exists(writers.path(m.member_id))]
        if clobbered:
            sys.exit(f"member trajectories already exist ({clobbered[0]}.out"
                     f" + {len(clobbered) - 1} more); pass --overwrite")
    runner = EnsembleRunner(system, batch_impl=batch_impl or spec.batch_impl)
    # skelly-scope stream for the drain: lane admit/backfill/retire events,
    # per-round batched-step spans (lane occupancy), compile events
    tracer = obs_tracer.Tracer(trace_path) if trace_path else None
    scope = (obs_tracer.use(tracer) if tracer is not None
             else contextlib.nullcontext())
    if profile_dir is not None:
        from ..obs.profile import profile_session

        prof = profile_session(profile_dir)
    else:
        prof = contextlib.nullcontext()
    try:
        with writers, EnsembleMetricsWriter(metrics_path) as metrics, \
                scope, prof:
            if runner.di_enabled:
                # dynamic-instability sweeps: the scenario front-end runs
                # the in-trace DI update on the ensemble lanes and handles
                # capacity-growth reseats across rungs (docs/scenarios.md)
                from ..scenarios import ScenarioEnsemble

                # the base config's [runtime] policy rides along: growth
                # reseats must land on the SAME ladder rungs admission
                # bucketized onto, or --resume re-bucketizes onto a rung
                # the live run never occupied
                sched = ScenarioEnsemble(
                    system, members, batch or spec.batch, runner=runner,
                    policy=policy, writer=writers, metrics=metrics,
                    write_initial_frames=True,
                    on_dt_underflow="retire", on_failure="retire")
                retired = sched.run()
            else:
                sched = EnsembleScheduler(
                    runner, members, batch or spec.batch, writer=writers,
                    metrics=metrics, write_initial_frames=True,
                    on_dt_underflow="retire",
                    # quarantine, not abort: one poisoned member must not
                    # take down a 10k-member sweep (docs/robustness.md) —
                    # its "failed" record + verdict land in the metrics
                    # JSONL
                    on_failure="retire")
                retired = sched.run()
        if profile_dir is not None:
            # the dump is written at prof's exit above — fold it into the
            # active telemetry stream (the CLI's --trace-file, or an
            # externally installed tracer) as device_phase events
            from ..obs.profile import emit_device_phases

            emit_device_phases(profile_dir, tracer)
    finally:
        # close even when the drain raises (System.run's tracer lifecycle)
        if tracer is not None:
            tracer.close()
    print(f"ensemble finished: {len(retired)}/{len(members)} members "
          f"retired over {sched.rounds} batched steps")
    return retired


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="skellysim-tpu-ensemble",
        description="batched ensemble sweeps with a continuous-batching "
                    "scheduler (docs/ensemble.md)")
    ap.add_argument("--sweep-file", default="ensemble.toml",
                    help="sweep-spec TOML ([ensemble] table)")
    ap.add_argument("--output-dir", default=None,
                    help="member trajectories + metrics land here "
                         "(default: the sweep file's directory)")
    ap.add_argument("--batch", type=int, default=None,
                    help="override the spec's compiled lane count B")
    ap.add_argument("--batch-impl", default=None,
                    choices=("vmap", "unroll"),
                    help="override the spec's execution plan")
    ap.add_argument("--overwrite", action="store_true",
                    help="overwrite existing member trajectories")
    ap.add_argument("--metrics-file", default=None,
                    help="aggregated ensemble metrics JSONL "
                         "(default: <output-dir>/ensemble_metrics.jsonl)")
    ap.add_argument("--trace-file", default=None,
                    help="skelly-scope telemetry JSONL (lane events + "
                         "batched-step spans; `python -m skellysim_tpu.obs "
                         "summarize` reports lane occupancy from it)")
    ap.add_argument("--profile", default=None, metavar="DIR",
                    help="device profiler capture of the drain "
                         "(obs.profile.profile_session); the dump is "
                         "parsed afterwards and device_phase events are "
                         "appended to --trace-file — render with `obs "
                         "profile DIR` / `obs timeline` "
                         "(docs/observability.md)")
    ap.add_argument("--jax-cache", default=None, metavar="DIR",
                    help="persistent XLA compilation cache directory shared "
                         "across runs/CLIs (default-on: [runtime] jax_cache "
                         "of the BASE config, else the package .jax_cache)")
    ap.add_argument("--no-jax-cache", action="store_true",
                    help="disable the persistent compilation cache")
    ap.add_argument("--log-level",
                    default=os.environ.get("SKELLYSIM_LOG", "INFO"))
    args = ap.parse_args(argv)

    import logging

    logging.basicConfig(level=args.log_level.upper(),
                        format="[%(asctime)s] [%(levelname)s] %(message)s",
                        stream=sys.stderr)

    # x64 for the same reason as the single-run CLI (cli.py): without it the
    # builder's "f64" members silently canonicalize to f32 and tight
    # tolerances floor at f32 noise while steps are still accepted
    import jax

    jax.config.update("jax_enable_x64", True)

    from ..cli import resolve_cache_dir
    from ..utils.bootstrap import enable_compilation_cache

    # the [runtime] jax_cache key lives in the sweep's BASE config: resolve
    # the base path through the sweep spec, then apply the ONE shared
    # precedence chain (cli.resolve_cache_dir — --no-jax-cache > --jax-cache
    # > [runtime] jax_cache > auto; unreadable specs fall back to "auto")
    try:
        from ..config.sweep import load_sweep, resolve_base_config

        base_path = resolve_base_config(load_sweep(args.sweep_file),
                                        args.sweep_file)
    except Exception:
        base_path = ""
    enable_compilation_cache(resolve_cache_dir(
        base_path, flag=args.jax_cache, off=args.no_jax_cache))

    run(args.sweep_file, output_dir=args.output_dir, batch=args.batch,
        batch_impl=args.batch_impl, overwrite=args.overwrite,
        metrics_path=args.metrics_file, trace_path=args.trace_file,
        profile_dir=args.profile)
