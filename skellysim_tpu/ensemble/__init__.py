"""skelly-ensemble: batched execution of independent simulations.

SkellySim's real scientific workload is not one simulation but thousands:
stochastic replicas and parameter sweeps, each a small-N Stokes solve that
leaves an accelerator chip mostly idle (docs/performance.md: the hot kernels
only saturate at ~65k+ nodes). This subsystem inverts the
one-simulation-per-process architecture: B independent members run as one
compiled program by batching the existing jit'd trial step
(`System.trial_step`) over a stacked `SimState` member axis, with per-member
adaptive timestepping done as device-side masked accept/reject and a
host-side continuous-batching scheduler that keeps the B lanes full from a
work queue — the direct analogue of an inference server's batch scheduler
(ROADMAP north star: "batching, async, caching").

Layers (see docs/ensemble.md):

* `runner`    — `EnsembleState` (stacked member pytree) + `EnsembleRunner`
                (the jit'd masked batch step; `vmap` and bit-reproducible
                `unroll` execution plans);
* `scheduler` — work queue, lane retirement at `t_final`, backfill without
                retracing (same static shapes, new leaves);
* `cli`       — `python -m skellysim_tpu.ensemble`: TOML sweep spec
                (`config.sweep`) -> per-member trajectories + one aggregated
                metrics JSONL (`io.ensemble_io`).
"""

from .runner import (EnsembleRunner, EnsembleState,  # noqa: F401
                     EnsembleStepInfo, lane_state, set_lane, stack_states)
from .scheduler import (EnsembleScheduler, MemberSpec,  # noqa: F401
                        run_ensemble)
