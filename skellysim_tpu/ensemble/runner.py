"""Batched member stepping: stacked states, masked adaptive accept/reject.

The member axis is an ordinary leading batch axis over every `SimState` leaf
(per-member ``time``/``dt`` ride along as [B] leaves), so the existing pure
trial step (`System.trial_step` -> prep / GMRES / component advance) batches
with `jax.vmap` unchanged — the JAX Fast Stokesian Dynamics recipe
(PAPERS.md: arxiv 2503.07847) applied to the coupled SkellySim step. The
host adaptive loop of `System._run_loop` becomes device-side masked
selection: each member carries its own clock, rejected members roll back via
`jnp.where` against the backup pytree (the step's input — backup/restore is
free on immutable pytrees), and members past their ``t_final`` are inert
masked lanes whose leaves pass through unchanged (lane neutralization
follows docs/audit.md "Masking discipline"; the `mask` audit check proves
it on the lowered `ensemble_step` program).

Two execution plans for the same batched program (`EnsembleRunner(...,
batch_impl=...)`):

* ``"vmap"`` (default) — one fused program over the member axis; the
  throughput mode, and the only mode whose member axis can be sharded
  across a device mesh (`parallel.shard_ensemble`). Batched GEMM
  accumulation orders differ from the unbatched step at ~1 ulp, so members
  match sequential runs to roundoff, not bitwise.
* ``"unroll"`` — the per-member step inlined once per lane inside the SAME
  jit program. Each lane compiles to the exact unbatched computation (XLA
  re-associates nothing across independent inlined subgraphs — measured;
  `lax.map` does NOT have this property, its scan-body codegen differs
  from the standalone program at ~1 ulp), so member trajectories are
  BITWISE identical to sequential `System.run` executions — the
  reproducibility mode, pinned by `tests/test_ensemble.py`. Trace/compile
  time scales with B; the masked stepping, scheduler, and
  backfill-without-retrace behave identically to vmap.

The accept/reject/dt arithmetic reproduces `System._run_loop` exactly: it
runs in float64 (the host loop computes it in Python floats) and casts back
to the state dtype, so the per-member dt sequences are bit-identical to the
sequential loop's for any state dtype.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..guard import verdict as _verdict
from ..system.system import SimState, System


class EnsembleState(NamedTuple):
    """B members as one pytree (leaves of `states` carry a leading [B])."""

    states: SimState
    #: [B] float64 per-member end time; a lane whose ``time >= t_final`` is
    #: inert (finished or idle — the scheduler parks empty lanes at -inf)
    t_final: jnp.ndarray
    #: [B, 3] int32 per-member RNG stream carry (seed, stream_id, counter)
    #: for device-side dynamic instability (`scenarios.di_device`): each
    #: member's `SimRNG.member(i)` ``distributed`` stream as trace DATA,
    #: advanced by `di_device.DRAWS_PER_STEP` per applied update. None when
    #: the system has no dynamic instability (bit-identical pre-scenario
    #: pytree).
    di_rng: jnp.ndarray | None = None


class EnsembleStepInfo(NamedTuple):
    """Per-member outcome of one batched trial step (all leaves [B])."""

    running: jnp.ndarray          # lane was live at step entry
    accepted: jnp.ndarray         # trial accepted and state advanced
    converged: jnp.ndarray
    iters: jnp.ndarray
    residual: jnp.ndarray
    residual_true: jnp.ndarray
    fiber_error: jnp.ndarray
    refines: jnp.ndarray
    loss_of_accuracy: jnp.ndarray
    collided: jnp.ndarray
    #: adaptive dt fell below dt_min: the lane is frozen un-advanced (the
    #: sequential loop raises RuntimeError here; the scheduler decides)
    dt_underflow: jnp.ndarray
    dt_used: jnp.ndarray          # the dt this trial stepped with
    t: jnp.ndarray                # per-member time AFTER the step
    dt_next: jnp.ndarray          # per-member dt AFTER the step
    solutions: jnp.ndarray        # [B, n_solution]
    #: [B] GMRES restart cycles (skelly-scope `gmres_cycles`; always the
    #: per-member row count of ``history``)
    cycles: jnp.ndarray = 0
    #: [B, gmres_history, 3] per-member convergence ring buffers
    #: (`solver.gmres` docstring), or None when Params.gmres_history == 0
    history: jnp.ndarray | None = None
    #: [B] int32 packed health words (`guard.verdict` bit layout: the
    #: solver's nonfinite/stagnation/breakdown bits plus the dt_underflow
    #: bit stamped here) — 0 = healthy lane
    health: jnp.ndarray = 0
    #: [B] terminal-verdict quarantine mask: the lane carries a verdict no
    #: retry can repair (`verdict.is_terminal`) and was frozen un-advanced
    #: this round — the scheduler retires it as ``failed`` (siblings'
    #: leaves are bitwise-unaffected: frozen lanes are masked selects,
    #: exactly like rejected and finished lanes)
    failed: jnp.ndarray = False
    #: [B] guard-ladder retries this round (`StepInfo.guard_retries`)
    guard_retries: jnp.ndarray = 0
    #: [B] int32 dynamic-instability events APPLIED this round (rejected /
    #: frozen lanes report 0 — like the host loop, a rejected trial
    #: discards its nucleations/catastrophes); all-zero without DI
    nucleations: jnp.ndarray = 0
    catastrophes: jnp.ndarray = 0
    #: [B] int32 live fiber count after the round's merge (0 without DI)
    active_fibers: jnp.ndarray = 0
    #: [B] a nucleation burst outgrew the lane's capacity bucket: the lane
    #: froze un-advanced (RNG counter untouched) — the scheduler reseats it
    #: onto the next `buckets.next_fiber_capacity` rung (scenarios.sweep)
    needs_growth: jnp.ndarray = False


def _check_member(i, template_leaves, state):
    leaves = jax.tree_util.tree_leaves(state)
    if len(leaves) != len(template_leaves):
        raise ValueError(
            f"member {i}: pytree structure differs from member 0 "
            "(ensemble members must share one compiled program)")
    for j, (a, b) in enumerate(zip(template_leaves, leaves)):
        a, b = jnp.asarray(a), jnp.asarray(b)
        if a.shape != b.shape or a.dtype != b.dtype:
            raise ValueError(
                f"member {i}: leaf {j} is {b.shape}/{b.dtype} vs member 0's "
                f"{a.shape}/{a.dtype}; ensemble members must share static "
                "shapes and dtypes (pad fiber capacity to a common size)")


def stack_states(states) -> SimState:
    """[SimState, ...] -> one SimState whose leaves carry a leading member
    axis. Every member must share the pytree structure, leaf shapes, and
    dtypes — the ensemble's one-compiled-program contract."""
    states = list(states)
    if not states:
        raise ValueError("stack_states needs at least one member state")
    treedef = jax.tree_util.tree_structure(states[0])
    template_leaves = jax.tree_util.tree_leaves(states[0])
    for i, s in enumerate(states[1:], start=1):
        if jax.tree_util.tree_structure(s) != treedef:
            raise ValueError(
                f"member {i}: pytree structure differs from member 0 "
                "(ensemble members must share one compiled program)")
        _check_member(i, template_leaves, s)
    return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *states)


def lane_state(bstates: SimState, lane: int) -> SimState:
    """Member ``lane``'s SimState view of a stacked batch."""
    return jax.tree_util.tree_map(lambda leaf: leaf[lane], bstates)


def set_lane(bstates: SimState, lane: int, state: SimState) -> SimState:
    """Replace lane ``lane``'s leaves — the scheduler's backfill primitive.

    Pure leaf substitution at fixed shapes/dtypes, so a jit'd step over the
    result reuses its compiled program (no retrace); shape/dtype mismatches
    raise instead of silently retracing."""
    _check_member(lane, jax.tree_util.tree_leaves(lane_state(bstates, 0)),
                  state)
    return jax.tree_util.tree_map(
        lambda leaf, s: leaf.at[lane].set(jnp.asarray(s, dtype=leaf.dtype)),
        bstates, state)


def rng_carry(rng) -> jnp.ndarray:
    """A member `SimRNG` -> its [3] int32 ``distributed``-stream carry
    (seed, stream_id, counter) — the device DI draw state
    (`scenarios.di_device`). None -> an inert zero stream (idle lanes)."""
    if rng is None:
        return jnp.zeros(3, dtype=jnp.int32)
    s = rng.distributed
    return jnp.asarray([s.seed, s.stream_id, s.counter], dtype=jnp.int32)


def _where_lanes(mask, new_tree, old_tree):
    """Per-lane select over every leaf (mask [B] broadcast to leaf rank)."""
    def sel(n, o):
        m = mask.reshape(mask.shape + (1,) * (n.ndim - 1))
        return jnp.where(m, n, o)

    return jax.tree_util.tree_map(sel, new_tree, old_tree)


class EnsembleRunner:
    """The jit'd batched trial step with masked per-member adaptive dt.

    One compiled program for a fixed lane count B: the scheduler swaps
    member leaves in and out of lanes without retracing. The host-REBUILT
    fast evaluators (ewald/tree re-plan per step) are incompatible with a
    closed batched trace, so they are rejected at construction rather than
    silently degraded. The spectral evaluator is the exception: its plan
    is bucket-quantized data that never rebuilds under drift, so
    `make_ensemble` builds the pair spec ONCE from the template member and
    `step` threads it (static) plus its anchors (traced operand — NOT a
    closure constant, which would go stale on a rung hop) through every
    batched call.

    Dynamic instability runs IN-TRACE when the params enable it
    (`scenarios.di_device`, docs/scenarios.md): nucleation/catastrophe are
    masked flips over each member's fixed-capacity fiber batch, drawn from
    per-member RNG stream carries riding `EnsembleState.di_rng`, applied
    at the top of every member trial exactly where the sequential loop
    applies the host update. A member whose capacity bucket fills reports
    ``needs_growth`` and freezes; the scheduler reseats it host-side.
    ``di_sample_fn`` overrides the natural draws (`di_device.sample_draws`)
    — the deterministic-injection seam the host/device parity tests use.
    """

    def __init__(self, system: System, batch_impl: str = "vmap",
                 di_sample_fn=None):
        if batch_impl not in ("vmap", "unroll"):
            raise ValueError(
                f"unknown batch_impl {batch_impl!r}; use 'vmap' (throughput; "
                "shardable member axis) or 'unroll' (bit-reproducible lanes)")
        p = system.params
        if p.pair_evaluator in ("ewald", "tree"):
            raise ValueError(
                "ensemble batching does not support pair_evaluator="
                f"{p.pair_evaluator!r}: the fast-summation plan is rebuilt "
                "host-side per step and cannot live inside the closed "
                "batched trace; use 'direct' (small-N members are below the "
                "fast-evaluator crossover anyway) or 'spectral' for "
                "periodic scenes (its bucket-quantized plan is static data)")
        if p.pair_evaluator == "ring" and system.mesh is not None:
            raise ValueError(
                "ensemble batching does not support the ring pair evaluator "
                "(shard_map inside the member batch axis); shard the MEMBER "
                "axis instead (parallel.shard_ensemble) — batch parallelism "
                "is the outer axis for small-N members")
        self.system = system
        self.batch_impl = batch_impl
        self.di_enabled = p.dynamic_instability.n_nodes > 0
        self._di_sample_fn = di_sample_fn
        # spectral pair spec + anchors, filled by make_ensemble; the pair
        # is a static jit argument, so a plan-rung hop (new stripped plan)
        # retraces instead of silently reusing the stale program
        self._pair = None
        self._pair_anchors = None
        # through the compile observer (obs.compile_log): with a tracer
        # active, the scheduler's timeline shows exactly when (and with
        # what member signature) the batched step compiled — the runtime
        # twin of the backfill-never-retraces test pin
        from ..obs.compile_log import observed_jit

        self._step_jit = observed_jit(self.step_impl, name="ensemble_step",
                                      static_argnames=("pair",))

    # ------------------------------------------------------------- assembly

    def make_ensemble(self, states, t_finals, rngs=None) -> EnsembleState:
        """Stack member states + per-member end times into an EnsembleState.

        With dynamic instability enabled, ``rngs`` (one `SimRNG` or None
        per member) seeds the [B, 3] ``di_rng`` stream carry — rng-less
        lanes (idle templates) carry a zero stream that never advances
        (frozen/idle lanes do not draw)."""
        # normalize the flight-recorder ring (skelly-flight) so every
        # member shares the template's pytree structure — snapshot-decoded
        # states carry no ring (the wire never does)
        states = [self.system.ensure_flight(s) for s in states]
        if self.system.params.pair_evaluator == "spectral":
            # ONE plan for the whole ensemble, built from the template
            # member: the stripped pair spec is rung-quantized static data
            # and the anchors (box_lo/cell_lo) are fixed by the periodic
            # box the members share, so they hold for every lane
            self._pair, self._pair_anchors = self.system._pair_args(
                states[0])
        stacked = stack_states(states)
        t_final = jnp.asarray(list(t_finals), dtype=jnp.float64)
        if t_final.shape != (stacked.time.shape[0],):
            raise ValueError(
                f"t_finals has shape {t_final.shape}, expected "
                f"({stacked.time.shape[0]},)")
        di_rng = None
        if self.di_enabled:
            from ..scenarios.di_device import check_di_state

            check_di_state(states[0], self.system.params)
            rngs = list(rngs) if rngs is not None else [None] * len(states)
            if len(rngs) != len(states):
                raise ValueError(
                    f"rngs has {len(rngs)} entries for {len(states)} members")
            t_np = np.asarray(t_final)
            missing = [i for i, r in enumerate(rngs)
                       if r is None and t_np[i] > float("-inf")]
            if missing:
                # only IDLE (t_final = -inf) template lanes may go rng-less:
                # a RUNNING zero-stream lane would draw the same seed-0
                # stream as every other rng-less lane — silently correlated
                # "stochastic" members
                raise ValueError(
                    f"members {missing}: dynamic-instability members need a "
                    "per-member SimRNG (SimRNG(seed).member(i)) — rng-less "
                    "lanes are only legal as idle templates")
            di_rng = jnp.stack([rng_carry(r) for r in rngs])
        return EnsembleState(states=stacked, t_final=t_final, di_rng=di_rng)

    # ------------------------------------------------------------- the step

    def _member_body(self, state: SimState, di_rng=None, *, pair=None,
                     pair_anchors=None):
        """One member's trial: DI update (when enabled) + solve + (under the
        adaptive gate) collision. The DI flips ride ``new_state`` only — a
        rejected trial rolls back to the pre-DI state, exactly like the
        sequential loop's backup/restore (which also discards the host DI
        update on reject without rewinding the RNG)."""
        if self.di_enabled:
            from ..scenarios.di_device import di_update

            state, di_info = di_update(state, self.system.params, di_rng,
                                       sample_fn=self._di_sample_fn)
        else:
            di_info = None
        new_state, solution, info = self.system.trial_step(
            state, pair=pair, pair_anchors=pair_anchors)
        if self.system.params.adaptive_timestep_flag:
            collided = self.system.collision(new_state)
        else:
            collided = jnp.asarray(False)
        return new_state, solution, info, collided, di_info

    def step_impl(self, ens: EnsembleState, pair=None, pair_anchors=None):
        """(EnsembleState, EnsembleStepInfo) after one masked batched trial.

        Pure and jit-compiled once per (B, member structure, pair spec);
        the scheduler drives it. The accept/reject ladder mirrors
        `System._run_loop` line for line, vectorized over members in
        float64. ``pair``/``pair_anchors`` (spectral only) are shared by
        all lanes: the anchors enter as a traced operand and broadcast
        over the member axis.
        """
        p = self.system.params
        states = ens.states
        running = states.time.astype(jnp.float64) < ens.t_final

        if self.batch_impl == "vmap":
            args = (states, ens.di_rng) if self.di_enabled else (states,)
            # closing over pair_anchors here is safe: inside this trace it
            # is a TRACER (an operand of step_impl), broadcast by vmap —
            # not a baked-in host constant
            body = (lambda *a: self._member_body(
                *a, pair=pair, pair_anchors=pair_anchors))
            new_states, solutions, infos, collided, di_infos = jax.vmap(
                body)(*args)
        else:
            # one inlined copy of the member step per lane: bit-identical to
            # the unbatched program (see the module docstring)
            outs = [self._member_body(
                lane_state(states, i),
                ens.di_rng[i] if self.di_enabled else None,
                pair=pair, pair_anchors=pair_anchors)
                for i in range(states.time.shape[0])]
            (new_states, solutions, infos, collided,
             di_infos) = jax.tree_util.tree_map(
                lambda *ls: jnp.stack(ls), *outs)

        conv = infos.converged
        # a needs_growth lane is frozen WHOLESALE: no advance/reject, dt
        # kept, RNG counter kept — its round re-runs after the host-side
        # capacity reseat (scenarios.sweep)
        growth = (running & di_infos.needs_growth if self.di_enabled
                  else jnp.zeros_like(conv))
        # the host loop's ladder runs in Python floats (f64); matching it
        # bitwise for any state dtype means doing the dt/t arithmetic in f64
        # and casting back only at the state boundary. The dt that actually
        # advanced is infos.dt_used — identical to states.dt unless the
        # guard escalation ladder retried at a halved dt (guard.escalate)
        dt_used = jnp.asarray(infos.dt_used, dtype=states.dt.dtype)
        dt64 = dt_used.astype(jnp.float64)
        ferr64 = infos.fiber_error.astype(jnp.float64)
        false_lanes = jnp.zeros_like(conv)
        if p.adaptive_timestep_flag:
            good = conv & (ferr64 <= p.fiber_error_tol)
            grow = ferr64 <= 0.9 * p.fiber_error_tol
            dt_new64 = jnp.where(
                good,
                jnp.where(grow, jnp.minimum(p.dt_max, dt64 * p.beta_up), dt64),
                dt64 * p.beta_down)
            coll = conv & collided
            dt_new64 = jnp.where(coll, dt64 * 0.5, dt_new64)
            accept = good & ~coll
            dt_underflow = running & (dt_new64 < p.dt_min) & ~growth
        else:
            accept = jnp.ones_like(conv)
            dt_new64 = dt64
            coll = false_lanes
            dt_underflow = false_lanes

        # the packed per-lane health word: the solver/step verdicts plus
        # the dt_underflow bit stamped here (guard.verdict layout). A lane
        # whose verdict is TERMINAL (nonfinite — no dt can repair a
        # poisoned state) is quarantined: frozen un-advanced this round
        # and flagged `failed` for the scheduler to retire. dt_underflow
        # keeps its dedicated path (on_dt_underflow policy), bit included.
        health = (jnp.asarray(infos.health, dtype=jnp.int32)
                  | jnp.where(dt_underflow,
                              jnp.int32(_verdict.DT_UNDERFLOW),
                              jnp.int32(0)))
        failed = running & _verdict.is_terminal(health) & ~dt_underflow \
            & ~growth

        # the sequential loop raises BEFORE applying an underflowed update,
        # leaving the state untouched: frozen (underflowed, quarantined, or
        # growth-pending) lanes here do the same — masked selects, so
        # sibling lanes' leaves are bitwise-unaffected (pinned by
        # tests/test_ensemble.py)
        advance = running & accept & ~dt_underflow & ~failed & ~growth
        reject = running & ~accept & ~dt_underflow & ~failed & ~growth

        merged = _where_lanes(advance, new_states, states)
        if states.flight is not None:
            # the flight ring advances for every lane that RAN a trial —
            # including rejected, underflowed, and quarantined ones: the
            # fatal row is the recorder's whole point, and freezing it
            # with the physics rollback would discard exactly the evidence
            # the provenance report needs. Growth-frozen lanes never ran
            # (their round re-runs at the next rung), so they keep their
            # ring untouched, like their RNG counter.
            merged = merged._replace(flight=_where_lanes(
                running & ~growth, new_states.flight, states.flight))
        t_new64 = states.time.astype(jnp.float64) + dt64
        time_out = jnp.where(advance, t_new64.astype(states.time.dtype),
                             states.time)
        dt_out = jnp.where(advance | reject,
                           dt_new64.astype(states.dt.dtype), states.dt)
        merged = merged._replace(time=time_out, dt=dt_out)

        zeros_i = jnp.zeros(conv.shape, dtype=jnp.int32)
        di_rng_out = ens.di_rng
        if self.di_enabled:
            # the stream counter advances for every lane that actually drew
            # this round — including rejected/failed ones (the sequential
            # loop does not rewind the RNG on reject either); growth-frozen
            # lanes never drew: their round re-runs at the next rung
            from ..scenarios.di_device import DRAWS_PER_STEP

            adv = jnp.where(running & ~growth,
                            jnp.int32(DRAWS_PER_STEP), jnp.int32(0))
            di_rng_out = ens.di_rng.at[:, 2].add(adv)
            nucleations = jnp.where(advance, di_infos.nucleations, 0)
            catastrophes = jnp.where(advance, di_infos.catastrophes, 0)
            active_fibers = jnp.sum(merged.fibers.active,
                                    axis=1).astype(jnp.int32)
        else:
            nucleations = catastrophes = active_fibers = zeros_i

        info = EnsembleStepInfo(
            running=running, accepted=advance, converged=conv,
            iters=infos.iters, residual=infos.residual,
            residual_true=infos.residual_true, fiber_error=infos.fiber_error,
            refines=jnp.broadcast_to(
                jnp.asarray(infos.refines, dtype=jnp.int32), conv.shape),
            loss_of_accuracy=jnp.broadcast_to(
                jnp.asarray(infos.loss_of_accuracy), conv.shape),
            collided=coll, dt_underflow=dt_underflow, dt_used=dt_used,
            t=merged.time, dt_next=merged.dt, solutions=solutions,
            cycles=jnp.broadcast_to(
                jnp.asarray(infos.cycles, dtype=jnp.int32), conv.shape),
            history=infos.history,
            health=jnp.broadcast_to(health, conv.shape),
            failed=jnp.broadcast_to(failed, conv.shape),
            guard_retries=jnp.broadcast_to(
                jnp.asarray(infos.guard_retries, dtype=jnp.int32),
                conv.shape),
            nucleations=nucleations, catastrophes=catastrophes,
            active_fibers=active_fibers, needs_growth=growth)
        return EnsembleState(states=merged, t_final=ens.t_final,
                             di_rng=di_rng_out), info

    def step(self, ens: EnsembleState):
        """One compiled batched trial step (same signature as `step_impl`)."""
        if self._pair is not None:
            return self._step_jit(ens, pair=self._pair,
                                  pair_anchors=self._pair_anchors)
        return self._step_jit(ens)


# ---------------------------------------------------------------- skelly-audit

def auditable_programs():
    """The ensemble layer's audit entry: the vmapped batched trial step
    over B=4 free-fiber members. Pins that batching stays collective-free
    and callback-free (members are independent rows) and that the scheduler
    can swap member leaves without retracing (the continuous-batching
    invariant `tests/test_ensemble.py` relies on)."""
    from ..audit import fixtures
    from ..audit.registry import AuditProgram, built_from

    def make_runner_and_ensemble(n_fibers=4, n_nodes=8):
        system = fixtures.make_system()
        runner = EnsembleRunner(system)
        import jax.numpy as jnp

        from ..system import BackgroundFlow

        states = [system.make_state(
            fibers=fixtures.make_fibers(n_fibers=n_fibers, n_nodes=n_nodes,
                                        seed=i),
            background=BackgroundFlow.make(uniform=(1.0, 0.0, 0.0),
                                           dtype=jnp.float64))
            for i in range(4)]
        return runner, runner.make_ensemble(states, [1e-2] * 4)

    def build():
        runner, ens = make_runner_and_ensemble()
        return built_from(runner._step_jit, ens)

    def retrace_probe():
        from ..testing import trace_counting_jit

        runner, ens = make_runner_and_ensemble()
        step = trace_counting_jit(runner.step_impl)
        new_ens, _ = step(ens)
        step(new_ens)  # same lane structure, new values: must not retrace
        return step.trace_count

    return [AuditProgram(
        name="ensemble_step", layer="ensemble",
        summary="vmapped batched trial step (B=4 free-fiber members, "
                "masked per-member accept ladder)",
        build=build, retrace_probe=retrace_probe)]
