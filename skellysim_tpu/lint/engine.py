"""skelly-lint engine: module parsing, jit-reachability, pragmas, rule driver.

Pure-stdlib AST analysis (no jax import — the linter must run before any
backend exists, e.g. as the first CI gate). The engine is repo-aware in two
ways the generic linters we could not `pip install` are not:

* **import-alias tables** per module, so rules match `jax.numpy` through any
  local alias (`jnp`, `_jnp`, ...) instead of a hardcoded spelling;
* a **jit-reachability call graph**: functions are seeds when decorated with
  (or wrapped by) `jax.jit`, and reachability propagates through calls the
  AST can resolve — bare names (from-imports / same-module defs), module
  aliases (`fc.update_cache`), and `self.` methods. Trace-hygiene findings
  fire only inside reachable functions, so host-side code (trajectory
  writers, the adaptive run loop, Ewald planning) is not flooded with
  false positives for its legitimate `float()` / `np.*` use.

Suppressions are pragmas with a mandatory reason, parsed from real comment
tokens only (pragma examples inside strings/docstrings are inert)::

    x = jnp.zeros(n)  # skelly-lint: ignore[dtype-discipline] -- reason here

A per-line pragma on a comment-only line applies to the next line. The
function-scoped variant ``ignore-function`` sits on (or immediately above) a
``def`` line and suppresses the named rules in that whole function — for
host-precompute helpers whose np-on-static-int work is deliberately frozen
into the trace. Pragmas that suppress nothing are themselves findings
(`lint-pragma`), so every pragma in the tree stays load-bearing: deleting
any one of them re-exposes its finding and the lint gate fails.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field

#: directories (relative to the package root) whose code is "hot path" —
#: inside the per-step jit programs or the multi-chip evaluators. Blanket
#: host-sync checks (block_until_ready / device_get) apply to every function
#: here, reachable or not.
HOT_PATH_DIRS = ("ops", "solver", "fibers", "bodies", "periphery", "parallel",
                 "system")

#: declared mixed-precision seams: files whose whole point is explicit
#: hi/lo dtype surgery (double-float kernels). dtype-discipline's
#: hardcoded-dtype check does not apply there.
DTYPE_SEAM_FILES = ("ops/df_kernels.py", "ops/pallas_df.py")

PRAGMA_RE = re.compile(
    r"#\s*skelly-lint:\s*(ignore|ignore-function)\[([^\]]*)\]"
    r"\s*(?:—|–|--|-)?\s*(.*)")


@dataclass(frozen=True)
class Finding:
    path: str          # path as given on the command line (relative ok)
    line: int
    col: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"


@dataclass
class Pragma:
    line: int          # line the pragma comment sits on
    target_line: int   # line (or `def` line for function scope) it covers
    rules: tuple       # rule ids it names
    reason: str
    #: "line" or "function" (`ignore-function` covers the def's whole body)
    scope: str = "line"
    used: bool = False


@dataclass
class FunctionInfo:
    qualname: str      # "fn" or "Class.method"; nested defs fold into parents
    node: ast.AST      # FunctionDef / AsyncFunctionDef
    cls: str | None    # enclosing class name, None at module level
    is_seed: bool = False


@dataclass
class ModuleInfo:
    path: str                      # as passed on the CLI
    relpath: str                   # package-relative, posix ("ops/kernels.py")
    tree: ast.Module = None
    lines: list = field(default_factory=list)
    pragmas: list = field(default_factory=list)       # [Pragma]
    functions: dict = field(default_factory=dict)     # qualname -> FunctionInfo
    #: local alias -> dotted module ("jnp" -> "jax.numpy", "fc" -> "...container")
    import_aliases: dict = field(default_factory=dict)
    #: local name -> (module, attr) for `from m import a [as b]`
    from_imports: dict = field(default_factory=dict)
    syntax_error: str | None = None

    def in_hot_path(self) -> bool:
        top = self.relpath.split("/", 1)[0]
        return top in HOT_PATH_DIRS

    @property
    def np_aliases(self) -> frozenset:
        """Local names bound to numpy (computed once; rules hit this for
        every visited Call node)."""
        if "_np_aliases" not in self.__dict__:
            self.__dict__["_np_aliases"] = frozenset(
                a for a, m in self.import_aliases.items() if m == "numpy")
        return self.__dict__["_np_aliases"]

    @property
    def jnp_aliases(self) -> frozenset:
        if "_jnp_aliases" not in self.__dict__:
            self.__dict__["_jnp_aliases"] = frozenset(
                a for a, m in self.import_aliases.items()
                if m == "jax.numpy")
        return self.__dict__["_jnp_aliases"]


def _parse_pragmas(src: str):
    """Pragmas from COMMENT tokens only — the rendered syntax inside
    docstrings (docs, error messages, this file) must stay inert."""
    pragmas = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(src).readline)
        comments = [(t.start[0], t.start[1], t.string)
                    for t in tokens if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError):
        return pragmas
    lines = src.splitlines()
    for lineno, col, text in comments:
        m = PRAGMA_RE.match(text)
        if m is None:
            continue
        kind = m.group(1)
        rules = tuple(r.strip() for r in m.group(2).split(",") if r.strip())
        reason = m.group(3).strip()
        own_line = col == 0 or lines[lineno - 1][:col].strip() == ""
        pragmas.append(Pragma(
            line=lineno, target_line=lineno + 1 if own_line else lineno,
            rules=rules, reason=reason,
            scope="function" if kind == "ignore-function" else "line"))
    return pragmas


def _module_relpath(path: str) -> str:
    """Path relative to the skellysim_tpu package root, posix separators.
    Files outside the package keep their basename-led path (rules that scope
    by package dir simply will not match them)."""
    norm = path.replace(os.sep, "/")
    marker = "skellysim_tpu/"
    idx = norm.rfind(marker)
    if idx >= 0:
        return norm[idx + len(marker):]
    return norm.lstrip("./")


def parse_module(path: str) -> ModuleInfo:
    mod = ModuleInfo(path=path, relpath=_module_relpath(path))
    with open(path, "r", encoding="utf-8") as fh:
        src = fh.read()
    mod.lines = src.splitlines()
    try:
        mod.tree = ast.parse(src, filename=path)
    except SyntaxError as e:  # compileall gates this first; report anyway
        mod.syntax_error = f"syntax error: {e.msg} (line {e.lineno})"
        return mod
    mod.pragmas = _parse_pragmas(src)

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                mod.import_aliases[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom):
            if node.module is None:
                # `from . import X [as Y]` binds a module object
                for a in node.names:
                    mod.import_aliases[a.asname or a.name] = a.name
            else:
                for a in node.names:
                    mod.from_imports[a.asname or a.name] = (node.module,
                                                            a.name)

    def collect(body, cls, prefix):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{node.name}"
                mod.functions[qual] = FunctionInfo(qualname=qual, node=node,
                                                   cls=cls)
            elif isinstance(node, ast.ClassDef):
                collect(node.body, node.name, f"{node.name}.")

    collect(mod.tree.body, None, "")
    return mod


# --------------------------------------------------------------- call graph

def _is_cached_fn(fi: FunctionInfo) -> bool:
    """True for functions decorated with functools.lru_cache/cache.

    These are sound REACHABILITY BARRIERS: a cached function hashes its
    arguments, and JAX tracers are unhashable — so in any working program a
    cached function (and everything below it) only ever sees static host
    values. Its np-heavy body is the repo's deliberate
    build-constants-at-trace-time pattern (FibMats, Vandermonde caches),
    not a trace-hygiene violation.
    """
    for d in fi.node.decorator_list:
        target = d.func if isinstance(d, ast.Call) else d
        name = (target.attr if isinstance(target, ast.Attribute)
                else target.id if isinstance(target, ast.Name) else None)
        if name in ("lru_cache", "cache"):
            return True
    return False


#: callables that compile their first argument like `jax.jit` does — the
#: repo's own jit twins count as reachability seeds too: skelly-scope's
#: compile observer (`obs.compile_log.observed_jit`, what System/ensemble
#: entry points route through since the telemetry PR) and the test/audit
#: trace counter. Missing one of these would silently drop whole call
#: trees out of the dtype/trace/host-sync gates (caught when the
#: observed_jit migration orphaned two pragmas).
_JIT_WRAPPER_NAMES = ("jit", "observed_jit", "trace_counting_jit")


def _is_jit_expr(node, mod: ModuleInfo) -> bool:
    """True for expressions that (possibly via functools.partial) name
    jax.jit or a repo jit twin: `jax.jit`, `jit` (from-imported),
    `observed_jit`, `trace_counting_jit`, `partial(jax.jit, ...)`."""
    if isinstance(node, ast.Attribute) and node.attr in _JIT_WRAPPER_NAMES:
        return True
    if isinstance(node, ast.Name):
        tgt = mod.from_imports.get(node.id)
        if tgt is not None and tgt[1] in _JIT_WRAPPER_NAMES:
            return True
    if isinstance(node, ast.Call) and node.args:
        fn = node.func
        is_partial = ((isinstance(fn, ast.Name) and fn.id == "partial")
                      or (isinstance(fn, ast.Attribute)
                          and fn.attr == "partial"))
        if is_partial:
            return _is_jit_expr(node.args[0], mod)
    return False


def _resolve_call(node, mod: ModuleInfo, enclosing_cls, modules_by_name):
    """Resolve a Name/Attribute callee to (module, qualname) or None.

    modules_by_name: dotted-module-suffix -> ModuleInfo for package modules.
    """
    if isinstance(node, ast.Name):
        tgt = mod.from_imports.get(node.id)
        if tgt is not None:
            other = modules_by_name.get(tgt[0].rsplit(".", 1)[-1])
            if other is not None and tgt[1] in other.functions:
                return other, tgt[1]
            return None
        if node.id in mod.functions:
            return mod, node.id
        return None
    if isinstance(node, ast.Attribute):
        recv = node.value
        if isinstance(recv, ast.Name):
            if recv.id == "self" and enclosing_cls is not None:
                qual = f"{enclosing_cls}.{node.attr}"
                if qual in mod.functions:
                    return mod, qual
                return None
            modname = None
            dotted = mod.import_aliases.get(recv.id)
            if dotted is not None:
                modname = dotted.rsplit(".", 1)[-1]
            elif recv.id in mod.from_imports:
                # `from ..bodies import bodies as bd` binds a module object
                # through a from-import; the imported NAME is the module
                modname = mod.from_imports[recv.id][1]
            if modname is not None:
                other = modules_by_name.get(modname)
                if other is not None and node.attr in other.functions:
                    return other, node.attr
    return None


class RepoContext:
    """Cross-module state shared by rules: the jit-reachable function set."""

    def __init__(self, modules):
        self.modules = modules
        # last dotted component -> module. Real module files take priority
        # over package __init__ stems (`bodies/bodies.py` over `bodies/`),
        # matching how `from ..bodies import bodies` resolves; remaining
        # collisions keep the first, which only risks missing an edge,
        # never inventing one.
        self.modules_by_name = {}
        inits = []
        for m in modules:
            if m.tree is None:
                continue
            stem = os.path.splitext(os.path.basename(m.relpath))[0]
            if stem == "__init__":
                inits.append(m)
                continue
            self.modules_by_name.setdefault(stem, m)
        for m in inits:
            stem = os.path.basename(os.path.dirname(m.relpath))
            if stem:
                self.modules_by_name.setdefault(stem, m)
        self.reachable = set()      # {(ModuleInfo, qualname)}
        self._build_reachability()

    # -- seeds -------------------------------------------------------------
    def _seed_functions(self):
        seeds = []
        for mod in self.modules:
            if mod.tree is None:
                continue
            for qual, fi in mod.functions.items():
                if any(_is_jit_expr(d, mod) for d in fi.node.decorator_list):
                    fi.is_seed = True
                    seeds.append((mod, qual))
            # jax.jit(fn, ...) wrapping anywhere in the module (e.g.
            # `self._solve_jit = jax.jit(self._solve_impl, ...)`)
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Call)
                        and _is_jit_expr(node.func, mod) and node.args):
                    continue
                cls = self._enclosing_class(mod, node)
                tgt = _resolve_call(node.args[0], mod, cls,
                                    self.modules_by_name)
                if tgt is not None:
                    tgt[0].functions[tgt[1]].is_seed = True
                    seeds.append(tgt)
        return seeds

    def _enclosing_class(self, mod, node):
        """Class whose method subtree contains ``node`` (None otherwise)."""
        for qual, fi in mod.functions.items():
            if fi.cls is None:
                continue
            for sub in ast.walk(fi.node):
                if sub is node:
                    return fi.cls
        return None

    # -- propagation -------------------------------------------------------
    def _build_reachability(self):
        work = list(self._seed_functions())
        seen = {(m.path, q) for m, q in work}
        while work:
            mod, qual = work.pop()
            fi = mod.functions[qual]
            for node in ast.walk(fi.node):
                target = None
                if isinstance(node, ast.Call):
                    target = _resolve_call(node.func, mod, fi.cls,
                                           self.modules_by_name)
                elif isinstance(node, (ast.Name, ast.Attribute)):
                    # bare references too: functions passed higher-order
                    # (matvec=..., jax.vmap(fn)) are traced when called
                    target = _resolve_call(node, mod, fi.cls,
                                           self.modules_by_name)
                if target is not None:
                    key = (target[0].path, target[1])
                    if (key not in seen
                            and not _is_cached_fn(
                                target[0].functions[target[1]])):
                        seen.add(key)
                        work.append(target)
        self.reachable = seen

    def is_reachable(self, mod: ModuleInfo, qualname: str) -> bool:
        return (mod.path, qualname) in self.reachable


def _function_span(mod: ModuleInfo, def_line: int):
    """(first, last) line of the def anchored at ``def_line``, or None.

    A decorated def's ``node.lineno`` is the ``def`` line, below its
    decorators — but a pragma "directly above the def" lands on the first
    decorator line, so any line from the first decorator through the
    ``def`` itself anchors the pragma.
    """
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        first = min([d.lineno for d in node.decorator_list] + [node.lineno])
        if first <= def_line <= node.lineno:
            return node.lineno, node.end_lineno
    return None


# ------------------------------------------------------------------ driver

def iter_py_files(paths):
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
        elif p.endswith(".py"):
            out.append(p)
    # de-dup while keeping order
    seen = set()
    uniq = []
    for p in out:
        key = os.path.abspath(p)
        if key not in seen:
            seen.add(key)
            uniq.append(p)
    return uniq


def lint_paths(paths, rules=None):
    """Run the registered rules over every .py under ``paths``.

    Returns a sorted list of unsuppressed `Finding`s (including lint-pragma
    findings for malformed/unknown/unused pragmas).
    """
    from .rules import RULES

    if rules is None:
        active = list(RULES)
    else:
        known = {r.id for r in RULES}
        unknown = sorted(set(rules) - known)
        if unknown:
            # a typo'd filter must not return a vacuous "clean" result —
            # callers gate on the emptiness of the return value
            raise ValueError(
                f"unknown rule id(s): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(known))})")
        active = [r for r in RULES if r.id in set(rules)]
    files = iter_py_files(paths)
    modules = [parse_module(f) for f in files]
    ctx = RepoContext([m for m in modules if m.tree is not None])

    known_ids = {r.id for r in RULES} | {"lint-pragma"}
    findings = []
    for mod in modules:
        if mod.syntax_error is not None:
            findings.append(Finding(mod.path, 1, 0, "lint-pragma",
                                    mod.syntax_error))
            continue
        raw = []
        for rule in active:
            raw.extend(rule.check(mod, ctx))
        # pragma validation
        for pr in mod.pragmas:
            for rid in pr.rules:
                if rid not in known_ids:
                    findings.append(Finding(
                        mod.path, pr.line, 0, "lint-pragma",
                        f"pragma names unknown rule id {rid!r} "
                        f"(known: {', '.join(sorted(known_ids))})"))
            if not pr.rules:
                findings.append(Finding(
                    mod.path, pr.line, 0, "lint-pragma",
                    "pragma names no rule id: use "
                    "`# skelly-lint: ignore[rule-id] — reason`"))
            if not pr.reason:
                findings.append(Finding(
                    mod.path, pr.line, 0, "lint-pragma",
                    "pragma is missing its reason: every suppression must "
                    "say why (`# skelly-lint: ignore[rule-id] — reason`)"))
        # suppression pass
        spans = {}
        for pr in mod.pragmas:
            if pr.scope == "function":
                spans[pr.line] = _function_span(mod, pr.target_line)
                if spans[pr.line] is None:
                    findings.append(Finding(
                        mod.path, pr.line, 0, "lint-pragma",
                        "ignore-function pragma is not attached to a `def` "
                        "line (place it on, or directly above, the def)"))
        for f in raw:
            suppressed = False
            for pr in mod.pragmas:
                if f.rule not in pr.rules:
                    continue
                if pr.scope == "line":
                    hit = f.line == pr.target_line
                else:
                    span = spans.get(pr.line)
                    hit = span is not None and span[0] <= f.line <= span[1]
                if hit:
                    pr.used = True
                    suppressed = True
            if not suppressed:
                findings.append(f)
        # a pragma that suppresses nothing is dead weight — or a typo hiding
        # the finding it meant to suppress. Only counted when its rules all
        # ran this invocation (a filtered run must not flag pragmas for
        # rules it skipped).
        active_ids = {r.id for r in active}
        for pr in mod.pragmas:
            if (not pr.used and pr.rules and pr.reason
                    and set(pr.rules) <= active_ids):
                findings.append(Finding(
                    mod.path, pr.line, 0, "lint-pragma",
                    f"unused suppression for {', '.join(pr.rules)}: the "
                    "pragma matches no finding on its line — remove it"))

    uniq = sorted(set(findings), key=lambda f: (f.path, f.line, f.col, f.rule,
                                                f.message))
    return uniq
