"""skelly-lint: repo-native static analysis for dtype, trace, and sharding
discipline.

Usage::

    python -m skellysim_tpu.lint [paths] [--list-rules]

or programmatically::

    from skellysim_tpu.lint import lint_paths
    findings = lint_paths(["skellysim_tpu"])   # [] when green

Rules and the suppression pragma syntax are documented in docs/lint.md.
This package is pure stdlib (ast) — importing it never initializes a JAX
backend, so it can run as the first CI gate.
"""

from .engine import Finding, lint_paths
from .rules import RULES

__all__ = ["Finding", "lint_paths", "RULES"]
