"""skelly-lint rule registry.

Three rule families, each motivated by a failure mode this codebase has
already hit or is structurally exposed to (docs/lint.md has the full
write-ups and the pragma syntax):

* ``dtype-discipline`` — the weak-type / f64-promotion leak family behind
  commit 46b498b (a silent f64 flow promoting the whole Krylov pipeline)
  and the round-2 FibMats leak (f64 constants promoting f32 states until
  TPU's f32-only LU fell off the device).
* ``trace-hygiene`` — concretizations inside jit-traced code:
  ``bool()``/``np.*`` on traced values abort tracing or silently bake
  run-time values into the compiled program; ``block_until_ready``/
  ``device_get`` in hot-path modules stall the device pipeline mid-solve.
* ``host-sync`` — the device->host transfer family inside jit-reachable
  code: ``.item()``, ``float()``/``int()``, and ``np.asarray``/``np.array``
  applied to traced values. Under jit these abort tracing; in eager
  callers of the same helpers they silently serialize the pipeline one
  scalar at a time. The runtime companion (`skellysim_tpu.audit`'s
  host-sync check) catches the callback-based variants the AST cannot see.
* ``sharding-annotation`` — ``shard_map`` without explicit
  ``in_specs``/``out_specs`` (or ``device_put`` in ``parallel/`` without an
  explicit sharding) silently replicates operands: the expected O(N/D)
  per-chip footprint becomes D full copies, an OOM found only in a
  profiler.

Every check is syntactic and conservative: when the AST cannot prove the
pattern (unknown receiver, dynamic dispatch), it stays silent. Deliberate
violations carry a per-line pragma with a reason.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from .engine import (DTYPE_SEAM_FILES, Finding, ModuleInfo, RepoContext)

#: jnp constructors whose result dtype defaults to the x64-dependent float
#: (f64 under jax_enable_x64, f32 otherwise) when ``dtype`` is omitted.
FLOAT_DEFAULT_CREATORS = ("zeros", "ones", "empty")
#: constructors that inherit dtype from their payload: flagged only when the
#: payload contains a Python float literal (weak-typed, width follows x64).
PAYLOAD_CREATORS = ("array", "asarray", "full", "linspace")
#: positional index of the dtype argument per constructor:
#: zeros/ones/empty(shape, dtype), full(shape, fill, dtype),
#: array/asarray(obj, dtype), arange(start, stop, step, dtype),
#: linspace(start, stop, num, endpoint, retstep, dtype), eye(N, M, k, dtype).
DTYPE_ARG_POS = {"zeros": 1, "ones": 1, "empty": 1, "full": 2, "array": 1,
                 "asarray": 1, "arange": 3, "linspace": 5, "eye": 3}

#: np.* attributes that are safe inside traced code (host-side constants and
#: dtype/metadata queries, not array ops on traced values).
NP_TRACE_SAFE = {
    "pi", "e", "inf", "nan", "newaxis", "euler_gamma",
    "float16", "float32", "float64", "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool_", "complex64",
    "complex128", "dtype", "finfo", "iinfo", "ndarray", "integer",
    "floating", "issubdtype",
}


@dataclass(frozen=True)
class Rule:
    id: str
    summary: str
    check: object  # callable(mod, ctx) -> list[Finding]


# ------------------------------------------------------------------ helpers

def _jnp_creator(node: ast.Call, mod: ModuleInfo):
    """Name of the jnp constructor a call invokes, or None."""
    fn = node.func
    if (isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name)
            and fn.value.id in mod.jnp_aliases):
        return fn.attr
    if isinstance(fn, ast.Name):
        tgt = mod.from_imports.get(fn.id)
        if tgt is not None and tgt[0].endswith("numpy") and tgt[0] != "numpy":
            return tgt[1]
    return None


def _has_dtype(node: ast.Call, creator: str) -> bool:
    if any(kw.arg == "dtype" for kw in node.keywords):
        return True
    pos = DTYPE_ARG_POS.get(creator)
    return pos is not None and len(node.args) > pos


def _contains_float_literal(nodes) -> bool:
    for n in nodes:
        for sub in ast.walk(n):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, float):
                return True
    return False


def _is_hard_dtype(node, mod: ModuleInfo) -> str | None:
    """'float64'/'float32' when ``node`` is a bare jnp/np f64/f32 dtype
    reference (not nested in a wider expression)."""
    if (isinstance(node, ast.Attribute)
            and node.attr in ("float64", "float32")
            and isinstance(node.value, ast.Name)
            and (node.value.id in mod.jnp_aliases
                 or node.value.id in mod.np_aliases)):
        return node.attr
    return None


def _in_signature_defaults(func_nodes, target) -> bool:
    """True when ``target`` sits in a def's default-argument list — API
    defaults like ``def make_group(..., dtype=jnp.float64)`` are the
    caller-visible contract, not a leak."""
    for fn in func_nodes:
        defaults = list(fn.args.defaults) + [d for d in fn.args.kw_defaults
                                             if d is not None]
        for d in defaults:
            for sub in ast.walk(d):
                if sub is target:
                    return True
    return False


# ------------------------------------------------- rule: dtype-discipline

def check_dtype_discipline(mod: ModuleInfo, ctx: RepoContext):
    out = []
    rid = "dtype-discipline"

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        creator = _jnp_creator(node, mod)
        if creator is None:
            continue
        if creator in FLOAT_DEFAULT_CREATORS and not _has_dtype(node, creator):
            out.append(Finding(
                mod.path, node.lineno, node.col_offset, rid,
                f"jnp.{creator}(...) without an explicit dtype defaults to "
                "the x64-dependent float width (the 46b498b f64-leak "
                "family); pass dtype=... derived from the state"))
        elif creator == "arange" and not _has_dtype(node, creator):
            out.append(Finding(
                mod.path, node.lineno, node.col_offset, rid,
                "jnp.arange(...) without an explicit dtype follows the x64 "
                "flag (int64/f64 under x64, int32/f32 without); index "
                "arrays should pin dtype=jnp.int32"))
        elif (creator in PAYLOAD_CREATORS and not _has_dtype(node, creator)
              and _contains_float_literal(node.args)):
            out.append(Finding(
                mod.path, node.lineno, node.col_offset, rid,
                f"jnp.{creator}(...) of a Python float literal without an "
                "explicit dtype is weak-typed: its width follows "
                "jax_enable_x64, not the state"))

    # hardcoded f64/f32 casts in jit-reachable hot-path code, outside the
    # declared double-float seam files. Host-side assembly (shell operator
    # build, quadrature precompute, Ewald planning) legitimately pins f64;
    # the leak family is a pinned width on the TRACED data path, where the
    # state's dtype must rule.
    if mod.in_hot_path() and mod.relpath not in DTYPE_SEAM_FILES:
        func_nodes = [fi.node for fi in mod.functions.values()]
        reachable_nodes = [fi.node for q, fi in mod.functions.items()
                           if ctx.is_reachable(mod, q)]
        hard_sites = []
        for root in reachable_nodes:
            hard_sites.extend(_hard_dtype_sites(root, mod))
        for call, target, which in hard_sites:
            if _in_signature_defaults(func_nodes, target):
                continue
            # anchor at the CALL line (not the dtype expression's own line):
            # a `dtype=` on a 79-column continuation line must still be
            # suppressible by a pragma on the statement line, like the
            # missing-dtype sub-checks
            out.append(Finding(
                mod.path, call.lineno, call.col_offset, rid,
                f"hardcoded {which} on the jit-traced data path pins a "
                "precision the state does not carry; derive the dtype from "
                "an operand (declared mixed-precision seams live in "
                f"{' / '.join(DTYPE_SEAM_FILES)})"))
    return out


def _hard_dtype_sites(root, mod: ModuleInfo):
    sites = []
    for node in ast.walk(root):
        if not isinstance(node, ast.Call):
            continue
        # (a) dtype=jnp.float64 keyword on any call
        for kw in node.keywords:
            which = kw.arg == "dtype" and _is_hard_dtype(kw.value, mod)
            if which:
                sites.append((node, kw.value, which))
        # (b) .astype(jnp.float64)
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype" and node.args):
            which = _is_hard_dtype(node.args[0], mod)
            if which:
                sites.append((node, node.args[0], which))
        # (c) positional dtype slot of a jnp constructor
        creator = _jnp_creator(node, mod)
        pos = DTYPE_ARG_POS.get(creator)
        if pos is not None and len(node.args) > pos:
            which = _is_hard_dtype(node.args[pos], mod)
            if which:
                sites.append((node, node.args[pos], which))
    return sites


# --------------------------------------------------- rule: trace-hygiene

def _shape_like(node) -> bool:
    """Expressions that are Python ints at trace time: x.shape[i], x.ndim,
    x.size, len(...), and arithmetic over those."""
    if isinstance(node, ast.BinOp):
        return _shape_like(node.left) and _shape_like(node.right)
    if isinstance(node, ast.Constant):
        # any literal: float("inf") / int("0x10", 16) are host conversions
        return True
    if isinstance(node, ast.Subscript):
        return (isinstance(node.value, ast.Attribute)
                and node.value.attr == "shape")
    if isinstance(node, ast.Attribute):
        return node.attr in ("ndim", "size", "n_nodes", "n_fibers",
                             "n_bodies", "solution_size")
    if isinstance(node, ast.Call):
        return isinstance(node.func, ast.Name) and node.func.id == "len"
    return False


def _literal_payload(node) -> bool:
    """Payloads that are host constants at trace time (literals, possibly
    nested in lists/tuples, or shape arithmetic) — `np.asarray` of these
    freezes a constant rather than syncing a traced value."""
    if isinstance(node, (ast.List, ast.Tuple)):
        return all(_literal_payload(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp):
        return _literal_payload(node.operand)
    return isinstance(node, ast.Constant) or _shape_like(node)


def check_trace_hygiene(mod: ModuleInfo, ctx: RepoContext):
    out = []
    rid = "trace-hygiene"
    np_names = mod.np_aliases

    shadowed = set(mod.from_imports) | set(mod.import_aliases)

    def scan_body(fn_node, qualname):
        for node in ast.walk(fn_node):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (isinstance(fn, ast.Name) and fn.id == "bool"
                    and fn.id not in shadowed and node.args
                    and not _shape_like(node.args[0])):
                out.append(Finding(
                    mod.path, node.lineno, node.col_offset, rid,
                    f"bool() inside jit-reachable `{qualname}` "
                    "concretizes its operand: a traced value here aborts "
                    "tracing (or silently bakes in a host constant)"))
            elif (isinstance(fn, ast.Attribute)
                  and isinstance(fn.value, ast.Name)
                  and fn.value.id in np_names
                  and fn.attr not in NP_TRACE_SAFE
                  and not (fn.attr in NP_SYNC_CALLS and node.args
                           and not _literal_payload(node.args[0]))):
                # asarray/array of a NON-literal payload is host-sync's
                # (a device->host transfer, not a frozen constant)
                out.append(Finding(
                    mod.path, node.lineno, node.col_offset, rid,
                    f"np.{fn.attr}() inside jit-reachable `{qualname}` "
                    "evaluates on host: traced operands abort tracing, "
                    "constant operands silently freeze into the program "
                    "(use jnp, or hoist to setup code)"))

    for qual, fi in mod.functions.items():
        if ctx.is_reachable(mod, qual):
            scan_body(fi.node, qual)

    # blanket host-sync check: these stall the pipeline wherever they appear
    # in hot-path modules, host-side driver code included
    if mod.in_hot_path():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name in ("block_until_ready", "device_get"):
                out.append(Finding(
                    mod.path, node.lineno, node.col_offset, rid,
                    f"{name} in a hot-path module stalls the device "
                    "pipeline; fetch results once per step at the loop "
                    "boundary instead"))
    return out


# ------------------------------------------------------- rule: host-sync

#: np calls that force a device->host transfer when their payload is a
#: traced value (not a literal/shape constant)
NP_SYNC_CALLS = ("asarray", "array")


def check_host_sync(mod: ModuleInfo, ctx: RepoContext):
    """Device->host transfers at trace time inside jit-reachable code.

    `.item()`, `float()`/`int()`, and `np.asarray`/`np.array` on a traced
    value abort tracing under jit; reached from an eager caller they
    silently sync the device pipeline one value at a time (the per-scalar
    transfer stall SURVEY §5.8 charges against the reference's host loop).
    The lowered-program twin is `skellysim_tpu.audit`'s host-sync check,
    which catches the callback-based syncs no source pattern reveals.
    """
    out = []
    rid = "host-sync"
    np_names = mod.np_aliases
    shadowed = set(mod.from_imports) | set(mod.import_aliases)

    for qual, fi in mod.functions.items():
        if not ctx.is_reachable(mod, qual):
            continue
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (isinstance(fn, ast.Name) and fn.id in ("float", "int")
                    and fn.id not in shadowed and node.args
                    and not _shape_like(node.args[0])):
                out.append(Finding(
                    mod.path, node.lineno, node.col_offset, rid,
                    f"{fn.id}() inside jit-reachable `{qual}` pulls its "
                    "operand to host: a traced value here aborts tracing; "
                    "an eager caller syncs the pipeline per scalar"))
            elif isinstance(fn, ast.Attribute) and fn.attr == "item":
                out.append(Finding(
                    mod.path, node.lineno, node.col_offset, rid,
                    f".item() inside jit-reachable `{qual}` forces a "
                    "device->host sync per call"))
            elif (isinstance(fn, ast.Attribute)
                  and isinstance(fn.value, ast.Name)
                  and fn.value.id in np_names
                  and fn.attr in NP_SYNC_CALLS and node.args
                  and not _literal_payload(node.args[0])):
                out.append(Finding(
                    mod.path, node.lineno, node.col_offset, rid,
                    f"np.{fn.attr}() of a non-literal payload inside "
                    f"jit-reachable `{qual}` transfers the value to host "
                    "(aborts tracing under jit; serializes the device "
                    "pipeline in eager callers) — use jnp.asarray, or "
                    "fetch once at the loop boundary"))
    return out


# ------------------------------------------------------- rule: axis-name

#: mesh-axis-taking collective callables, with the positional slot of their
#: axis-name argument: psum(x, axis_name), ppermute(x, axis_name, perm),
#: all_gather(x, axis_name, ...), axis_index(axis_name), ...
COLLECTIVE_AXIS_ARG_POS = {
    "psum": 1, "pmax": 1, "pmin": 1, "pmean": 1, "ppermute": 1,
    "pshuffle": 1, "all_gather": 1, "all_to_all": 1, "psum_scatter": 1,
    "axis_index": 0,
}
#: keyword spellings of the same argument across the lax collective family
_AXIS_KWARGS = ("axis_name", "axes")


def _axis_literal(node):
    """The ast node of a hardcoded axis-name string in ``node`` (a string
    constant, possibly inside a tuple/list of axis names), or None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node
    if isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                return e
    return None


def check_axis_name(mod: ModuleInfo, ctx: RepoContext):
    """Collective calls with a hardcoded axis-name string literal.

    The mesh axes are defined ONCE (`parallel.mesh.FIBER_AXIS` /
    `MEMBER_AXIS`); a collective spelled `lax.psum(x, "fib")` keeps working
    until someone renames the mesh axis, then hangs or mis-reduces with no
    error pointing at the drifted literal. Jit-reachable code only — the
    replication analyzer (`audit.repflow`, docs/parallel.md) checks the
    lowered twin of the same discipline.
    """
    out = []
    rid = "axis-name"
    for qual, fi in mod.functions.items():
        if not ctx.is_reachable(mod, qual):
            continue
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            pos = COLLECTIVE_AXIS_ARG_POS.get(name)
            if pos is None:
                continue
            candidates = [kw.value for kw in node.keywords
                          if kw.arg in _AXIS_KWARGS]
            if len(node.args) > pos:
                candidates.append(node.args[pos])
            for cand in candidates:
                if _axis_literal(cand) is not None:
                    out.append(Finding(
                        mod.path, node.lineno, node.col_offset, rid,
                        f"{name}() with a hardcoded axis-name string "
                        "literal in jit-reachable code: a mesh-axis rename "
                        "silently strands this collective — use "
                        "parallel.mesh.FIBER_AXIS / MEMBER_AXIS"))
                    break
    return out


# ----------------------------------------------- rule: sharding-annotation

def check_sharding_annotation(mod: ModuleInfo, ctx: RepoContext):
    out = []
    rid = "sharding-annotation"
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = (fn.attr if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name) else None)
        if name == "shard_map":
            kws = {kw.arg for kw in node.keywords}
            missing = [k for k in ("in_specs", "out_specs") if k not in kws]
            if missing:
                out.append(Finding(
                    mod.path, node.lineno, node.col_offset, rid,
                    f"shard_map without explicit {'/'.join(missing)}: "
                    "implicit specs replicate operands (D full copies "
                    "instead of O(N/D) per chip) — annotate every operand"))
        elif (name == "device_put"
              and mod.relpath.startswith("parallel/")):
            has_sharding = (len(node.args) >= 2
                            or any(kw.arg in ("device", "sharding", None)
                                   for kw in node.keywords))
            if not has_sharding:
                out.append(Finding(
                    mod.path, node.lineno, node.col_offset, rid,
                    "device_put in parallel/ without an explicit sharding "
                    "places on the default device (silent replication / "
                    "wrong placement on a mesh); pass a NamedSharding"))
    return out


# --------------------------------------------------------- rule: raw-dma

#: Pallas DMA / semaphore primitives whose safety argument (happens-before
#: ordering, credit balance) only the `dma` audit check can verify
RAW_DMA_CALLS = ("make_async_remote_copy", "semaphore_signal",
                 "semaphore_wait", "get_barrier_semaphore")


def check_raw_dma(mod: ModuleInfo, ctx: RepoContext):
    """DMA/semaphore primitives outside the audited kernel modules.

    `skellysim_tpu.audit`'s ``dma`` check (skelly-fence) statically proves
    read-before-arrival, overwrite-in-flight, and credit-balance safety —
    but only for kernels registered through the ``auditable_kernels()``
    seam (`audit.kernels`). A raw `pltpu.make_async_remote_copy` /
    semaphore call in any other jit-reachable code is an UNVERIFIED race
    surface: the verifier never sees it, CI cannot execute it, and its
    safety argument is whatever comment sits next to it. Modules defining
    ``auditable_kernels`` at top level are the licensed boundary.
    """
    out = []
    rid = "raw-dma"
    if "auditable_kernels" in mod.functions:
        return out
    for qual, fi in mod.functions.items():
        if not ctx.is_reachable(mod, qual):
            continue
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name in RAW_DMA_CALLS:
                out.append(Finding(
                    mod.path, node.lineno, node.col_offset, rid,
                    f"{name} inside jit-reachable `{qual}` is outside any "
                    "module registered via auditable_kernels(): the dma "
                    "audit check cannot verify its ordering/credit safety "
                    "— move the kernel into a registered module (see "
                    "audit/kernels.py)"))
    return out


# -------------------------------------------------------- rule: mul-mask

#: terminal identifier names that read as boolean live-masks in this tree
#: (fibers.active, node_mask, keep, valid) — conservative on purpose: a
#: float *weight* array named `w` multiplying a field is legitimate math
MASK_NAMES = {"mask", "keep", "active", "valid", "alive", "live"}


def _mask_like(node) -> bool:
    """Expressions that read as a boolean live-mask: names/attributes with
    a mask-ish terminal identifier, an inline comparison, `~mask`, a mask
    broadcast (`active[:, None]`), or a mask cast (`mask.astype(...)`)."""
    if isinstance(node, ast.Compare):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Invert):
        return _mask_like(node.operand)
    if isinstance(node, ast.Subscript):
        return _mask_like(node.value)
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype"):
        return _mask_like(node.func.value)
    name = (node.id if isinstance(node, ast.Name)
            else node.attr if isinstance(node, ast.Attribute) else None)
    if name is None:
        return False
    low = name.lower()
    return (low in MASK_NAMES or low.endswith("mask")
            or low.endswith("_active") or low.startswith("active"))


def check_mul_mask(mod: ModuleInfo, ctx: RepoContext):
    """Multiplicative masking (`field * mask` / `mask * field`) in
    jit-reachable code.

    `x * mask` neutralizes padded slots only while `x` is finite: the IEEE
    products `0 * inf` and `0 * nan` are NaN, so one overflowed lane turns
    its zero mask into poison that every downstream reduction absorbs.
    `jnp.where(mask, x, 0)` is bitwise identical for finite `x` and exact
    for nonfinite `x` — it is the discipline the `mask` audit check
    (`audit.maskflow`, docs/audit.md "Masking discipline") proves on the
    lowered program; this rule catches the source-level pattern before it
    lowers. Flags single-sided mask products only (mask * mask is integer
    occupancy math, not field neutralization).
    """
    out = []
    rid = "mul-mask"
    for qual, fi in mod.functions.items():
        if not ctx.is_reachable(mod, qual):
            continue
        for node in ast.walk(fi.node):
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Mult)):
                continue
            if _mask_like(node.left) == _mask_like(node.right):
                continue
            out.append(Finding(
                mod.path, node.lineno, node.col_offset, rid,
                "multiplicative masking: `x * mask` maps a nonfinite x to "
                "NaN (0 * inf) instead of zero — use jnp.where(mask, x, 0) "
                "(bitwise identical for finite x; docs/audit.md \"Masking "
                "discipline\")"))
    return out


RULES = (
    Rule("dtype-discipline",
         "array creation without explicit dtype / hardcoded f64-f32 casts "
         "in hot-path code (the 46b498b weak-type leak family)",
         check_dtype_discipline),
    Rule("trace-hygiene",
         "bool()/np.* concretizations inside jit-reachable functions; "
         "block_until_ready/device_get in hot-path modules",
         check_trace_hygiene),
    Rule("host-sync",
         ".item()/float()/int()/np.asarray on traced values in "
         "jit-reachable code (device->host transfer at trace time)",
         check_host_sync),
    Rule("axis-name",
         "collective calls (psum/ppermute/all_gather/...) with a hardcoded "
         "axis-name string literal instead of parallel.mesh.FIBER_AXIS in "
         "jit-reachable code",
         check_axis_name),
    Rule("sharding-annotation",
         "shard_map without explicit in_specs/out_specs; device_put in "
         "parallel/ without an explicit sharding",
         check_sharding_annotation),
    Rule("raw-dma",
         "pltpu DMA/semaphore primitives in jit-reachable code outside "
         "modules registered via auditable_kernels() (the dma audit "
         "check's verified boundary)",
         check_raw_dma),
    Rule("mul-mask",
         "multiplicative masking (`field * mask`) of float fields in "
         "jit-reachable code: 0 * inf = NaN — use jnp.where(mask, x, 0) "
         "(the source-level twin of the mask audit check)",
         check_mul_mask),
    Rule("lint-pragma",
         "malformed, unknown-rule, reason-less, or unused suppression "
         "pragmas (engine-enforced; keeps every pragma load-bearing)",
         lambda mod, ctx: []),
)
