"""skelly-lint CLI: `python -m skellysim_tpu.lint [paths] [--list-rules]`.

Exit status: 0 when every finding is suppressed (or none exist), 1 when any
unsuppressed finding remains, 2 on usage errors — so CI can gate on it
directly (`ci/run_ci.sh` runs it right after the byte-compile stage in every
tier).
"""

from __future__ import annotations

import argparse
import os
import sys

from .engine import iter_py_files, lint_paths
from .rules import RULES


def _default_paths():
    """The skellysim_tpu package directory containing this linter."""
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m skellysim_tpu.lint",
        description="Repo-native static analysis: dtype, trace, and "
                    "sharding discipline (see docs/lint.md).")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to lint (default: the "
                             "skellysim_tpu package)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print every rule id with its one-line summary "
                             "and exit")
    parser.add_argument("--rule", action="append", default=None,
                        metavar="RULE-ID",
                        help="run only this rule (repeatable)")
    args = parser.parse_args(argv)

    if args.list_rules:
        width = max(len(r.id) for r in RULES)
        for r in RULES:
            print(f"{r.id:<{width}}  {r.summary}")
        return 0

    paths = args.paths or _default_paths()
    for p in paths:
        if not os.path.exists(p):
            print(f"skelly-lint: no such path: {p}", file=sys.stderr)
            return 2
        if not os.path.isdir(p) and not p.endswith(".py"):
            print(f"skelly-lint: not a Python file or directory: {p}",
                  file=sys.stderr)
            return 2
    if not iter_py_files(paths):
        # a gating invocation that lints zero files must not report success
        print("skelly-lint: no .py files found under the given paths",
              file=sys.stderr)
        return 2
    if args.rule:
        known = {r.id for r in RULES}
        unknown = [r for r in args.rule if r not in known]
        if unknown:
            print(f"skelly-lint: unknown rule id(s): {', '.join(unknown)} "
                  f"(try --list-rules)", file=sys.stderr)
            return 2

    findings = lint_paths(paths, rules=args.rule)
    for f in findings:
        print(f.render())
    if findings:
        print(f"skelly-lint: {len(findings)} finding(s). Fix them or "
              "suppress per line with "
              "`# skelly-lint: ignore[rule-id] — reason` (docs/lint.md).",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
