"""skellysim_tpu: TPU-native cytoskeletal hydrodynamics framework.

A ground-up JAX/XLA re-design of the capabilities of SkellySim
(flatironinstitute/SkellySim): flexible fibers (slender-body theory),
rigid bodies, a confining periphery, and point/background flow sources
coupled through zero-Reynolds-number Stokes hydrodynamics, solved each
timestep with matrix-free preconditioned GMRES.

Design stance (see SURVEY.md §7): pure-functional state pytrees + jit'd
operators instead of the reference's object-soup + MPI. Fibers are a
dense batched tensor [n_fib, n_nodes, ...]; the N-body Stokes kernel
evaluations run as blocked dense contractions on the MXU; multi-chip
scaling uses jax.sharding.Mesh + shard_map with ICI collectives instead
of MPI.
"""

import os as _os

import jax as _jax

__version__ = "0.1.0"

TRAJECTORY_VERSION = 1

# MXU precision policy. TPU float32 matmuls default to single-pass bfloat16,
# which loses ~5 decimal digits in every contraction: the GMRES operator then
# converges (self-consistently) to the solution of a 1e-2-perturbed system and
# e.g. a force-free fiber radiates O(0.1) spurious far-field flow. Every
# contraction in the implicit solve path therefore runs at HIGHEST precision
# by default (6-pass bf16 on MXU ~= true f32). Override per-process with
# SKELLYSIM_MATMUL_PRECISION={default,high,highest} for perf experiments.
_jax.config.update("jax_default_matmul_precision",
                   _os.environ.get("SKELLYSIM_MATMUL_PRECISION", "highest"))
