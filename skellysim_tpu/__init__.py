"""skellysim_tpu: TPU-native cytoskeletal hydrodynamics framework.

A ground-up JAX/XLA re-design of the capabilities of SkellySim
(flatironinstitute/SkellySim): flexible fibers (slender-body theory),
rigid bodies, a confining periphery, and point/background flow sources
coupled through zero-Reynolds-number Stokes hydrodynamics, solved each
timestep with matrix-free preconditioned GMRES.

Design stance (see SURVEY.md §7): pure-functional state pytrees + jit'd
operators instead of the reference's object-soup + MPI. Fibers are a
dense batched tensor [n_fib, n_nodes, ...]; the N-body Stokes kernel
evaluations run as blocked dense contractions on the MXU; multi-chip
scaling uses jax.sharding.Mesh + shard_map with ICI collectives instead
of MPI.
"""

__version__ = "0.1.0"

TRAJECTORY_VERSION = 1
