from . import param_tools, toml_io
from .schema import (BackgroundSource, Body, Config, ConfigEllipsoidal,
                     ConfigRevolution, ConfigSpherical, DynamicInstability,
                     EllipsoidalPeriphery, Fiber, Params, Periphery,
                     PeripheryBinding, Point, RevolutionPeriphery,
                     SphericalPeriphery, load_config, perturbed_fiber_positions,
                     to_runtime_params, unpack)
