from . import param_tools, toml_io
from .schema import (BackgroundSource, Body, Config, ConfigEllipsoidal,
                     ConfigRevolution, ConfigSpherical, DynamicInstability,
                     EllipsoidalPeriphery, EnsembleSweep, Fiber, Params,
                     Periphery, PeripheryBinding, Point, RevolutionPeriphery,
                     RuntimeConfig, ServeConfig, SphericalPeriphery,
                     SweepAxis, config_from_data, load_config,
                     load_runtime_config, load_serve_config,
                     perturbed_fiber_positions, to_runtime_params, unpack)
from .sweep import (MemberPlan, apply_overrides, expand_members,  # noqa: F401
                    load_members, load_sweep)
