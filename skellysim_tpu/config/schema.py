"""Typed configuration schema → TOML (the user-facing config contract).

Capability mirror of the reference Python toolkit's dataclass schema
(`/root/reference/src/skelly_sim/skelly_config.py:253-1036`): the field names
and defaults ARE the TOML contract read by the runtime, so they match the
reference exactly; the placement/generation logic is re-implemented on
vectorized numpy + `param_tools`.

Layout notes vs the reference:
- `Config.save()` validates types and unknown attributes, then TOML-dumps.
- `load_config()` is the inverse (the reference only reads TOML from C++).
- `to_runtime_params()` bridges the schema-level `Params` to the runtime
  `skellysim_tpu.params.Params` (static jit-relevant configuration).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field, fields, is_dataclass
from typing import List

import numpy as np
from scipy.optimize import brentq
from scipy.special import ellipe, ellipeinc

from . import param_tools, toml_io
from .. import params as runtime_params

__all__ = [
    "Fiber", "DynamicInstability", "PeripheryBinding", "Params",
    "Periphery", "SphericalPeriphery", "EllipsoidalPeriphery",
    "RevolutionPeriphery", "Body", "Point", "BackgroundSource",
    "Config", "ConfigSpherical", "ConfigEllipsoidal", "ConfigRevolution",
    "EnsembleSweep", "SweepAxis", "RuntimeConfig",
    "perturbed_fiber_positions", "load_config", "load_runtime_config",
    "unpack", "to_runtime_params",
]


# ---------------------------------------------------------------------------
# helpers

def _vec3() -> List[float]:
    return [0.0, 0.0, 0.0]


def _ivec3() -> List[int]:
    return [0, 1, 2]


def _quat_identity() -> List[float]:
    return [0.0, 0.0, 0.0, 1.0]


def _random_unit_vector(rng: np.random.Generator) -> np.ndarray:
    v = rng.normal(size=3)
    return v / np.linalg.norm(v)


def _random_orthogonal(normal: np.ndarray, rng: np.random.Generator) -> np.ndarray:
    off = np.array([1.0, 0, 0]) if (normal[1] or normal[2]) else np.array([0, 1.0, 0])
    b = np.cross(normal, off)
    b /= np.linalg.norm(b)
    c = np.cross(normal, b)
    c /= np.linalg.norm(c)
    theta = 2 * np.pi * rng.uniform()
    return b * np.cos(theta) + c * np.sin(theta)


def _sin_arc_length(amplitude: float, xf: float) -> float:
    """Arc length of amplitude*sin(2πx/xf) over one period [0, xf]."""
    a2 = (2 * np.pi * amplitude / xf) ** 2
    return xf / np.pi * (ellipe(-a2) + np.sqrt(1 + a2) * ellipe(a2 / (1 + a2)))


def _cos_arc_length(amplitude: float, xi: float, xf: float, x_max: float) -> float:
    """Arc length of amplitude*cos(2πx/x_max) on [xi, xf]."""
    k = 2 * np.pi / x_max
    a2 = (k * amplitude) ** 2
    return (ellipeinc(k * xf, -a2) - ellipeinc(k * xi, -a2)) / k


def perturbed_fiber_positions(amplitude: float, length: float, x0, normal,
                              n_nodes: int, ortho=None,
                              rng: np.random.Generator | None = None) -> np.ndarray:
    """[n_nodes, 3] fiber nodes: straight along `normal` with a one-period
    cosine perturbation of the given amplitude, arc-length-parameterized so
    node spacing is uniform in arc length and the total equals `length`
    (reference `perturbed_fiber_positions`, `skelly_config.py:130-169`)."""
    rng = rng or np.random.default_rng()
    x0 = np.asarray(x0, dtype=float)
    normal = np.asarray(normal, dtype=float)

    # axial extent x_max such that the perturbed curve has the right length
    x_max = brentq(lambda xf: _sin_arc_length(amplitude, xf) - length,
                   1e-3 * length, length)
    if ortho is None:
        ortho = _random_orthogonal(normal, rng)

    # place nodes at equal arc-length increments by inverting s(x)
    ds = length / (n_nodes - 1)
    xs = np.zeros(n_nodes)
    for i in range(1, n_nodes):
        lo = xs[i - 1]
        xs[i] = brentq(
            lambda xf: _cos_arc_length(amplitude, lo, xf, x_max) - ds,
            lo, x_max + 1e-9) if i < n_nodes - 1 else x_max
    positions = np.outer(xs, normal)
    positions += np.outer(amplitude * (np.cos(2 * np.pi * xs / x_max) - 1.0), ortho)
    return positions + x0


def _min_sep_ok(x0: np.ndarray, minus_ends: list, ds_min: float) -> bool:
    if not minus_ends:
        return True
    d2 = np.sum((np.asarray(minus_ends) - x0) ** 2, axis=1)
    return bool(np.all(d2 >= ds_min * ds_min))


# ---------------------------------------------------------------------------
# schema dataclasses (field names/defaults = the TOML contract)

@dataclass
class Fiber:
    """One fiber (reference `Fiber`, `skelly_config.py:253-308`)."""
    n_nodes: int = 32
    parent_body: int = -1
    parent_site: int = -1
    force_scale: float = 0.0
    bending_rigidity: float = 2.5e-3
    radius: float = 0.0125
    length: float = 1.0
    minus_clamped: bool = False
    x: List[float] = field(default_factory=list)

    def fill_node_positions(self, x0, normal) -> None:
        """Straight fiber from x0 along `normal`, uniformly spaced."""
        x0 = np.asarray(x0, dtype=float)
        normal = np.asarray(normal, dtype=float)
        s = np.linspace(0.0, self.length, self.n_nodes)
        self.x = (x0[None, :] + s[:, None] * normal[None, :]).ravel().tolist()


@dataclass
class DynamicInstability:
    n_nodes: int = 0
    v_growth: float = 0.0
    f_catastrophe: float = 0.0
    v_grow_collision_scale: float = 0.5
    f_catastrophe_collision_scale: float = 2.0
    nucleation_rate: float = 0.0
    radius: float = 0.025
    min_length: float = 0.5
    bending_rigidity: float = 2.5e-3
    min_separation: float = 0.1


@dataclass
class PeripheryBinding:
    active: bool = False
    polar_angle_start: float = 0.0
    polar_angle_end: float = 0.5 * np.pi
    threshold: float = 0.75


@dataclass
class Params:
    """System parameters (reference `Params`, `skelly_config.py:373-430`)."""
    eta: float = 1.0
    dt_initial: float = 0.025
    dt_min: float = 1e-5
    dt_max: float = 0.025
    dt_write: float = 0.1
    t_final: float = 100.0
    gmres_tol: float = 1e-8
    # communication-avoiding s-step GMRES block size (1 = the sequential
    # cycle; see skellysim_tpu/params.py `gmres_block_s` for semantics)
    gmres_block_s: int = 1
    # skelly-guard device-side escalation ladder (all default OFF; see
    # skellysim_tpu/params.py `guard_*` and docs/robustness.md): on a
    # retryable solver health verdict, retry the trial at halved dt up to
    # N times, then fall back gmres_block_s -> 1, then the full-f64 dense
    # Krylov interior, before declaring the member failed
    guard_dt_halvings: int = 0
    guard_block_fallback: bool = False
    guard_f64_fallback: bool = False
    # skelly-flight physics flight recorder: device-side [K, 13] ring of
    # per-step diagnostics (strain/speed/clearance/norms/health) with
    # nonfinite anomaly provenance (offender field/fiber/node); 0 = off
    # (see skellysim_tpu/params.py `flight_window` and
    # docs/observability.md "Flight recorder")
    flight_window: int = 0
    fiber_error_tol: float = 0.1
    seed: int = 130319
    implicit_motor_activation_delay: float = 0.0
    dynamic_instability: DynamicInstability = field(default_factory=DynamicInstability)
    periphery_binding: PeripheryBinding = field(default_factory=PeripheryBinding)
    periphery_interaction_flag: bool = False
    adaptive_timestep_flag: bool = True
    pair_evaluator: str = "TPU"
    fiber_type: str = "FiniteDifference"
    # TPU-specific extensions (no reference analogue; see runtime Params):
    # solver precision tier ("full"/"mixed"/"auto" — auto = mixed on
    # accelerators for f64 states, full elsewhere), Ewald/treecode
    # evaluator tolerances, pairwise tile, and the mixed solver's
    # refinement tile
    solver_precision: str = "auto"
    ewald_tol: float = 1e-6
    tree_tol: float = 1e-4
    # periodic boundary for the "spectral" evaluator: [] = free space,
    # [Lx, Ly, Lz] = triply periodic, [Lx, Ly] = doubly periodic slab
    # (x/y periodic, z free); validate() requires it for "spectral" and
    # rejects it for every other evaluator (docs/spectral.md)
    periodic_box: list = field(default_factory=list)
    # target relative accuracy of the spectral Ewald evaluator
    spectral_tol: float = 1e-6
    kernel_impl: str = "exact"
    refine_pair_impl: str = "auto"
    ewald_min_sources: int = 2048
    # coupled-solve preconditioner: "gs" (block Gauss-Seidel, shell-first
    # coupling correction) or "jacobi" (the reference's independent blocks)
    precond: str = "gs"


@dataclass
class Periphery:
    """Base periphery (use a shaped subclass)."""
    n_nodes: int = 6000
    precompute_file: str = "periphery_precompute.npz"

    def find_binding_site(self, fibers, ds_min):
        raise NotImplementedError

    def move_fibers_to_surface(self, fibers, ds_min, verbose=True,
                               rng=None) -> None:
        """Place fibers' minus ends uniformly on the surface pointing inward,
        rejecting sites closer than ds_min to prior minus ends."""
        rng = rng or np.random.default_rng()
        ends: list = []
        for i, fib in enumerate(fibers):
            x0, inward = self.find_binding_site_impl(ends, ds_min, rng)
            fib.fill_node_positions(x0, inward)
            ends.append(x0)
            if verbose:
                print(f"Inserted fiber {i} at {x0}")


@dataclass
class SphericalPeriphery(Periphery):
    shape: str = "sphere"
    radius: float = 6.0

    def find_binding_site_impl(self, minus_ends, ds_min, rng):
        while True:
            u0 = _random_unit_vector(rng)
            x0 = 0.99999999 * self.radius * u0
            if _min_sep_ok(x0, minus_ends, ds_min):
                return x0, -u0

    def find_binding_site(self, fibers, ds_min, rng=None):
        rng = rng or np.random.default_rng()
        ends = [np.asarray(f.x[0:3]) for f in fibers if len(f.x) >= 3]
        x0, inward = self.find_binding_site_impl(ends, ds_min, rng)
        return x0, -inward


@dataclass
class EllipsoidalPeriphery(Periphery):
    """(x/a)² + (y/b)² + (z/c)² = 1."""
    shape: str = "ellipsoid"
    a: float = 7.8
    b: float = 4.16
    c: float = 4.16

    def move_fibers_to_surface(self, fibers, ds_min, verbose=True, rng=None):
        rng = rng or np.random.default_rng()
        # sample uniform-by-area trial points slightly inside the surface
        a, b, c = self.a / 1.04, self.b / 1.04, self.c / 1.04

        def surf(t, u):
            return np.stack([a * np.cos(t) * np.sin(u),
                             b * np.sin(t) * np.sin(u),
                             c * np.cos(u)])

        n_trials = max(5 * len(fibers), 64)
        trials = param_tools.r_surface(n_trials, surf, 0, 2 * np.pi, 0, np.pi,
                                       rng=rng)[0].T
        ends: list = []
        i_trial = 0
        for i, fib in enumerate(fibers):
            while True:
                if i_trial >= n_trials:
                    raise RuntimeError(
                        "Unable to insert fibers; decrease density or ds_min")
                x0 = trials[i_trial]
                i_trial += 1
                if _min_sep_ok(x0, ends, ds_min):
                    break
            normal = np.array([x0[0] / self.a ** 2, x0[1] / self.b ** 2,
                               x0[2] / self.c ** 2])
            normal = -normal / np.linalg.norm(normal)
            fib.fill_node_positions(x0, normal)
            ends.append(x0)
            if verbose:
                print(f"Inserted fiber {i} at {x0}")


class EnvelopeConfig(dict):
    """Envelope table with attribute-style access (reference API parity:
    `config.periphery.envelope.n_nodes_target = ...` works like the
    reference's `Envelope` dataclass, `skelly_config.py:609-716`) while
    remaining a plain dict for TOML round-tripping and the precompute
    pipeline."""

    def __getattr__(self, key):
        try:
            return self[key]
        except KeyError:
            raise AttributeError(key) from None

    def __setattr__(self, key, value):
        self[key] = value


@dataclass
class RevolutionPeriphery(Periphery):
    """Surface of revolution of a height function h(x) around the x axis.

    `envelope` keys (reference `RevolutionPeriphery`, `skelly_config.py:609-716`):
    height (a one-line expression of x), lower_bound, upper_bound,
    n_nodes_target, plus free parameters referenced by the expression.
    Both dict-style (`envelope["height"]`) and attribute-style
    (`envelope.height`) access work.
    """
    shape: str = "surface_of_revolution"
    n_nodes: int = 0
    envelope: dict = field(default_factory=EnvelopeConfig)

    def __post_init__(self):
        if not isinstance(self.envelope, EnvelopeConfig):
            self.envelope = EnvelopeConfig(self.envelope)

    def move_fibers_to_surface(self, fibers, ds_min, verbose=True, rng=None):
        from ..periphery.shapes import Envelope
        rng = rng or np.random.default_rng()
        env = Envelope(self.envelope)
        lb, ub = self.envelope["lower_bound"], self.envelope["upper_bound"]

        # CDF of the area element h(x)·√(dx² + dh²) for uniform-by-area sampling
        xs = np.linspace(lb, ub, 1000)
        h = np.maximum(env.raw_height(xs), 0.0)
        slant = np.sqrt(np.diff(xs) ** 2 + np.diff(h) ** 2)
        dA = 0.5 * (h[1:] + h[:-1]) * slant
        cdf = np.concatenate([[0.0], np.cumsum(dA)])
        cdf /= cdf[-1]

        ends: list = []
        for i, fib in enumerate(fibers):
            while True:
                x_t = np.interp(rng.uniform(), cdf, xs)
                h_t = float(env.raw_height(x_t))
                theta = 2 * np.pi * rng.uniform()
                x0 = np.array([x_t, h_t * np.cos(theta), h_t * np.sin(theta)])
                if _min_sep_ok(x0, ends, ds_min):
                    break
            if x0[0] <= env.lower_bound:
                normal = np.array([1.0, 0.0, 0.0])
            elif x0[0] >= env.upper_bound:
                normal = np.array([-1.0, 0.0, 0.0])
            else:
                normal = np.array([float(env(x0[0]) * env.differentiate(x0[0])),
                                   -x0[1], -x0[2]])
                normal /= np.linalg.norm(normal)
            fib.fill_node_positions(x0, normal)
            ends.append(x0)
            if verbose:
                print(f"Inserted fiber {i} at {x0}")


@dataclass
class Body:
    """One rigid body (reference `Body`, `skelly_config.py:719-872`)."""
    n_nucleation_sites: int = 0
    position: List[float] = field(default_factory=_vec3)
    orientation: List[float] = field(default_factory=_quat_identity)
    shape: str = "sphere"
    radius: float = 1.0
    n_nodes: int = 600
    axis_length: List[float] = field(default_factory=_vec3)
    precompute_file: str = "body_precompute.npz"
    external_force_type: str = "Linear"
    external_force: List[float] = field(default_factory=_vec3)
    external_torque: List[float] = field(default_factory=_vec3)
    nucleation_sites: List[float] = field(default_factory=list)
    external_oscillation_force_amplitude: float = 0.0
    external_oscillation_force_frequency: float = 0.0
    external_oscillation_force_phase: float = 0.0

    def _require_sphere(self):
        if self.shape != "sphere":
            raise ValueError("fiber attachment only implemented for spherical bodies")

    def find_binding_site(self, fibers, ds_min, rng=None):
        self._require_sphere()
        rng = rng or np.random.default_rng()
        com = np.asarray(self.position)
        ends = [np.asarray(f.x[0:3]) for f in fibers if len(f.x) >= 3]
        while True:
            u0 = _random_unit_vector(rng)
            x0 = com + self.radius * u0
            if _min_sep_ok(x0, ends, ds_min):
                return x0, u0

    def generate_nucleation_sites(self, ds_min, verbose=True, rng=None) -> None:
        self._require_sphere()
        rng = rng or np.random.default_rng()
        com = np.asarray(self.position)
        sites: list = []
        for isite in range(self.n_nucleation_sites):
            while True:
                x0 = com + self.radius * _random_unit_vector(rng)
                if _min_sep_ok(x0, sites, ds_min):
                    sites.append(x0)
                    if verbose:
                        print(f"Inserting site {isite} at {x0}")
                    break
        self.nucleation_sites = np.asarray(sites).ravel().tolist()

    def move_fibers_to_surface(self, fibers, ds_min, verbose=True, rng=None):
        """Place fibers on the body surface pointing outward."""
        self._require_sphere()
        rng = rng or np.random.default_rng()
        com = np.asarray(self.position)
        ends: list = []
        for i, fib in enumerate(fibers):
            while True:
                u0 = _random_unit_vector(rng)
                x0 = com + self.radius * u0
                if _min_sep_ok(x0, ends, ds_min):
                    break
            fib.fill_node_positions(x0, u0)
            ends.append(x0)
            if verbose:
                print(f"Inserted fiber {i} at {x0}")


@dataclass
class Point:
    """Point force/torque source (reference `Point`, `skelly_config.py:875-894`)."""
    position: List[float] = field(default_factory=_vec3)
    force: List[float] = field(default_factory=_vec3)
    torque: List[float] = field(default_factory=_vec3)
    time_to_live: float = 0.0


@dataclass
class BackgroundSource:
    """Uniform + linear-shear background flow (reference `skelly_config.py:897-913`)."""
    components: List[int] = field(default_factory=_ivec3)
    scale_factor: List[float] = field(default_factory=_vec3)
    uniform: List[float] = field(default_factory=_vec3)


@dataclass
class SweepAxis:
    """One swept parameter: a dotted config path and its values.

    ``key`` addresses the BASE config (`skelly_config.toml`) with dots and
    list indices, e.g. ``"fibers.0.length"``, ``"bodies.0.external_force"``,
    ``"background.uniform"``. Member configs take the cartesian product over
    all axes. Only values that land in simulation STATE are sweepable —
    swept members share one compiled program, so a key that changes the
    static runtime Params (eta, tolerances, evaluator choices, ...) is
    rejected at expansion; `params.t_final` and `params.seed` are the two
    params exceptions (per-member end time / RNG stream).
    """
    key: str = ""
    values: List = field(default_factory=list)


@dataclass
class EnsembleSweep:
    """`[ensemble]` table of a sweep-spec TOML (`python -m
    skellysim_tpu.ensemble --sweep-file=...`; see docs/ensemble.md).

    A sweep spec is its own small TOML file next to (or pointing at) a base
    run config; members = ``replicas`` copies of every point in the sweep
    axes' cartesian product, each with a deterministic per-member RNG
    (`SimRNG.member(i)`) so replicas are reproducible independent of
    scheduling order.
    """
    #: base run config, resolved relative to the sweep-spec file
    base_config: str = "skelly_config.toml"
    #: stochastic replicas per sweep point
    replicas: int = 1
    #: compiled lane count B (the continuous-batching scheduler's batch)
    batch: int = 8
    #: base seed for per-member RNG streams; -1 = the base config's
    #: params.seed
    seed: int = -1
    #: per-member end time; -1.0 = the base config's params.t_final
    t_final: float = -1.0
    #: batched execution plan: "vmap" (throughput) or "unroll" (bit-reproducible
    #: lanes; see docs/ensemble.md)
    batch_impl: str = "vmap"
    sweep: List[SweepAxis] = field(default_factory=list)


def normalized_member_params(params: "Params") -> "Params":
    """Params with the per-member knobs zeroed — two configs whose
    normalized params are equal can share ONE compiled program.

    seed and t_final are the only params handled outside the trace (the
    member RNG stream and the masked stepper's per-lane horizon). The ONE
    definition of that contract: the ensemble sweep CLI's
    members-share-a-program check and skelly-serve's admission gate both
    call this — a new per-member knob lands in both by editing here.
    """
    return dataclasses.replace(params, seed=0, t_final=0.0)


@dataclass
class RuntimeConfig:
    """`[runtime]` table: host-side execution policy (skelly-bucket +
    compile cache), shared by every CLI front door (run, ensemble, serve,
    listener — docs/performance.md "Warm programs and capacity buckets").

    These knobs never enter the traced program: they decide which padded
    capacity bucket a scene lands in (`system.buckets.BucketPolicy`) and
    where compiled executables persist across processes.
    """

    #: persistent XLA compilation cache: "auto" (default) = the package
    #: root's `.jax_cache` (shared with bench.py and the obs cost gate),
    #: "off" = disabled, anything else = an explicit directory. CLIs also
    #: take --jax-cache DIR / --no-jax-cache, which override this key.
    jax_cache: str = "auto"
    #: fiber-capacity ladder (ascending ints): scenes pad to the smallest
    #: rung with inert masked fibers so differently-sized scenes share one
    #: compiled program. [] = identity (no padding, the default); [-1] =
    #: the built-in geometric x2 ladder (buckets.GEOMETRIC_FIBER_LADDER).
    bucket_ladder: List[int] = field(default_factory=list)
    #: nodes-per-fiber ladder (subset of the valid fiber resolutions
    #: 8/16/24/32/48/64/96/128): scenes below a rung pad with masked node
    #: rows whose matrices ride the state as data, so different live
    #: resolutions share one program. [] = identity (no node padding).
    node_ladder: List[int] = field(default_factory=list)
    #: shell quadrature ladder: shells pad to the smallest rung with
    #: masked quadrature rows (identity-padded operators). [] = off.
    #: Incompatible with pair_evaluator = "ewald"/"tree".
    shell_ladder: List[int] = field(default_factory=list)
    #: spectral-evaluator FFT grid-dimension ladder (ascending ints): a
    #: drifting scene's per-axis grid requirement snaps UP onto a rung so
    #: the SpectralPlan — the jit key — is stable under drift. [] = the
    #: built-in 2^a 3^b ladder (ops.spectral.GRID_RUNGS). Rungs should be
    #: FFT-friendly sizes (2^a 3^b 5^c).
    grid_ladder: List[int] = field(default_factory=list)


def load_runtime_config(path_or_data) -> RuntimeConfig:
    """`[runtime]` table of a config TOML (path or parsed dict) ->
    RuntimeConfig; defaults when absent, unknown keys rejected like
    `[serve]` (a typo'd ladder silently running identity padding would
    quietly forfeit every warm-program hit)."""
    data = (toml_io.load(path_or_data) if isinstance(path_or_data, str)
            else (path_or_data or {}))
    table = data.get("runtime", {})
    known = {f.name for f in dataclasses.fields(RuntimeConfig)}
    unknown = set(table) - known
    if unknown:
        raise ValueError(f"unknown [runtime] keys {sorted(unknown)}; "
                         f"valid keys: {sorted(known)}")
    cfg = RuntimeConfig(**table)
    for name in ("bucket_ladder", "node_ladder", "shell_ladder",
                 "grid_ladder"):
        lad = getattr(cfg, name)
        if name == "bucket_ladder" and list(lad) == [-1]:
            continue  # the "geometric" spelling
        if any(int(v) < 1 for v in lad):
            raise ValueError(f"[runtime] {name} entries must be >= 1 "
                             "(or bucket_ladder = [-1] for the geometric "
                             "ladder)")
        if list(lad) != sorted(set(int(v) for v in lad)):
            raise ValueError(f"[runtime] {name} must be strictly ascending")
    return cfg


@dataclass
class ServeConfig:
    """`[serve]` table of a server config TOML (`python -m
    skellysim_tpu.serve`; see docs/serving.md).

    Lives in the SERVER's run config file alongside the usual tables: the
    config's fibers/params define the warm compiled program every tenant
    must match, and `[serve]` sizes the service around it. Each capacity
    bucket is one compiled ensemble program whose lanes hold tenants with
    fiber counts up to that capacity (smaller scenes are padded with inert
    masked fibers — the ensemble masked-lane trick applied to admission).
    """
    #: bind address for the TCP service
    host: str = "127.0.0.1"
    #: listen port; 0 = ephemeral (pair with the CLI's --port-file)
    port: int = 0
    #: padded fiber capacities, one warm compiled program (bucket) each;
    #: empty = derived from the bucket policy (`[runtime] bucket_ladder`
    #: rungs starting at the base config's fiber count, `bucket_count`
    #: rungs) — this list remains the manual override
    bucket_capacities: List[int] = field(default_factory=list)
    #: number of policy-ladder rungs to derive buckets from when
    #: `bucket_capacities` is empty (starting at the base scene's rung);
    #: 1 = a single bucket at the base scene's own rung (the default)
    bucket_count: int = 1
    #: concurrent tenant slots (compiled ensemble lanes) per bucket
    max_lanes: int = 4
    #: admission-queue bound per bucket; a submit beyond it is REJECTED
    #: (admission control: shed load instead of growing an unbounded queue)
    queue_depth: int = 16
    #: batched execution plan for the lanes: "vmap" (throughput) or
    #: "unroll" (bit-reproducible lanes; see docs/ensemble.md)
    batch_impl: str = "vmap"
    #: per-send socket timeout: a client that stops reading its responses
    #: is dropped (and its tenants evicted) instead of freezing the
    #: single-threaded event loop on a full TCP window
    send_timeout_s: float = 30.0
    #: terminal tenant-record retention (seconds): finished / evicted /
    #: cancelled records (and their final-state snapshots) expire this long
    #: after retirement, bounding server memory under sustained traffic.
    #: 0 disables expiry (the pre-TTL behavior: records live until
    #: shutdown). An expired tenant answers "unknown tenant" — clients
    #: must fetch snapshots/frames within the TTL.
    record_ttl_s: float = 0.0
    #: crash-safe write-ahead tenant journal (serve.journal,
    #: docs/robustness.md): append-only trajectory-v1 snapshots on
    #: admit / evict / every `journal_every` rounds. A restarted server
    #: pointed at the same path re-admits every live tenant from the
    #: journal with at most `journal_every` rounds of replay. Empty =
    #: journaling off (the pre-guard behavior: a killed server loses its
    #: tenants).
    journal_path: str = ""
    #: checkpoint cadence (batched rounds) for live lanes when journaling;
    #: the bound on replay after a crash. Must be >= 1 when journaling.
    journal_every: int = 8
    #: allow `chaos` requests (guard.chaos fault injection — the CI chaos
    #: smoke and the fault-injection tests). NEVER enable in production:
    #: a chaos request deliberately poisons tenant state.
    chaos_enabled: bool = False
    #: per-connection frame-size bound (bytes): a header claiming more
    #: answers a structured error and the connection survives
    #: (protocol.FrameDecoder skip mode); the default matches
    #: protocol.MAX_FRAME_BYTES
    max_frame_bytes: int = 1 << 31


def load_serve_config(path: str) -> ServeConfig:
    """`[serve]` table of a config TOML -> ServeConfig (defaults when the
    table is absent; unknown keys rejected — a typo'd knob silently running
    defaults would mis-size a production service)."""
    table = toml_io.load(path).get("serve", {})
    known = {f.name for f in dataclasses.fields(ServeConfig)}
    unknown = set(table) - known
    if unknown:
        raise ValueError(f"{path}: unknown [serve] keys {sorted(unknown)}; "
                         f"valid keys: {sorted(known)}")
    cfg = ServeConfig(**table)
    if cfg.max_lanes < 1:
        raise ValueError(f"{path}: [serve] max_lanes must be >= 1")
    if cfg.queue_depth < 0:
        raise ValueError(f"{path}: [serve] queue_depth must be >= 0")
    if cfg.batch_impl not in ("vmap", "unroll"):
        raise ValueError(f"{path}: unknown [serve] batch_impl "
                         f"{cfg.batch_impl!r}; use 'vmap' or 'unroll'")
    if any(c < 1 for c in cfg.bucket_capacities):
        raise ValueError(f"{path}: [serve] bucket_capacities must be >= 1")
    if cfg.bucket_count < 1:
        raise ValueError(f"{path}: [serve] bucket_count must be >= 1")
    if cfg.send_timeout_s <= 0:
        raise ValueError(f"{path}: [serve] send_timeout_s must be > 0")
    if cfg.journal_path and cfg.journal_every < 1:
        raise ValueError(f"{path}: [serve] journal_every must be >= 1 "
                         "when journal_path is set")
    if cfg.max_frame_bytes < 1 << 16:
        raise ValueError(f"{path}: [serve] max_frame_bytes must be >= 64 KiB "
                         "(a single status response must fit)")
    return cfg


@dataclass
class Config:
    """Free-space config (no bounding volume)."""
    params: Params = field(default_factory=Params)
    bodies: List[Body] = field(default_factory=list)
    fibers: List[Fiber] = field(default_factory=list)
    point_sources: List[Point] = field(default_factory=list)
    background: BackgroundSource = field(default_factory=BackgroundSource)

    def validate(self) -> list[str]:
        problems = _validate(self)
        problems += _validate_periodic(self)
        for j, b in enumerate(self.bodies):
            if getattr(b, "shape", None) == "deformable":
                # fail at schema-validation time with the stub named, not
                # deep in the builder's make_group: the reference declares
                # DeformableBody but never implements it
                problems.append(
                    f"bodies[{j}].shape: 'deformable' is declared but "
                    "unimplemented (reference parity stub skellysim_tpu/"
                    "bodies/deformable.py, mirroring body_deformable.cpp:"
                    "13-41 whose methods are empty and whose flow throws); "
                    "use shape = 'sphere' or 'ellipsoid'")
        return problems

    def save(self, filename: str = "skelly_config.toml") -> None:
        problems = self.validate()
        if problems:
            raise ValueError("invalid config:\n  " + "\n  ".join(problems))
        toml_io.dump(unpack(self), filename)


@dataclass
class ConfigSpherical(Config):
    periphery: SphericalPeriphery = field(default_factory=SphericalPeriphery)


@dataclass
class ConfigEllipsoidal(Config):
    periphery: EllipsoidalPeriphery = field(default_factory=EllipsoidalPeriphery)


@dataclass
class ConfigRevolution(Config):
    periphery: RevolutionPeriphery = field(default_factory=RevolutionPeriphery)


# ---------------------------------------------------------------------------
# validation / (de)serialization

def _validate_periodic(cfg) -> list[str]:
    """Periodic-box / evaluator pairing rules (docs/spectral.md).

    The box shapes the spectral evaluator's FFT grid: [Lx, Ly, Lz] =
    triply periodic, [Lx, Ly] = doubly periodic slab, [] = free space.
    Only "spectral" can honor periodic images, so the pairing is validated
    both ways — a periodic box under a dense evaluator would silently
    simulate free space.
    """
    problems: list[str] = []
    box = cfg.params.periodic_box
    if len(box) not in (0, 2, 3):
        problems.append(
            "params.periodic_box: length must be 2 (doubly periodic slab "
            f"[Lx, Ly]) or 3 (triply periodic [Lx, Ly, Lz]), got {len(box)}")
    for j, L in enumerate(box):
        if isinstance(L, bool) or not isinstance(L, (int, float)) or L <= 0:
            problems.append(
                f"params.periodic_box[{j}]: must be a positive length, "
                f"got {L!r}")
    ev = _EVALUATOR_NAMES.get(str(cfg.params.pair_evaluator).strip().lower())
    if ev == "spectral" and not box:
        problems.append(
            "params.pair_evaluator: 'spectral' is the periodic/confined "
            "evaluator and needs params.periodic_box ([Lx, Ly, Lz] or "
            "[Lx, Ly]); for free space use 'ewald' or 'tree'")
    if ev is not None and ev != "spectral" and box:
        problems.append(
            f"params.periodic_box: set, but pair_evaluator {ev!r} sums "
            "free-space kernels and would ignore the periodic images; "
            "use pair_evaluator = 'spectral'")
    return problems


def _validate(obj, prefix: str = "") -> list[str]:
    """Type-check every field against its annotation; flag unknown attributes
    (reference `check_type` + `_check_invalid_attributes`,
    `skelly_config.py:202-228,958-973`)."""
    problems: list[str] = []
    known = {f.name for f in fields(obj)}
    for name in vars(obj):
        if name not in known:
            problems.append(f"{prefix}{name}: unknown attribute")
    for f in fields(obj):
        v = getattr(obj, f.name)
        where = f"{prefix}{f.name}"
        if is_dataclass(v):
            problems += _validate(v, where + ".")
        elif isinstance(v, list):
            for j, item in enumerate(v):
                if is_dataclass(item):
                    problems += _validate(item, f"{where}[{j}].")
                elif isinstance(item, (np.floating, np.integer)):
                    problems.append(f"{where}[{j}]: numpy scalar; use float/int")
        elif isinstance(v, (np.floating, np.integer, np.ndarray)):
            problems.append(f"{where}: numpy type; use plain float/int/list")
        elif isinstance(v, dict):
            for k, item in v.items():
                # numpy scalars are unpacked to plain types at save; flag
                # anything else non-TOML-serializable
                if not isinstance(item, (bool, float, int, str, list, dict,
                                         np.floating, np.integer, np.ndarray)):
                    problems.append(
                        f"{where}[{k!r}]: unsupported type {type(item).__name__}")
        elif isinstance(v, (bool, float, int, str)):
            pass
        else:
            problems.append(f"{where}: unsupported type {type(v).__name__}")
    return problems


def unpack(obj) -> dict:
    """Dataclass tree → plain dict suitable for TOML (drops empty lists the
    runtime treats as absent? no — keeps everything; the TOML is the contract)."""
    if is_dataclass(obj):
        return {f.name: unpack(getattr(obj, f.name)) for f in fields(obj)}
    if isinstance(obj, dict):
        return {k: unpack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [unpack(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, (np.integer,)):
        return int(obj)
    return obj


def _from_dict(cls, data: dict):
    kwargs = {}
    known = {f.name: f for f in fields(cls)}
    for k, v in data.items():
        if k not in known:
            continue  # forward compatibility: ignore unknown keys on load
        f = known[k]
        ann = str(f.type)
        if "DynamicInstability" in ann:
            v = _from_dict(DynamicInstability, v)
        elif "PeripheryBinding" in ann:
            v = _from_dict(PeripheryBinding, v)
        kwargs[k] = v
    return cls(**kwargs)


def load_config(path: str):
    """TOML file → Config (shaped subclass chosen by periphery.shape)."""
    return config_from_data(toml_io.load(path))


def config_from_data(data: dict):
    """Parsed TOML dict → Config — the path-free half of `load_config`,
    shared with skelly-serve's submit path (tenant configs arrive as TOML
    TEXT over the wire, never touching the server's filesystem)."""
    peri = data.get("periphery")
    if peri is None:
        cfg = Config()
    else:
        shape = peri.get("shape", "sphere")
        cls, pcls = {
            "sphere": (ConfigSpherical, SphericalPeriphery),
            "ellipsoid": (ConfigEllipsoidal, EllipsoidalPeriphery),
            "surface_of_revolution": (ConfigRevolution, RevolutionPeriphery),
        }[shape]
        cfg = cls()
        cfg.periphery = _from_dict(pcls, peri)
    cfg.params = _from_dict(Params, data.get("params", {}))
    cfg.fibers = [_from_dict(Fiber, d) for d in data.get("fibers", [])]
    cfg.bodies = [_from_dict(Body, d) for d in data.get("bodies", [])]
    cfg.point_sources = [_from_dict(Point, d) for d in data.get("point_sources", [])]
    cfg.background = _from_dict(BackgroundSource, data.get("background", {}))
    return cfg


# one alias table shared with the listener protocol — see
# ops.evaluator.EVALUATOR_ALIASES for the name semantics
from skellysim_tpu.ops.evaluator import EVALUATOR_ALIASES as _EVALUATOR_NAMES


def _runtime_evaluator(name: str) -> str:
    try:
        return _EVALUATOR_NAMES[name.strip().lower()]
    except KeyError:
        raise ValueError(
            f"unknown pair_evaluator {name!r}; valid names: "
            + ", ".join(sorted(_EVALUATOR_NAMES))) from None


def to_runtime_params(p: Params) -> runtime_params.Params:
    """Schema-level Params → runtime (jit-static) Params."""
    return runtime_params.Params(
        eta=p.eta,
        dt_initial=p.dt_initial,
        dt_min=p.dt_min,
        dt_max=p.dt_max,
        adaptive_timestep_flag=p.adaptive_timestep_flag,
        dt_write=p.dt_write,
        t_final=p.t_final,
        gmres_tol=p.gmres_tol,
        gmres_block_s=p.gmres_block_s,
        guard_dt_halvings=p.guard_dt_halvings,
        guard_block_fallback=p.guard_block_fallback,
        guard_f64_fallback=p.guard_f64_fallback,
        flight_window=p.flight_window,
        fiber_error_tol=p.fiber_error_tol,
        seed=p.seed,
        implicit_motor_activation_delay=p.implicit_motor_activation_delay,
        periphery_interaction_flag=p.periphery_interaction_flag,
        # reference evaluator names: "FMM" (the reference's fast evaluator)
        # maps to the spectral-Ewald fast path, "tree" to the barycentric
        # treecode, "ring" opts into the collective-permute ring kernels,
        # CPU/GPU/TPU map to dense direct; anything else is a typo the user
        # must see, not a silent fallback
        pair_evaluator=_runtime_evaluator(p.pair_evaluator),
        solver_precision=p.solver_precision,
        ewald_tol=p.ewald_tol,
        tree_tol=p.tree_tol,
        periodic_box=tuple(float(L) for L in p.periodic_box),
        spectral_tol=p.spectral_tol,
        ewald_min_sources=p.ewald_min_sources,
        kernel_impl=p.kernel_impl,
        refine_pair_impl=p.refine_pair_impl,
        precond=p.precond,
        dynamic_instability=runtime_params.DynamicInstability(
            **dataclasses.asdict(p.dynamic_instability)),
        periphery_binding=runtime_params.PeripheryBinding(
            **dataclasses.asdict(p.periphery_binding)),
    )
