"""Uniform random sampling on parametric curves and surfaces.

Capability mirror of the reference's vendored `param_tools`
(`/root/reference/src/skelly_sim/param_tools.py`: `r_arc`, `arc_length`,
`r_surface`, `surface_area`) — sampling uniformly *by arc length / surface
area* via CDF inversion — re-implemented with vectorized numpy (midpoint field
evaluation + `np.interp` inversion instead of scipy interp1d/interp2d/brentq).
Used by the config generators to place fibers uniformly on periphery surfaces.
"""

from __future__ import annotations

import numpy as np


def arc_cumulative(func, t0: float, t1: float, precision: int = 225):
    """Cumulative arc length of the curve func(t) -> (3, n) on [t0, t1]."""
    t = np.linspace(t0, t1, precision)
    coords = np.asarray(func(t), dtype=float)
    ds = np.linalg.norm(np.diff(coords, axis=-1), axis=0)
    return t, np.concatenate([[0.0], np.cumsum(ds)])


def arc_length(func, t0: float, t1: float, precision: int = 225) -> float:
    """Total arc length of func(t) on [t0, t1]."""
    return arc_cumulative(func, t0, t1, precision)[1][-1]


def r_arc(n: int, func, t0: float, t1: float, precision: int = 225,
          rng: np.random.Generator | None = None):
    """Sample n points uniformly by arc length on the curve func.

    Returns (coords[3, n], t[n], s[n]).
    """
    rng = rng or np.random.default_rng()
    t, cum_s = arc_cumulative(func, t0, t1, precision)
    s = rng.uniform(0.0, cum_s[-1], size=n)
    ts = np.interp(s, cum_s, t)
    return np.asarray(func(ts), dtype=float), ts, s


def _area_elements(func, t0, t1, u0, u1, t_precision, u_precision):
    """Midpoint-rule area elements |x_t × x_u| dt du on a (t, u) grid."""
    t_edges = np.linspace(t0, t1, t_precision + 1)
    u_edges = np.linspace(u0, u1, u_precision + 1)
    tm = 0.5 * (t_edges[:-1] + t_edges[1:])
    um = 0.5 * (u_edges[:-1] + u_edges[1:])
    dt = t_edges[1] - t_edges[0]
    du = u_edges[1] - u_edges[0]
    T, U = np.meshgrid(tm, um, indexing="ij")
    eps_t = 1e-6 * (t1 - t0)
    eps_u = 1e-6 * (u1 - u0)
    x_t = (np.asarray(func(T + eps_t, U)) - np.asarray(func(T - eps_t, U))) / (2 * eps_t)
    x_u = (np.asarray(func(T, U + eps_u)) - np.asarray(func(T, U - eps_u))) / (2 * eps_u)
    dA = np.linalg.norm(np.cross(x_t, x_u, axis=0), axis=0) * dt * du
    return tm, um, dA


def surface_area(func, t0, t1, u0, u1, t_precision: int = 25,
                 u_precision: int = 25) -> float:
    """Total area of the parametric surface func(t, u) -> (3, ...)."""
    return _area_elements(func, t0, t1, u0, u1, t_precision, u_precision)[2].sum()


def r_surface(n: int, func, t0, t1, u0, u1, t_precision: int = 100,
              u_precision: int = 100, rng: np.random.Generator | None = None):
    """Sample n points uniformly by area on the surface func(t, u) -> (3, ...).

    Returns (coords[3, n], t[n], u[n]) — same leading contract as the
    reference's `param_tools.r_surface` (coords first).
    """
    rng = rng or np.random.default_rng()
    tm, um, dA = _area_elements(func, t0, t1, u0, u1, t_precision, u_precision)
    p = dA.ravel() / dA.sum()
    cells = rng.choice(p.size, size=n, p=p)
    it, iu = np.unravel_index(cells, dA.shape)
    # jitter uniformly inside each chosen cell
    dt = (t1 - t0) / t_precision
    du = (u1 - u0) / u_precision
    ts = tm[it] + rng.uniform(-0.5, 0.5, n) * dt
    us = um[iu] + rng.uniform(-0.5, 0.5, n) * du
    return np.asarray(func(ts, us), dtype=float), ts, us
