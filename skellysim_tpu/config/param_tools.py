"""Uniform random sampling on parametric curves and surfaces.

Capability mirror of the reference's vendored `param_tools`
(`/root/reference/src/skelly_sim/param_tools.py`: `arc_cumulator`, `r_arc`,
`r_arc_from_data`, `arc_length`, `sample_to_arc`, `surface_cumulator`,
`r_surface`, `r_surface_from_data`, `surface_area`) — sampling uniformly *by
arc length / surface area* via CDF inversion — re-implemented with vectorized
numpy (midpoint field evaluation + `np.interp` inversion instead of scipy
interp1d/interp2d). Used by the config generators to place fibers uniformly on
periphery surfaces.
"""

from __future__ import annotations

import numpy as np


def arc_cumulative(func, t0: float, t1: float, precision: int = 225):
    """Cumulative arc length of the curve func(t) -> (3, n) on [t0, t1]."""
    t = np.linspace(t0, t1, precision)
    coords = np.asarray(func(t), dtype=float)
    ds = np.linalg.norm(np.diff(coords, axis=-1), axis=0)
    return t, np.concatenate([[0.0], np.cumsum(ds)])


def arc_length(func, t0: float, t1: float, precision: int = 225) -> float:
    """Total arc length of func(t) on [t0, t1]."""
    return arc_cumulative(func, t0, t1, precision)[1][-1]


def r_arc(n: int, func, t0: float, t1: float, precision: int = 225,
          rng: np.random.Generator | None = None):
    """Sample n points uniformly by arc length on the curve func.

    Returns (coords[3, n], t[n], s[n]).
    """
    rng = rng or np.random.default_rng()
    t, cum_s = arc_cumulative(func, t0, t1, precision)
    s = rng.uniform(0.0, cum_s[-1], size=n)
    ts = np.interp(s, cum_s, t)
    return np.asarray(func(ts), dtype=float), ts, s


def _area_elements(func, t0, t1, u0, u1, t_precision, u_precision):
    """Midpoint-rule area elements |x_t × x_u| dt du on a (t, u) grid."""
    t_edges = np.linspace(t0, t1, t_precision + 1)
    u_edges = np.linspace(u0, u1, u_precision + 1)
    tm = 0.5 * (t_edges[:-1] + t_edges[1:])
    um = 0.5 * (u_edges[:-1] + u_edges[1:])
    dt = t_edges[1] - t_edges[0]
    du = u_edges[1] - u_edges[0]
    T, U = np.meshgrid(tm, um, indexing="ij")
    eps_t = 1e-6 * (t1 - t0)
    eps_u = 1e-6 * (u1 - u0)
    x_t = (np.asarray(func(T + eps_t, U)) - np.asarray(func(T - eps_t, U))) / (2 * eps_t)
    x_u = (np.asarray(func(T, U + eps_u)) - np.asarray(func(T, U - eps_u))) / (2 * eps_u)
    dA = np.linalg.norm(np.cross(x_t, x_u, axis=0), axis=0) * dt * du
    return tm, um, dA


def surface_area(func, t0, t1, u0, u1, t_precision: int = 25,
                 u_precision: int = 25) -> float:
    """Total area of the parametric surface func(t, u) -> (3, ...)."""
    return _area_elements(func, t0, t1, u0, u1, t_precision, u_precision)[2].sum()


def arc_cumulator(t, coords):
    """Cumulative arc length from sampled curve data (`param_tools.py:10-38`).

    ``coords`` is [d, n] positions at sorted parameters ``t`` (or None for a
    uniform [0, 1] grid). Returns (t, cum_s).
    """
    coords = np.asarray(coords, dtype=float)
    if t is None:
        t = np.linspace(0.0, 1.0, coords.shape[-1])
    t = np.asarray(t, dtype=float)
    if t.shape != coords.shape[1:]:
        raise ValueError("need same number of parameters as coordinates")
    ds = np.linalg.norm(np.diff(coords), axis=0)
    return t, np.concatenate([[0.0], np.cumsum(ds)])


def r_arc_from_data(n: int, t, coords, interp: bool = True,
                    rng: np.random.Generator | None = None):
    """Sample n points uniformly by arc length from curve *data*
    (`param_tools.py:41-123`). Returns (coords[d, n] if interp, t[n], s[n])."""
    rng = rng or np.random.default_rng()
    coords = np.asarray(coords, dtype=float)
    t, cum_s = arc_cumulator(t, coords)
    rand_s = rng.uniform(0.0, cum_s[-1], size=n)
    rand_t = np.interp(rand_s, cum_s, t)
    if not interp:
        return rand_t, rand_s
    rand_coords = np.stack([np.interp(rand_t, t, coords[i])
                            for i in range(coords.shape[0])])
    return rand_coords, rand_t, rand_s


def sample_to_arc(sample, func, t0: float = 0.0, precision: int = 225,
                  ub: float = 1e11):
    """Map arbitrary arc-length samples to points on the curve ``func``
    (`param_tools.py:154-234`): arc length 0 lands at parameter ``t0``,
    negative arc lengths map to parameters below it.

    Returns (sample_x [d, n], sample_t [n]).
    """
    sample = np.asarray(sample, dtype=float)
    if t0 != 0.0:
        sample = sample + arc_length(func, 0.0, t0, precision) * np.sign(t0)

    neg = sample < 0.0
    sample_t = np.empty_like(sample)

    max_pts = 1 << 22

    def converged_cum(t_lim, sign):
        """(grid, cum): arc length on [0, sign*t_lim], grid refined until the
        total converges (a fixed point count loses accuracy as t_lim grows)."""
        n = precision
        grid = np.linspace(0.0, sign * t_lim, n)
        _, cum = arc_cumulator(grid, np.atleast_2d(
            np.asarray(func(grid), dtype=float)))
        while n < max_pts:
            n = 2 * n
            grid2 = np.linspace(0.0, sign * t_lim, n)
            _, cum2 = arc_cumulator(grid2, np.atleast_2d(
                np.asarray(func(grid2), dtype=float)))
            done = abs(cum2[-1] - cum[-1]) <= 1e-9 * max(cum2[-1], 1e-300)
            grid, cum = grid2, cum2
            if done:
                break
        return grid, cum

    def one_sided(s_abs, sign):
        """Invert |arc length| -> t on one side of t=0."""
        s_max = s_abs.max()
        # grow the parameter range until the cumulative arc length covers
        # s_max (chord-based bracketing as in the reference fails on closed
        # curves, whose chord is bounded by the diameter)
        t_lim = max(s_max, 1e-6)
        while True:
            grid, cum = converged_cum(t_lim, sign)
            if cum[-1] >= s_max:
                return sign * np.interp(s_abs, cum, sign * grid)
            if t_lim >= ub:
                raise ValueError(f"curve does not reach arc length {s_max} "
                                 f"within parameter {ub}")
            t_lim = min(2.0 * t_lim, ub)

    if neg.any():
        sample_t[neg] = one_sided(np.abs(sample[neg]), -1.0)
    if (~neg).any():
        sample_t[~neg] = one_sided(sample[~neg], 1.0)
    return np.asarray(func(sample_t), dtype=float), sample_t


def surface_cumulator(t, u, coords):
    """Marginal cumulative surface areas from surface *data*
    (`param_tools.py:237-287`).

    ``coords`` is [d, nu, nt]; returns (t, u, cum_S_t [nt], cum_S_u [nu]) —
    the cumulative area marginalized over the other parameter.
    """
    coords = np.asarray(coords, dtype=float)
    if t is None:
        t, _ = np.meshgrid(np.linspace(0, 1, coords.shape[-1]),
                           np.linspace(0, 1, coords.shape[-2]))
    if u is None:
        _, u = np.meshgrid(np.linspace(0, 1, coords.shape[-1]),
                           np.linspace(0, 1, coords.shape[-2]))
    t = np.asarray(t, dtype=float)
    u = np.asarray(u, dtype=float)
    if not (t.shape == u.shape == coords.shape[1:]):
        raise ValueError("need same number of parameters as coordinates")

    # parallelogram areas, zero-padded on the leading edge so tiny cumulative
    # values still interpolate (`param_tools.py:274-283`)
    delta_t = np.zeros_like(coords)
    delta_u = np.zeros_like(coords)
    delta_t[:, :, 1:] = np.diff(coords, axis=2)
    delta_u[:, 1:, :] = np.diff(coords, axis=1)
    dS = np.linalg.norm(np.cross(delta_t, delta_u, axisa=0, axisb=0), axis=2)
    return t, u, np.cumsum(dS.sum(axis=0)), np.cumsum(dS.sum(axis=1))


def r_surface_from_data(n: int, t, u, coords, interp: bool = True,
                        rng: np.random.Generator | None = None):
    """Sample n points approximately uniformly by area from surface *data*
    via the marginal CDFs (`param_tools.py:290-394`).

    Returns (coords[d, n] if interp, t[n], u[n], S_t[n], S_u[n]).
    """
    rng = rng or np.random.default_rng()
    coords = np.asarray(coords, dtype=float)
    t, u, cum_S_t, cum_S_u = surface_cumulator(t, u, coords)

    rand_S_t = rng.random(n) * cum_S_t[-1]
    rand_S_u = rng.random(n) * cum_S_u[-1]
    rand_t = np.interp(rand_S_t, cum_S_t, t[0, :])
    rand_u = np.interp(rand_S_u, cum_S_u, u[:, 0])
    if not interp:
        return rand_t, rand_u, rand_S_t, rand_S_u

    # bilinear interpolation of each coordinate on the (u, t) grid
    tg, ug = t[0, :], u[:, 0]
    it = np.clip(np.searchsorted(tg, rand_t) - 1, 0, len(tg) - 2)
    iu = np.clip(np.searchsorted(ug, rand_u) - 1, 0, len(ug) - 2)
    wt = (rand_t - tg[it]) / (tg[it + 1] - tg[it])
    wu = (rand_u - ug[iu]) / (ug[iu + 1] - ug[iu])
    c00 = coords[:, iu, it]
    c01 = coords[:, iu, it + 1]
    c10 = coords[:, iu + 1, it]
    c11 = coords[:, iu + 1, it + 1]
    rand_coords = ((1 - wu) * ((1 - wt) * c00 + wt * c01)
                   + wu * ((1 - wt) * c10 + wt * c11))
    return rand_coords, rand_t, rand_u, rand_S_t, rand_S_u


def r_surface(n: int, func, t0, t1, u0, u1, t_precision: int = 100,
              u_precision: int = 100, rng: np.random.Generator | None = None):
    """Sample n points uniformly by area on the surface func(t, u) -> (3, ...).

    Returns (coords[3, n], t[n], u[n]) — same leading contract as the
    reference's `param_tools.r_surface` (coords first).
    """
    rng = rng or np.random.default_rng()
    tm, um, dA = _area_elements(func, t0, t1, u0, u1, t_precision, u_precision)
    p = dA.ravel() / dA.sum()
    cells = rng.choice(p.size, size=n, p=p)
    it, iu = np.unravel_index(cells, dA.shape)
    # jitter uniformly inside each chosen cell
    dt = (t1 - t0) / t_precision
    du = (u1 - u0) / u_precision
    ts = tm[it] + rng.uniform(-0.5, 0.5, n) * dt
    us = um[iu] + rng.uniform(-0.5, 0.5, n) * du
    return np.asarray(func(ts, us), dtype=float), ts, us
