"""Sweep-spec TOML -> expanded member plans (the ensemble work queue's source).

`load_sweep` reads the `[ensemble]` table (+ `[[ensemble.sweep]]` axes) into
the `schema.EnsembleSweep` dataclass; `expand_members` takes the cartesian
product of the sweep axes times ``replicas`` and yields one `MemberPlan` per
member: a member id, the dotted config overrides for that sweep point, and
the member's seed/t_final. `apply_overrides` materializes a member's Config
from the base.

Overrides are restricted to values that land in simulation STATE (fiber
geometry/stiffness, body positions/forces, background flow, point sources):
all members of one ensemble run through ONE compiled program, so a key that
would change the static runtime `Params` is rejected here — with the two
exceptions the scheduler handles outside the trace (`params.t_final` is a
per-member array, `params.seed` selects the member RNG stream).
"""

from __future__ import annotations

import copy
import dataclasses
import itertools
import os
from typing import List

from . import toml_io
from .schema import Config, EnsembleSweep, SweepAxis, load_config


@dataclasses.dataclass
class MemberPlan:
    """One expanded member: overrides to apply to the base config + the
    per-member knobs the scheduler handles outside the compiled step."""

    member_id: str
    index: int               # global member index (RNG stream selector)
    overrides: dict          # dotted key -> value for this sweep point
    seed: int
    t_final: float           # <= 0 means "use base params.t_final"


def load_sweep(path: str) -> EnsembleSweep:
    """Sweep-spec TOML file -> EnsembleSweep (unknown keys rejected — a
    typo'd sweep key silently running the base config N times would burn a
    whole sweep's compute)."""
    data = toml_io.load(path)
    table = data.get("ensemble")
    if table is None:
        raise ValueError(f"{path}: missing [ensemble] table")
    known = {f.name for f in dataclasses.fields(EnsembleSweep)}
    unknown = set(table) - known
    if unknown:
        raise ValueError(
            f"{path}: unknown [ensemble] keys {sorted(unknown)}; "
            f"valid keys: {sorted(known)}")
    axes = [SweepAxis(**ax) for ax in table.get("sweep", [])]
    kwargs = {k: v for k, v in table.items() if k != "sweep"}
    spec = EnsembleSweep(sweep=axes, **kwargs)
    if spec.replicas < 1:
        raise ValueError(f"{path}: replicas must be >= 1, got {spec.replicas}")
    if spec.batch < 1:
        raise ValueError(f"{path}: batch must be >= 1, got {spec.batch}")
    if spec.batch_impl not in ("vmap", "unroll"):
        raise ValueError(
            f"{path}: unknown batch_impl {spec.batch_impl!r}; "
            "use 'vmap' or 'unroll'")
    for ax in spec.sweep:
        if not ax.key:
            raise ValueError(f"{path}: sweep axis without a key")
        if not ax.values:
            raise ValueError(f"{path}: sweep axis {ax.key!r} has no values")
        _check_sweepable(ax.key)
    return spec


#: params.* keys members may differ in without splitting the compiled
#: program (handled host-side by the scheduler, not traced)
_PARAMS_SWEEPABLE = ("params.t_final", "params.seed")


def _check_sweepable(key: str):
    if key.startswith("params.") and key not in _PARAMS_SWEEPABLE:
        raise ValueError(
            f"sweep key {key!r} changes the static runtime Params: all "
            "ensemble members share one compiled program, so only state "
            f"values are sweepable (params exceptions: "
            f"{', '.join(_PARAMS_SWEEPABLE)}). Run separate ensembles for "
            "different solver/physics parameters.")


def expand_members(spec: EnsembleSweep, base: Config) -> List[MemberPlan]:
    """Cartesian product of sweep axes x replicas -> member plans.

    Member ids are ``m<index:05d>``; the id order (axes outer, replicas
    inner) is the queue order, and ``index`` feeds `SimRNG.member(index)` —
    both deterministic, so a sweep is reproducible independent of how the
    scheduler packs lanes.
    """
    base_seed = spec.seed if spec.seed >= 0 else base.params.seed
    base_t_final = (spec.t_final if spec.t_final > 0.0
                    else base.params.t_final)
    points = (itertools.product(*[[(ax.key, v) for v in ax.values]
                                  for ax in spec.sweep])
              if spec.sweep else [()])
    plans = []
    for point in points:
        for _ in range(spec.replicas):
            idx = len(plans)
            overrides = dict(point)
            seed = int(overrides.pop("params.seed", base_seed))
            t_final = float(overrides.pop("params.t_final", base_t_final))
            if t_final <= 0.0:
                # the documented "<= 0 means base" fallback applies to swept
                # values too: a degenerate t_final would seat an
                # already-finished member (the scheduler retires it unstepped)
                t_final = float(base_t_final)
            plans.append(MemberPlan(member_id=f"m{idx:05d}", index=idx,
                                    overrides=overrides, seed=seed,
                                    t_final=t_final))
    return plans


def apply_overrides(base: Config, overrides: dict) -> Config:
    """Deep-copied base config with dotted-path overrides applied.

    Paths address attributes and list indices: ``fibers.0.length``,
    ``background.uniform``, ``bodies.1.external_force.2``. A path that does
    not resolve raises (never a silent no-op)."""
    cfg = copy.deepcopy(base)
    for key, value in overrides.items():
        _check_sweepable(key)
        parts = key.split(".")
        obj = cfg
        for part in parts[:-1]:
            obj = _descend(obj, part, key)
        _assign(obj, parts[-1], value, key)
    return cfg


def _descend(obj, part: str, key: str):
    if part.isdigit():
        try:
            return obj[int(part)]
        except (IndexError, TypeError) as e:
            raise ValueError(f"override {key!r}: index {part} out of range "
                             f"({e})") from None
    if not hasattr(obj, part):
        raise ValueError(f"override {key!r}: {type(obj).__name__} has no "
                         f"field {part!r}")
    return getattr(obj, part)


def _assign(obj, part: str, value, key: str):
    if part.isdigit():
        try:
            obj[int(part)] = value
        except (IndexError, TypeError) as e:
            raise ValueError(f"override {key!r}: index {part} out of range "
                             f"({e})") from None
        return
    if not hasattr(obj, part):
        raise ValueError(f"override {key!r}: {type(obj).__name__} has no "
                         f"field {part!r}")
    setattr(obj, part, value)


def resolve_base_config(spec: EnsembleSweep, sweep_path: str) -> str:
    """Base-config path, resolved relative to the sweep-spec file."""
    if os.path.isabs(spec.base_config):
        return spec.base_config
    return os.path.join(os.path.dirname(os.path.abspath(sweep_path)),
                        spec.base_config)


def load_members(sweep_path: str):
    """(spec, base_config_path, base Config, [MemberPlan]) from a sweep-spec
    TOML — the `python -m skellysim_tpu.ensemble` front half."""
    spec = load_sweep(sweep_path)
    base_path = resolve_base_config(spec, sweep_path)
    base = load_config(base_path)
    return spec, base_path, base, expand_members(spec, base)
