"""Minimal TOML emitter + tomllib-based loader.

The runtime config contract is a TOML file (reference: the Python toolkit dumps
`toml.dump(_unpack(config))`, `/root/reference/src/skelly_sim/skelly_config.py:958-973`,
and the C++ side parses it with toml11, `src/core/params.cpp:3-80`). Python ships
`tomllib` (read-only), so writing needs a small emitter. Supported value types:
bool/int/float/str, flat lists, nested dicts (tables), lists of dicts (arrays of
tables) — exactly the shapes the config schema produces.
"""

from __future__ import annotations

try:
    import tomllib  # Python >= 3.11
except ModuleNotFoundError:
    # API-compatible backport: on 3.10 boxes a bare `import tomllib` killed
    # every config-dependent test module at collection
    import tomli as tomllib
from typing import Any


def _format_scalar(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, int):
        return str(v)
    if isinstance(v, float):
        s = repr(v)
        # TOML floats need a '.' or exponent; repr(inf/nan) needs mapping
        if s in ("inf", "-inf"):
            return s
        if s == "nan":
            return "nan"
        if "." not in s and "e" not in s and "E" not in s:
            s += ".0"
        return s
    if isinstance(v, str):
        escaped = v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
        return f'"{escaped}"'
    raise TypeError(f"unsupported TOML scalar: {type(v)!r}")


def _format_value(v: Any) -> str:
    if isinstance(v, (list, tuple)):
        return "[" + ", ".join(_format_value(x) for x in v) + "]"
    return _format_scalar(v)


def _format_key(k: str) -> str:
    """Bare keys are [A-Za-z0-9_-]+ in TOML; anything else (the audit
    contracts' "float32->float64" promotion edges) must be quoted."""
    if k and all(c.isascii() and (c.isalnum() or c in "_-") for c in k):
        return k
    return _format_scalar(str(k))


def _is_table(v: Any) -> bool:
    return isinstance(v, dict)


def _is_table_array(v: Any) -> bool:
    return isinstance(v, (list, tuple)) and len(v) > 0 and all(
        isinstance(x, dict) for x in v)


def _emit_table(out: list[str], table: dict, prefix: str) -> None:
    scalars = {k: v for k, v in table.items()
               if not _is_table(v) and not _is_table_array(v)}
    for k, v in scalars.items():
        out.append(f"{_format_key(k)} = {_format_value(v)}")
    for k, v in table.items():
        if _is_table(v):
            name = f"{prefix}{_format_key(k)}"
            out.append("")
            out.append(f"[{name}]")
            _emit_table(out, v, name + ".")
    for k, v in table.items():
        if _is_table_array(v):
            name = f"{prefix}{_format_key(k)}"
            for item in v:
                out.append("")
                out.append(f"[[{name}]]")
                _emit_table(out, item, name + ".")


def dumps(data: dict) -> str:
    """Serialize a nested dict to a TOML string."""
    out: list[str] = []
    _emit_table(out, data, "")
    return "\n".join(out).lstrip("\n") + "\n"


def dump(data: dict, path: str) -> None:
    with open(path, "w") as f:
        f.write(dumps(data))


def load(path: str) -> dict:
    with open(path, "rb") as f:
        return tomllib.load(f)


def loads(s: str) -> dict:
    return tomllib.loads(s)
