"""System parameters.

Mirror of the reference `Params` struct and its TOML defaults
(`/root/reference/include/params.hpp:7-67`, `src/core/params.cpp:3-80`). These are
static (hashable) configuration — they select compiled programs; the dynamic
simulation state lives in `system.SimState`.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

#: valid Params.refine_pair_impl names — the single source for
#: System.__init__'s validation and the tuning scripts' argument checks
REFINE_PAIR_IMPLS = ("auto", "exact", "df", "pallas_df")


@dataclass(frozen=True)
class DynamicInstability:
    n_nodes: int = 0
    v_growth: float = 0.0
    f_catastrophe: float = 0.0
    v_grow_collision_scale: float = 0.5
    f_catastrophe_collision_scale: float = 2.0
    nucleation_rate: float = 0.0
    min_length: float = 0.5
    radius: float = 0.025
    bending_rigidity: float = 2.5e-3
    min_separation: float = 0.1


@dataclass(frozen=True)
class PeripheryBinding:
    active: bool = False
    polar_angle_start: float = 0.0
    polar_angle_end: float = math.pi
    threshold: float = 0.75


@dataclass(frozen=True)
class FiberPeripheryInteraction:
    f_0: float = 20.0
    l_0: float = 0.05


@dataclass(frozen=True)
class Params:
    eta: float = 1.0
    dt_initial: float = 1e-2
    dt_min: float = 1e-4
    dt_max: float = 2.0
    beta_up: float = 1.2
    beta_down: float = 0.5
    adaptive_timestep_flag: bool = True
    dt_write: float = 0.25
    t_final: float = 1.0
    gmres_tol: float = 1e-10
    gmres_restart: int = 100
    gmres_maxiter: int = 1000
    # communication-avoiding s-step GMRES block size (`solver.gmres
    # block_s`): each Arnoldi round generates s preconditioned Krylov
    # candidates and orthogonalizes them in TWO batched Gram reductions
    # instead of 3 per iteration — under `step_spmd` that is 2 psum rounds
    # per s iterations instead of 3s, the lever that flips the multi-chip
    # coupled-solve ladder positive (docs/parallel.md). 1 = the sequential
    # cycle, BITWISE identical to the pre-s-step solver (parity-pinned);
    # 4 is the measured sweet spot on the bench scenes — larger s trades
    # monomial-basis conditioning (f32 Krylov interior) for fewer rounds.
    gmres_block_s: int = 1
    # skelly-scope convergence history: ring-buffer capacity (rows) of
    # per-restart (iters, implicit, explicit) residuals carried device-side
    # through the solve and surfaced as the metrics JSONL's `gmres_history`
    # field (docs/observability.md). Pure masked writes — no host sync in
    # the loop, so audit's host-sync contract stays empty. 0 disables (the
    # [N,3] carry vanishes from the lowered program entirely).
    gmres_history: int = 16
    # skelly-flight physics flight recorder (obs.flight,
    # docs/observability.md "Flight recorder"): ring-buffer capacity (rows)
    # of per-step physics diagnostics — fiber max |strain| + argmax id, max
    # node speed, min signed node-periphery clearance, body/solution norms,
    # dt_used, the guard health word, and nonfinite anomaly provenance
    # (field/fiber/node of the first offender) — carried device-side
    # through the trial step as a [K, 13] f32 ring on `SimState.flight`.
    # Same discipline as gmres_history: pure masked `.at[].set` writes, no
    # host sync (audit's host-sync contract stays empty), vmaps per
    # ensemble member, psum'd/pmax'd under step_spmd so shards agree
    # bitwise. 0 (the default) disables — the carry vanishes from the
    # pytree and every pre-flight program is bitwise identical (the armed
    # K=32 twin is contract-pinned as its own auditable program,
    # `step_flight`).
    flight_window: int = 0
    fiber_error_tol: float = 1e-1
    # --- skelly-guard escalation ladder (guard.escalate,
    # docs/robustness.md): on a RETRYABLE solver health verdict
    # (stagnation/breakdown — never a poisoned nonfinite state) the trial
    # re-solves DEVICE-SIDE, inside the same jitted program, before the
    # member is declared failed. Stages run in order; each is a bounded
    # lax.while_loop, so a healthy solve pays zero extra trips (and under
    # vmap a healthy BATCH pays zero — the batched while_loop's cond is
    # any-member). All stages default OFF: the default program is the
    # pre-guard one, and every golden/parity pin stays bitwise. Applies to
    # the single-chip solve and the vmapped ensemble; `step_spmd` threads
    # the health WORD only and warns at build time if these are armed.
    # In-mesh escalation remains a follow-up, but no longer a folkloric
    # one: the replication analyzer (audit.repflow, `--check replication`)
    # proves both the guard-armed mesh build and the ladder's retry
    # while_loop pattern replication-safe (tests/test_guard.py), so the
    # blocker is wiring + per-stage compile cost, not deadlock risk —
    # docs/robustness.md "In-mesh escalation".
    #
    # guard_dt_halvings: retry up to N times at dt/2, dt/4, ... (floored
    # at dt_min under the adaptive gate); the successful retry's dt is
    # reported via StepInfo.dt_used and advances time.
    guard_dt_halvings: int = 0
    # then fall back gmres_block_s -> 1 (the sequential Arnoldi cycle):
    # the s-step monomial basis trades conditioning for fewer collectives
    # — its breakdowns resolve on the exact cycle (no-op at block_s=1)
    guard_block_fallback: bool = False
    # then route the Krylov interior through the full-precision f64 dense
    # path (the role-gated `pair=None` operator): the last resort when the
    # f32 interior's noise floor is the stall (no-op for "full" states;
    # NOTE on TPU this stage pays the emulated-f64 cliff — it is a
    # correctness stage, not a fast path)
    guard_f64_fallback: bool = False
    seed: int = 1
    # pairwise-kernel backend, mirroring the reference's params.pair_evaluator
    # ("CPU"/"GPU"/"FMM", `include/params.hpp:50`): "direct" = dense blocked
    # kernels (GSPMD inserts all-gathers on a mesh); "ring" = source blocks
    # rotate the ICI ring via collective-permute (free-space fiber systems on
    # a mesh; falls back to direct when a shell/bodies are present); "ewald" =
    # O(N log N) spectral Ewald (`ops.ewald` — the slot the reference fills
    # with STKFMM) for the fiber Stokeslet flows, re-planned host-side each
    # step like the reference's FMM tree rebuild; "tree" = the O(N log N)
    # barycentric Lagrange treecode (`ops.treecode` — the hierarchical
    # answer to the same FMM slot: fixed-depth octree, static interaction
    # lists, MXU-batched cluster matmuls), composing with both the
    # single-chip solve and the SPMD step (docs/treecode.md); "spectral" =
    # the O(N log N) particle-mesh Ewald far field over a periodic or
    # slab-confined box (`ops.spectral`, docs/spectral.md — requires
    # `periodic_box`), the PVFMM slot for the reference's periodic scenes
    pair_evaluator: str = "direct"
    # target relative accuracy of the Ewald evaluator; in "mixed" solver
    # precision the Ewald path serves only the f32 Krylov interior (the f64
    # refinement residual stays on the dense double-float tile), so 1e-6
    # does not cap the converged residual
    ewald_tol: float = 1e-6
    # periodic boundary of the simulation box for the "spectral" evaluator
    # (the slot the reference serves through PVFMM's periodic kernels):
    # () = free space (every other evaluator), a 3-tuple (Lx, Ly, Lz) =
    # triply periodic, a 2-tuple (Lx, Ly) = doubly periodic slab (x/y
    # periodic, z free — arXiv 2210.01837's confined formulation). Static
    # config: it shapes the FFT grid, so it selects compiled programs like
    # every other Params field
    periodic_box: tuple = ()
    # target relative accuracy of the spectral Ewald evaluator
    # (`ops.spectral.plan_spectral` derives xi, the window width P, and the
    # rung-snapped grid dims from it). Same f32-Krylov role gating as
    # ewald_tol: in "mixed" precision the spectral path serves the f32
    # interior only, so it does not cap the converged residual
    spectral_tol: float = 1e-6
    # target relative accuracy of the treecode evaluator
    # (`ops.treecode.plan_tree` picks interpolation order p from it via the
    # measured ~5x-per-order contraction rule, and octree depth from the
    # active node count). Same role gating as ewald_tol in "mixed"
    # precision: the tree serves the f32 Krylov interior only, so the
    # looser default does not cap the converged residual — and at f32 the
    # dense tile's own rounding is ~1e-6 on big sums anyway
    tree_tol: float = 1e-4
    # pairwise-kernel tile implementation: "exact" (displacement-tensor form,
    # the reference's semantics bit-for-bit), "mxu" (matmul form — the
    # O(N^2*3) contractions ride the MXU; see kernels.stokeslet_block_mxu's
    # near-field cancellation caveat — for well-separated fiber clouds),
    # "df" (double-float f32, the f64-grade accuracy tier), "pallas"
    # (fused VMEM-tile kernels, `ops.pallas_kernels` — the f32 throughput
    # tier at scale: 53/48 Gpairs/s stokeslet/stresslet on v5e, 3.4x/8x the
    # XLA path; f64 operands fall back to "exact"; interpret mode off-TPU),
    # or "pallas_df" (the DF arithmetic fused into Pallas tiles,
    # `ops.pallas_df` — f64-grade accuracy at VMEM-tile throughput)
    kernel_impl: str = "exact"
    # solver precision strategy (no reference analogue — the reference is
    # f64-everywhere on CPU; TPU XLA's LuDecomposition is f32-only and the
    # MXU prefers f32/bf16):
    #   "full"  — everything in the state dtype (f64 states need a CPU or an
    #             f64-capable LU path; f32 states run anywhere)
    #   "mixed" — f64 state/assembly/residuals, f32 Krylov loop + LU
    #             preconditioner, iterative refinement to gmres_tol
    #             (solver.gmres_ir); reaches the reference's 1e-10 tolerance
    #             with the hot loop at accelerator-native f32
    #   "auto"  — "mixed" exactly where it pays: f64 states on an
    #             accelerator backend (where native f64 flows are emulated
    #             and LU is f32-only); "full" otherwise. On CPU, measured
    #             mixed/full ratios are 2-3.5x SLOWER (f32 buys no CPU
    #             flops but refinement sweeps still repeat the solve), so
    #             the fallback is automatic rather than documented-only.
    # "auto" is the DEFAULT (round 5): the CLI builds f64 states, and a
    # "full" default would land default-config TPU runs on the f32-only LU
    # / emulated-f64 cliff the tier exists to avoid; on CPU "auto"
    # resolves to "full", i.e. exactly the old behavior
    solver_precision: str = "auto"
    # inner (f32) GMRES tolerance per refinement sweep in "mixed" mode;
    # each sweep contracts the error by about this factor. The trade is
    # sweeps (one expensive high-precision residual matvec each) against
    # inner iterations (cheap f32). Measured on the walkthrough scene
    # (scripts/mixed_tune.py, r5): 1e-5 reaches 1e-10 in ~9 total inner
    # iterations at ~2 sweeps vs 1e-4's ~12 iterations at 3 sweeps — fewer
    # of BOTH costs; 1e-6 flips back to ~13 iterations. Hence 1e-5.
    inner_tol: float = 1e-5
    # pairwise-kernel tile for the f64 refinement residual (and prep flows)
    # in "mixed" mode: "exact" = native f64 (fast on CPU, ~100x slower than
    # f32 on TPUs, whose f64 is software-emulated), "df" = double-float f32
    # (`ops.df_kernels`, ~1e-14 relative — far beyond gmres_tol needs),
    # "pallas_df" = the same double-float arithmetic fused into Pallas VMEM
    # tiles (`ops.pallas_df` — removes the XLA path's HBM-staged fusion
    # round trips), "auto" = "df" on accelerators, "exact" on CPU. The ring
    # evaluator serves both DF spellings with its own double-float tiles
    # (`parallel.ring.ring_stokeslet_df` / `ring_stresslet_df`)
    refine_pair_impl: str = "auto"  # one of REFINE_PAIR_IMPLS
    # max refinement sweeps in "mixed" mode
    max_refine: int = 8
    # coupled-solve preconditioner structure. The reference preconditions
    # with independent block solves (`apply_preconditioner`,
    # `system.cpp:248-262`) — "jacobi" here. "gs" upgrades that to a block
    # Gauss-Seidel sweep, shell block first: the shell solve's double-layer
    # flow corrects the fiber/body right-hand sides before their block
    # solves, folding the strong shell->fiber coupling of clamped-fiber
    # configs into the preconditioner. Measured on the oocyte BASELINE
    # scene: 70 -> 27 GMRES iterations at tol 1e-10, and the implicit
    # residual no longer drifts from the explicit one (no restart-repair
    # cycles). Cost: one shell->fiber/body kernel evaluation per
    # application — asymptotically cheaper than the full matvec. With no
    # shell (or nothing coupled to it) the two settings are identical.
    precond: str = "gs"
    # a fast pair_evaluator ("ewald"/"tree") routes a component's pairwise
    # flow through its evaluator only when its SOURCE count reaches this
    # bound; below it the dense tile is strictly cheaper than an extra
    # FFT-grid / tree-traversal pass (a 400-node body against 640k targets
    # is ~0.26 Gpairs — tens of ms dense, vs a full M^3 grid round-trip).
    # Host-side static dispatch, mirroring how the reference only pays FMM
    # setup for point sets that warrant it; set to 0 to force every flow
    # through the fast evaluator (parity tests)
    ewald_min_sources: int = 2048
    implicit_motor_activation_delay: float = 0.0
    periphery_interaction_flag: bool = False
    dynamic_instability: DynamicInstability = field(default_factory=DynamicInstability)
    periphery_binding: PeripheryBinding = field(default_factory=PeripheryBinding)
    fiber_periphery_interaction: FiberPeripheryInteraction = field(
        default_factory=FiberPeripheryInteraction)


def resolve_precision(solver_precision: str, is_f64: bool) -> str:
    """Resolve Params.solver_precision to a concrete "full"/"mixed".

    "auto" picks "mixed" only where the tier pays: f64 states on an
    accelerator backend, where native-f64 flows hit the emulation cliff and
    LU is f32-only; on CPU measured mixed/full ratios are 2-3.5x SLOWER, so
    "auto" stays "full" there. Shared by `System._precision_for` (per-state)
    and `builder.build_simulation` (choosing the shell preconditioner dtype
    before any state exists) so the policy cannot drift between them.
    """
    if solver_precision != "auto":
        return solver_precision
    if not is_f64:
        return "full"
    import jax

    return "mixed" if jax.default_backend() != "cpu" else "full"
