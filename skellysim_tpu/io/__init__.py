from .trajectory import (TrajectoryReader, TrajectoryWriter, frame_to_state,
                         resume_state)
from .listener_client import (Listener, Request, StreamlinesRequest,
                              VelocityFieldRequest)
from .ensemble_io import (EnsembleMetricsWriter,  # noqa: F401
                          MemberTrajectoryWriters)
