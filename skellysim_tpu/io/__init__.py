from .trajectory import (TrajectoryReader, TrajectoryWriter, frame_to_state,
                         resume_state)
