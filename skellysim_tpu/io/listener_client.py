"""Client for the listener-mode post-processing server.

Mirror of the reference Python toolkit's `Listener` / `Request` dataclasses
(`/root/reference/src/skelly_sim/reader.py:64-194`): spawns the simulator in
``--listen`` mode and exchanges length-prefixed msgpack messages over
stdin/stdout. The wire schema is identical, so this client also drives the
reference binary (and the reference client drives our server).
"""

from __future__ import annotations

import subprocess
import sys
from dataclasses import asdict, dataclass, field

import numpy as np

from ..serve import protocol


def _default_seeds() -> np.ndarray:
    return np.zeros((0, 3), dtype=np.float64)


@dataclass
class StreamlinesRequest:
    """Streamline batch request (`reader.py:65-89` field set)."""

    dt_init: float = 0.1
    t_final: float = 1.0
    abs_err: float = 1e-10
    rel_err: float = 1e-6
    back_integrate: bool = True
    x0: np.ndarray = field(default_factory=_default_seeds)


@dataclass
class VelocityFieldRequest:
    x: np.ndarray = field(default_factory=_default_seeds)


@dataclass
class Request:
    frame_no: int = 0
    evaluator: str = "CPU"
    streamlines: StreamlinesRequest = field(default_factory=StreamlinesRequest)
    vortexlines: StreamlinesRequest = field(default_factory=StreamlinesRequest)
    velocity_field: VelocityFieldRequest = field(
        default_factory=VelocityFieldRequest)


class Listener:
    """Drives a ``--listen`` server subprocess for on-the-fly analysis."""

    def __init__(self, toml_file: str = "skelly_config.toml",
                 binary: list[str] | None = None):
        cmd = binary or [sys.executable, "-m", "skellysim_tpu", "--listen",
                         f"--config-file={toml_file}"]
        self._proc = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                                      stdout=subprocess.PIPE)

    def request(self, command: Request) -> dict | None:
        """Send one request; returns the decoded response dict (or None for an
        invalid frame)."""
        protocol.write_message(self._proc.stdin, asdict(command))
        payload = protocol.read_frame(self._proc.stdout)
        if payload is None:
            raise RuntimeError("listener server closed unexpectedly")
        if payload == b"":
            return None
        return protocol.unpack_message(payload)

    def close(self):
        if self._proc.poll() is None:
            try:
                protocol.write_empty(self._proc.stdin)
                self._proc.wait(timeout=10)
            except (BrokenPipeError, subprocess.TimeoutExpired):
                self._proc.terminate()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
