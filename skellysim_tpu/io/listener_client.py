"""Client for the listener-mode post-processing server.

Mirror of the reference Python toolkit's `Listener` / `Request` dataclasses
(`/root/reference/src/skelly_sim/reader.py:64-194`): spawns the simulator in
``--listen`` mode and exchanges length-prefixed msgpack messages over
stdin/stdout. The wire schema is identical, so this client also drives the
reference binary (and the reference client drives our server).
"""

from __future__ import annotations

import struct
import subprocess
import sys
from dataclasses import asdict, dataclass, field

import msgpack
import numpy as np

from . import eigen


def _default_seeds() -> np.ndarray:
    return np.zeros((0, 3), dtype=np.float64)


@dataclass
class StreamlinesRequest:
    """Streamline batch request (`reader.py:65-89` field set)."""

    dt_init: float = 0.1
    t_final: float = 1.0
    abs_err: float = 1e-10
    rel_err: float = 1e-6
    back_integrate: bool = True
    x0: np.ndarray = field(default_factory=_default_seeds)


@dataclass
class VelocityFieldRequest:
    x: np.ndarray = field(default_factory=_default_seeds)


@dataclass
class Request:
    frame_no: int = 0
    evaluator: str = "CPU"
    streamlines: StreamlinesRequest = field(default_factory=StreamlinesRequest)
    vortexlines: StreamlinesRequest = field(default_factory=StreamlinesRequest)
    velocity_field: VelocityFieldRequest = field(
        default_factory=VelocityFieldRequest)


def _ndencode(obj):
    if isinstance(obj, np.ndarray):
        return eigen.pack_matrix(obj)
    return obj


class Listener:
    """Drives a ``--listen`` server subprocess for on-the-fly analysis."""

    def __init__(self, toml_file: str = "skelly_config.toml",
                 binary: list[str] | None = None):
        cmd = binary or [sys.executable, "-m", "skellysim_tpu", "--listen",
                         f"--config-file={toml_file}"]
        self._proc = subprocess.Popen(cmd, stdin=subprocess.PIPE,
                                      stdout=subprocess.PIPE)

    def request(self, command: Request) -> dict | None:
        """Send one request; returns the decoded response dict (or None for an
        invalid frame)."""
        msg = msgpack.packb(asdict(command), default=_ndencode)
        self._proc.stdin.write(struct.pack("<Q", len(msg)))
        self._proc.stdin.write(msg)
        self._proc.stdin.flush()
        hdr = self._proc.stdout.read(8)
        if len(hdr) < 8:
            raise RuntimeError("listener server closed unexpectedly")
        (ressize,) = struct.unpack("<Q", hdr)
        if ressize == 0:
            return None
        payload = b""
        while len(payload) < ressize:
            chunk = self._proc.stdout.read(ressize - len(payload))
            if not chunk:
                raise RuntimeError("listener server closed mid-response")
            payload += chunk
        return eigen.decode_tree(msgpack.unpackb(payload, raw=False))

    def close(self):
        if self._proc.poll() is None:
            try:
                self._proc.stdin.write(struct.pack("<Q", 0))
                self._proc.stdin.flush()
                self._proc.wait(timeout=10)
            except (BrokenPipeError, subprocess.TimeoutExpired):
                self._proc.terminate()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
