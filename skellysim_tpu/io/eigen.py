"""Wire-format helpers for the reference trajectory encoding.

The reference serializes Eigen matrices as msgpack arrays
``['__eigen__', rows, cols, <data in column-major order>]``
(`/root/reference/include/eigen_matrix_plugin.h:30-41`) and quaternions as
``['__quat__', w, x, y, z]`` (`eigen_quaternion_plugin.h:27-36`). Matching the
format byte-for-byte means the reference's Python toolkit (reader, ParaView
utilities) can read our trajectories unmodified.
"""

from __future__ import annotations

import msgpack
import numpy as np


def mp_array_header(n: int) -> bytes:
    """Raw msgpack array header (fixarray / array16 / array32)."""
    if n < 16:
        return bytes([0x90 | n])
    if n < 65536:
        return b"\xdc" + n.to_bytes(2, "big")
    return b"\xdd" + n.to_bytes(4, "big")


def mp_map_header(n: int) -> bytes:
    """Raw msgpack map header (fixmap / map16 / map32)."""
    if n < 16:
        return bytes([0x80 | n])
    if n < 65536:
        return b"\xde" + n.to_bytes(2, "big")
    return b"\xdf" + n.to_bytes(4, "big")


def mp_doubles(a: np.ndarray) -> np.ndarray:
    """[n, 9] uint8 rows: each double as msgpack float64 (0xcb + BE payload).

    Vectorized replacement for per-element packing — the 10k-fiber frame has
    ~3M doubles, and Python-level float packing was the whole encode cost.
    """
    flat = np.ascontiguousarray(a, dtype=np.float64).reshape(-1)
    out = np.empty((flat.size, 9), dtype=np.uint8)
    out[:, 0] = 0xCB
    out[:, 1:] = flat.astype(">f8").view(np.uint8).reshape(flat.size, 8)
    return out


_EIGEN_TAG = msgpack.packb("__eigen__")


def pack_matrix_bytes(a: np.ndarray) -> bytes:
    """Raw msgpack bytes equivalent to ``packb(pack_matrix(a))``, with the
    double payload emitted vectorized. Decoders cannot tell the difference."""
    a = np.asarray(a, dtype=np.float64)
    if a.ndim == 1:
        rows, cols, flat = a.shape[0], 1, a
    elif a.ndim == 2 and a.shape[1] == 3:
        rows, cols, flat = 3, a.shape[0], a.ravel()
    elif a.ndim == 2:
        rows, cols, flat = a.shape[0], a.shape[1], a.ravel(order="F")
    else:
        raise ValueError(f"cannot encode array of shape {a.shape}")
    return (mp_array_header(3 + flat.size) + _EIGEN_TAG
            + msgpack.packb(int(rows)) + msgpack.packb(int(cols))
            + mp_doubles(flat).tobytes())


def pack_matrix(a: np.ndarray) -> list:
    """Encode an array as an ``__eigen__`` list.

    Convention mapping to the reference: a point cloud we store as [n, 3]
    (points along rows) is the reference's 3 x n column-major matrix, so its
    column-major ravel equals our row-major ravel — encode rows=3, cols=n with
    the row-major ravel of the [n, 3] array.
    """
    a = np.asarray(a, dtype=np.float64)
    if a.ndim == 1:
        return ["__eigen__", a.shape[0], 1] + a.tolist()
    if a.ndim == 2 and a.shape[1] == 3:
        return ["__eigen__", 3, a.shape[0]] + a.ravel().tolist()
    if a.ndim == 2:
        return ["__eigen__", a.shape[0], a.shape[1]] + a.ravel(order="F").tolist()
    raise ValueError(f"cannot encode array of shape {a.shape}")


def unpack_matrix(d: list) -> np.ndarray:
    """Decode an ``__eigen__`` list (mirrors `reader.py:28-62` semantics)."""
    rows, cols = d[1], d[2]
    data = np.asarray(d[3:], dtype=np.float64)
    if rows == 1 or cols == 1:
        return data
    if rows == 3:
        # column-major 3 x n == row-major [n, 3] points
        return data.reshape(cols, rows)
    return data.reshape(cols, rows).T


def pack_quat(q) -> list:
    """Encode a (w, x, y, z) quaternion."""
    q = np.asarray(q, dtype=np.float64)
    return ["__quat__"] + q.tolist()


def decode_tree(d):
    """Recursively convert ``__eigen__``/``__quat__`` lists to numpy arrays."""
    if isinstance(d, list):
        if d and d[0] == "__eigen__":
            return unpack_matrix(d)
        if d and d[0] == "__quat__":
            return np.asarray(d[1:], dtype=np.float64)
        return [decode_tree(v) for v in d]
    if isinstance(d, dict):
        return {k: decode_tree(v) for k, v in d.items()}
    return d
