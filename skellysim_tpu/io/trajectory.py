"""Trajectory writer/reader + checkpoint-resume.

Byte-compatible with the reference trajectory format v1:
frame = msgpack map {time, dt, rng_state, fibers, bodies, shell}
(`/root/reference/include/io_maps.hpp:17-38`), preceded by a header map
{trajversion, number_mpi_ranks, fiber_type, ...} (`io_maps.hpp:43-56`), with
Eigen/quaternion payloads in the ``__eigen__``/``__quat__`` wire encoding.
The trajectory doubles as the checkpoint (`SURVEY.md` §5.4): `resume_state`
replays the last frame into a fresh `SimState`.

Fast random access uses a ``.cindex`` side file {mtime, offsets, times}
(`trajectory_reader.cpp:78-124`, `reader.py:293-329`), built by the native C++
scanner (`skellysim_tpu/native/trajscan.cpp`) with a Python fallback.
"""

from __future__ import annotations

import ctypes
import os
import platform
import time as _time
from typing import Optional

import msgpack
import numpy as np

from .. import TRAJECTORY_VERSION, __version__
from ..native import load_library
from . import eigen

FIBER_TYPE_NONE = 0
FIBER_TYPE_FINITE_DIFFERENCE = 1


def _bucket_list(fibers) -> list:
    """SimState.fibers (group | tuple of resolution buckets | None) -> list.

    Masked node padding (skelly-bucket) is stripped here — the ONE place
    every frame encoder goes through — so the wire carries live node rows
    only and a bucketized run's trajectory is byte-identical to an
    unpadded run's (inactive fiber slots are already dropped per fiber)."""
    from ..fibers.container import as_buckets, strip_node_padding

    return [strip_node_padding(g) for g in as_buckets(fibers)]


def _active_ranks(group) -> np.ndarray:
    """Config-order ranks of the active slots (slot order)."""
    active = np.asarray(group.active)
    if group.config_rank is None:
        return np.flatnonzero(active)
    return np.asarray(group.config_rank)[active]


def _shell_wire_density(state) -> np.ndarray:
    """Shell density as the wire carries it: live quadrature rows only —
    masked padding rows (skelly-bucket) hold exact zeros and are sliced
    off, keeping padded runs byte-identical to unpadded ones."""
    if state.shell is None:
        return np.zeros(0)
    density = np.asarray(state.shell.density, dtype=np.float64)
    if state.shell.node_mask is not None:
        density = density[:3 * int(np.asarray(
            state.shell.node_mask).sum())]
    return density


# ---------------------------------------------------------------- frame build

def _fiber_maps(fibers):
    """Per-fiber msgpack maps (`fiber_finite_difference.hpp:160-161` field set).

    One host transfer per *field* (not per fiber): at the 10k-fiber BASELINE
    scale, per-fiber device fetches would dominate the frame encode. The
    remaining Python loop only assembles dicts of prefetched NumPy scalars —
    the msgpack wire format is per-fiber maps, so a loop of some form is
    inherent to the trajectory-v1 contract.
    """
    x = np.asarray(fibers.x, dtype=np.float64)
    tension = np.asarray(fibers.tension, dtype=np.float64)
    active = np.asarray(fibers.active)
    n_nodes = int(x.shape[1])
    # .tolist() gives native Python scalars in one pass (msgpack rejects
    # numpy scalar types)
    radius = np.asarray(fibers.radius, dtype=float).tolist()
    length = np.asarray(fibers.length, dtype=float).tolist()
    length_prev = np.asarray(fibers.length_prev, dtype=float).tolist()
    bending = np.asarray(fibers.bending_rigidity, dtype=float).tolist()
    penalty = np.asarray(fibers.penalty, dtype=float).tolist()
    force_scale = np.asarray(fibers.force_scale, dtype=float).tolist()
    beta_tstep = np.asarray(fibers.beta_tstep, dtype=float).tolist()
    binding = np.stack([np.asarray(fibers.binding_body),
                        np.asarray(fibers.binding_site)], axis=1).tolist()
    minus_clamped = np.asarray(fibers.minus_clamped).tolist()
    return [{
        "n_nodes_": n_nodes,
        "radius_": radius[i],
        "length_": length[i],
        "length_prev_": length_prev[i],
        "bending_rigidity_": bending[i],
        "penalty_param_": penalty[i],
        "force_scale_": force_scale[i],
        "beta_tstep_": beta_tstep[i],
        "binding_site_": binding[i],
        "tension_": eigen.pack_matrix(tension[i]),
        "x_": eigen.pack_matrix(x[i]),
        "minus_clamped_": minus_clamped[i],
    } for i in np.nonzero(active)[0]]


def _body_maps(bodies):
    """Bodies as [spherical, deformable, ellipsoidal] (`body_container.hpp:158`).

    Multiple shape/resolution buckets merge back into config order within
    each kind (`config_rank`), matching the reference's declaration-order
    serialization of its mixed container."""
    from ..bodies.bodies import as_buckets

    entries = []                       # (rank, is_sphere, map)
    for g in as_buckets(bodies):
        pos = np.asarray(g.position, dtype=np.float64)
        orient = np.asarray(g.orientation, dtype=np.float64)
        sol = np.asarray(g.solution, dtype=np.float64)
        kind_sphere = np.asarray(g.kind_sphere)
        ranks = (np.asarray(g.config_rank) if g.config_rank is not None
                 else np.arange(g.n_bodies))
        for i in range(pos.shape[0]):
            m = {
                "radius_": float(g.radius[i]),
                "position_": eigen.pack_matrix(pos[i]),
                "orientation_": eigen.pack_quat(orient[i]),
                "solution_vec_": eigen.pack_matrix(sol[i]),
            }
            entries.append((int(ranks[i]), bool(kind_sphere[i]), m))
    entries.sort(key=lambda t: t[0])
    spheres = [m for _, is_s, m in entries if is_s]
    ellipsoids = [m for _, is_s, m in entries if not is_s]
    return [spheres, [], ellipsoids]


def state_to_frame(state, rng_state=None) -> dict:
    """Encode a SimState as a trajectory-v1 frame map.

    With multiple resolution buckets, fibers are merged back into config
    order (by `config_rank`) so the wire stays reference-ordered — the
    reference writes its mixed-resolution `std::list` in declaration order.
    """
    buckets = _bucket_list(state.fibers)
    if buckets:
        entries = []
        for g in buckets:
            entries.extend(zip(_active_ranks(g).tolist(), _fiber_maps(g)))
        entries.sort(key=lambda t: t[0])
        fibers_field = [FIBER_TYPE_FINITE_DIFFERENCE,
                        [m for _, m in entries]]
    else:
        fibers_field = [FIBER_TYPE_NONE, []]
    shell_sol = _shell_wire_density(state)
    return {
        "time": float(state.time),
        "dt": float(state.dt),
        "rng_state": rng_state if rng_state is not None else [],
        "fibers": fibers_field,
        "bodies": _body_maps(state.bodies),
        "shell": {"solution_vec_": eigen.pack_matrix(shell_sol)},
    }


# Raw-bytes frame encoder: identical wire format to
# ``msgpack.packb(state_to_frame(...))`` but with every double payload packed
# vectorized (eigen.mp_doubles). A 10k-fiber frame encodes in ~0.1 s instead
# of ~1.4 s — the per-element Python float packing was the whole cost
# (SURVEY.md §2.3 gatherless-writer note; VERDICT r2 weak #5).

_FIBER_KEYS = ["n_nodes_", "radius_", "length_", "length_prev_",
               "bending_rigidity_", "penalty_param_", "force_scale_",
               "beta_tstep_", "binding_site_", "tension_", "x_",
               "minus_clamped_"]
_FIBER_KEY_BYTES = [msgpack.packb(k) for k in _FIBER_KEYS]


def _fiber_array_bytes_native(fibers) -> bytes | None:
    """Native C++ encode of the active-fiber map array
    (`native/frameenc.cpp`); None when the toolchain is unavailable."""
    lib = load_library("frameenc")
    if lib is None:
        return None
    lib.frameenc_fibers.restype = ctypes.c_int64
    dbl = ctypes.POINTER(ctypes.c_double)
    lib.frameenc_fibers.argtypes = [dbl] * 9 + [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_uint64)]

    def darr(a):
        return np.ascontiguousarray(np.asarray(a, dtype=np.float64))

    x = darr(fibers.x)
    nf, n = x.shape[0], x.shape[1]
    tension = darr(fibers.tension)
    scalars = [darr(getattr(fibers, f)) for f in
               ("radius", "length", "length_prev", "bending_rigidity",
                "penalty", "force_scale", "beta_tstep")]
    binding = np.ascontiguousarray(np.stack(
        [np.asarray(fibers.binding_body), np.asarray(fibers.binding_site)],
        axis=1).astype(np.int32))
    active = np.ascontiguousarray(np.asarray(fibers.active, dtype=np.uint8))
    mclamp = np.ascontiguousarray(
        np.asarray(fibers.minus_clamped, dtype=np.uint8))

    out_p = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_uint64()
    args = [x, tension] + scalars
    rc = lib.frameenc_fibers(
        *[a.ctypes.data_as(dbl) for a in args],
        binding.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        active.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        mclamp.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        nf, n, ctypes.byref(out_p), ctypes.byref(out_len))
    if rc < 0:
        return None
    try:
        return ctypes.string_at(out_p, out_len.value)
    finally:
        lib.frameenc_free(out_p)


def _fiber_array_bytes(fibers) -> bytes:
    """msgpack bytes of the active-fiber map array: native C++ fast path
    (`native/frameenc.cpp`) with the field-vectorized Python encoder as the
    fallback — both byte-identical to `packb` of the object maps."""
    native = _fiber_array_bytes_native(fibers)
    if native is not None:
        return native
    return _fiber_array_bytes_py(fibers)


def _fiber_array_bytes_py(fibers) -> bytes:
    """Pure-Python encode of the active-fiber map array, field-vectorized."""
    chunks = _fiber_chunk_bytes_py(fibers)
    return eigen.mp_array_header(len(chunks)) + b"".join(chunks)


def _fiber_chunk_bytes_py(fibers) -> list:
    """Per-active-fiber msgpack map bytes (slot order), field-vectorized."""
    x = np.asarray(fibers.x, dtype=np.float64)
    tension = np.asarray(fibers.tension, dtype=np.float64)
    active = np.nonzero(np.asarray(fibers.active))[0]
    nf, n = x.shape[0], int(x.shape[1])

    # scalar fields: one [nf, 9] vectorized float64 encoding per field
    scalars = [eigen.mp_doubles(np.asarray(getattr(fibers, f), dtype=float))
               for f in ("radius", "length", "length_prev", "bending_rigidity",
                         "penalty", "force_scale", "beta_tstep")]
    binding = np.stack([np.asarray(fibers.binding_body),
                        np.asarray(fibers.binding_site)], axis=1).tolist()
    minus_clamped = np.asarray(fibers.minus_clamped)

    # per-node payloads: [nf, n*9] / [nf, 3n*9] rows, one slice per fiber
    tension_rows = eigen.mp_doubles(tension).reshape(nf, n * 9)
    x_rows = eigen.mp_doubles(x).reshape(nf, 3 * n * 9)
    tension_head = (eigen.mp_array_header(3 + n) + eigen._EIGEN_TAG
                    + msgpack.packb(n) + msgpack.packb(1))
    x_head = (eigen.mp_array_header(3 + 3 * n) + eigen._EIGEN_TAG
              + msgpack.packb(3) + msgpack.packb(n))

    kb = _FIBER_KEY_BYTES
    map_head = eigen.mp_map_header(len(_FIBER_KEYS))
    n_nodes_b = msgpack.packb(n)
    parts = []
    for i in active:
        parts.append(b"".join([
            map_head,
            kb[0], n_nodes_b,
            kb[1], scalars[0][i].tobytes(),
            kb[2], scalars[1][i].tobytes(),
            kb[3], scalars[2][i].tobytes(),
            kb[4], scalars[3][i].tobytes(),
            kb[5], scalars[4][i].tobytes(),
            kb[6], scalars[5][i].tobytes(),
            kb[7], scalars[6][i].tobytes(),
            kb[8], msgpack.packb(binding[i]),
            kb[9], tension_head, tension_rows[i].tobytes(),
            kb[10], x_head, x_rows[i].tobytes(),
            kb[11], msgpack.packb(bool(minus_clamped[i])),
        ]))
    return parts


def frame_bytes(state, rng_state=None) -> bytes:
    """Raw msgpack bytes of a trajectory-v1 frame; decoders cannot tell this
    apart from ``msgpack.packb(state_to_frame(state, rng_state))``."""
    buckets = _bucket_list(state.fibers)
    if len(buckets) == 1 and np.all(np.diff(_active_ranks(buckets[0])) > 0):
        # single bucket in config order: the native C++ fast path applies
        fibers_b = (eigen.mp_array_header(2)
                    + msgpack.packb(FIBER_TYPE_FINITE_DIFFERENCE)
                    + _fiber_array_bytes(buckets[0]))
    elif buckets:
        # mixed resolutions (or permuted ranks): per-fiber byte chunks from
        # the field-vectorized encoder, merged back into config order
        entries = []
        for g in buckets:
            entries.extend(zip(_active_ranks(g).tolist(),
                               _fiber_chunk_bytes_py(g)))
        entries.sort(key=lambda t: t[0])
        fibers_b = (eigen.mp_array_header(2)
                    + msgpack.packb(FIBER_TYPE_FINITE_DIFFERENCE)
                    + eigen.mp_array_header(len(entries))
                    + b"".join(c for _, c in entries))
    else:
        fibers_b = msgpack.packb([FIBER_TYPE_NONE, []])
    shell_sol = _shell_wire_density(state)
    return b"".join([
        eigen.mp_map_header(6),
        msgpack.packb("time"), msgpack.packb(float(state.time)),
        msgpack.packb("dt"), msgpack.packb(float(state.dt)),
        msgpack.packb("rng_state"),
        msgpack.packb(rng_state if rng_state is not None else []),
        msgpack.packb("fibers"), fibers_b,
        msgpack.packb("bodies"), msgpack.packb(_body_maps(state.bodies)),
        msgpack.packb("shell"),
        eigen.mp_map_header(1) + msgpack.packb("solution_vec_")
        + eigen.pack_matrix_bytes(shell_sol),
    ])


# -------------------------------------------------------------------- writer

class TrajectoryWriter:
    """Appends header + frames to a trajectory file (`System::write`,
    `system.cpp:100-218`)."""

    def __init__(self, path: str, *, append: bool = False,
                 fiber_type: int = FIBER_TYPE_FINITE_DIFFERENCE):
        self.path = path
        self._fh = open(path, "ab" if append else "wb")
        if not append:
            self._fh.write(msgpack.packb({
                "trajversion": TRAJECTORY_VERSION,
                "number_mpi_ranks": 1,
                "fiber_type": fiber_type,
                "skellysim_version": __version__,
                "skellysim_commit": "skellysim_tpu",
                "simdate": _time.strftime("%Y-%m-%d %H:%M:%S"),
                "hostname": platform.node(),
            }))
            self._fh.flush()

    def write_frame(self, state, solution=None, *, rng_state=None):
        """Append one frame. ``solution`` is accepted (and ignored) so this can
        be passed directly as ``System.run(..., writer=tw.write_frame)``."""
        self._fh.write(frame_bytes(state, rng_state))
        self._fh.flush()

    def close(self):
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class FieldWriter:
    """Appends velocity-field frames {time, dt, x_grid, v_grid} readable by
    `paraview_utils/field_reader.py` (the reference's `skelly_sim.vf` layout:
    point clouds in the 3 x n ``__eigen__`` encoding)."""

    def __init__(self, path: str = "skelly_sim.vf", *, append: bool = False):
        self.path = path
        self._fh = open(path, "ab" if append else "wb")

    def write_frame(self, time: float, positions, velocities, dt: float = 0.0):
        x = np.asarray(positions, dtype=np.float64).reshape(-1, 3)
        v = np.asarray(velocities, dtype=np.float64).reshape(-1, 3)
        self._fh.write(msgpack.packb({
            "time": float(time),
            "dt": float(dt),
            "x_grid": eigen.pack_matrix(x),
            "v_grid": eigen.pack_matrix(v),
        }))
        self._fh.flush()

    def close(self):
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# --------------------------------------------------------------------- index

def _scan_native(path: str):
    lib = load_library("trajscan")
    if lib is None:
        return None
    lib.trajscan_buffer.restype = ctypes.c_int64
    lib.trajscan_buffer.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_double))]
    import mmap

    offsets_p = ctypes.POINTER(ctypes.c_uint64)()
    times_p = ctypes.POINTER(ctypes.c_double)()
    with open(path, "rb") as fh:
        size = os.fstat(fh.fileno()).st_size
        if size == 0:
            return [], []
        # ACCESS_COPY: pages stay lazily file-backed (no up-front RAM copy of a
        # multi-GB trajectory) but the buffer is writable, which
        # ctypes.from_buffer requires; the scanner never writes.
        mm = mmap.mmap(fh.fileno(), 0, access=mmap.ACCESS_COPY)
        try:
            cbuf = ctypes.c_char.from_buffer(mm)
            n = lib.trajscan_buffer(ctypes.addressof(cbuf), size,
                                    ctypes.byref(offsets_p),
                                    ctypes.byref(times_p))
            del cbuf
        finally:
            mm.close()
    if n < 0:
        return None
    offsets = np.ctypeslib.as_array(offsets_p, shape=(max(n, 1),))[:n].copy()
    times = np.ctypeslib.as_array(times_p, shape=(max(n, 1),))[:n].copy()
    lib.trajscan_free(offsets_p)
    lib.trajscan_free(times_p)
    return offsets.tolist(), times.tolist()


def _scan_python(path: str):
    offsets, times = [], []
    with open(path, "rb") as fh:
        unpacker = msgpack.Unpacker(fh, raw=False)
        while True:
            try:
                pos = unpacker.tell()
                obj = unpacker.unpack()
            except msgpack.exceptions.OutOfData:
                break
            if isinstance(obj, dict) and "time" in obj:
                offsets.append(pos)
                times.append(obj["time"])
    return offsets, times


def build_index(path: str, use_native: bool = True):
    """Frame (offsets, times); written to `<path>.cindex` like the reference."""
    # stat BEFORE scanning: a frame appended mid-scan must invalidate the
    # index. mtime is truncated to int like the reference (`reader.py:238`)
    # so the reference's TrajectoryReader accepts our .cindex verbatim
    # instead of rebuilding on a float-vs-int mtime mismatch; the extra
    # "size" key (ignored by the reference reader) closes the 1-second
    # append window whole-second mtimes can't see.
    st = os.stat(path)
    res = _scan_native(path) if use_native else None
    if res is None:
        res = _scan_python(path)
    offsets, times = res
    index = {"mtime": int(st.st_mtime), "size": st.st_size,
             "offsets": offsets, "times": times}
    with open(path + ".cindex", "wb") as fh:
        msgpack.dump(index, fh)
    return offsets, times


# --------------------------------------------------------------------- reader

class TrajectoryReader:
    """Random-access frame reader (`reader.py:198-355` semantics)."""

    def __init__(self, path: str = "skelly_sim.out"):
        self.path = path
        self._fh = open(path, "rb")
        self.header = msgpack.Unpacker(self._fh, raw=False).unpack()
        if not (isinstance(self.header, dict) and "trajversion" in self.header):
            raise ValueError(f"{path}: missing trajectory header")
        self.trajectory_version = self.header["trajversion"]
        self.fiber_type = self.header["fiber_type"]

        index_file = path + ".cindex"
        st = os.stat(path)
        offsets = times = None
        if os.path.exists(index_file):
            with open(index_file, "rb") as fh:
                index = msgpack.unpack(fh, raw=False)
            # "size" guards same-second appends that the reference's
            # whole-second mtime comparison cannot detect; absent (a
            # reference-reader-built index) it falls back to mtime alone
            if (index.get("mtime") == int(st.st_mtime)
                    and index.get("size", st.st_size) == st.st_size):
                offsets, times = index["offsets"], index["times"]
        if offsets is None:
            offsets, times = build_index(path)
        self._fpos = offsets
        self.times = times
        self._frame = None

    def __len__(self):
        return len(self._fpos)

    def load_frame(self, i: int) -> dict:
        i = int(i)
        if i < 0:
            i += len(self)
        self._fh.seek(self._fpos[i])
        raw = msgpack.Unpacker(self._fh, raw=False).unpack()
        self._frame = eigen.decode_tree(raw)
        return self._frame

    def __getitem__(self, key):
        if self._frame is None:
            self.load_frame(0)
        if key == "bodies":
            return [b for sub in self._frame["bodies"] for b in sub]
        if key == "fibers":
            return self._frame["fibers"][1]
        return self._frame[key]

    def keys(self):
        return self._frame.keys() if self._frame is not None else []

    def close(self):
        self._fh.close()


# -------------------------------------------------------------------- resume

def frame_to_state(frame: dict, template_state, dtype=None):
    """Rebuild a SimState from a decoded frame.

    Fibers are fully reconstructed from the frame (their parameters are
    serialized); bodies and shell keep their geometry/operators from
    ``template_state`` and take position/orientation/solution from the frame
    (`trajectory_reader.cpp:139-251`).
    """
    import jax.numpy as jnp

    from ..fibers import container as fc

    if dtype is None:
        tb = _bucket_list(template_state.fibers)
        dtype = tb[0].x.dtype if tb else jnp.float64
    state = template_state

    fiber_maps = frame["fibers"][1] if frame["fibers"][0] else []
    if fiber_maps:
        # regroup by resolution into buckets, first-appearance order (the
        # same stable bucketing the builder applies to the config), with the
        # frame position recorded as config_rank so a re-written trajectory
        # keeps the wire order
        by_n: dict = {}
        for rank, f in enumerate(fiber_maps):
            by_n.setdefault(int(f["n_nodes_"]), []).append((rank, f))

        def one_bucket(items):
            ranks = [r for r, _ in items]
            maps = [f for _, f in items]
            x = np.stack([np.asarray(f["x_"]).reshape(-1, 3) for f in maps])
            g = fc.make_group(
                x,
                lengths=np.array([f["length_"] for f in maps]),
                bending_rigidity=np.array([f["bending_rigidity_"] for f in maps]),
                radius=np.array([f["radius_"] for f in maps]),
                penalty=np.array([f["penalty_param_"] for f in maps]),
                beta_tstep=np.array([f["beta_tstep_"] for f in maps]),
                force_scale=np.array([f["force_scale_"] for f in maps]),
                minus_clamped=np.array([f["minus_clamped_"] for f in maps]),
                binding_body=np.array([f["binding_site_"][0] for f in maps]),
                binding_site=np.array([f["binding_site_"][1] for f in maps]),
                config_rank=np.array(ranks, dtype=np.int32),
                dtype=dtype)
            return g._replace(
                tension=jnp.asarray(np.stack([f["tension_"] for f in maps]),
                                    dtype=dtype),
                length_prev=jnp.asarray([f["length_prev_"] for f in maps],
                                        dtype=dtype))

        groups = [one_bucket(items) for items in by_n.values()]
        state = state._replace(
            fibers=groups[0] if len(groups) == 1 else tuple(groups))
    elif template_state.fibers is not None:
        state = state._replace(fibers=None)

    bodies_wire = [b for sub in frame["bodies"] for b in sub]
    if bodies_wire:
        from ..bodies.bodies import BodyGroup, as_buckets

        b_list = list(as_buckets(state.bodies))
        if not b_list or sum(g.n_bodies for g in b_list) != len(bodies_wire):
            raise ValueError("trajectory bodies do not match the configured state")
        # the wire groups bodies as [spheres..., ellipsoids...] each in
        # config order; map wire slots back to (bucket, slot) through the
        # template's kind + config_rank
        entries = []                   # (is_ellipsoid, rank, bucket, slot)
        for bi, g in enumerate(b_list):
            ks = np.asarray(g.kind_sphere)
            ranks = (np.asarray(g.config_rank) if g.config_rank is not None
                     else np.arange(g.n_bodies))
            for slot in range(g.n_bodies):
                entries.append((not bool(ks[slot]), int(ranks[slot]),
                                bi, slot))
        entries.sort()
        pos = [np.asarray(g.position).copy() for g in b_list]
        orient = [np.asarray(g.orientation).copy() for g in b_list]
        sol = [np.asarray(g.solution).copy() for g in b_list]
        for wire_slot, (_, _, bi, slot) in enumerate(entries):
            m = bodies_wire[wire_slot]
            pos[bi][slot] = m["position_"]
            orient[bi][slot] = m["orientation_"]
            sol[bi][slot] = m["solution_vec_"]
        new_b = tuple(
            g._replace(position=jnp.asarray(pos[bi], dtype=dtype),
                       orientation=jnp.asarray(orient[bi], dtype=dtype),
                       solution=jnp.asarray(sol[bi], dtype=dtype))
            for bi, g in enumerate(b_list))
        state = state._replace(
            bodies=(new_b[0] if isinstance(state.bodies, BodyGroup)
                    else new_b))

    shell_sol = np.asarray(frame["shell"]["solution_vec_"])
    if state.shell is not None and shell_sol.size == state.shell.density.shape[0]:
        state = state._replace(shell=state.shell._replace(
            density=jnp.asarray(shell_sol, dtype=dtype)))
    elif (state.shell is not None and state.shell.node_mask is not None
          and shell_sol.size == 3 * int(np.asarray(
              state.shell.node_mask).sum())):
        # live-rows wire density over a capacity-padded template: scatter
        # into the live prefix, padded rows stay exact zero
        full = np.zeros(state.shell.density.shape[0])
        full[:shell_sol.size] = shell_sol.reshape(-1)
        state = state._replace(shell=state.shell._replace(
            density=jnp.asarray(full, dtype=dtype)))

    state = state._replace(
        time=jnp.asarray(frame["time"], dtype=dtype),
        dt=jnp.asarray(frame["dt"], dtype=dtype))
    return state


def resume_state(path: str, template_state):
    """(state, rng_state, reader) from the last frame (`--resume`,
    `system.cpp:223-228`)."""
    reader = TrajectoryReader(path)
    if len(reader) == 0:
        raise ValueError(f"{path}: no frames to resume from")
    frame = reader.load_frame(len(reader) - 1)
    state = frame_to_state(frame, template_state)
    return state, frame.get("rng_state", []), reader
