"""Ensemble I/O: per-member trajectory writers + one aggregated metrics JSONL.

Each member gets its own reference-format trajectory
(`<out_dir>/<member_id>.out`, byte-compatible with `io.trajectory` — every
existing reader/paraview tool works per member), opened lazily on the
member's first frame so a 10k-member sweep holds file handles only for the
members currently in lanes. The aggregated metrics stream is one JSONL file
with lane/member/step records — the ensemble analogue of the run-loop
metrics JSONL (docs/performance.md), with `event` discriminating record
kinds (schema below + docs/ensemble.md; pinned by tests/test_ensemble.py).
"""

from __future__ import annotations

import json
import os
from typing import Optional

from .trajectory import TrajectoryWriter

#: keys of an ``event == "step"`` record, one per member trial step — the
#: sequential METRICS_FIELDS (system.system) plus the ensemble coordinates.
#: `wall_s`/`wall_ms` are the BATCHED round's wall time, shared by every
#: lane of that round — `round` is the shared-round id consumers must
#: dedupe wall sums by (`obs.summarize` does); `gmres_cycles`/
#: `gmres_history` are per member (docs/observability.md)
ENSEMBLE_STEP_FIELDS = ("event", "member", "lane", "round", "step", "t",
                        "dt", "iters", "gmres_cycles", "residual",
                        "residual_true", "fiber_error", "accepted",
                        "refines", "loss_of_accuracy", "health",
                        "guard_retries", "nucleations", "catastrophes",
                        "active_fibers", "wall_s", "wall_ms",
                        "gmres_history", "flight")

#: keys of an ``event == "start"`` record (member entered a lane);
#: ``queue_wait_s`` is the admission latency (queue entry -> lane seat) —
#: the serving SLO skelly-serve's /stats aggregates
ENSEMBLE_START_FIELDS = ("event", "member", "lane", "t", "t_final",
                         "queue_wait_s")

#: keys of an ``event == "retire"`` record (lane freed at t_final)
ENSEMBLE_RETIRE_FIELDS = ("event", "member", "lane", "t", "steps", "frames")

#: keys of an ``event == "failed"`` / ``"dt_underflow"`` record (lane
#: quarantined/frozen): the retire keys plus the packed health word, its
#: decoded bit names (`guard.verdict` — docs/robustness.md), and the
#: flight recorder's blast-radius payload — ``{"tail": [decoded rows...],
#: "provenance": {field, fiber, node} | None}`` (`obs.flight
#: .failure_payload`; None at `Params.flight_window == 0`) — the
#: diagnostics trajectory INTO the fault plus the first nonfinite's
#: coordinates (docs/observability.md "Flight recorder")
ENSEMBLE_FAILURE_FIELDS = ENSEMBLE_RETIRE_FIELDS + ("health", "verdict",
                                                    "flight")

#: keys of an ``event == "growth"`` record: a dynamic-instability member's
#: nucleation outgrew its fiber ``capacity`` bucket — the lane froze
#: un-advanced and the member reseats onto the next capacity rung
#: (scenarios.sweep / skelly-serve; docs/scenarios.md "Growth reseats")
ENSEMBLE_GROWTH_FIELDS = ENSEMBLE_RETIRE_FIELDS + ("capacity",)


class EnsembleMetricsWriter:
    """Append ensemble records as JSON lines; usable as the scheduler's
    ``metrics`` callable."""

    def __init__(self, path: str, *, append: bool = False):
        self.path = path
        self._fh = open(path, "a" if append else "w")

    def write(self, record: dict):
        self._fh.write(json.dumps(record) + "\n")
        self._fh.flush()

    __call__ = write

    def close(self):
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class MemberTrajectoryWriters:
    """Per-member trajectory files under one directory; usable as the
    scheduler's ``writer`` callable.

    Handles open lazily (first frame) and close on `close_member` /
    `close`, so the live handle count tracks the lane count, not the sweep
    size. Existing member files are refused unless ``overwrite`` — the
    single-run CLI's no-clobber guard, per member.
    """

    def __init__(self, out_dir: str, *, overwrite: bool = False):
        self.out_dir = out_dir
        self.overwrite = overwrite
        os.makedirs(out_dir, exist_ok=True)
        self._writers: dict = {}

    def path(self, member_id: str) -> str:
        return os.path.join(self.out_dir, f"{member_id}.out")

    def _writer(self, member_id: str) -> TrajectoryWriter:
        w = self._writers.get(member_id)
        if w is None:
            path = self.path(member_id)
            if os.path.exists(path) and not self.overwrite:
                raise FileExistsError(
                    f"member trajectory '{path}' already exists; pass "
                    "overwrite=True (or the CLI's --overwrite) to replace it")
            w = self._writers[member_id] = TrajectoryWriter(path)
        return w

    def write_frame(self, member_id: str, state, *,
                    rng_state: Optional[list] = None):
        self._writer(member_id).write_frame(state, rng_state=rng_state)

    __call__ = write_frame

    def close_member(self, member_id: str):
        w = self._writers.pop(member_id, None)
        if w is not None:
            w.close()

    def close(self):
        for member_id in list(self._writers):
            self.close_member(member_id)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
