"""Literal NumPy transcription of the reference FiberFiniteDifference math.

Test oracle only. Follows `/root/reference/src/core/fiber_finite_difference.cpp`
statement-by-statement in the reference's columns-as-points [3, n] layout, so a
discrepancy with the idiomatic JAX implementation indicates a transcription bug
in one of the two. Not used by the framework itself.
"""

import numpy as np

from skellysim_tpu.ops.finite_diff import barycentric_matrix, finite_diff


class RefMats:
    def __init__(self, n):
        self.alpha = np.linspace(-1, 1, n)
        nr = n - 4
        self.alpha_roots = 2 * (0.5 + np.arange(nr)) / nr - 1
        nt = n - 2
        self.alpha_tension = 2 * (0.5 + np.arange(nt)) / nt - 1
        # pre-transposed like the reference (D_1_0 etc.)
        self.D_1_0 = finite_diff(self.alpha, 1, 5).T
        self.D_2_0 = finite_diff(self.alpha, 2, 6).T
        self.D_3_0 = finite_diff(self.alpha, 3, 7).T
        self.D_4_0 = finite_diff(self.alpha, 4, 8).T
        self.P_X = barycentric_matrix(self.alpha, self.alpha_roots)
        self.P_T = barycentric_matrix(self.alpha, self.alpha_tension)
        self.weights_0 = np.full(n, 2.0)
        self.weights_0[[0, -1]] = 1.0
        self.weights_0 /= n - 1
        P = np.zeros((4 * n - 14, 4 * n))
        P[0 * (n - 4):1 * (n - 4), 0 * n:1 * n] = self.P_X
        P[1 * (n - 4):2 * (n - 4), 1 * n:2 * n] = self.P_X
        P[2 * (n - 4):3 * (n - 4), 2 * n:3 * n] = self.P_X
        P[3 * (n - 4):3 * (n - 4) + nt, 3 * n:4 * n] = self.P_T
        self.P_downsample_bc = P


class RefFiber:
    """BCs: 'velocity' (clamped) or 'force' (free) minus end; plus end
    'velocity' (hinged) or 'force'."""

    def __init__(self, x, length, bending_rigidity, radius, eta,
                 length_prev=None, penalty=500.0, beta_tstep=1.0, v_growth=0.0):
        self.x = np.asarray(x)          # [3, n]
        self.n = self.x.shape[1]
        self.mats = RefMats(self.n)
        self.L = length
        self.L_prev = length if length_prev is None else length_prev
        self.E = bending_rigidity
        self.radius = radius
        self.eta = eta
        self.penalty = penalty
        self.beta = beta_tstep
        self.v_growth = v_growth
        eps = radius / length
        self.c0 = -np.log(np.e * eps**2) / (8 * np.pi * eta)
        self.c1 = 2.0 / (8 * np.pi * eta)
        self.update_derivatives()

    def update_derivatives(self):
        m = self.mats
        self.xs = (2.0 / self.L_prev) * self.x @ m.D_1_0
        self.xss = (2.0 / self.L_prev) ** 2 * self.x @ m.D_2_0
        self.xsss = (2.0 / self.L_prev) ** 3 * self.x @ m.D_3_0
        self.xssss = (2.0 / self.L_prev) ** 4 * self.x @ m.D_4_0

    def update_linear_operator(self, dt):
        n = self.n
        m = self.mats
        D1 = m.D_1_0.T * (2.0 / self.L)
        D2 = m.D_2_0.T * (2.0 / self.L) ** 2
        D3 = m.D_3_0.T * (2.0 / self.L) ** 3
        D4 = m.D_4_0.T * (2.0 / self.L) ** 4
        I = np.eye(n)
        xs, xss, xsss = self.xs, self.xss, self.xsss
        E, c0, c1 = self.E, self.c0, self.c1
        A = np.zeros((4 * n, 4 * n))

        def blk(i, j):
            return A[i * n:(i + 1) * n, j * n:(j + 1) * n]

        for i in range(3):
            blk(i, i)[:] = self.beta / dt * I \
                + E * c0 * ((1 + xs[i] ** 2)[:, None] * D4) \
                + E * c1 * ((1 - xs[i] ** 2)[:, None] * D4)
        for i, j in [(0, 1), (0, 2), (1, 2)]:
            blk(i, j)[:] = E * (c0 - c1) * ((xs[i] * xs[j])[:, None] * D4)
            blk(j, i)[:] = blk(i, j)
        for i in range(3):
            blk(i, 3)[:] = -(2 * c0) * (xs[i][:, None] * D1) - (c0 + c1) * np.diag(xss[i])
            blk(3, i)[:] = -(c1 + 7 * c0) * E * (xss[i][:, None] * D4) \
                - 6 * c0 * E * (xsss[i][:, None] * D3) \
                - self.penalty * (xs[i][:, None] * D1)
        blk(3, 3)[:] = -2 * c0 * D2 + (c0 + c1) * np.diag((xss ** 2).sum(axis=0))
        self.A = A

    def update_RHS(self, dt, flow, f_external):
        n = self.n
        m = self.mats
        D_1 = m.D_1_0 * (2.0 / self.L)
        xs = self.xs
        s_dot = (1.0 + m.alpha) * 0.5 * self.v_growth
        RHS = np.zeros(4 * n)
        for i in range(3):
            RHS[i * n:(i + 1) * n] = self.x[i] / dt + s_dot * xs[i]
        RHS[3 * n:] = -self.penalty
        if flow is not None:
            for i in range(3):
                RHS[i * n:(i + 1) * n] += flow[i]
            RHS[3 * n:] += sum(xs[i] * (flow[i] @ D_1) for i in range(3))
        if f_external is not None:
            f = f_external
            fs = f @ D_1
            c0, c1 = self.c0, self.c1
            xsf = sum(xs[i] * f[i] for i in range(3))
            for i in range(3):
                RHS[i * n:(i + 1) * n] += c0 * (f[i] + xs[i] * xsf) + c1 * (f[i] - xs[i] * xsf)
            RHS[3 * n:] += 2 * c0 * sum(xs[i] * fs[i] for i in range(3))
            RHS[3 * n:] += (c0 - c1) * sum(self.xss[i] * f[i] for i in range(3))
        self.RHS = RHS

    def apply_bc_rectangular(self, dt, v_on_fiber, f_on_fiber, bc_minus, bc_plus):
        n = self.n
        m = self.mats
        D_1 = m.D_1_0.T * (2.0 / self.L)
        D_2 = m.D_2_0.T * (2.0 / self.L) ** 2
        D_3 = m.D_3_0.T * (2.0 / self.L) ** 3
        E, c0 = self.E, self.c0
        xs, xss = self.xs, self.xss

        A = np.zeros_like(self.A)
        A[:4 * n - 14] = m.P_downsample_bc @ self.A
        RHS = np.zeros_like(self.RHS)
        RHS[:4 * n - 14] = m.P_downsample_bc @ self.RHS
        B = A[4 * n - 14:]
        B_RHS = RHS[4 * n - 14:]

        v0 = v_on_fiber[:, 0] if v_on_fiber is not None else np.zeros(3)
        ve = v_on_fiber[:, -1] if v_on_fiber is not None else np.zeros(3)
        f0 = f_on_fiber[:, 0] if f_on_fiber is not None else np.zeros(3)
        fe = f_on_fiber[:, -1] if f_on_fiber is not None else np.zeros(3)

        if bc_minus == "velocity":
            B[0, 0 * n] = self.beta / dt
            B[1, 1 * n] = self.beta / dt
            B[2, 2 * n] = self.beta / dt
            for i in range(3):
                B[3, i * n:(i + 1) * n] = 6 * E * c0 * xss[i, 0] * D_3[0]
            B[3, 3 * n:] = 2 * c0 * D_1[0]
            B_RHS[0:3] = self.x[:, 0] / dt
            B_RHS[3] = -xs[:, 0] @ v0 - 2 * c0 * (xs[:, 0] @ f0)
        else:
            for i in range(3):
                B[i, i * n:(i + 1) * n] = E * D_3[0]
                B[i, 3 * n] = -xs[i, 0]
                B[3, i * n:(i + 1) * n] = -E * D_2[0] * xss[i, 0]
            B[3, 3 * n] = -1.0
            B_RHS[0:3] = f0
            B_RHS[3] = f0 @ xs[:, 0]

        if bc_minus == "velocity":  # AngularVelocity
            for i in range(3):
                B[4 + i, i * n:(i + 1) * n] = self.beta / dt * D_1[0]
            B_RHS[4:7] = xs[:, 0] / dt
        else:  # Torque
            for i in range(3):
                B[4 + i, i * n:(i + 1) * n] = D_2[0]
            B_RHS[4:7] = 0.0

        if bc_plus == "velocity":
            B[7, 1 * n - 1] = self.beta / dt
            B[8, 2 * n - 1] = self.beta / dt
            B[9, 3 * n - 1] = self.beta / dt
            for i in range(3):
                B[10, i * n:(i + 1) * n] = 6 * E * c0 * D_3[-1] * xss[i, -1]
            B[10, 3 * n:] = 2 * c0 * D_1[-1]
            B_RHS[7:10] = self.x[:, -1] / dt
            B_RHS[10] = -xs[:, -1] @ ve - 2 * c0 * (xs[:, -1] @ fe)
        else:
            for i in range(3):
                B[7 + i, i * n:(i + 1) * n] = -E * D_3[-1]
                B[7 + i, 4 * n - 1] = xs[i, -1]
                B[10, i * n:(i + 1) * n] = E * D_2[-1] * xss[i, -1]
            B[10, 4 * n - 1] = 1.0
            B_RHS[7:10] = fe
            B_RHS[10] = fe @ xs[:, -1]

        for i in range(3):  # plus Torque (always)
            B[11 + i, i * n:(i + 1) * n] = D_2[-1]
        B_RHS[11:14] = 0.0

        self.A_bc = A
        self.RHS_bc = RHS

    def update_force_operator(self):
        n = self.n
        m = self.mats
        D_1 = m.D_1_0 * (2.0 / self.L)
        D_4 = m.D_4_0 * (2.0 / self.L) ** 4
        fo = np.zeros((3 * n, 4 * n))
        for i in range(3):
            fo[i * n:(i + 1) * n, i * n:(i + 1) * n] = -self.E * D_4.T
            fo[i * n:(i + 1) * n, 3 * n:] += np.diag(self.xss[i])
            fo[i * n:(i + 1) * n, 3 * n:] += (D_1 * self.xs[i][None, :]).T
        self.force_operator = fo

    def matvec(self, xvec, v, v_boundary, bc_plus):
        n = self.n
        m = self.mats
        bc_start = 4 * n - 14
        D_1 = m.D_1_0 * (2.0 / self.L_prev)
        xsDs = (D_1 * self.xs[0][:, None]).T
        ysDs = (D_1 * self.xs[1][:, None]).T
        zsDs = (D_1 * self.xs[2][:, None]).T
        vT = np.zeros(4 * n)
        vT[0 * n:1 * n] = v[0]
        vT[1 * n:2 * n] = v[1]
        vT[2 * n:3 * n] = v[2]
        vT[3 * n:] = xsDs @ v[0] + ysDs @ v[1] + zsDs @ v[2]
        vT_in = np.zeros(4 * n)
        vT_in[:bc_start] = m.P_downsample_bc @ vT
        xs_vT = np.zeros(4 * n)
        xs_vT[bc_start + 3] = v[:, 0] @ self.xs[:, 0]
        if bc_plus == "velocity":
            xs_vT[bc_start + 10] = v[:, -1] @ self.xs[:, -1]
        y_BC = np.zeros(4 * n)
        if v_boundary is not None:
            y_BC[bc_start:bc_start + 7] = v_boundary
        return self.A_bc @ xvec - vT_in + xs_vT + y_BC
