"""Periphery tests: quadrature accuracy, operator consistency, and an analytic
interior-Stokes oracle.

The physics oracle: a point force F at the center of a rigid no-slip sphere of
radius R has the closed-form interior solution (Stokeslet + stokeson + uniform
completion; classical Lorenz-type result)

    u(x) = k (F/r + (F.x)x/r^3) - (k/R^3)((F.x)x - 2 r^2 F) - (3k/R) F,
    k = 1/(8 pi eta),

which vanishes identically on r = R. The solved shell density must reproduce
this field at interior points to quadrature accuracy.
"""

import numpy as np
import pytest
from scipy.spatial import ConvexHull

import jax.numpy as jnp

from skellysim_tpu.ops import kernels
from skellysim_tpu.params import Params
from skellysim_tpu.periphery import (periphery as peri, sphere_shape,
                                     surface_quadrature_weights)
from skellysim_tpu.periphery.periphery import PeripheryShape
from skellysim_tpu.system import PointSources, System


def build_sphere_shell(n_nodes, radius, eta=1.0):
    spec = sphere_shape(n_nodes, radius=radius)
    normals = -spec.node_normals  # inward, periphery convention
    tris = ConvexHull(spec.nodes).simplices
    weights = surface_quadrature_weights(spec.nodes, tris, spec.gradh)
    operator, M_inv = peri.build_shell_operator(spec.nodes, normals, weights, eta=eta)
    return peri.make_state(spec.nodes, normals, weights, operator, M_inv)


def test_quadrature_sphere_area():
    spec = sphere_shape(400, radius=1.3)
    tris = ConvexHull(spec.nodes).simplices
    w = surface_quadrature_weights(spec.nodes, tris, spec.gradh)
    exact = 4 * np.pi * 1.3**2
    assert abs(w.sum() - exact) / exact < 5e-5


def test_shell_operator_inverse_consistent():
    shell = build_sphere_shell(200, radius=1.0)
    M = np.asarray(shell.stresslet_plus_complementary)
    M_inv = np.asarray(shell.M_inv)
    err = np.abs(M @ M_inv - np.eye(M.shape[0])).max()
    assert err < 1e-8, err


def analytic_center_force(points, R, eta, F):
    k = 1.0 / (8 * np.pi * eta)
    r = np.linalg.norm(points, axis=1)
    Fx = points @ F
    u = k * (F[None, :] / r[:, None] + Fx[:, None] * points / r[:, None] ** 3)
    u -= (k / R**3) * (Fx[:, None] * points - 2 * (r**2)[:, None] * F[None, :])
    u -= (3 * k / R) * F[None, :]
    return u


def test_point_force_in_sphere_analytic():
    eta = 1.1
    R = 2.0
    F = np.array([0.0, 0.0, 1.0])
    shell = build_sphere_shell(700, radius=R, eta=eta)

    # RHS: the shell cancels the point-source slip velocity at its nodes
    v_shell = np.asarray(kernels.oseen_contract(
        np.zeros((1, 3)), shell.nodes, F[None, :], eta))
    rhs = -v_shell.reshape(-1)

    # solve the second-kind system directly with the precomputed inverse
    density = jnp.asarray(np.asarray(shell.M_inv) @ rhs)

    rng = np.random.default_rng(3)
    pts = rng.uniform(-0.9, 0.9, size=(20, 3))
    pts = pts[np.linalg.norm(pts, axis=1) > 0.4]

    u_ps = np.asarray(kernels.oseen_contract(np.zeros((1, 3)), pts, F[None, :], eta))
    u_shell = np.asarray(peri.flow(shell, jnp.asarray(pts), density, eta))
    u_total = u_ps + u_shell
    u_exact = analytic_center_force(pts, R, eta, F)

    scale = np.abs(u_exact).max()
    err = np.abs(u_total - u_exact).max() / scale
    assert err < 1e-4, err


def test_system_solve_with_shell_matches_direct_inverse():
    """GMRES through the coupled System must reproduce the direct M_inv solve."""
    eta = 1.0
    R = 1.5
    shell = build_sphere_shell(300, radius=R, eta=eta)
    params = Params(eta=eta, dt_initial=1e-3, t_final=1e-3, gmres_tol=1e-12,
                    adaptive_timestep_flag=False)
    system = System(params, shell_shape=PeripheryShape(kind="sphere", radius=R))
    points = PointSources.make(position=[[0.3, 0.0, 0.0]], force=[[0.0, 0.0, 1.0]])
    state = system.make_state(points=points, shell=shell)

    new_state, solution, info = system.step(state)
    assert bool(info.converged)

    v_shell = np.asarray(points.flow(shell.nodes, eta, 0.0))
    direct = np.asarray(shell.M_inv) @ (-v_shell.reshape(-1))
    np.testing.assert_allclose(np.asarray(solution), direct, rtol=1e-8, atol=1e-10)


def test_fiber_steric_force_direction():
    shape = PeripheryShape(kind="sphere", radius=1.0)
    pts = jnp.asarray([[0.0, 0.0, 0.97], [0.0, 0.0, 0.2]])
    f = peri.fiber_steric_force(shape, pts, 20.0, 0.05, skip_first=jnp.asarray(False))
    f = np.asarray(f)
    assert f[0, 2] < 0.0          # pushes the near-wall node inward
    assert abs(f[0, 2]) > abs(f[1, 2])  # decays away from the wall


def test_collision_detection():
    shape = PeripheryShape(kind="sphere", radius=1.0)
    inside = jnp.asarray([[0.0, 0.0, 0.5]])
    outside = jnp.asarray([[0.0, 0.0, 1.01]])
    assert not bool(peri.check_collision(shape, inside, 0.0))
    assert bool(peri.check_collision(shape, outside, 0.0))


def test_fiber_inside_shell_coupled_solve():
    """Fiber + periphery coupled matvec converges and keeps the fiber inside."""
    from skellysim_tpu.fibers import container as fc

    eta = 1.0
    R = 2.0
    shell = build_sphere_shell(300, radius=R, eta=eta)
    params = Params(eta=eta, dt_initial=1e-3, t_final=2e-3, gmres_tol=1e-10,
                    adaptive_timestep_flag=False, periphery_interaction_flag=True)
    system = System(params, shell_shape=PeripheryShape(kind="sphere", radius=R))

    t = np.linspace(0, 1, 16)
    x = np.stack([0.8 * t, np.zeros(16), np.zeros(16)], axis=1)[None]
    fibers = fc.make_group(x, lengths=0.8, bending_rigidity=0.01, radius=0.0125)
    points = PointSources.make(position=[[0.0, 0.5, 0.0]], force=[[1.0, 0.0, 0.0]])
    state = system.make_state(fibers=fibers, points=points, shell=shell)

    new_state, _, info = system.step(state)
    assert bool(info.converged)
    assert float(info.fiber_error) < 0.05
    assert not bool(system._collision_jit(new_state))
    # the shell density actually responded to the flow
    assert float(jnp.linalg.norm(new_state.shell.density)) > 0.0
