"""Fiber operator assembly vs a literal NumPy transcription of the reference.

Two independent transcriptions of `fiber_finite_difference.cpp` (the idiomatic
JAX one in skellysim_tpu.fibers.fd_fiber, layout [n, 3]; the literal Eigen-layout
one in tests/ref_fiber.py) must agree to roundoff on A, RHS, BC rows, force
operator, and matvec for both boundary-condition settings.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from skellysim_tpu.fibers import fd_fiber, get_mats
from tests.ref_fiber import RefFiber

N = 16
ETA = 1.3
DT = 0.013
LENGTH = 2.1
LENGTH_PREV = 2.05
E_BEND = 0.05
RADIUS = 0.0125


def make_fiber_x(n=N, seed=0):
    """Smooth, slightly bent fiber: arc with small perturbation."""
    rng = np.random.default_rng(seed)
    t = np.linspace(0, 1, n)
    x = np.stack([
        LENGTH * t,
        0.1 * np.sin(2.0 * t),
        0.05 * t**2 + 0.02 * np.cos(3 * t),
    ], axis=1)
    return x + 1e-3 * rng.standard_normal((n, 3))


def scalars(v_growth=0.0):
    return fd_fiber.FiberScalars(
        length=jnp.asarray(LENGTH), length_prev=jnp.asarray(LENGTH_PREV),
        bending_rigidity=jnp.asarray(E_BEND), radius=jnp.asarray(RADIUS),
        penalty=jnp.asarray(500.0), beta_tstep=jnp.asarray(1.0),
        v_growth=jnp.asarray(v_growth))


def ref_fiber(x, v_growth=0.0):
    return RefFiber(x.T, LENGTH, E_BEND, RADIUS, ETA,
                    length_prev=LENGTH_PREV, v_growth=v_growth)


@pytest.mark.parametrize("minus_clamped,plus_pinned",
                         [(False, False), (True, False), (False, True), (True, True)])
def test_operator_rhs_bc_match_reference(minus_clamped, plus_pinned):
    x = make_fiber_x()
    mats = get_mats(N)
    sc = scalars(v_growth=0.7)
    rng = np.random.default_rng(7)
    flow = rng.standard_normal((N, 3))
    f_ext = rng.standard_normal((N, 3))

    xs, xss, xsss, _ = fd_fiber.derivatives(jnp.asarray(x), sc.length_prev, mats)
    A = fd_fiber.build_A(xs, xss, xsss, DT, ETA, sc, mats)
    RHS = fd_fiber.build_RHS(jnp.asarray(x), xs, xss, DT, ETA, sc, mats,
                             flow=jnp.asarray(flow), f_external=jnp.asarray(f_ext))
    A_bc, RHS_bc = fd_fiber.apply_bc_rectangular(
        A, RHS, jnp.asarray(x), xs, xss, DT, ETA, sc, mats,
        minus_clamped, plus_pinned,
        v_on_fiber=jnp.asarray(flow), f_on_fiber=jnp.asarray(f_ext))

    ref = ref_fiber(x, v_growth=0.7)
    ref.update_linear_operator(DT)
    ref.update_RHS(DT, flow.T, f_ext.T)
    ref.apply_bc_rectangular(DT, flow.T, f_ext.T,
                             "velocity" if minus_clamped else "force",
                             "velocity" if plus_pinned else "force")

    # tolerances are relative to the matrix scale: D4 entries reach ~1e6, so
    # float ordering differences between the two transcriptions give ~1e-7 abs
    def close(got, want):
        scale = np.abs(want).max()
        np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-11 * scale)

    close(np.asarray(A), ref.A)
    close(np.asarray(A_bc), ref.A_bc)
    close(np.asarray(RHS_bc), ref.RHS_bc)


def test_force_operator_matches_reference():
    x = make_fiber_x(seed=2)
    mats = get_mats(N)
    sc = scalars()
    xs, xss, _, _ = fd_fiber.derivatives(jnp.asarray(x), sc.length_prev, mats)
    fo = fd_fiber.force_operator(xs, xss, ETA, sc, mats)

    ref = ref_fiber(x)
    ref.update_force_operator()
    np.testing.assert_allclose(np.asarray(fo), ref.force_operator, rtol=1e-12, atol=1e-12)


@pytest.mark.parametrize("plus_pinned", [False, True])
def test_matvec_matches_reference(plus_pinned):
    x = make_fiber_x(seed=3)
    mats = get_mats(N)
    sc = scalars()
    rng = np.random.default_rng(11)
    xvec = rng.standard_normal(4 * N)
    v = rng.standard_normal((N, 3))
    v_bdy = rng.standard_normal(7)

    xs, xss, xsss, _ = fd_fiber.derivatives(jnp.asarray(x), sc.length_prev, mats)
    A = fd_fiber.build_A(xs, xss, xsss, DT, ETA, sc, mats)
    RHS = fd_fiber.build_RHS(jnp.asarray(x), xs, xss, DT, ETA, sc, mats)
    A_bc, _ = fd_fiber.apply_bc_rectangular(
        A, RHS, jnp.asarray(x), xs, xss, DT, ETA, sc, mats, False, plus_pinned)
    got = fd_fiber.matvec(A_bc, jnp.asarray(xvec), jnp.asarray(v),
                          jnp.asarray(v_bdy), xs, sc, mats, plus_pinned)

    ref = ref_fiber(x)
    ref.update_linear_operator(DT)
    ref.update_RHS(DT, None, None)
    ref.apply_bc_rectangular(DT, None, None, "force",
                             "velocity" if plus_pinned else "force")
    want = ref.matvec(xvec, v.T, v_bdy, "velocity" if plus_pinned else "force")
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-9, atol=1e-9 * np.abs(want).max())


def test_fiber_error_straight_fiber_zero():
    n = 16
    t = np.linspace(0, 1, n)
    x = np.stack([LENGTH * t, np.zeros(n), np.zeros(n)], axis=1)
    err = fd_fiber.fiber_error(jnp.asarray(x), LENGTH, get_mats(n))
    assert float(err) < 1e-12
