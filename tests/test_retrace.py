"""Retrace-count regression: the runtime companion of skelly-lint.

The static pass (`skellysim_tpu.lint`) catches dtype/trace/sharding drift at
review time; `testing.trace_counting_jit` catches the symptom at run time —
a retrace means an argument's static signature changed between calls
(Python scalar vs jnp scalar, dtype flip, shape change), and every retrace
pays full XLA compilation inside the time loop.
"""

import jax.numpy as jnp

from skellysim_tpu.testing import trace_counting_jit


def test_trace_counting_jit_counts():
    calls = trace_counting_jit(lambda x: x * 2.0)
    a = jnp.ones(4, dtype=jnp.float32)
    calls(a)
    calls(a + 1.0)
    assert calls.trace_count == 1, "same signature must not retrace"
    calls(jnp.ones(5, dtype=jnp.float32))
    assert calls.trace_count == 2, "new shape must retrace"


def test_system_step_traces_once_across_same_shape_calls():
    """The top-level implicit step compiles exactly once for a fixed state
    signature: stepping the stepped state (same shapes/dtypes, new values)
    must reuse the compiled program. A failure here means something in
    `_solve_impl`'s closure leaks a trace-time-varying static (the
    per-step-recompile failure mode the adaptive loop cannot afford)."""
    from __graft_entry__ import _make_system

    system, state = _make_system(n_fibers=2, n_nodes=16, dtype=jnp.float32)
    step = trace_counting_jit(system._solve_impl,
                              static_argnames=("pair",))
    new_state, _, info = step(state)
    assert bool(info.converged)
    assert step.trace_count == 1

    # same pytree structure, same shapes/dtypes, different values
    new_state, _, _ = step(new_state)
    assert step.trace_count == 1, (
        "top-level system step retraced on a same-shape state")
