"""On-device shell-operator precompute: parity with the host/scipy path.

The reference precomputes the dense second-kind shell operator on the host
and inverts it with LAPACK (`/root/reference/src/skelly_sim/precompute.py:113-133`
— the O(N^3) pole of the whole precompute). `periphery.build_shell_operator_device`
moves assembly + inverse onto the accelerator; these tests pin that the device
path produces the SAME operator (same math, same kernels, different execution
placement) and a preconditioner-grade inverse, including through the recursive
Schur-complement blocking that replaces the single big LU on TPU.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from skellysim_tpu.periphery import precompute as pc
from skellysim_tpu.periphery.periphery import (
    block_inv,
    build_shell_operator,
    build_shell_operator_device,
)
from skellysim_tpu.periphery.shapes import sphere_shape


@pytest.fixture(scope="module")
def small_shell():
    spec = sphere_shape(120, radius=2.0)
    nodes = spec.nodes
    normals = -spec.node_normals
    weights = np.full(len(nodes), 4 * np.pi * 2.0**2 / len(nodes))
    return nodes, normals, weights


def test_device_operator_matches_host(small_shell):
    nodes, normals, weights = small_shell
    M_host, _ = build_shell_operator(nodes, normals, weights)
    M_dev, M_inv = build_shell_operator_device(nodes, normals, weights,
                                               op_dtype=jnp.float64,
                                               inv_dtype=jnp.float64)
    assert np.linalg.norm(M_dev - M_host) / np.linalg.norm(M_host) < 1e-12
    resid = np.linalg.norm(M_dev @ M_inv - np.eye(M_dev.shape[0]), ord="fro")
    assert resid < 1e-8


def test_block_inv_recursion_matches_direct(small_shell):
    nodes, normals, weights = small_shell
    M, _ = build_shell_operator_device(nodes, normals, weights,
                                       op_dtype=jnp.float64,
                                       inv_dtype=jnp.float64)
    M = jnp.asarray(M)
    # force two levels of Schur recursion (360 rows > 100 > 50)
    blocked = np.asarray(block_inv(M, max_direct=100))
    direct = np.asarray(jnp.linalg.inv(M))
    # preconditioner-grade agreement: identical math up to blocked roundoff
    assert np.linalg.norm(blocked - direct) / np.linalg.norm(direct) < 1e-9


def test_f32_inverse_is_preconditioner_grade(small_shell):
    nodes, normals, weights = small_shell
    M, M_inv = build_shell_operator_device(nodes, normals, weights,
                                           op_dtype=jnp.float64,
                                           inv_dtype=jnp.float32)
    assert M_inv.dtype == np.float32
    n = M.shape[0]
    resid = np.linalg.norm(M @ M_inv.astype(np.float64) - np.eye(n),
                           ord="fro") / np.sqrt(n)
    # f32 inverse: rows apply to ~f32 eps — plenty for a right preconditioner
    assert resid < 1e-4


def test_precompute_periphery_device_backend(small_shell):
    out_host = pc.precompute_periphery("sphere", 120, radius=2.0,
                                       operator_backend="host")
    out_dev = pc.precompute_periphery("sphere", 120, radius=2.0,
                                      operator_backend="device")
    assert set(out_dev) == set(out_host)
    np.testing.assert_allclose(out_dev["nodes"], out_host["nodes"])
    d = np.linalg.norm(out_dev["stresslet_plus_complementary"]
                       - out_host["stresslet_plus_complementary"])
    assert d / np.linalg.norm(out_host["stresslet_plus_complementary"]) < 1e-12
    with pytest.raises(ValueError):
        pc.precompute_periphery("sphere", 120, radius=2.0,
                                operator_backend="gpu")
