"""skelly-fence tests (`skellysim_tpu.audit.dmaflow`, the ``dma`` check).

The acceptance battery: synthetic racy ring kernels — each seeding ONE
discipline break next to a disciplined twin — flip the `--check dma` CLI
to exit 1 while the twin exits 0; the entry-only-barrier counterexample is
*derived* by the explicit-state model (safe on a 3-ring, hazardous on a
4-ring — which is why the model runs at 4); contract drift/stale/
suppression paths mirror test_audit.py's discipline; and the VMEM budget
is consumed by `fused_ring_fits` and the verifier from ONE definition
(perturbing it flips both together).

The racy kernels are TRACED only, never executed — same as the real fused
rings on CPU CI, which is the entire reason the verifier exists.
"""

import jax
import jax.numpy as jnp
import pytest
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from skellysim_tpu.audit import dmaflow, engine
from skellysim_tpu.audit.cli import main as audit_main
from skellysim_tpu.audit.registry import AuditKernel, BuiltKernel
from skellysim_tpu.config import toml_io
from skellysim_tpu.parallel.compat import shard_map
from skellysim_tpu.parallel.mesh import FIBER_AXIS, make_mesh

N_DEV = 4
ROWS, NS = 8, 128


def _ring_variant(variant, n_dev=N_DEV):
    """A minimal fused-ring-shaped kernel body; ``variant`` seeds exactly
    one discipline break ("clean" is the disciplined twin)."""

    def kernel(blk_ref, out_ref, comm, send_sem, recv_sem):
        my = lax.axis_index(FIBER_AXIS)
        right = lax.rem(my + 1, n_dev)
        left = lax.rem(my + n_dev - 1, n_dev)
        comm[0] = blk_ref[:]
        out_ref[:] = jnp.zeros_like(out_ref)

        def barrier():
            bar = pltpu.get_barrier_semaphore()
            for nb in (left, right):
                pltpu.semaphore_signal(
                    bar, inc=1, device_id=nb,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
            pltpu.semaphore_wait(bar, 1 if variant == "unbalanced" else 2)

        barrier()
        for step in range(n_dev):
            rdma = None
            if step < n_dev - 1:
                rdma = pltpu.make_async_remote_copy(
                    src_ref=comm.at[step], dst_ref=comm.at[step + 1],
                    send_sem=send_sem.at[step],
                    recv_sem=recv_sem.at[step + 1], device_id=right,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
                rdma.start()
                if variant == "overwrite-src":
                    comm[step] = blk_ref[:]   # clobbers the in-flight src
            out_ref[:] += comm[step]
            if rdma is not None:
                if variant == "missing-recv-wait":
                    rdma.wait_send()          # recv credit never consumed
                else:
                    rdma.wait()
        if variant != "entry-only":
            barrier()

    return kernel


def _built(variant, n_dev=N_DEV):
    def local(blk):
        return pl.pallas_call(
            _ring_variant(variant, n_dev),
            out_shape=jax.ShapeDtypeStruct((ROWS, NS), jnp.float32),
            scratch_shapes=(
                pltpu.VMEM((n_dev, ROWS, NS), jnp.float32),
                pltpu.SemaphoreType.DMA((n_dev,)),
                pltpu.SemaphoreType.DMA((n_dev,)),
            ),
            compiler_params=pltpu.TPUCompilerParams(collective_id=7),
        )(blk)

    f = shard_map(local, mesh=make_mesh(n_dev),
                  in_specs=(P(None, FIBER_AXIS),),
                  out_specs=P(None, FIBER_AXIS))
    closed = jax.make_jaxpr(f)(
        jnp.zeros((ROWS, NS * n_dev), jnp.float32))
    (kj, gm), = dmaflow.pallas_calls(closed.jaxpr)
    return BuiltKernel(kernel_jaxpr=kj, grid_mapping=gm, n_dev=n_dev,
                       scene={})


def _kern(built, name="syn_ring"):
    return AuditKernel(name=name, layer="test", summary="synthetic",
                       build=lambda: built)


def _kinds(report):
    return sorted({f.kind for f in report.findings})


# --------------------------------------------------------- analyzer direct

def test_clean_twin_verifies_with_skew_bound():
    rep = dmaflow.analyze(_built("clean"))
    assert rep.findings == []
    assert rep.observed["comm_slots"] == N_DEV
    assert rep.observed["remote_writes"] == N_DEV - 1
    assert rep.observed["barrier_signals"] == 4
    assert rep.observed["barrier_waits"] == 4
    # the model PROVES the entry+exit pairing bounds neighbor skew to 1
    assert rep.observed["phase_skew_bound"] == 1


def test_missing_recv_wait_is_read_before_arrival():
    rep = dmaflow.analyze(_built("missing-recv-wait"))
    kinds = _kinds(rep)
    assert dmaflow.KIND_READ in kinds          # unordered slot loads
    assert dmaflow.KIND_BALANCE in kinds       # recv credits unconsumed
    assert any("no preceding wait on its recv semaphore" in f.message
               for f in rep.findings)


def test_overwrite_of_inflight_source_is_flagged():
    rep = dmaflow.analyze(_built("overwrite-src"))
    assert dmaflow.KIND_OVERWRITE in _kinds(rep)
    assert any("no intervening send-semaphore wait" in f.message
               for f in rep.findings)


def test_entry_only_barrier_violation_is_derived_not_asserted():
    """The module-docstring counterexample, re-derived from the traced
    kernel: dropping the EXIT barrier must produce an overwrite finding
    whose message carries the model's interleaving witness."""
    rep = dmaflow.analyze(_built("entry-only"))
    hazards = [f for f in rep.findings if f.kind == dmaflow.KIND_OVERWRITE]
    assert hazards, _kinds(rep)
    assert any("derived interleaving" in f.message for f in hazards)
    # and the witness names concrete device steps, not prose
    assert any("send@inst" in f.message for f in hazards)


def test_unbalanced_barrier_credits_flagged():
    rep = dmaflow.analyze(_built("unbalanced"))
    assert dmaflow.KIND_BALANCE in _kinds(rep)
    assert any("signals 4 credit(s) ringwide but waits for 2" in f.message
               for f in rep.findings)


def test_over_budget_ring_shape_flagged():
    """The real fused-ring kernel traced at a shape `fused_ring_fits`
    rejects: the verifier's accounting must reject it too (same
    formula)."""
    from skellysim_tpu.parallel import ring_fused

    n_dev, n_trg, n_src = 4, 8, 1 << 17
    assert not ring_fused.fused_ring_fits("stokeslet", n_trg, n_src, n_dev)

    def local(r, s, w):
        return ring_fused.fused_ring_block_sum(
            "stokeslet", r, s, w, axis_name=FIBER_AXIS, n_dev=n_dev)

    f = shard_map(local, mesh=make_mesh(n_dev),
                  in_specs=(P(FIBER_AXIS),) * 3, out_specs=P(FIBER_AXIS))
    closed = jax.make_jaxpr(f)(
        jnp.zeros((n_trg * n_dev, 3), jnp.float32),
        jnp.zeros((n_src * n_dev, 3), jnp.float32),
        jnp.zeros((n_src * n_dev, 3), jnp.float32))
    (kj, gm), = dmaflow.pallas_calls(closed.jaxpr)
    rep = dmaflow.analyze(BuiltKernel(
        kernel_jaxpr=kj, grid_mapping=gm, n_dev=n_dev,
        scene={"kind": "stokeslet", "n_trg": n_trg, "n_src": n_src}))
    vmem = [f for f in rep.findings if f.kind == dmaflow.KIND_VMEM]
    assert vmem and "over budget" in vmem[0].message


# ------------------------------------------------- the model checker itself

def test_entry_only_counterexample_needs_the_4_ring():
    """Why `_MODEL_RING` is 4: on a 3-ring the victim itself gates the
    credit chain and entry-only is (coincidentally) safe; the 4-ring has
    the far-side fast chain that launders anonymous credits past the
    lagging victim."""
    sigs = ("sigs", ((1, 1), (-1, 1)))
    entry_only = (sigs, ("wait", 2), ("send",), ("read",))
    assert dmaflow._check_ring_protocol(entry_only, 3, 1)[0] is None
    hazard, _, _, truncated = dmaflow._check_ring_protocol(entry_only, 4, 1)
    assert hazard is not None and not truncated
    assert "has not finished" in hazard[-1]

    both = entry_only + (sigs, ("wait", 2))
    hazard, skew, deadlock, truncated = dmaflow._check_ring_protocol(
        both, 4, 1)
    assert hazard is None and deadlock is None and not truncated
    assert skew == 1


def test_model_detects_overwaiting_deadlock():
    sigs = ("sigs", ((1, 1), (-1, 1)))
    wedged = (sigs, ("wait", 3), ("send",), ("read",), sigs, ("wait", 2))
    hazard, _, deadlock, _ = dmaflow._check_ring_protocol(wedged, 4, 1)
    assert hazard is None and deadlock is not None


# ----------------------------------------------------- shared budget formula

def test_budget_perturbation_flips_builder_and_verifier_together(
        monkeypatch):
    """THE dedupe pin: one budget definition feeds `fused_ring_fits`
    (build-time eligibility) and `analyze` (verify-time gate). Shrinking
    it must flip both in the same breath."""
    from skellysim_tpu.parallel import ring_fused

    built = _built("clean")
    assert ring_fused.fused_ring_fits("stokeslet", ROWS, NS, N_DEV)
    assert dmaflow.analyze(built).findings == []

    monkeypatch.setattr(dmaflow, "VMEM_PAIR_BUDGET", 64)
    assert not ring_fused.fused_ring_fits("stokeslet", ROWS, NS, N_DEV)
    rep = dmaflow.analyze(built)
    assert any(f.kind == dmaflow.KIND_VMEM for f in rep.findings)


def test_footprint_formula_values():
    fp = dmaflow.fused_ring_footprint(3, 8, 8, 128)
    assert fp == {"pair_elems": 1024, "comm_floats": 8 * 6 * 128}
    assert dmaflow.gridded_footprint(256, 1024) == {"pair_elems": 262144}
    assert not dmaflow.gridded_within_budget(1024, 2048)


# ------------------------------------------------ contract / CLI discipline

def _contract_for(built, name):
    return toml_io.loads(engine.dump_kernel_contract(_kern(built, name)))


def test_contract_drift_stale_and_missing_pins():
    built = _built("clean")
    kern = _kern(built)
    good = _contract_for(built, "syn_ring")
    assert engine.run_kernel_audit(kern, contract=good) == []

    # The perturbed contracts below carry only a [dma] section, so pin the
    # dma surface in isolation (the mask check would flag their missing
    # [mask] table — its own contract surface has its own test).
    # no [dma] section at all
    f = engine.run_kernel_audit(kern, contract={}, checks=["dma"])
    assert len(f) == 1 and "[dma] contract section missing" in f[0].message
    # a drifted pin
    drift = {"dma": dict(good["dma"], comm_slots=7)}
    f = engine.run_kernel_audit(kern, contract=drift, checks=["dma"])
    assert len(f) == 1 and "comm_slots drifted" in f[0].message
    # a stale pin the analyzer no longer reports
    stale = {"dma": dict(good["dma"], retired_knob=3)}
    f = engine.run_kernel_audit(kern, contract=stale, checks=["dma"])
    assert len(f) == 1 and "stale pin `retired_knob`" in f[0].message
    # a missing pin for an observed key
    missing = {"dma": {k: v for k, v in good["dma"].items()
                       if k != "remote_writes"}}
    f = engine.run_kernel_audit(kern, contract=missing, checks=["dma"])
    assert len(f) == 1 and "no `remote_writes` pin" in f[0].message


def test_kernel_suppression_discipline():
    built = _built("entry-only")
    kern = _kern(built)
    base = _contract_for(built, "syn_ring")
    sup = dict(base, suppress=[{
        "check": "dma", "match": "derived interleaving",
        "reason": "fixture: the counterexample under test"}])
    assert engine.run_kernel_audit(kern, contract=sup) == []
    # an unused suppression is itself a finding (lint-pragma, contract-side)
    unused = dict(base, suppress=[{
        "check": "dma", "match": "no-such-finding",
        "reason": "stale"}])
    f = engine.run_kernel_audit(kern, contract=unused)
    assert any("unused suppression" in x.message for x in f)


def test_dump_contract_roundtrips_through_toml_io():
    built = _built("clean")
    text = engine.dump_kernel_contract(_kern(built))
    data = toml_io.loads(text)
    assert data["program"] == {"name": "syn_ring"}
    assert data["dma"] == dmaflow.analyze(built).observed


def test_racy_kernels_gate_the_cli_exit_code(tmp_path, monkeypatch):
    """The acceptance pin: every seeded violation class flips `--check
    dma` to exit 1; the disciplined twin exits 0. Contracts pin each
    kernel's own observed inventory so the ONLY findings are the seeded
    safety violations."""
    import skellysim_tpu.audit.kernels as kernels_mod

    def rc(variant):
        built = _built(variant)
        kern = _kern(built)
        monkeypatch.setattr(kernels_mod, "all_kernels", lambda: [kern])
        monkeypatch.setattr(engine, "CONTRACT_DIR", str(tmp_path))
        (tmp_path / "syn_ring.toml").write_text(
            engine.dump_kernel_contract(kern))
        return audit_main(["--check", "dma"])

    assert rc("missing-recv-wait") == 1
    assert rc("overwrite-src") == 1
    assert rc("entry-only") == 1
    assert rc("unbalanced") == 1
    assert rc("clean") == 0


def test_cli_dump_contract_covers_kernels(capsys):
    assert audit_main(["--dump-contract", "ring_stokeslet_fused"]) == 0
    data = toml_io.loads(capsys.readouterr().out)
    assert data["dma"]["kernel"] == "fused-ring"
    assert data["dma"]["phase_skew_bound"] == 1


def test_tree_kernels_are_contract_clean():
    """Both fused ring kernels AND the gridded tile kernels verify clean
    with ZERO suppressions against the checked-in contracts."""
    from skellysim_tpu.audit.kernels import all_kernels

    kerns = all_kernels()
    assert sorted(k.name for k in kerns) == [
        "ring_stokeslet_fused", "ring_stresslet_fused",
        "stokeslet_pallas_tiles", "stresslet_pallas_tiles"]
    for kern in kerns:
        contract, findings = engine.load_contract(kern.name)
        assert findings == []
        assert not contract.get("suppress")
    assert audit_main(["--check", "dma"]) == 0
