"""Pallas kernels vs XLA direct kernels (interpret mode on CPU).

Extends the backend-consistency matrix (SURVEY.md §4.1) to the Pallas
backend; on real TPU hardware the same comparisons run compiled.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from skellysim_tpu.ops import kernels
from skellysim_tpu.ops.pallas_kernels import stokeslet_pallas, stresslet_pallas

GATE_F64 = 5e-9   # `kernel_test.cpp:93`
GATE_F32 = 2e-4   # f32 accumulation over ~1k sources


@pytest.fixture(scope="module")
def cloud():
    rng = np.random.default_rng(21)
    n_src, n_trg = 700, 300   # deliberately not tile multiples
    r_src = rng.uniform(-2, 2, (n_src, 3))
    r_trg = rng.uniform(-2, 2, (n_trg, 3))
    f = rng.standard_normal((n_src, 3))
    return r_src, r_trg, f


def _rel_err(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return np.abs(a - b).max() / max(np.abs(b).max(), 1e-300)


@pytest.mark.parametrize("dtype,gate", [(jnp.float64, GATE_F64),
                                        (jnp.float32, GATE_F32)])
def test_stokeslet_pallas_matches_direct(cloud, dtype, gate):
    r_src, r_trg, f = (jnp.asarray(a, dtype=dtype) for a in cloud)
    u_p = stokeslet_pallas(r_src, r_trg, f, 1.3, tile_t=128, tile_s=256,
                           interpret=True)
    u_d = kernels.stokeslet_direct(r_src, r_trg, f, 1.3)
    assert _rel_err(u_p, u_d) < gate


def test_stokeslet_pallas_self_term(cloud):
    """Coincident points drop; padded sources contribute exactly zero."""
    r_src, _, f = cloud
    pts = jnp.asarray(r_src, dtype=jnp.float64)
    ff = jnp.asarray(f, dtype=jnp.float64)
    u_p = stokeslet_pallas(pts, pts, ff, 1.0, tile_t=128, tile_s=256,
                           interpret=True)
    u_d = kernels.stokeslet_direct(pts, pts, ff, 1.0)
    assert np.all(np.isfinite(np.asarray(u_p)))
    assert _rel_err(u_p, u_d) < GATE_F64


@pytest.mark.parametrize("dtype,gate", [(jnp.float64, GATE_F64),
                                        (jnp.float32, 5e-4)])
def test_stresslet_pallas_matches_direct(cloud, dtype, gate):
    r_src, r_trg, _ = cloud
    rng = np.random.default_rng(33)
    S = jnp.asarray(rng.standard_normal((r_src.shape[0], 3, 3)), dtype=dtype)
    r_src = jnp.asarray(r_src, dtype=dtype)
    r_trg = jnp.asarray(r_trg, dtype=dtype)
    u_p = stresslet_pallas(r_src, r_trg, S, 0.8, tile_t=128, tile_s=256,
                           interpret=True)
    u_d = kernels.stresslet_direct(r_src, r_trg, S, 0.8)
    assert _rel_err(u_p, u_d) < gate


def test_pallas_reachable_through_kernel_seam():
    """kernel_impl="pallas" dispatches through the production seam
    (round-3 verdict: no unreachable production code path) — interpret
    mode off-TPU, Mosaic on real chips."""
    import numpy as np

    from skellysim_tpu.ops import kernels
    from skellysim_tpu.params import Params
    from skellysim_tpu.system import System

    rng = np.random.default_rng(3)
    r = jnp.asarray(rng.uniform(-2, 2, (96, 3)), dtype=jnp.float32)
    f = jnp.asarray(rng.standard_normal((96, 3)), dtype=jnp.float32)
    u = np.asarray(kernels.stokeslet_direct(r, r, f, 1.3, impl="pallas"))
    ref = np.asarray(kernels.stokeslet_direct(r, r, f, 1.3))
    assert np.linalg.norm(u - ref) / np.linalg.norm(ref) < 1e-5
    S = jnp.asarray(rng.standard_normal((96, 3, 3)), dtype=jnp.float32)
    uS = np.asarray(kernels.stresslet_direct(r, r, S, 1.3, impl="pallas"))
    refS = np.asarray(kernels.stresslet_direct(r, r, S, 1.3))
    assert np.linalg.norm(uS - refS) / np.linalg.norm(refS) < 1e-5
    # the Params knob validates (typos rejected at System construction)
    System(Params(kernel_impl="pallas", adaptive_timestep_flag=False))
    import pytest

    with pytest.raises(ValueError):
        System(Params(kernel_impl="palas", adaptive_timestep_flag=False))


def test_pallas_seam_f64_falls_back_to_exact():
    """The pallas tier is f32-only by contract: f64 inputs through the
    dispatch take the exact XLA path bit-for-bit (Mosaic has no f64 on
    TPU; the accuracy tiers are "exact"/"df")."""
    rng = np.random.default_rng(7)
    r = jnp.asarray(rng.uniform(-2, 2, (64, 3)), dtype=jnp.float64)
    f = jnp.asarray(rng.standard_normal((64, 3)), dtype=jnp.float64)
    u = np.asarray(kernels.stokeslet_direct(r, r, f, 1.1, impl="pallas"))
    ref = np.asarray(kernels.stokeslet_direct(r, r, f, 1.1))
    np.testing.assert_array_equal(u, ref)
    S = jnp.asarray(rng.standard_normal((64, 3, 3)), dtype=jnp.float64)
    uS = np.asarray(kernels.stresslet_direct(r, r, S, 1.1, impl="pallas"))
    refS = np.asarray(kernels.stresslet_direct(r, r, S, 1.1))
    np.testing.assert_array_equal(uS, refS)
