"""Explicitly-sharded full-step program (`parallel.spmd`) vs the
single-program ground truth on the 8-device virtual CPU mesh.

Distributed-correctness strategy per SURVEY.md §4.3: real sharded execution,
no mocks. Beyond value parity, the lowered program is audited (via the
skelly-audit engine, `skellysim_tpu.audit`) to pin the SPMD collective
contract: psum (all-reduce) reductions, ppermute (collective-permute)
rings, and NO all-gather larger than the shell density — the failure mode
this subsystem exists to rule out is GSPMD silently all-gathering a
fiber-cache-sized operand onto every chip.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skellysim_tpu.fibers import container as fc
from skellysim_tpu.params import Params
from skellysim_tpu.parallel import make_mesh, shard_state
from skellysim_tpu.parallel.spmd import spmd_shell_mode
from skellysim_tpu.periphery.periphery import PeripheryShape
from skellysim_tpu.system import BackgroundFlow, System
from skellysim_tpu.testing import make_coupled_parts

N_DEV = 8
#: the reference's backend-agreement gate (`kernel_test.cpp:93`)
GATE = 5e-9

PARAMS = dict(eta=1.0, dt_initial=1e-3, t_final=1e-2, gmres_tol=1e-10,
              adaptive_timestep_flag=False)
SHAPE = PeripheryShape(kind="sphere", radius=6.0)


def _fibers(n_fibers=16, n_nodes=16, seed=5, box=4.0):
    rng = np.random.default_rng(seed)
    t = np.linspace(0, 1, n_nodes)
    origins = rng.uniform(-box, box, size=(n_fibers, 3))
    dirs = rng.normal(size=(n_fibers, 3))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    x = origins[:, None, :] + t[None, :, None] * dirs[:, None, :]
    return fc.make_group(x, lengths=1.0, bending_rigidity=0.01,
                         radius=0.0125, dtype=jnp.float64)


def _free_state(system):
    return system.make_state(
        fibers=_fibers(),
        background=BackgroundFlow.make(uniform=(1.0, 0.0, 0.0),
                                       dtype=jnp.float64))


@pytest.fixture(scope="module")
def coupled_parts():
    # 56 shell nodes split node-aligned over the 8-mesh: the row-sharded
    # shell path is under test, not the replicated fallback
    return make_coupled_parts(56, 50, jnp.float64)


def _coupled_state(system, parts):
    shell, _, bodies = parts
    return system.make_state(fibers=_fibers(seed=7, box=2.0), shell=shell,
                             bodies=bodies)


@pytest.mark.slow  # the coupled parity test below is the per-commit gate;
# this free-space variant rides the full tier (tier-1 runs near its timeout)
def test_spmd_free_fiber_solve_matches_single_program():
    sys_ref = System(Params(**PARAMS))
    s_ref, sol_ref, info_ref = sys_ref.step(_free_state(sys_ref))

    mesh = make_mesh(N_DEV)
    sys_sp = System(Params(**PARAMS))
    state = shard_state(_free_state(sys_sp), mesh)
    s_sp, sol_sp, info_sp = sys_sp.step_spmd(state, mesh)

    assert bool(info_sp.converged)
    assert abs(float(info_sp.residual_true)
               - float(info_ref.residual_true)) <= GATE
    np.testing.assert_allclose(np.asarray(sol_sp), np.asarray(sol_ref),
                               atol=GATE)
    np.testing.assert_allclose(np.asarray(s_sp.fibers.x),
                               np.asarray(s_ref.fibers.x), atol=GATE)
    # fiber state stays sharded across the step (no implicit gather)
    assert len(s_sp.fibers.x.sharding.device_set) == N_DEV


@pytest.mark.slow  # heavy coupled-solve integration; sibling fast tests keep the seam covered (ISSUE-9 870s-budget re-triage)
def test_spmd_coupled_solve_matches_single_program(coupled_parts):
    sys_ref = System(Params(**PARAMS), shell_shape=SHAPE)
    s_ref, sol_ref, info_ref = sys_ref.step(
        _coupled_state(sys_ref, coupled_parts))
    assert bool(info_ref.converged)

    mesh = make_mesh(N_DEV)
    sys_sp = System(Params(**PARAMS), shell_shape=SHAPE)
    state = shard_state(_coupled_state(sys_sp, coupled_parts), mesh)
    assert spmd_shell_mode(state, mesh) == "sharded"
    s_sp, sol_sp, info_sp = sys_sp.step_spmd(state, mesh)

    assert bool(info_sp.converged)
    assert abs(float(info_sp.residual_true)
               - float(info_ref.residual_true)) <= GATE
    np.testing.assert_allclose(np.asarray(sol_sp), np.asarray(sol_ref),
                               atol=GATE)
    np.testing.assert_allclose(np.asarray(s_sp.shell.density),
                               np.asarray(s_ref.shell.density), atol=GATE)
    np.testing.assert_allclose(np.asarray(s_sp.bodies.position),
                               np.asarray(s_ref.bodies.position), atol=1e-10)
    # the dense shell operators stay row-sharded through the step
    assert len(s_sp.shell.M_inv.sharding.device_set) == N_DEV


@pytest.mark.slow
def test_spmd_mixed_refinement_inside_mesh(coupled_parts):
    """Mixed precision (f32 Krylov + f64 refinement through the double-float
    ring tiles) composes inside the same shard_map program — refinement
    sweeps never leave the mesh. (slow-marked: the per-commit gate covers
    this path via the graft-entry dryrun's mixed leg.)"""
    pm = dict(PARAMS, solver_precision="mixed", refine_pair_impl="df")
    sys_ref = System(Params(**pm), shell_shape=SHAPE)
    _, _, info_ref = sys_ref.step(_coupled_state(sys_ref, coupled_parts))

    mesh = make_mesh(N_DEV)
    sys_sp = System(Params(**pm), shell_shape=SHAPE)
    state = shard_state(_coupled_state(sys_sp, coupled_parts), mesh)
    _, _, info_sp = sys_sp.step_spmd(state, mesh)

    assert bool(info_sp.converged)
    assert float(info_sp.residual_true) <= pm["gmres_tol"]
    # residual parity at the backend-agreement gate; the solutions agree
    # only to the tolerance ball (different f32 Krylov trajectories)
    assert abs(float(info_sp.residual_true)
               - float(info_ref.residual_true)) <= GATE
    assert int(info_sp.refines) == int(info_ref.refines)


@pytest.mark.slow
def test_spmd_replicated_shell_fallback():
    """A shell that cannot split node-aligned raises; the explicit
    replicated opt-in still matches the single program."""
    parts = make_coupled_parts(100, 50, jnp.float64)  # 100 % 8 != 0
    mesh = make_mesh(N_DEV)
    sys_sp = System(Params(**PARAMS), shell_shape=SHAPE)
    state = _coupled_state(sys_sp, parts)
    with pytest.raises(ValueError, match="multiple of 8"):
        spmd_shell_mode(state, mesh)

    sys_ref = System(Params(**PARAMS), shell_shape=SHAPE)
    _, sol_ref, info_ref = sys_ref.step(_coupled_state(sys_ref, parts))

    state = shard_state(state, mesh, allow_replicated_shell=True)
    _, sol_sp, info_sp = sys_sp.step_spmd(state, mesh,
                                          allow_replicated_shell=True)
    assert bool(info_sp.converged)
    assert abs(float(info_sp.residual_true)
               - float(info_ref.residual_true)) <= GATE
    np.testing.assert_allclose(np.asarray(sol_sp), np.asarray(sol_ref),
                               atol=GATE)


def test_spmd_indivisible_fibers_raise():
    mesh = make_mesh(N_DEV)
    sys_sp = System(Params(**PARAMS))
    state = sys_sp.make_state(
        fibers=_fibers(n_fibers=12),  # 12 % 8 != 0
        background=BackgroundFlow.make(uniform=(1.0, 0.0, 0.0),
                                       dtype=jnp.float64))
    with pytest.raises(ValueError, match="grow_capacity"):
        spmd_shell_mode(state, mesh)


def test_spmd_indivisible_shell_raises():
    """Node-misaligned shells must fail loudly (never silently replicate
    the O(n^2) operators); the explicit opt-in reports 'replicated'."""
    parts = make_coupled_parts(100, 50, jnp.float64)  # 100 % 8 != 0
    mesh = make_mesh(N_DEV)
    sys_sp = System(Params(**PARAMS), shell_shape=SHAPE)
    state = _coupled_state(sys_sp, parts)
    with pytest.raises(ValueError, match="multiple of 8"):
        spmd_shell_mode(state, mesh)
    assert spmd_shell_mode(state, mesh,
                           allow_replicated_shell=True) == "replicated"


# ------------------------------------------------- lowered-program contracts
# Ported onto the skelly-audit API (docs/audit.md): the collective
# inventory, the all-gather size bound, and the donation markers that used
# to live here as ad-hoc HLO regexes are now pinned by
# audit/contracts/step_spmd_d8.toml (+ step_single*.toml). These wrappers
# keep the per-commit pin in the test tier while the audit engine owns the
# single source of truth (ci/run_ci.sh gates the full program matrix).

@pytest.fixture(scope="module")
def spmd_audit():
    """(findings, contract) for the d8 coupled SPMD step — the same scene
    this module's parity tests run, traced + lowered once by the audit
    engine."""
    from skellysim_tpu.audit.engine import load_contract, run_program_audit
    from skellysim_tpu.audit.programs import get_program

    # run_program_audit re-loads the contract and already includes any
    # contract-validation findings; load_contract here only fetches the
    # parsed dict for the invariant assertions below
    contract, _ = load_contract("step_spmd_d8")
    return run_program_audit(get_program("step_spmd_d8")), contract


def test_spmd_collectives_bounded(spmd_audit):
    """The GMRES inner iteration issues a bounded, documented collective
    set: all-reduces (psum reductions), collective-permutes (source-block
    rings), and all-gathers of AT MOST shell-density size — never a
    fiber-cache-sized operand (the GSPMD failure mode)."""
    findings, contract = spmd_audit
    assert [f.render() for f in findings] == []
    # the contract itself must keep pinning the invariant this test exists
    # for: psum + ppermute present, and nothing gathered beyond the density
    colls = contract["collectives"]
    assert colls["all_reduce"]["count"] > 0
    assert colls["collective_permute"]["count"] > 0
    assert colls["all_gather"]["max_elems"] == 3 * 56  # the density vector


def test_spmd_state_donation_marked(spmd_audit):
    """The input state's buffers are marked donated at lowering time, so the
    sharded step does not double-buffer the pass-through leaves (the dense
    shell operators) per step."""
    findings, contract = spmd_audit
    assert [f.render() for f in findings] == []
    assert contract["donation"]["donated"] is True


def test_run_loop_donating_jit_marks_consumption():
    """`System._solve_jit_donated` (selected by the run loop when the
    adaptive gate is off) records input->output aliasing at lowering time —
    and the non-donating twin must NOT alias (rollback safety). Both pins
    live in the audit donation contracts now; this runs just that check."""
    from skellysim_tpu.audit.engine import run_program_audit
    from skellysim_tpu.audit.programs import get_program

    for name in ("step_single_donated", "step_single"):
        findings = run_program_audit(get_program(name), checks=["donation"])
        assert [f.render() for f in findings] == [], name


@pytest.mark.slow
def test_spmd_block_s4_coupled_parity(coupled_parts):
    """ISSUE 8: the communication-avoiding solver (gmres_block_s=4 — the
    configuration the d8 audit contract pins) solves the coupled scene on
    the mesh to the same backend-agreement gate as the single program, at
    the sequential cycle's iteration count."""
    params = Params(**PARAMS, gmres_block_s=4)
    sys_ref = System(params, shell_shape=SHAPE)
    _, sol_ref, info_ref = sys_ref.step(_coupled_state(sys_ref,
                                                       coupled_parts))
    assert bool(info_ref.converged)

    mesh = make_mesh(N_DEV)
    sys_sp = System(params, shell_shape=SHAPE)
    state = shard_state(_coupled_state(sys_sp, coupled_parts), mesh)
    _, sol_sp, info_sp = sys_sp.step_spmd(state, mesh)
    assert bool(info_sp.converged)
    assert abs(float(info_sp.residual_true)
               - float(info_ref.residual_true)) <= GATE
    np.testing.assert_allclose(np.asarray(sol_sp), np.asarray(sol_ref),
                               atol=GATE)

    # sequential-cycle reference on the SAME mesh scene: the s-step basis
    # must not cost extra iterations (ISSUE 8 acceptance: within 10%)
    sys_s1 = System(Params(**PARAMS), shell_shape=SHAPE)
    state1 = shard_state(_coupled_state(sys_s1, coupled_parts), mesh)
    _, _, info_s1 = sys_s1.step_spmd(state1, mesh)
    assert int(info_sp.iters) <= int(np.ceil(1.1 * int(info_s1.iters) / 4) * 4)


def test_spmd_contract_pins_batched_gram_rounds(spmd_audit):
    """The updated d8 contract IS the s-step pin (ISSUE 8 acceptance): the
    largest psum operand is the batched [(m+1)+s, s] Gram block — the
    sequential [m+1] per-iteration reduction shape is gone from the
    inventory, and the solver loop pays 2 rounds per s=4 iterations
    instead of 3 per iteration (>= 3x fewer rounds per cycle)."""
    findings, contract = spmd_audit
    assert [f.render() for f in findings] == []
    ar = contract["collectives"]["all_reduce"]
    # (gmres_restart rounded to a block multiple + 1 + s) * s = 420
    assert ar["max_elems"] == (100 + 1 + 4) * 4
