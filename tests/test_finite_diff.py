"""Finite-difference weight and barycentric-resampling tests.

Oracle strategy: differentiate/resample known polynomials exactly (the FD matrices
of order 4 with the reference's stencil sizes are exact on low-degree polynomials),
rather than comparing against golden outputs.
"""

import numpy as np

from skellysim_tpu.ops.finite_diff import barycentric_matrix, finite_diff


def test_finite_diff_exact_on_polynomials():
    n = 32
    s = np.linspace(-1, 1, n)
    # reference uses n_s = 4 + order (compute_matrices_finitediff,
    # /root/reference/src/core/fiber_finite_difference.cpp:537-540)
    for order, n_s in [(1, 5), (2, 6), (3, 7), (4, 8)]:
        D = finite_diff(s, order, n_s)
        for deg in range(order, 5):
            p = np.polynomial.Polynomial(np.arange(1.0, deg + 2))
            want = p.deriv(order)(s)
            got = D @ p(s)
            np.testing.assert_allclose(got, want, rtol=1e-8, atol=1e-8)


def test_finite_diff_rows_sum_zero():
    s = np.linspace(-1, 1, 16)
    for order in (1, 2, 3, 4):
        D = finite_diff(s, order, 4 + order)
        np.testing.assert_allclose(D @ np.ones(16), 0.0, atol=1e-9)


def test_barycentric_resamples_polynomials_on_chebyshev_nodes():
    # The reference's weights ([0.5, -1, 1, ..., +-0.5], src/core/utils.cpp:16-20)
    # are the barycentric weights of Chebyshev points of the 2nd kind, so the
    # resampling is an exact polynomial interpolant on that grid.
    n, m = 24, 20
    x = -np.cos(np.pi * np.arange(n) / (n - 1))
    y = 2 * (0.5 + np.arange(m)) / m - 1
    P = barycentric_matrix(x, y)
    for deg in range(6):
        p = np.polynomial.Polynomial(np.ones(deg + 1))
        np.testing.assert_allclose(P @ p(x), p(y), rtol=1e-9, atol=1e-9)


def test_barycentric_partition_of_unity_equispaced():
    # On the equispaced grids the fibers actually use, the operator still
    # reproduces constants exactly (terms/S sums to 1 per row).
    x = np.linspace(-1, 1, 24)
    y = 2 * (0.5 + np.arange(20)) / 20 - 1
    P = barycentric_matrix(x, y)
    np.testing.assert_allclose(P @ np.ones(24), 1.0, atol=1e-12)


def test_barycentric_handles_coincident_points():
    x = np.linspace(-1, 1, 9)
    y = np.array([x[3]])
    P = barycentric_matrix(x, y)
    e = np.zeros(9)
    e[3] = 1.0
    np.testing.assert_allclose(P[0], e, atol=1e-12)
