"""Post-processing: velocity_at_targets, streamlines, vortex lines, listener.

Oracles are closed-form flows: uniform background advection for streamlines,
rigid rotation (omega x r, curl = 2*omega) for vorticity, and a point force's
Oseen field for velocity_at_targets consistency.
"""

import io as _io
import os
import struct

import jax.numpy as jnp
import msgpack
import numpy as np
import pytest

from skellysim_tpu import builder, cli
from skellysim_tpu.io import eigen
from skellysim_tpu.io.trajectory import TrajectoryReader, frame_to_state
from skellysim_tpu.postprocess import (make_vorticity_fn, streamlines,
                                       vortex_lines)
from skellysim_tpu.system.system import solution_from_state
from skellysim_tpu import listener as listener_mod


# ---------------------------------------------------------------- integrator

def test_streamline_uniform_flow_straight_line():
    u = np.array([0.3, -0.2, 0.1])

    def vel(x):
        return jnp.broadcast_to(jnp.asarray(u), x.shape)

    x0 = np.array([[0.0, 0.0, 0.0], [1.0, 2.0, 3.0]])
    lines = streamlines(vel, x0, dt_init=0.1, t_final=1.0, back_integrate=True)
    assert len(lines) == 2
    for seed, ln in zip(x0, lines):
        # x(t) = seed + u t for t in [-1, 1]; times ascend through 0
        assert ln["time"][0] == pytest.approx(-1.0, abs=1e-8)
        assert ln["time"][-1] == pytest.approx(1.0, abs=1e-8)
        assert np.all(np.diff(ln["time"]) > 0)
        expect = seed[None, :] + ln["time"][:, None] * u[None, :]
        np.testing.assert_allclose(ln["x"], expect, atol=1e-8)
        np.testing.assert_allclose(ln["val"], np.tile(u, (len(ln["time"]), 1)),
                                   atol=1e-12)


def test_streamline_forward_only_rotation():
    # rigid rotation about z: streamlines are circles of constant radius
    def vel(x):
        return jnp.stack([-x[:, 1], x[:, 0], jnp.zeros_like(x[:, 0])], axis=-1)

    lines = streamlines(vel, np.array([[1.0, 0.0, 0.5]]), dt_init=0.05,
                        t_final=2.0, back_integrate=False, rel_err=1e-10,
                        abs_err=1e-12)
    ln = lines[0]
    r = np.linalg.norm(ln["x"][:, :2], axis=1)
    np.testing.assert_allclose(r, 1.0, atol=1e-7)
    np.testing.assert_allclose(ln["x"][:, 2], 0.5, atol=1e-12)
    # reached the requested final time
    assert ln["time"][-1] == pytest.approx(2.0, abs=1e-8)


def test_streamline_singularity_bailout():
    # speed ramps with |x|; beyond ||v|| > 1e3 the line must stop early
    def vel(x):
        return 200.0 * x

    lines = streamlines(vel, np.array([[1.0, 1.0, 1.0]]), dt_init=1e-3,
                        t_final=5.0, back_integrate=False)
    ln = lines[0]
    assert ln["time"][-1] < 5.0  # bailed out before t_final
    assert np.linalg.norm(200.0 * ln["x"][-1]) > 1e3


def test_vorticity_rigid_rotation():
    omega = np.array([0.0, 0.0, 0.7])

    def vel(x):
        return jnp.cross(jnp.broadcast_to(jnp.asarray(omega), x.shape), x)

    vort = make_vorticity_fn(vel)
    w = np.asarray(vort(jnp.asarray([[0.3, -0.2, 0.9], [1.0, 1.0, 1.0]])))
    np.testing.assert_allclose(w, np.tile(2 * omega, (2, 1)), atol=1e-6)


def test_vortex_lines_follow_omega():
    omega = np.array([0.0, 0.0, 0.5])

    def vel(x):
        return jnp.cross(jnp.broadcast_to(jnp.asarray(omega), x.shape), x)

    lines = vortex_lines(vel, np.array([[0.2, 0.1, 0.0]]), dt_init=0.1,
                         t_final=1.0, back_integrate=False)
    ln = lines[0]
    # vorticity field is uniform 2*omega: the line goes straight up z
    np.testing.assert_allclose(ln["x"][:, 0], 0.2, atol=1e-8)
    np.testing.assert_allclose(ln["x"][:, 1], 0.1, atol=1e-8)
    assert ln["x"][-1, 2] > 0.9  # advanced ~ 2*0.5*1.0 = 1.0 in z
    np.testing.assert_allclose(ln["val"], np.tile(2 * omega, (len(ln["time"]), 1)),
                               atol=1e-6)


# ------------------------------------------------------- velocity_at_targets

def _run_fiber_sim(tmp_path):
    from skellysim_tpu.config import BackgroundSource, Config, Fiber

    cfg = Config()
    cfg.params.eta = 1.3
    cfg.params.dt_initial = 0.005
    cfg.params.dt_write = 0.005
    cfg.params.t_final = 0.01
    cfg.params.adaptive_timestep_flag = False
    fib = Fiber(n_nodes=16, length=1.0, bending_rigidity=0.01)
    fib.fill_node_positions(np.zeros(3), np.array([0.0, 0.0, 1.0]))
    cfg.fibers = [fib]
    cfg.background = BackgroundSource(uniform=[1.0, 0.0, 0.0])
    path = str(tmp_path / "skelly_config.toml")
    cfg.save(path)
    cli.run(path)
    return path, str(tmp_path / "skelly_sim.out")


def test_velocity_at_targets_far_field(tmp_path):
    """Far from a weakly-forced fiber, velocity ~ background uniform flow."""
    cfg_path, traj_path = _run_fiber_sim(tmp_path)
    system, template, _ = builder.build_simulation(cfg_path)
    reader = TrajectoryReader(traj_path)
    state = frame_to_state(reader.load_frame(len(reader) - 1), template)
    solution = solution_from_state(state)

    r_far = np.array([[80.0, 0.0, 0.0], [0.0, 90.0, 10.0]])
    v = np.asarray(system.velocity_at_targets(state, solution, r_far))
    np.testing.assert_allclose(v, [[1.0, 0.0, 0.0]] * 2, atol=5e-2)
    # a freely-advected fiber is force-free: even the near field is the
    # undisturbed background flow
    v_near = np.asarray(system.velocity_at_targets(
        state, solution, np.array([[0.1, 0.0, 0.5]])))
    np.testing.assert_allclose(v_near[0], [1.0, 0.0, 0.0], atol=1e-8)


@pytest.mark.slow  # coupled-solve + field integration (fast-tier budget)
def test_velocity_inside_body_is_rigid_motion(tmp_path):
    """Targets inside a rigid body report v + omega x dx (`system.cpp:364-381`)."""
    from skellysim_tpu.config import Body, ConfigSpherical
    from skellysim_tpu import precompute

    cfg = ConfigSpherical()
    cfg.params.eta = 1.0
    cfg.params.dt_initial = 0.01
    cfg.params.dt_write = 0.01
    cfg.params.t_final = 0.02
    cfg.params.adaptive_timestep_flag = False
    cfg.periphery.n_nodes = 100
    cfg.periphery.radius = 4.0
    body = Body(position=[0.0, 0.0, 0.0], shape="sphere", radius=0.5,
                n_nodes=100, external_force=[0.0, 0.0, 1.0])
    cfg.bodies = [body]
    path = str(tmp_path / "skelly_config.toml")
    cfg.save(path)
    precompute.precompute_from_config(path, verbose=False)
    cli.run(path)

    system, template, _ = builder.build_simulation(path)
    reader = TrajectoryReader(str(tmp_path / "skelly_sim.out"))
    state = frame_to_state(reader.load_frame(len(reader) - 1), template)
    solution = solution_from_state(state)

    center = np.asarray(state.bodies.position)[0]
    v_in = np.asarray(system.velocity_at_targets(
        state, solution, center[None, :] + [[0.0, 0.0, 0.1]]))
    v_body = np.asarray(state.bodies.solution)[0, -6:-3]
    omega = np.asarray(state.bodies.solution)[0, -3:]
    np.testing.assert_allclose(v_in[0], v_body + np.cross(omega, [0.0, 0.0, 0.1]),
                               atol=1e-12)
    # drag force upward -> body moves upward
    assert v_body[2] > 0


# ----------------------------------------------------------------- listener

@pytest.mark.slow  # listener server e2e (fast-tier budget)
def test_listener_server_roundtrip(tmp_path):
    """Full request/response through the in-process server loop."""
    cfg_path, traj_path = _run_fiber_sim(tmp_path)

    req = {
        "frame_no": 1,
        "evaluator": "CPU",
        "streamlines": {"dt_init": 0.05, "t_final": 0.2, "abs_err": 1e-8,
                        "rel_err": 1e-6, "back_integrate": True,
                        "x0": eigen.pack_matrix(np.array([[2.0, 0.0, 0.5]]))},
        "vortexlines": {"x0": eigen.pack_matrix(np.zeros((0, 3)))},
        "velocity_field": {"x": eigen.pack_matrix(np.array([[50.0, 0.0, 0.0]]))},
    }
    msg = msgpack.packb(req)
    stdin = _io.BytesIO(struct.pack("<Q", len(msg)) + msg + struct.pack("<Q", 0))
    stdout = _io.BytesIO()
    listener_mod.serve(cfg_path, traj_path, stdin=stdin, stdout=stdout)

    stdout.seek(0)
    (size,) = struct.unpack("<Q", stdout.read(8))
    assert size > 0
    res = eigen.decode_tree(msgpack.unpackb(stdout.read(size), raw=False))
    assert res["i_frame"] == 1
    assert res["n_frames"] == len(TrajectoryReader(traj_path))
    assert len(res["streamlines"]) == 1
    ln = res["streamlines"][0]
    assert ln["x"].shape[1] == 3 and ln["x"].shape[0] == len(ln["time"])
    assert res["vortexlines"] == []
    # far-field velocity ~ background (single point decodes 1-D per the
    # reference's __eigen__ convention)
    np.testing.assert_allclose(
        np.asarray(res["velocity_field"]).reshape(-1, 3)[0],
        [1.0, 0.0, 0.0], atol=5e-2)


@pytest.mark.slow  # subprocess pipeline (fast-tier budget)
def test_listener_client_subprocess(tmp_path, monkeypatch):
    """The Python client drives a real --listen server subprocess
    (`reader.py:126-194` semantics)."""
    from skellysim_tpu.io import Listener, Request, VelocityFieldRequest

    cfg_path, traj_path = _run_fiber_sim(tmp_path)
    monkeypatch.chdir(tmp_path)  # server resolves skelly_sim.out next to config
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    monkeypatch.setenv("PYTHONPATH", repo_root)
    with Listener(toml_file=cfg_path) as listener:
        req = Request(frame_no=0)
        req.velocity_field = VelocityFieldRequest(
            x=np.array([[60.0, 0.0, 0.0], [0.0, 70.0, 0.0]]))
        res = listener.request(req)
        assert res["i_frame"] == 0 and res["n_frames"] >= 2
        np.testing.assert_allclose(np.asarray(res["velocity_field"]),
                                   [[1.0, 0.0, 0.0]] * 2, atol=5e-2)
        assert listener.request(Request(frame_no=512)) is None


def test_listener_invalid_frame_returns_empty(tmp_path):
    cfg_path, traj_path = _run_fiber_sim(tmp_path)
    msg = msgpack.packb({"frame_no": 9999})
    stdin = _io.BytesIO(struct.pack("<Q", len(msg)) + msg + struct.pack("<Q", 0))
    stdout = _io.BytesIO()
    listener_mod.serve(cfg_path, traj_path, stdin=stdin, stdout=stdout)
    stdout.seek(0)
    (size,) = struct.unpack("<Q", stdout.read(8))
    assert size == 0

@pytest.mark.slow  # coupled-solve + field integration (fast-tier budget)
def test_velocity_inside_ellipsoid_body_is_rigid_motion():
    """Ellipsoid containment override (`system.cpp:371-380`): probes inside
    an ELLIPSOIDAL body report its rigid motion v + omega x dx, including
    points outside the inscribed sphere; just-outside probes keep the
    computed exterior flow."""
    import jax.numpy as jnp

    from skellysim_tpu.bodies import bodies as bd
    from skellysim_tpu.params import Params
    from skellysim_tpu.periphery.precompute import precompute_body
    from skellysim_tpu.system import System

    a, b, c = 0.8, 0.4, 0.4
    pre = precompute_body("ellipsoid", 400, a=a, b=b, c=c)
    group = bd.make_group(pre["node_positions_ref"], pre["node_normals_ref"],
                          pre["node_weights"], kind="ellipsoid",
                          semiaxes=[a, b, c],
                          external_force=[0.0, 0.0, 1.0])
    params = Params(eta=1.0, dt_initial=0.05, t_final=0.05, gmres_tol=1e-10,
                    adaptive_timestep_flag=False)
    system = System(params)
    state, solution, info = system.step(system.make_state(bodies=group))
    assert bool(info.converged)

    v_body = np.asarray(state.bodies.solution)[0, -6:-3]
    omega = np.asarray(state.bodies.solution)[0, -3:]
    # inside along the long axis — OUTSIDE the inscribed b-sphere, so the
    # sphere-only containment of round 3 misses it
    probes = np.array([[0.6, 0.0, 0.0], [0.0, 0.2, 0.1]])
    v_in = np.asarray(system.velocity_at_targets(state, solution, probes))
    for p, v in zip(probes, v_in):
        # atol at the solve's noise floor: omega and the transverse velocity
        # components are ~1e-8-class numerical zeros
        np.testing.assert_allclose(v, v_body + np.cross(omega, p),
                                   rtol=0, atol=1e-8)
    # just outside the surface: must NOT be overridden (differs from the
    # rigid field because the exterior Stokes flow decays)
    p_out = np.array([[1.2, 0.0, 0.0]])
    v_out = np.asarray(system.velocity_at_targets(state, solution, p_out))
    assert not np.allclose(v_out[0], v_body + np.cross(omega, p_out[0]),
                           atol=1e-12)

@pytest.mark.slow  # 24s Ewald streamline integration (fast-tier budget)
def test_listener_streamlines_through_ewald(tmp_path):
    """An "FMM" request integrates streamlines through the spectral-Ewald
    evaluator (per-request extended-box plan, matching the reference's
    whole-request evaluator switch, `listener.cpp:117`) and agrees with the
    dense evaluator to the Ewald tolerance."""
    cfg_path, traj_path = _run_fiber_sim(tmp_path)

    def one(evaluator):
        req = {
            "frame_no": 1,
            "evaluator": evaluator,
            "streamlines": {"dt_init": 0.05, "t_final": 0.2,
                            "abs_err": 1e-8, "rel_err": 1e-6,
                            "back_integrate": True,
                            "x0": eigen.pack_matrix(
                                np.array([[2.0, 0.0, 0.5]]))},
        }
        msg = msgpack.packb(req)
        stdin = _io.BytesIO(struct.pack("<Q", len(msg)) + msg
                            + struct.pack("<Q", 0))
        stdout = _io.BytesIO()
        listener_mod.serve(cfg_path, traj_path, stdin=stdin, stdout=stdout)
        stdout.seek(0)
        (size,) = struct.unpack("<Q", stdout.read(8))
        assert size > 0
        res = eigen.decode_tree(msgpack.unpackb(stdout.read(size), raw=False))
        return res["streamlines"][0]

    dense = one("CPU")
    fmm = one("FMM")
    # identical step acceptance and near-identical trajectories: Ewald's
    # 1e-6-class field error perturbs the adaptive integrator only slightly
    n = min(dense["x"].shape[0], fmm["x"].shape[0])
    assert n >= 3
    err = np.linalg.norm(np.asarray(fmm["x"][:n]) - np.asarray(dense["x"][:n]))
    scale = np.linalg.norm(np.asarray(dense["x"][:n]))
    assert err / scale < 1e-3, err / scale
