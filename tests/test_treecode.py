"""Barycentric Lagrange treecode vs the dense kernel oracle.

The treecode is the hierarchical answer to the reference's FMM slot
(`include/kernels.hpp:56-134`; `ops.ewald` is the grid-based one): every
stage here is pinned against the dense `kernels.stokeslet_direct` /
`stresslet_direct` / `oseen_contract` sums, the plan rules against their
docstring contracts, and the full implicit solve against the direct
evaluator's converged solution.

Accuracy gates use the plan's FIELD-NORMALIZED error measure
(max_i |du_i| / max_i |u_i| — see `TreePlan.tol`): per-point relative error
is unbounded at near-zero-velocity targets for any summation scheme.
"""

import dataclasses

import numpy as np
import pytest

import jax.numpy as jnp

from skellysim_tpu.ops import kernels
from skellysim_tpu.ops import treecode as tc
from skellysim_tpu.ops.evaluator import EVALUATORS, PairEvaluator, make_pair


def _uniform_cloud(n, seed=3, box=1.5):
    rng = np.random.default_rng(seed)
    pts = rng.uniform(-box, box, (n, 3))
    f = rng.standard_normal((n, 3))
    return pts, f


def _fiber_cloud(n_fib, n_nodes, seed=7, box=2.0):
    """Line-clustered cloud (the fiber geometry the evaluator exists for)."""
    rng = np.random.default_rng(seed)
    origins = rng.uniform(-box, box, (n_fib, 3))
    dirs = rng.normal(size=(n_fib, 3))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    t = np.linspace(0, 1.0, n_nodes)
    pts = (origins[:, None, :] + t[None, :, None] * dirs[:, None, :])
    return pts.reshape(-1, 3), rng.standard_normal((n_fib * n_nodes, 3))


def _field_rel(u, u_ref):
    d = np.linalg.norm(np.asarray(u) - np.asarray(u_ref), axis=1)
    return d.max() / np.linalg.norm(np.asarray(u_ref), axis=1).max()


# ------------------------------------------------------------------ plan rules

def test_plan_degenerates_to_dense_below_two_levels():
    """Small clouds (no well-separated cells above the 2-level minimum) get
    the depth-0 dense-fallback plan."""
    pts, _ = _uniform_cloud(200)
    plan = tc.plan_tree(pts, tol=1e-4)
    assert plan.depth == 0


def test_plan_depth_and_capacity_rules():
    """depth = ceil(log8(N_q / target_occ)) on the pow2-laddered count;
    leaf capacity sits on the 8-aligned x1.5 rung ladder above measured
    occupancy."""
    pts, _ = _uniform_cloud(3000)
    plan = tc.plan_tree(pts, tol=1e-4)       # N_q = 4096, occ 32 -> depth 3
    assert plan.depth == 3
    assert plan.max_occ % 8 == 0
    deep = tc.plan_tree(pts, tol=1e-4, target_occ=4.0, max_depth=4)
    assert deep.depth == 4                   # clamped by max_depth


def test_plan_order_rule_from_tol():
    """order from the measured ~5x-per-order contraction rule, clamped."""
    assert tc.order_for_tol(1e-2) < tc.order_for_tol(1e-4) \
        < tc.order_for_tol(1e-6)
    assert tc.order_for_tol(1e-4) == 6
    assert tc.order_for_tol(1e-12, max_order=12) == 12
    pts, _ = _uniform_cloud(3000)
    assert tc.plan_tree(pts, tol=1e-4).order == tc.order_for_tol(1e-4)


def test_plan_stable_under_drift():
    """The anchor-stripped plan (the jit key) is invariant under a small
    translation of the cloud; the anchor hops only on the leaf lattice."""
    pts, _ = _uniform_cloud(3000)
    plan1 = tc.plan_tree(pts, tol=1e-4)
    cell = plan1.leaf_size
    plan2 = tc.plan_tree(pts + 0.01 * cell, tol=1e-4)
    assert tc.strip_anchors(plan1) == tc.strip_anchors(plan2)
    # the anchor itself is leaf-lattice quantized
    for a in plan1.box_lo:
        assert abs(a / cell - round(a / cell)) < 1e-9


# ------------------------------------------------------------------- oracles

def test_degenerate_one_leaf_bitwise_equals_dense():
    """depth == 0 dispatches to the dense kernels themselves: bitwise."""
    pts, f = _uniform_cloud(150, seed=11)
    plan = tc.plan_tree(pts, tol=1e-4)
    assert plan.depth == 0
    P, F = jnp.asarray(pts), jnp.asarray(f)
    assert np.array_equal(np.asarray(tc.stokeslet_tree(plan, P, P, F, 1.3)),
                          np.asarray(kernels.stokeslet_direct(P, P, F, 1.3)))
    S = jnp.asarray(np.random.default_rng(2).standard_normal((150, 3, 3)))
    assert np.array_equal(np.asarray(tc.stresslet_tree(plan, P, P, S, 1.3)),
                          np.asarray(kernels.stresslet_direct(P, P, S, 1.3)))
    assert np.array_equal(np.asarray(tc.oseen_tree(plan, P, P, F, 1.3)),
                          np.asarray(kernels.oseen_contract(P, P, F, 1.3)))


def test_treecode_matches_dense_uniform_cloud():
    """Uniform random cloud at the loose setting (depth 2, order 5):
    Stokeslet + regularized Oseen within the plan's target accuracy."""
    pts, f = _uniform_cloud(1500, seed=1)
    plan = tc.plan_tree(pts, tol=1e-3)
    assert plan.depth >= 2
    P, F = jnp.asarray(pts), jnp.asarray(f)
    err_s = _field_rel(tc.stokeslet_tree(plan, P, P, F, 1.3),
                       kernels.stokeslet_direct(P, P, F, 1.3))
    assert err_s < plan.tol, err_s
    err_o = _field_rel(tc.oseen_tree(plan, P, P, F, 1.3),
                       kernels.oseen_contract(P, P, F, 1.3))
    assert err_o < plan.tol, err_o


@pytest.mark.slow  # tight-setting oracle (order-8 proxies + 1.5k dense tile);
# the fast tier keeps the loose-setting uniform/disjoint oracles
def test_treecode_matches_dense_fiber_clusters():
    """Line-clustered cloud at the tight setting (depth 2, order 8):
    Stokeslet + stresslet within the plan's target accuracy."""
    pts, f = _fiber_cloud(60, 25, seed=5)
    plan = tc.plan_tree(pts, tol=1e-5)
    assert plan.depth >= 2 and plan.order > tc.order_for_tol(1e-3)
    P, F = jnp.asarray(pts), jnp.asarray(f)
    err_s = _field_rel(tc.stokeslet_tree(plan, P, P, F, 1.0),
                       kernels.stokeslet_direct(P, P, F, 1.0))
    assert err_s < plan.tol, err_s
    S = jnp.asarray(
        np.random.default_rng(8).standard_normal((pts.shape[0], 3, 3)))
    err_t = _field_rel(tc.stresslet_tree(plan, P, P, S, 1.0),
                       kernels.stresslet_direct(P, P, S, 1.0))
    assert err_t < plan.tol, err_t


def test_treecode_disjoint_targets():
    """Targets off the source cloud (velocity-field probes): no self-pair
    anywhere, same accuracy gate."""
    pts, f = _uniform_cloud(1500, seed=13)
    rng = np.random.default_rng(17)
    trg = rng.uniform(-1.4, 1.4, (300, 3))
    plan = tc.plan_tree(np.vstack([pts, trg]), tol=1e-3)
    assert plan.depth >= 2
    P, T, F = jnp.asarray(pts), jnp.asarray(trg), jnp.asarray(f)
    err = _field_rel(tc.stokeslet_tree(plan, P, T, F, 1.0),
                     kernels.stokeslet_direct(P, T, F, 1.0))
    assert err < plan.tol, err


@pytest.mark.slow  # deep-octree case: depth-3 tree + 4k dense oracle
def test_treecode_deep_octree_matches_dense():
    """Depth-3 tree (child->parent transfer path across two levels) on a
    4k clustered cloud — the second (depth, order) setting of the oracle
    suite."""
    pts, f = _fiber_cloud(160, 25, seed=19)
    plan = tc.plan_tree(pts, tol=1e-4, target_occ=8.0)
    assert plan.depth == 3
    P, F = jnp.asarray(pts), jnp.asarray(f)
    err = _field_rel(tc.stokeslet_tree(plan, P, P, F, 1.0),
                     kernels.stokeslet_direct(P, P, F, 1.0))
    assert err < plan.tol, err


@pytest.mark.slow  # 16k-node case (~GB-scale dense oracle tile)
def test_treecode_16k_nodes_matches_dense():
    pts, f = _uniform_cloud(16384, seed=23)
    plan = tc.plan_tree(pts, tol=1e-4)
    assert plan.depth >= 3
    P, F = jnp.asarray(pts), jnp.asarray(f)
    err = _field_rel(tc.stokeslet_tree(plan, P, P, F, 1.0),
                     kernels.stokeslet_direct(P, P, F, 1.0))
    assert err < plan.tol, err


def test_anchor_hop_reuses_compiled_program():
    """A pure translation of the cloud (leaf-lattice anchor hop) must not
    retrace the jitted evaluator: the anchors are traced operands."""
    pts, f = _uniform_cloud(1500, seed=29)
    plan1 = tc.plan_tree(pts, tol=1e-3)
    P, F = jnp.asarray(pts), jnp.asarray(f)
    u1 = tc.stokeslet_tree(plan1, P, P, F, 1.0)
    n_compiled = tc._stokeslet_tree_impl._cache_size()
    shift = 5.0 * plan1.leaf_size
    pts2 = pts + np.array([shift, 0.0, 0.0])
    plan2 = tc.plan_tree(pts2, tol=1e-3)
    assert tc.strip_anchors(plan2) == tc.strip_anchors(plan1)
    u2 = tc.stokeslet_tree(plan2, jnp.asarray(pts2), jnp.asarray(pts2), F,
                           1.0)
    assert tc._stokeslet_tree_impl._cache_size() == n_compiled, \
        "anchor hop forced a recompile"
    # translation invariance of the physics
    np.testing.assert_allclose(np.asarray(u2), np.asarray(u1),
                               rtol=0, atol=1e-8)


# ------------------------------------------------------- PairEvaluator spec

def test_pair_evaluator_spec_validation():
    assert "tree" in EVALUATORS
    with pytest.raises(ValueError, match="unknown pair evaluator"):
        PairEvaluator(evaluator="fmm")
    spec = PairEvaluator(evaluator="tree", impl="exact")
    assert not spec.is_fast          # no plan attached = dense tiles


def test_make_pair_strips_anchors_and_materializes_them():
    pts, _ = _uniform_cloud(1500, seed=31)
    plan = tc.plan_tree(pts, tol=1e-3)
    spec, anchors = make_pair("tree", "exact", plan)
    assert spec.is_fast
    assert spec.plan.box_lo is None                  # stripped = jit key
    np.testing.assert_allclose(np.asarray(anchors)[0], plan.box_lo)
    # a stripped plan's anchors can never be silently re-fabricated (they
    # would be garbage): they must ride next to the spec as the traced
    # operand make_pair returned
    with pytest.raises(ValueError, match="anchor-stripped"):
        tc.plan_anchors(spec.plan)
    spec_d, anchors_d = make_pair("direct", "exact")
    assert spec_d.plan is None and anchors_d is None


def test_system_rejects_unknown_evaluator():
    from skellysim_tpu.params import Params
    from skellysim_tpu.system import System

    with pytest.raises(ValueError, match="tree"):
        System(Params(pair_evaluator="fmm"))


def test_config_schema_maps_tree_evaluator():
    from skellysim_tpu.config import schema

    p = schema.Params(pair_evaluator="tree", tree_tol=3e-4)
    rp = schema.to_runtime_params(p)
    assert rp.pair_evaluator == "tree"
    assert rp.tree_tol == 3e-4


# ------------------------------------------------------------- system solves

def _free_fiber_state(system, n_fib, n_nodes, seed=23):
    from skellysim_tpu.fibers import container as fc
    from skellysim_tpu.system import BackgroundFlow

    rng = np.random.default_rng(seed)
    origins = rng.uniform(-2, 2, (n_fib, 3))
    dirs = rng.normal(size=(n_fib, 3))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    t = np.linspace(0, 1.0, n_nodes)
    x = origins[:, None, :] + t[None, :, None] * dirs[:, None, :]
    fibers = fc.make_group(x, lengths=1.0, bending_rigidity=0.01,
                           radius=0.0125)
    return system.make_state(
        fibers=fibers, background=BackgroundFlow.make(uniform=(1.0, 0.0, 0.0)))


@pytest.mark.slow  # two full System builds + 4 jit compiles (fast-tier budget:
# the not-slow tier sits against the 870s timeout)
def test_system_solve_with_tree_evaluator():
    """Acceptance: pair_evaluator="tree" converges the full implicit step to
    the same GMRES tolerance as the dense path (residual parity,
    tolerance-gated not bitwise), through a REAL depth>=2 tree, and the
    velocity field at off-node targets matches to the evaluator accuracy."""
    from skellysim_tpu.params import Params
    from skellysim_tpu.system import System

    base = Params(eta=1.0, dt_initial=1e-3, t_final=1e-2, gmres_tol=1e-10,
                  adaptive_timestep_flag=False, tree_tol=1e-6)
    probes = jnp.asarray(np.random.default_rng(41).uniform(-2, 2, (32, 3)))
    out = {}
    for ev in ("direct", "tree"):
        system = System(dataclasses.replace(base, pair_evaluator=ev))
        state = _free_fiber_state(system, n_fib=48, n_nodes=24)
        if ev == "tree":
            assert system.make_tree_plan(state).depth >= 2
        new_state, solution, info = system.step(state)
        assert bool(info.converged), ev
        assert float(info.residual) < base.gmres_tol, ev
        out[ev] = (np.asarray(solution),
                   np.asarray(system.velocity_at_targets(new_state, solution,
                                                         probes)))
    err_sol = (np.linalg.norm(out["tree"][0] - out["direct"][0])
               / np.linalg.norm(out["direct"][0]))
    assert err_sol < 1e-5, err_sol
    err_vel = _field_rel(out["tree"][1], out["direct"][1])
    assert err_vel < 1e-4, err_vel


@pytest.mark.slow  # heavy in-process integration (fast-tier budget)
def test_system_tree_with_inactive_padding_fibers():
    """grow_capacity padding (inactive slots replicating slot 0) must not
    blow up leaf occupancy or change results: padded sources are spread
    over the box with zero strength (`fc._spread_inactive`), with capacity
    reserved by `plan_tree(n_fill=...)`."""
    from skellysim_tpu.fibers import container as fc
    from skellysim_tpu.params import Params
    from skellysim_tpu.system import BackgroundFlow, System

    rng = np.random.default_rng(29)
    n_fib, n_nodes = 24, 24
    origins = rng.uniform(-2, 2, (n_fib, 3))
    dirs = rng.normal(size=(n_fib, 3))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    t = np.linspace(0, 1.0, n_nodes)
    x = origins[:, None, :] + t[None, :, None] * dirs[:, None, :]

    params = Params(eta=1.0, dt_initial=1e-3, t_final=1e-2, gmres_tol=1e-8,
                    pair_evaluator="tree", tree_tol=1e-6,
                    adaptive_timestep_flag=False)
    system = System(params)
    fibers = fc.make_group(x, lengths=1.0, bending_rigidity=0.01,
                           radius=0.0125)
    bg = BackgroundFlow.make(uniform=(1.0, 0.0, 0.0))
    state = system.make_state(fibers=fibers, background=bg)
    _, sol_ref, info_ref = system.step(state)
    assert bool(info_ref.converged)

    grown = fc.grow_capacity(fibers, 2 * n_fib)   # half inactive padding
    state_g = system.make_state(fibers=grown, background=bg)
    # plan reserves spread fill capacity, not one hot leaf
    plan = system.make_tree_plan(state_g)
    plan_ref = system.make_tree_plan(state)
    if plan_ref.depth > 0 and plan.depth > 0:
        assert plan.max_occ <= 4 * plan_ref.max_occ
    _, sol_g, info_g = system.step(state_g)
    assert bool(info_g.converged)
    n_active = n_fib * 4 * n_nodes
    err = (np.linalg.norm(np.asarray(sol_g)[:n_active] - np.asarray(sol_ref))
           / np.linalg.norm(np.asarray(sol_ref)))
    assert err < 1e-6, err


@pytest.mark.slow  # multi-device compile (fast-tier budget)
def test_spmd_step_composes_with_tree_evaluator():
    """pair_evaluator="tree" + step_spmd: the Krylov fiber flows route
    through the treecode on every shard (one tiled source all-gather,
    `flow_multi_local`'s tree branch) and the sharded step matches the
    single-chip tree step."""
    from skellysim_tpu.params import Params
    from skellysim_tpu.parallel import make_mesh, shard_state
    from skellysim_tpu.system import System

    params = Params(eta=1.0, dt_initial=1e-3, t_final=1e-2, gmres_tol=1e-10,
                    adaptive_timestep_flag=False, pair_evaluator="tree",
                    tree_tol=1e-6)
    system = System(params)
    state = _free_fiber_state(system, n_fib=48, n_nodes=24)
    assert system.make_tree_plan(state).depth >= 2
    _, sol1, info1 = system.step(state)

    mesh = make_mesh(2)
    st_sh = shard_state(state, mesh)
    _, sol2, info2 = system.step_spmd(st_sh, mesh, donate=False)
    assert bool(info1.converged) and bool(info2.converged)
    assert float(info2.residual) < params.gmres_tol
    err = (np.linalg.norm(np.asarray(sol2) - np.asarray(sol1))
           / np.linalg.norm(np.asarray(sol1)))
    assert err < 1e-9, err


def test_build_spmd_step_rejects_tree_pair_with_inactive_fibers():
    """Direct `build_spmd_step(pair=...)` callers with inactive-padded
    fibers must get a build-time error, not silent point eviction: the
    SPMD layout has no global inactive-slot spread, so padding nodes
    (replicating slot 0) would overflow the plan's static leaf buckets.
    The guard raises before any tracing; `System.step_spmd` instead falls
    back to the ring flows for such states (its all-active gate)."""
    from skellysim_tpu.fibers import container as fc
    from skellysim_tpu.params import Params
    from skellysim_tpu.parallel import make_mesh, shard_state
    from skellysim_tpu.parallel.spmd import build_spmd_step
    from skellysim_tpu.system import System

    params = Params(eta=1.0, dt_initial=1e-3, gmres_tol=1e-8,
                    adaptive_timestep_flag=False, pair_evaluator="tree",
                    tree_tol=1e-4)
    system = System(params)
    state = _free_fiber_state(system, n_fib=16, n_nodes=16)
    grown = fc.grow_capacity(state.fibers, 32)  # half the slots inactive
    state = state._replace(fibers=grown)
    pair, _ = system._pair_args(state)
    assert pair is not None and pair.is_fast
    mesh = make_mesh(2)
    with pytest.raises(ValueError, match="every fiber slot active"):
        build_spmd_step(system, mesh, shard_state(state, mesh), pair=pair)
