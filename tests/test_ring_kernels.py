"""Ring-pass kernels vs dense direct kernels on the 8-device virtual mesh.

The TPU analogue of the reference's kernel-backend consistency matrix
(`/root/reference/tests/core/kernel_test.cpp:1-120`): every backend must agree
with the ground-truth direct evaluation to tight tolerance (the reference
gates at 5e-9 in f64).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skellysim_tpu.ops import kernels
from skellysim_tpu.parallel import (make_mesh, ring_oseen_contract,
                                    ring_stokeslet, ring_stresslet)

N_DEV = 8
GATE = 5e-9  # `kernel_test.cpp:93`


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() >= N_DEV
    return make_mesh(N_DEV)


@pytest.fixture(scope="module")
def cloud():
    rng = np.random.default_rng(7)
    n_src, n_trg = 4 * N_DEV * 3, 4 * N_DEV * 2
    r_src = jnp.asarray(rng.uniform(-1, 1, (n_src, 3)))
    r_trg = jnp.asarray(rng.uniform(-1, 1, (n_trg, 3)))
    f = jnp.asarray(rng.standard_normal((n_src, 3)))
    return r_src, r_trg, f


def _rel_err(a, b):
    a, b = np.asarray(a), np.asarray(b)
    return np.abs(a - b).max() / max(np.abs(b).max(), 1e-300)


def test_ring_stokeslet_matches_direct(mesh, cloud):
    r_src, r_trg, f = cloud
    u_ring = ring_stokeslet(r_src, r_trg, f, 1.7, mesh=mesh)
    u_direct = kernels.stokeslet_direct(r_src, r_trg, f, 1.7)
    assert _rel_err(u_ring, u_direct) < GATE


def test_ring_stokeslet_self_term_masked(mesh):
    """Coincident source/target pairs must drop even across ring blocks."""
    rng = np.random.default_rng(3)
    pts = jnp.asarray(rng.uniform(-1, 1, (2 * N_DEV, 3)))
    f = jnp.asarray(rng.standard_normal((2 * N_DEV, 3)))
    u_ring = ring_stokeslet(pts, pts, f, 1.0, mesh=mesh)
    u_direct = kernels.stokeslet_direct(pts, pts, f, 1.0)
    assert np.all(np.isfinite(np.asarray(u_ring)))
    assert _rel_err(u_ring, u_direct) < GATE


def test_ring_stresslet_matches_direct(mesh, cloud):
    r_src, r_trg, _ = cloud
    rng = np.random.default_rng(11)
    S = jnp.asarray(rng.standard_normal((r_src.shape[0], 3, 3)))
    u_ring = ring_stresslet(r_src, r_trg, S, 0.9, mesh=mesh)
    u_direct = kernels.stresslet_direct(r_src, r_trg, S, 0.9)
    assert _rel_err(u_ring, u_direct) < GATE


def test_ring_oseen_contract_matches_direct(mesh, cloud):
    r_src, r_trg, f = cloud
    u_ring = ring_oseen_contract(r_src, r_trg, f, 1.2, mesh=mesh)
    u_direct = kernels.oseen_contract(r_src, r_trg, f, 1.2)
    assert _rel_err(u_ring, u_direct) < GATE


def test_ring_output_sharding(mesh, cloud):
    """The result stays sharded over the mesh (no implicit gather)."""
    r_src, r_trg, f = cloud
    u = ring_stokeslet(r_src, r_trg, f, 1.0, mesh=mesh)
    assert len(u.sharding.device_set) == N_DEV


def test_ring_mxu_impl_matches_single_program():
    """Ring evaluation with the MXU tiles agrees with the single-program
    exact kernels on well-separated points."""
    import numpy as np

    from skellysim_tpu.ops import kernels
    from skellysim_tpu.parallel import make_mesh
    from skellysim_tpu.parallel.ring import ring_stokeslet, ring_stresslet

    mesh = make_mesh(N_DEV)
    rng = np.random.default_rng(41)
    n = 8 * 16
    r = jnp.asarray(rng.uniform(-10, 10, (n, 3)))
    f = jnp.asarray(rng.standard_normal((n, 3)))
    S = jnp.asarray(rng.standard_normal((n, 3, 3)))
    ref = kernels.stokeslet_direct(r, r, f, 1.2)
    out = ring_stokeslet(r, r, f, 1.2, mesh=mesh, impl="mxu")
    err = np.linalg.norm(np.asarray(out - ref)) / np.linalg.norm(np.asarray(ref))
    assert err < 1e-9, err
    ref_s = kernels.stresslet_direct(r, r, S, 1.2)
    out_s = ring_stresslet(r, r, S, 1.2, mesh=mesh, impl="mxu")
    err = np.linalg.norm(np.asarray(out_s - ref_s)) / np.linalg.norm(np.asarray(ref_s))
    assert err < 1e-9, err


def test_ring_df_fast_agreement(mesh):
    """Non-slow DF-ring coverage on the 8-device virtual mesh (the slow twin
    below adds the pallas_df interpret tiles): the mixed solver's refinement
    matvec path must be exercised in the per-commit tier."""
    from skellysim_tpu.parallel.ring import (ring_stokeslet_df,
                                             ring_stresslet_df)

    rng = np.random.default_rng(47)
    n = 8 * 4
    r = jnp.asarray(rng.uniform(-3, 3, (n, 3)), dtype=jnp.float64)
    f = jnp.asarray(rng.standard_normal((n, 3)), dtype=jnp.float64)
    S = jnp.asarray(rng.standard_normal((n, 3, 3)), dtype=jnp.float64)

    out = ring_stokeslet_df(r, r, f, 1.3, mesh=mesh)
    assert out.dtype == jnp.float64
    ref = kernels.stokeslet_direct(r, r, f, 1.3)
    err = np.linalg.norm(np.asarray(out - ref)) / np.linalg.norm(
        np.asarray(ref))
    assert err < 1e-12, err

    out_s = ring_stresslet_df(r, r, S, 1.3, mesh=mesh)
    ref_s = kernels.stresslet_direct(r, r, S, 1.3)
    err = (np.linalg.norm(np.asarray(out_s - ref_s))
           / np.linalg.norm(np.asarray(ref_s)))
    assert err < 1e-12, err


@pytest.mark.slow
def test_ring_df_tiles_match_f64_direct():
    """Double-float ring tiles (the mixed solver's refinement matvec on a
    mesh) reach DF-class agreement with native-f64 dense kernels — f32
    inputs, f64 output, no emulated f64 in the pair arithmetic."""
    from skellysim_tpu.parallel.ring import (ring_stokeslet_df,
                                             ring_stresslet_df)

    mesh = make_mesh(N_DEV)
    rng = np.random.default_rng(43)
    n = 8 * 16
    r64 = rng.uniform(-3, 3, (n, 3))
    f64 = rng.standard_normal((n, 3))
    S64 = rng.standard_normal((n, 3, 3))
    r, f, S = (jnp.asarray(a, dtype=jnp.float64) for a in (r64, f64, S64))

    ref = kernels.stokeslet_direct(r, r, f, 1.2)
    out = ring_stokeslet_df(r, r, f, 1.2, mesh=mesh)
    assert out.dtype == jnp.float64
    err = np.linalg.norm(np.asarray(out - ref)) / np.linalg.norm(np.asarray(ref))
    assert err < 1e-12, err

    ref_s = kernels.stresslet_direct(r, r, S, 1.2)
    out_s = ring_stresslet_df(r, r, S, 1.2, mesh=mesh)
    err = (np.linalg.norm(np.asarray(out_s - ref_s))
           / np.linalg.norm(np.asarray(ref_s)))
    assert err < 1e-12, err

    # the fused Pallas DF tiles ride the same ring (interpret mode here):
    # same DF-class agreement against the native-f64 dense kernels
    out_p = ring_stokeslet_df(r, r, f, 1.2, mesh=mesh, impl="pallas_df")
    err = np.linalg.norm(np.asarray(out_p - ref)) / np.linalg.norm(
        np.asarray(ref))
    assert err < 1e-12, err
    out_ps = ring_stresslet_df(r, r, S, 1.2, mesh=mesh, impl="pallas_df")
    err = (np.linalg.norm(np.asarray(out_ps - ref_s))
           / np.linalg.norm(np.asarray(ref_s)))
    assert err < 1e-12, err


def test_ring_pallas_impl_matches_single_program():
    """Ring evaluation with the Pallas VMEM tiles (interpret mode on the CPU
    test mesh) agrees with the single-program exact kernels; f64 operands
    fall back to the exact tile like the `ops.kernels` seam."""
    import numpy as np

    from skellysim_tpu.ops import kernels
    from skellysim_tpu.parallel import make_mesh
    from skellysim_tpu.parallel.ring import ring_stokeslet, ring_stresslet

    mesh = make_mesh(N_DEV)
    rng = np.random.default_rng(43)
    n = 8 * 8
    r = jnp.asarray(rng.uniform(-10, 10, (n, 3)), dtype=jnp.float32)
    f = jnp.asarray(rng.standard_normal((n, 3)), dtype=jnp.float32)
    S = jnp.asarray(rng.standard_normal((n, 3, 3)), dtype=jnp.float32)
    ref = kernels.stokeslet_direct(r, r, f, 1.2)
    out = ring_stokeslet(r, r, f, 1.2, mesh=mesh, impl="pallas")
    err = np.linalg.norm(np.asarray(out - ref)) / np.linalg.norm(np.asarray(ref))
    assert err < 1e-5, err
    ref_s = kernels.stresslet_direct(r, r, S, 1.2)
    out_s = ring_stresslet(r, r, S, 1.2, mesh=mesh, impl="pallas")
    err = np.linalg.norm(np.asarray(out_s - ref_s)) / np.linalg.norm(np.asarray(ref_s))
    assert err < 1e-5, err

    # f64 operands route to the exact tile bit-for-bit
    r64 = jnp.asarray(np.asarray(r), dtype=jnp.float64)
    f64 = jnp.asarray(np.asarray(f), dtype=jnp.float64)
    out64 = ring_stokeslet(r64, r64, f64, 1.2, mesh=mesh, impl="pallas")
    ref64 = ring_stokeslet(r64, r64, f64, 1.2, mesh=mesh, impl="exact")
    np.testing.assert_array_equal(np.asarray(out64), np.asarray(ref64))


# ------------------------------------------------------- fused ring (ISSUE 8)

def test_fused_ring_traces_with_correct_shapes():
    """The fused Pallas ring kernel (`parallel.ring_fused`) abstract-evals
    inside shard_map with the ring contract's shapes — compiled execution
    is TPU-only (tests/test_compat.py::test_fused_ring_executes_on_tpu),
    but shape/trace regressions must fail on CPU CI too."""
    from jax.sharding import PartitionSpec as P

    from skellysim_tpu.parallel.compat import shard_map
    from skellysim_tpu.parallel.ring_fused import fused_ring_block_sum

    mesh = make_mesh(4)
    st = jax.ShapeDtypeStruct((64, 3), jnp.float32)
    out = jax.eval_shape(
        shard_map(lambda r, s, f: fused_ring_block_sum(
            "stokeslet", r, s, f, axis_name="fib", n_dev=4),
            mesh=mesh, in_specs=(P("fib"),) * 3, out_specs=P("fib"),
            check_vma=False), st, st, st)
    assert out.shape == (64, 3) and out.dtype == jnp.float32
    # stresslet family: [ns, 3, 3] payload
    out = jax.eval_shape(
        shard_map(lambda r, s, f: fused_ring_block_sum(
            "stresslet", r, s, f, axis_name="fib", n_dev=4),
            mesh=mesh,
            in_specs=(P("fib"), P("fib"), P("fib", None, None)),
            out_specs=P("fib"), check_vma=False),
        st, st, jax.ShapeDtypeStruct((64, 3, 3), jnp.float32))
    assert out.shape == (64, 3)


def test_fused_ring_fits_budget():
    # the budget constant moved to the audit analyzer (single source of
    # truth shared by this build-time gate and the `dma` audit check)
    from skellysim_tpu.audit.dmaflow import VMEM_PAIR_BUDGET
    from skellysim_tpu.parallel.ring_fused import fused_ring_fits

    assert fused_ring_fits("stokeslet", 64, 64, 8)
    assert fused_ring_fits("stresslet", 512, 2048, 8)
    # beyond the whole-block VMEM budget: bandwidth-bound, keep ppermute
    assert not fused_ring_fits("stokeslet", 4096, 4096, 8)
    assert 4096 * 4096 > VMEM_PAIR_BUDGET
    # the n_dev-slot comm buffer has its own budget (slots are never
    # reused within an instance — the ring-safety scheme)
    assert not fused_ring_fits("stresslet", 8, 2048, 256)
    # unknown kernel families never take the fused path
    assert not fused_ring_fits("oseen", 8, 8, 8)


def test_ring_cpu_build_selects_ppermute(mesh, cloud):
    """On the CPU backend the build-time seam keeps every ring on
    ppermute — results bit-match a build with the fused path explicitly
    disabled (i.e. the dispatch really did not take the fused branch)."""
    r_src, r_trg, f = cloud
    rs, rt, f32 = (r_src.astype(jnp.float32), r_trg.astype(jnp.float32),
                   f.astype(jnp.float32))
    u_default = ring_stokeslet(rs, rt, f32, 1.0, mesh=mesh, impl="exact")
    import os

    os.environ["SKELLY_FUSED_RING"] = "0"
    try:
        jax.clear_caches()
        u_off = ring_stokeslet(rs, rt, f32, 1.0, mesh=mesh, impl="exact")
    finally:
        os.environ.pop("SKELLY_FUSED_RING", None)
    assert np.array_equal(np.asarray(u_default), np.asarray(u_off))
