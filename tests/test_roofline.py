"""skelly-roofline: the per-phase roofline join (`obs roofline`), the
vs-best perf gate trajectory, and the `bench.py --campaign` manifest
contract.

The oracle tests drive `roofline.analyze` with every input injected and
check against values RE-DERIVED BY HAND from the checked-in profile
fixture's phase walls (tests/golden/profile_fixture/) and the hand-sized
cost sidecar (cost_sidecar.toml, AI exactly 2.0) — not against the code
under test.
"""

import json
import os

import pytest

from skellysim_tpu.obs import roofline
from skellysim_tpu.obs.profile import load_device_trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "golden", "profile_fixture")
SIDECAR = os.path.join(FIXTURE, "cost_sidecar.toml")

# the fixture's per-phase rollup, summed by hand from plugins/profile/
# mini_run (pinned: a fixture edit must update these AND the oracle)
PHASE_WALL_US = {
    "gmres/psum-dots": 371.292,
    "gmres/arnoldi": 150.438,
    "prep": 32.222,
    "(unattributed)": 22.216,
    "advance": 3.313,
}
PSUM_COMM_US = 313.476           # all_reduce dur inside gmres/psum-dots
PSUM_COMM_COUNT = 4
TOTAL_US = sum(PHASE_WALL_US.values())                       # 579.481
TOTAL_COMPUTE_US = TOTAL_US - PSUM_COMM_US                   # 266.005

# the sidecar's hand-sized table
FLOPS, BYTES, COMM_MAX_BYTES = 2.0e9, 1.0e9, 4096.0
# synthetic peaks chosen so ridge == 1.0 < AI == 2.0 (compute-bound)
PEAKS = {"peak_flops": 1e13, "hbm_gbps": 1e4, "ici_gbps": 1.0}


# --------------------------------------------------------- rating table

def test_device_peaks_table_rows_complete():
    table = roofline.load_device_peaks()
    assert table, "device_peaks.toml must ship rated rows"
    for key, row in table.items():
        for k in roofline.PEAK_KEYS:
            assert k in row and float(row[k]) > 0, (key, k)


def test_peaks_for_kind_longest_substring_wins():
    key, peaks = roofline.peaks_for_kind("TPU v5p-8")
    assert key == "TPU v5p"          # not the shorter "TPU v5" row
    key5, _ = roofline.peaks_for_kind("TPU v5 lite")
    assert key5 == "TPU v5"
    assert roofline.peaks_for_kind("QPU v99") == (None, None)
    assert roofline.peaks_for_kind(None) == (None, None)
    assert roofline.peaks_for_kind("") == (None, None)


# -------------------------------------------------------- oracle: analyze

def test_analyze_matches_hand_computed_oracle():
    trace = load_device_trace(FIXTURE)
    doc = roofline.analyze(
        trace, cost={"flops": FLOPS, "bytes_accessed": BYTES,
                     "peak_bytes": 123456},
        collective_bytes={"all_reduce": COMM_MAX_BYTES},
        peaks=PEAKS, executions=1, n_devices=1)

    assert doc["ai"] == pytest.approx(2.0)
    assert doc["ridge_flops_per_byte"] == pytest.approx(1.0)
    assert doc["total_us"] == pytest.approx(TOTAL_US, abs=1e-3)
    assert doc["peak_memory_bytes"] == 123456
    by = {p["phase"]: p for p in doc["phases"]}
    assert set(by) == set(PHASE_WALL_US)

    # comms-bound phase: collectives take 313.476/371.292 = 84% of its
    # wall; ICI rate = pinned bytes over measured comm time
    psum = by["gmres/psum-dots"]
    assert psum["comm_frac"] == pytest.approx(
        PSUM_COMM_US / PHASE_WALL_US["gmres/psum-dots"], abs=1e-4)
    assert psum["verdict"] == "comms-bound"
    assert psum["comm_bytes"] == pytest.approx(
        PSUM_COMM_COUNT * COMM_MAX_BYTES)
    ici_bps = PSUM_COMM_COUNT * COMM_MAX_BYTES / (PSUM_COMM_US * 1e-6)
    assert psum["ici_bytes_per_s"] == pytest.approx(ici_bps, rel=1e-4)
    assert psum["achieved_vs_peak"] == pytest.approx(
        ici_bps / (PEAKS["ici_gbps"] * 1e9), rel=1e-3)

    # compute phase: program flops apportioned over compute self-time, so
    # every pure-compute phase achieves flops_total / total_compute_time
    # per chip; AI 2.0 >= ridge 1.0 -> compute-bound, vs-peak vs peak_flops
    arnoldi = by["gmres/arnoldi"]
    frac = PHASE_WALL_US["gmres/arnoldi"] / TOTAL_COMPUTE_US
    assert arnoldi["verdict"] == "compute-bound"
    assert arnoldi["flops"] == pytest.approx(FLOPS * frac, rel=1e-4)
    achieved = FLOPS / (TOTAL_COMPUTE_US * 1e-6)
    assert arnoldi["achieved_flops_per_s"] == pytest.approx(achieved,
                                                            rel=1e-4)
    assert arnoldi["achieved_vs_peak"] == pytest.approx(
        achieved / PEAKS["peak_flops"], rel=1e-3)

    # every named phase got a verdict + ratio -> classified == attributed
    assert doc["classified_frac"] == pytest.approx(
        (TOTAL_US - PHASE_WALL_US["(unattributed)"]) / TOTAL_US, abs=1e-4)
    assert doc["classified_frac"] == pytest.approx(doc["attributed_frac"],
                                                   abs=1e-4)
    # window MFU: program flops over the whole per-chip window
    assert doc["totals"]["mfu"] == pytest.approx(
        FLOPS / (TOTAL_US * 1e-6) / PEAKS["peak_flops"], rel=1e-3)


def test_analyze_memory_bound_when_ai_below_ridge():
    # same join, peaks with ridge 166 >> AI 2.0: compute phases flip to
    # memory-bound and rate against HBM instead of flops
    trace = load_device_trace(FIXTURE)
    peaks = {"peak_flops": 459e12, "hbm_gbps": 2765.0, "ici_gbps": 600.0}
    doc = roofline.analyze(
        trace, cost={"flops": FLOPS, "bytes_accessed": BYTES},
        collective_bytes={"all_reduce": COMM_MAX_BYTES},
        peaks=peaks, n_devices=1)
    by = {p["phase"]: p for p in doc["phases"]}
    assert by["gmres/arnoldi"]["verdict"] == "memory-bound"
    achieved_bps = BYTES / (TOTAL_COMPUTE_US * 1e-6)
    assert by["gmres/arnoldi"]["achieved_vs_peak"] == pytest.approx(
        achieved_bps / (2765.0 * 1e9), rel=1e-3)
    assert by["gmres/psum-dots"]["verdict"] == "comms-bound"


def test_analyze_unknown_device_kind_degrades_not_crashes():
    trace = load_device_trace(FIXTURE)
    doc = roofline.analyze(
        trace, cost={"flops": FLOPS, "bytes_accessed": BYTES},
        collective_bytes={}, peaks=None, n_devices=1)
    by = {p["phase"]: p for p in doc["phases"]}
    # the comm/compute split is measured, so comms-bound SURVIVES unrated
    assert by["gmres/psum-dots"]["verdict"] == "comms-bound"
    for name in ("gmres/arnoldi", "prep", "advance"):
        assert by[name]["verdict"] == "unrated"
    assert all(p["achieved_vs_peak"] is None for p in doc["phases"])
    assert doc["classified_frac"] == 0.0
    assert doc["ridge_flops_per_byte"] is None


def test_analyze_without_cost_table_keeps_measured_facts():
    trace = load_device_trace(FIXTURE)
    doc = roofline.analyze(trace, cost=None,
                           collective_bytes={"all_reduce": COMM_MAX_BYTES},
                           peaks=PEAKS, n_devices=1)
    by = {p["phase"]: p for p in doc["phases"]}
    assert by["gmres/psum-dots"]["verdict"] == "comms-bound"
    assert by["gmres/psum-dots"]["ici_bytes_per_s"] is not None
    assert by["gmres/arnoldi"]["verdict"] == "unrated"
    assert doc["ai"] is None and doc["totals"]["mfu"] is None


# -------------------------------------------------- report + CLI contract

def test_roofline_report_sidecar_join_and_rating():
    doc = roofline.roofline_report(FIXTURE, cost_table=SIDECAR,
                                   device_kind="TPU v5p")
    assert doc["rated_as"] == "TPU v5p"
    assert doc["ai"] == pytest.approx(2.0)
    by = {p["phase"]: p for p in doc["phases"]}
    # the sidecar pins all_reduce bytes -> the psum phase is sized
    assert by["gmres/psum-dots"]["comm_bytes"] == pytest.approx(
        PSUM_COMM_COUNT * COMM_MAX_BYTES)
    assert not by["gmres/psum-dots"]["unsized_collectives"]
    text = roofline.render_roofline(doc)
    assert "rated as 'TPU v5p'" in text and "comms-bound" in text

    unknown = roofline.roofline_report(FIXTURE, cost_table=SIDECAR,
                                       device_kind="QPU v99")
    assert unknown["rated_as"] is None
    assert {p["verdict"] for p in unknown["phases"]} <= {"unrated",
                                                         "comms-bound"}
    assert "UNRATED" in roofline.render_roofline(unknown)


def test_roofline_report_program_baseline_join():
    # the checked-in step_spmd_d2 baseline + audit contract join without
    # any sidecar: flops from obs/baselines/, comm bytes from the
    # contract's max_bytes pins (all_reduce = 3360)
    doc = roofline.roofline_report(FIXTURE, program="step_spmd_d2",
                                   device_kind="cpu")
    assert doc["rated_as"] == "cpu"
    assert doc["ai"] is not None and doc["ai"] > 0
    by = {p["phase"]: p for p in doc["phases"]}
    coll = by["gmres/psum-dots"]["collectives"]["all_reduce"]
    assert coll["bytes"] == pytest.approx(PSUM_COMM_COUNT * 3360.0)
    with pytest.raises(KeyError):
        roofline.roofline_report(FIXTURE, program="no_such_program")


def test_roofline_cli_exit_codes(tmp_path, capsys):
    from skellysim_tpu.obs.cli import main

    assert main(["roofline", str(tmp_path / "nope")]) == 2
    capsys.readouterr()
    assert main(["roofline", FIXTURE, "--program", "no_such_program"]) == 2
    assert "no cost baseline" in capsys.readouterr().err
    rc = main(["roofline", FIXTURE, "--cost-table", SIDECAR,
               "--device-kind", "TPU v5p", "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    doc = json.loads(out)
    assert doc["rated_as"] == "TPU v5p"
    assert doc["phases"] and doc["classified_frac"] > 0.9


# ------------------------------------------------- perf: vs-best gating

def _round(dirpath, group, number, doc):
    p = os.path.join(str(dirpath), f"{group}_r{number:02d}.json")
    with open(p, "w") as fh:
        json.dump(doc, fh)


def test_perf_vs_best_catches_slow_drift(tmp_path):
    """Three real rounds drifting -15% each: every ADJACENT diff is
    within the 25% gate, but r03 vs the r01 best is -27.5% -> the
    vs-best gate fails the run. Downscaling either end softens to WARN."""
    from skellysim_tpu.obs.perf import render_report, report_json

    for n, v in ((1, 2.0), (2, 1.7), (3, 1.45)):
        _round(tmp_path, "DRIFT", n, {"m": {"speedup_vs_1dev": v}})
    report, rc = render_report(str(tmp_path), gate_pct=25.0)
    assert rc == 1
    assert "vs best" in report and "REGRESSION" in report
    doc, jrc = report_json(str(tmp_path), gate_pct=25.0)
    assert jrc == 1 and doc["failures"] >= 1
    entry = doc["groups"]["drift"]
    assert entry["verdict"] == "FAIL"
    assert entry["best"]["m.speedup_vs_1dev"]["value"] == 2.0
    assert entry["best"]["m.speedup_vs_1dev"]["round"] == "r01"

    # same drift but the latest round is a downscaled CPU run: WARN only
    _round(tmp_path, "DRIFT", 3, {"m": {"speedup_vs_1dev": 1.45},
                                  "downscaled": True})
    report, rc = render_report(str(tmp_path), gate_pct=25.0)
    assert rc == 0 and "WARN (downscaled" in report

    # ... and a downscaled BEST cannot hard-gate a real round either
    _round(tmp_path, "DRIFT", 1, {"m": {"speedup_vs_1dev": 2.0},
                                  "downscaled": True})
    _round(tmp_path, "DRIFT", 3, {"m": {"speedup_vs_1dev": 1.45}})
    report, rc = render_report(str(tmp_path), gate_pct=25.0)
    assert rc == 0


def test_perf_trajectory_renders_best_row(tmp_path):
    from skellysim_tpu.obs.perf import render_report

    for n, v in ((1, 1.0), (2, 3.0), (3, 2.5)):
        _round(tmp_path, "TRAJ", n, {"m": {"speedup_vs_1dev": v}})
    report, rc = render_report(str(tmp_path), gate_pct=90.0)
    assert rc == 0
    assert "best" in report and "3@r02" in report


def test_perf_scan_skips_campaign_manifests(tmp_path):
    from skellysim_tpu.obs.perf import scan_rounds

    _round(tmp_path, "REAL", 1, {"m": {"speedup_vs_1dev": 1.0}})
    _round(tmp_path, "CAMPAIGN", 1, {"groups": {}, "gate": {"rc": 0}})
    assert set(scan_rounds(str(tmp_path))) == {"real"}


# -------------------------------------------------- campaign manifests

def _valid_manifest():
    return {
        "round": "r01",
        "generated_by": "bench.py --campaign",
        "groups": {"flight": {"status": "ok", "s": 12.0},
                   "kernels": {"status": "skipped_budget", "s": 0.0}},
        "rounds": {"flight": "r02"},
        "rooflines": {"flight": {"program": "step_flight",
                                 "classified_frac": 0.98,
                                 "phases": [{"phase": "prep",
                                             "verdict": "memory-bound"}]}},
        "gate": {"rc": 0, "report": {"groups": {"flight":
                                                {"verdict": "PASS"}}}},
        "backend": "cpu", "jax_version": "0.0", "device_kind": "cpu",
        "downscaled": True, "downscale_reason": "test",
        "telemetry_version": 1,
    }


def test_campaign_validate_and_render():
    from skellysim_tpu.obs.perf import render_campaign, validate_campaign

    doc = _valid_manifest()
    assert validate_campaign(doc) == []
    text = render_campaign(doc)
    assert "campaign r01" in text
    assert "flight" in text and "memory-bound" in text
    assert "[DOWNSCALED]" in text and "gate: rc=0" in text

    bad = _valid_manifest()
    bad.pop("device_kind")
    assert any("device_kind" in e for e in validate_campaign(bad))
    bad = _valid_manifest()
    bad["groups"]["flight"]["status"] = "exploded"
    assert validate_campaign(bad)
    bad = _valid_manifest()
    bad["round"] = "seven"
    assert validate_campaign(bad)
    bad = _valid_manifest()
    bad["downscaled"] = "yes"          # must be an explicit bool
    assert validate_campaign(bad)
    assert validate_campaign({"round": "r01"})   # missing everything else


def test_campaign_cli_exit_codes(tmp_path, capsys):
    from skellysim_tpu.obs.cli import main

    assert main(["campaign", str(tmp_path / "nope.json")]) == 2
    capsys.readouterr()

    p = tmp_path / "CAMPAIGN_r01.json"
    p.write_text(json.dumps(_valid_manifest()))
    assert main(["campaign", str(p)]) == 0
    assert "gate: rc=0" in capsys.readouterr().out

    failed = _valid_manifest()
    failed["gate"] = {"rc": 1}
    p.write_text(json.dumps(failed))
    # a failed armed gate propagates through the manifest CLI
    assert main(["campaign", str(p)]) == 1
    capsys.readouterr()

    invalid = _valid_manifest()
    invalid.pop("groups")
    p.write_text(json.dumps(invalid))
    assert main(["campaign", str(p)]) == 2
    assert "groups" in capsys.readouterr().err

    assert main(["campaign", str(p), "--json"]) == 2


def test_checked_in_campaign_manifest_validates():
    """The committed CAMPAIGN round must satisfy its own validator (the
    same check `obs campaign` applies), carry the uniform provenance
    stamp, and reference only known bench groups."""
    import glob

    from skellysim_tpu.obs.perf import (CAMPAIGN_PROVENANCE_KEYS,
                                        validate_campaign)

    paths = sorted(glob.glob(os.path.join(REPO, "benchmarks",
                                          "CAMPAIGN_r*.json")))
    assert paths, "a campaign round must be checked in under benchmarks/"
    with open(paths[-1]) as fh:
        doc = json.load(fh)
    assert validate_campaign(doc) == []
    for key in CAMPAIGN_PROVENANCE_KEYS:
        assert key in doc, key
    assert isinstance(doc["downscaled"], bool)
    if doc["downscaled"]:
        assert doc.get("downscale_reason")
    assert doc["rooflines"], "campaign must carry roofline summaries"


# ---------------------------------------------- slow acceptance pins

@pytest.mark.slow
def test_d2_roofline_acceptance(tmp_path):
    """Acceptance pin (skelly-roofline): `obs roofline` over a profile of
    the d2 SPMD coupled solve classifies >= 90% of attributed device time
    — every counted phase carries a bound verdict AND an
    achieved-vs-peak ratio (classified_frac counts nothing less)."""
    import numpy as np

    from skellysim_tpu.audit import fixtures
    from skellysim_tpu.obs import profile as profile_mod
    from skellysim_tpu.parallel.mesh import make_mesh

    system = fixtures.make_system(shell=True)
    state = fixtures.coupled_state(system)
    mesh = make_mesh(2)
    _, sol, _ = system.step_spmd(state, mesh, donate=False)
    np.asarray(sol)   # compile + drain outside the capture window
    prof_dir = str(tmp_path / "prof_d2")
    with profile_mod.profile_session(prof_dir):
        _, sol, _ = system.step_spmd(state, mesh, donate=False)
        np.asarray(sol)

    doc = roofline.roofline_report(prof_dir, program="step_spmd_d2",
                                   device_kind="cpu")
    assert doc["rated_as"] == "cpu"
    # >= 90% of ATTRIBUTED time classified (the fixture's provenance
    # sidecar rates the dump; cpu peaks are nominal but rated)
    assert doc["attributed_frac"] >= 0.9
    assert doc["classified_frac"] >= 0.9 * doc["attributed_frac"], doc
    for p in doc["phases"]:
        if p["phase"] == "(unattributed)":
            continue
        assert p["verdict"] in roofline.VERDICTS[:3], p
        assert p["achieved_vs_peak"] is not None, p
    # provenance sidecar landed and self-rated the dump
    assert (doc.get("provenance") or {}).get("device_kind")


@pytest.mark.slow
def test_campaign_one_group_end_to_end(tmp_path):
    """`bench.py --campaign --campaign-groups flight` on the CPU box:
    one command -> archived FLIGHT round with the uniform provenance
    stamp, a validated downscale-stamped CAMPAIGN manifest with a
    roofline summary, and a WARN-only (rc=0) gate."""
    import subprocess
    import sys

    archive = tmp_path / "benchmarks"
    archive.mkdir()
    env = dict(os.environ)
    env.update({
        "BENCH_FORCE_CPU": "1", "BENCH_PROBE_S": "1",
        "BENCH_BUDGET_S": "160",
        "BENCH_ARCHIVE_DIR": str(archive),
        "BENCH_JSON_PATH": str(tmp_path / "BENCH.json"),
        "BENCH_MULTICHIP_PATH": str(tmp_path / "MULTICHIP.json"),
        "BENCH_TREECODE_PATH": str(tmp_path / "TREECODE.json"),
        "BENCH_TRACE_PATH": str(tmp_path / "trace.jsonl"),
        "BENCH_PROFILE_ROOT": str(tmp_path / "prof"),
    })
    env.pop("JAX_PLATFORMS", None)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    env["XLA_FLAGS"] = " ".join(flags) if flags else ""
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--campaign",
         "--campaign-groups", "flight"],
        capture_output=True, text=True, timeout=260, env=env, cwd=REPO)
    assert p.returncode == 0, p.stderr[-2000:]
    line = json.loads([ln for ln in p.stdout.splitlines() if ln.strip()][0])
    assert line["campaign"]["round"] == "r01"
    assert line["campaign"]["gate_rc"] == 0    # downscaled -> WARN only

    from skellysim_tpu.obs.perf import validate_campaign

    with open(archive / "CAMPAIGN_r01.json") as fh:
        manifest = json.load(fh)
    assert validate_campaign(manifest) == []
    assert manifest["downscaled"] is True      # CPU box, stamped
    assert manifest["groups"]["flight"]["status"] == "ok"
    assert manifest["rounds"]["flight"] == "r01"   # empty archive dir
    assert "flight" in manifest["rooflines"]

    with open(archive / "FLIGHT_r01.json") as fh:
        flight = json.load(fh)
    for key in ("backend", "jax_version", "device_kind", "downscaled",
                "telemetry_version", "round"):
        assert key in flight, key
    assert flight["downscaled"] is True
