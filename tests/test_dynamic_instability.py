"""RNG + dynamic instability tests.

Statistical oracles follow the reference's dynamic-instability probe
(`tests/core/dynamic_instability_test.cpp:18-50` records count/length
trajectories) plus exact catastrophe/nucleation probabilities from
`dynamic_instability.cpp:83-84,115-116`.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from skellysim_tpu.bodies import bodies as bd
from skellysim_tpu.fibers import container as fc
from skellysim_tpu.params import DynamicInstability, Params
from skellysim_tpu.periphery.precompute import precompute_body
from skellysim_tpu.system import System, apply_dynamic_instability
from skellysim_tpu.system.dynamic_instability import _grow_capacity
from skellysim_tpu.utils.rng import SimRNG


def make_body_with_sites(n_sites=20, radius=0.5):
    pre = precompute_body("sphere", 200, radius=radius)
    rng = np.random.default_rng(7)
    sites = rng.standard_normal((n_sites, 3))
    sites = radius * sites / np.linalg.norm(sites, axis=1, keepdims=True)
    return bd.make_group(pre["node_positions_ref"], pre["node_normals_ref"],
                         pre["node_weights"], nucleation_sites_ref=sites[None],
                         radius=radius)


def di_params(**kw):
    base = dict(n_nodes=16, v_growth=0.5, f_catastrophe=1.0,
                nucleation_rate=10.0, min_length=0.4,
                radius=0.0125, bending_rigidity=0.01)
    base.update(kw)
    di = DynamicInstability(**base)
    return Params(eta=1.0, dt_initial=0.05, t_final=1.0, gmres_tol=1e-8,
                  adaptive_timestep_flag=False, dynamic_instability=di)


def make_state(params, bodies=None, fibers=None):
    system = System(params)
    return system, system.make_state(fibers=fibers, bodies=bodies)


# ------------------------------------------------------------------------ RNG

def test_rng_dump_restore_reproduces_sequence():
    a = SimRNG(seed=42)
    _ = a.distributed.uniform(size=5)
    state = a.dump_state()
    seq1 = [a.distributed.uniform(), a.distributed.poisson_int(3.0),
            a.distributed.uniform_int(0, 100)]
    b = SimRNG.from_state(state)
    seq2 = [b.distributed.uniform(), b.distributed.poisson_int(3.0),
            b.distributed.uniform_int(0, 100)]
    assert seq1 == seq2
    # streams are independent
    c = SimRNG(seed=42)
    assert c.shared.uniform() != c.distributed.uniform()


# -------------------------------------------------------------- catastrophe

def test_catastrophe_survival_fraction():
    """Survival probability over one step must be exp(-dt * f_cat)."""
    nf, n = 2000, 16
    x = np.tile(np.linspace(0, 1, n)[None, :, None], (nf, 1, 3))
    fibers = fc.make_group(x, lengths=1.0, bending_rigidity=0.01, radius=0.0125)
    params = di_params(nucleation_rate=0.0)
    system, state = make_state(params, fibers=fibers,
                               bodies=make_body_with_sites())
    rng = SimRNG(seed=0)
    out = apply_dynamic_instability(state, params, rng)
    frac = float(np.asarray(out.fibers.active).mean())
    expected = np.exp(-0.05 * 1.0)
    assert frac == pytest.approx(expected, abs=3 * np.sqrt(expected / nf))
    # survivors grew, dead fibers kept their length
    grown = np.asarray(out.fibers.length)[np.asarray(out.fibers.active)]
    assert np.allclose(grown, 1.0 + 0.05 * 0.5)


def test_plus_pinned_scales_rates():
    nf, n = 4000, 16
    x = np.tile(np.linspace(0, 1, n)[None, :, None], (nf, 1, 3))
    fibers = fc.make_group(x, lengths=1.0, bending_rigidity=0.01, radius=0.0125)
    fibers = fibers._replace(plus_pinned=jnp.ones(nf, dtype=bool))
    params = di_params(nucleation_rate=0.0)
    system, state = make_state(params, fibers=fibers,
                               bodies=make_body_with_sites())
    out = apply_dynamic_instability(state, params, SimRNG(seed=1))
    frac = float(np.asarray(out.fibers.active).mean())
    # f_cat doubled by default collision scale
    expected = np.exp(-0.05 * 2.0)
    assert frac == pytest.approx(expected, abs=3 * np.sqrt(expected / nf))
    grown = np.asarray(out.fibers.length)[np.asarray(out.fibers.active)]
    assert np.allclose(grown, 1.0 + 0.05 * 0.5 * 0.5)  # v_growth halved


# --------------------------------------------------------------- nucleation

def test_nucleation_fills_free_sites():
    params = di_params(f_catastrophe=0.0, nucleation_rate=1e3)
    bodies = make_body_with_sites(n_sites=12)
    system, state = make_state(params, bodies=bodies)
    rng = SimRNG(seed=3)
    out = apply_dynamic_instability(state, params, rng)
    fibers = out.fibers
    assert fibers is not None
    active = np.asarray(fibers.active)
    assert active.sum() > 0
    # no duplicate sites
    bb = np.asarray(fibers.binding_body)[active]
    bs = np.asarray(fibers.binding_site)[active]
    assert len(set(zip(bb.tolist(), bs.tolist()))) == active.sum()
    # fibers point radially from the body's position at min_length
    _, _, sites = bd.place(out.bodies)
    sites = np.asarray(sites)[0]
    x = np.asarray(fibers.x)[active]
    for k in range(x.shape[0]):
        d = np.linalg.norm(x[k, -1] - x[k, 0])
        assert d == pytest.approx(params.dynamic_instability.min_length)
        np.testing.assert_allclose(x[k, 0], sites[bs[k]], atol=1e-12)
    assert np.all(np.asarray(fibers.minus_clamped)[active])

    # a second application must not nucleate onto occupied sites
    out2 = apply_dynamic_instability(out, params, rng)
    active2 = np.asarray(out2.fibers.active)
    bb2 = np.asarray(out2.fibers.binding_body)[active2]
    bs2 = np.asarray(out2.fibers.binding_site)[active2]
    assert len(set(zip(bb2.tolist(), bs2.tolist()))) == active2.sum()
    assert active2.sum() <= 12


def test_nucleation_rate_statistics():
    """Mean nucleations ~= dt * rate * n_free over many trials."""
    params = di_params(f_catastrophe=0.0, nucleation_rate=2.0)
    bodies = make_body_with_sites(n_sites=50)
    system, state = make_state(params, bodies=bodies)
    rng = SimRNG(seed=9)
    counts = []
    for _ in range(300):
        out = apply_dynamic_instability(state, params, rng)
        counts.append(int(np.asarray(out.fibers.active).sum())
                      if out.fibers is not None else 0)
    mean = np.mean(counts)
    lam = 0.05 * 2.0 * 50
    assert mean == pytest.approx(lam, abs=4 * np.sqrt(lam / 300))


def test_capacity_growth_preserves_state():
    x = np.tile(np.linspace(0, 1, 16)[None, :, None], (3, 1, 3))
    fibers = fc.make_group(x, lengths=1.0, bending_rigidity=0.01, radius=0.0125)
    grown = _grow_capacity(fibers, 8)
    assert grown.n_fibers == 8
    assert np.asarray(grown.active).sum() == 3
    np.testing.assert_array_equal(np.asarray(grown.x)[:3], x)
    assert np.all(np.asarray(grown.binding_body)[3:] == -1)


def test_capacity_growth_padding_is_finite_in_flow():
    """Regression: zero-padded slots (length=0) made the fiber cache NaN and
    0-weight * NaN leaked through the stokeslet sum, poisoning all targets."""
    import jax.numpy as jnp
    x = np.tile(np.linspace(0, 1, 16)[None, :, None], (2, 1, 3)) \
        + np.array([[[1.0, 0, 0]], [[-1.0, 0, 0]]])
    fibers = fc.make_group(x, lengths=1.0, bending_rigidity=0.01, radius=0.0125)
    grown = _grow_capacity(fibers, 5)
    # device round-trip of every ARRAY leaf (optional fields — rt_mats,
    # absent metadata — stay as-is: jnp.asarray(None) is NaN-bound)
    grown = grown._replace(**{
        name: jnp.asarray(leaf)
        for name, leaf in zip(grown._fields, grown)
        if name != "rt_mats" and leaf is not None})
    caches = fc.update_cache(grown, dt=0.01, eta=1.0)
    for leaf in caches:
        assert np.all(np.isfinite(np.asarray(leaf))), "NaN in fiber cache"
    r_trg = jnp.asarray(np.random.default_rng(0).uniform(-2, 2, (7, 3)))
    forces = jnp.zeros_like(grown.x)
    u = fc.flow(grown, caches, r_trg, forces, eta=1.0, subtract_self=False)
    assert np.all(np.isfinite(np.asarray(u)))


# ------------------------------------------------------------- integration

def test_run_loop_with_dynamic_instability():
    """End-to-end: nucleate, solve, grow; solver must stay convergent."""
    params = Params(eta=1.0, dt_initial=0.02, t_final=0.08, gmres_tol=1e-8,
                    adaptive_timestep_flag=False,
                    dynamic_instability=DynamicInstability(
                        n_nodes=16, v_growth=0.2, f_catastrophe=0.5,
                        nucleation_rate=50.0, min_length=0.4,
                        radius=0.0125, bending_rigidity=0.01))
    bodies = make_body_with_sites(n_sites=8, radius=0.5)
    system = System(params)
    state = system.make_state(bodies=bodies)
    rng = SimRNG(seed=11)
    final = system.run(state, rng=rng)
    assert final.fibers is not None
    assert np.asarray(final.fibers.active).sum() > 0
    assert float(final.time) >= params.t_final
    # bound fibers still rooted on their (possibly moved) nucleation sites
    _, _, sites = bd.place(final.bodies)
    sites = np.asarray(sites)[0]
    act = np.asarray(final.fibers.active)
    bs = np.asarray(final.fibers.binding_site)[act]
    x0 = np.asarray(final.fibers.x)[act][:, 0]
    np.testing.assert_allclose(x0, sites[bs], atol=1e-8)


def test_nucleation_into_grown_slots_keeps_fd_defaults():
    """Slots created by capacity growth must get real penalty/beta_tstep."""
    from skellysim_tpu.fibers import fd_fiber

    params = di_params(f_catastrophe=0.0, nucleation_rate=1e4)
    bodies = make_body_with_sites(n_sites=30)
    # a full 2-slot group of unbound fibers: nucleation must grow capacity
    x = np.tile(np.linspace(0, 1, 16)[None, :, None], (2, 1, 3)) + 3.0
    fibers = fc.make_group(x, lengths=1.0, bending_rigidity=0.01, radius=0.0125)
    system, state = make_state(params, bodies=bodies, fibers=fibers)
    out2 = apply_dynamic_instability(state, params, SimRNG(seed=21))
    active2 = np.asarray(out2.fibers.active)
    assert out2.fibers.n_fibers > 2 and active2.sum() > 2  # capacity grew
    assert np.all(np.asarray(out2.fibers.penalty)[active2]
                  == fd_fiber.DEFAULT_PENALTY)
    assert np.all(np.asarray(out2.fibers.beta_tstep)[active2]
                  == fd_fiber.DEFAULT_BETA_TSTEP)
