"""Mixed-precision solver: f32 Krylov + LU, f64 refinement to reference tols.

TPU XLA's `LuDecomposition` is f32-only and the MXU prefers f32, but the
reference's gates are f64-grade (GMRES tol 1e-10, `solver_hydro.cpp:71-78`;
Stokes drag 1e-6, `tests/combined/test_body_const_force.py:81`). The `mixed`
solver precision (Params.solver_precision) answers this with iterative
refinement (`solver.gmres_ir`): these tests pin that the f64 tolerance is
actually reached while every LU factor in play is float32.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skellysim_tpu.bodies import bodies as bd
from skellysim_tpu.fibers import container as fc
from skellysim_tpu.params import Params
from skellysim_tpu.solver import gmres_ir
from skellysim_tpu.system import System
from skellysim_tpu.testing import make_coupled_parts


def test_gmres_ir_reaches_f64_tol_with_f32_inner():
    """A dense SPD-ish f64 system solved to 1e-12 via f32 inner solves."""
    rng = np.random.default_rng(3)
    n = 120
    A = jnp.asarray(rng.standard_normal((n, n)) / np.sqrt(n) + 3.0 * np.eye(n))
    x_true = jnp.asarray(rng.standard_normal(n))
    b = A @ x_true

    A32 = A.astype(jnp.float32)
    res = gmres_ir(lambda v: A @ v, lambda v: A32 @ v, b,
                   tol=1e-12, inner_tol=1e-5, restart=60, maxiter=600)
    assert res.x.dtype == jnp.float64
    assert bool(res.converged)
    assert float(res.residual) <= 1e-12
    assert float(jnp.linalg.norm(res.x - x_true) / jnp.linalg.norm(x_true)) < 1e-10


@pytest.mark.slow  # heavy coupled-solve integration; sibling fast tests keep the seam covered (ISSUE-9 870s-budget re-triage)
def test_mixed_coupled_solve_hits_reference_tol():
    """Walkthrough-style coupled scene: mixed mode reaches gmres_tol=1e-10
    (the reference's tolerance class) with f32 LU preconditioners."""
    dtype = jnp.float64
    shell, shape, bodies = make_coupled_parts(192, 96, dtype)
    t = np.linspace(0, 1, 32)
    x = np.array([0.0, 3.0, 0.0])[None, :] + t[:, None] * np.array([0.0, 0.0, 1.0])
    fibers = fc.make_group(x[None], lengths=1.0, bending_rigidity=0.01,
                           radius=0.0125, dtype=dtype)
    params = Params(eta=1.0, dt_initial=0.1, t_final=1.0, gmres_tol=1e-10,
                    solver_precision="mixed", adaptive_timestep_flag=False)
    system = System(params, shell_shape=shape)
    state = system.make_state(fibers=fibers, shell=shell, bodies=bodies)

    # the preconditioner factors really are f32 (what TPU LU requires);
    # _prep returns per-bucket lists since the heterogeneous-buckets refactor
    _, caches, body_caches, _, _ = system._prep(state)
    assert caches[0].lu.dtype == jnp.float32
    assert body_caches[0].lu.dtype == jnp.float32
    assert caches[0].A_bc.dtype == jnp.float64  # assembly stays f64

    new_state, solution, info = system.step(state)
    assert solution.dtype == jnp.float64
    assert bool(info.converged)
    # gmres_ir reports the explicit residual — no implicit/true drift possible
    assert float(info.residual_true) <= 1e-10


def test_auto_precision_falls_back_to_full_on_cpu(monkeypatch):
    """solver_precision="auto" resolves to "full" on the CPU backend (where
    mixed is measured 2-3.5x slower): the preconditioner factors stay f64
    and the plain-GMRES path runs. On an accelerator backend the same
    config resolves to "mixed" for f64 states and "full" for f32 states
    (`System._precision_for`) — pinned here by faking the backend name,
    since CI has no accelerator."""
    dtype = jnp.float64
    shell, shape, bodies = make_coupled_parts(192, 96, dtype)
    params = Params(eta=1.0, dt_initial=0.1, t_final=1.0, gmres_tol=1e-10,
                    solver_precision="auto", adaptive_timestep_flag=False)
    system = System(params, shell_shape=shape)
    state = system.make_state(shell=shell, bodies=bodies)
    assert system._precision_for(state) == "full"
    _, _, body_caches, _, _ = system._prep(state)
    assert body_caches[0].lu.dtype == jnp.float64

    def cast32(tree):
        return jax.tree.map(
            lambda x: x.astype(jnp.float32)
            if hasattr(x, "dtype") and x.dtype == jnp.float64 else x, tree)

    state32 = system.make_state(shell=cast32(shell), bodies=cast32(bodies))
    assert system._precision_for(state32) == "full"

    # accelerator branch: f64 -> mixed, f32 -> still full (the dtype guard)
    from skellysim_tpu.system import system as system_mod

    monkeypatch.setattr(system_mod.jax, "default_backend", lambda: "tpu")
    assert system._precision_for(state) == "mixed"
    assert system._precision_for(state32) == "full"


def test_mixed_matches_full_solution():
    """Mixed and full f64 modes agree to well below the fiber dynamics scale."""
    dtype = jnp.float64
    t = np.linspace(0, 1, 32)
    x = np.stack([np.zeros(32), np.zeros(32), t], axis=-1)
    fibers = fc.make_group(x[None], lengths=1.0, bending_rigidity=0.01,
                           radius=0.0125, dtype=dtype)
    from skellysim_tpu.system.sources import BackgroundFlow

    bg = BackgroundFlow.make(uniform=[0.0, 0.0, 1.0], dtype=dtype)
    base = Params(eta=1.0, dt_initial=0.05, t_final=1.0, gmres_tol=1e-11,
                  adaptive_timestep_flag=False)

    sols = {}
    for mode in ("full", "mixed"):
        params = dataclasses.replace(base, solver_precision=mode)
        system = System(params)
        state = system.make_state(fibers=fibers, background=bg)
        _, solution, info = system.step(state)
        assert bool(info.converged), mode
        sols[mode] = np.asarray(solution)
    err = np.linalg.norm(sols["mixed"] - sols["full"]) / np.linalg.norm(sols["full"])
    assert err < 1e-9, err


def test_mixed_body_stokes_drag_oracle():
    """Sphere under constant force reaches the analytic Stokes drag velocity
    within the reference's 1e-6 gate with the mixed solver
    (`tests/combined/test_body_const_force.py:39-81`; same calibration as
    `test_bodies.test_body_const_force_stokes_drag`: the effective radius is
    the quadrature-node radius)."""
    dtype = jnp.float64
    from skellysim_tpu.periphery.precompute import precompute_body

    eta, radius, force = 1.0, 0.5, 1.0
    pre = precompute_body("sphere", 600, radius=radius)
    bodies = bd.make_group(
        pre["node_positions_ref"], pre["node_normals_ref"], pre["node_weights"],
        position=np.zeros((1, 3)), external_force=np.array([[0.0, 0.0, force]]),
        radius=np.array([radius]), kind="sphere", dtype=dtype)
    params = Params(eta=eta, dt_initial=0.1, t_final=1.0, gmres_tol=1e-10,
                    solver_precision="mixed", adaptive_timestep_flag=False)
    system = System(params)
    state = system.make_state(bodies=bodies)
    new_state, solution, info = system.step(state)
    assert bool(info.converged)

    r_eff = np.linalg.norm(np.asarray(pre["node_positions_ref"])[0])
    v_theory = force / (6 * np.pi * eta * r_eff)
    v_measured = float(new_state.bodies.velocity[0, 2])
    rel = abs(1 - v_measured / v_theory)
    assert rel < 1e-6, rel  # the reference's gate
    # solver-side accuracy: explicit residual at the reference's tolerance
    assert float(info.residual_true) <= 1e-10


def test_f32_solution_quality_vs_f64():
    """Pure-f32 'full' mode (the TPU speed mode) carries ~1e-3-class solution
    error on stiff fiber systems (measured 7.5e-4 here): eps_f32 amplified by
    the fiber operator's conditioning. This is the f32 quality pin round-2's
    verdict asked for (weak #4) — and the quantitative reason `mixed` mode
    exists for accuracy-gated work. The f32 *explicit* residual is
    noise-dominated by the stiff fiber rows, so solution error is the
    meaningful metric."""
    import numpy as np

    from skellysim_tpu.system.sources import BackgroundFlow

    t = np.linspace(0, 1, 32)
    x = np.stack([np.zeros(32), np.zeros(32), t], axis=-1)
    sols = {}
    for dtype, tol in ((jnp.float64, 1e-11), (jnp.float32, 1e-7)):
        fibers = fc.make_group(x[None], lengths=1.0, bending_rigidity=0.01,
                               radius=0.0125, dtype=dtype)
        bg = BackgroundFlow.make(uniform=[0.0, 0.0, 1.0], dtype=dtype)
        system = System(Params(eta=1.0, dt_initial=0.05, t_final=1.0,
                               gmres_tol=tol, adaptive_timestep_flag=False))
        state = system.make_state(fibers=fibers, background=bg)
        _, solution, info = system.step(state)
        assert bool(info.converged), dtype
        sols[dtype] = np.asarray(solution, dtype=np.float64)
    err = (np.linalg.norm(sols[jnp.float32] - sols[jnp.float64])
           / np.linalg.norm(sols[jnp.float64]))
    assert err < 5e-3, err


@pytest.mark.slow
def test_mixed_df_refinement_matches_exact_refinement():
    """refine_pair_impl="df" (the accelerator default: double-float f32
    residual/prep flows) reaches gmres_tol and agrees with native-f64
    refinement to the DF envelope."""
    dtype = jnp.float64
    shell, shape, bodies = make_coupled_parts(192, 96, dtype)
    t = np.linspace(0, 1, 32)
    x = np.array([0.0, 3.0, 0.0])[None, :] + t[:, None] * np.array([0.0, 0.0, 1.0])
    base = Params(eta=1.0, dt_initial=0.1, t_final=1.0, gmres_tol=1e-10,
                  solver_precision="mixed", adaptive_timestep_flag=False)

    sols = {}
    for impl in ("exact", "df"):
        params = dataclasses.replace(base, refine_pair_impl=impl)
        system = System(params, shell_shape=shape)
        fibers = fc.make_group(x[None], lengths=1.0, bending_rigidity=0.01,
                               radius=0.0125, dtype=dtype)
        state = system.make_state(fibers=fibers, shell=shell, bodies=bodies)
        _, solution, info = system.step(state)
        assert bool(info.converged), impl
        assert float(info.residual_true) <= 1e-10, impl
        sols[impl] = np.asarray(solution)
    err = (np.linalg.norm(sols["df"] - sols["exact"])
           / np.linalg.norm(sols["exact"]))
    assert err < 1e-9, err
