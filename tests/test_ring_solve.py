"""Full implicit solve through the ring evaluator == direct evaluator.

Distributed-correctness strategy per SURVEY.md §4.3: real sharded execution on
the virtual 8-device mesh, compared against the single-program ground truth —
no mocks.
"""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from skellysim_tpu.fibers import container as fc
from skellysim_tpu.params import Params
from skellysim_tpu.parallel import make_mesh, shard_state, use_mesh
from skellysim_tpu.system import BackgroundFlow, System

N_DEV = 8


def _state(system, n_fibers=2 * N_DEV, n_nodes=16):
    rng = np.random.default_rng(5)
    t = np.linspace(0, 1, n_nodes)
    origins = rng.uniform(-4.0, 4.0, size=(n_fibers, 3))
    dirs = rng.normal(size=(n_fibers, 3))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    x = origins[:, None, :] + t[None, :, None] * dirs[:, None, :]
    fibers = fc.make_group(x, lengths=1.0, bending_rigidity=0.01, radius=0.0125,
                           dtype=jnp.float64)
    return system.make_state(
        fibers=fibers,
        background=BackgroundFlow.make(uniform=(1.0, 0.0, 0.0),
                                       dtype=jnp.float64))


def test_ring_solve_matches_direct_solve():
    mesh = make_mesh(N_DEV)
    params = dict(eta=1.0, dt_initial=1e-3, t_final=1e-2, gmres_tol=1e-10,
                  adaptive_timestep_flag=False)

    sys_direct = System(Params(**params))
    s_direct, sol_direct, info_direct = sys_direct.step(_state(sys_direct))

    sys_ring = System(Params(**params, pair_evaluator="ring"), mesh=mesh)
    state = shard_state(_state(sys_ring), mesh)
    with use_mesh(mesh):
        s_ring, sol_ring, info_ring = sys_ring.step(state)
        jax.block_until_ready(s_ring)

    assert bool(info_ring.converged)
    np.testing.assert_allclose(np.asarray(s_ring.fibers.x),
                               np.asarray(s_direct.fibers.x), atol=5e-11)
    np.testing.assert_allclose(np.asarray(sol_ring), np.asarray(sol_direct),
                               atol=5e-9)


def _coupled_state(system):
    """Fibers + spherical shell + one forced body; shell (100 nodes) and body
    (77 nodes) counts deliberately NOT divisible by the 8-device mesh, so the
    ring path's zero-strength source pads and far-point target pads are
    exercised."""
    from skellysim_tpu.testing import make_coupled_parts

    shell, _, bodies = make_coupled_parts(100, 77, jnp.float64)

    rng = np.random.default_rng(7)
    n_fibers, n_nodes = 2 * N_DEV, 16
    t = np.linspace(0, 1, n_nodes)
    origins = rng.uniform(-2.0, 2.0, size=(n_fibers, 3))
    dirs = rng.normal(size=(n_fibers, 3))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    x = origins[:, None, :] + t[None, :, None] * dirs[:, None, :]
    fibers = fc.make_group(x, lengths=1.0, bending_rigidity=0.01, radius=0.0125,
                           dtype=jnp.float64)
    return system.make_state(fibers=fibers, shell=shell, bodies=bodies)


@pytest.mark.slow  # heavy coupled-solve integration; sibling fast tests keep the seam covered (ISSUE-9 870s-budget re-triage)
def test_ring_coupled_solve_matches_direct_solve():
    """The ring evaluator must serve coupled (fiber+shell+body) states — the
    reference's FMM serves all components through one evaluator seam
    (`/root/reference/include/kernels.hpp:78-122`)."""
    from skellysim_tpu.periphery.periphery import PeripheryShape

    mesh = make_mesh(N_DEV)
    shape = PeripheryShape(kind="sphere", radius=6.0)
    params = dict(eta=1.0, dt_initial=1e-3, t_final=1e-2, gmres_tol=1e-10,
                  adaptive_timestep_flag=False)

    sys_direct = System(Params(**params), shell_shape=shape)
    s_direct, sol_direct, info_direct = sys_direct.step(_coupled_state(sys_direct))

    sys_ring = System(Params(**params, pair_evaluator="ring"),
                      shell_shape=shape, mesh=mesh)
    # 300 shell rows don't divide the 8-mesh: explicitly accept replication
    # of the (tiny) dense operators; the ring path is what's under test
    state = shard_state(_coupled_state(sys_ring), mesh,
                        allow_replicated_shell=True)
    with use_mesh(mesh):
        s_ring, sol_ring, info_ring = sys_ring.step(state)
        jax.block_until_ready(s_ring)

    assert bool(info_direct.converged) and bool(info_ring.converged)
    np.testing.assert_allclose(np.asarray(sol_ring), np.asarray(sol_direct),
                               atol=1e-9)
    np.testing.assert_allclose(np.asarray(s_ring.fibers.x),
                               np.asarray(s_direct.fibers.x), atol=1e-10)
    np.testing.assert_allclose(np.asarray(s_ring.bodies.position),
                               np.asarray(s_direct.bodies.position), atol=1e-10)


def test_ring_indivisible_fiber_nodes_raises():
    """Silent sharding degradation is forbidden: a fiber-node count that the
    mesh cannot split evenly must fail with an actionable message."""
    import pytest

    mesh = make_mesh(5)  # all legal n_nodes are multiples of 8 -> use a 5-mesh
    sys_ring = System(Params(eta=1.0, dt_initial=1e-3, t_final=1e-2,
                             gmres_tol=1e-8, adaptive_timestep_flag=False,
                             pair_evaluator="ring"), mesh=mesh)
    state = _state(sys_ring, n_fibers=3, n_nodes=8)  # 24 nodes % 5 != 0
    with pytest.raises(ValueError, match="divisible by the mesh size"):
        with use_mesh(mesh):
            sys_ring.step(state)


def test_builder_autopads_ring_fiber_batch(tmp_path):
    """A user config whose fiber count is not mesh-divisible gets inert
    padding fibers from the builder instead of the deep ring ValueError
    (round-2 verdict weak #6)."""
    import numpy as np

    from skellysim_tpu import builder
    from skellysim_tpu.config import Config, Fiber

    cfg = Config()
    cfg.params.dt_initial = 0.01
    cfg.params.t_final = 0.02
    cfg.params.adaptive_timestep_flag = False
    cfg.params.pair_evaluator = "ring"
    fibs = []
    for i in range(3):  # 3 fibers x 16 nodes = 48 nodes: not divisible by 8? 48%8==0...
        f = Fiber(n_nodes=16, length=1.0, bending_rigidity=0.01)
        f.fill_node_positions(np.array([2.0 * i, 0.0, 0.0]),
                              np.array([0.0, 0.0, 1.0]))
        fibs.append(f)
    cfg.fibers = fibs

    mesh = make_mesh(5)  # 48 % 5 != 0 -> padding needed
    system, state, rng = builder.build_simulation(cfg, mesh=mesh)
    nf, n = state.fibers.n_fibers, state.fibers.n_nodes
    assert (nf * n) % mesh.size == 0
    assert int(np.asarray(state.fibers.active).sum()) == 3
    # the padded state still solves
    with use_mesh(mesh):
        _, _, info = system.step(shard_state(state, mesh))
    assert bool(info.converged)
