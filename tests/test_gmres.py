"""GMRES solver tests against dense numpy solves."""

import numpy as np

import jax.numpy as jnp

from skellysim_tpu.solver import gmres


def _system(n, seed, cond_boost=0.0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((n, n)) / np.sqrt(n) + (2.0 + cond_boost) * np.eye(n)
    b = rng.standard_normal(n)
    return A, b


def test_gmres_unpreconditioned():
    A, b = _system(60, 0)
    res = gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b), tol=1e-12, restart=60)
    assert bool(res.converged)
    np.testing.assert_allclose(np.asarray(res.x), np.linalg.solve(A, b), rtol=1e-9, atol=1e-10)


def test_gmres_right_preconditioned_fewer_iters():
    A, b = _system(80, 1)
    M = np.linalg.inv(A + 0.05 * np.random.default_rng(2).standard_normal((80, 80)))
    plain = gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b), tol=1e-10, restart=80)
    prec = gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b),
                 precond=lambda v: jnp.asarray(M) @ v, tol=1e-10, restart=80)
    assert bool(prec.converged)
    assert int(prec.iters) < int(plain.iters)
    np.testing.assert_allclose(np.asarray(prec.x), np.linalg.solve(A, b), rtol=1e-7, atol=1e-8)


def test_gmres_restarted():
    A, b = _system(100, 3, cond_boost=2.0)
    res = gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b), tol=1e-10, restart=25, maxiter=400)
    assert bool(res.converged)
    np.testing.assert_allclose(np.asarray(res.x), np.linalg.solve(A, b), rtol=1e-7, atol=1e-8)


def test_gmres_zero_rhs():
    A, _ = _system(20, 4)
    res = gmres(lambda v: jnp.asarray(A) @ v, jnp.zeros(20), tol=1e-12)
    assert bool(res.converged)
    assert int(res.iters) == 0
    np.testing.assert_allclose(np.asarray(res.x), 0.0)


def test_gmres_exact_in_n_iterations():
    # Krylov exactness: an n-dim system converges within n inner iterations
    A, b = _system(30, 5)
    res = gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b), tol=1e-13, restart=30)
    assert int(res.iters) <= 30
    explicit = np.linalg.norm(A @ np.asarray(res.x) - b) / np.linalg.norm(b)
    assert explicit < 1e-11


def test_gmres_explicit_residual_agrees_with_implicit():
    """The post-solve explicit residual (`solver_hydro.cpp:81-92` analogue)
    must agree with the implicit Givens residual to ~10x tol on a conditioned
    problem, and must equal a hand-computed ||b - Ax|| / ||b||."""
    A, b = _system(120, 3, cond_boost=3.0)
    M = np.linalg.inv(np.diag(np.diag(A)))
    res = gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b),
                precond=lambda v: jnp.asarray(M) @ v, tol=1e-10, restart=40,
                maxiter=400)
    assert bool(res.converged)
    hand = np.linalg.norm(A @ np.asarray(res.x) - b) / np.linalg.norm(b)
    np.testing.assert_allclose(float(res.residual_true), hand, rtol=1e-6)
    assert float(res.residual_true) <= 10.0 * 1e-10
    # implicit and explicit agree to within an order of magnitude
    assert float(res.residual_true) <= 10.0 * max(float(res.residual), 1e-16)


def test_step_info_carries_true_residual():
    from skellysim_tpu.params import Params
    from skellysim_tpu.system import BackgroundFlow, System
    from skellysim_tpu.fibers import container as fc

    t = np.linspace(0, 1, 16)
    x = np.array([2.0, 0.0, 0.0])[None, :] + t[:, None] * np.array([0.0, 0.0, 1.0])
    fibers = fc.make_group(x[None], lengths=1.0, bending_rigidity=0.01,
                           radius=0.0125, dtype=jnp.float64)
    system = System(Params(eta=1.0, dt_initial=1e-3, t_final=1e-2,
                           gmres_tol=1e-10, adaptive_timestep_flag=False))
    state = system.make_state(
        fibers=fibers,
        background=BackgroundFlow.make(uniform=(1.0, 0.0, 0.0), dtype=jnp.float64))
    _, _, info = system.step(state)
    assert np.isfinite(float(info.residual_true))
    assert float(info.residual_true) <= 10.0 * 1e-10
    assert not bool(info.loss_of_accuracy)
