"""GMRES solver tests against dense numpy solves."""

import numpy as np

import jax.numpy as jnp

from skellysim_tpu.solver import gmres


def _system(n, seed, cond_boost=0.0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((n, n)) / np.sqrt(n) + (2.0 + cond_boost) * np.eye(n)
    b = rng.standard_normal(n)
    return A, b


def test_gmres_unpreconditioned():
    A, b = _system(60, 0)
    res = gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b), tol=1e-12, restart=60)
    assert bool(res.converged)
    np.testing.assert_allclose(np.asarray(res.x), np.linalg.solve(A, b), rtol=1e-9, atol=1e-10)


def test_gmres_right_preconditioned_fewer_iters():
    A, b = _system(80, 1)
    M = np.linalg.inv(A + 0.05 * np.random.default_rng(2).standard_normal((80, 80)))
    plain = gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b), tol=1e-10, restart=80)
    prec = gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b),
                 precond=lambda v: jnp.asarray(M) @ v, tol=1e-10, restart=80)
    assert bool(prec.converged)
    assert int(prec.iters) < int(plain.iters)
    np.testing.assert_allclose(np.asarray(prec.x), np.linalg.solve(A, b), rtol=1e-7, atol=1e-8)


def test_gmres_restarted():
    A, b = _system(100, 3, cond_boost=2.0)
    res = gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b), tol=1e-10, restart=25, maxiter=400)
    assert bool(res.converged)
    np.testing.assert_allclose(np.asarray(res.x), np.linalg.solve(A, b), rtol=1e-7, atol=1e-8)


def test_gmres_zero_rhs():
    A, _ = _system(20, 4)
    res = gmres(lambda v: jnp.asarray(A) @ v, jnp.zeros(20), tol=1e-12)
    assert bool(res.converged)
    assert int(res.iters) == 0
    np.testing.assert_allclose(np.asarray(res.x), 0.0)


def test_gmres_exact_in_n_iterations():
    # Krylov exactness: an n-dim system converges within n inner iterations
    A, b = _system(30, 5)
    res = gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b), tol=1e-13, restart=30)
    assert int(res.iters) <= 30
    explicit = np.linalg.norm(A @ np.asarray(res.x) - b) / np.linalg.norm(b)
    assert explicit < 1e-11


def test_gmres_explicit_residual_agrees_with_implicit():
    """The post-solve explicit residual (`solver_hydro.cpp:81-92` analogue)
    must agree with the implicit Givens residual to ~10x tol on a conditioned
    problem, and must equal a hand-computed ||b - Ax|| / ||b||."""
    A, b = _system(120, 3, cond_boost=3.0)
    M = np.linalg.inv(np.diag(np.diag(A)))
    res = gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b),
                precond=lambda v: jnp.asarray(M) @ v, tol=1e-10, restart=40,
                maxiter=400)
    assert bool(res.converged)
    hand = np.linalg.norm(A @ np.asarray(res.x) - b) / np.linalg.norm(b)
    np.testing.assert_allclose(float(res.residual_true), hand, rtol=1e-6)
    assert float(res.residual_true) <= 10.0 * 1e-10
    # implicit and explicit agree to within an order of magnitude
    assert float(res.residual_true) <= 10.0 * max(float(res.residual), 1e-16)


def test_step_info_carries_true_residual():
    from skellysim_tpu.params import Params
    from skellysim_tpu.system import BackgroundFlow, System
    from skellysim_tpu.fibers import container as fc

    t = np.linspace(0, 1, 16)
    x = np.array([2.0, 0.0, 0.0])[None, :] + t[:, None] * np.array([0.0, 0.0, 1.0])
    fibers = fc.make_group(x[None], lengths=1.0, bending_rigidity=0.01,
                           radius=0.0125, dtype=jnp.float64)
    system = System(Params(eta=1.0, dt_initial=1e-3, t_final=1e-2,
                           gmres_tol=1e-10, adaptive_timestep_flag=False))
    state = system.make_state(
        fibers=fibers,
        background=BackgroundFlow.make(uniform=(1.0, 0.0, 0.0), dtype=jnp.float64))
    _, _, info = system.step(state)
    assert np.isfinite(float(info.residual_true))
    assert float(info.residual_true) <= 10.0 * 1e-10
    assert not bool(info.loss_of_accuracy)


# ---------------------------------------------------- s-step (block) GMRES

def test_gmres_block_s1_bitwise_default():
    """block_s=1 routes through the EXACT sequential cycle: the result is
    bit-identical to the default call (the pre-s-step solver — the parity
    every golden-trajectory / unroll-ensemble / serve pin rides on)."""
    A, b = _system(60, 8)
    base = gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b), tol=1e-12,
                 restart=25, maxiter=200)
    s1 = gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b), tol=1e-12,
               restart=25, maxiter=200, block_s=1)
    assert np.array_equal(np.asarray(base.x), np.asarray(s1.x))
    assert int(base.iters) == int(s1.iters)
    assert float(base.residual) == float(s1.residual)


def test_gmres_block_matches_sequential_iterations():
    """s > 1 reaches the same explicit-residual tolerance with iteration
    count within 10% of the sequential cycle (the ISSUE 8 acceptance pin),
    on a conditioned and a restarted problem."""
    for n, seed, restart, boost in ((80, 1, 80, 0.0), (100, 3, 12, 2.0)):
        A, b = _system(n, seed, cond_boost=boost)
        mv = lambda v: jnp.asarray(A) @ v
        r1 = gmres(mv, jnp.asarray(b), tol=1e-10, restart=restart,
                   maxiter=600)
        assert bool(r1.converged)
        for s in (2, 4):
            rs = gmres(mv, jnp.asarray(b), tol=1e-10, restart=restart,
                       maxiter=600, block_s=s)
            assert bool(rs.converged), (n, s)
            explicit = (np.linalg.norm(A @ np.asarray(rs.x) - b)
                        / np.linalg.norm(b))
            assert explicit <= 1e-9, (n, s, explicit)
            # an s-step round can only stop on round boundaries mid-cycle,
            # so allow the ceil-to-s slack on top of the 10%
            assert int(rs.iters) <= int(np.ceil(1.1 * int(r1.iters) / s) * s), \
                (n, s, int(rs.iters), int(r1.iters))


def test_gmres_block_history_and_cycles_semantics():
    """The convergence ring buffer keeps its one-row-per-restart contract
    under block_s (skelly-scope decode invariant: rows written ==
    result.cycles)."""
    from skellysim_tpu.solver.gmres import history_rows

    A, b = _system(100, 5, cond_boost=2.0)
    res = gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b), tol=1e-11,
                restart=12, maxiter=400, history=8, block_s=4)
    assert bool(res.converged)
    rows = history_rows(res.history, res.cycles)
    assert len(rows) == min(int(res.cycles), 8)
    assert rows[-1][0] == int(res.iters)          # cumulative iters
    assert rows[-1][2] == float(res.residual_true)


def test_gmres_block_two_gram_rounds_per_cycle_body():
    """The communication-avoiding claim, pinned at trace level: the s-step
    loop body performs exactly TWO batched (matrix-operand) reductions
    through the rdot seam per s iterations — the sequential body's three
    vector reductions per iteration are gone. Per restart cycle of m
    iterations that is 2*(m/s) rounds vs 3*m, a 6x drop at s=4 (the >= 3x
    acceptance bound follows arithmetically)."""
    A, b = _system(40, 2)

    def make_counting_rdot(log):
        def rdot(Av, w):
            log.append(getattr(w, "ndim", 1))
            return Av @ w
        return rdot

    log_s1, log_s4 = [], []
    gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b), tol=1e-10,
          restart=16, maxiter=64, rdot=make_counting_rdot(log_s1))
    gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b), tol=1e-10,
          restart=16, maxiter=64, rdot=make_counting_rdot(log_s4), block_s=4)
    # sequential trace: no matrix-operand reductions anywhere
    assert log_s1.count(2) == 0
    # block trace: exactly 2 batched Gram reductions in the (once-traced)
    # round body, covering s=4 iterations each
    assert log_s4.count(2) == 2
    # and the block path introduces no NEW vector reductions beyond the
    # sequential path's outer-loop norms (entry beta, b_norm, explicit
    # residual): the 3-per-iteration ICGS/norm reductions are gone
    assert log_s4.count(1) < log_s1.count(1)


def test_collective_rounds_formula():
    """`collective_rounds` (the obs-summarize metrics derivation): >= 3x
    fewer dot-product rounds at s=4 for any realistic iteration count."""
    from skellysim_tpu.solver.gmres import collective_rounds

    assert collective_rounds(10, 1, 1) == 32          # 3*10 + 2
    assert collective_rounds(10, 1, 4) == 8           # 2*ceil(10/4) + 2
    for iters, cycles in ((4, 1), (30, 1), (100, 2), (400, 5)):
        r1 = collective_rounds(iters, cycles, 1)
        r4 = collective_rounds(iters, cycles, 4)
        assert r1 >= 3 * r4, (iters, cycles, r1, r4)
    # gmres_ir results carry cycles=SWEEPS: restart= floors the boundary
    # count at ceil(iters/restart), so an inner restart blow-up (300 inner
    # iterations across only 2 sweeps at restart=30) still moves the metric
    assert collective_rounds(300, 2, 1, restart=30) == 3 * 300 + 2 * 10
    assert collective_rounds(10, 2, 1, restart=100) == 3 * 10 + 2 * 2


def test_gmres_ir_block_reaches_tol():
    """Mixed-precision refinement with the s-step inner solve: same f64
    explicit-residual contract as the sequential inner loop."""
    from skellysim_tpu.solver import gmres_ir

    rng = np.random.default_rng(9)
    n = 96
    A = rng.standard_normal((n, n)) / np.sqrt(n) + 3.0 * np.eye(n)
    b = rng.standard_normal(n)
    A32 = jnp.asarray(A, dtype=jnp.float32)
    res = gmres_ir(lambda v: jnp.asarray(A) @ v,
                   lambda v: (A32 @ v.astype(jnp.float32)).astype(v.dtype),
                   jnp.asarray(b), tol=1e-10, inner_tol=1e-5, restart=48,
                   maxiter=200, block_s=4)
    assert bool(res.converged)
    explicit = np.linalg.norm(A @ np.asarray(res.x) - b) / np.linalg.norm(b)
    assert explicit <= 1e-9
