"""GMRES solver tests against dense numpy solves."""

import numpy as np

import jax.numpy as jnp

from skellysim_tpu.solver import gmres


def _system(n, seed, cond_boost=0.0):
    rng = np.random.default_rng(seed)
    A = rng.standard_normal((n, n)) / np.sqrt(n) + (2.0 + cond_boost) * np.eye(n)
    b = rng.standard_normal(n)
    return A, b


def test_gmres_unpreconditioned():
    A, b = _system(60, 0)
    res = gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b), tol=1e-12, restart=60)
    assert bool(res.converged)
    np.testing.assert_allclose(np.asarray(res.x), np.linalg.solve(A, b), rtol=1e-9, atol=1e-10)


def test_gmres_right_preconditioned_fewer_iters():
    A, b = _system(80, 1)
    M = np.linalg.inv(A + 0.05 * np.random.default_rng(2).standard_normal((80, 80)))
    plain = gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b), tol=1e-10, restart=80)
    prec = gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b),
                 precond=lambda v: jnp.asarray(M) @ v, tol=1e-10, restart=80)
    assert bool(prec.converged)
    assert int(prec.iters) < int(plain.iters)
    np.testing.assert_allclose(np.asarray(prec.x), np.linalg.solve(A, b), rtol=1e-7, atol=1e-8)


def test_gmres_restarted():
    A, b = _system(100, 3, cond_boost=2.0)
    res = gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b), tol=1e-10, restart=25, maxiter=400)
    assert bool(res.converged)
    np.testing.assert_allclose(np.asarray(res.x), np.linalg.solve(A, b), rtol=1e-7, atol=1e-8)


def test_gmres_zero_rhs():
    A, _ = _system(20, 4)
    res = gmres(lambda v: jnp.asarray(A) @ v, jnp.zeros(20), tol=1e-12)
    assert bool(res.converged)
    assert int(res.iters) == 0
    np.testing.assert_allclose(np.asarray(res.x), 0.0)


def test_gmres_exact_in_n_iterations():
    # Krylov exactness: an n-dim system converges within n inner iterations
    A, b = _system(30, 5)
    res = gmres(lambda v: jnp.asarray(A) @ v, jnp.asarray(b), tol=1e-13, restart=30)
    assert int(res.iters) <= 30
    explicit = np.linalg.norm(A @ np.asarray(res.x) - b) / np.linalg.norm(b)
    assert explicit < 1e-11
