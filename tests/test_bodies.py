"""Rigid-body physics oracles.

Mirrors of the reference integration tests:
* `tests/combined/test_body_const_force.py`: sphere under constant force moves
  at the Stokes drag velocity F/(6 pi eta R_eff), rel. error < 1e-6, where
  R_eff is the (shrunken) quadrature node radius.
* `tests/combined/test_body_const_torque.py` analogue: rotation under constant
  torque at T/(8 pi eta R^3).
* mobility symmetry/sanity for the ellipsoidal formulation via the
  sphere-as-ellipsoid consistency check (`tests/combined/bodies/`).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from skellysim_tpu.bodies import bodies as bd
from skellysim_tpu.params import Params
from skellysim_tpu.periphery.precompute import precompute_body
from skellysim_tpu.system import System


def make_sphere_body(n_nodes=600, radius=0.5, **kw):
    pre = precompute_body("sphere", n_nodes, radius=radius)
    return bd.make_group(pre["node_positions_ref"], pre["node_normals_ref"],
                         pre["node_weights"], radius=radius, kind="sphere", **kw), pre


def test_body_const_force_stokes_drag():
    eta = 0.9
    force = 1.5
    group, pre = make_sphere_body(n_nodes=600, radius=0.5,
                                  external_force=[0.0, 0.0, force])
    r_eff = np.linalg.norm(pre["node_positions_ref"][0])

    params = Params(eta=eta, dt_initial=0.1, t_final=0.3, gmres_tol=1e-10,
                    adaptive_timestep_flag=False)
    system = System(params)
    state = system.make_state(bodies=group)

    z0 = float(state.bodies.position[0, 2])
    t0 = float(state.time)
    state = system.run(state)
    z1 = float(state.bodies.position[0, 2])
    t1 = float(state.time)

    v_measured = (z1 - z0) / (t1 - t0)
    v_theory = force / (6 * np.pi * eta * r_eff)
    rel_err = abs(1 - v_measured / v_theory)
    assert rel_err < 1e-6, rel_err


def test_body_const_torque_rotation():
    eta = 1.2
    torque = 0.7
    group, pre = make_sphere_body(n_nodes=600, radius=0.5,
                                  external_torque=[0.0, 0.0, torque])
    r_eff = np.linalg.norm(pre["node_positions_ref"][0])

    params = Params(eta=eta, dt_initial=0.05, t_final=0.05, gmres_tol=1e-10,
                    adaptive_timestep_flag=False)
    system = System(params)
    state = system.make_state(bodies=group)
    state, _, info = system.step(state)
    assert bool(info.converged)

    w_measured = float(state.bodies.angular_velocity[0, 2])
    w_theory = torque / (8 * np.pi * eta * r_eff**3)
    rel_err = abs(1 - w_measured / w_theory)
    assert rel_err < 1e-4, rel_err


def test_ellipsoid_as_sphere_matches_sphere_drag():
    """Ellipsoid with a==b==c must reproduce the spherical mobility
    (`tests/combined/bodies/test_ellipsoid_assphere_constforce.py`)."""
    eta = 1.0
    r = 0.4
    pre = precompute_body("ellipsoid", 500, a=r, b=r, c=r)
    group = bd.make_group(pre["node_positions_ref"], pre["node_normals_ref"],
                          pre["node_weights"], kind="ellipsoid",
                          external_force=[0.0, 0.0, 1.0])
    params = Params(eta=eta, dt_initial=0.05, t_final=0.05, gmres_tol=1e-10,
                    adaptive_timestep_flag=False)
    system = System(params)
    state = system.make_state(bodies=group)
    state, _, info = system.step(state)
    assert bool(info.converged)
    v = float(state.bodies.velocity[0, 2])
    v_theory = 1.0 / (6 * np.pi * eta * r)
    assert abs(1 - v / v_theory) < 1e-3


def test_fiber_body_link_holds():
    """A fiber bound to a body stays pinned to its nucleation site as the
    body translates under force."""
    from skellysim_tpu.fibers import container as fc

    eta = 1.0
    group, pre = make_sphere_body(n_nodes=400, radius=0.5,
                                  external_force=[0.0, 0.0, 1.0],
                                  nucleation_sites_ref=[[0.0, 0.0, 0.5]])
    params = Params(eta=eta, dt_initial=0.01, t_final=0.03, gmres_tol=1e-9,
                    adaptive_timestep_flag=False)
    system = System(params)

    t = np.linspace(0, 1, 16)
    x = np.stack([np.zeros(16), np.zeros(16), 0.5 + 0.6 * t], axis=1)[None]
    fibers = fc.make_group(x, lengths=0.6, bending_rigidity=0.01, radius=0.0125,
                           binding_body=0, binding_site=0)
    state = system.make_state(fibers=fibers, bodies=group)
    state = system.run(state)

    _, _, sites = bd.place(state.bodies)
    gap = np.linalg.norm(np.asarray(state.fibers.x[0, 0]) - np.asarray(sites[0, 0]))
    assert gap < 1e-12
    # body actually moved
    assert float(state.bodies.position[0, 2]) > 1e-3


def test_body_oscillatory_force_schedule():
    group, _ = make_sphere_body(n_nodes=200, radius=0.5,
                                external_force=[0.0, 0.0, 1.0],
                                ext_force_type=bd.EXTFORCE_OSCILLATORY,
                                osc_amplitude=2.0, osc_omega=2 * np.pi,
                                osc_phase=0.0)
    ft = np.asarray(bd.external_forces_torques(group, jnp.asarray(0.25)))
    np.testing.assert_allclose(ft[0, 2], 2.0 * np.sin(np.pi / 2), rtol=1e-12)
    ft0 = np.asarray(bd.external_forces_torques(group, jnp.asarray(0.0)))
    np.testing.assert_allclose(ft0[0, 2], 0.0, atol=1e-12)


def test_body_collision_checks():
    group, _ = make_sphere_body(n_nodes=200, radius=0.5)
    two = bd.make_group(
        np.stack([np.asarray(group.nodes_ref[0])] * 2),
        np.stack([np.asarray(group.normals_ref[0])] * 2),
        np.stack([np.asarray(group.weights[0])] * 2),
        position=np.array([[0.0, 0.0, 0.0], [0.0, 0.0, 0.8]]),
        radius=0.5, kind="sphere")
    assert bool(bd.check_collision_pairwise(two, 0.0))
    apart = two._replace(position=jnp.asarray([[0.0, 0.0, 0.0], [0.0, 0.0, 1.5]]))
    assert not bool(bd.check_collision_pairwise(apart, 0.0))
    assert bool(bd.check_collision_shell(apart, 1.8, 0.0))
    assert not bool(bd.check_collision_shell(apart, 2.5, 0.0))
