"""Rigid-body physics oracles.

Mirrors of the reference integration tests:
* `tests/combined/test_body_const_force.py`: sphere under constant force moves
  at the Stokes drag velocity F/(6 pi eta R_eff), rel. error < 1e-6, where
  R_eff is the (shrunken) quadrature node radius.
* `tests/combined/test_body_const_torque.py` analogue: rotation under constant
  torque at T/(8 pi eta R^3).
* mobility symmetry/sanity for the ellipsoidal formulation via the
  sphere-as-ellipsoid consistency check (`tests/combined/bodies/`).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from skellysim_tpu.bodies import bodies as bd
from skellysim_tpu.params import Params
from skellysim_tpu.periphery.precompute import precompute_body
from skellysim_tpu.system import System


def make_sphere_body(n_nodes=600, radius=0.5, **kw):
    pre = precompute_body("sphere", n_nodes, radius=radius)
    return bd.make_group(pre["node_positions_ref"], pre["node_normals_ref"],
                         pre["node_weights"], radius=radius, kind="sphere", **kw), pre


def test_body_const_force_stokes_drag():
    eta = 0.9
    force = 1.5
    group, pre = make_sphere_body(n_nodes=600, radius=0.5,
                                  external_force=[0.0, 0.0, force])
    r_eff = np.linalg.norm(pre["node_positions_ref"][0])

    params = Params(eta=eta, dt_initial=0.1, t_final=0.3, gmres_tol=1e-10,
                    adaptive_timestep_flag=False)
    system = System(params)
    state = system.make_state(bodies=group)

    z0 = float(state.bodies.position[0, 2])
    t0 = float(state.time)
    state = system.run(state)
    z1 = float(state.bodies.position[0, 2])
    t1 = float(state.time)

    v_measured = (z1 - z0) / (t1 - t0)
    v_theory = force / (6 * np.pi * eta * r_eff)
    rel_err = abs(1 - v_measured / v_theory)
    assert rel_err < 1e-6, rel_err


def test_body_const_torque_rotation():
    eta = 1.2
    torque = 0.7
    group, pre = make_sphere_body(n_nodes=600, radius=0.5,
                                  external_torque=[0.0, 0.0, torque])
    r_eff = np.linalg.norm(pre["node_positions_ref"][0])

    params = Params(eta=eta, dt_initial=0.05, t_final=0.05, gmres_tol=1e-10,
                    adaptive_timestep_flag=False)
    system = System(params)
    state = system.make_state(bodies=group)
    state, _, info = system.step(state)
    assert bool(info.converged)

    w_measured = float(state.bodies.angular_velocity[0, 2])
    w_theory = torque / (8 * np.pi * eta * r_eff**3)
    rel_err = abs(1 - w_measured / w_theory)
    assert rel_err < 1e-4, rel_err


def test_ellipsoid_as_sphere_matches_sphere_drag():
    """Ellipsoid with a==b==c must reproduce the spherical mobility
    (`tests/combined/bodies/test_ellipsoid_assphere_constforce.py`)."""
    eta = 1.0
    r = 0.4
    pre = precompute_body("ellipsoid", 500, a=r, b=r, c=r)
    group = bd.make_group(pre["node_positions_ref"], pre["node_normals_ref"],
                          pre["node_weights"], kind="ellipsoid",
                          external_force=[0.0, 0.0, 1.0])
    params = Params(eta=eta, dt_initial=0.05, t_final=0.05, gmres_tol=1e-10,
                    adaptive_timestep_flag=False)
    system = System(params)
    state = system.make_state(bodies=group)
    state, _, info = system.step(state)
    assert bool(info.converged)
    v = float(state.bodies.velocity[0, 2])
    v_theory = 1.0 / (6 * np.pi * eta * r)
    assert abs(1 - v / v_theory) < 1e-3


def _ellipsoid_velocity(a, b, c, force_axis, eta=1.0, n_nodes=600):
    """Rigid-velocity response of an ellipsoid to a unit force on one axis."""
    force = [0.0, 0.0, 0.0]
    force[force_axis] = 1.0
    pre = precompute_body("ellipsoid", n_nodes, a=a, b=b, c=c)
    group = bd.make_group(pre["node_positions_ref"], pre["node_normals_ref"],
                          pre["node_weights"], kind="ellipsoid",
                          external_force=force)
    params = Params(eta=eta, dt_initial=0.05, t_final=0.05, gmres_tol=1e-10,
                    adaptive_timestep_flag=False)
    system = System(params)
    state, _, info = system.step(system.make_state(bodies=group))
    assert bool(info.converged)
    return float(state.bodies.velocity[0, force_axis])


def test_prolate_spheroid_perrin_mobility():
    """Prolate spheroid drag along/perpendicular to the symmetry axis vs the
    exact Perrin results F_par = 16 pi eta a e^3 v / ((1+e^2) L - 2e),
    F_perp = 32 pi eta a e^3 v / ((3e^2-1) L + 2e) with
    L = ln((1+e)/(1-e)) (`tests/combined/bodies/` prolate mobility)."""
    eta = 1.0
    a_ax, b_ax = 0.6, 0.3  # symmetry axis along x (precompute a-axis)
    e = np.sqrt(a_ax**2 - b_ax**2) / a_ax
    L = np.log((1 + e) / (1 - e))

    v_par = _ellipsoid_velocity(a_ax, b_ax, b_ax, force_axis=0, eta=eta)
    v_perp = _ellipsoid_velocity(a_ax, b_ax, b_ax, force_axis=1, eta=eta)

    v_par_theory = ((1 + e**2) * L - 2 * e) / (16 * np.pi * eta * a_ax * e**3)
    v_perp_theory = ((3 * e**2 - 1) * L + 2 * e) / (32 * np.pi * eta * a_ax * e**3)

    assert abs(1 - v_par / v_par_theory) < 5e-3
    assert abs(1 - v_perp / v_perp_theory) < 5e-3
    # anisotropy: drag along the long axis is lower
    assert v_par > v_perp


@pytest.mark.slow  # heavy coupled-solve integration; sibling fast tests keep the seam covered (ISSUE-9 870s-budget re-triage)
def test_oblate_spheroid_perrin_mobility():
    """Oblate spheroid (a < b = c) mobility vs the exact result
    F_par = 8 pi eta c e^3 v / (e sqrt(1-e^2) - (1-2e^2) asin(e)) along the
    short (symmetry) axis, F_perp = 16 pi eta c e^3 v /
    ((1+2e^2) asin(e) - e sqrt(1-e^2)) across it, e = sqrt(c^2-a^2)/c."""
    eta = 1.0
    a_ax, c_ax = 0.3, 0.6  # symmetry axis x short; b = c = 0.6
    e = np.sqrt(c_ax**2 - a_ax**2) / c_ax

    v_par = _ellipsoid_velocity(a_ax, c_ax, c_ax, force_axis=0, eta=eta)
    v_perp = _ellipsoid_velocity(a_ax, c_ax, c_ax, force_axis=1, eta=eta)

    v_par_theory = (e * np.sqrt(1 - e**2) - (1 - 2 * e**2) * np.arcsin(e)) / (
        8 * np.pi * eta * c_ax * e**3)
    v_perp_theory = ((1 + 2 * e**2) * np.arcsin(e) - e * np.sqrt(1 - e**2)) / (
        16 * np.pi * eta * c_ax * e**3)

    assert abs(1 - v_par / v_par_theory) < 5e-3
    assert abs(1 - v_perp / v_perp_theory) < 5e-3
    # the flat face moving broadside drags more
    assert v_perp > v_par


def test_fiber_body_link_holds():
    """A fiber bound to a body stays pinned to its nucleation site as the
    body translates under force."""
    from skellysim_tpu.fibers import container as fc

    eta = 1.0
    group, pre = make_sphere_body(n_nodes=400, radius=0.5,
                                  external_force=[0.0, 0.0, 1.0],
                                  nucleation_sites_ref=[[0.0, 0.0, 0.5]])
    params = Params(eta=eta, dt_initial=0.01, t_final=0.03, gmres_tol=1e-9,
                    adaptive_timestep_flag=False)
    system = System(params)

    t = np.linspace(0, 1, 16)
    x = np.stack([np.zeros(16), np.zeros(16), 0.5 + 0.6 * t], axis=1)[None]
    fibers = fc.make_group(x, lengths=0.6, bending_rigidity=0.01, radius=0.0125,
                           binding_body=0, binding_site=0)
    state = system.make_state(fibers=fibers, bodies=group)
    state = system.run(state)

    _, _, sites = bd.place(state.bodies)
    gap = np.linalg.norm(np.asarray(state.fibers.x[0, 0]) - np.asarray(sites[0, 0]))
    assert gap < 1e-12
    # body actually moved
    assert float(state.bodies.position[0, 2]) > 1e-3


def test_body_oscillatory_force_schedule():
    group, _ = make_sphere_body(n_nodes=200, radius=0.5,
                                external_force=[0.0, 0.0, 1.0],
                                ext_force_type=bd.EXTFORCE_OSCILLATORY,
                                osc_amplitude=2.0, osc_omega=2 * np.pi,
                                osc_phase=0.0)
    ft = np.asarray(bd.external_forces_torques(group, jnp.asarray(0.25)))
    np.testing.assert_allclose(ft[0, 2], 2.0 * np.sin(np.pi / 2), rtol=1e-12)
    ft0 = np.asarray(bd.external_forces_torques(group, jnp.asarray(0.0)))
    np.testing.assert_allclose(ft0[0, 2], 0.0, atol=1e-12)


def test_body_collision_checks():
    group, _ = make_sphere_body(n_nodes=200, radius=0.5)
    two = bd.make_group(
        np.stack([np.asarray(group.nodes_ref[0])] * 2),
        np.stack([np.asarray(group.normals_ref[0])] * 2),
        np.stack([np.asarray(group.weights[0])] * 2),
        position=np.array([[0.0, 0.0, 0.0], [0.0, 0.0, 0.8]]),
        radius=0.5, kind="sphere")
    assert bool(bd.check_collision_pairwise(two, 0.0))
    apart = two._replace(position=jnp.asarray([[0.0, 0.0, 0.0], [0.0, 0.0, 1.5]]))
    assert not bool(bd.check_collision_pairwise(apart, 0.0))
    assert bool(bd.check_collision_shell(apart, 1.8, 0.0))
    assert not bool(bd.check_collision_shell(apart, 2.5, 0.0))
