"""End-to-end pipeline: gen_config -> precompute -> run -> read trajectory.

Mirrors the reference's 4-stage combined tests
(`/root/reference/tests/combined/`, `src/skelly_sim/testing.py:18-33`), driven
in-process through the builder/CLI instead of a subprocess binary.
"""

import os

import numpy as np
import pytest

from skellysim_tpu import builder, cli, precompute
from skellysim_tpu.config import (Body, Config, ConfigSpherical, Fiber, Point,
                                  BackgroundSource)
from skellysim_tpu.io.trajectory import TrajectoryReader


def _free_fiber_config(tmp_path, n_nodes=16):
    cfg = Config()
    cfg.params.eta = 1.0
    cfg.params.dt_initial = 0.005
    cfg.params.dt_write = 0.005
    cfg.params.t_final = 0.02
    cfg.params.gmres_tol = 1e-10
    cfg.params.adaptive_timestep_flag = False
    fib = Fiber(n_nodes=n_nodes, length=1.0, bending_rigidity=0.01)
    fib.fill_node_positions(np.zeros(3), np.array([0.0, 0.0, 1.0]))
    cfg.fibers = [fib]
    cfg.background = BackgroundSource(uniform=[1.0, 0.0, 0.0])
    path = str(tmp_path / "skelly_config.toml")
    cfg.save(path)
    return path


@pytest.mark.slow
def test_cli_subprocess_enables_x64(tmp_path):
    """`python -m skellysim_tpu` must converge a 1e-10 mixed solve: without
    the CLI's x64 enable the builder's "f64" state silently canonicalizes to
    f32 and the residual floors at ~1e-5 while steps are still accepted
    (found by round-5 verify — the same class as the precompute CLI bug)."""
    import subprocess
    import sys

    cfg = Config()
    cfg.params.eta = 1.0
    cfg.params.dt_initial = 0.005
    cfg.params.dt_write = 0.005
    cfg.params.t_final = 0.02
    cfg.params.gmres_tol = 1e-10
    # mixed precision exercises the refinement ladder the bug starved
    cfg.params.solver_precision = "mixed"
    cfg.params.adaptive_timestep_flag = False
    fib = Fiber(n_nodes=16, length=1.0, bending_rigidity=0.01)
    fib.fill_node_positions(np.zeros(3), np.array([0.0, 0.0, 1.0]))
    cfg.fibers = [fib]
    cfg.background = BackgroundSource(uniform=[1.0, 0.0, 0.0])
    cfg_path = str(tmp_path / "skelly_config.toml")
    cfg.save(cfg_path)

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # subprocess skips conftest's CPU pin
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # PYTHONPATH = repo ONLY: inheriting the session's .axon_site
    # sitecustomize can hang the subprocess at plugin init when the TPU
    # tunnel is wedged, regardless of JAX_PLATFORMS
    env["PYTHONPATH"] = repo
    p = subprocess.run([sys.executable, "-m", "skellysim_tpu",
                       f"--config-file={cfg_path}", "--overwrite"],
                      capture_output=True, text=True, timeout=420, env=env,
                      cwd=str(tmp_path))
    assert p.returncode == 0, p.stderr[-2000:]
    steps = [ln for ln in p.stderr.splitlines() if "step t=" in ln]
    assert steps, p.stderr[-1000:]
    for ln in steps:
        residual = float(ln.split("residual=")[1].split(" ")[0])
        assert residual <= 1e-10, ln
    assert "did not converge" not in p.stderr


def test_cli_metrics_file_schema_pinned(tmp_path):
    """--metrics-file appends one JSON step record per trial step with
    EXACTLY the METRICS_FIELDS schema (structured metrics, SURVEY.md
    §5.1/§5.5; documented in docs/performance.md)."""
    import json

    from skellysim_tpu.system.system import METRICS_FIELDS

    cfg_path = _free_fiber_config(tmp_path)
    metrics = str(tmp_path / "metrics.jsonl")
    cli.run(cfg_path, metrics_path=metrics)
    lines = [json.loads(ln) for ln in open(metrics)]
    assert len(lines) >= 2
    for rec in lines:
        assert set(rec) == set(METRICS_FIELDS)
        assert rec["accepted"] and rec["residual"] < 1e-8
        assert rec["residual_true"] < 1e-7
        assert rec["refines"] >= 0 and rec["loss_of_accuracy"] is False
    # trial-step index: contiguous from 0 within one run
    assert [rec["step"] for rec in lines] == list(range(len(lines)))


def test_snapshot_path_aliasing_guard():
    """cli._snapshot_path: '.out' is substituted, anything else appended —
    a naive replace could alias the trajectory file itself."""
    assert (cli._snapshot_path("skelly_sim.out", "initial_config")
            == "skelly_sim.initial_config")
    assert (cli._snapshot_path("/a/b/run.out", "final_config")
            == "/a/b/run.final_config")
    # non-.out trajectories get the suffix APPENDED, never substituted
    assert (cli._snapshot_path("traj.bin", "initial_config")
            == "traj.bin.initial_config")
    assert (cli._snapshot_path("noext", "initial_config")
            == "noext.initial_config")
    # '.out' only counts as the final extension
    assert (cli._snapshot_path("weird.out.bak", "initial_config")
            == "weird.out.bak.initial_config")
    # the snapshot path never equals the trajectory path
    for traj in ("skelly_sim.out", "traj.bin", "noext", "a.out.out"):
        assert cli._snapshot_path(traj, "initial_config") != traj


def test_crossed_write_boundary_float_robust():
    """Satellite: the dt_write boundary check survives accumulated float
    error. With dt == dt_write == 0.1 every step crosses a boundary, but
    repeated addition lands t=0.7999999999999999 whose naive frame index is
    still 7 — the naive check skips that frame."""
    from skellysim_tpu.system.system import crossed_write_boundary

    dt = dt_write = 0.1
    t = 0.0
    naive_missed = 0
    for _ in range(16):
        t += dt
        assert crossed_write_boundary(t, dt, dt_write), t
        if not int(t / dt_write) > int((t - dt) / dt_write):
            naive_missed += 1
    assert naive_missed >= 1, "the regression case no longer reproduces"
    # no double-fire: a step strictly inside one frame window stays silent
    assert not crossed_write_boundary(0.25, 0.04, 0.1)
    assert crossed_write_boundary(0.32, 0.04, 0.1)


def test_run_loop_writes_every_exact_boundary_frame(tmp_path):
    """Integration regression: dt dividing dt_write exactly must produce a
    frame at EVERY boundary (the naive check dropped one around t=0.8)."""
    cfg = Config()
    cfg.params.dt_initial = 0.1
    cfg.params.dt_write = 0.1
    # 0.95, not 1.0: accumulated t reaches 0.9999999999999999 and the loop's
    # strict `t < t_final` would take an 11th step — the off-boundary end
    # keeps this a pure frame-boundary regression
    cfg.params.t_final = 0.95
    cfg.params.gmres_tol = 1e-10
    cfg.params.adaptive_timestep_flag = False
    fib = Fiber(n_nodes=16, length=1.0, bending_rigidity=0.01)
    fib.fill_node_positions(np.zeros(3), np.array([0.0, 0.0, 1.0]))
    cfg.fibers = [fib]
    cfg.background = BackgroundSource(uniform=[1.0, 0.0, 0.0])
    cfg_path = str(tmp_path / "skelly_config.toml")
    cfg.save(cfg_path)
    cli.run(cfg_path)

    r = TrajectoryReader(str(tmp_path / "skelly_sim.out"))
    # initial frame + one per 0.1-boundary in (0, 1.0]
    assert len(r) == 11, [r.load_frame(i)["time"] for i in range(len(r))]
    times = [r.load_frame(i)["time"] for i in range(len(r))]
    np.testing.assert_allclose(times, np.arange(11) * 0.1, atol=1e-9)
    r.close()


def test_cli_run_free_fiber_uniform_background(tmp_path):
    """Fiber advected by uniform background: x advances by u*t (the reference's
    `test_fiber_uniform_background.py` oracle)."""
    cfg_path = _free_fiber_config(tmp_path)
    cli.run(cfg_path)

    traj = str(tmp_path / "skelly_sim.out")
    assert os.path.exists(traj)
    assert os.path.exists(str(tmp_path / "skelly_sim.initial_config"))
    assert os.path.exists(str(tmp_path / "skelly_sim.final_config"))

    r = TrajectoryReader(traj)
    assert len(r) >= 2
    first, last = r.load_frame(0), r.load_frame(len(r) - 1)
    t_el = last["time"] - first["time"]
    x0 = np.asarray(first["fibers"][1][0]["x_"])
    x1 = np.asarray(last["fibers"][1][0]["x_"])
    drift = (x1 - x0).reshape(-1, 3)
    np.testing.assert_allclose(drift[:, 0], t_el, atol=1e-10)
    np.testing.assert_allclose(drift[:, 1:], 0.0, atol=1e-10)
    r.close()


def test_cli_guards(tmp_path):
    cfg_path = _free_fiber_config(tmp_path)
    cli.run(cfg_path)
    with pytest.raises(SystemExit, match="refusing"):
        cli.run(cfg_path)
    with pytest.raises(SystemExit, match="does not exist"):
        cli.run(str(tmp_path / "skelly_config.toml"),
                trajectory_path=str(tmp_path / "nope.out"), resume=True)


def test_cli_resume_continues_and_appends_metrics(tmp_path):
    """--resume extends the trajectory, and with --metrics-file appends to
    the existing metrics file after a {"resume": true} marker line so
    post-hoc analysis can segment runs (step indices restart at 0 per
    run)."""
    import json

    cfg_path = _free_fiber_config(tmp_path)
    metrics = str(tmp_path / "metrics.jsonl")
    cli.run(cfg_path, metrics_path=metrics)
    traj = str(tmp_path / "skelly_sim.out")
    r = TrajectoryReader(traj)
    t_end1 = r.load_frame(len(r) - 1)["time"]
    n1 = len(r)
    r.close()
    n_first = len(open(metrics).readlines())

    # extend t_final and resume
    from skellysim_tpu.config import load_config
    cfg = load_config(cfg_path)
    cfg.params.t_final = 0.04
    cfg.save(cfg_path)
    cli.run(cfg_path, resume=True, metrics_path=metrics)

    r = TrajectoryReader(traj)
    assert len(r) > n1
    t_end2 = r.load_frame(len(r) - 1)["time"]
    assert t_end2 > t_end1
    assert t_end2 == pytest.approx(0.04, abs=0.006)
    r.close()

    lines = [json.loads(ln) for ln in open(metrics)]
    assert len(lines) > n_first + 1
    markers = [(i, rec) for i, rec in enumerate(lines) if "resume" in rec]
    assert len(markers) == 1
    i_mark, marker = markers[0]
    assert i_mark == n_first and marker["resume"] is True
    assert marker["t"] == pytest.approx(0.02, abs=0.006)
    # both segments' step indices restart at 0
    assert lines[0]["step"] == 0 and lines[i_mark + 1]["step"] == 0


def test_precompute_and_body_drag_pipeline(tmp_path):
    """Config with a sphere body under constant force inside no periphery:
    velocity matches Stokes drag 6*pi*eta*R*v (reference
    `test_body_const_force.py`, 1e-6 gate relaxed to quadrature accuracy)."""
    cfg = Config()
    cfg.params.eta = 1.3
    cfg.params.dt_initial = 0.005
    cfg.params.dt_write = 0.005
    cfg.params.t_final = 0.01
    cfg.params.adaptive_timestep_flag = False
    cfg.params.gmres_tol = 1e-10
    body = Body(radius=0.6, n_nodes=600, external_force=[0.0, 0.0, 1.0],
                precompute_file="body.npz")
    cfg.bodies = [body]
    cfg_path = str(tmp_path / "skelly_config.toml")
    cfg.save(cfg_path)

    precompute.precompute_from_config(cfg_path, verbose=False)
    assert os.path.exists(str(tmp_path / "body.npz"))

    system, state, rng = builder.build_simulation(cfg_path)
    new_state, solution, info = system.step(state)
    assert bool(info.converged)
    v = np.asarray(new_state.bodies.velocity)[0]
    # hydrodynamic radius is the quadrature-node radius (0.6 - 0.1)
    expected = 1.0 / (6 * np.pi * 1.3 * 0.5)
    assert abs(v[2] - expected) / expected < 2e-3
    np.testing.assert_allclose(v[:2], 0.0, atol=1e-8)


def test_precompute_spherical_periphery_pipeline(tmp_path):
    """Point force inside a spherical shell: rigid-wall flow at the shell is
    cancelled (shell solve converges and density is finite)."""
    cfg = ConfigSpherical()
    cfg.params.eta = 1.0
    cfg.params.dt_initial = 0.01
    cfg.params.t_final = 0.01
    cfg.params.adaptive_timestep_flag = False
    cfg.periphery.radius = 2.0
    cfg.periphery.n_nodes = 300
    cfg.periphery.precompute_file = "periphery.npz"
    cfg.point_sources = [Point(position=[0.0, 0.0, 0.5], force=[0.0, 0.0, 1.0])]
    fib = Fiber(n_nodes=16, length=0.5, bending_rigidity=0.01)
    fib.fill_node_positions(np.array([0.5, 0.0, 0.0]), np.array([0.0, 0.0, 1.0]))
    cfg.fibers = [fib]
    cfg_path = str(tmp_path / "skelly_config.toml")
    cfg.save(cfg_path)

    precompute.precompute_from_config(cfg_path, verbose=False)
    # the stored operator must be genuine float64: the assembly runs through
    # the JAX kernels, and a missing x64 enable silently degraded it to
    # f32-grade values (~2.7e-8 relative — found by round-5 verify)
    peri_npz = np.load(str(tmp_path / "periphery.npz"))
    assert peri_npz["stresslet_plus_complementary"].dtype == np.float64
    system, state, rng = builder.build_simulation(cfg_path)
    new_state, solution, info = system.step(state)
    assert bool(info.converged)
    assert np.all(np.isfinite(np.asarray(new_state.shell.density)))
    assert np.all(np.isfinite(np.asarray(new_state.fibers.x)))


def test_builder_buckets_mixed_resolution(tmp_path):
    """Mixed n_nodes configs bucket by resolution (round 4 — previously
    rejected; the reference's mixed std::list container,
    `fiber_finite_difference.cpp:519-562`)."""
    cfg = Config()
    f1 = Fiber(n_nodes=16); f1.fill_node_positions(np.zeros(3), np.array([0, 0, 1.0]))
    f2 = Fiber(n_nodes=32); f2.fill_node_positions(np.ones(3), np.array([0, 0, 1.0]))
    cfg.fibers = [f1, f2]
    groups = builder.build_fibers(cfg.fibers, np.float64)
    assert isinstance(groups, tuple) and len(groups) == 2
    assert [g.n_nodes for g in groups] == [16, 32]
    assert [int(g.config_rank[0]) for g in groups] == [0, 1]


def test_listener_evaluator_mapping():
    """Reference evaluator names map onto the pair-evaluator seam
    (`listener.cpp:117` -> direct/ring/ewald)."""
    from skellysim_tpu.listener import switch_evaluator
    from skellysim_tpu.params import Params
    from skellysim_tpu.system import System

    system = System(Params(adaptive_timestep_flag=False))
    for name in ("CPU", "GPU", "TPU", None, "direct"):
        s2, switched = switch_evaluator(system, name)
        assert not switched and s2 is system, name
    # unrecognized names are rejected (the schema path's reject-typos policy)
    with pytest.raises(ValueError):
        switch_evaluator(system, "unknown")
    s2, switched = switch_evaluator(system, "FMM")
    assert switched and s2.params.pair_evaluator == "ewald"
    s2r, switched = switch_evaluator(system, "ring")
    assert switched and s2r.params.pair_evaluator == "ring"
    # and back
    s3, switched = switch_evaluator(s2, "CPU")
    assert switched and s3.params.pair_evaluator == "direct"


@pytest.mark.slow
def test_cli_pipeline_revolution_periphery(tmp_path):
    """gen -> precompute -> run for a surface-of-revolution periphery
    (`examples/oocyte` shape at fixture scale): exercises the envelope fit,
    the precompute node-count write-back, and the generic-shell solve."""
    from skellysim_tpu.config import ConfigRevolution

    cfg = ConfigRevolution()
    cfg.params.dt_initial = 0.01
    cfg.params.dt_write = 0.01
    cfg.params.t_final = 0.02
    cfg.params.adaptive_timestep_flag = False
    cfg.periphery.envelope = {
        "n_nodes_target": 150,
        "lower_bound": -3.75, "upper_bound": 3.75,
        "height": "0.5 * T * ((1 + 2*x/length)**p1) * ((1 - 2*x/length)**p2) * length",
        "T": 0.72, "p1": 0.4, "p2": 0.2, "length": 7.5,
    }
    fib = Fiber(n_nodes=8, length=0.5, bending_rigidity=0.0025,
                minus_clamped=True)
    cfg.fibers = [fib]
    cfg.periphery.move_fibers_to_surface(cfg.fibers, ds_min=0.2, verbose=False,
                                         rng=np.random.default_rng(3))
    cfg_path = str(tmp_path / "skelly_config.toml")
    cfg.save(cfg_path)

    precompute.precompute_from_config(cfg_path, verbose=False)
    # revolution precompute rewrites the config with the realized node count
    from skellysim_tpu.config import load_config

    back = load_config(cfg_path)
    assert os.path.exists(str(tmp_path / back.periphery.precompute_file))
    n_realized = int(np.load(str(tmp_path / back.periphery.precompute_file))
                     ["nodes"].shape[0])
    assert back.periphery.n_nodes == n_realized

    cli.run(cfg_path)
    traj = TrajectoryReader(str(tmp_path / "skelly_sim.out"))
    assert len(traj) >= 1
    frame = traj.load_frame(-1)
    assert np.asarray(frame["shell"]["solution_vec_"]).size == 3 * n_realized


@pytest.mark.slow
def test_cli_pipeline_ellipsoid_periphery(tmp_path):
    """gen -> precompute -> run for an ellipsoidal periphery
    (`examples/ellipsoid` shape at fixture scale)."""
    from skellysim_tpu.config import ConfigEllipsoidal, load_config

    cfg = ConfigEllipsoidal()
    cfg.params.dt_initial = 0.01
    cfg.params.dt_write = 0.01
    cfg.params.t_final = 0.02
    cfg.params.adaptive_timestep_flag = False
    cfg.periphery.n_nodes = 150
    cfg.periphery.a, cfg.periphery.b, cfg.periphery.c = 6.0, 4.0, 4.0
    fib = Fiber(n_nodes=8, length=0.5, bending_rigidity=0.0025,
                minus_clamped=True)
    cfg.fibers = [fib]
    cfg.periphery.move_fibers_to_surface(cfg.fibers, ds_min=0.2, verbose=False,
                                         rng=np.random.default_rng(5))
    cfg_path = str(tmp_path / "skelly_config.toml")
    cfg.save(cfg_path)

    precompute.precompute_from_config(cfg_path, verbose=False)
    cli.run(cfg_path)
    traj = TrajectoryReader(str(tmp_path / "skelly_sim.out"))
    assert len(traj) >= 1
    back = load_config(cfg_path)
    frame = traj.load_frame(-1)
    assert (np.asarray(frame["shell"]["solution_vec_"]).size
            == 3 * back.periphery.n_nodes)
