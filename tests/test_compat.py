"""Direct coverage for `parallel.compat` — the jax-version seam itself.

The seam un-broke 27 seed tests (PR 3) but until ISSUE 8 had no tests of
its own: everything exercised it only through the big SPMD programs. These
pin the three behaviors the call sites rely on, fast-tier sized:

* `shard_map` routes to whatever API the running jax ships, and the 0.4.x
  fallback ALWAYS disables replication checking (`check_rep=False`) — the
  old checker has no while/scan rule, and every solver loop here is a
  `lax.while_loop` (requesting `check_vma=True` must still build);
* `use_mesh` yields a context manager on every jax (modern `jax.set_mesh`
  or the legacy Mesh-as-context), None being a no-op;
* `fused_ring_mode` selects the ring transfer path at build time:
  ppermute on CPU / non-pallas tiles / explicit opt-out, the fused Pallas
  kernel only where the backend can compile it.
"""

import jax
import jax.numpy as jnp
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from skellysim_tpu.parallel import make_mesh
from skellysim_tpu.parallel.compat import (fused_ring_mode, shard_map,
                                           use_mesh)
from skellysim_tpu.parallel.mesh import FIBER_AXIS


def test_shard_map_fallback_selection():
    """The wrapper uses `jax.shard_map` where it exists, else the 0.4.x
    experimental spelling — exactly one of the two, chosen by presence."""
    mesh = make_mesh(2)
    f = shard_map(lambda x: lax.psum(x, FIBER_AXIS), mesh=mesh,
                  in_specs=(P(FIBER_AXIS),), out_specs=P(FIBER_AXIS))
    x = jnp.arange(8, dtype=jnp.float32)
    out = f(x)
    # psum of per-shard partials: every element = sum of its shard pair
    expected = jnp.repeat(x.reshape(2, 4).sum(0), 2).reshape(4, 2).T.reshape(-1)
    assert jnp.allclose(out, expected)


def test_shard_map_check_vma_survives_while_loop():
    """check_vma=True must BUILD AND RUN a while_loop body on the pinned
    0.4.x jax: the fallback maps it onto check_rep=False because the old
    replication checker rejects every solver loop (the exact seed
    breakage this seam exists to absorb)."""
    mesh = make_mesh(4)

    def local(x):
        def cond(c):
            _, i = c
            return i < 3

        def body(c):
            y, i = c
            return y + lax.psum(y, FIBER_AXIS) * 0.0 + 1.0, i + 1

        y, _ = lax.while_loop(cond, body, (x, jnp.int32(0)))
        return y

    f = shard_map(local, mesh=mesh, in_specs=(P(FIBER_AXIS),),
                  out_specs=P(FIBER_AXIS), check_vma=True)
    out = f(jnp.zeros(8, dtype=jnp.float32))
    assert jnp.allclose(out, 3.0)


def test_use_mesh_none_and_mesh():
    with use_mesh(None):
        pass  # no-op context
    mesh = make_mesh(2)
    with use_mesh(mesh):
        # inside the active-mesh context sharded computation still works
        assert jnp.asarray(1.0) + 1.0 == 2.0


def test_fused_ring_mode_cpu_defaults_to_ppermute(monkeypatch):
    monkeypatch.delenv("SKELLY_FUSED_RING", raising=False)
    # CPU backend: never the compiled fused kernel
    assert fused_ring_mode("pallas") == "ppermute"


def test_fused_ring_mode_non_pallas_tiles_keep_ppermute(monkeypatch):
    monkeypatch.delenv("SKELLY_FUSED_RING", raising=False)
    # exact/mxu/df probes must keep their tile semantics on the ring
    for impl in ("exact", "mxu", "df", "pallas_df"):
        assert fused_ring_mode(impl) == "ppermute", impl


def test_fused_ring_mode_overrides(monkeypatch):
    monkeypatch.setenv("SKELLY_FUSED_RING", "0")
    assert fused_ring_mode("pallas") == "ppermute"
    monkeypatch.setenv("SKELLY_FUSED_RING", "off")
    assert fused_ring_mode("pallas") == "ppermute"
    # interpret opt-in selects the interpreter kernel even off-TPU (its
    # remote-DMA emulation is a jax-version capability; selection is not
    # execution)
    monkeypatch.setenv("SKELLY_FUSED_RING", "interpret")
    assert fused_ring_mode("pallas") == "fused-interpret"
    # but the opt-out beats impl gating either way
    monkeypatch.setenv("SKELLY_FUSED_RING", "ppermute")
    assert fused_ring_mode("pallas") == "ppermute"


def test_fused_ring_mode_tpu_selects_fused(monkeypatch):
    monkeypatch.delenv("SKELLY_FUSED_RING", raising=False)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert fused_ring_mode("pallas") == "fused"
    assert fused_ring_mode("exact") == "ppermute"


def test_fused_ring_fallback_emits_fault_event(monkeypatch):
    """ISSUE-9 satellite pin: an ENVIRONMENTAL fallback from a pallas
    fused-ring request (CPU backend here — CI's case) degrades cleanly to
    ppermute AND logs a structured `fault` telemetry event, so a
    production run that silently lost its fused rings shows up in
    `obs summarize`'s fault table. Explicit opt-outs stay silent."""
    from skellysim_tpu.obs import tracer as obs_tracer

    monkeypatch.delenv("SKELLY_FUSED_RING", raising=False)
    tr = obs_tracer.Tracer()
    with obs_tracer.use(tr):
        assert fused_ring_mode("pallas") == "ppermute"
    faults = [e for e in tr.events if e["ev"] == "fault"]
    assert len(faults) == 1
    assert faults[0]["kind"] == "fused_ring_fallback"
    assert faults[0]["reason"]  # names WHY (backend-cpu / no-remote-dma)

    # the deliberate opt-out emits nothing (it is not a fault)
    monkeypatch.setenv("SKELLY_FUSED_RING", "0")
    tr2 = obs_tracer.Tracer()
    with obs_tracer.use(tr2):
        assert fused_ring_mode("pallas") == "ppermute"
    assert not [e for e in tr2.events if e["ev"] == "fault"]
    monkeypatch.delenv("SKELLY_FUSED_RING", raising=False)
    tr3 = obs_tracer.Tracer()
    with obs_tracer.use(tr3):
        assert fused_ring_mode("exact") == "ppermute"
    assert not [e for e in tr3.events if e["ev"] == "fault"]


def test_fused_ring_fallback_without_remote_dma(monkeypatch):
    """`pltpu.make_async_remote_copy` missing at build time (older pallas
    builds) must fall back with the no-remote-dma reason, not crash."""
    from jax.experimental.pallas import tpu as pltpu

    from skellysim_tpu.obs import tracer as obs_tracer

    monkeypatch.delenv("SKELLY_FUSED_RING", raising=False)
    monkeypatch.delattr(pltpu, "make_async_remote_copy", raising=False)
    tr = obs_tracer.Tracer()
    with obs_tracer.use(tr):
        assert fused_ring_mode("pallas") == "ppermute"
    faults = [e for e in tr.events if e["ev"] == "fault"]
    assert faults and faults[0]["reason"] == "no-remote-dma"
    assert faults[0]["leg"] == "missing-api"


def test_fused_ring_fallback_legs(monkeypatch):
    """ISSUE-16 satellite: every fallback fault names WHICH eligibility
    leg failed. The platform leg comes from `fused_ring_mode` (CPU
    backend); the budget leg from the `parallel.ring` call site when the
    shape fails the VMEM check on an otherwise-eligible backend."""
    from skellysim_tpu.obs import tracer as obs_tracer
    from skellysim_tpu.parallel.compat import fused_ring_budget_fallback

    monkeypatch.delenv("SKELLY_FUSED_RING", raising=False)
    tr = obs_tracer.Tracer()
    with obs_tracer.use(tr):
        assert fused_ring_mode("pallas") == "ppermute"
    (fault,) = [e for e in tr.events if e["ev"] == "fault"]
    assert fault["leg"] == "platform"

    tr2 = obs_tracer.Tracer()
    with obs_tracer.use(tr2):
        fused_ring_budget_fallback("stokeslet", 4096, 4096, 8)
    (fault,) = [e for e in tr2.events if e["ev"] == "fault"]
    assert fault["kind"] == "fused_ring_fallback"
    assert fault["leg"] == "budget"
    assert "vmem-budget-stokeslet-4096x4096x8" == fault["reason"]


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="compiled fused ring needs a TPU backend")
def test_fused_ring_executes_on_tpu():
    """On real hardware the fused kernel must agree with the ppermute ring
    (same tile math, same accumulation order) to f32 tile tolerance."""
    import numpy as np

    from skellysim_tpu.ops import kernels
    from skellysim_tpu.parallel.ring import ring_stokeslet

    rng = np.random.default_rng(0)
    n = 512
    r = jnp.asarray(rng.uniform(-1, 1, (n, 3)), dtype=jnp.float32)
    f = jnp.asarray(rng.standard_normal((n, 3)), dtype=jnp.float32)
    mesh = make_mesh(min(4, len(jax.devices())))
    ref = kernels.stokeslet_direct(r, r, f, 1.0)
    u = ring_stokeslet(r, r, f, 1.0, mesh=mesh, impl="pallas")
    assert float(jnp.abs(u - ref).max()) < 5e-5
