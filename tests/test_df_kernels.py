"""Double-float f32 kernels vs the f64 oracle.

The reference's backend-agreement gate is 5e-9
(`/root/reference/tests/core/kernel_test.cpp:93`); TPU native f64 is ~113x
slower than f32. `ops.df_kernels` reaches ~1e-14 from f32 VPU arithmetic —
these tests pin that, including under jit (XLA's simplifier cancelled the
compensation terms before the optimization barriers went in) and for f64
inputs via hi/lo splitting.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skellysim_tpu.ops import kernels
from skellysim_tpu.ops.df_kernels import (_df_rsqrt, _two_prod, _two_sum,
                                          stokeslet_direct_df)


def test_error_free_transforms_exact_under_jit():
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.uniform(-100, 100, 2000), jnp.float32)
    b = jnp.asarray(rng.uniform(-100, 100, 2000), jnp.float32)
    p, e = jax.jit(_two_prod)(a, b)
    exact = a.astype(jnp.float64) * b.astype(jnp.float64)
    assert float(jnp.max(jnp.abs(p.astype(jnp.float64)
                                 + e.astype(jnp.float64) - exact))) == 0.0
    s, e2 = jax.jit(_two_sum)(a, b)
    exact = a.astype(jnp.float64) + b.astype(jnp.float64)
    assert float(jnp.max(jnp.abs(s.astype(jnp.float64)
                                 + e2.astype(jnp.float64) - exact))) == 0.0


def test_df_rsqrt_full_precision_under_jit():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.uniform(1e-4, 1e4, 4000), jnp.float32)
    yh, yl = jax.jit(_df_rsqrt)(x, jnp.zeros_like(x))
    ref = 1.0 / np.sqrt(np.asarray(x, np.float64))
    rel = np.abs((np.asarray(yh, np.float64) + np.asarray(yl, np.float64))
                 / ref - 1.0)
    assert rel.max() < 1e-13, rel.max()


@pytest.mark.slow
def test_stokeslet_df_beats_reference_gate_f32_inputs():
    rng = np.random.default_rng(5)
    n = 1500
    r32 = jnp.asarray(rng.uniform(-10, 10, (n, 3)), jnp.float32)
    f32 = jnp.asarray(rng.standard_normal((n, 3)), jnp.float32)
    ref = np.asarray(kernels.stokeslet_direct(
        r32.astype(jnp.float64), r32.astype(jnp.float64),
        f32.astype(jnp.float64), 1.3))
    df = np.asarray(stokeslet_direct_df(r32, r32, f32, 1.3))
    err = np.linalg.norm(df - ref) / np.linalg.norm(ref)
    assert err < 5e-9, err   # the reference gate, with orders of margin
    assert err < 1e-12, err  # the actual DF envelope


def test_stokeslet_df_f64_inputs_via_hi_lo_split():
    """f64 positions/forces (the mixed solver's residual operands) keep
    ~2^-48-class accuracy through the hi/lo split."""
    rng = np.random.default_rng(7)
    n = 800
    r = jnp.asarray(rng.uniform(-10, 10, (n, 3)))
    f = jnp.asarray(rng.standard_normal((n, 3)))
    assert r.dtype == jnp.float64
    ref = np.asarray(kernels.stokeslet_direct(r, r, f, 0.9))
    df = np.asarray(stokeslet_direct_df(r, r, f, 0.9))
    err = np.linalg.norm(df - ref) / np.linalg.norm(ref)
    assert err < 1e-11, err
    # chunking invariance
    df2 = np.asarray(stokeslet_direct_df(r, r, f, 0.9, block_size=128,
                                         source_block=256))
    np.testing.assert_allclose(df2, df, rtol=0, atol=1e-13)


def test_stokeslet_df_masks_coincident_pairs():
    rng = np.random.default_rng(9)
    r = jnp.asarray(rng.uniform(-1, 1, (64, 3)), jnp.float32)
    f = jnp.asarray(rng.standard_normal((64, 3)), jnp.float32)
    # targets == sources: the self pair must drop, matching the exact kernel
    ref = np.asarray(kernels.stokeslet_direct(r, r, f, 1.0))
    df = np.asarray(stokeslet_direct_df(r, r, f, 1.0))
    assert np.all(np.isfinite(df))
    np.testing.assert_allclose(df, ref, rtol=0, atol=1e-6)


def test_stokeslet_df_near_pairs_f64():
    """Close f64 pairs keep DF accuracy down to (and past) physical node
    spacings. The displacement's relative accuracy is bounded by the 48-bit
    hi/lo position split: ~2^-48 * |x| / |d| — at coordinate magnitude ~4
    that is ~1.4e-14/|d|, so any separation above ~3e-6 stays under the 5e-9
    gate; separations below the f32 ulp degrade gracefully (normalized by
    the full two_sum in comp()) to the split's representation limit rather
    than failing."""
    base = np.array([1.0, 2.0, 3.0])
    f = jnp.asarray(np.eye(3) * [[1.0], [0.5], [2.0]])
    for sep, gate in ((1e-2, 5e-9), (1e-4, 5e-9), (3e-8, 1e-4)):
        r = jnp.asarray(np.stack([base, base + [sep, 0, 0],
                                  base + [5.0, 0, 0]]))
        assert r.dtype == jnp.float64
        ref = np.asarray(kernels.stokeslet_direct(r, r, f, 1.0))
        df = np.asarray(stokeslet_direct_df(r, r, f, 1.0))
        err = np.linalg.norm(df - ref) / np.linalg.norm(ref)
        assert err < gate, (sep, err)


@pytest.mark.slow
def test_stresslet_df_beats_reference_gate():
    """DF stresslet vs the native-f64 kernel at both f32 and f64 inputs."""
    from skellysim_tpu.ops.df_kernels import stresslet_direct_df

    rng = np.random.default_rng(11)
    n = 600
    r64 = jnp.asarray(rng.uniform(-5, 5, (n, 3)))
    S64 = jnp.asarray(rng.standard_normal((n, 3, 3)))
    assert r64.dtype == jnp.float64
    ref = np.asarray(kernels.stresslet_direct(r64, r64, S64, 1.1))
    df = np.asarray(stresslet_direct_df(r64, r64, S64, 1.1))
    err = np.linalg.norm(df - ref) / np.linalg.norm(ref)
    assert err < 5e-9, err   # the reference gate
    assert err < 1e-11, err  # the DF envelope

    r32, S32 = r64.astype(jnp.float32), S64.astype(jnp.float32)
    ref32 = np.asarray(kernels.stresslet_direct(
        r32.astype(jnp.float64), r32.astype(jnp.float64),
        S32.astype(jnp.float64), 1.1))
    df32 = np.asarray(stresslet_direct_df(r32, r32, S32, 1.1))
    err32 = np.linalg.norm(df32 - ref32) / np.linalg.norm(ref32)
    assert err32 < 1e-12, err32

    # chunking invariance + separate target set
    trg = jnp.asarray(rng.uniform(-5, 5, (97, 3)))
    a = np.asarray(stresslet_direct_df(r64, trg, S64, 1.1))
    b = np.asarray(stresslet_direct_df(r64, trg, S64, 1.1, block_size=32,
                                       source_block=128))
    np.testing.assert_allclose(a, b, rtol=0, atol=1e-13)


def test_df_dispatch_smoke():
    """Fast-tier guard for the `impl="df"` dispatch (the accelerator-default
    refinement path): tiny n so the per-commit tier keeps covering the
    seam while the thorough block-shape/accuracy tests are slow-marked."""
    rng = np.random.default_rng(13)
    r = jnp.asarray(rng.uniform(-3, 3, (48, 3)))
    f = jnp.asarray(rng.standard_normal((48, 3)))
    via_seam = np.asarray(kernels.stokeslet_direct(r, r, f, 1.0, impl="df"))
    assert via_seam.dtype == np.float64
    ref = np.asarray(kernels.stokeslet_direct(
        r.astype(jnp.float64), r.astype(jnp.float64),
        f.astype(jnp.float64), 1.0))
    assert np.linalg.norm(via_seam - ref) / np.linalg.norm(ref) < 1e-12


@pytest.mark.slow
def test_df_impl_through_kernel_seam():
    """`impl="df"` on the public kernels dispatches to the DF tiles."""
    rng = np.random.default_rng(13)
    r = jnp.asarray(rng.uniform(-3, 3, (200, 3)))
    f = jnp.asarray(rng.standard_normal((200, 3)))
    S = jnp.asarray(rng.standard_normal((200, 3, 3)))
    a = np.asarray(kernels.stokeslet_direct(r, r, f, 1.0, impl="df"))
    b = np.asarray(stokeslet_direct_df(r, r, f, 1.0))
    np.testing.assert_array_equal(a, b)
    from skellysim_tpu.ops.df_kernels import stresslet_direct_df

    c = np.asarray(kernels.stresslet_direct(r, r, S, 1.0, impl="df"))
    d = np.asarray(stresslet_direct_df(r, r, S, 1.0))
    np.testing.assert_array_equal(c, d)
