"""Golden regression: committed final fiber positions.

Mirror of the reference's regression tier
(`tests/combined/regression_tests/test_body_fdfiber_compression.py` with
`fdfiber_compression_finalpositions.npz`): a deterministic coupled sim whose
final state is compared bit-tightly against a committed npz. Regenerate after
an intentional physics change with:

    python tests/test_golden_regression.py --regen
"""

import os

import jax.numpy as jnp
import numpy as np

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "golden", "shear_motor_finalpositions.npz")


def _run():
    from skellysim_tpu.fibers import container as fc
    from skellysim_tpu.params import Params
    from skellysim_tpu.system import BackgroundFlow, System

    rng = np.random.default_rng(17)
    nf, n = 4, 16
    t = np.linspace(0, 1, n)
    origins = rng.uniform(-1.0, 1.0, size=(nf, 3))
    dirs = rng.normal(size=(nf, 3))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    x = origins[:, None, :] + t[None, :, None] * dirs[:, None, :]

    fibers = fc.make_group(x, lengths=1.0, bending_rigidity=0.01,
                           radius=0.0125, force_scale=-0.05,
                           dtype=jnp.float64)
    params = Params(eta=1.0, dt_initial=0.005, t_final=0.05, gmres_tol=1e-12,
                    adaptive_timestep_flag=False)
    system = System(params)
    state = system.make_state(
        fibers=fibers,
        background=BackgroundFlow.make(uniform=(0.0, 0.0, 0.0),
                                       components=(1, 0, 2),
                                       scale=(0.5, 0.0, 0.0),
                                       dtype=jnp.float64))
    final = system.run(state)
    return np.asarray(final.fibers.x), np.asarray(final.fibers.tension)


def test_golden_final_positions():
    x, tension = _run()
    assert os.path.exists(GOLDEN), (
        f"golden file missing; regenerate with python {__file__} --regen")
    with np.load(GOLDEN) as z:
        # relative-ish tolerance: an adaptive f64 sim can shift by BLAS /
        # platform / jax version; the golden is not platform-pinned
        np.testing.assert_allclose(x, z["x"], rtol=1e-8, atol=1e-8)
        np.testing.assert_allclose(tension, z["tension"], rtol=1e-6, atol=1e-6)


if __name__ == "__main__":
    import sys

    if "--regen" in sys.argv:
        x, tension = _run()
        os.makedirs(os.path.dirname(GOLDEN), exist_ok=True)
        np.savez(GOLDEN, x=x, tension=tension)
        print(f"wrote {GOLDEN}")
