"""Pallas double-float tiles: accuracy + seam routing (interpret on CPU).

Mirrors the XLA DF kernel pins (`test_df_kernels.py`): the fused Pallas
tiles must deliver the same ~1e-14-class relative accuracy from pure f32
pair arithmetic, drop self pairs, survive padding, and ride the
`kernels.*_direct(impl="pallas_df")` seam. The real-hardware authority is
the `@pytest.mark.tpu` agreement gate at the bottom (interpret mode runs
XLA:CPU arithmetic, not Mosaic's).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from skellysim_tpu.ops import kernels
from skellysim_tpu.ops.df_kernels import stokeslet_direct_df, stresslet_direct_df
from skellysim_tpu.ops.pallas_df import stokeslet_pallas_df, stresslet_pallas_df

RNG = np.random.default_rng(11)


def _cloud(n_src, n_trg, overlap=0):
    r_src = RNG.uniform(-5, 5, (n_src, 3))
    r_trg = RNG.uniform(-5, 5, (n_trg, 3))
    if overlap:
        r_trg[:overlap] = r_src[:overlap]  # exercise self-pair dropping
    f = RNG.standard_normal((n_src, 3))
    return r_src, r_trg, f


def _oracle_stokeslet(r_src, r_trg, f_src, eta=1.0):
    d = r_trg[:, None, :] - r_src[None, :, :]
    r2 = np.sum(d * d, axis=-1)
    rinv = np.where(r2 > 0, 1.0 / np.sqrt(np.where(r2 > 0, r2, 1.0)), 0.0)
    df = np.einsum("tsk,sk->ts", d, f_src)
    u = np.einsum("ts,sk->tk", rinv, f_src) + np.einsum("ts,tsk->tk",
                                                        df * rinv**3, d)
    return u / (8 * np.pi * eta)


def _oracle_stresslet(r_dl, r_trg, S, eta=1.0):
    d = r_trg[:, None, :] - r_dl[None, :, :]
    r2 = np.sum(d * d, axis=-1)
    rinv = np.where(r2 > 0, 1.0 / np.sqrt(np.where(r2 > 0, r2, 1.0)), 0.0)
    dSd = np.einsum("tsi,sij,tsj->ts", d, S, d)
    return np.einsum("ts,tsk->tk", -3.0 * dSd * rinv**5, d) / (8 * np.pi * eta)


@pytest.mark.slow  # interpret-mode pallas: minutes-class on the 1-core CPU tier
def test_stokeslet_pallas_df_f64_accuracy():
    r_src, r_trg, f = _cloud(300, 200, overlap=40)
    got = np.asarray(stokeslet_pallas_df(jnp.asarray(r_src), jnp.asarray(r_trg),
                                         jnp.asarray(f), 1.3, interpret=True))
    assert got.dtype == np.float64
    ref = _oracle_stokeslet(r_src, r_trg, f, 1.3)
    assert np.linalg.norm(got - ref) / np.linalg.norm(ref) < 5e-13


@pytest.mark.slow  # interpret-mode pallas: minutes-class on the 1-core CPU tier
def test_stokeslet_pallas_df_matches_xla_df_twin():
    r_src, r_trg, f = _cloud(520, 140)  # src spans >1 source tile (512)
    a = np.asarray(stokeslet_pallas_df(jnp.asarray(r_src), jnp.asarray(r_trg),
                                       jnp.asarray(f), 1.0, interpret=True))
    b = np.asarray(stokeslet_direct_df(jnp.asarray(r_src), jnp.asarray(r_trg),
                                       jnp.asarray(f), 1.0))
    assert np.linalg.norm(a - b) / np.linalg.norm(b) < 1e-13


@pytest.mark.slow  # interpret-mode pallas: minutes-class on the 1-core CPU tier
def test_stokeslet_pallas_df_f32_inputs():
    """f32 inputs pass through with zero lo words — still DF-accurate
    relative to the f64 evaluation of the same f32 points."""
    r_src, r_trg, f = _cloud(130, 90)
    r32s, r32t, f32 = (a.astype(np.float32) for a in (r_src, r_trg, f))
    got = np.asarray(stokeslet_pallas_df(jnp.asarray(r32s), jnp.asarray(r32t),
                                         jnp.asarray(f32), 1.0,
                                         interpret=True))
    ref = _oracle_stokeslet(r32s.astype(np.float64), r32t.astype(np.float64),
                            f32.astype(np.float64))
    assert np.linalg.norm(got - ref) / np.linalg.norm(ref) < 5e-13


@pytest.mark.slow  # interpret-mode pallas: minutes-class on the 1-core CPU tier
def test_stresslet_pallas_df_accuracy():
    r_dl = RNG.uniform(-3, 3, (300, 3))
    r_trg = np.concatenate([r_dl[:50], RNG.uniform(-3, 3, (100, 3))], axis=0)
    S = RNG.standard_normal((300, 3, 3))
    got = np.asarray(stresslet_pallas_df(jnp.asarray(r_dl), jnp.asarray(r_trg),
                                         jnp.asarray(S), 0.7, interpret=True))
    ref = _oracle_stresslet(r_dl, r_trg, S, 0.7)
    assert np.linalg.norm(got - ref) / np.linalg.norm(ref) < 5e-13
    twin = np.asarray(stresslet_direct_df(jnp.asarray(r_dl),
                                          jnp.asarray(r_trg),
                                          jnp.asarray(S), 0.7))
    assert np.linalg.norm(got - twin) / np.linalg.norm(twin) < 1e-13


@pytest.mark.slow  # heavy coupled-solve integration; sibling fast tests keep the seam covered (ISSUE-9 870s-budget re-triage)
def test_empty_and_seam_routing():
    assert stokeslet_pallas_df(jnp.zeros((0, 3)), jnp.zeros((5, 3)),
                               jnp.zeros((0, 3)), 1.0,
                               interpret=True).shape == (5, 3)
    # the evaluator seam: impl="pallas_df" routes here (interpret on CPU)
    r_src, r_trg, f = _cloud(64, 48)
    via_seam = np.asarray(kernels.stokeslet_direct(
        jnp.asarray(r_src), jnp.asarray(r_trg), jnp.asarray(f), 1.0,
        impl="pallas_df"))
    ref = _oracle_stokeslet(r_src, r_trg, f)
    assert np.linalg.norm(via_seam - ref) / np.linalg.norm(ref) < 5e-13


@pytest.mark.slow  # interpret-mode pallas: minutes-class on the 1-core CPU tier
def test_mixed_solver_accepts_pallas_df():
    """refine_pair_impl="pallas_df": the mixed solve converges to 1e-10 with
    the Pallas DF residual tiles (interpret mode on this CPU suite)."""
    from __graft_entry__ import _make_system

    system, state = _make_system(n_fibers=2, n_nodes=16, dtype=jnp.float64,
                                 solver_precision="mixed",
                                 refine_pair_impl="pallas_df")
    import jax

    _, _, info = jax.jit(system._solve_impl)(state)
    assert float(info.residual_true) <= 1e-10


_TPU_SNIPPET = r"""
import json
import numpy as np
import jax
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
from skellysim_tpu.ops.pallas_df import stokeslet_pallas_df, stresslet_pallas_df

rng = np.random.default_rng(7)
r_src = rng.uniform(-5, 5, (1024, 3))
r_trg = np.concatenate([r_src[:128], rng.uniform(-5, 5, (517, 3))], axis=0)
f = rng.standard_normal((1024, 3))
S = rng.standard_normal((1024, 3, 3))

d = r_trg[:, None, :] - r_src[None, :, :]
r2 = np.sum(d * d, axis=-1)
rinv = np.where(r2 > 0, 1.0 / np.sqrt(np.where(r2 > 0, r2, 1.0)), 0.0)
df = np.einsum("tsk,sk->ts", d, f)
ref_sto = (np.einsum("ts,sk->tk", rinv, f)
           + np.einsum("ts,tsk->tk", df * rinv**3, d)) / (8 * np.pi)
dSd = np.einsum("tsi,sij,tsj->ts", d, S, d)
ref_str = np.einsum("ts,tsk->tk", -3.0 * dSd * rinv**5, d) / (8 * np.pi)

got_sto = np.asarray(stokeslet_pallas_df(
    jnp.asarray(r_src), jnp.asarray(r_trg), jnp.asarray(f), 1.0))
got_str = np.asarray(stresslet_pallas_df(
    jnp.asarray(r_src), jnp.asarray(r_trg), jnp.asarray(S), 1.0))
print("RESULT=" + json.dumps({
    "backend": jax.default_backend(),
    "err_sto": float(np.linalg.norm(got_sto - ref_sto)
                     / np.linalg.norm(ref_sto)),
    "err_str": float(np.linalg.norm(got_str - ref_str)
                     / np.linalg.norm(ref_str)),
}))
"""


@pytest.mark.tpu
@pytest.mark.slow  # interpret-mode pallas: minutes-class on the 1-core CPU tier
def test_tpu_agreement():
    """Mosaic-compiled DF tiles on the real chip: the hardware authority for
    the compensation surviving the TPU pipeline (the reference's 5e-9
    backend-agreement gate, `kernel_test.cpp:93`, with 4+ orders margin)."""
    from tests.test_tpu_device import _tpu_available, _tpu_env

    if not _tpu_available():
        pytest.skip("no reachable TPU backend")
    p = subprocess.run([sys.executable, "-c", _TPU_SNIPPET],
                       capture_output=True, text=True, timeout=540,
                       env=_tpu_env())
    assert p.returncode == 0, p.stderr[-2000:]
    line = next(ln for ln in p.stdout.splitlines() if ln.startswith("RESULT="))
    res = json.loads(line[len("RESULT="):])
    assert res["backend"] == "tpu"
    assert res["err_sto"] < 1e-12, res
    assert res["err_str"] < 1e-12, res
