"""Trajectory round-trip + index + resume tests.

Mirrors the reference's serialization unit tests
(`tests/core/unit_tests/unit_test_serialization.cpp`) and the checkpoint/resume
subsystem (SURVEY.md §5.4): write frames, rebuild the index, reload and compare
state bit-for-bit (float64 payloads survive msgpack exactly).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from skellysim_tpu.fibers import container as fc
from skellysim_tpu.io import TrajectoryReader, TrajectoryWriter, resume_state
from skellysim_tpu.io import eigen, trajectory
from skellysim_tpu.params import Params
from skellysim_tpu.system import BackgroundFlow, System


def make_state(nf=3, n=16):
    rng = np.random.default_rng(3)
    x = np.cumsum(rng.standard_normal((nf, n, 3)) * 0.05, axis=1)
    params = Params(eta=1.0, dt_initial=2.5e-3, t_final=1e-2, gmres_tol=1e-10,
                    adaptive_timestep_flag=False)
    system = System(params)
    fibers = fc.make_group(x, lengths=1.0, bending_rigidity=0.01, radius=0.0125)
    fibers = fibers._replace(tension=jnp.asarray(rng.standard_normal((nf, n))))
    state = system.make_state(
        fibers=fibers, background=BackgroundFlow.make(uniform=(0.1, 0.0, 0.0)))
    return system, state


def test_eigen_wire_roundtrip():
    rng = np.random.default_rng(0)
    for shape in [(7,), (5, 3), (4, 6)]:
        a = rng.standard_normal(shape)
        wire = eigen.pack_matrix(a)
        back = eigen.unpack_matrix(wire)
        np.testing.assert_array_equal(back.reshape(a.shape), a)
        # reference reader semantics: [n,3] arrays come back as points-by-rows
    q = rng.standard_normal(4)
    assert eigen.pack_quat(q)[0] == "__quat__"
    np.testing.assert_array_equal(eigen.decode_tree(eigen.pack_quat(q)), q)


def test_write_read_roundtrip(tmp_path):
    path = str(tmp_path / "skelly_sim.out")
    system, state = make_state()
    with TrajectoryWriter(path) as tw:
        tw.write_frame(state, rng_state=[["main", "0:1:2"]])
        state2 = state._replace(time=state.time + state.dt)
        tw.write_frame(state2)

    tr = TrajectoryReader(path)
    assert tr.trajectory_version == 1
    assert len(tr) == 2
    assert tr.times == pytest.approx([0.0, 2.5e-3])

    tr.load_frame(0)
    fibs = tr["fibers"]
    assert len(fibs) == 3
    np.testing.assert_allclose(fibs[0]["x_"], np.asarray(state.fibers.x[0]),
                               rtol=0, atol=0)
    np.testing.assert_allclose(fibs[1]["tension_"],
                               np.asarray(state.fibers.tension[1]), rtol=0, atol=0)
    assert tr["bodies"] == []
    assert tr.load_frame(1)["rng_state"] == []


def test_native_index_matches_python(tmp_path):
    path = str(tmp_path / "skelly_sim.out")
    system, state = make_state(nf=2, n=8)
    with TrajectoryWriter(path) as tw:
        for k in range(5):
            tw.write_frame(state._replace(time=state.time + k * state.dt))

    py_off, py_t = trajectory._scan_python(path)
    nat = trajectory._scan_native(path)
    assert len(py_off) == 5
    if nat is None:
        pytest.skip("no C++ toolchain")
    assert nat[0] == py_off
    np.testing.assert_allclose(nat[1], py_t)


def test_index_cache_reused(tmp_path):
    path = str(tmp_path / "skelly_sim.out")
    system, state = make_state(nf=1, n=8)
    with TrajectoryWriter(path) as tw:
        tw.write_frame(state)
    tr1 = TrajectoryReader(path)
    # second open must load the cached .cindex (same mtime)
    tr2 = TrajectoryReader(path)
    assert tr2._fpos == tr1._fpos


def test_resume_roundtrip(tmp_path):
    path = str(tmp_path / "skelly_sim.out")
    system, state = make_state()
    new_state, solution, info = system.step(state)
    new_state = new_state._replace(time=state.time + state.dt)
    with TrajectoryWriter(path) as tw:
        tw.write_frame(state)
        tw.write_frame(new_state)

    resumed, rng_state, reader = resume_state(path, state)
    np.testing.assert_array_equal(np.asarray(resumed.fibers.x),
                                  np.asarray(new_state.fibers.x))
    np.testing.assert_array_equal(np.asarray(resumed.fibers.tension),
                                  np.asarray(new_state.fibers.tension))
    assert float(resumed.time) == pytest.approx(float(new_state.time))

    # resumed state must be steppable and agree with stepping the original
    a, _, _ = system.step(resumed)
    b, _, _ = system.step(new_state)
    np.testing.assert_allclose(np.asarray(a.fibers.x), np.asarray(b.fibers.x),
                               rtol=0, atol=1e-12)


def test_reference_reader_compatible_layout(tmp_path):
    """The raw frame must follow the reference's wire schema exactly."""
    import msgpack

    path = str(tmp_path / "skelly_sim.out")
    system, state = make_state(nf=1, n=8)
    with TrajectoryWriter(path) as tw:
        tw.write_frame(state)
    with open(path, "rb") as fh:
        unpacker = msgpack.Unpacker(fh, raw=False)
        header = unpacker.unpack()
        frame = unpacker.unpack()
    assert list(header)[0] == "trajversion"
    assert set(frame) == {"time", "dt", "rng_state", "fibers", "bodies", "shell"}
    assert frame["fibers"][0] == trajectory.FIBER_TYPE_FINITE_DIFFERENCE
    fib = frame["fibers"][1][0]
    assert fib["x_"][0] == "__eigen__" and fib["x_"][1] == 3  # 3 x n col-major
    assert frame["bodies"] == [[], [], []]
    assert frame["shell"]["solution_vec_"][0] == "__eigen__"


def test_writer_as_run_callback(tmp_path):
    """TrajectoryWriter.write_frame accepts (state, solution) directly."""
    path = str(tmp_path / "skelly_sim.out")
    rng = np.random.default_rng(3)
    x = np.cumsum(rng.standard_normal((1, 8, 3)) * 0.05, axis=1)
    params = Params(eta=1.0, dt_initial=2.5e-3, dt_write=2.5e-3, t_final=1e-2,
                    gmres_tol=1e-10, adaptive_timestep_flag=False)
    system = System(params)
    fibers = fc.make_group(x, lengths=1.0, bending_rigidity=0.01, radius=0.0125)
    state = system.make_state(
        fibers=fibers, background=BackgroundFlow.make(uniform=(0.1, 0.0, 0.0)))
    with TrajectoryWriter(path) as tw:
        system.run(state, writer=tw.write_frame, max_steps=2)
    tr = TrajectoryReader(path)
    assert len(tr) == 2


def test_resume_mixed_body_kind_order(tmp_path):
    """Wire regroups bodies as [spheres, ellipsoids]; resume must undo it."""
    from skellysim_tpu.bodies import bodies as bd
    from skellysim_tpu.io import frame_to_state
    from skellysim_tpu.io.trajectory import state_to_frame
    from skellysim_tpu.io import eigen as _eigen
    from skellysim_tpu.periphery.precompute import precompute_body

    pre = precompute_body("sphere", 100, radius=0.5)
    group = bd.make_group(
        np.stack([pre["node_positions_ref"]] * 2),
        np.stack([pre["node_normals_ref"]] * 2),
        np.stack([pre["node_weights"]] * 2),
        position=np.array([[1.0, 0, 0], [2.0, 0, 0]]), radius=0.5)
    # body 0 ellipsoid, body 1 sphere: wire order is [body1, body0]
    group = group._replace(kind_sphere=jnp.asarray([False, True]))
    params = Params(eta=1.0, dt_initial=1e-3, t_final=1e-2, gmres_tol=1e-8,
                    adaptive_timestep_flag=False)
    system = System(params)
    state = system.make_state(bodies=group)
    frame = _eigen.decode_tree(state_to_frame(state))
    back = frame_to_state(frame, state)
    np.testing.assert_array_equal(np.asarray(back.bodies.position),
                                  np.asarray(state.bodies.position))


def test_frame_bytes_matches_object_encoder():
    """The vectorized raw encoder produces the identical wire bytes as
    msgpack.packb of the object-level frame."""
    import msgpack

    import jax.numpy as jnp

    from skellysim_tpu.fibers import container as fc
    from skellysim_tpu.io.trajectory import frame_bytes, state_to_frame
    from skellysim_tpu.system.system import SimState

    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.standard_normal((5, 16, 3)))
    fibers = fc.make_group(x, lengths=1.5, bending_rigidity=0.01,
                           radius=0.0125, minus_clamped=[True, False, True,
                                                         False, True])
    fibers = fibers._replace(active=jnp.asarray([True, True, False, True, True]))
    state = SimState(time=jnp.float64(1.25), dt=jnp.float64(0.05),
                     fibers=fibers, points=None, background=None)
    raw = frame_bytes(state, rng_state=[1, "abc"])
    ref = msgpack.packb(state_to_frame(state, rng_state=[1, "abc"]))
    assert raw == ref
    # and decodes to the same tree
    assert msgpack.unpackb(raw, raw=False) == msgpack.unpackb(ref, raw=False)


def test_native_frame_encoder_matches_python():
    """The C++ fiber-array encoder is byte-identical to the Python one (and
    thus to msgpack.packb of the object maps)."""
    import jax.numpy as jnp

    from skellysim_tpu.fibers import container as fc
    from skellysim_tpu.io import trajectory as tj

    rng = np.random.default_rng(13)
    x = jnp.asarray(rng.standard_normal((300, 16, 3)))
    fibers = fc.make_group(x, lengths=rng.uniform(0.5, 2.0, 300),
                           bending_rigidity=0.01, radius=0.0125,
                           minus_clamped=rng.random(300) > 0.5)
    fibers = fibers._replace(
        active=jnp.asarray(rng.random(300) > 0.1),
        binding_body=jnp.asarray(rng.integers(-1, 300, 300), dtype=jnp.int32),
        tension=jnp.asarray(rng.standard_normal((300, 16))))

    native = tj._fiber_array_bytes_native(fibers)
    if native is None:
        import pytest

        pytest.skip("no native toolchain")
    assert native == tj._fiber_array_bytes_py(fibers)
