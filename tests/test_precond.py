"""Block Gauss-Seidel preconditioner (Params.precond, VERDICT r4 #5).

The reference preconditions the coupled solve with independent block solves
(`apply_preconditioner`, `system.cpp:248-262`). `precond="gs"` folds the
shell->fiber/body coupling into a shell-first Gauss-Seidel sweep; these tests
pin that (a) the preconditioner changes only the convergence path, not the
solution, (b) it actually cuts iterations on the clamped-fiber + shell
configs it targets, and (c) it degenerates to block Jacobi when nothing is
coupled to a shell.
"""

import dataclasses

import numpy as np
import pytest

import jax.numpy as jnp

from skellysim_tpu.fibers import container as fc
from skellysim_tpu.params import Params
from skellysim_tpu.periphery import periphery as peri
from skellysim_tpu.periphery import shapes
from skellysim_tpu.system import BackgroundFlow, System
from skellysim_tpu.testing import make_coupled_parts

BASE = Params(eta=1.0, dt_initial=8e-3, t_final=1.0, gmres_tol=1e-10,
              gmres_restart=60, gmres_maxiter=300,
              adaptive_timestep_flag=False)


def _clamped_shell_scene(params, shell_n=96, n_fibers=6, fiber_nodes=24):
    """Mini oocyte-class scene: fibers clamped on a spherical shell,
    pointing inward — the config class whose fiber<->shell coupling the GS
    preconditioner targets."""
    dtype = jnp.float64
    radius = 4.0
    spec = shapes.sphere_shape(shell_n, radius=radius)
    normals = -spec.node_normals
    weights = np.full(shell_n, 4 * np.pi * radius ** 2 / shell_n)
    op, M_inv = peri.build_shell_operator(spec.nodes, normals, weights)
    shell = peri.make_state(spec.nodes, normals, weights, op, M_inv,
                            dtype=dtype)
    shape = peri.PeripheryShape(kind="sphere", radius=radius)

    stride = max(1, shell_n // n_fibers)
    origins = np.asarray(spec.nodes)[::stride][:n_fibers] * 0.98
    inward = -np.asarray(spec.node_normals)[::stride][:n_fibers]
    t = np.linspace(0, 1.0, fiber_nodes)
    x = origins[:, None, :] + t[None, :, None] * inward[:, None, :]
    fibers = fc.make_group(x, lengths=1.0, bending_rigidity=2.5e-3,
                           radius=0.0125, force_scale=-0.05,
                           minus_clamped=True, dtype=dtype)
    system = System(params, shell_shape=shape)
    state = system.make_state(fibers=fibers, shell=shell)
    return system, state


def _step_info(params, scene=_clamped_shell_scene):
    system, state = scene(params)
    _, solution, info = system.step(state)
    assert bool(info.converged), params.precond
    return np.asarray(solution), info


def test_gs_matches_jacobi_solution():
    sols = {}
    for mode in ("gs", "jacobi"):
        sols[mode], info = _step_info(dataclasses.replace(BASE, precond=mode))
        assert float(info.residual_true) <= 1e-9
    # two converged iterates of the same system differ by up to
    # ~condition x residual (measured 2.3e-8 at residual 1e-10 here)
    err = (np.linalg.norm(sols["gs"] - sols["jacobi"])
           / np.linalg.norm(sols["jacobi"]))
    assert err < 5e-7, err


def test_gs_cuts_iterations_on_clamped_shell_scene():
    _, info_gs = _step_info(dataclasses.replace(BASE, precond="gs"))
    _, info_j = _step_info(dataclasses.replace(BASE, precond="jacobi"))
    # measured on the full oocyte BASELINE config: 70 -> 27; this mini
    # scene shows the same structural gain
    assert int(info_gs.iters) < int(info_j.iters), (
        int(info_gs.iters), int(info_j.iters))


def test_gs_corrects_bodies_too():
    """Shell + body (no fibers): the body block's RHS correction engages."""
    dtype = jnp.float64
    sols = {}
    for mode in ("gs", "jacobi"):
        params = dataclasses.replace(BASE, precond=mode)
        shell, shape, bodies = make_coupled_parts(192, 96, dtype)
        system = System(params, shell_shape=shape)
        state = system.make_state(shell=shell, bodies=bodies)
        _, solution, info = system.step(state)
        assert bool(info.converged)
        sols[mode] = np.asarray(solution)
    err = (np.linalg.norm(sols["gs"] - sols["jacobi"])
           / np.linalg.norm(sols["jacobi"]))
    assert err < 1e-8, err


def test_gs_equals_jacobi_without_shell():
    """No shell => the GS correction is inert: identical iterates."""
    dtype = jnp.float64
    t = np.linspace(0, 1, 24)
    x = np.stack([np.zeros(24), np.zeros(24), t], axis=-1)
    res = {}
    for mode in ("gs", "jacobi"):
        params = dataclasses.replace(BASE, precond=mode)
        fibers = fc.make_group(x[None], lengths=1.0, bending_rigidity=0.01,
                               radius=0.0125, dtype=dtype)
        bg = BackgroundFlow.make(uniform=[0.0, 0.0, 1.0], dtype=dtype)
        system = System(params)
        state = system.make_state(fibers=fibers, background=bg)
        _, solution, info = system.step(state)
        res[mode] = (np.asarray(solution), int(info.iters))
    np.testing.assert_array_equal(res["gs"][0], res["jacobi"][0])
    assert res["gs"][1] == res["jacobi"][1]


@pytest.mark.slow  # heavy coupled-solve integration; sibling fast tests keep the seam covered (ISSUE-9 870s-budget re-triage)
def test_mixed_precision_solve_through_gs():
    """The mixed solver's f32 inner precond also takes the GS correction."""
    dtype = jnp.float64
    shell, shape, bodies = make_coupled_parts(192, 96, dtype)
    t = np.linspace(0, 1, 32)
    x = (np.array([0.0, 3.0, 0.0])[None, :]
         + t[:, None] * np.array([0.0, 0.0, 1.0]))
    fibers = fc.make_group(x[None], lengths=1.0, bending_rigidity=0.01,
                           radius=0.0125, dtype=dtype)
    params = dataclasses.replace(BASE, dt_initial=0.1,
                                 solver_precision="mixed", precond="gs")
    system = System(params, shell_shape=shape)
    state = system.make_state(fibers=fibers, shell=shell, bodies=bodies)
    _, solution, info = system.step(state)
    assert bool(info.converged)
    assert float(info.residual_true) <= 1e-10


def test_unknown_precond_rejected():
    with pytest.raises(ValueError, match="precond"):
        System(dataclasses.replace(BASE, precond="gss"))
