"""Dtype discipline: an f32 state must stay f32 end-to-end under jax_enable_x64.

Round-2 regression: the NumPy-f64 `FibMats` constants promoted every downstream
op to f64 (`fd_fiber.py`), so a float32 `SimState` produced float64 `A_bc`/LU —
and TPU XLA's `LuDecomposition` is f32-only, killing the on-device solve
(BENCH_r02 tail). The suite runs with x64 enabled (conftest), exactly the
configuration bench.py uses on the TPU, so these assertions catch any new
f64 constant closed over f32 jit code.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skellysim_tpu.fibers import container as fc
from skellysim_tpu.fibers import fd_fiber
from skellysim_tpu.fibers.matrices import get_mats, typed
from skellysim_tpu.params import Params
from skellysim_tpu.system import System


def _line_group(dtype, nf=2, n=32):
    t = np.linspace(0, 1, n)
    x = np.stack([np.zeros(n), np.zeros(n), t], axis=-1)
    xs = np.stack([x + np.array([3.0 * i, 0, 0]) for i in range(nf)])
    return fc.make_group(xs, lengths=1.0, bending_rigidity=0.01, radius=0.0125,
                         dtype=dtype)


def test_typed_mats_cast():
    m64 = get_mats(32)
    m32 = typed(m64, jnp.float32)
    assert m32.D1.dtype == np.float32
    assert m32.P_down.dtype == np.float32
    assert m32.weights0.dtype == np.float32
    # f64 request returns the original f64 set
    assert typed(m64, jnp.float64) is m64
    # cached: same object on repeat calls
    assert typed(m64, jnp.float32) is m32


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.float64])
def test_fiber_caches_keep_dtype(dtype):
    assert jax.config.jax_enable_x64  # the promotion only bites with x64 on
    group = _line_group(dtype)
    caches = fc.update_cache(group, 0.1, 1.0)
    assert caches.xs.dtype == dtype
    assert caches.stokeslet.dtype == dtype
    assert caches.force_op.dtype == dtype

    nf, n = group.n_fibers, group.n_nodes
    v = jnp.zeros((nf, n, 3), dtype=dtype)
    f = jnp.zeros((nf, n, 3), dtype=dtype)
    caches = fc.update_rhs_and_bc(group, caches, 0.1, 1.0, v, f, f)
    assert caches.A_bc.dtype == dtype, "A_bc promoted — FibMats leak is back"
    assert caches.RHS.dtype == dtype
    assert caches.lu.dtype == dtype, "LU must match state dtype (TPU LU is f32-only)"

    x = jnp.zeros((nf, 4 * n), dtype=dtype)
    assert fc.apply_preconditioner(group, caches, x).dtype == dtype
    vb = jnp.zeros((nf, 7), dtype=dtype)
    assert fc.matvec(group, caches, x, v, vb).dtype == dtype
    assert fc.fiber_error(group).dtype == dtype


def test_single_fiber_solve_stays_f32():
    dtype = jnp.float32
    group = _line_group(dtype, nf=1, n=32)
    params = Params(eta=1.0, dt_initial=0.1, t_final=1.0, gmres_tol=1e-6,
                    adaptive_timestep_flag=False)
    system = System(params)
    from skellysim_tpu.system.sources import BackgroundFlow

    bg = BackgroundFlow.make(uniform=[0.0, 0.0, 1.0], dtype=dtype)
    state = system.make_state(fibers=group, background=bg)
    new_state, solution, info = system.step(state)
    assert solution.dtype == dtype
    assert new_state.fibers.x.dtype == dtype
    assert bool(info.converged)


def test_force_operator_and_error_f32():
    mats = get_mats(16)
    x = jnp.asarray(np.linspace(0, 1, 16)[:, None] * np.array([0.0, 0, 1.0]),
                    dtype=jnp.float32)
    xs, xss, _, _ = fd_fiber.derivatives(x, jnp.float32(1.0), mats)
    assert xs.dtype == jnp.float32
    sc = fd_fiber.FiberScalars(*[jnp.float32(v) for v in
                                 (1.0, 1.0, 0.01, 0.0125, 500.0, 1.0, 0.0)])
    fop = fd_fiber.force_operator(xs, xss, 1.0, sc, mats)
    assert fop.dtype == jnp.float32
    assert fd_fiber.fiber_error(x, jnp.float32(1.0), mats).dtype == jnp.float32


def test_fiberless_f32_state_stays_f32():
    """Shell/bodies-only f32 states must not up-cast in the matvec (the
    lo_dtype seam must be a no-op without a lo triple)."""
    from skellysim_tpu.testing import make_coupled_parts

    shell, shape, bodies = make_coupled_parts(96, 64, jnp.float32)
    params = Params(dt_initial=0.1, t_final=1.0, gmres_tol=1e-6,
                    adaptive_timestep_flag=False)
    system = System(params, shell_shape=shape)
    state = system.make_state(shell=shell, bodies=bodies)
    assert state.time.dtype == jnp.float32

    state2, caches, body_caches, _, _ = system._prep(state)
    n = shell.solution_size + bodies.solution_size
    x = jnp.ones(n, dtype=jnp.float32)
    out = system._apply_matvec(state2, caches, body_caches, x)
    assert out.dtype == jnp.float32
    new_state, solution, info = system.step(state)
    assert solution.dtype == jnp.float32
    assert bool(info.converged)


def _lint_dtype(relpath):
    import os

    from skellysim_tpu.lint import lint_paths

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return lint_paths([os.path.join(root, relpath)],
                      rules=["dtype-discipline"])


def test_gmres_dtype_lint_clean():
    """Pins the skelly-lint dtype audit of the solver: the `_icgs` mask and
    back-substitution index aranges are int32 (not x64-following int64)."""
    assert _lint_dtype("skellysim_tpu/solver/gmres.py") == []


def test_container_dtype_lint_clean():
    """Pins the skelly-lint dtype audit of the fiber container (every array
    constructor derives its dtype from the state — the FibMats-leak file)."""
    assert _lint_dtype("skellysim_tpu/fibers/container.py") == []


@pytest.mark.slow  # drives the full mixed solve through the DF tier: ~1 min on the CPU tier
def test_df_tier_kernel_impl_preserves_f32_solve_dtype():
    """The DF tiles return float64 internally; the evaluator seam must cast
    back so an f32 solve with kernel_impl="df"/"pallas_df" stays f32 end to
    end (round 5: the unconverted f64 flow promoted the whole Krylov
    pipeline)."""
    import dataclasses

    from __graft_entry__ import _make_system

    for impl in ("df", "pallas_df"):
        system, state = _make_system(n_fibers=2, n_nodes=16,
                                     dtype=jnp.float32)
        system.params = dataclasses.replace(system.params, kernel_impl=impl)
        _, solution, info = jax.jit(system._solve_impl)(state)
        assert solution.dtype == jnp.float32, impl
        assert bool(info.converged), impl
