"""skelly-scope: span tracing, compile events, cost baselines, convergence
history (docs/observability.md).

Covers every leg of the telemetry subsystem: span nesting/attribution in
the tracer, compile events firing exactly once per compiled program
(cross-checked against `testing.trace_counting_jit`), the cost-baseline
drift gate's flag/pass/suppress/drift ladder (synthetic programs + the real
CLI on the cheapest registered program), and the GMRES convergence ring
buffer against the solver's own debug-print residuals. Multi-device
fixture compiles stay out of this module (the cost CLI test restricts to
``gmres_f32``) to protect the not-slow tier's 870 s budget.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from skellysim_tpu.obs import tracer as obs_tracer
from skellysim_tpu.obs.compile_log import observed_jit
from skellysim_tpu.obs.tracer import TELEMETRY_VERSION, Tracer


# ------------------------------------------------------------------ tracer

def test_span_nesting_and_attribution():
    tr = Tracer()  # in-memory
    with obs_tracer.use(tr):
        with obs_tracer.span("outer", kind="test"):
            with obs_tracer.span("inner") as sp:
                sp.note(iters=3)
            with obs_tracer.span("inner"):
                pass
    evs = tr.events
    assert evs[0]["ev"] == "telemetry"
    assert evs[0]["version"] == TELEMETRY_VERSION
    spans = [e for e in evs if e["ev"] == "span"]
    # children close before their parent; paths carry the open stack
    assert [s["path"] for s in spans] == ["outer/inner", "outer/inner",
                                         "outer"]
    assert spans[0]["iters"] == 3
    assert spans[2]["kind"] == "test"
    assert all(s["dur_s"] >= 0.0 and "pid" in s and "host" in s
               for s in spans)
    # the parent's duration covers its children
    assert spans[2]["dur_s"] >= spans[0]["dur_s"] + spans[1]["dur_s"]


def test_span_sync_blocks_on_device_work():
    tr = Tracer()
    with obs_tracer.use(tr):
        with obs_tracer.span("work") as sp:
            sp.sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    (span,) = [e for e in tr.events if e["ev"] == "span"]
    assert span["name"] == "work"


def test_span_and_emit_are_noops_without_tracer():
    assert obs_tracer.active() is None
    with obs_tracer.span("nobody-listening") as sp:
        sp.note(x=1)
        sp.sync(jnp.zeros(3))
    obs_tracer.emit("lane", action="admit")  # must not raise


def test_tracer_jsonl_roundtrip(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    tr = Tracer(path)
    with tr.span("a"):
        tr.emit("custom", value=7)
    tr.close()
    recs = [json.loads(ln) for ln in open(path)]
    assert [r["ev"] for r in recs] == ["telemetry", "custom", "span"]
    assert recs[1]["value"] == 7


# ----------------------------------------------------------- compile events

def test_compile_events_fire_exactly_once_per_program():
    """One compile event per (program x signature) — cross-checked against
    trace_counting_jit semantics via the shared trace counter."""
    from skellysim_tpu.testing import trace_counting_jit

    def f(x):
        return (x * 2.0).sum()

    obs = observed_jit(f, name="toy")
    ref = trace_counting_jit(f)
    tr = Tracer()
    with obs_tracer.use(tr):
        x = jnp.ones(8)
        obs(x), ref(x)
        obs(x + 1.0), ref(x + 1.0)      # same signature: no event
        obs(jnp.ones(16)), ref(jnp.ones(16))  # new shape: one more event
    compiles = [e for e in tr.events if e["ev"] == "compile"]
    assert len(compiles) == 2
    assert obs.trace_count == ref.trace_count == 2
    assert [c["name"] for c in compiles] == ["toy", "toy"]
    assert compiles[0]["arg_sig"].startswith("f64[8]")
    assert compiles[1]["arg_sig"].startswith("f64[16]")
    assert all(c["wall_s"] >= c["trace_s"] >= 0.0 for c in compiles)


def test_compile_event_skipped_when_warm():
    """A tracer installed AFTER the program compiled sees no event — only
    genuine compiles land in the timeline."""
    g = observed_jit(lambda x: x + 1.0, name="warm")
    g(jnp.ones(4))
    tr = Tracer()
    with obs_tracer.use(tr):
        g(jnp.ones(4))
    assert [e for e in tr.events if e["ev"] == "compile"] == []


def test_observed_jit_trace_passthrough_and_donation_field():
    """`built_from` consumes ObservedJit directly (the audit/cost seam) and
    the compile event carries the donated argument positions."""
    from skellysim_tpu.audit.registry import built_from

    h = observed_jit(lambda x: x * 3.0, name="donating", donate_argnums=(0,))
    built = built_from(h, jnp.ones(4))
    assert built.lowered is not None
    assert "stablehlo" in built.lowered_text or "func.func" in built.lowered_text
    tr = Tracer()
    with obs_tracer.use(tr):
        h(jnp.ones(8))
    (ev,) = [e for e in tr.events if e["ev"] == "compile"]
    assert ev["donated"] == [0]


# ------------------------------------------------------------ cost baselines

def _toy_program(name="toy_prog", scale=1.0):
    from skellysim_tpu.audit.registry import AuditProgram, built_from

    def build():
        a = jnp.ones((32, 32)) * scale
        return built_from(jax.jit(lambda x: (x @ x).sum()), a)

    return AuditProgram(name=name, layer="solver", summary="toy", build=build)


def test_cost_uncovered_then_update_then_pass(tmp_path):
    from skellysim_tpu.obs import cost

    prog = _toy_program()
    bdir = str(tmp_path)
    rows, findings = cost.audit_costs([prog], baseline_dir=bdir)
    assert any("no cost baseline" in f.message for f in findings)
    assert rows[0]["flops"] > 0 and rows[0]["peak_bytes"] > 0

    rows, findings = cost.audit_costs([prog], baseline_dir=bdir, update=True)
    assert findings == []
    rows, findings = cost.audit_costs([prog], baseline_dir=bdir)
    assert findings == []  # measured == baseline: deterministic static analysis


def test_cost_drift_flagged_and_suppressible(tmp_path):
    from skellysim_tpu.config import toml_io
    from skellysim_tpu.obs import cost

    prog = _toy_program()
    bdir = str(tmp_path)
    cost.audit_costs([prog], baseline_dir=bdir, update=True)
    path = cost.baseline_path(prog.name, bdir)
    data = toml_io.load(path)
    data["cost"]["flops"] = data["cost"]["flops"] * 2.0  # fake a regression
    toml_io.dump(data, path)
    _, findings = cost.audit_costs([prog], baseline_dir=bdir)
    assert any("flops drifted" in f.message and "improvement" in f.message
               for f in findings)

    # suppression with a reason absorbs it; an unused one is itself a finding
    data["suppress"] = [{"check": "cost-baseline", "match": "flops drifted",
                         "reason": "testing the suppress path"}]
    toml_io.dump(data, path)
    _, findings = cost.audit_costs([prog], baseline_dir=bdir)
    assert findings == []
    data["cost"]["flops"] = data["cost"]["flops"] / 2.0  # back to truth
    toml_io.dump(data, path)
    _, findings = cost.audit_costs([prog], baseline_dir=bdir)
    assert any("unused suppression" in f.message for f in findings)


def test_cost_suppress_requires_reason_and_match(tmp_path):
    from skellysim_tpu.config import toml_io
    from skellysim_tpu.obs import cost

    prog = _toy_program()
    bdir = str(tmp_path)
    cost.audit_costs([prog], baseline_dir=bdir, update=True)
    path = cost.baseline_path(prog.name, bdir)
    data = toml_io.load(path)
    data["suppress"] = [{"check": "cost-baseline", "match": "flops"}]
    toml_io.dump(data, path)
    _, findings = cost.audit_costs([prog], baseline_dir=bdir)
    assert any("missing its reason" in f.message for f in findings)


def test_cost_stale_baseline_and_tol_pct(tmp_path):
    from skellysim_tpu.config import toml_io
    from skellysim_tpu.obs import cost

    prog = _toy_program()
    bdir = str(tmp_path)
    cost.audit_costs([prog], baseline_dir=bdir, update=True)
    # a generous tol_pct absorbs a small nudge (and --update preserves it)
    path = cost.baseline_path(prog.name, bdir)
    data = toml_io.load(path)
    data["cost"]["tol_pct"] = 90.0
    data["cost"]["flops"] = data["cost"]["flops"] * 1.5
    toml_io.dump(data, path)
    _, findings = cost.audit_costs([prog], baseline_dir=bdir)
    assert findings == []
    cost.audit_costs([prog], baseline_dir=bdir, update=True)
    assert toml_io.load(path)["cost"]["tol_pct"] == 90.0
    # a baseline whose program vanished is a finding
    _, findings = cost.audit_costs([_toy_program(name="other")],
                                   baseline_dir=bdir)
    assert any("stale baseline" in f.message for f in findings)
    assert any("no cost baseline" in f.message for f in findings)


def test_cost_cli_exit_codes(tmp_path):
    """`obs cost --check` exits 1 on drift/uncovered, 0 once baselined —
    on the real registry restricted to its cheapest program (gmres_f32;
    the multi-device programs stay in the CI gate, not the test tier)."""
    from skellysim_tpu.obs.cli import main

    bdir = str(tmp_path)
    assert main(["cost", "--check", "--program", "gmres_f32",
                 "--baseline-dir", bdir]) == 1  # uncovered
    # findings exit 1 with or without --check (mirrors lint/audit)
    assert main(["cost", "--program", "gmres_f32",
                 "--baseline-dir", bdir]) == 1
    assert main(["cost", "--update", "--program", "gmres_f32",
                 "--baseline-dir", bdir]) == 0
    assert main(["cost", "--check", "--program", "gmres_f32",
                 "--baseline-dir", bdir]) == 0
    assert main(["cost", "--check", "--update"]) == 2  # usage error
    assert main(["cost", "--check", "--program", "nope",
                 "--baseline-dir", bdir]) == 2
    # against the REAL baseline dir, a single-program run must not read
    # the other programs' baselines as stale (the --program workflow)
    assert main(["cost", "--check", "--program", "gmres_f32"]) == 0


def test_cost_stale_scan_uses_full_registry_names(tmp_path):
    from skellysim_tpu.obs import cost

    a, b = _toy_program(name="prog_a"), _toy_program(name="prog_b")
    bdir = str(tmp_path)
    cost.audit_costs([a, b], baseline_dir=bdir, update=True)
    # auditing only prog_a with the full name set: prog_b's baseline is fine
    _, findings = cost.audit_costs([a], baseline_dir=bdir,
                                   registry_names={"prog_a", "prog_b"})
    assert findings == []
    # without the full set (a caller that filtered and forgot): stale
    _, findings = cost.audit_costs([a], baseline_dir=bdir)
    assert any("stale baseline" in f.message for f in findings)


def test_every_registered_program_has_a_checked_in_baseline():
    """Acceptance pin: the registry and obs/baselines/ agree exactly (the
    full drift check runs in CI; here only the cheap file<->name match)."""
    import os

    from skellysim_tpu.audit.programs import all_programs
    from skellysim_tpu.obs.cost import BASELINE_DIR

    names = {p.name for p in all_programs()}
    files = {os.path.splitext(f)[0] for f in os.listdir(BASELINE_DIR)
             if f.endswith(".toml")}
    assert names == files


# ------------------------------------------------- gmres convergence history

def _dense_problem(n=80, seed=3, dtype=jnp.float64):
    rng = np.random.default_rng(seed)
    A = jnp.asarray(np.eye(n) + 0.3 * rng.standard_normal((n, n)) / np.sqrt(n),
                    dtype=dtype)
    b = jnp.asarray(rng.standard_normal(n), dtype=dtype)
    return A, b


def test_gmres_history_matches_debug_print(capsys):
    """The device-side ring buffer records the SAME per-restart residuals
    the solver's debug path prints — without any host callback in the
    compiled program (the debug path adds one; history must not)."""
    from skellysim_tpu.solver.gmres import gmres, history_rows

    A, b = _dense_problem()
    r = gmres(lambda x: A @ x, b, tol=1e-12, restart=5, maxiter=200,
              history=16, debug=True)
    jax.effects_barrier()
    printed = []
    for ln in capsys.readouterr().out.splitlines():
        if "gmres restart" in ln:
            printed.append((int(ln.split("iters=")[1].split(" ")[0]),
                            float(ln.split("implicit=")[1].split(" ")[0]),
                            float(ln.split("explicit=")[1])))
    rows = history_rows(r.history, r.cycles)
    assert len(rows) == len(printed) == int(r.cycles) >= 3
    for (it_h, imp_h, exp_h), (it_p, imp_p, exp_p) in zip(rows, printed):
        assert it_h == it_p
        assert imp_h == pytest.approx(imp_p, rel=2e-3)  # print is %.3e
        assert exp_h == pytest.approx(exp_p, rel=2e-3)
    assert rows[-1][2] == float(r.residual_true)


def test_gmres_history_ring_wraps_chronologically():
    from skellysim_tpu.solver.gmres import gmres, history_rows

    A, b = _dense_problem()
    full = gmres(lambda x: A @ x, b, tol=1e-12, restart=5, maxiter=200,
                 history=32)
    wrapped = gmres(lambda x: A @ x, b, tol=1e-12, restart=5, maxiter=200,
                    history=3)
    all_rows = history_rows(full.history, full.cycles)
    last3 = history_rows(wrapped.history, wrapped.cycles)
    assert int(full.cycles) > 3  # the wrap actually happened
    assert len(last3) == 3
    assert last3 == all_rows[-3:]  # ring holds the LAST cycles, oldest first
    # disabled history costs nothing and changes nothing
    off = gmres(lambda x: A @ x, b, tol=1e-12, restart=5, maxiter=200)
    assert off.history is None
    np.testing.assert_array_equal(np.asarray(off.x), np.asarray(full.x))


def test_gmres_ir_history_one_row_per_sweep():
    from skellysim_tpu.solver.gmres import gmres_ir, history_rows

    A, b = _dense_problem()
    r = gmres_ir(lambda x: A @ x, lambda x: A @ x, b, tol=1e-12,
                 inner_tol=1e-4, restart=30, maxiter=200, history=8)
    rows = history_rows(r.history, r.cycles)
    assert len(rows) == int(r.refines) == int(r.cycles) >= 2
    assert rows[-1][2] == float(r.residual_true)
    exps = [row[2] for row in rows]
    assert exps == sorted(exps, reverse=True)  # sweeps contract the residual


def test_history_rows_handles_empty_and_none():
    from skellysim_tpu.solver.gmres import history_rows

    assert history_rows(None, 5) == []
    assert history_rows(np.zeros((4, 3)), 0) == []
    assert history_rows(np.zeros((0, 3)), 3) == []


def test_vmapped_gmres_history_is_per_member():
    """The ring buffer is an ordinary carry: vmap gives each member its own
    buffer (the ensemble runner's per-lane convergence history)."""
    from skellysim_tpu.solver.gmres import gmres, history_rows

    A, b = _dense_problem()
    bb = jnp.stack([b, 2.0 * b])
    vr = jax.vmap(lambda bi: gmres(lambda x: A @ x, bi, tol=1e-12,
                                   restart=5, maxiter=200, history=8))(bb)
    assert vr.history.shape[0] == 2
    r0 = history_rows(vr.history[0], vr.cycles[0])
    r1 = history_rows(vr.history[1], vr.cycles[1])
    # scaled RHS: same relative trajectory, per-member buffers decode alone
    assert len(r0) == len(r1) == int(vr.cycles[0])
    assert r0[-1][2] == pytest.approx(float(vr.residual_true[0]))


# ---------------------------------------------------- run-loop + ensemble

def test_run_metrics_and_trace_render_through_summarize(tmp_path):
    """Acceptance criterion: System.run(metrics_path, trace_path) -> `obs
    summarize` renders per-span timings, compile events, and convergence
    stats from the pair."""
    from skellysim_tpu.audit import fixtures
    from skellysim_tpu.obs.summarize import summarize_files
    from skellysim_tpu.system.system import METRICS_FIELDS

    system = fixtures.make_system()
    state = fixtures.free_state(system)
    m = str(tmp_path / "metrics.jsonl")
    t = str(tmp_path / "trace.jsonl")
    system.run(state, max_steps=2, metrics_path=m, trace_path=t)

    recs = [json.loads(ln) for ln in open(m)]
    assert len(recs) == 2
    for rec in recs:
        assert set(rec) == set(METRICS_FIELDS)
        assert rec["gmres_cycles"] >= 1
        assert rec["wall_ms"] == pytest.approx(rec["wall_s"] * 1e3, rel=0.1)
        hist = rec["gmres_history"]
        assert len(hist) == rec["gmres_cycles"]
        # last ring row's explicit residual is the step's residual_true
        assert hist[-1][2] == pytest.approx(rec["residual_true"])
        assert hist[-1][0] == rec["iters"]

    evs = [json.loads(ln) for ln in open(t)]
    kinds = [e["ev"] for e in evs]
    assert kinds[0] == "telemetry"
    assert "compile" in kinds and "span" in kinds
    (compile_ev,) = [e for e in evs if e["ev"] == "compile"]
    assert compile_ev["name"] == "system.solve"  # compiled exactly once
    step_spans = [e for e in evs if e["ev"] == "span"
                  and e["name"] == "step"]
    assert len(step_spans) == 2
    assert all(s["path"] == "run/step" for s in step_spans)

    report = summarize_files([m, t])
    for section in ("== spans ==", "== compile events ==",
                    "== solver convergence =="):
        assert section in report
    assert "run/step" in report and "system.solve" in report


@pytest.mark.slow
def test_scheduler_lane_events_and_no_backfill_retrace(tmp_path):
    """Lane admit/backfill/retire events flow through the tracer, occupancy
    renders in summarize, and the telemetry does not break the
    backfill-never-retraces invariant (trace_counting_jit cross-check).

    Slow-marked (a 4-member batched-step compile) to keep the not-slow
    tier inside the driver's 870 s budget; the full tier runs it."""
    from skellysim_tpu.audit import fixtures
    from skellysim_tpu.ensemble import (EnsembleRunner, EnsembleScheduler,
                                        MemberSpec)
    from skellysim_tpu.io.ensemble_io import ENSEMBLE_STEP_FIELDS
    from skellysim_tpu.obs.summarize import summarize_files
    from skellysim_tpu.system import BackgroundFlow
    from skellysim_tpu.testing import trace_counting_jit

    system = fixtures.make_system()
    states = [system.make_state(
        fibers=fixtures.make_fibers(n_fibers=2, n_nodes=8, seed=i),
        background=BackgroundFlow.make(uniform=(1.0, 0.0, 0.0),
                                       dtype=jnp.float64))
        for i in range(4)]
    members = [MemberSpec(member_id=f"m{i}", state=s, t_final=2e-3)
               for i, s in enumerate(states)]
    runner = EnsembleRunner(system)
    counting = trace_counting_jit(runner.step_impl)

    metrics_records = []
    t = str(tmp_path / "trace.jsonl")
    tr = Tracer(t)
    with obs_tracer.use(tr):
        sched = EnsembleScheduler(runner, members, 2,
                                  metrics=metrics_records.append,
                                  step_fn=counting)
        retired = sched.run()
    tr.close()
    assert sorted(retired) == ["m0", "m1", "m2", "m3"]
    # lane events: 2 admits (initial seats), 2 backfills, 4 retires — and
    # backfill swapped member leaves without a retrace
    evs = [json.loads(ln) for ln in open(t)]
    lanes = [e for e in evs if e["ev"] == "lane"]
    actions = [e["action"] for e in lanes]
    assert actions.count("admit") == 2
    assert actions.count("backfill") == 2
    assert actions.count("retire") == 4
    assert counting.trace_count == 1
    steps = [r for r in metrics_records if r["event"] == "step"]
    assert steps and all(set(r) == set(ENSEMBLE_STEP_FIELDS) for r in steps)
    assert all(len(r["gmres_history"]) == r["gmres_cycles"] for r in steps)

    report = summarize_files([t])
    assert "== ensemble lanes ==" in report
    assert "mean occupancy" in report
    assert "admit=2" in report and "backfill=2" in report


# ------------------------------------------------------------- bench format

def test_bench_telemetry_version_pinned():
    """bench.py's jax-free parent pins its own TELEMETRY_VERSION literal;
    it must track obs.tracer's (the one-format contract)."""
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_for_version_pin", os.path.join(repo, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    assert bench.TELEMETRY_VERSION == TELEMETRY_VERSION


def test_summarize_tolerates_mixed_and_garbage_lines(tmp_path):
    from skellysim_tpu.obs.summarize import summarize_files

    p = str(tmp_path / "mixed.jsonl")
    with open(p, "w") as fh:
        fh.write("not json at all\n")
        fh.write(json.dumps({"resume": True, "t": 0.5}) + "\n")
        fh.write(json.dumps({"ev": "span", "name": "a", "path": "a",
                             "dur_s": 0.5}) + "\n")
        fh.write(json.dumps({"step": 0, "iters": 4, "accepted": True,
                             "residual_true": 1e-11}) + "\n")
    report = summarize_files([p])
    assert "== spans ==" in report
    assert "trial steps: 1" in report
    assert "resume markers: 1" in report
    assert "1 unparseable line(s) skipped" in report


def test_summarize_dedupes_shared_round_wall(tmp_path):
    """Ensemble step records share one batched round's wall across lanes;
    the wall total must count each round once, not lanes x wall."""
    from skellysim_tpu.obs.summarize import summarize_files

    p = str(tmp_path / "ens.jsonl")
    with open(p, "w") as fh:
        for rnd in range(2):
            for lane in range(4):
                fh.write(json.dumps({
                    "event": "step", "member": f"m{lane}", "lane": lane,
                    "round": rnd, "step": rnd, "iters": 3, "accepted": True,
                    "wall_ms": 10.0}) + "\n")
    report = summarize_files([p])
    # 2 rounds x 10 ms = 0.020 s — NOT 8 records x 10 ms = 0.080 s
    assert "batched-round wall: total 0.020s" in report
    # two runs' files summarized together: per-run round ids both start at
    # 0, so the dedupe must key per stream — totals ADD across files
    import shutil

    p2 = str(tmp_path / "ens2.jsonl")
    shutil.copy(p, p2)
    assert "batched-round wall: total 0.040s" in summarize_files([p, p2])
